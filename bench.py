"""Benchmark entry point (driver-run, real TPU).

Workload: BASELINE.md row 1 — exhaust (or depth/time-capped sweep of) the
reference `standard-raft/Raft.cfg` state space with the TPU checker and
report sustained distinct-states/sec.

vs_baseline: the reference publishes NO performance numbers
(BASELINE.md: "published: {}"), and TLC (Java) is not present in this
image, so the comparison baseline is the in-repo pure-Python oracle
interpreter (the same role as TLC: a CPU explicit-state checker of the
identical spec + VIEW/SYMMETRY semantics) measured on the same machine on
a depth-capped slice of the same workload. vs_baseline = tpu_rate /
oracle_rate.

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
"""

import json
import os
import sys
import time

os.environ.setdefault("BENCH_TIME_BUDGET_S", "300")


def tpu_rate() -> tuple[float, dict]:
    from raft_tpu.utils.cfg import parse_cfg
    from raft_tpu.models.registry import build_from_cfg
    from raft_tpu.checker.bfs import BFSChecker

    import numpy as np

    cfg = parse_cfg("/root/reference/specifications/standard-raft/Raft.cfg")
    setup = build_from_cfg(cfg, msg_slots=32)
    chunk = int(os.environ.get("BENCH_CHUNK", "2048"))
    checker = BFSChecker(
        setup.model, invariants=setup.invariants, symmetry=True, chunk=chunk
    )
    # warm-up: compile the expansion / fingerprint / invariant kernels at
    # the exact shapes the BFS loop uses, so the recorded rate is the
    # sustained throughput (first TPU compile is ~20-40 s and would
    # otherwise dominate a short budget)
    model = setup.model
    init = model.init_states()
    batch = np.repeat(init, chunk, axis=0)
    succs, valid, _rank, _ovf = model.expand(batch)
    flat = succs.reshape(-1, model.layout.W)
    checker.canon.fingerprints(flat).block_until_ready()
    checker.canon.fingerprints(init).block_until_ready()  # run()'s init call
    # invariant batches are power-of-two bucketed by the checker; warm the
    # buckets a depth-capped Raft.cfg run actually visits
    size = 1
    while size <= chunk * 8:
        model.invariants[setup.invariants[0]](
            np.repeat(init, size, axis=0)
        ).block_until_ready()
        for name in setup.invariants[1:]:
            model.invariants[name](np.repeat(init, size, axis=0)).block_until_ready()
        size *= 2
    budget = float(os.environ["BENCH_TIME_BUDGET_S"])
    max_depth = int(os.environ.get("BENCH_MAX_DEPTH", "0")) or None
    t0 = time.perf_counter()
    res = checker.run(max_depth=max_depth, time_budget_s=budget)
    dt = time.perf_counter() - t0
    meta = {
        "distinct": res.distinct,
        "depth": res.depth,
        "exhausted": res.exhausted,
        "seconds": round(dt, 2),
        "violation": res.violation.invariant if res.violation else None,
    }
    return res.states_per_sec, meta


def oracle_rate() -> float:
    from raft_tpu.oracle.raft_oracle import RaftOracle

    # same spec/constants as Raft.cfg, depth-capped for time
    oracle = RaftOracle(3, 1, 2, 0)
    t0 = time.perf_counter()
    res = oracle.bfs(
        invariants=("LeaderHasAllAckedValues", "NoLogDivergence"),
        symmetry=True,
        max_depth=int(os.environ.get("BENCH_ORACLE_DEPTH", "7")),
    )
    dt = time.perf_counter() - t0
    return res["distinct"] / dt


def main():
    rate, meta = tpu_rate()
    base = oracle_rate()
    out = {
        "metric": "distinct_states_per_sec_raft3_cfg",
        "value": round(rate, 1),
        "unit": "distinct states/s",
        "vs_baseline": round(rate / base, 2) if base > 0 else None,
        "detail": meta,
        "baseline_kind": "in-repo python oracle checker (TLC stand-in), depth-capped",
    }
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
