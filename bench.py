"""Benchmark entry point (driver-run, real TPU).

Workload: BASELINE.md row 1 — the reference `standard-raft/Raft.cfg` state
space on the device-resident checker (DeviceBFS), reported as sustained
distinct-states/sec over a time-budgeted deep run.

Round-5 protocol (verdict Next #5 — reproducibility under tunnel
dispatch-floor drift and remote-compile stalls):
  0. PRECOMPILE phase, untimed: the engine is built at its FINAL
     capacities (no growth retraces) and DeviceBFS.precompile() compiles
     the chunk program + the full LSM merge ladder. With the persistent
     compile cache (.jax_cache, committed) this is a disk reload; cold
     it is the one-time compile cost, and either way the TIMED region
     never compiles. LSM consolidation is host-side since round 5, so
     no program signature can appear mid-run.
  1. The deep run comes FIRST (it is the headline number) with per-wave
     metrics; the measured null-dispatch floor is reported alongside.
  2. Parity gate before any number is emitted: depths 1..GATE_DEPTH at
     two chunk geometries must produce bit-identical per-depth counts.
     A gate failure prints value 0 and exits nonzero.
  3. Same-depth comparison for vs_baseline (python oracle = TLC stand-in;
     the reference publishes no numbers and TLC is not in this image) and
     vs_strong_baseline (the SAME engine on the XLA CPU backend).

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}
"""

import json
import os
import sys
import time

CFG = "/root/reference/specifications/standard-raft/Raft.cfg"


def _setup_or_fallback():
    """(model, invariants, workload label). The driver benchmark runs
    against the reference Raft.cfg; without a reference checkout an
    equivalent built-in 3-server geometry stands in (same S, same
    symmetry group — the axes the rate depends on)."""
    if os.path.exists(CFG):
        from raft_tpu.models.registry import build_from_cfg
        from raft_tpu.utils.cfg import parse_cfg

        setup = build_from_cfg(parse_cfg(CFG), msg_slots=32)
        return setup.model, setup.invariants, "standard-raft/Raft.cfg"
    from raft_tpu.models.raft import RaftParams, cached_model

    p = RaftParams(n_servers=3, n_values=2, max_elections=3,
                   max_restarts=1, msg_slots=32)
    return (cached_model(p),
            ("LeaderHasAllAckedValues", "NoLogDivergence"),
            "builtin raft3 (no /root/reference checkout)")


def _emit_micro_summary():
    """Digest of EMIT_MICRO.json (scripts/emit_micro.py) when present:
    the measured emit-strategy costs the round-6 append emit rests on,
    attached to the benchmark's provenance so the rate number carries
    the evidence for its emit path. None when the microbench has not
    been run on this checkout."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "EMIT_MICRO.json")
    if not os.path.exists(path):
        return None
    with open(path) as f:
        em = json.load(f)
    worst = max(em["rows"], key=lambda r: r["scatter_over_compact"])
    return {
        "device": em["meta"]["device"],
        "when": em["meta"]["when"],
        "cells": len(em["rows"]),
        "worst_scatter_over_compact": worst["scatter_over_compact"],
        "worst_cell": {k: worst[k] for k in
                       ("vc", "fcap", "scatter_full_ms", "compact_dus_ms",
                        "sort_emit_ms")},
    }


def repro_main():
    """--repro: two consecutive IN-PROCESS deep runs after one
    precompile, both sustained rates recorded — the reproducibility
    proof (VERDICT task #8). Writes BENCH_r06-style JSON to stdout;
    the caller redirects it into the round file."""
    depth = int(os.environ.get("BENCH_REPRO_DEPTH", "14"))
    chunk = int(os.environ.get("BENCH_CHUNK", "2048"))

    import jax

    from raft_tpu.checker.device_bfs import DeviceBFS

    model, invs, workload = _setup_or_fallback()
    t0 = time.perf_counter()
    # FINAL capacities up front: a growth retrace in run 1 that run 2
    # does not pay would fake a rate difference (raft3 depth 14 peaks
    # at a ~519k frontier, ~913k seen)
    dev = DeviceBFS(
        model, invariants=invs, symmetry=True, chunk=chunk,
        frontier_cap=1 << 20, seen_cap=1 << 21, journal_cap=1 << 21,
        max_frontier_cap=1 << 21, max_seen_cap=1 << 23,
        max_journal_cap=1 << 23,
    )
    dev.precompile()
    precompile_s = time.perf_counter() - t0

    # one untimed warm-up run first: the first post-precompile run
    # page-faults the cap-sized buffers in and warms host-side caches
    # (measured +20-35% one-off on CPU); its rate is recorded anyway
    runs = []
    for _ in range(3):
        t0 = time.perf_counter()
        res = dev.run(max_depth=depth)
        runs.append({
            "distinct": res.distinct,
            "depth": res.depth,
            "seconds": round(time.perf_counter() - t0, 2),
            "distinct_per_s": round(res.states_per_sec, 1),
        })
    warm, r1, r2 = runs
    ratio = (r2["distinct_per_s"] / r1["distinct_per_s"]
             if r1["distinct_per_s"] else 0.0)
    out = {
        "metric": "bench_repro_consecutive_runs",
        "workload": workload,
        "platform": jax.devices()[0].platform,
        "device": str(jax.devices()[0]),
        "when": time.strftime("%Y-%m-%d %H:%M:%S"),
        "protocol": (
            "one engine, one precompile, one untimed warm-up run, then "
            f"two consecutive in-process depth-{depth} runs; nothing "
            "compiles in the timed regions"
        ),
        "precompile_s": round(precompile_s, 1),
        "warmup_run": warm,
        "run1": r1,
        "run2": r2,
        "counts_match": (warm["distinct"] == r1["distinct"] == r2["distinct"]
                         and r1["depth"] == r2["depth"]),
        "rate_ratio": round(ratio, 4),
        "within_10pct": bool(abs(ratio - 1.0) <= 0.10),
    }
    print(json.dumps(out, indent=1))
    return 0 if out["within_10pct"] and out["counts_match"] else 1


SWEEP_MANIFEST = {
    "spec": "Raft",
    "defaults": {
        "constants": {"Server": ["s1", "s2", "s3"], "Value": ["v1"],
                      "MaxElections": 1, "MaxRestarts": 1},
        "invariants": ["NoLogDivergence"],
        "msg_slots": 24,
    },
    # 16 configs, one packed layout: MaxElections 1 and 2 share the
    # 2-bit term width, MaxRestarts never shapes the program
    "grid": {"MaxRestarts": [1, 2, 3, 4, 5, 6, 7, 8],
             "MaxElections": [1, 2]},
}


def sweep_main():
    """--sweep: fleet amortization benchmark (host engine, CPU-friendly).

    Runs the 16-config Raft sweep twice — once as 16 serial runs (one
    fresh model per job, the cost a user pays without the fleet driver)
    and once through `run_sweep` as ONE packed group — asserts per-job
    bit-identical distinct/total/depth/violation, and prints one JSON
    line whose detail carries the fleet amortization stats (precompile
    count vs job count) as provenance."""
    depth = int(os.environ.get("BENCH_SWEEP_DEPTH", "6"))

    import jax

    from raft_tpu.checker.bfs import BFSChecker
    from raft_tpu.fleet import SweepOptions, parse_manifest_obj, run_sweep
    from raft_tpu.fleet.grouping import build_setup, group_jobs

    mf = parse_manifest_obj(SWEEP_MANIFEST, path="bench.py --sweep")

    # serial leg: a fresh model per job = a fresh jit cache per job
    serial = {}
    t0 = time.perf_counter()
    for job in mf.jobs:
        setup = build_setup(job, mf.path)
        res = BFSChecker(
            setup.model, invariants=setup.invariants,
            symmetry=setup.symmetry,
        ).run(max_depth=depth)
        serial[job.name] = {
            "distinct": res.distinct, "total": res.total,
            "depth": res.depth,
            "violation": res.violation.invariant if res.violation else None,
        }
    serial_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    fleet = run_sweep(mf, SweepOptions(engine="host", max_depth=depth))
    fleet_s = time.perf_counter() - t0

    mismatches = []
    for j in fleet.jobs:
        s = serial[j.name]
        f = {
            "distinct": j.distinct, "total": j.total, "depth": j.depth,
            "violation": j.violation["invariant"] if j.violation else None,
        }
        if f != s:
            mismatches.append({"job": j.name, "serial": s, "fleet": f})
    groups = group_jobs(mf)
    am = fleet.amortization
    ok = (not mismatches
          and am["precompiles"] <= am["groups"]
          and fleet_s < serial_s)
    out = {
        "metric": "fleet_sweep_speedup_vs_serial",
        "value": round(serial_s / fleet_s, 2) if fleet_s > 0 else None,
        "unit": "x (16-config Raft sweep, host engine)",
        "platform": jax.devices()[0].platform,
        "when": time.strftime("%Y-%m-%d %H:%M:%S"),
        "detail": {
            "jobs": len(mf.jobs),
            "max_depth": depth,
            "serial_s": round(serial_s, 2),
            "fleet_s": round(fleet_s, 2),
            "amortization": am,
            "group_kinds": [g.kind for g in groups],
            "counts_bit_identical": not mismatches,
            "mismatches": mismatches[:4],
        },
    }
    print(json.dumps(out))
    return 0 if ok else 1


def measure_floor(reps: int = 5) -> float:
    """Median wall seconds of a null dispatch + device_get sync — the
    tunnel floor every wave pays once. block_until_ready does not
    actually wait on this backend; device_get does."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    f = jax.jit(lambda x: x + 1)
    x = jnp.zeros((8,), jnp.int32)
    np.asarray(jax.device_get(f(x)))  # compile
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        np.asarray(jax.device_get(f(x)))
        ts.append(time.perf_counter() - t0)
    return float(sorted(ts)[len(ts) // 2])


def main():
    budget = float(os.environ.get("BENCH_TIME_BUDGET_S", "300"))
    cmp_depth = int(os.environ.get("BENCH_CMP_DEPTH", "16"))
    gate_depth = int(os.environ.get("BENCH_GATE_DEPTH", "12"))
    chunk = int(os.environ.get("BENCH_CHUNK", "4096"))

    from raft_tpu.utils.cfg import parse_cfg
    from raft_tpu.models.registry import build_from_cfg
    from raft_tpu.checker.device_bfs import DeviceBFS
    from raft_tpu.checker.parity import parity_gate
    from raft_tpu.obs import Telemetry, coverage_digest

    cfg = parse_cfg(CFG)
    setup = build_from_cfg(cfg, msg_slots=32)
    model, invs = setup.model, setup.invariants

    # 00. kernel contract audit (raft_tpu lint --strict, in-process):
    # the BENCH row records the static-analysis verdict as provenance,
    # and a dirty strict verdict refuses publication BEFORE any wave
    # runs — mirroring the BENCH_GATE_BASELINE pattern: the bench
    # stays a measurement, the contract verdict travels with it.
    # RAFT_TPU_BENCH_NO_LINT=1 opts out (e.g. a deliberately mutated
    # tree under study).
    lint_row = None
    if os.environ.get("RAFT_TPU_BENCH_NO_LINT") != "1":
        from raft_tpu.analysis.cli import lint_verdict

        try:
            lv = lint_verdict(strict=True)
        except Exception as e:  # a crashed auditor is not a clean one
            lv = {"clean": False, "strict": True,
                  "error": f"{type(e).__name__}: {e}"}
        lint_row = {
            k: lv[k]
            for k in ("strict", "errors", "warnings", "checked",
                      "clean", "error")
            if k in lv
        }
        if not lv.get("clean"):
            findings = [
                f"[{p['pass']}] {f['file']}:{f['line']} {f['message']}"
                for p in lv.get("passes", ())
                for f in p.get("findings", ())
            ]
            print(json.dumps({
                "metric": "distinct_states_per_sec_raft3_cfg",
                "value": 0,
                "unit": "distinct states/s",
                "vs_baseline": None,
                "error": "strict lint FAILED: kernel contract findings "
                         "refuse publication (RAFT_TPU_BENCH_NO_LINT=1 "
                         "to override)",
                "detail": {"lint": lint_row, "findings": findings[:10]},
            }))
            return 1

    # live telemetry for the headline run: the JSONL stream is the
    # benchmark's provenance record (manifest = engine geometry + device;
    # wave events = the trajectory below), schema-checked after the run
    metrics_path = os.environ.get(
        "BENCH_METRICS_OUT", "/tmp/bench_metrics.jsonl")
    tel = Telemetry(metrics_path=metrics_path)

    # 0. build at FINAL capacities (growth would retrace the chunk
    # program mid-run: ~100 s each through the remote-compile service)
    # and warm every program signature before anything is timed.
    t0 = time.perf_counter()
    big = DeviceBFS(
        model, invariants=invs, symmetry=True, chunk=chunk,
        frontier_cap=1 << 22, seen_cap=1 << 25, journal_cap=1 << 25,
        max_frontier_cap=1 << 22, max_seen_cap=1 << 25,
        max_journal_cap=1 << 25,
    )
    big.precompile(telemetry=tel)
    precompile_s = time.perf_counter() - t0
    floor_s = measure_floor()

    # 1. deep run: sustained rate under the time budget (the headline),
    # timed in a process region that compiles nothing
    deep = big.run(time_budget_s=budget, telemetry=tel)
    manifest = next(
        (e for e in tel.events if e["event"] == "manifest"), {})
    waves = tel.wave_events()
    trajectory = [
        {k: m[k] for k in ("depth", "new", "wave_s", "distinct_per_s")}
        for m in waves[-10:]
    ]
    deep_summary = tel.last_summary or {}
    tel.close()
    from scripts.check_metrics_schema import validate_file

    _, metrics_problems = validate_file(metrics_path)

    # optional perf-regression gate: when BENCH_GATE_BASELINE names a
    # baseline JSON (scripts/bench_gate.py format), the deep-run summary
    # is gated against it and the verdict rides the provenance block —
    # the bench stays a measurement, the gate verdict travels with it
    gate_baseline = os.environ.get("BENCH_GATE_BASELINE")
    bench_gate_verdict = None
    if gate_baseline:
        from scripts.bench_gate import evaluate as gate_evaluate

        try:
            with open(gate_baseline) as fh:
                bench_gate_verdict = gate_evaluate(deep_summary, json.load(fh))
        except (OSError, ValueError) as e:
            bench_gate_verdict = {"error": f"{type(e).__name__}: {e}"}
        bench_gate_verdict["baseline_file"] = gate_baseline

    # 2. parity gate at a second chunk geometry (defense against the
    # batch-geometry miscompile class, ops/bag.py)
    small_chunk = chunk // 2 if chunk // 2 >= 128 else chunk * 2
    small_fcap = ((1 << 17) + small_chunk - 1) // small_chunk * small_chunk
    small = DeviceBFS(
        model, invariants=invs, symmetry=True, chunk=small_chunk,
        frontier_cap=small_fcap, seen_cap=1 << 21, journal_cap=1 << 21,
    )
    gate = parity_gate(depth=gate_depth, checkers=(small, big))
    if not gate.ok:
        print(json.dumps({
            "metric": "distinct_states_per_sec_raft3_cfg",
            "value": 0,
            "unit": "distinct states/s",
            "vs_baseline": None,
            "error": "parity gate FAILED: chunk-geometry-dependent counts",
            "detail": {"chunks": list(gate.chunks),
                       "counts": [list(c) for c in gate.counts]},
        }))
        return 1

    # 3. same-depth comparison (workload identical on every side).
    # The engine is warm — this times execution, not compilation.
    t0 = time.perf_counter()
    tpu_cmp = big.run(max_depth=cmp_depth)
    t_tpu = time.perf_counter() - t0

    from raft_tpu.models.registry import oracle_for_setup

    oracle = oracle_for_setup(setup)
    t0 = time.perf_counter()
    ores = oracle.bfs(invariants=invs, symmetry=True, max_depth=cmp_depth,
                      time_budget_s=4 * budget)
    t_oracle = time.perf_counter() - t0
    same_workload = (
        ores["distinct"] == tpu_cmp.distinct
        and ores["depth_counts"] == tpu_cmp.depth_counts
    )
    cmp_note = None
    if not same_workload:
        cmp_note = (
            "oracle hit its own time budget before the comparison depth"
            if len(ores["depth_counts"]) - 1 < cmp_depth
            else "oracle counts diverge from device counts"
        )

    # 3b. strong CPU baseline: the SAME engine on the XLA CPU backend,
    # same depth-capped workload (subprocess: JAX platform is
    # process-global)
    import subprocess

    strong = None
    try:
        out_cpu = subprocess.run(
            [sys.executable, "scripts/cpu_baseline.py", CFG,
             str(cmp_depth), str(chunk), "32"],
            capture_output=True, text=True, timeout=40 * 60,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        strong = json.loads(out_cpu.stdout.strip().splitlines()[-1])
    except Exception as e:  # keep the bench alive; record why
        strong = {"error": f"{type(e).__name__}: {e}"}
    strong_match = (
        "error" not in strong
        and strong.get("distinct") == tpu_cmp.distinct
        and list(strong.get("depth_counts", [])) == list(tpu_cmp.depth_counts)
    )

    out = {
        "metric": "distinct_states_per_sec_raft3_cfg",
        "value": round(deep.states_per_sec, 1),
        "unit": "distinct states/s",
        "vs_baseline": (
            round(t_oracle / t_tpu, 2) if t_tpu > 0 and same_workload else None
        ),
        "vs_strong_baseline": (
            round(strong["seconds"] / t_tpu, 2)
            if t_tpu > 0 and strong_match else None
        ),
        "detail": {
            "deep": {
                "distinct": deep.distinct,
                "depth": deep.depth,
                "exhausted": deep.exhausted,
                "seconds": round(deep.seconds, 2),
                "violation": deep.violation.invariant if deep.violation else None,
                # action-coverage digest: a rate number also says how
                # much of the Next relation the run exercised
                "coverage": (
                    coverage_digest(model.ACTION_NAMES, deep.coverage)
                    if deep.coverage is not None
                    and getattr(model, "ACTION_NAMES", None) else None
                ),
            },
            "dispatch_floor_ms": round(floor_s * 1e3, 1),
            "precompile_s": round(precompile_s, 1),
            "wave_trajectory": trajectory,
            # provenance from the telemetry manifest/summary events
            "manifest": {
                k: manifest.get(k)
                for k in ("ident", "hashv", "canon_memo_cap", "device",
                          "platform", "chunk")
            },
            "exit_cause": deep_summary.get("exit_cause"),
            "canon_memo_hit_rate": deep_summary.get("canon_memo_hit_rate"),
            "emit_micro": _emit_micro_summary(),
            "metrics_file": {
                "path": metrics_path,
                "schema_ok": not metrics_problems,
                "problems": metrics_problems[:5],
            },
            "bench_gate": bench_gate_verdict,
            "lint": lint_row,
            "same_depth_cmp": {
                "depth": cmp_depth,
                "distinct": tpu_cmp.distinct,
                "tpu_s": round(t_tpu, 2),
                "oracle_s": round(t_oracle, 2),
                "counts_match": same_workload,
                "note": cmp_note,
            },
            "strong_baseline_cpu": strong,
            "parity_gate": str(gate),
        },
        "baseline_kind": (
            "in-repo python oracle (TLC stand-in): wall-clock ratio on the "
            "identical same-depth workload; value is the deep-run sustained rate"
        ),
    }
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    if "--sweep" in sys.argv[1:]:
        sys.exit(sweep_main())
    sys.exit(repro_main() if "--repro" in sys.argv[1:] else main())
