"""Benchmark entry point (driver-run, real TPU).

Workload: BASELINE.md row 1 — the reference `standard-raft/Raft.cfg` state
space on the device-resident checker (DeviceBFS), reported as sustained
distinct-states/sec over a time-budgeted deep run.

Protocol (round-2 verdict items 1 and Weak #6; cmp ordered before the
gate in round 4 — see the in-code note on tunnel dispatch-floor drift):
  1. vs_baseline is measured on the SAME workload both sides: wall-clock
     to the same depth cap (BENCH_CMP_DEPTH, default 16) for the Python
     oracle (the TLC stand-in; reference publishes no numbers and TLC is
     not in this image) and for DeviceBFS. vs_baseline = t_oracle / t_tpu;
     vs_strong_baseline divides by the SAME engine on the XLA CPU backend.
  2. Parity gate before any number is emitted: depths 1..GATE_DEPTH at
     two chunk geometries must produce bit-identical per-depth counts
     (defense against the axon batch-geometry miscompile class fixed in
     ops/bag.py). A gate failure prints value 0 and exits nonzero.
  3. value is the deep-run sustained rate (time budget
     BENCH_TIME_BUDGET_S, default 300 s), reported with depth/distinct
     detail so depth-dependent rate growth is visible rather than hidden.

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}
"""

import json
import os
import sys
import time

CFG = "/root/reference/specifications/standard-raft/Raft.cfg"


def main():
    budget = float(os.environ.get("BENCH_TIME_BUDGET_S", "300"))
    cmp_depth = int(os.environ.get("BENCH_CMP_DEPTH", "16"))
    gate_depth = int(os.environ.get("BENCH_GATE_DEPTH", "12"))
    chunk = int(os.environ.get("BENCH_CHUNK", "4096"))
    deep_caps = dict(
        frontier_cap=1 << 20,
        seen_cap=1 << 23,
        journal_cap=1 << 23,
        max_frontier_cap=1 << 22,
        max_seen_cap=1 << 25,
        max_journal_cap=1 << 25,
    )

    from raft_tpu.utils.cfg import parse_cfg
    from raft_tpu.models.registry import build_from_cfg
    from raft_tpu.checker.device_bfs import DeviceBFS
    from raft_tpu.checker.parity import parity_gate

    cfg = parse_cfg(CFG)
    setup = build_from_cfg(cfg, msg_slots=32)
    model, invs = setup.model, setup.invariants

    def device(ch, **caps):
        return DeviceBFS(model, invariants=invs, symmetry=True, chunk=ch, **caps)

    # 1. same-depth comparison FIRST (workload identical both sides).
    # Ordering note: long tunnel-connected processes develop a ~100 ms
    # per-dispatch floor after heavy compile activity, and the shallow
    # cmp run is dispatch-latency-bound (small waves) — measured 16 s in
    # a young process vs 30-50 s after the gate's compiles. The gate
    # still validates below BEFORE any number is emitted.
    big = device(chunk, **deep_caps)
    big.run(max_depth=1)  # compile outside the timed window
    t0 = time.perf_counter()
    tpu_cmp = big.run(max_depth=cmp_depth)
    t_tpu = time.perf_counter() - t0

    # 2. parity gate: a small-geometry arm at a DIFFERENT chunk size,
    # plus an arm at the exact deep-run geometry (the big instance is
    # reused for the deep run below)
    small_chunk = chunk // 2 if chunk // 2 >= 128 else chunk * 2
    small_fcap = ((1 << 17) + small_chunk - 1) // small_chunk * small_chunk
    small = device(small_chunk, frontier_cap=small_fcap,
                   seen_cap=1 << 21, journal_cap=1 << 21)
    gate = parity_gate(depth=gate_depth, checkers=(small, big))
    if not gate.ok:
        print(json.dumps({
            "metric": "distinct_states_per_sec_raft3_cfg",
            "value": 0,
            "unit": "distinct states/s",
            "vs_baseline": None,
            "error": "parity gate FAILED: chunk-geometry-dependent counts",
            "detail": {"chunks": list(gate.chunks),
                       "counts": [list(c) for c in gate.counts]},
        }))
        return 1

    from raft_tpu.models.registry import oracle_for_setup

    oracle = oracle_for_setup(setup)
    t0 = time.perf_counter()
    ores = oracle.bfs(invariants=invs, symmetry=True, max_depth=cmp_depth,
                      time_budget_s=4 * budget)
    t_oracle = time.perf_counter() - t0
    same_workload = (
        ores["distinct"] == tpu_cmp.distinct
        and ores["depth_counts"] == tpu_cmp.depth_counts
    )
    # a null vs_baseline must say WHY (round-3 verdict Weak #6: a slow-day
    # oracle timeout silently reads as "not measured")
    cmp_note = None
    if not same_workload:
        cmp_note = (
            "oracle hit its own time budget before the comparison depth"
            if len(ores["depth_counts"]) - 1 < cmp_depth
            else "oracle counts diverge from device counts"
        )

    # 2b. strong CPU baseline (round-4 verdict Next #5): the SAME engine
    # on the XLA CPU backend (vectorized single-core on this host), same
    # depth-capped workload, compile excluded — a far stronger denominator
    # than the interpreted python oracle. Subprocess because the JAX
    # platform is process-global.
    import subprocess

    strong = None
    try:
        out_cpu = subprocess.run(
            [sys.executable, "scripts/cpu_baseline.py", CFG,
             str(cmp_depth), str(chunk), "32"],
            capture_output=True, text=True, timeout=40 * 60,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        strong = json.loads(out_cpu.stdout.strip().splitlines()[-1])
    except Exception as e:  # keep the bench alive; record why
        strong = {"error": f"{type(e).__name__}: {e}"}
    strong_match = (
        "error" not in strong
        and strong.get("distinct") == tpu_cmp.distinct
        and list(strong.get("depth_counts", [])) == list(tpu_cmp.depth_counts)
    )

    # 3. deep run: sustained rate under the time budget
    deep = big.run(time_budget_s=budget)

    out = {
        "metric": "distinct_states_per_sec_raft3_cfg",
        "value": round(deep.states_per_sec, 1),
        "unit": "distinct states/s",
        # the ratio is only meaningful on the identical workload: null it
        # out if the oracle diverged or was cut short by its own budget
        "vs_baseline": (
            round(t_oracle / t_tpu, 2) if t_tpu > 0 and same_workload else None
        ),
        # same-engine-on-CPU wall-clock ratio, identical workload: the
        # honest "optimized CPU checker" yardstick (BASELINE.md §strong)
        "vs_strong_baseline": (
            round(strong["seconds"] / t_tpu, 2)
            if t_tpu > 0 and strong_match else None
        ),
        "detail": {
            "deep": {
                "distinct": deep.distinct,
                "depth": deep.depth,
                "exhausted": deep.exhausted,
                "seconds": round(deep.seconds, 2),
                "violation": deep.violation.invariant if deep.violation else None,
            },
            "same_depth_cmp": {
                "depth": cmp_depth,
                "distinct": tpu_cmp.distinct,
                "tpu_s": round(t_tpu, 2),
                "oracle_s": round(t_oracle, 2),
                "counts_match": same_workload,
                "note": cmp_note,
            },
            "strong_baseline_cpu": strong,
            "parity_gate": str(gate),
        },
        "baseline_kind": (
            "in-repo python oracle (TLC stand-in): wall-clock ratio on the "
            "identical same-depth workload; value is the deep-run sustained rate"
        ),
    }
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
