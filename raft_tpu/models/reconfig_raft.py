"""TPU lowering of the thesis-style add/remove reconfiguration Raft spec.

Reference: ``/root/reference/specifications/standard-raft/
RaftWithReconfigAddRemove.tla`` (1,083 lines). Every action kernel cites
the TLA+ lines it lowers.

Structural notes:
  - log entries are (command, term, value) records where the value of a
    config command carries (id, new/old member, member set); entries lower
    to six parallel per-server lane arrays, with member sets as bitmasks;
  - the current config is DERIVED state — ``MostRecentReconfigEntry:252``
    + ``ConfigFor:265`` — lowered to a masked lane max + gather;
  - snapshot messages embed the sender's whole log
    (``SendSnapshot:862-876``), so records pack into N-word WidePacker
    keys with one packed field set per log lane; the ``msg_word`` layout
    kind + generalized Canonicalizer handle N-word bags;
  - ``nextIndex`` carries the snapshot sentinels ``-1``/``-2``
    (``:271-272``) directly in its int32 lanes;
  - quorums are popcount thresholds over the config-member bitmask —
    replacing the ``SUBSET``-based ``Quorum:169`` the reference itself
    flags as a TLC hot spot ("Very inefficient for TLC - TODO replace");
  - ``ResetWithSameIdentity:385`` is enabled in ``Next:965``; its
    ``CHOOSE``-a-leader is lowered as lowest-index-first.

Derived bounds: terms in [0, 1+MaxElections] (term 0 = never-member);
log length <= 1 (InitClusterCommand) + min(|Value|, terms*MaxValuesPerTerm)
+ MaxAddReconfigs + MaxRemoveReconfigs; config ids <= 1 + MaxAddReconfigs
+ MaxRemoveReconfigs.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import bag
from ..ops.packing import EMPTY, WidePacker, bits_for
from .base import Layout, messages_are_valid_kernel

from .config_common import (  # shared enums: single source of truth
    ACK_FALSE, ACK_NIL, ACK_TRUE, CANDIDATE, FOLLOWER, LEADER, NIL,
    NOTMEMBER, PENDING_SNAP_REQUEST, PENDING_SNAP_RESPONSE,
    AEREQ, AERESP, RVREQ, RVRESP, SNAPREQ, SNAPRESP,
)

# log-entry commands (RaftWithReconfigAddRemove.tla:66-69); 0 = empty lane
CMD_NONE, CMD_INIT, CMD_APPEND, CMD_ADD, CMD_REMOVE = range(5)
CMD_NAMES = {
    CMD_INIT: "InitClusterCommand",
    CMD_APPEND: "AppendCommand",
    CMD_ADD: "AddServerCommand",
    CMD_REMOVE: "RemoveServerCommand",
}

# mtype (:78-80)
# AppendEntries result codes (:75); Ok=1 so 0 means "field absent"
RC_OK, RC_STALE, RC_MISMATCH, RC_NEEDSNAP = 1, 2, 3, 4


# Next-disjunct ranks (:943-965), for trace labels.
ENTRY_FIELDS = ("term", "cmd", "val", "cid", "cmem", "cmembers")

(
    A_RESTART,
    A_UPDATETERM,
    A_REQUESTVOTE,
    A_BECOMELEADER,
    A_HANDLE_RVREQ,
    A_HANDLE_RVRESP,
    A_CLIENTREQUEST,
    A_ADVANCECOMMIT,
    A_APPENDENTRIES,
    A_REJECT_AE,
    A_ACCEPT_AE,
    A_HANDLE_AERESP,
    A_APPEND_ADD,
    A_APPEND_REMOVE,
    A_SENDSNAP,
    A_HANDLE_SNAPREQ,
    A_HANDLE_SNAPRESP,
    A_RESET_IDENTITY,
) = range(18)

from .config_common import (
    ConfigRaftCommon,
    MTYPE_NAMES,
    RC_NAMES,
    R_ACCEPT_AE as _R_AC,
    R_APPENDENTRIES as _R_AE,
    R_CLIENTREQUEST as _R_CR,
    R_HANDLE_AERESP as _R_HA,
    R_HANDLE_RVREQ as _R_HQ,
    R_HANDLE_RVRESP as _R_HP,
    R_HANDLE_SNAPREQ as _R_SQ,
    R_HANDLE_SNAPRESP as _R_SP,
    R_REJECT_AE as _R_RJ,
    R_REQUESTVOTE as _R_RV,
    R_RESTART as _R_RS,
    R_SENDSNAP as _R_SS,
    R_UPDATETERM as _R_UT,
)

# the mixin's kernels emit the shared rank constants; both variants lay
# their Next out so these coincide (config_common.py docstring)
assert (A_RESTART, A_REQUESTVOTE, A_CLIENTREQUEST,
        A_APPENDENTRIES, A_SENDSNAP) == (
    _R_RS, _R_RV, _R_CR, _R_AE, _R_SS)
assert (A_UPDATETERM, A_HANDLE_RVREQ, A_HANDLE_RVRESP,
        A_REJECT_AE, A_ACCEPT_AE, A_HANDLE_AERESP,
        A_HANDLE_SNAPREQ, A_HANDLE_SNAPRESP) == (
    _R_UT, _R_HQ, _R_HP, _R_RJ, _R_AC, _R_HA, _R_SQ, _R_SP)

ACTION_NAMES = [
    "Restart",
    "UpdateTerm",
    "RequestVote",
    "BecomeLeader",
    "HandleRequestVoteRequest",
    "HandleRequestVoteResponse",
    "ClientRequest",
    "AdvanceCommitIndex",
    "AppendEntries",
    "RejectAppendEntriesRequest",
    "AcceptAppendEntriesRequest",
    "HandleAppendEntriesResponse",
    "AppendAddServerCommandToLog",
    "AppendRemoveServerCommandToLog",
    "SendSnapshot",
    "HandleSnapshotRequest",
    "HandleSnapshotResponse",
    "ResetWithSameIdentity",
]


@dataclass(frozen=True)
class ReconfigRaftParams:
    n_servers: int
    n_values: int
    init_cluster_size: int
    max_elections: int
    max_restarts: int
    max_values_per_term: int
    max_add_reconfigs: int
    max_remove_reconfigs: int
    min_cluster_size: int
    max_cluster_size: int
    include_thesis_bug: bool = False
    msg_slots: int = 96

    @property
    def max_term(self) -> int:
        return 1 + self.max_elections

    @property
    def max_cfg_id(self) -> int:
        return 1 + self.max_add_reconfigs + self.max_remove_reconfigs

    @property
    def max_log(self) -> int:
        appends = min(self.n_values, self.max_term * self.max_values_per_term)
        return 1 + appends + self.max_add_reconfigs + self.max_remove_reconfigs


# per-lane log-entry field widths (shared by state arrays and message keys)
def _entry_fields(p: ReconfigRaftParams) -> list[tuple[str, int]]:
    tb = bits_for(p.max_term)
    return [
        ("term", tb),
        ("cmd", 3),
        ("val", bits_for(p.n_values)),
        ("cid", bits_for(p.max_cfg_id)),
        ("cmem", bits_for(p.n_servers)),  # new/old member, nil-valued
        ("cmembers", p.n_servers),  # member-set bitmask
    ]


def _build_layout(p: ReconfigRaftParams, n_words: int) -> Layout:
    S, V, L, M = p.n_servers, p.n_values, p.max_log, p.msg_slots
    lay = Layout(S)
    # VIEW (:159) = messages, serverVars, candidateVars, leaderVars,
    # logVars. ALL aux vars (acked + five counters) are excluded.
    lay.add("config_id", "per_server", (S,))
    lay.add("config_members", "server_bitmask", (S,))
    lay.add("config_committed", "per_server", (S,))
    lay.add("currentTerm", "per_server", (S,))
    lay.add("state", "per_server", (S,))
    lay.add("votedFor", "per_server_val", (S,))
    lay.add("votesGranted", "server_bitmask", (S,))
    lay.add("log_term", "per_server", (S, L))
    lay.add("log_cmd", "per_server", (S, L))
    lay.add("log_val", "per_server", (S, L))
    lay.add("log_cid", "per_server", (S, L))
    lay.add("log_cmem", "per_server_val", (S, L))  # 0 = none, i+1 = server i
    lay.add("log_cmembers", "server_bitmask", (S, L))
    lay.add("log_len", "per_server", (S,))
    lay.add("commitIndex", "per_server", (S,))
    lay.add("nextIndex", "per_server_pair", (S, S))  # may hold -1/-2
    lay.add("matchIndex", "per_server_pair", (S, S))
    lay.add("pendingResponse", "server_bitmask", (S,))
    for k in range(n_words):
        lay.add(f"msg_w{k}", "msg_word", (M,))
    lay.add("msg_cnt", "msg_cnt", (M,))
    lay.add("acked", "aux", (V,))
    lay.add("electionCtr", "aux")
    lay.add("restartCtr", "aux")
    lay.add("addReconfigCtr", "aux")
    lay.add("removeReconfigCtr", "aux")
    lay.add("valueCtr", "aux", (p.max_term,))
    return lay.finish()


def _build_packer(p: ReconfigRaftParams) -> WidePacker:
    tb = bits_for(p.max_term)
    sb = bits_for(p.n_servers - 1)
    lb = bits_for(p.max_log + 1)
    ef = _entry_fields(p)
    fields = [
        ("mtype", 3),
        ("mterm", tb),
        ("msource", sb),
        ("mdest", sb),
        ("mlastLogTerm", tb),  # RequestVoteRequest (:437-442)
        ("mlastLogIndex", lb),
        ("mvoteGranted", 1),  # RequestVoteResponse (:465-470)
        ("mprevLogIndex", lb),  # AppendEntriesRequest (:563-570)
        ("mprevLogTerm", tb),
        ("nentries", 1),
        *[(f"e_{n}", w) for n, w in ef],  # the <=1 entry
        ("mcommitIndex", lb),  # also SnapshotRequest (:873)
        ("mresult", 3),  # AppendEntriesResponse (:685-691)
        ("mmatchIndex", lb),  # also SnapshotResponse (:900)
        ("msuccess", 1),  # SnapshotResponse (:897-902)
        ("mloglen", lb),  # SnapshotRequest embedded log (:872)
        ("mmembers", p.n_servers),
        *[(f"l{k}_{n}", w) for k in range(p.max_log) for n, w in ef],
    ]
    for n_words in range(2, 12):
        try:
            return WidePacker(fields, n_words)
        except ValueError:
            continue
    raise ValueError("message schema too wide")


def cached_model(params: "ReconfigRaftParams") -> "ReconfigRaftModel":
    return _cached_model(params)


class ReconfigRaftModel(ConfigRaftCommon):
    """Vectorized successor/invariant kernels for one (spec, constants) pair."""

    name = "RaftWithReconfigAddRemove"
    ENTRY_FIELDS = ENTRY_FIELDS
    CMD_SEED = CMD_INIT  # Init's seeded first entry (:324-338)
    MEMBERS_FIELD = "cmembers"
    CMD_APPEND = CMD_APPEND
    ACTION_NAMES = ACTION_NAMES

    def __init__(self, params, server_names=None, value_names=None):
        self.p = params
        self.packer = _build_packer(params)
        self.n_words = self.packer.n_words
        self.layout = _build_layout(params, self.n_words)
        S, V, M, L = params.n_servers, params.n_values, params.msg_slots, params.max_log
        self.server_names = list(server_names or [f"s{i+1}" for i in range(S)])
        self.value_names = list(value_names or [f"v{i+1}" for i in range(V)])

        # symmetry contract: packed fields that transform under sigma
        spec = [("msource", "server"), ("mdest", "server"),
                ("e_cmem", "server_nil"), ("e_cmembers", "server_bitmask"),
                ("mmembers", "server_bitmask")]
        for k in range(L):
            spec.append((f"l{k}_cmem", "server_nil"))
            spec.append((f"l{k}_cmembers", "server_bitmask"))
        self.msg_perm_spec = tuple(spec)

        # Candidate table: non-receipt disjuncts in Next order (:943-965),
        # receipt disjuncts fused per slot at the end.
        self._all_pairs = [(i, j) for i in range(S) for j in range(S)]
        self._finish_init()

    # ---------------- field access helpers ----------------

    def _mrce(self, d, i):
        """MostRecentReconfigEntry over log[i] — :252-258. Returns
        (index, cid, cmembers); index 0 = no config command (callers gate
        on reachability, member logs always carry InitClusterCommand)."""
        L = self.p.max_log
        lanes = jnp.arange(L, dtype=jnp.int32)
        cmd = d["log_cmd"][i]
        is_cfg = (cmd == CMD_INIT) | (cmd == CMD_ADD) | (cmd == CMD_REMOVE)
        mask = (lanes < d["log_len"][i]) & is_cfg
        idx = jnp.max(jnp.where(mask, lanes + 1, 0))
        pos = jnp.clip(idx - 1, 0)
        return idx, d["log_cid"][i][pos], d["log_cmembers"][i][pos]

    # ---------------- action kernels ----------------

    def _become_leader(self, s, i):
        """BecomeLeader(i) — :505-518: votesGranted must be a quorum OF the
        member set (subset + majority)."""
        S = self.p.n_servers
        d = self._dec(s)
        members = d["config_members"][i]
        vg = d["votesGranted"][i]
        subset = (vg & ~members) == 0
        quorum = 2 * self._popcount(vg, S) > self._popcount(members, S)
        valid = (d["state"][i] == CANDIDATE) & subset & quorum
        succ = self._asm(
            d,
            state=d["state"].at[i].set(LEADER),
            nextIndex=d["nextIndex"].at[i].set(
                jnp.full((S,), 1, jnp.int32) * (d["log_len"][i] + 1)
            ),
            matchIndex=d["matchIndex"].at[i].set(jnp.zeros((S,), jnp.int32)),
            pendingResponse=d["pendingResponse"].at[i].set(0),
        )
        return valid, succ, jnp.int32(A_BECOMELEADER), jnp.asarray(False)

    def _commit_quorum_ok(self, d, i, idxs, match_row, ks):
        """Member-set quorum with leader self-inclusion (:612-618)."""
        S = self.p.n_servers
        members = d["config_members"][i]
        member_k = ((members >> ks) & 1) > 0  # [S]
        in_agree = member_k[None, :] & (
            (match_row[None, :] >= idxs[:, None]) | (ks[None, :] == i)
        )
        return 2 * jnp.sum(in_agree, axis=1) > self._popcount(members, S)

    def _commit_config_upd(self, d, i, new_ci) -> dict:
        """Config re-derivation (:627-632)."""
        cfg_idx, cfg_id, cfg_members = self._mrce(d, i)
        cfg_committed = (new_ci >= cfg_idx).astype(jnp.int32)
        return dict(
            config_id=d["config_id"].at[i].set(cfg_id),
            config_members=d["config_members"].at[i].set(cfg_members),
            config_committed=d["config_committed"].at[i].set(cfg_committed),
        )

    def _commit_removed(self, d, i, in_range):
        """IsRemovedFromCluster (:598-603)."""
        return jnp.any(
            in_range
            & (d["log_cmd"][i] == CMD_REMOVE)
            & (((d["log_cmembers"][i] >> i) & 1) == 0)
        )

    def _append_add(self, s, i, a):
        """AppendAddServerCommandToLog(i, a) — :795-824."""
        p, S, L = self.p, self.p.n_servers, self.p.max_log
        d = self._dec(s)
        members = d["config_members"][i]
        valid = (
            (d["state"][i] == LEADER)
            & (d["addReconfigCtr"] < p.max_add_reconfigs)
            & (self._popcount(members, S) < p.max_cluster_size)
            & (d["config_committed"][i] > 0)  # ~HasPendingConfigCommand (:248)
            & (((members >> a) & 1) == 0)
        )
        if not p.include_thesis_bug:
            # LeaderHasCommittedEntriesInCurrentTerm (:275-278)
            lanes = jnp.arange(L, dtype=jnp.int32)
            has_committed = jnp.any(
                (lanes < d["log_len"][i])
                & (d["log_term"][i] == d["currentTerm"][i])
                & (d["commitIndex"][i] >= lanes + 1)
            )
            valid &= has_committed
        new_members = members | (jnp.int32(1) << a)
        new_id = d["config_id"][i] + 1
        pos = d["log_len"][i]
        ovf = valid & (pos >= L)
        posc = jnp.clip(pos, 0, L - 1)
        succ = self._asm(
            d,
            log_term=d["log_term"].at[i, posc].set(d["currentTerm"][i]),
            log_cmd=d["log_cmd"].at[i, posc].set(CMD_ADD),
            log_cid=d["log_cid"].at[i, posc].set(new_id),
            log_cmem=d["log_cmem"].at[i, posc].set(a + 1),
            log_cmembers=d["log_cmembers"].at[i, posc].set(new_members),
            log_len=d["log_len"].at[i].add(1),
            config_id=d["config_id"].at[i].set(new_id),
            config_members=d["config_members"].at[i].set(new_members),
            # committed = ci >= Len(newLog) — always FALSE here (:814-816)
            config_committed=d["config_committed"].at[i].set(
                (d["commitIndex"][i] >= pos + 1).astype(jnp.int32)
            ),
            addReconfigCtr=d["addReconfigCtr"] + 1,
            nextIndex=d["nextIndex"].at[i, a].set(PENDING_SNAP_REQUEST),
        )
        return valid, succ, jnp.int32(A_APPEND_ADD), ovf

    def _append_remove(self, s, i, r):
        """AppendRemoveServerCommandToLog(i, r) — :828-853."""
        p, S, L = self.p, self.p.n_servers, self.p.max_log
        d = self._dec(s)
        members = d["config_members"][i]
        valid = (
            (d["state"][i] == LEADER)
            & (d["removeReconfigCtr"] < p.max_remove_reconfigs)
            & (self._popcount(members, S) > p.min_cluster_size)
            & (d["config_committed"][i] > 0)
            & (((members >> r) & 1) > 0)
        )
        if not p.include_thesis_bug:
            lanes = jnp.arange(L, dtype=jnp.int32)
            has_committed = jnp.any(
                (lanes < d["log_len"][i])
                & (d["log_term"][i] == d["currentTerm"][i])
                & (d["commitIndex"][i] >= lanes + 1)
            )
            valid &= has_committed
        new_members = members & ~(jnp.int32(1) << r)
        new_id = d["config_id"][i] + 1
        pos = d["log_len"][i]
        ovf = valid & (pos >= L)
        posc = jnp.clip(pos, 0, L - 1)
        succ = self._asm(
            d,
            log_term=d["log_term"].at[i, posc].set(d["currentTerm"][i]),
            log_cmd=d["log_cmd"].at[i, posc].set(CMD_REMOVE),
            log_cid=d["log_cid"].at[i, posc].set(new_id),
            log_cmem=d["log_cmem"].at[i, posc].set(r + 1),
            log_cmembers=d["log_cmembers"].at[i, posc].set(new_members),
            log_len=d["log_len"].at[i].add(1),
            config_id=d["config_id"].at[i].set(new_id),
            config_members=d["config_members"].at[i].set(new_members),
            config_committed=d["config_committed"].at[i].set(
                (d["commitIndex"][i] >= pos + 1).astype(jnp.int32)
            ),
            removeReconfigCtr=d["removeReconfigCtr"] + 1,
        )
        return valid, succ, jnp.int32(A_APPEND_REMOVE), ovf

    def _reset_with_same_identity(self, s, i):
        """ResetWithSameIdentity(i) — :385-400; CHOOSE-a-leader lowered as
        lowest index with IsCurrentLeader (:367-373)."""
        p, S = self.p, self.p.n_servers
        d = self._dec(s)
        ct = d["currentTerm"]
        is_cur_leader = (d["state"] == LEADER) & jnp.all(
            ct[:, None] >= ct[None, :], axis=1
        )
        exists = jnp.any(is_cur_leader)
        leader = jnp.argmax(is_cur_leader)  # lowest index
        valid = (
            (ct[i] > 0)
            & exists
            & (leader != i)
            & (((d["config_members"][leader] >> i) & 1) == 0)
            & (d["config_committed"][leader] > 0)
        )
        L = p.max_log
        succ = self._asm(
            d,
            state=d["state"].at[i].set(NOTMEMBER),
            config_id=d["config_id"].at[i].set(0),
            config_members=d["config_members"].at[i].set(0),
            config_committed=d["config_committed"].at[i].set(0),
            currentTerm=d["currentTerm"].at[i].set(0),
            votedFor=d["votedFor"].at[i].set(NIL),
            votesGranted=d["votesGranted"].at[i].set(0),
            nextIndex=d["nextIndex"].at[i].set(jnp.ones((S,), jnp.int32)),
            matchIndex=d["matchIndex"].at[i].set(jnp.zeros((S,), jnp.int32)),
            pendingResponse=d["pendingResponse"].at[i].set(0),
            commitIndex=d["commitIndex"].at[i].set(0),
            log_term=d["log_term"].at[i].set(jnp.zeros((L,), jnp.int32)),
            log_cmd=d["log_cmd"].at[i].set(jnp.zeros((L,), jnp.int32)),
            log_val=d["log_val"].at[i].set(jnp.zeros((L,), jnp.int32)),
            log_cid=d["log_cid"].at[i].set(jnp.zeros((L,), jnp.int32)),
            log_cmem=d["log_cmem"].at[i].set(jnp.zeros((L,), jnp.int32)),
            log_cmembers=d["log_cmembers"].at[i].set(jnp.zeros((L,), jnp.int32)),
            log_len=d["log_len"].at[i].set(0),
        )
        return valid, succ, jnp.int32(A_RESET_IDENTITY), jnp.asarray(False)

    # -------- fused message-receipt kernel (slot m) --------

    def _is_cfg_cmd(self, cmd):
        """InitCluster / AddServer / RemoveServer entries carry a
        configuration (:66-69); hook for the shared receipt kernel."""
        return (cmd == CMD_INIT) | (cmd == CMD_ADD) | (cmd == CMD_REMOVE)

    def _config_updates_from_log(self, d, dst, logs, cfg_pos, cfg_idx, mci):
        """Config cache from the most recent config entry (:734-739):
        id, member set, committed watermark; in_new = membership of dst
        in the installed member set."""
        cmembers = logs["cmembers"][cfg_pos]
        upd = dict(
            config_id=d["config_id"].at[dst].set(logs["cid"][cfg_pos]),
            config_members=d["config_members"].at[dst].set(cmembers),
            config_committed=d["config_committed"].at[dst].set(
                (mci >= cfg_idx).astype(jnp.int32)
            ),
        )
        in_new = ((cmembers >> dst) & 1) > 0
        return upd, in_new

    # ---------------- full expansion ----------------

    def _kernel_overrides(self) -> dict:
        return {
            "AppendAddServerCommandToLog": self._append_add,
            "AppendRemoveServerCommandToLog": self._append_remove,
        }

    def _config_bindings(self) -> list:
        b = []
        for ij in self._all_pairs:
            b.append(("AppendAddServerCommandToLog", ij))
        for ij in self._all_pairs:
            b.append(("AppendRemoveServerCommandToLog", ij))
        return b

    def _pre_msg_bindings(self) -> list:
        return [("ResetWithSameIdentity", (i,))
                for i in range(self.p.n_servers)]

    def _config_outs(self, s) -> list:
        import jax

        ap_i = jnp.asarray([ij[0] for ij in self._all_pairs], jnp.int32)
        ap_j = jnp.asarray([ij[1] for ij in self._all_pairs], jnp.int32)
        return [
            jax.vmap(lambda i, a: self._append_add(s, i, a))(ap_i, ap_j),
            jax.vmap(lambda i, r: self._append_remove(s, i, r))(ap_i, ap_j),
        ]

    def _pre_msg_outs(self, s, iota_s) -> list:
        import jax

        return [
            jax.vmap(lambda i: self._reset_with_same_identity(s, i))(iota_s)
        ]

    def _live_reconfig_p(self, states):
        """ReconfigurationCompletes antecedent — :992-996: some leader has
        a config command in its log."""
        lay, L = self.layout, self.p.max_log
        st = lay.get(states, "state")
        cmd = lay.get(states, "log_cmd")
        ll = lay.get(states, "log_len")
        lanes = jnp.arange(L, dtype=jnp.int32)
        is_cfg = (
            (cmd == CMD_INIT) | (cmd == CMD_ADD) | (cmd == CMD_REMOVE)
        ) & (lanes[None, None, :] < ll[..., None])
        return jnp.any((st == LEADER)[..., None] & is_cfg, axis=(1, 2))

    def _live_reconfig_q(self, states):
        """ReconfigurationCompletes consequent — :998-1005: some leader
        has a config command that every member of that entry's member set
        has replicated identically at the same index."""
        lay, S, L = self.layout, self.p.n_servers, self.p.max_log
        st = lay.get(states, "state")
        cmd = lay.get(states, "log_cmd")
        ll = lay.get(states, "log_len")
        lanes = jnp.arange(L, dtype=jnp.int32)
        is_cfg = (
            (cmd == CMD_INIT) | (cmd == CMD_ADD) | (cmd == CMD_REMOVE)
        ) & (lanes[None, None, :] < ll[..., None])
        # entry equality between server i and j at each lane: [B,S,S,L]
        eq = jnp.ones(st.shape[:1] + (S, S, L), dtype=bool)
        for n in ENTRY_FIELDS:
            f = lay.get(states, f"log_{n}")
            eq &= f[:, :, None, :] == f[:, None, :, :]
        in_log_j = lanes[None, None, None, :] < ll[:, None, :, None]  # [B,1,S,L]
        member_j = (
            (lay.get(states, "log_cmembers")[:, :, None, :]
             >> jnp.arange(S, dtype=jnp.int32)[None, None, :, None]) & 1
        ) > 0  # [B,S(i),S(j),L]
        ok_j = ~member_j | (in_log_j & eq)
        complete = jnp.all(ok_j, axis=2)  # [B,S,L]
        return jnp.any((st == LEADER)[..., None] & is_cfg & complete, axis=(1, 2))

    def _inv_max_one_reconfig(self, states):
        """MaxOneReconfigurationAtATime — :1031-1039."""
        lay, L = self.layout, self.p.max_log
        st = lay.get(states, "state")
        ci = lay.get(states, "commitIndex")
        cmd = lay.get(states, "log_cmd")
        ll = lay.get(states, "log_len")
        lanes = jnp.arange(1, L + 1, dtype=jnp.int32)
        is_cfg = (cmd == CMD_INIT) | (cmd == CMD_ADD) | (cmd == CMD_REMOVE)
        uncommitted = (
            is_cfg
            & (lanes[None, None, :] <= ll[:, :, None])
            & (lanes[None, None, :] > ci[:, :, None])
        )
        n_uncommitted = jnp.sum(uncommitted, axis=2)
        bad = (st == LEADER) & (n_uncommitted >= 2)
        return ~jnp.any(bad, axis=1)

    def _inv_committed_majority(self, states):
        """CommittedEntriesReachMajority — :1067-1078 (quorum drawn from
        config[i].members, exact majority size, i in quorum)."""
        lay, S, L = self.layout, self.p.n_servers, self.p.max_log
        st = lay.get(states, "state")
        ci = lay.get(states, "commitIndex")
        ll = lay.get(states, "log_len")
        members = lay.get(states, "config_members")
        lead = (st == LEADER) & (ci > 0)
        pos = jnp.clip(ci - 1, 0, L - 1)
        match = jnp.ones(st.shape[:1] + (S, S), dtype=bool)  # [B, i, j]
        for n in ENTRY_FIELDS:
            f = lay.get(states, f"log_{n}")  # [B,S,L]
            fi = jnp.take_along_axis(f, pos[:, :, None], axis=2)[:, :, 0]  # [B,S]
            fj = jnp.take_along_axis(
                jnp.broadcast_to(f[:, None, :, :], f.shape[:1] + (S,) + f.shape[1:]),
                jnp.broadcast_to(pos[:, :, None, None], pos.shape + (S, 1)),
                axis=3,
            )[..., 0]
            match &= fj == fi[..., None]
        match &= ll[:, None, :] >= ci[:, :, None]
        ks = jnp.arange(S, dtype=jnp.int32)
        member_j = ((members[:, :, None] >> ks[None, None, :]) & 1) > 0  # [B,i,j]
        agree = match & member_j
        n_members = jnp.sum(member_j, axis=2)
        eye = jnp.eye(S, dtype=bool)
        self_in = jnp.any(agree & eye[None, :, :], axis=2)  # i \in quorum
        enough = (jnp.sum(agree, axis=2) >= (n_members // 2 + 1)) & self_in
        ok_exists = jnp.any(lead & enough, axis=1)
        return ~jnp.any(lead, axis=1) | ok_exists

    # ---------------- host-side decode/encode ----------------

    def _decode_entry(self, term, cmd, val, cid, cmem, cmembers):
        cmd_name = CMD_NAMES[int(cmd)]
        members = frozenset(
            j for j in range(self.p.n_servers) if (int(cmembers) >> j) & 1
        )
        if cmd_name == "AppendCommand":
            return (cmd_name, int(term), int(val) - 1)
        if cmd_name == "InitClusterCommand":
            return (cmd_name, int(term), (int(cid), members))
        return (cmd_name, int(term), (int(cid), int(cmem) - 1, members))

    def _encode_entry(self, entry):
        cmd_name, term, val = entry
        inv_cmd = {v: k for k, v in CMD_NAMES.items()}
        cmd = inv_cmd[cmd_name]
        if cmd == CMD_APPEND:
            return dict(term=term, cmd=cmd, val=val + 1, cid=0, cmem=0, cmembers=0)
        if cmd == CMD_INIT:
            return dict(
                term=term, cmd=cmd, val=0, cid=val[0], cmem=0,
                cmembers=sum(1 << j for j in val[1]),
            )
        return dict(
            term=term, cmd=cmd, val=0, cid=val[0], cmem=val[1] + 1,
            cmembers=sum(1 << j for j in val[2]),
        )

    counter_fields = ("addReconfigCtr", "removeReconfigCtr")

    def _decode_config(self, g):
        return tuple(
            (
                int(g("config_id")[i]),
                self._fs(g("config_members")[i]),
                bool(g("config_committed")[i]),
            )
            for i in range(self.p.n_servers)
        )

    def _encode_config(self, vec, st) -> None:
        lay = self.layout
        vec[lay.sl("config_id")] = [c[0] for c in st["config"]]
        vec[lay.sl("config_members")] = [
            sum(1 << j for j in c[1]) for c in st["config"]
        ]
        vec[lay.sl("config_committed")] = [int(c[2]) for c in st["config"]]


@lru_cache(maxsize=None)
def _cached_model(params: "ReconfigRaftParams") -> "ReconfigRaftModel":
    return ReconfigRaftModel(params)
