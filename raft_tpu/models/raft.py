"""TPU lowering of the core Raft spec.

Reference: ``/root/reference/specifications/standard-raft/Raft.tla`` (652
lines). Every action kernel cites the TLA+ lines it lowers so parity can be
audited. The lowering is *not* a translation: actions become branchless,
``vmap``-able successor kernels over a packed int32 state vector; enabling
conditions become validity masks; ``CHOOSE``-determinism (Min/Max,
``Raft.tla:190-192``) is realized as lane reductions.

Derived bounds that make the encoding tight:
  - terms live in [1, 1+MaxElections]: only ``RequestVote`` (``Raft.tla:246``)
    mints a new term and it is gated by ``electionCtr < MaxElections``;
  - each value enters the log system at most once globally — the
    ``acked[v] = Nil`` gate (``Raft.tla:306``) never resets — so per-server
    log length is bounded by |Value| and entries keep their (index, term);
  - the message-bag DOMAIN grows monotonically (see ops/bag.py), so a
    behavior's distinct-message count bounds the slot table; overflow is a
    hard error surfaced to the driver, never silent.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import bag
from ..ops.packing import EMPTY, BitPacker, bits_for
from .base import (
    ActionLabelMixin,
    FleetConstMixin,
    Layout,
    SparseExpandMixin,
    messages_are_valid_kernel,
    onehot_row,
    onehot_set,
    onehot_set2,
)

# state[i] encoding (CONSTANTS Follower/Candidate/Leader, Raft.tla:38)
FOLLOWER, CANDIDATE, LEADER = 0, 1, 2
NIL = 0  # votedFor Nil (Raft.tla:41); server i is stored as i+1
ACK_NIL, ACK_FALSE, ACK_TRUE = 0, 1, 2  # acked[v] (Raft.tla:62-65)
RVREQ, RVRESP, AEREQ, AERESP = 1, 2, 3, 4  # mtype (Raft.tla:44-45)

# Next-disjunct order (Raft.tla:527-539), used for TLC-order tie-breaking.
(
    R_RESTART,
    R_REQUESTVOTE,
    R_BECOMELEADER,
    R_CLIENTREQUEST,
    R_ADVANCECOMMIT,
    R_APPENDENTRIES,
    R_UPDATETERM,
    R_HANDLE_RVREQ,
    R_HANDLE_RVRESP,
    R_REJECT_AE,
    R_ACCEPT_AE,
    R_HANDLE_AERESP,
) = range(12)
R_TIMEOUT, R_ADVANCEFSYNC = 12, 13  # RaftFsync-only disjuncts

ACTION_NAMES = [
    "Restart",
    "RequestVote",
    "BecomeLeader",
    "ClientRequest",
    "AdvanceCommitIndex",
    "AppendEntries",
    "UpdateTerm",
    "HandleRequestVoteRequest",
    "HandleRequestVoteResponse",
    "RejectAppendEntriesRequest",
    "AcceptAppendEntriesRequest",
    "HandleAppendEntriesResponse",
    "Timeout",
    "AdvanceFsyncIndex",
]

STATE_NAMES = {FOLLOWER: "Follower", CANDIDATE: "Candidate", LEADER: "Leader"}
MTYPE_NAMES = {
    RVREQ: "RequestVoteRequest",
    RVRESP: "RequestVoteResponse",
    AEREQ: "AppendEntriesRequest",
    AERESP: "AppendEntriesResponse",
}


@dataclass(frozen=True)
class RaftParams:
    n_servers: int
    n_values: int
    max_elections: int
    max_restarts: int
    msg_slots: int = 48
    # ---- variant knobs (defaults = standard-raft/Raft.tla) ----
    # FlexibleRaft (flexible-raft/FlexibleRaft.tla): count-based quorums
    # (FlexibleRaft.tla:262,296); None means strict majority.
    election_quorum: int | None = None
    replication_quorum: int | None = None
    # FlexibleRaft sends/replies strictly once: Send requires the record
    # not in DOMAIN (FlexibleRaft.tla:127-129) and Reply requires the
    # response not in DOMAIN (FlexibleRaft.tla:148-151).
    strict_send_once: bool = False
    # FlexibleRaft has no pendingResponse flow control (leaderVars,
    # FlexibleRaft.tla:109 vs Raft.tla:103-107).
    has_pending_response: bool = True
    # FlexibleRaft's NeedsTruncation is a term-mismatch test with no
    # empty-entries arm (FlexibleRaft.tla:413-416 vs Raft.tla:445-449).
    trunc_term_mismatch: bool = False
    # RaftFsync (raft-and-fsync/RaftFsync.tla): fsyncIndex var (:92),
    # crash-truncation to it (:211-216), split Timeout (:222) +
    # per-peer RequestVote(i,j) (:234), AdvanceFsyncIndex (:339), and
    # the three fsync policy constants (:50-52). Implies strict
    # send-once (:132-134,149-152), no pendingResponse, and
    # term-mismatch truncation (:441-444).
    has_fsync: bool = False
    fsync_leader_before_ae: bool = False  # LeaderFsyncBeforeAppendEntries
    fsync_leader_quorum: bool = False  # LeaderFsyncBeforeIncludeInQuorum
    fsync_follower_reply: bool = False  # FollowerFsyncBeforeReply
    # Opt-in network-fault actions (Raft.tla:508-523, commented out of
    # Next at :540-541): DuplicateMessage re-delivers a bag record,
    # DropMessage discards one delivery. Duplication is bounded by
    # max_msg_copies per record (the unbounded TLA+ form has an infinite
    # state space; documented divergence).
    net_faults: bool = False
    max_msg_copies: int = 2
    # Fleet packing (models/base.py FleetConstMixin): dyn_consts names
    # the params whose guards read a per-state lane instead of the
    # static value; fleet adds the job + constant lanes to the layout.
    dyn_consts: tuple = ()
    fleet: bool = False

    @property
    def max_term(self) -> int:
        return 1 + self.max_elections

    @property
    def max_log(self) -> int:
        return max(1, self.n_values)


def _build_layout(p: RaftParams) -> Layout:
    S, V, L, M = p.n_servers, p.n_values, p.max_log, p.msg_slots
    lay = Layout(S)
    # VIEW variables (Raft.tla:115): messages, serverVars, candidateVars,
    # leaderVars, logVars.
    lay.add("currentTerm", "per_server", (S,))
    lay.add("state", "per_server", (S,))
    lay.add("votedFor", "per_server_val", (S,))
    lay.add("votesGranted", "server_bitmask", (S,))  # set -> bitmask (Raft.tla:93)
    lay.add("log_term", "per_server", (S, L))
    lay.add("log_value", "per_server", (S, L))
    lay.add("log_len", "per_server", (S,))
    lay.add("commitIndex", "per_server", (S,))
    if p.has_fsync:
        lay.add("fsyncIndex", "per_server", (S,))  # RaftFsync.tla:92,117
    lay.add("nextIndex", "per_server_pair", (S, S))
    lay.add("matchIndex", "per_server_pair", (S, S))
    if p.has_pending_response:
        lay.add("pendingResponse", "server_bitmask", (S,))  # bool matrix -> bitmask
    lay.add("msg_hi", "msg_hi", (M,))
    lay.add("msg_lo", "msg_lo", (M,))
    lay.add("msg_cnt", "msg_cnt", (M,))
    if p.fleet:
        # Fleet config axis (models/base.py FleetConstMixin): VIEW
        # scalars so jobs never dedup against each other.
        lay.add("fleet_job", "scalar")
        for nm in p.dyn_consts:
            lay.add("c_" + nm, "scalar")
    # aux (VIEW-excluded: Raft.tla:60-68,115)
    lay.add("acked", "aux", (V,))
    lay.add("electionCtr", "aux")
    lay.add("restartCtr", "aux")
    return lay.finish()


def _build_packer(p: RaftParams) -> BitPacker:
    tb = bits_for(p.max_term)
    sb = bits_for(p.n_servers - 1)
    lb = bits_for(p.max_log + 1)  # indices in 0..L (+1 headroom for nextIndex-1 math)
    vb = bits_for(p.n_values)
    return BitPacker(
        [
            ("mtype", 3),
            ("mterm", tb),
            ("msource", sb),
            ("mdest", sb),
            ("mlastLogTerm", tb),  # RequestVoteRequest (Raft.tla:251-256)
            ("mlastLogIndex", lb),
            ("mvoteGranted", 1),  # RequestVoteResponse (Raft.tla:374-378)
            ("mprevLogIndex", lb),  # AppendEntriesRequest (Raft.tla:277-284)
            ("mprevLogTerm", tb),
            ("nentries", 1),  # <=1 entry per request (Raft.tla:260-274)
            ("eterm", tb),
            ("evalue", vb),
            ("mcommitIndex", lb),
            ("msuccess", 1),  # AppendEntriesResponse (Raft.tla:422-427,476-482)
            ("mmatchIndex", lb),
        ]
    )


def cached_model(params: "RaftParams") -> "RaftModel":
    """Memoized model factory: reusing one instance shares its jitted
    kernels (compile cost dominates small runs and the test suite)."""
    return _cached_model(params)


class RaftModel(SparseExpandMixin, FleetConstMixin, ActionLabelMixin):
    """Vectorized successor/invariant kernels for one (spec, constants) pair."""

    name = "Raft"

    def __init__(self, params: RaftParams, server_names=None, value_names=None):
        self.p = params
        # Variant-accurate rank table: plain Raft only emits ranks 0..11;
        # Timeout/AdvanceFsyncIndex (12/13) exist only with has_fsync.
        self.ACTION_NAMES = (
            list(ACTION_NAMES) if params.has_fsync else list(ACTION_NAMES[:12])
        )
        if params.net_faults:
            # Raft.tla:508-523 (commented out of Next at :540-541):
            # opt-in ranks appended past the variant's standard table.
            self._r_dup = len(self.ACTION_NAMES)
            self._r_drop = self._r_dup + 1
            self.ACTION_NAMES += ["DuplicateMessage", "DropMessage"]
        self.layout = _build_layout(params)
        self.packer = _build_packer(params)
        S, V, M = params.n_servers, params.n_values, params.msg_slots
        self.server_names = list(server_names or [f"s{i+1}" for i in range(S)])
        self.value_names = list(value_names or [f"v{i+1}" for i in range(V)])

        # Candidate table: Next-disjunct order (Raft.tla:527-539); the six
        # message-receipt disjuncts are mutually exclusive per record, so
        # they fuse into one kernel per slot (rank resolved dynamically).
        self.bindings: list[tuple[str, tuple]] = []
        self._ae_pairs = [(i, j) for i in range(S) for j in range(S) if i != j]
        for i in range(S):
            self.bindings.append(("Restart", (i,)))
        if params.has_fsync:
            # RaftFsync Next order (RaftFsync.tla:522-536): Timeout is split
            # from the per-peer RequestVote(i,j), and AdvanceFsyncIndex
            # follows AppendEntries.
            for i in range(S):
                self.bindings.append(("Timeout", (i,)))
            for ij in self._ae_pairs:
                self.bindings.append(("RequestVotePair", ij))
        else:
            for i in range(S):
                self.bindings.append(("RequestVote", (i,)))
        for i in range(S):
            self.bindings.append(("BecomeLeader", (i,)))
        for i in range(S):
            for v in range(V):
                self.bindings.append(("ClientRequest", (i, v)))
        for i in range(S):
            self.bindings.append(("AdvanceCommitIndex", (i,)))
        for ij in self._ae_pairs:
            self.bindings.append(("AppendEntries", ij))
        if params.has_fsync:
            for i in range(S):
                self.bindings.append(("AdvanceFsyncIndex", (i,)))
        for m in range(M):
            self.bindings.append(("HandleMessage", (m,)))
        if params.net_faults:
            for m in range(M):
                self.bindings.append(("DuplicateMessage", (m,)))
            for m in range(M):
                self.bindings.append(("DropMessage", (m,)))
        self.A = len(self.bindings)

        self.expand = jax.jit(jax.vmap(self._expand1))
        self.invariants = {
            "MessagesAreValid": jax.jit(
                messages_are_valid_kernel(self.layout, self.packer)
            ),
            "NoLogDivergence": jax.jit(self._inv_no_log_divergence),
            "LeaderHasAllAckedValues": jax.jit(self._inv_leader_has_acked),
            "CommittedEntriesReachMajority": jax.jit(self._inv_committed_majority),
            "TestInv": jax.jit(lambda s: jnp.ones(s.shape[:-1], dtype=bool)),
        }
        # temporal properties under WF_vars(Next) (checker/liveness.py):
        # ValuesNotStuck == \A v : []<> ValueAllOrNothing(v)
        # (Raft.tla:567-576); []<>Q instances have P = None
        self.liveness = {
            "ValuesNotStuck": [
                (self.value_names[v], None,
                 jax.jit(partial(self._live_value_all_or_nothing, v)))
                for v in range(V)
            ],
        }

    # ---------------- field access helpers ----------------

    def _dec(self, s):
        g = self.layout.get
        return {f: g(s, f) for f in self.layout.fields}

    def _asm(self, d, **updates):
        """Reassemble a state vector from field dict + updates (layout order)."""
        parts = []
        for name, f in self.layout.fields.items():
            arr = updates.get(name, d[name])
            arr = jnp.asarray(arr, jnp.int32)
            parts.append(arr.reshape(-1) if f.shape else arr.reshape(1))
        return jnp.concatenate(parts)

    def _pack(self, **vals):
        hi, lo = self.packer.pack(**vals)
        return jnp.asarray(hi, jnp.int32), jnp.asarray(lo, jnp.int32)

    @staticmethod
    def _last_term(d, i):
        """LastTerm(log[i]) — Raft.tla:126 (one-hot row selects: dynamic
        row gathers serialize on scattered indices, models/base.py)."""
        ll = onehot_row(d["log_len"], i)
        lt = onehot_row(d["log_term"], i)
        return jnp.where(ll > 0, onehot_row(lt, jnp.clip(ll - 1, 0)), 0)

    # ---------------- action kernels ----------------
    # Each returns (valid, succ_vec, rank, overflow).

    def _restart(self, s, i):
        """Restart(i) — Raft.tla:226-235 (FlexibleRaft.tla:200-208).
        RaftFsync (RaftFsync.tla:203-218) additionally truncates the log
        back to fsyncIndex[i] — all three IF arms reduce to
        Len' = min(Len, fsyncIndex)."""
        p, S = self.p, self.p.n_servers
        d = self._dec(s)
        valid = d["restartCtr"] < self._cv(d, "max_restarts")
        upd = dict(
            state=d["state"].at[i].set(FOLLOWER),
            votesGranted=d["votesGranted"].at[i].set(0),
            nextIndex=d["nextIndex"].at[i].set(jnp.ones((S,), jnp.int32)),
            matchIndex=d["matchIndex"].at[i].set(jnp.zeros((S,), jnp.int32)),
            commitIndex=d["commitIndex"].at[i].set(0),
            restartCtr=d["restartCtr"] + 1,
        )
        if p.has_pending_response:
            upd["pendingResponse"] = d["pendingResponse"].at[i].set(0)
        if p.has_fsync:
            new_ll = jnp.minimum(d["log_len"][i], d["fsyncIndex"][i])
            keep = jnp.arange(p.max_log, dtype=jnp.int32) < new_ll
            upd["log_term"] = d["log_term"].at[i].set(
                jnp.where(keep, d["log_term"][i], 0)
            )
            upd["log_value"] = d["log_value"].at[i].set(
                jnp.where(keep, d["log_value"][i], 0)
            )
            upd["log_len"] = d["log_len"].at[i].set(new_ll)
        succ = self._asm(d, **upd)
        return valid, succ, jnp.int32(R_RESTART), jnp.asarray(False)

    def _timeout(self, s, i):
        """Timeout(i) — RaftFsync.tla:222-230: start an election without
        sending (RequestVote(i,j) sends per peer separately)."""
        p = self.p
        d = self._dec(s)
        st_i = d["state"][i]
        valid = (d["electionCtr"] < self._cv(d, "max_elections")) & (
            (st_i == FOLLOWER) | (st_i == CANDIDATE)
        )
        succ = self._asm(
            d,
            state=d["state"].at[i].set(CANDIDATE),
            currentTerm=d["currentTerm"].at[i].set(d["currentTerm"][i] + 1),
            votedFor=d["votedFor"].at[i].set(i + 1),
            votesGranted=d["votesGranted"].at[i].set(jnp.int32(1) << i),
            electionCtr=d["electionCtr"] + 1,
        )
        return valid, succ, jnp.int32(R_TIMEOUT), jnp.asarray(False)

    def _request_vote_pair(self, s, i, j):
        """RequestVote(i, j) — RaftFsync.tla:234-243: candidate i sends one
        send-once RequestVoteRequest (at its current term) to peer j."""
        d = self._dec(s)
        valid = d["state"][i] == CANDIDATE
        khi, klo = self._pack(
            mtype=RVREQ,
            mterm=d["currentTerm"][i],
            mlastLogTerm=self._last_term(d, i),
            mlastLogIndex=d["log_len"][i],
            msource=i,
            mdest=j,
        )
        hi, lo, cnt, existed, ovf = bag.bag_put(
            d["msg_hi"], d["msg_lo"], d["msg_cnt"], khi, klo
        )
        valid &= ~existed  # Send (RaftFsync.tla:132-134) is send-once
        succ = self._asm(d, msg_hi=hi, msg_lo=lo, msg_cnt=cnt)
        return valid, succ, jnp.int32(R_REQUESTVOTE), ovf & valid

    def _advance_fsync_index(self, s, i):
        """AdvanceFsyncIndex(i) — RaftFsync.tla:339-343."""
        d = self._dec(s)
        valid = d["fsyncIndex"][i] < d["log_len"][i]
        succ = self._asm(d, fsyncIndex=d["fsyncIndex"].at[i].add(1))
        return valid, succ, jnp.int32(R_ADVANCEFSYNC), jnp.asarray(False)

    def _request_vote(self, s, i):
        """RequestVote(i) — Raft.tla:242-257 (fused Timeout+RequestVote)."""
        p, S = self.p, self.p.n_servers
        d = self._dec(s)
        st_i = d["state"][i]
        valid = (d["electionCtr"] < self._cv(d, "max_elections")) & (
            (st_i == FOLLOWER) | (st_i == CANDIDATE)
        )
        new_term = d["currentTerm"][i] + 1
        last_t = self._last_term(d, i)
        ll_i = d["log_len"][i]
        hi, lo, cnt = d["msg_hi"], d["msg_lo"], d["msg_cnt"]
        ovf = jnp.asarray(False)
        # SendMultipleOnce of RequestVoteRequest to all peers (Raft.tla:250-256):
        # valid only if none was ever sent before.
        for delta in range(1, S):
            j = jnp.mod(i + delta, S)
            khi, klo = self._pack(
                mtype=RVREQ,
                mterm=new_term,
                mlastLogTerm=last_t,
                mlastLogIndex=ll_i,
                msource=i,
                mdest=j,
            )
            hi, lo, cnt, existed, o = bag.bag_put(hi, lo, cnt, khi, klo)
            valid &= ~existed
            ovf |= o
        succ = self._asm(
            d,
            state=d["state"].at[i].set(CANDIDATE),
            currentTerm=d["currentTerm"].at[i].set(new_term),
            votedFor=d["votedFor"].at[i].set(i + 1),
            votesGranted=d["votesGranted"].at[i].set(jnp.int32(1) << i),
            electionCtr=d["electionCtr"] + 1,
            msg_hi=hi,
            msg_lo=lo,
            msg_cnt=cnt,
        )
        return valid, succ, jnp.int32(R_REQUESTVOTE), ovf & valid

    def _become_leader(self, s, i):
        """BecomeLeader(i) — Raft.tla:289-300. Quorum (Raft.tla:123) is a
        popcount threshold, replacing TLC's SUBSET enumeration;
        FlexibleRaft uses Cardinality >= ElectionQuorumSize
        (FlexibleRaft.tla:260-269)."""
        p, S = self.p, self.p.n_servers
        d = self._dec(s)
        votes = jnp.sum((d["votesGranted"][i] >> jnp.arange(S, dtype=jnp.int32)) & 1)
        if p.election_quorum is not None:
            quorum = votes >= p.election_quorum
        else:
            quorum = 2 * votes > S
        valid = (d["state"][i] == CANDIDATE) & quorum
        upd = dict(
            state=d["state"].at[i].set(LEADER),
            nextIndex=d["nextIndex"].at[i].set(
                jnp.full((S,), 1, jnp.int32) * (d["log_len"][i] + 1)
            ),
            matchIndex=d["matchIndex"].at[i].set(jnp.zeros((S,), jnp.int32)),
        )
        if p.has_pending_response:
            upd["pendingResponse"] = d["pendingResponse"].at[i].set(0)
        succ = self._asm(d, **upd)
        return valid, succ, jnp.int32(R_BECOMELEADER), jnp.asarray(False)

    def _client_request(self, s, i, v):
        """ClientRequest(i, v) — Raft.tla:304-313."""
        L = self.p.max_log
        d = self._dec(s)
        valid = (d["state"][i] == LEADER) & (d["acked"][v] == ACK_NIL)
        pos = d["log_len"][i]
        ovf = valid & (pos >= L)
        posc = jnp.clip(pos, 0, L - 1)
        succ = self._asm(
            d,
            log_term=d["log_term"].at[i, posc].set(d["currentTerm"][i]),
            log_value=d["log_value"].at[i, posc].set(v + 1),
            log_len=d["log_len"].at[i].add(1),
            acked=d["acked"].at[v].set(ACK_FALSE),
        )
        return valid, succ, jnp.int32(R_CLIENTREQUEST), ovf

    def _advance_commit_index(self, s, i):
        """AdvanceCommitIndex(i) — Raft.tla:320-344."""
        p = self.p
        S, L, V = p.n_servers, p.max_log, p.n_values
        d = self._dec(s)
        ll_i = d["log_len"][i]
        ci_i = d["commitIndex"][i]
        match_row = d["matchIndex"][i]  # [S]
        idxs = jnp.arange(1, L + 1, dtype=jnp.int32)  # candidate indexes
        # Agree(index) = {i} u {k : matchIndex[i][k] >= index} (Raft.tla:323-324).
        # RaftFsync (RaftFsync.tla:313-315): when LeaderFsyncBeforeIncludeInQuorum
        # and index > fsyncIndex[i], the leader excludes itself.
        self_in = jnp.arange(S, dtype=jnp.int32)[None, :] == i
        if p.has_fsync and p.fsync_leader_quorum:
            self_in = self_in & (idxs[:, None] <= d["fsyncIndex"][i])
        agree = self_in | (match_row[None, :] >= idxs[:, None])
        agree_cnt = jnp.sum(agree, axis=1)
        if p.replication_quorum is not None:
            # FlexibleRaft.tla:296: Cardinality(Agree) >= ReplicationQuorumSize
            quorum_ok = agree_cnt >= p.replication_quorum
        else:
            quorum_ok = 2 * agree_cnt > S
        is_agree = quorum_ok & (idxs <= ll_i)  # quorum + in-log
        max_agree = jnp.max(jnp.where(is_agree, idxs, 0))  # Max (Raft.tla:333)
        term_at = d["log_term"][i][jnp.clip(max_agree - 1, 0)]
        # current-term gate (Raft.tla:330-335)
        new_ci = jnp.where((max_agree > 0) & (term_at == d["currentTerm"][i]), max_agree, ci_i)
        valid = (d["state"][i] == LEADER) & (ci_i < new_ci)
        # acked[v]: FALSE -> (v committed in (ci, new_ci]) (Raft.tla:339-342)
        lanes = jnp.arange(L, dtype=jnp.int32)
        in_range = (lanes + 1 > ci_i) & (lanes + 1 <= new_ci)
        vals_row = d["log_value"][i]
        committed = jnp.any(
            in_range[None, :] & (vals_row[None, :] == jnp.arange(1, V + 1, dtype=jnp.int32)[:, None]),
            axis=1,
        )
        acked = jnp.where((d["acked"] == ACK_FALSE) & committed, ACK_TRUE, d["acked"])
        succ = self._asm(
            d, commitIndex=d["commitIndex"].at[i].set(new_ci), acked=acked
        )
        return valid, succ, jnp.int32(R_ADVANCECOMMIT), jnp.asarray(False)

    def _append_entries(self, s, i, j):
        """AppendEntries(i, j) — Raft.tla:263-285 (FlexibleRaft.tla:236-256
        has no pendingResponse gate). i != j statically."""
        p = self.p
        L = p.max_log
        d = self._dec(s)
        valid = d["state"][i] == LEADER
        if p.has_pending_response:
            pending = (d["pendingResponse"][i] >> j) & 1
            valid &= pending == 0
        ni_ij = d["nextIndex"][i, j]
        prev_idx = ni_ij - 1
        lt_row = d["log_term"][i]
        lv_row = d["log_value"][i]
        prev_term = jnp.where(prev_idx > 0, lt_row[jnp.clip(prev_idx - 1, 0, L - 1)], 0)
        last_entry = jnp.minimum(d["log_len"][i], ni_ij)  # Min (Raft.tla:273)
        if p.has_fsync and p.fsync_leader_before_ae:
            # LeaderFsyncBeforeAppendEntries gate (RaftFsync.tla:261-263)
            valid &= d["fsyncIndex"][i] >= last_entry
        nent = (last_entry >= ni_ij).astype(jnp.int32)  # <=1 entry
        epos = jnp.clip(ni_ij - 1, 0, L - 1)
        eterm = jnp.where(nent > 0, lt_row[epos], 0)
        evalue = jnp.where(nent > 0, lv_row[epos], 0)
        khi, klo = self._pack(
            mtype=AEREQ,
            mterm=d["currentTerm"][i],
            mprevLogIndex=prev_idx,
            mprevLogTerm=prev_term,
            nentries=nent,
            eterm=eterm,
            evalue=evalue,
            mcommitIndex=jnp.minimum(d["commitIndex"][i], last_entry),
            msource=i,
            mdest=j,
        )
        hi, lo, cnt, existed, ovf = bag.bag_put(
            d["msg_hi"], d["msg_lo"], d["msg_cnt"], khi, klo
        )
        if p.strict_send_once:
            # FlexibleRaft Send (FlexibleRaft.tla:127-129): always send-once.
            valid &= ~existed
        else:
            # Send (Raft.tla:145-149): empty AppendEntriesRequest is send-once.
            valid &= (nent > 0) | ~existed
        upd = dict(msg_hi=hi, msg_lo=lo, msg_cnt=cnt)
        if p.has_pending_response:
            upd["pendingResponse"] = d["pendingResponse"].at[i].set(
                d["pendingResponse"][i] | (jnp.int32(1) << j)
            )
        succ = self._asm(d, **upd)
        return valid, succ, jnp.int32(R_APPENDENTRIES), ovf & valid

    # -------- network-fault kernels (opt-in, params.net_faults) --------

    def _duplicate_message(self, s, m):
        """DuplicateMessage(m) — Raft.tla:508-515: re-deliver a record
        already in the bag DOMAIN (Duplicate == count + 1). The TLA+
        form is unbounded; we gate on count < max_msg_copies so the
        state space stays finite (documented divergence)."""
        p = self.p
        d = self._dec(s)
        cnt = d["msg_cnt"]
        occupied = d["msg_hi"][m] != EMPTY
        valid = occupied & (cnt[m] >= 1) & (cnt[m] < p.max_msg_copies)
        oh = (jnp.arange(p.msg_slots, dtype=jnp.int32) == m).astype(jnp.int32)
        succ = self._asm(d, msg_cnt=cnt + oh)
        return valid, succ, jnp.int32(self._r_dup), jnp.asarray(False)

    def _drop_message(self, s, m):
        """DropMessage(m) — Raft.tla:517-523: Discard one delivery of a
        receivable record. The DOMAIN keeps the count-0 record, exactly
        like the receipt kernels' bag_discard (ops/bag.py)."""
        d = self._dec(s)
        cnt = d["msg_cnt"]
        occupied = d["msg_hi"][m] != EMPTY
        valid = occupied & (cnt[m] >= 1)
        succ = self._asm(d, msg_cnt=bag.bag_discard_at(cnt, m))
        return valid, succ, jnp.int32(self._r_drop), jnp.asarray(False)

    # -------- fused message-receipt kernel (slot m) --------
    # The six receipt disjuncts of Next (Raft.tla:534-539) are mutually
    # exclusive for a fixed record m (they partition on mtype and on the
    # mterm-vs-currentTerm[mdest] comparison), so one kernel per slot
    # computes whichever fires; `rank` reports which for trace ordering.

    def _handle_message(self, s, m):
        pk = self.p, self.packer
        p, packer = pk
        L = p.max_log
        d = self._dec(s)
        hi, lo, cnt = d["msg_hi"], d["msg_lo"], d["msg_cnt"]
        khi, klo, kcnt = hi[m], lo[m], cnt[m]
        occupied = khi != EMPTY
        u = partial(packer.unpack, khi, klo)
        mtype, mterm = u("mtype"), u("mterm")
        src, dst = u("msource"), u("mdest")
        ct_dst = onehot_row(d["currentTerm"], dst)
        st_dst = onehot_row(d["state"], dst)
        recv = occupied & (kcnt > 0)  # ReceivableMessage (Raft.tla:181-187)

        # Reply(response, request) — Raft.tla:170-176. The six handler
        # branches are pairwise DISJOINT (mtype/term/LogOk guards), so the
        # incoming Discard and the response Send collapse into ONE
        # bag_discard + ONE bag_put on the branch-selected response at the
        # end (bag_put embeds an M-lane slot sort; the round-4 kernel paid
        # it three times per slot instance).
        c2 = bag.bag_discard_at(cnt, m)

        # --- UpdateTerm (Raft.tla:348-355): any DOMAIN record (count may be
        # 0!) with mterm > currentTerm[mdest]; message untouched.
        b_upd = occupied & (mterm > ct_dst)

        # --- HandleRequestVoteRequest (Raft.tla:360-381)
        last_t = self._last_term(d, dst)
        ll_dst = onehot_row(d["log_len"], dst)
        vf_dst = onehot_row(d["votedFor"], dst)
        rv_logok = (u("mlastLogTerm") > last_t) | (
            (u("mlastLogTerm") == last_t) & (u("mlastLogIndex") >= ll_dst)
        )
        grant = (
            (mterm == ct_dst)
            & rv_logok
            & ((vf_dst == NIL) | (vf_dst == src + 1))
        )
        b_rvreq = recv & (mtype == RVREQ) & (mterm <= ct_dst)
        rhi, rlo = self._pack(
            mtype=RVRESP,
            mterm=ct_dst,
            mvoteGranted=grant.astype(jnp.int32),
            msource=dst,
            mdest=src,
        )

        # --- HandleRequestVoteResponse (Raft.tla:386-401)
        b_rvresp = recv & (mtype == RVRESP) & (mterm == ct_dst)
        vg = jnp.where(
            u("mvoteGranted") > 0,
            onehot_set(
                d["votesGranted"], dst,
                onehot_row(d["votesGranted"], dst) | (jnp.int32(1) << src)),
            d["votesGranted"],
        )

        # --- AppendEntries request handling: LogOk (Raft.tla:406-410)
        prev_idx = u("mprevLogIndex")
        prev_term = u("mprevLogTerm")
        nent = u("nentries")
        lt_row = onehot_row(d["log_term"], dst)
        lv_row = onehot_row(d["log_value"], dst)
        ae_logok = (prev_idx == 0) | (
            (prev_idx > 0)
            & (prev_idx <= ll_dst)
            & (prev_term == onehot_row(lt_row, jnp.clip(prev_idx - 1, 0, L - 1)))
        )

        # --- RejectAppendEntriesRequest (Raft.tla:412-430)
        b_reject = (
            recv
            & (mtype == AEREQ)
            & (mterm <= ct_dst)
            & (
                (mterm < ct_dst)
                | ((mterm == ct_dst) & (st_dst == FOLLOWER) & ~ae_logok)
            )
        )
        rjhi, rjlo = self._pack(
            mtype=AERESP, mterm=ct_dst, msuccess=0, mmatchIndex=0, msource=dst, mdest=src
        )

        # --- AcceptAppendEntriesRequest (Raft.tla:454-485)
        b_accept = (
            recv
            & (mtype == AEREQ)
            & (mterm == ct_dst)
            & ((st_dst == FOLLOWER) | (st_dst == CANDIDATE))
            & ae_logok
        )
        can_append = (nent != 0) & (ll_dst == prev_idx)  # CanAppend (Raft.tla:438-440)
        if p.trunc_term_mismatch:
            # NeedsTruncation (FlexibleRaft.tla:413-416): conflicting term
            # at the incoming index; no empty-entries arm.
            at_idx = onehot_row(lt_row, jnp.clip(prev_idx, 0, L - 1))  # term at prev+1
            needs_trunc = (nent != 0) & (ll_dst >= prev_idx + 1) & (at_idx != u("eterm"))
        else:
            needs_trunc = ((nent != 0) & (ll_dst >= prev_idx + 1)) | (
                (nent == 0) & (ll_dst > prev_idx)
            )  # NeedsTruncation (Raft.tla:445-449)
        appending = can_append | (needs_trunc & (nent != 0))
        new_ll = jnp.where(
            appending, prev_idx + 1, jnp.where(needs_trunc, prev_idx, ll_dst)
        )
        lanes = jnp.arange(L, dtype=jnp.int32)
        changes = appending | needs_trunc
        # truncate to prevLogIndex (TruncateLog, Raft.tla:451-452) then
        # append m.mentries[1] if present; padding lanes stay zero.
        keep = lanes < prev_idx
        app_pos = jnp.clip(prev_idx, 0, L - 1)
        nlt = onehot_set(
            jnp.where(keep, lt_row, 0), app_pos,
            jnp.where(appending, u("eterm"), 0),
        )
        nlv = onehot_set(
            jnp.where(keep, lv_row, 0), app_pos,
            jnp.where(appending, u("evalue"), 0),
        )
        nlt = jnp.where(changes, nlt, lt_row)
        nlv = jnp.where(changes, nlv, lv_row)
        ac_ovf = b_accept & appending & (prev_idx >= L)
        achi, aclo = self._pack(
            mtype=AERESP,
            mterm=ct_dst,
            msuccess=1,
            mmatchIndex=prev_idx + nent,
            msource=dst,
            mdest=src,
        )

        # --- HandleAppendEntriesResponse (Raft.tla:490-505)
        b_aeresp = recv & (mtype == AERESP) & (mterm == ct_dst)
        succm = u("msuccess") > 0
        mmatch = u("mmatchIndex")
        ni_ds = onehot_row(onehot_row(d["nextIndex"], dst), src)
        ni2 = onehot_set2(
            d["nextIndex"], dst, src,
            jnp.where(succm, mmatch + 1, jnp.maximum(ni_ds - 1, 1)),
        )
        mi2 = jnp.where(
            succm, onehot_set2(d["matchIndex"], dst, src, mmatch),
            d["matchIndex"])

        # --- shared Reply: put the branch-selected response once ---
        resp_hi = jnp.where(b_rvreq, rhi, jnp.where(b_reject, rjhi, achi))
        resp_lo = jnp.where(b_rvreq, rlo, jnp.where(b_reject, rjlo, aclo))
        phi, plo, pcnt, ex, povf = bag.bag_put(hi, lo, c2, resp_hi, resp_lo)
        if p.strict_send_once:
            # FlexibleRaft Reply (FlexibleRaft.tla:148-151): disabled when
            # the response already exists (ex is the selected response's).
            b_rvreq &= ~ex
            b_reject &= ~ex
            b_accept &= ~ex
        putb = b_rvreq | b_reject | b_accept
        dropb = b_rvresp | b_aeresp  # Discard only, no response

        # --- per-field combination (disjoint branches => order-free) ---
        upd = dict(
            currentTerm=jnp.where(
                b_upd, onehot_set(d["currentTerm"], dst, mterm),
                d["currentTerm"]),
            state=jnp.where(
                b_upd | b_accept, onehot_set(d["state"], dst, FOLLOWER),
                d["state"]),
            votedFor=jnp.where(
                b_upd, onehot_set(d["votedFor"], dst, NIL),
                jnp.where(b_rvreq & grant,
                          onehot_set(d["votedFor"], dst, src + 1),
                          d["votedFor"])),
            votesGranted=jnp.where(b_rvresp, vg, d["votesGranted"]),
            commitIndex=jnp.where(
                b_accept, onehot_set(d["commitIndex"], dst, u("mcommitIndex")),
                d["commitIndex"]),
            log_term=jnp.where(
                b_accept, onehot_set(d["log_term"], dst, nlt), d["log_term"]),
            log_value=jnp.where(
                b_accept, onehot_set(d["log_value"], dst, nlv), d["log_value"]),
            log_len=jnp.where(
                b_accept, onehot_set(d["log_len"], dst, new_ll), d["log_len"]),
            nextIndex=jnp.where(b_aeresp, ni2, d["nextIndex"]),
            matchIndex=jnp.where(b_aeresp, mi2, d["matchIndex"]),
            msg_hi=jnp.where(putb, phi, hi),
            msg_lo=jnp.where(putb, plo, lo),
            msg_cnt=jnp.where(putb, pcnt, jnp.where(dropb, c2, cnt)),
        )
        if p.has_fsync and p.fsync_follower_reply:
            # FollowerFsyncBeforeReply: fsyncIndex := Len(new_log)
            # (RaftFsync.tla:468-470), even when the log didn't change.
            upd["fsyncIndex"] = jnp.where(
                b_accept, onehot_set(d["fsyncIndex"], dst, new_ll),
                d["fsyncIndex"])
        if p.has_pending_response:
            upd["pendingResponse"] = jnp.where(
                b_aeresp,
                onehot_set(
                    d["pendingResponse"], dst,
                    onehot_row(d["pendingResponse"], dst)
                    & ~(jnp.int32(1) << src)),
                d["pendingResponse"])
        succ = self._asm(d, **upd)

        branches = [
            (b_upd, R_UPDATETERM, jnp.asarray(False)),
            (b_rvreq, R_HANDLE_RVREQ, povf),
            (b_rvresp, R_HANDLE_RVRESP, jnp.asarray(False)),
            (b_reject, R_REJECT_AE, povf),
            (b_accept, R_ACCEPT_AE, povf | ac_ovf),
            (b_aeresp, R_HANDLE_AERESP, jnp.asarray(False)),
        ]
        valid = jnp.asarray(False)
        rank = jnp.int32(-1)
        ovf = jnp.asarray(False)
        for b, rk, ob in branches:
            valid = valid | b
            rank = jnp.where(b, jnp.int32(rk), rank)
            ovf = ovf | (b & ob)
        return valid, succ, rank, ovf

    # ---------------- full expansion ----------------

    def _expand1(self, s):
        """All successor candidates of one state, in Next-disjunct order.

        Returns (succs [A, W], valid [A], rank [A], ovf [A])."""
        p = self.p
        S, V, M = p.n_servers, p.n_values, p.msg_slots
        iota_s = jnp.arange(S, dtype=jnp.int32)
        ae_i = jnp.asarray([ij[0] for ij in self._ae_pairs], jnp.int32)
        ae_j = jnp.asarray([ij[1] for ij in self._ae_pairs], jnp.int32)
        outs = []
        outs.append(jax.vmap(lambda i: self._restart(s, i))(iota_s))
        if p.has_fsync:
            outs.append(jax.vmap(lambda i: self._timeout(s, i))(iota_s))
            outs.append(
                jax.vmap(lambda i, j: self._request_vote_pair(s, i, j))(ae_i, ae_j)
            )
        else:
            outs.append(jax.vmap(lambda i: self._request_vote(s, i))(iota_s))
        outs.append(jax.vmap(lambda i: self._become_leader(s, i))(iota_s))
        cr_i = jnp.repeat(iota_s, V)
        cr_v = jnp.tile(jnp.arange(V, dtype=jnp.int32), S)
        outs.append(jax.vmap(lambda i, v: self._client_request(s, i, v))(cr_i, cr_v))
        outs.append(jax.vmap(lambda i: self._advance_commit_index(s, i))(iota_s))
        outs.append(jax.vmap(lambda i, j: self._append_entries(s, i, j))(ae_i, ae_j))
        if p.has_fsync:
            outs.append(jax.vmap(lambda i: self._advance_fsync_index(s, i))(iota_s))
        outs.append(
            jax.vmap(lambda m: self._handle_message(s, m))(jnp.arange(M, dtype=jnp.int32))
        )
        if p.net_faults:
            iota_m = jnp.arange(M, dtype=jnp.int32)
            outs.append(jax.vmap(lambda m: self._duplicate_message(s, m))(iota_m))
            outs.append(jax.vmap(lambda m: self._drop_message(s, m))(iota_m))
        valid = jnp.concatenate([o[0] for o in outs])
        succs = jnp.concatenate([o[1] for o in outs])
        rank = jnp.concatenate([o[2] for o in outs])
        ovf = jnp.concatenate([o[3] for o in outs])
        return succs, valid, rank, ovf

    # ---------------- initial states ----------------

    def init_states(self) -> np.ndarray:
        """Init — Raft.tla:213-218. A single state."""
        p = self.p
        vec = self.layout.zeros((1,))
        lay = self.layout
        vec[0, lay.sl("currentTerm")] = 1
        vec[0, lay.sl("state")] = FOLLOWER
        vec[0, lay.sl("votedFor")] = NIL
        vec[0, lay.sl("nextIndex")] = 1
        vec[0, lay.sl("msg_hi")] = int(EMPTY)
        vec[0, lay.sl("msg_lo")] = int(EMPTY)
        vec[0, lay.sl("acked")] = ACK_NIL
        return self._fleet_stamp(vec)

    # ---------------- invariants ----------------
    # Each maps states [B, W] -> ok bool [B] (True = invariant holds).

    def _inv_no_log_divergence(self, states):
        """NoLogDivergence — Raft.tla:588-596."""
        lay, L = self.layout, self.p.max_log
        ci = lay.get(states, "commitIndex")  # [B,S]
        lt = lay.get(states, "log_term")  # [B,S,L]
        lv = lay.get(states, "log_value")
        mci = jnp.minimum(ci[:, :, None], ci[:, None, :])  # [B,S,S]
        lanes = jnp.arange(1, L + 1, dtype=jnp.int32)
        in_common = lanes[None, None, None, :] <= mci[..., None]  # [B,S,S,L]
        eq = (lt[:, :, None, :] == lt[:, None, :, :]) & (
            lv[:, :, None, :] == lv[:, None, :, :]
        )
        return jnp.all(~in_common | eq, axis=(1, 2, 3))

    def _inv_leader_has_acked(self, states):
        """LeaderHasAllAckedValues — Raft.tla:604-620."""
        lay, V = self.layout, self.p.n_values
        ct = lay.get(states, "currentTerm")
        st = lay.get(states, "state")
        lv = lay.get(states, "log_value")  # [B,S,L]
        acked = lay.get(states, "acked")  # [B,V]
        # newest (non-stale) leader: no other server has a higher term
        not_stale = jnp.all(ct[:, :, None] >= ct[:, None, :], axis=2)  # [B,S]
        is_lead = (st == LEADER) & not_stale
        vals = jnp.arange(1, V + 1, dtype=jnp.int32)
        has_v = jnp.any(lv[:, :, None, :] == vals[None, None, :, None], axis=3)  # [B,S,V]
        bad = jnp.any(
            (acked[:, None, :] == ACK_TRUE) & is_lead[:, :, None] & ~has_v, axis=(1, 2)
        )
        return ~bad

    def _live_value_all_or_nothing(self, v, states):
        """ValueAllOrNothing(v) — Raft.tla:560-573: TRUE when the last
        permissible election failed with no leader (progress legitimately
        impossible), else v must be on EVERY server log or on NONE."""
        lay, L = self.layout, self.p.max_log
        ec = lay.get(states, "electionCtr")
        st = lay.get(states, "state")
        lv = lay.get(states, "log_value")
        ll = lay.get(states, "log_len")
        lanes = jnp.arange(L, dtype=jnp.int32)
        in_log = lanes[None, None, :] < ll[..., None]
        has_v = jnp.any(in_log & (lv == v + 1), axis=2)  # [B, S]
        all_have = jnp.all(has_v, axis=1)
        none_have = ~jnp.any(has_v, axis=1)
        no_leader = ~jnp.any(st == LEADER, axis=1)
        spent = ec == self._cv_batch(states, "max_elections")
        return (spent & no_leader) | all_have | none_have

    def _inv_committed_majority(self, states):
        """CommittedEntriesReachMajority — Raft.tla:625-636."""
        lay, S, L = self.layout, self.p.n_servers, self.p.max_log
        st = lay.get(states, "state")
        ci = lay.get(states, "commitIndex")
        ll = lay.get(states, "log_len")
        lt = lay.get(states, "log_term")
        lv = lay.get(states, "log_value")
        lead = (st == LEADER) & (ci > 0)  # [B,S]
        pos = jnp.clip(ci - 1, 0, L - 1)  # [B,S]
        lt_i = jnp.take_along_axis(lt, pos[:, :, None], axis=2)[:, :, 0]  # [B,S]
        lv_i = jnp.take_along_axis(lv, pos[:, :, None], axis=2)[:, :, 0]
        # match[b,i,j]: server j has leader i's entry at index ci[i]
        posj = jnp.broadcast_to(pos[:, :, None], pos.shape + (S,))  # [B,S,S] index of i
        lt_j = jnp.take_along_axis(
            jnp.broadcast_to(lt[:, None, :, :], lt.shape[:1] + (S,) + lt.shape[1:]),
            posj[..., None],
            axis=3,
        )[..., 0]
        lv_j = jnp.take_along_axis(
            jnp.broadcast_to(lv[:, None, :, :], lv.shape[:1] + (S,) + lv.shape[1:]),
            posj[..., None],
            axis=3,
        )[..., 0]
        match = (ll[:, None, :] >= ci[:, :, None]) & (lt_j == lt_i[..., None]) & (
            lv_j == lv_i[..., None]
        )
        enough = jnp.sum(match, axis=2) >= (S // 2 + 1)  # quorum incl. i
        ok_exists = jnp.any(lead & enough, axis=1)
        return ~jnp.any(lead, axis=1) | ok_exists

    # ---------------- host-side decode/encode ----------------

    def decode(self, vec: np.ndarray) -> dict:
        """Decode one packed state into the canonical python form shared with
        the oracle interpreter (0-based ints; messages as a frozenset of
        (record, count); record = tuple of sorted (field, value))."""
        lay = self.layout
        p = self.p
        g = lambda n: np.asarray(vec[lay.sl(n)])
        S, L = p.n_servers, p.max_log
        lt = g("log_term").reshape(S, L)
        lv = g("log_value").reshape(S, L)
        ll = g("log_len")
        log = tuple(
            tuple((int(lt[i, k]), int(lv[i, k]) - 1) for k in range(int(ll[i])))
            for i in range(S)
        )
        vg = g("votesGranted")
        votes = tuple(
            frozenset(j for j in range(S) if (int(vg[i]) >> j) & 1) for i in range(S)
        )
        if p.has_pending_response:
            pr = g("pendingResponse")
            pending = tuple(
                tuple(bool((int(pr[i]) >> j) & 1) for j in range(S)) for i in range(S)
            )
        else:  # variant without the var: constant all-False in the shared form
            pending = ((False,) * S,) * S
        msgs = {}
        hi, lo, cnt = g("msg_hi"), g("msg_lo"), g("msg_cnt")
        for k in range(p.msg_slots):
            if int(hi[k]) == int(EMPTY):
                continue
            msgs[self.decode_msg(int(hi[k]), int(lo[k]))] = int(cnt[k])
        extra = (
            {"fsyncIndex": tuple(int(x) for x in g("fsyncIndex"))}
            if p.has_fsync
            else {}
        )
        return extra | {
            "currentTerm": tuple(int(x) for x in g("currentTerm")),
            "state": tuple(int(x) for x in g("state")),
            "votedFor": tuple(int(x) - 1 if x > 0 else None for x in g("votedFor")),
            "votesGranted": votes,
            "log": log,
            "commitIndex": tuple(int(x) for x in g("commitIndex")),
            "nextIndex": tuple(
                tuple(int(x) for x in row) for row in g("nextIndex").reshape(S, S)
            ),
            "matchIndex": tuple(
                tuple(int(x) for x in row) for row in g("matchIndex").reshape(S, S)
            ),
            "pendingResponse": pending,
            "messages": frozenset(msgs.items()),
            "acked": tuple(
                {ACK_NIL: None, ACK_FALSE: False, ACK_TRUE: True}[int(x)]
                for x in g("acked")
            ),
            "electionCtr": int(vec[lay.fields["electionCtr"].offset]),
            "restartCtr": int(vec[lay.fields["restartCtr"].offset]),
        }

    def decode_msg(self, hi: int, lo: int) -> tuple:
        """Packed key -> canonical record tuple (sorted (field, value) pairs)."""
        u = self.packer.unpack_all(hi, lo)
        mtype = int(u["mtype"])
        rec = {
            "mtype": MTYPE_NAMES[mtype],
            "mterm": int(u["mterm"]),
            "msource": int(u["msource"]),
            "mdest": int(u["mdest"]),
        }
        if mtype == RVREQ:
            rec["mlastLogTerm"] = int(u["mlastLogTerm"])
            rec["mlastLogIndex"] = int(u["mlastLogIndex"])
        elif mtype == RVRESP:
            rec["mvoteGranted"] = bool(u["mvoteGranted"])
        elif mtype == AEREQ:
            rec["mprevLogIndex"] = int(u["mprevLogIndex"])
            rec["mprevLogTerm"] = int(u["mprevLogTerm"])
            rec["mentries"] = (
                ((int(u["eterm"]), int(u["evalue"]) - 1),) if u["nentries"] else ()
            )
            rec["mcommitIndex"] = int(u["mcommitIndex"])
        elif mtype == AERESP:
            rec["msuccess"] = bool(u["msuccess"])
            rec["mmatchIndex"] = int(u["mmatchIndex"])
        return tuple(sorted(rec.items()))

    def encode_msg(self, rec: tuple) -> tuple[int, int]:
        d = dict(rec)
        mtype = {v: k for k, v in MTYPE_NAMES.items()}[d["mtype"]]
        kw = dict(mtype=mtype, mterm=d["mterm"], msource=d["msource"], mdest=d["mdest"])
        if mtype == RVREQ:
            kw.update(mlastLogTerm=d["mlastLogTerm"], mlastLogIndex=d["mlastLogIndex"])
        elif mtype == RVRESP:
            kw.update(mvoteGranted=int(d["mvoteGranted"]))
        elif mtype == AEREQ:
            ent = d["mentries"]
            kw.update(
                mprevLogIndex=d["mprevLogIndex"],
                mprevLogTerm=d["mprevLogTerm"],
                nentries=len(ent),
                eterm=ent[0][0] if ent else 0,
                evalue=ent[0][1] + 1 if ent else 0,
                mcommitIndex=d["mcommitIndex"],
            )
        elif mtype == AERESP:
            kw.update(msuccess=int(d["msuccess"]), mmatchIndex=d["mmatchIndex"])
        return self.packer.pack(**kw)

    def encode(self, st: dict) -> np.ndarray:
        """Inverse of decode (canonical slot order for the message bag)."""
        lay, p = self.layout, self.p
        S, L = p.n_servers, p.max_log
        vec = lay.zeros(())
        vec[lay.sl("currentTerm")] = st["currentTerm"]
        vec[lay.sl("state")] = st["state"]
        vec[lay.sl("votedFor")] = [0 if v is None else v + 1 for v in st["votedFor"]]
        vec[lay.sl("votesGranted")] = [
            sum(1 << j for j in vs) for vs in st["votesGranted"]
        ]
        lt = np.zeros((S, L), np.int32)
        lv = np.zeros((S, L), np.int32)
        for i, lg in enumerate(st["log"]):
            for k, (t, v) in enumerate(lg):
                lt[i, k] = t
                lv[i, k] = v + 1
        vec[lay.sl("log_term")] = lt.reshape(-1)
        vec[lay.sl("log_value")] = lv.reshape(-1)
        vec[lay.sl("log_len")] = [len(lg) for lg in st["log"]]
        vec[lay.sl("commitIndex")] = st["commitIndex"]
        if p.has_fsync:
            vec[lay.sl("fsyncIndex")] = st["fsyncIndex"]
        vec[lay.sl("nextIndex")] = np.asarray(st["nextIndex"]).reshape(-1)
        vec[lay.sl("matchIndex")] = np.asarray(st["matchIndex"]).reshape(-1)
        if p.has_pending_response:
            vec[lay.sl("pendingResponse")] = [
                sum(1 << j for j, b in enumerate(row) if b)
                for row in st["pendingResponse"]
            ]
        keys = sorted(
            (self.encode_msg(rec), cnt) for rec, cnt in st["messages"]
        )
        if len(keys) > p.msg_slots:
            raise OverflowError("message bag exceeds msg_slots")
        hi = np.full(p.msg_slots, int(EMPTY), np.int32)
        lo = np.full(p.msg_slots, int(EMPTY), np.int32)
        cn = np.zeros(p.msg_slots, np.int32)
        for k, ((h, l), c) in enumerate(keys):
            hi[k], lo[k], cn[k] = h, l, c
        vec[lay.sl("msg_hi")] = hi
        vec[lay.sl("msg_lo")] = lo
        vec[lay.sl("msg_cnt")] = cn
        vec[lay.sl("acked")] = [
            {None: ACK_NIL, False: ACK_FALSE, True: ACK_TRUE}[a] for a in st["acked"]
        ]
        vec[lay.fields["electionCtr"].offset] = st["electionCtr"]
        vec[lay.fields["restartCtr"].offset] = st["restartCtr"]
        return vec


from functools import lru_cache as _lru_cache


@_lru_cache(maxsize=None)
def _cached_model(params: RaftParams) -> "RaftModel":
    return RaftModel(params)
