"""TPU lowering of the joint-consensus reconfiguration Raft spec.

Reference: ``/root/reference/specifications/standard-raft/
RaftWithReconfigJointConsensus.tla`` (1,145 lines). Every action kernel
cites the TLA+ lines it lowers.

Structural deltas vs. models/reconfig_raft.py (the add/remove variant):
  - log entries carry up to THREE member sets (``OldNewConfigCommand``'s
    old/new/joint-members, ``:837-842``) — seven parallel lane arrays, the
    sets as bitmasks;
  - configs track ``jointConsensus`` plus ``old``/``new``
    (``ConfigFor:279-290``);
  - dual quorums while joint: ``BecomeLeader:511-528`` and
    ``AdvanceCommitIndex:613-653`` need simultaneous majorities of old
    and new (popcount thresholds over both bitmasks);
  - the reconfiguration parameter space is pairs of member subsets
    constrained by ``ReconfigType`` (``IsValidReconfiguration:813-825``);
    the candidate table enumerates exactly the admitted (add, remove)
    mask pairs statically;
  - ``AppendNewConfigToLog:861-876`` fires on the unique committed
    OldNew entry with no later config command
    (``CommittedOldNewWithoutNew:232-242``);
  - ``MaxOneReconfigurationAtATime:1080-1101`` is an adjacency rule over
    every server's log;
  - ``ResetWithSameIdentity:391`` is NOT in ``Next`` (commented, ``:988``).
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass
from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import bag
from ..ops.packing import EMPTY, WidePacker, bits_for
from .base import Layout, messages_are_valid_kernel

from .config_common import (  # shared enums: single source of truth
    ACK_FALSE, ACK_NIL, ACK_TRUE, CANDIDATE, FOLLOWER, LEADER, NIL,
    NOTMEMBER, PENDING_SNAP_REQUEST, PENDING_SNAP_RESPONSE,
    AEREQ, AERESP, RVREQ, RVRESP, SNAPREQ, SNAPRESP,
)

# log-entry commands (:58-60); 0 = empty lane
CMD_NONE, CMD_APPEND, CMD_OLDNEW, CMD_NEW = range(4)
CMD_NAMES = {
    CMD_APPEND: "AppendCommand",
    CMD_OLDNEW: "OldNewConfigCommand",
    CMD_NEW: "NewConfigCommand",
}

RC_OK, RC_STALE, RC_MISMATCH, RC_NEEDSNAP = 1, 2, 3, 4


# Next-disjunct ranks (:966-988), for trace labels.
(
    J_RESTART,
    J_UPDATETERM,
    J_REQUESTVOTE,
    J_BECOMELEADER,
    J_HANDLE_RVREQ,
    J_HANDLE_RVRESP,
    J_CLIENTREQUEST,
    J_ADVANCECOMMIT,
    J_APPENDENTRIES,
    J_REJECT_AE,
    J_ACCEPT_AE,
    J_HANDLE_AERESP,
    J_APPEND_OLDNEW,
    J_APPEND_NEW,
    J_SENDSNAP,
    J_HANDLE_SNAPREQ,
    J_HANDLE_SNAPRESP,
) = range(17)

from .config_common import (
    ConfigRaftCommon,
    MTYPE_NAMES,
    RC_NAMES,
    R_ACCEPT_AE as _R_AC,
    R_APPENDENTRIES as _R_AE,
    R_CLIENTREQUEST as _R_CR,
    R_HANDLE_AERESP as _R_HA,
    R_HANDLE_RVREQ as _R_HQ,
    R_HANDLE_RVRESP as _R_HP,
    R_HANDLE_SNAPREQ as _R_SQ,
    R_HANDLE_SNAPRESP as _R_SP,
    R_REJECT_AE as _R_RJ,
    R_REQUESTVOTE as _R_RV,
    R_RESTART as _R_RS,
    R_SENDSNAP as _R_SS,
    R_UPDATETERM as _R_UT,
)

# the mixin's kernels emit the shared rank constants; both variants lay
# their Next out so these coincide (config_common.py docstring)
assert (J_RESTART, J_REQUESTVOTE, J_CLIENTREQUEST,
        J_APPENDENTRIES, J_SENDSNAP) == (
    _R_RS, _R_RV, _R_CR, _R_AE, _R_SS)
assert (J_UPDATETERM, J_HANDLE_RVREQ, J_HANDLE_RVRESP,
        J_REJECT_AE, J_ACCEPT_AE, J_HANDLE_AERESP,
        J_HANDLE_SNAPREQ, J_HANDLE_SNAPRESP) == (
    _R_UT, _R_HQ, _R_HP, _R_RJ, _R_AC, _R_HA, _R_SQ, _R_SP)

ACTION_NAMES = [
    "Restart",
    "UpdateTerm",
    "RequestVote",
    "BecomeLeader",
    "HandleRequestVoteRequest",
    "HandleRequestVoteResponse",
    "ClientRequest",
    "AdvanceCommitIndex",
    "AppendEntries",
    "RejectAppendEntriesRequest",
    "AcceptAppendEntriesRequest",
    "HandleAppendEntriesResponse",
    "AppendOldNewConfigToLog",
    "AppendNewConfigToLog",
    "SendSnapshot",
    "HandleSnapshotRequest",
    "HandleSnapshotResponse",
]

ENTRY_SET_FIELDS = ("old", "new", "members")
ENTRY_FIELDS = ("term", "cmd", "val", "cid") + ENTRY_SET_FIELDS


@dataclass(frozen=True)
class JointRaftParams:
    n_servers: int
    n_values: int
    init_cluster_size: int
    max_elections: int
    max_restarts: int
    max_reconfigs: int
    max_values_per_term: int
    reconfig_type: int
    msg_slots: int = 112

    @property
    def max_term(self) -> int:
        return 1 + self.max_elections

    @property
    def max_cfg_id(self) -> int:
        return max(1, self.max_reconfigs)

    @property
    def max_log(self) -> int:
        appends = min(self.n_values, self.max_term * self.max_values_per_term)
        return 1 + appends + 2 * self.max_reconfigs


def reconfig_shapes(n_servers: int, reconfig_type: int):
    """The (addMembers, removeMembers) subset pairs admitted by
    IsValidReconfiguration (:813-825), as bitmask pairs, deterministic
    order (matches oracle/joint_oracle.py's enumeration)."""
    servers = range(n_servers)
    subsets = []
    for r in range(n_servers + 1):
        subsets += [frozenset(c) for c in itertools.combinations(servers, r)]

    def valid(add, remove):
        if reconfig_type == 2:
            return len(add) == 1 and len(remove) == 1
        if reconfig_type == 3:
            return len(add) > 0 and len(remove) == 0
        if reconfig_type == 4:
            return len(add) == 0 and len(remove) > 0
        return bool(add) or bool(remove)

    out = []
    for add in subsets:
        for remove in subsets:
            if valid(add, remove):
                out.append(
                    (sum(1 << x for x in add), sum(1 << x for x in remove))
                )
    return out


def _entry_widths(p: JointRaftParams) -> list[tuple[str, int]]:
    tb = bits_for(p.max_term)
    return [
        ("term", tb),
        ("cmd", 2),
        ("val", bits_for(p.n_values)),
        ("cid", bits_for(p.max_cfg_id)),
        ("old", p.n_servers),
        ("new", p.n_servers),
        ("members", p.n_servers),
    ]


def _build_layout(p: JointRaftParams, n_words: int) -> Layout:
    S, V, L, M = p.n_servers, p.n_values, p.max_log, p.msg_slots
    lay = Layout(S)
    # VIEW (:144): all aux vars excluded.
    lay.add("config_id", "per_server", (S,))
    lay.add("config_joint", "per_server", (S,))
    lay.add("config_members", "server_bitmask", (S,))
    lay.add("config_old", "server_bitmask", (S,))
    lay.add("config_new", "server_bitmask", (S,))
    lay.add("config_committed", "per_server", (S,))
    lay.add("currentTerm", "per_server", (S,))
    lay.add("state", "per_server", (S,))
    lay.add("votedFor", "per_server_val", (S,))
    lay.add("votesGranted", "server_bitmask", (S,))
    lay.add("log_term", "per_server", (S, L))
    lay.add("log_cmd", "per_server", (S, L))
    lay.add("log_val", "per_server", (S, L))
    lay.add("log_cid", "per_server", (S, L))
    lay.add("log_old", "server_bitmask", (S, L))
    lay.add("log_new", "server_bitmask", (S, L))
    lay.add("log_members", "server_bitmask", (S, L))
    lay.add("log_len", "per_server", (S,))
    lay.add("commitIndex", "per_server", (S,))
    lay.add("nextIndex", "per_server_pair", (S, S))  # may hold -1/-2
    lay.add("matchIndex", "per_server_pair", (S, S))
    lay.add("pendingResponse", "server_bitmask", (S,))
    for k in range(n_words):
        lay.add(f"msg_w{k}", "msg_word", (M,))
    lay.add("msg_cnt", "msg_cnt", (M,))
    lay.add("acked", "aux", (V,))
    lay.add("electionCtr", "aux")
    lay.add("restartCtr", "aux")
    lay.add("reconfigCtr", "aux")
    lay.add("valueCtr", "aux", (p.max_term,))
    return lay.finish()


def _build_packer(p: JointRaftParams) -> WidePacker:
    tb = bits_for(p.max_term)
    sb = bits_for(p.n_servers - 1)
    lb = bits_for(p.max_log + 1)
    ew = _entry_widths(p)
    fields = [
        ("mtype", 3),
        ("mterm", tb),
        ("msource", sb),
        ("mdest", sb),
        ("mlastLogTerm", tb),
        ("mlastLogIndex", lb),
        ("mvoteGranted", 1),
        ("mprevLogIndex", lb),
        ("mprevLogTerm", tb),
        ("nentries", 1),
        *[(f"e_{n}", w) for n, w in ew],
        ("mcommitIndex", lb),
        ("mresult", 3),
        ("mmatchIndex", lb),
        ("msuccess", 1),
        ("mloglen", lb),
        ("mmembers", p.n_servers),
        *[(f"l{k}_{n}", w) for k in range(p.max_log) for n, w in ew],
    ]
    for n_words in range(2, 16):
        try:
            return WidePacker(fields, n_words)
        except ValueError:
            continue
    raise ValueError("message schema too wide")


def cached_model(params: "JointRaftParams") -> "JointRaftModel":
    return _cached_model(params)


class JointRaftModel(ConfigRaftCommon):
    """Vectorized successor/invariant kernels for one (spec, constants) pair."""

    name = "RaftWithReconfigJointConsensus"
    ENTRY_FIELDS = ENTRY_FIELDS
    CMD_SEED = CMD_NEW  # Init's seeded first entry (:341-354)
    MEMBERS_FIELD = "members"
    CMD_APPEND = CMD_APPEND
    ACTION_NAMES = ACTION_NAMES

    def __init__(self, params, server_names=None, value_names=None):
        self.p = params
        self.packer = _build_packer(params)
        self.n_words = self.packer.n_words
        self.layout = _build_layout(params, self.n_words)
        S, V, M, L = params.n_servers, params.n_values, params.msg_slots, params.max_log
        self.server_names = list(server_names or [f"s{i+1}" for i in range(S)])
        self.value_names = list(value_names or [f"v{i+1}" for i in range(V)])

        spec = [("msource", "server"), ("mdest", "server"),
                ("mmembers", "server_bitmask")]
        for n in ENTRY_SET_FIELDS:
            spec.append((f"e_{n}", "server_bitmask"))
        for k in range(L):
            for n in ENTRY_SET_FIELDS:
                spec.append((f"l{k}_{n}", "server_bitmask"))
        self.msg_perm_spec = tuple(spec)

        self.shapes = reconfig_shapes(S, params.reconfig_type)
        self._finish_init()

    # ---------------- field access helpers ----------------

    def _mrce(self, d, i):
        """MostRecentReconfigEntry — :251-257. Returns (index, cmd, cid,
        old, new, members) of the latest config command."""
        L = self.p.max_log
        lanes = jnp.arange(L, dtype=jnp.int32)
        cmd = d["log_cmd"][i]
        is_cfg = (cmd == CMD_OLDNEW) | (cmd == CMD_NEW)
        mask = (lanes < d["log_len"][i]) & is_cfg
        idx = jnp.max(jnp.where(mask, lanes + 1, 0))
        pos = jnp.clip(idx - 1, 0)
        return (
            idx,
            cmd[pos],
            d["log_cid"][i][pos],
            d["log_old"][i][pos],
            d["log_new"][i][pos],
            d["log_members"][i][pos],
        )

    def _config_for_upd(self, d, i, idx, cmd, cid, old, new, members, ci):
        """ConfigFor (:279-290) applied to server i's config fields."""
        joint = (cmd == CMD_OLDNEW).astype(jnp.int32)
        z = jnp.int32(0)
        return dict(
            config_id=d["config_id"].at[i].set(cid),
            config_joint=d["config_joint"].at[i].set(joint),
            config_members=d["config_members"].at[i].set(members),
            config_old=d["config_old"].at[i].set(jnp.where(joint > 0, old, z)),
            config_new=d["config_new"].at[i].set(jnp.where(joint > 0, new, z)),
            config_committed=d["config_committed"].at[i].set(
                (ci >= idx).astype(jnp.int32)
            ),
        )

    # ---------------- action kernels ----------------

    def _become_leader(self, s, i):
        """BecomeLeader(i) — :511-528: dual quorums while joint."""
        S = self.p.n_servers
        d = self._dec(s)
        vg = d["votesGranted"][i]
        joint = d["config_joint"][i] > 0
        members = d["config_members"][i]
        old = d["config_old"][i]
        new = d["config_new"][i]
        q_plain = ((vg & ~members) == 0) & (
            2 * self._popcount(vg, S) > self._popcount(members, S)
        )
        q_old = 2 * self._popcount(vg & old, S) > self._popcount(old, S)
        q_new = 2 * self._popcount(vg & new, S) > self._popcount(new, S)
        valid = (d["state"][i] == CANDIDATE) & jnp.where(
            joint, q_old & q_new, q_plain
        )
        succ = self._asm(
            d,
            state=d["state"].at[i].set(LEADER),
            nextIndex=d["nextIndex"].at[i].set(
                jnp.full((S,), 1, jnp.int32) * (d["log_len"][i] + 1)
            ),
            matchIndex=d["matchIndex"].at[i].set(jnp.zeros((S,), jnp.int32)),
            pendingResponse=d["pendingResponse"].at[i].set(0),
        )
        return valid, succ, jnp.int32(J_BECOMELEADER), jnp.asarray(False)

    def _commit_quorum_ok(self, d, i, idxs, match_row, ks):
        """Dual-quorum agreement while joint (:626-629)."""
        S = self.p.n_servers
        joint = d["config_joint"][i] > 0

        def quorum_over(member_mask):
            member_k = ((member_mask >> ks) & 1) > 0
            in_agree = member_k[None, :] & (
                (match_row[None, :] >= idxs[:, None]) | (ks[None, :] == i)
            )
            return 2 * jnp.sum(in_agree, axis=1) > self._popcount(member_mask, S)

        q_plain = quorum_over(d["config_members"][i])
        q_joint = quorum_over(d["config_old"][i]) & quorum_over(d["config_new"][i])
        return jnp.where(joint, q_joint, q_plain)

    def _commit_config_upd(self, d, i, new_ci) -> dict:
        idx, cmd, cid, c_old, c_new, c_members = self._mrce(d, i)
        return self._config_for_upd(
            d, i, idx, cmd, cid, c_old, c_new, c_members, new_ci
        )

    def _commit_removed(self, d, i, in_range):
        """IsRemovedFromCluster (:606-611): NewConfigCommand without i."""
        return jnp.any(
            in_range
            & (d["log_cmd"][i] == CMD_NEW)
            & (((d["log_members"][i] >> i) & 1) == 0)
        )

    def _append_old_new(self, s, i, add_mask, rem_mask):
        """AppendOldNewConfigToLog(i) for one admitted (add, remove) subset
        pair — :827-856."""
        p, S, L = self.p, self.p.n_servers, self.p.max_log
        d = self._dec(s)
        members = d["config_members"][i]
        add_m = jnp.int32(add_mask)
        rem_m = jnp.int32(rem_mask)
        # HasPendingConfigCommand (:246-248)
        pending = (d["config_committed"][i] == 0) | (d["config_joint"][i] > 0)
        valid = (
            (d["state"][i] == LEADER)
            & (d["reconfigCtr"] < p.max_reconfigs)
            & ~pending
            & ((add_m & members) == 0)  # addMembers disjoint (:834)
            & ((rem_m & members) == rem_m)  # removeMembers subset (:835)
        )
        old = members
        new = (members & ~rem_m) | add_m
        joint_members = members | add_m
        new_id = d["reconfigCtr"] + 1  # id = reconfigCtr + 1 (:839)
        pos = d["log_len"][i]
        ovf = valid & (pos >= L)
        posc = jnp.clip(pos, 0, L - 1)
        # nextIndex := PendingSnapshotRequest for s in new \ old (:849-853)
        ks = jnp.arange(S, dtype=jnp.int32)
        fresh = (((new >> ks) & 1) > 0) & (((old >> ks) & 1) == 0)
        ni_row = jnp.where(
            fresh, jnp.int32(PENDING_SNAP_REQUEST), d["nextIndex"][i]
        )
        succ = self._asm(
            d,
            log_term=d["log_term"].at[i, posc].set(d["currentTerm"][i]),
            log_cmd=d["log_cmd"].at[i, posc].set(CMD_OLDNEW),
            log_cid=d["log_cid"].at[i, posc].set(new_id),
            log_old=d["log_old"].at[i, posc].set(old),
            log_new=d["log_new"].at[i, posc].set(new),
            log_members=d["log_members"].at[i, posc].set(joint_members),
            log_len=d["log_len"].at[i].add(1),
            config_id=d["config_id"].at[i].set(new_id),
            config_joint=d["config_joint"].at[i].set(1),
            config_members=d["config_members"].at[i].set(joint_members),
            config_old=d["config_old"].at[i].set(old),
            config_new=d["config_new"].at[i].set(new),
            config_committed=d["config_committed"].at[i].set(
                (d["commitIndex"][i] >= pos + 1).astype(jnp.int32)
            ),
            reconfigCtr=d["reconfigCtr"] + 1,
            nextIndex=d["nextIndex"].at[i].set(ni_row),
        )
        return valid, succ, jnp.int32(J_APPEND_OLDNEW), ovf

    def _append_new(self, s, i):
        """AppendNewConfigToLog(i) — :861-876: fires on the unique
        committed OldNew with no later config command."""
        p, L = self.p, self.p.max_log
        d = self._dec(s)
        lanes = jnp.arange(L, dtype=jnp.int32)
        cmd_row = d["log_cmd"][i]
        ll_i = d["log_len"][i]
        in_log = lanes < ll_i
        is_oldnew = in_log & (cmd_row == CMD_OLDNEW)
        is_new = in_log & (cmd_row == CMD_NEW)
        last_oldnew = jnp.max(jnp.where(is_oldnew, lanes + 1, 0))
        last_new = jnp.max(jnp.where(is_new, lanes + 1, 0))
        # CommittedOldNewWithoutNew (:232-242)
        qualifies = (
            (last_oldnew > 0)
            & (d["commitIndex"][i] >= last_oldnew)
            & (last_new < last_oldnew)
        )
        valid = (d["state"][i] == LEADER) & qualifies
        tpos = jnp.clip(last_oldnew - 1, 0)
        new_members = d["log_new"][i][tpos]
        new_id = d["log_cid"][i][tpos]
        pos = ll_i
        ovf = valid & (pos >= L)
        posc = jnp.clip(pos, 0, L - 1)
        succ = self._asm(
            d,
            log_term=d["log_term"].at[i, posc].set(d["currentTerm"][i]),
            log_cmd=d["log_cmd"].at[i, posc].set(CMD_NEW),
            log_cid=d["log_cid"].at[i, posc].set(new_id),
            log_members=d["log_members"].at[i, posc].set(new_members),
            log_len=d["log_len"].at[i].add(1),
            config_id=d["config_id"].at[i].set(new_id),
            config_joint=d["config_joint"].at[i].set(0),
            config_members=d["config_members"].at[i].set(new_members),
            config_old=d["config_old"].at[i].set(0),
            config_new=d["config_new"].at[i].set(0),
            config_committed=d["config_committed"].at[i].set(
                (d["commitIndex"][i] >= pos + 1).astype(jnp.int32)
            ),
        )
        return valid, succ, jnp.int32(J_APPEND_NEW), ovf

    # -------- fused message-receipt kernel (slot m) --------

    def _is_cfg_cmd(self, cmd):
        """OldNewConfig / NewConfig entries carry a configuration
        (:58-60); hook for the shared receipt kernel."""
        return (cmd == CMD_OLDNEW) | (cmd == CMD_NEW)

    def _config_updates_from_log(self, d, dst, logs, cfg_pos, cfg_idx, mci):
        """ConfigFor projection (:279-290): id, jointConsensus flag,
        members, old/new sets, committed watermark; in_new = membership
        of dst in the installed config's member set."""
        z = jnp.int32(0)
        cfg_cmd = logs["cmd"][cfg_pos]
        cfg_joint = (cfg_cmd == CMD_OLDNEW).astype(jnp.int32)
        cfg_members = logs["members"][cfg_pos]
        upd = dict(
            config_id=d["config_id"].at[dst].set(logs["cid"][cfg_pos]),
            config_joint=d["config_joint"].at[dst].set(cfg_joint),
            config_members=d["config_members"].at[dst].set(cfg_members),
            config_old=d["config_old"].at[dst].set(
                jnp.where(cfg_joint > 0, logs["old"][cfg_pos], z)
            ),
            config_new=d["config_new"].at[dst].set(
                jnp.where(cfg_joint > 0, logs["new"][cfg_pos], z)
            ),
            config_committed=d["config_committed"].at[dst].set(
                (mci >= cfg_idx).astype(jnp.int32)
            ),
        )
        in_new = ((cfg_members >> dst) & 1) > 0
        return upd, in_new

    # ---------------- full expansion ----------------

    def _kernel_overrides(self) -> dict:
        return {
            "AppendOldNewConfigToLog": self._append_old_new,
            "AppendNewConfigToLog": self._append_new,
        }

    def _config_bindings(self) -> list:
        b = []
        for i in range(self.p.n_servers):
            for add_m, rem_m in self.shapes:
                b.append(("AppendOldNewConfigToLog", (i, add_m, rem_m)))
        for i in range(self.p.n_servers):
            b.append(("AppendNewConfigToLog", (i,)))
        return b

    def _config_outs(self, s) -> list:
        import jax

        S = self.p.n_servers
        iota_s = jnp.arange(S, dtype=jnp.int32)
        on_i = jnp.asarray(
            [i for i in range(S) for _ in self.shapes], jnp.int32
        )
        on_add = jnp.asarray(
            [a for _ in range(S) for a, _r in self.shapes], jnp.int32
        )
        on_rem = jnp.asarray(
            [r for _ in range(S) for _a, r in self.shapes], jnp.int32
        )
        return [
            jax.vmap(lambda i, a, r: self._append_old_new(s, i, a, r))(
                on_i, on_add, on_rem
            ),
            jax.vmap(lambda i: self._append_new(s, i))(iota_s),
        ]

    def _old_new_committed(self, states):
        """OldNewCommitted(i, index) over all (i, lane): committed
        OldNewConfigCommand entries — :1023-1025. [B,S,L] mask."""
        lay, L = self.layout, self.p.max_log
        cmd = lay.get(states, "log_cmd")
        ll = lay.get(states, "log_len")
        ci = lay.get(states, "commitIndex")
        lanes = jnp.arange(L, dtype=jnp.int32)
        return (
            (cmd == CMD_OLDNEW)
            & (lanes[None, None, :] < ll[..., None])
            & (ci[..., None] >= lanes[None, None, :] + 1)
        )

    def _live_reconfig_p(self, states):
        """ReconfigurationCompletes antecedent — :1040-1043: some server
        has a committed OldNewConfigCommand."""
        return jnp.any(self._old_new_committed(states), axis=(1, 2))

    def _live_reconfig_q(self, states):
        """ReconfigurationCompletes consequent — :1044-1054: the last
        permissible election failed leaderless, OR a majority of the new
        member set are self-aware members in {Leader,Follower,Candidate}
        holding the matching NewConfigCommand — :1027-1037."""
        lay, S, L = self.layout, self.p.n_servers, self.p.max_log
        st = lay.get(states, "state")
        ec = lay.get(states, "electionCtr")
        cmd = lay.get(states, "log_cmd")
        cid = lay.get(states, "log_cid")
        lnew = lay.get(states, "log_new")
        ll = lay.get(states, "log_len")
        cm = lay.get(states, "config_members")
        lanes = jnp.arange(L, dtype=jnp.int32)
        onc = self._old_new_committed(states)  # [B,S,L]
        # server j qualifies for config id c: self-aware member, active
        # state, and holds a NewConfigCommand with id c somewhere
        iota = jnp.arange(S, dtype=jnp.int32)
        self_member = ((cm >> iota[None, :]) & 1) > 0  # [B,S]
        active = st != NOTMEMBER  # Leader/Follower/Candidate
        has_new = (cmd == CMD_NEW) & (lanes[None, None, :] < ll[..., None])
        # qualifies[b, j, i, l]: j holds NewConfigCommand with the id of
        # entry (i, l)
        id_match = jnp.any(
            has_new[:, :, None, None, :]
            & (cid[:, :, None, None, :] == cid[:, None, :, :, None]),
            axis=4,
        )  # [B,j,i,l]
        qual = (self_member & active)[:, :, None, None] & id_match
        # majority of the entry's NEW member set
        new_bit = (
            (lnew[:, None, :, :] >> iota[None, :, None, None]) & 1
        ) > 0  # [B,j,i,l]
        count = jnp.sum(qual & new_bit, axis=1)  # [B,i,l]
        size = jnp.sum(new_bit, axis=1)  # [B,i,l]
        reached = jnp.any(onc & (2 * count > size), axis=(1, 2))
        no_leader = ~jnp.any(st == LEADER, axis=1)
        spent = ec == self.p.max_elections
        return (spent & no_leader) | reached

    def _inv_max_one_reconfig(self, states):
        """MaxOneReconfigurationAtATime — :1080-1101: same-type config
        commands need the opposite type strictly between them."""
        lay, L = self.layout, self.p.max_log
        cmd = lay.get(states, "log_cmd")  # [B,S,L]
        ll = lay.get(states, "log_len")
        lanes = jnp.arange(L, dtype=jnp.int32)
        in_log = lanes[None, None, :] < ll[:, :, None]
        ok = jnp.ones(cmd.shape[:2], dtype=bool)
        for c, other in ((CMD_OLDNEW, CMD_NEW), (CMD_NEW, CMD_OLDNEW)):
            is_c = in_log & (cmd == c)
            is_o = in_log & (cmd == other)
            # pair [.., k1, k2] with k1 < k2 both command c
            pair = is_c[..., :, None] & is_c[..., None, :]
            k1 = lanes[:, None]
            k2 = lanes[None, :]
            upper = k2 > k1
            # between[k1, k2]: exists opposite-type at k with k1 < k < k2
            between = (lanes[None, None, :] > k1[..., None]) & (
                lanes[None, None, :] < k2[..., None]
            )  # [L, L, L]
            has_between = jnp.any(
                between[None, None] & is_o[:, :, None, None, :], axis=-1
            )  # [B,S,L,L]
            bad = pair & upper[None, None] & ~has_between
            ok &= ~jnp.any(bad, axis=(2, 3))
        return jnp.all(ok, axis=1)

    def _inv_committed_majority(self, states):
        """CommittedEntriesReachMajority — :1129-1140."""
        lay, S, L = self.layout, self.p.n_servers, self.p.max_log
        st = lay.get(states, "state")
        ci = lay.get(states, "commitIndex")
        ll = lay.get(states, "log_len")
        members = lay.get(states, "config_members")
        lead = (st == LEADER) & (ci > 0)
        pos = jnp.clip(ci - 1, 0, L - 1)
        match = jnp.ones(st.shape[:1] + (S, S), dtype=bool)
        for n in ENTRY_FIELDS:
            f = lay.get(states, f"log_{n}")
            fi = jnp.take_along_axis(f, pos[:, :, None], axis=2)[:, :, 0]
            fj = jnp.take_along_axis(
                jnp.broadcast_to(f[:, None, :, :], f.shape[:1] + (S,) + f.shape[1:]),
                jnp.broadcast_to(pos[:, :, None, None], pos.shape + (S, 1)),
                axis=3,
            )[..., 0]
            match &= fj == fi[..., None]
        match &= ll[:, None, :] >= ci[:, :, None]
        ks = jnp.arange(S, dtype=jnp.int32)
        member_j = ((members[:, :, None] >> ks[None, None, :]) & 1) > 0
        agree = match & member_j
        n_members = jnp.sum(member_j, axis=2)
        eye = jnp.eye(S, dtype=bool)
        self_in = jnp.any(agree & eye[None, :, :], axis=2)
        enough = (jnp.sum(agree, axis=2) >= (n_members // 2 + 1)) & self_in
        ok_exists = jnp.any(lead & enough, axis=1)
        return ~jnp.any(lead, axis=1) | ok_exists

    # ---------------- host-side decode/encode ----------------

    def _decode_entry(self, term, cmd, val, cid, old, new, members):
        cmd_name = CMD_NAMES[int(cmd)]
        if cmd_name == "AppendCommand":
            return (cmd_name, int(term), int(val) - 1)
        if cmd_name == "NewConfigCommand":
            return (cmd_name, int(term), (int(cid), self._fs(members)))
        return (
            cmd_name,
            int(term),
            (int(cid), self._fs(old), self._fs(new), self._fs(members)),
        )

    def _encode_entry(self, entry):
        cmd_name, term, val = entry
        inv_cmd = {v: k for k, v in CMD_NAMES.items()}
        cmd = inv_cmd[cmd_name]
        mk = lambda fs: sum(1 << j for j in fs)
        if cmd == CMD_APPEND:
            return dict(term=term, cmd=cmd, val=val + 1, cid=0, old=0, new=0, members=0)
        if cmd == CMD_NEW:
            return dict(
                term=term, cmd=cmd, val=0, cid=val[0], old=0, new=0,
                members=mk(val[1]),
            )
        return dict(
            term=term, cmd=cmd, val=0, cid=val[0], old=mk(val[1]),
            new=mk(val[2]), members=mk(val[3]),
        )

    counter_fields = ("reconfigCtr",)

    def _decode_config(self, g):
        return tuple(
            (
                int(g("config_id")[i]),
                bool(g("config_joint")[i]),
                self._fs(g("config_members")[i]),
                self._fs(g("config_old")[i]),
                self._fs(g("config_new")[i]),
                bool(g("config_committed")[i]),
            )
            for i in range(self.p.n_servers)
        )

    def _encode_config(self, vec, st) -> None:
        lay = self.layout
        mk = lambda fs: sum(1 << j for j in fs)
        vec[lay.sl("config_id")] = [c[0] for c in st["config"]]
        vec[lay.sl("config_joint")] = [int(c[1]) for c in st["config"]]
        vec[lay.sl("config_members")] = [mk(c[2]) for c in st["config"]]
        vec[lay.sl("config_old")] = [mk(c[3]) for c in st["config"]]
        vec[lay.sl("config_new")] = [mk(c[4]) for c in st["config"]]
        vec[lay.sl("config_committed")] = [int(c[5]) for c in st["config"]]


@lru_cache(maxsize=None)
def _cached_model(params: "JointRaftParams") -> "JointRaftModel":
    return JointRaftModel(params)
