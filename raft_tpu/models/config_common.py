"""Shared action/invariant core of the two reconfiguration Raft lowerings.

``RaftWithReconfigJointConsensus.tla`` and ``RaftWithReconfigAddRemove.tla``
share their non-reconfig machinery almost verbatim (the reference itself
copy-inlines it); ``models/joint_raft.py`` and ``models/reconfig_raft.py``
mirrored that, leaving ~1k duplicated lines where a shared-action fix had
to land twice (round-2 verdict Weak #8). This mixin holds the common
kernels once, parameterized by three class attributes the variants set:

  ENTRY_FIELDS   log-entry lane suffixes (``log_{n}`` layout fields and
                 ``e_{n}`` / ``l{k}_{n}`` packed message fields)
  CMD_APPEND     the AppendCommand enum value (the two specs number their
                 command sets differently)
  ACTION_NAMES   Next-disjunct labels for traces

The shared Next-disjunct RANKS are identical by construction in both
specs (verified by asserts in each variant module): positions 0-11 for
the core-Raft actions and 14-16 for the snapshot trio.

Everything genuinely variant-specific — dual old/new quorums vs.
member-set quorums, reconfig append actions, LogOk strictness, the fused
receipt kernel — stays in the variant modules.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..ops import bag
from ..ops.packing import EMPTY
from .base import ActionLabelMixin, SparseExpandMixin

# enums shared by both variants (identical values in both specs' lowerings)
FOLLOWER, CANDIDATE, LEADER, NOTMEMBER = range(4)
NIL = 0
ACK_NIL, ACK_FALSE, ACK_TRUE = 0, 1, 2
RVREQ, RVRESP, AEREQ, AERESP, SNAPREQ, SNAPRESP = 1, 2, 3, 4, 5, 6
MTYPE_NAMES = {
    RVREQ: "RequestVoteRequest",
    RVRESP: "RequestVoteResponse",
    AEREQ: "AppendEntriesRequest",
    AERESP: "AppendEntriesResponse",
    SNAPREQ: "SnapshotRequest",
    SNAPRESP: "SnapshotResponse",
}
# AppendEntries result codes (AddRemove :75; Ok=1 so 0 = "field absent")
RC_OK, RC_STALE, RC_MISMATCH, RC_NEEDSNAP = 1, 2, 3, 4
RC_NAMES = {
    RC_OK: "Ok",
    RC_STALE: "StaleTerm",
    RC_MISMATCH: "EntryMismatch",
    RC_NEEDSNAP: "NeedSnapshot",
}
PENDING_SNAP_REQUEST = -1  # JointConsensus :293 / AddRemove :271
PENDING_SNAP_RESPONSE = -2

# shared Next-disjunct ranks (both variants lay their Next out so these
# land at the same indices; asserted in the variant modules)
(
    R_RESTART,
    R_UPDATETERM,
    R_REQUESTVOTE,
    R_BECOMELEADER,
    R_HANDLE_RVREQ,
    R_HANDLE_RVRESP,
    R_CLIENTREQUEST,
    R_ADVANCECOMMIT,
    R_APPENDENTRIES,
    R_REJECT_AE,
    R_ACCEPT_AE,
    R_HANDLE_AERESP,
) = range(12)
R_SENDSNAP, R_HANDLE_SNAPREQ, R_HANDLE_SNAPRESP = 14, 15, 16


class ConfigRaftCommon(SparseExpandMixin, ActionLabelMixin):
    """Mixin with the kernels common to both reconfig lowerings.

    Subclass contract: ``self.p`` (params with n_servers/max_log/
    max_term/max_elections/max_restarts/max_values_per_term/n_values),
    ``self.layout``/``self.packer``/``self.n_words``/``self.bindings``,
    layout fields named as in the variants (``config_members``,
    ``log_{n}`` for n in ENTRY_FIELDS, ...), and the three class attrs
    documented in the module docstring (``action_label`` itself comes
    from base.ActionLabelMixin)."""

    ENTRY_FIELDS: tuple[str, ...]
    CMD_APPEND: int
    ACTION_NAMES: list[str]

    # ---------------- field access helpers ----------------

    def _dec(self, s):
        g = self.layout.get
        return {f: g(s, f) for f in self.layout.fields}

    def _asm(self, d, **updates):
        parts = []
        for name, f in self.layout.fields.items():
            arr = updates.get(name, d[name])
            arr = jnp.asarray(arr, jnp.int32)
            parts.append(arr.reshape(-1) if f.shape else arr.reshape(1))
        return jnp.concatenate(parts)

    def _pack(self, **vals):
        return tuple(jnp.asarray(w, jnp.int32) for w in self.packer.pack(**vals))

    def _words(self, d):
        return [d[f"msg_w{k}"] for k in range(self.n_words)]

    def _bag_put(self, words, cnt, key):
        return bag.wide_bag_put(words, cnt, key)

    def _word_upd(self, words, cnt):
        upd = {f"msg_w{k}": w for k, w in enumerate(words)}
        upd["msg_cnt"] = cnt
        return upd

    @staticmethod
    def _last_term(d, i):
        """LastTerm — JointConsensus :252 / AddRemove :173."""
        ll = d["log_len"][i]
        return jnp.where(ll > 0, d["log_term"][i][jnp.clip(ll - 1, 0)], 0)

    @staticmethod
    def _popcount(x, S):
        return jnp.sum((x >> jnp.arange(S, dtype=jnp.int32)) & 1)

    # ---------------- shared action kernels ----------------

    def _restart(self, s, i):
        """Restart(i) — JointConsensus :362-374 / AddRemove :346-358:
        keeps config, currentTerm, votedFor, log."""
        p, S = self.p, self.p.n_servers
        d = self._dec(s)
        valid = d["restartCtr"] < p.max_restarts
        succ = self._asm(
            d,
            state=d["state"].at[i].set(FOLLOWER),
            votesGranted=d["votesGranted"].at[i].set(0),
            nextIndex=d["nextIndex"].at[i].set(jnp.ones((S,), jnp.int32)),
            matchIndex=d["matchIndex"].at[i].set(jnp.zeros((S,), jnp.int32)),
            pendingResponse=d["pendingResponse"].at[i].set(0),
            commitIndex=d["commitIndex"].at[i].set(0),
            restartCtr=d["restartCtr"] + 1,
        )
        return valid, succ, jnp.int32(R_RESTART), jnp.asarray(False)

    def _request_vote(self, s, i):
        """RequestVote(i) — JointConsensus :431-450 / AddRemove :425-444:
        member-only; RequestVoteRequests to the member set via
        SendMultipleOnce."""
        p, S = self.p, self.p.n_servers
        d = self._dec(s)
        st_i = d["state"][i]
        members = d["config_members"][i]
        valid = (
            (d["electionCtr"] < p.max_elections)
            & ((st_i == FOLLOWER) | (st_i == CANDIDATE))
            & (((members >> i) & 1) > 0)
        )
        new_term = d["currentTerm"][i] + 1
        last_t = self._last_term(d, i)
        ll_i = d["log_len"][i]
        words, cnt = self._words(d), d["msg_cnt"]
        ovf = jnp.asarray(False)
        for delta in range(1, S):
            j = jnp.mod(i + delta, S)
            is_member = ((members >> j) & 1) > 0
            key = self._pack(
                mtype=RVREQ,
                mterm=new_term,
                mlastLogTerm=last_t,
                mlastLogIndex=ll_i,
                msource=i,
                mdest=j,
            )
            w2, c2, existed, o = self._bag_put(words, cnt, key)
            valid &= (~is_member) | ~existed  # SendMultipleOnce
            ovf |= is_member & o
            words = [jnp.where(is_member, a, b) for a, b in zip(w2, words)]
            cnt = jnp.where(is_member, c2, cnt)
        succ = self._asm(
            d,
            state=d["state"].at[i].set(CANDIDATE),
            currentTerm=d["currentTerm"].at[i].set(new_term),
            votedFor=d["votedFor"].at[i].set(i + 1),
            votesGranted=d["votesGranted"].at[i].set(jnp.int32(1) << i),
            electionCtr=d["electionCtr"] + 1,
            **self._word_upd(words, cnt),
        )
        return valid, succ, jnp.int32(R_REQUESTVOTE), ovf & valid

    def _client_request(self, s, i, v):
        """ClientRequest(i, v) — JointConsensus :535-550 / AddRemove
        :525-540 (acked gate + per-term valueCtr)."""
        p, L = self.p, self.p.max_log
        d = self._dec(s)
        term = d["currentTerm"][i]
        tpos = jnp.clip(term - 1, 0, p.max_term - 1)
        valid = (
            (d["state"][i] == LEADER)
            & (d["acked"][v] == ACK_NIL)
            & (d["valueCtr"][tpos] < p.max_values_per_term)
        )
        pos = d["log_len"][i]
        ovf = valid & (pos >= L)
        posc = jnp.clip(pos, 0, L - 1)
        succ = self._asm(
            d,
            log_term=d["log_term"].at[i, posc].set(term),
            log_cmd=d["log_cmd"].at[i, posc].set(self.CMD_APPEND),
            log_val=d["log_val"].at[i, posc].set(v + 1),
            log_len=d["log_len"].at[i].add(1),
            acked=d["acked"].at[v].set(ACK_FALSE),
            valueCtr=d["valueCtr"].at[tpos].add(1),
        )
        return valid, succ, jnp.int32(R_CLIENTREQUEST), ovf

    def _append_entries(self, s, i, j):
        """AppendEntries(i, j) — JointConsensus :556-582 / AddRemove
        :546-572: member- and snapshot-sentinel-gated; empty requests are
        send-once."""
        p = self.p
        L = p.max_log
        d = self._dec(s)
        ni_ij = d["nextIndex"][i, j]
        valid = (
            (d["state"][i] == LEADER)
            & (((d["config_members"][i] >> j) & 1) > 0)
            & (ni_ij >= 0)
            & (((d["pendingResponse"][i] >> j) & 1) == 0)
        )
        prev_idx = ni_ij - 1
        prev_term = jnp.where(
            prev_idx > 0, d["log_term"][i][jnp.clip(prev_idx - 1, 0, L - 1)], 0
        )
        last_entry = jnp.minimum(d["log_len"][i], ni_ij)
        nent = (last_entry >= ni_ij).astype(jnp.int32)
        epos = jnp.clip(ni_ij - 1, 0, L - 1)
        z = jnp.int32(0)
        kw = dict(
            mtype=AEREQ,
            mterm=d["currentTerm"][i],
            mprevLogIndex=jnp.clip(prev_idx, 0),
            mprevLogTerm=prev_term,
            nentries=nent,
            mcommitIndex=jnp.clip(jnp.minimum(d["commitIndex"][i], last_entry), 0),
            msource=i,
            mdest=j,
        )
        for n in self.ENTRY_FIELDS:
            kw[f"e_{n}"] = jnp.where(nent > 0, d[f"log_{n}"][i][epos], z)
        key = self._pack(**kw)
        words, cnt, existed, ovf = self._bag_put(self._words(d), d["msg_cnt"], key)
        valid &= (nent > 0) | ~existed  # empty AEReq is send-once
        succ = self._asm(
            d,
            pendingResponse=d["pendingResponse"].at[i].set(
                d["pendingResponse"][i] | (jnp.int32(1) << j)
            ),
            **self._word_upd(words, cnt),
        )
        return valid, succ, jnp.int32(R_APPENDENTRIES), ovf & valid

    def _send_snapshot(self, s, i, j):
        """SendSnapshot(i, j) — JointConsensus :885-901 / AddRemove
        :862-878: embeds the whole log in the request."""
        p, L = self.p, self.p.max_log
        d = self._dec(s)
        valid = (
            (d["state"][i] == LEADER)
            & (((d["config_members"][i] >> j) & 1) > 0)
            & (d["nextIndex"][i, j] == PENDING_SNAP_REQUEST)
        )
        kw = dict(
            mtype=SNAPREQ,
            mterm=d["currentTerm"][i],
            mcommitIndex=d["commitIndex"][i],
            mmembers=d["config_members"][i],
            mloglen=d["log_len"][i],
            msource=i,
            mdest=j,
        )
        lanes = jnp.arange(L, dtype=jnp.int32)
        live = lanes < d["log_len"][i]
        for k in range(L):
            for n in self.ENTRY_FIELDS:
                kw[f"l{k}_{n}"] = jnp.where(live[k], d[f"log_{n}"][i][k], 0)
        key = self._pack(**kw)
        words, cnt, _existed, ovf = self._bag_put(self._words(d), d["msg_cnt"], key)
        succ = self._asm(
            d,
            nextIndex=d["nextIndex"].at[i, j].set(PENDING_SNAP_RESPONSE),
            **self._word_upd(words, cnt),
        )
        return valid, succ, jnp.int32(R_SENDSNAP), ovf & valid

    # ---------------- shared invariants ----------------

    def _inv_no_log_divergence(self, states):
        """NoLogDivergence — JointConsensus :1066-1074 / AddRemove
        :1017-1025 (full-entry equality over all entry lanes)."""
        lay, L = self.layout, self.p.max_log
        ci = lay.get(states, "commitIndex")
        mci = jnp.minimum(ci[:, :, None], ci[:, None, :])
        lanes = jnp.arange(1, L + 1, dtype=jnp.int32)
        in_common = lanes[None, None, None, :] <= mci[..., None]
        eq = jnp.ones(in_common.shape, dtype=bool)
        for n in self.ENTRY_FIELDS:
            f = lay.get(states, f"log_{n}")
            eq &= f[:, :, None, :] == f[:, None, :, :]
        return jnp.all(~in_common | eq, axis=(1, 2, 3))

    def _inv_leader_has_acked(self, states):
        """LeaderHasAllAckedValues — JointConsensus :1109-1125 / AddRemove
        :1047-1063."""
        lay, V = self.layout, self.p.n_values
        ct = lay.get(states, "currentTerm")
        st = lay.get(states, "state")
        lv = lay.get(states, "log_val")
        cmd = lay.get(states, "log_cmd")
        acked = lay.get(states, "acked")
        not_stale = jnp.all(ct[:, :, None] >= ct[:, None, :], axis=2)
        is_lead = (st == LEADER) & not_stale
        vals = jnp.arange(1, V + 1, dtype=jnp.int32)
        lv_app = jnp.where(cmd == self.CMD_APPEND, lv, 0)
        has_v = jnp.any(lv_app[:, :, None, :] == vals[None, None, :, None], axis=3)
        bad = jnp.any(
            (acked[:, None, :] == ACK_TRUE) & is_lead[:, :, None] & ~has_v,
            axis=(1, 2),
        )
        return ~bad

    # ---------------- shared fused receipt kernel ----------------
    #
    # Both reconfig specs receive the same eight message-triggered
    # actions with identical guards and effects — the ONLY variant
    # deltas are which log commands carry a configuration and what a
    # configuration install writes, so those are the two hooks.

    def _is_cfg_cmd(self, cmd):
        """Mask of log-entry command values that carry a configuration
        (JointConsensus: OldNewConfig/NewConfig; AddRemove: Init/Add/
        Remove). Variant hook."""
        raise NotImplementedError

    def _config_updates_from_log(self, d, dst, logs, cfg_pos, cfg_idx, mci):
        """(updates dict for the config_* layout fields, in_new bool)
        after installing the most recent config entry of `logs` at
        `cfg_pos` on server `dst` (commit watermark `mci`). Variant
        hook — the two specs cache different config projections."""
        raise NotImplementedError

    def _handle_message(self, s, m):
        """The fused receipt kernel: UpdateTerm, Handle{RequestVote,
        AppendEntries,Snapshot}{Request,Response} and Reject/Accept
        AppendEntries for bag slot m — JointConsensus :410-:944 /
        AddRemove :404-:921 (identical structure; the reference
        copy-inlines this machinery between the two specs)."""
        p = self.p
        L = p.max_log
        d = self._dec(s)
        words, cnt = self._words(d), d["msg_cnt"]
        key = [w[m] for w in words]
        kcnt = cnt[m]
        occupied = key[0] != EMPTY
        u = lambda n: self.packer.unpack(key, n)  # noqa: E731
        mtype, mterm = u("mtype"), u("mterm")
        src, dst = u("msource"), u("mdest")
        cur = d["currentTerm"][dst]
        st_dst = d["state"][dst]
        member_dst = ((d["config_members"][dst] >> dst) & 1) > 0
        recv = occupied & (kcnt > 0)
        le_term = mterm <= cur
        eq_term = mterm == cur
        cnt_disc = bag.bag_discard_at(cnt, m)

        # Reply: the eight handler branches are pairwise DISJOINT
        # (mtype/term/state/result-code guards), so the incoming Discard
        # and the response Send collapse into ONE bag_put on the branch-
        # selected response at the end, and the successor assembles ONCE
        # per field (round 5: eight full _asm materializations + eight
        # full-state select chains previously dominated the kernel and
        # blew up the XLA:CPU LLVM compile on the joint spec).

        # --- UpdateTerm (count may be 0)
        b_upd = occupied & (mterm > cur)

        # --- HandleRequestVoteRequest
        last_t = self._last_term(d, dst)
        ll_dst = d["log_len"][dst]
        rv_logok = (u("mlastLogTerm") > last_t) | (
            (u("mlastLogTerm") == last_t) & (u("mlastLogIndex") >= ll_dst)
        )
        grant = (
            eq_term
            & rv_logok
            & ((d["votedFor"][dst] == NIL) | (d["votedFor"][dst] == src + 1))
        )
        b_rvreq = recv & (mtype == RVREQ) & le_term
        rv_key = self._pack(
            mtype=RVRESP,
            mterm=cur,
            mvoteGranted=grant.astype(jnp.int32),
            msource=dst,
            mdest=src,
        )

        # --- HandleRequestVoteResponse
        b_rvresp = recv & (mtype == RVRESP) & eq_term & (st_dst == CANDIDATE)
        vg = jnp.where(
            u("mvoteGranted") > 0,
            d["votesGranted"].at[dst].set(
                d["votesGranted"][dst] | (jnp.int32(1) << src)
            ),
            d["votesGranted"],
        )

        # --- AppendEntries request handling: LogOk (strict empty-entries
        # arm, AddRemove :650-667 == JointConsensus) + result-code CASE
        prev_idx = u("mprevLogIndex")
        prev_term = u("mprevLogTerm")
        nent = u("nentries")
        lt_row = d["log_term"][dst]
        at_prev = lt_row[jnp.clip(prev_idx - 1, 0, L - 1)]
        ae_logok = jnp.where(
            nent > 0,
            (prev_idx > 0) & (prev_idx <= ll_dst) & (prev_term == at_prev),
            (prev_idx == ll_dst) & (prev_idx > 0) & (prev_term == at_prev),
        )
        rc = jnp.where(
            mterm < cur,
            RC_STALE,
            jnp.where(
                ~member_dst,
                RC_NEEDSNAP,
                jnp.where(
                    eq_term & (st_dst == FOLLOWER) & ~ae_logok, RC_MISMATCH, RC_OK
                ),
            ),
        )

        # RejectAppendEntriesRequest
        b_reject = recv & (mtype == AEREQ) & le_term & (rc != RC_OK)
        rj_key = self._pack(
            mtype=AERESP,
            mterm=cur,
            mresult=rc,
            mmatchIndex=0,
            msource=dst,
            mdest=src,
        )

        # AcceptAppendEntriesRequest
        b_accept = (
            recv
            & (mtype == AEREQ)
            & eq_term
            & ((st_dst == FOLLOWER) | (st_dst == CANDIDATE))
            & ae_logok
            & member_dst
        )
        can_append = (nent != 0) & (ll_dst == prev_idx)
        needs_trunc = (nent != 0) & (ll_dst >= prev_idx + 1)
        appending = can_append | needs_trunc
        new_ll = jnp.where(appending, prev_idx + 1, ll_dst)
        lanes = jnp.arange(L, dtype=jnp.int32)
        keep = lanes < prev_idx
        app_pos = jnp.clip(prev_idx, 0, L - 1)
        new_logs = {}
        for n in self.ENTRY_FIELDS:
            row = d[f"log_{n}"][dst]
            nrow = jnp.where(keep, row, 0).at[app_pos].set(
                jnp.where(appending, u(f"e_{n}"), 0)
            )
            new_logs[n] = jnp.where(appending, nrow, row)
        cfg_mask = (lanes < new_ll) & self._is_cfg_cmd(new_logs["cmd"])
        cfg_idx = jnp.max(jnp.where(cfg_mask, lanes + 1, 0))
        cfg_pos = jnp.clip(cfg_idx - 1, 0)
        mci = u("mcommitIndex")
        cfg_upd, in_new = self._config_updates_from_log(
            d, dst, new_logs, cfg_pos, cfg_idx, mci
        )
        ac_ovf = b_accept & appending & (prev_idx >= L)
        ac_key = self._pack(
            mtype=AERESP,
            mterm=cur,
            mresult=RC_OK,
            mmatchIndex=prev_idx + nent,
            msource=dst,
            mdest=src,
        )

        # --- HandleAppendEntriesResponse
        b_aeresp = recv & (mtype == AERESP) & eq_term & (st_dst == LEADER)
        res = u("mresult")
        mmatch = u("mmatchIndex")
        ni_cur = d["nextIndex"][dst, src]
        ni_new = jnp.where(
            res == RC_OK,
            mmatch + 1,
            jnp.where(
                res == RC_MISMATCH,
                jnp.maximum(ni_cur - 1, 1),
                jnp.where(res == RC_NEEDSNAP, PENDING_SNAP_REQUEST, ni_cur),
            ),
        )

        # --- HandleSnapshotRequest
        b_snapreq = recv & (mtype == SNAPREQ) & eq_term & (st_dst == FOLLOWER)
        sn_ll = u("mloglen")
        sn_logs = {
            n: jnp.stack([u(f"l{k}_{n}") for k in range(L)])
            for n in self.ENTRY_FIELDS
        }
        sn_mask = (lanes < sn_ll) & self._is_cfg_cmd(sn_logs["cmd"])
        sn_idx = jnp.max(jnp.where(sn_mask, lanes + 1, 0))
        sn_pos = jnp.clip(sn_idx - 1, 0)
        sn_mci = u("mcommitIndex")
        sn_cfg_upd, _sn_in_new = self._config_updates_from_log(
            d, dst, sn_logs, sn_pos, sn_idx, sn_mci
        )
        sq_key = self._pack(
            mtype=SNAPRESP,
            mterm=cur,
            msuccess=1,
            mmatchIndex=sn_ll,
            msource=dst,
            mdest=src,
        )

        # --- HandleSnapshotResponse
        b_snapresp = (
            recv
            & (mtype == SNAPRESP)
            & eq_term
            & (d["nextIndex"][dst, src] == PENDING_SNAP_RESPONSE)
        )

        # --- shared Reply: put the branch-selected response once ---
        resp_key = [
            jnp.where(
                b_rvreq, kr,
                jnp.where(b_reject, kj, jnp.where(b_accept, ka, kq)),
            )
            for kr, kj, ka, kq in zip(rv_key, rj_key, ac_key, sq_key)
        ]
        pw, pc, _ex, povf = self._bag_put(words, cnt_disc, resp_key)
        putb = b_rvreq | b_reject | b_accept | b_snapreq
        dropb = b_rvresp | b_aeresp | b_snapresp  # Discard only

        # --- per-field combination (disjoint branches => order-free) ---
        upd = dict(
            currentTerm=jnp.where(
                b_upd, d["currentTerm"].at[dst].set(mterm), d["currentTerm"]),
            state=jnp.where(
                b_upd, d["state"].at[dst].set(FOLLOWER),
                jnp.where(
                    b_accept,
                    d["state"].at[dst].set(
                        jnp.where(in_new, FOLLOWER, NOTMEMBER)),
                    d["state"])),
            votedFor=jnp.where(
                b_upd, d["votedFor"].at[dst].set(NIL),
                jnp.where(b_rvreq & grant,
                          d["votedFor"].at[dst].set(src + 1), d["votedFor"])),
            votesGranted=jnp.where(b_rvresp, vg, d["votesGranted"]),
            commitIndex=jnp.where(
                b_accept, d["commitIndex"].at[dst].set(mci),
                jnp.where(b_snapreq, d["commitIndex"].at[dst].set(sn_mci),
                          d["commitIndex"])),
            log_len=jnp.where(
                b_accept, d["log_len"].at[dst].set(new_ll),
                jnp.where(b_snapreq, d["log_len"].at[dst].set(sn_ll),
                          d["log_len"])),
            nextIndex=jnp.where(
                b_aeresp, d["nextIndex"].at[dst, src].set(ni_new),
                jnp.where(
                    b_snapresp,
                    d["nextIndex"].at[dst, src].set(u("mmatchIndex") + 1),
                    d["nextIndex"])),
            matchIndex=jnp.where(
                b_aeresp & (res == RC_OK),
                d["matchIndex"].at[dst, src].set(mmatch),
                jnp.where(
                    b_snapresp,
                    d["matchIndex"].at[dst, src].set(u("mmatchIndex")),
                    d["matchIndex"])),
            pendingResponse=jnp.where(
                b_aeresp,
                d["pendingResponse"].at[dst].set(
                    d["pendingResponse"][dst] & ~(jnp.int32(1) << src)),
                d["pendingResponse"]),
            msg_cnt=jnp.where(putb, pc, jnp.where(dropb, cnt_disc, cnt)),
        )
        for k, w in enumerate(pw):
            upd[f"msg_w{k}"] = jnp.where(putb, w, words[k])
        for n in self.ENTRY_FIELDS:
            upd[f"log_{n}"] = jnp.where(
                b_accept, d[f"log_{n}"].at[dst].set(new_logs[n]),
                jnp.where(b_snapreq, d[f"log_{n}"].at[dst].set(sn_logs[n]),
                          d[f"log_{n}"]))
        for k in cfg_upd:
            upd[k] = jnp.where(
                b_accept, cfg_upd[k],
                jnp.where(b_snapreq, sn_cfg_upd[k], d[k]))
        succ = self._asm(d, **upd)

        branches = [
            (b_upd, R_UPDATETERM, jnp.asarray(False)),
            (b_rvreq, R_HANDLE_RVREQ, povf),
            (b_rvresp, R_HANDLE_RVRESP, jnp.asarray(False)),
            (b_reject, R_REJECT_AE, povf),
            (b_accept, R_ACCEPT_AE, povf | ac_ovf),
            (b_aeresp, R_HANDLE_AERESP, jnp.asarray(False)),
            (b_snapreq, R_HANDLE_SNAPREQ, povf),
            (b_snapresp, R_HANDLE_SNAPRESP, jnp.asarray(False)),
        ]
        valid = jnp.asarray(False)
        rank = jnp.int32(-1)
        ovf = jnp.asarray(False)
        for b, rk, ob in branches:
            valid = valid | b
            rank = jnp.where(b, jnp.int32(rk), rank)
            ovf = ovf | (b & ob)
        return valid, succ, rank, ovf

    # ------------- shared Next-table + expansion (round-5 dedup) -------------
    # Bindings and the fused expansion candidates follow the SAME order:
    # Restart, RequestVote, BecomeLeader, ClientRequest, AdvanceCommit,
    # AppendEntries, <variant config arms>, SendSnapshot, <variant
    # pre-message arms>, HandleMessage — variants only supply the two
    # hook pairs, so rank/label parity cannot drift between them.

    def _config_bindings(self) -> list:
        raise NotImplementedError  # variant reconfig arms

    def _pre_msg_bindings(self) -> list:
        return []

    def _config_outs(self, s) -> list:
        raise NotImplementedError

    def _pre_msg_outs(self, s, iota_s) -> list:
        return []

    def _finish_init(self) -> None:
        """Build bindings/expand/invariants/liveness (call at the end of
        the variant __init__, after layout/packer/hook state exists)."""
        import jax

        p = self.p
        S, V, M = p.n_servers, p.n_values, p.msg_slots
        self._pairs = [(i, j) for i in range(S) for j in range(S) if i != j]
        b: list = []
        for i in range(S):
            b.append(("Restart", (i,)))
        for i in range(S):
            b.append(("RequestVote", (i,)))
        for i in range(S):
            b.append(("BecomeLeader", (i,)))
        for i in range(S):
            for v in range(V):
                b.append(("ClientRequest", (i, v)))
        for i in range(S):
            b.append(("AdvanceCommitIndex", (i,)))
        for ij in self._pairs:
            b.append(("AppendEntries", ij))
        b += self._config_bindings()
        for ij in self._pairs:
            b.append(("SendSnapshot", ij))
        b += self._pre_msg_bindings()
        for m in range(M):
            b.append(("HandleMessage", (m,)))
        self.bindings = b
        self.A = len(b)
        self.expand = jax.jit(jax.vmap(self._expand1))
        from .base import messages_are_valid_kernel

        self.invariants = {
            "MessagesAreValid": jax.jit(
                messages_are_valid_kernel(self.layout, self.packer)
            ),
            "NoLogDivergence": jax.jit(self._inv_no_log_divergence),
            "MaxOneReconfigurationAtATime": jax.jit(self._inv_max_one_reconfig),
            "LeaderHasAllAckedValues": jax.jit(self._inv_leader_has_acked),
            "CommittedEntriesReachMajority": jax.jit(self._inv_committed_majority),
            "TestInv": jax.jit(lambda s: jnp.ones(s.shape[:-1], dtype=bool)),
        }
        # ReconfigurationCompletes (JointConsensus :1039-1054 with the
        # last-election-failed carve-out; AddRemove :990-1005, spec says
        # run with MaxElections = 0). checker/liveness.py runs it.
        self.liveness = {
            "ReconfigurationCompletes": [
                ("", jax.jit(self._live_reconfig_p),
                 jax.jit(self._live_reconfig_q)),
            ],
        }

    def _expand1(self, s):
        import jax

        p = self.p
        S, V, M = p.n_servers, p.n_values, p.msg_slots
        iota_s = jnp.arange(S, dtype=jnp.int32)
        pr_i = jnp.asarray([ij[0] for ij in self._pairs], jnp.int32)
        pr_j = jnp.asarray([ij[1] for ij in self._pairs], jnp.int32)
        outs = []
        outs.append(jax.vmap(lambda i: self._restart(s, i))(iota_s))
        outs.append(jax.vmap(lambda i: self._request_vote(s, i))(iota_s))
        outs.append(jax.vmap(lambda i: self._become_leader(s, i))(iota_s))
        cr_i = jnp.repeat(iota_s, V)
        cr_v = jnp.tile(jnp.arange(V, dtype=jnp.int32), S)
        outs.append(jax.vmap(lambda i, v: self._client_request(s, i, v))(cr_i, cr_v))
        outs.append(jax.vmap(lambda i: self._advance_commit_index(s, i))(iota_s))
        outs.append(jax.vmap(lambda i, j: self._append_entries(s, i, j))(pr_i, pr_j))
        outs += self._config_outs(s)
        outs.append(jax.vmap(lambda i, j: self._send_snapshot(s, i, j))(pr_i, pr_j))
        outs += self._pre_msg_outs(s, iota_s)
        outs.append(
            jax.vmap(lambda m: self._handle_message(s, m))(
                jnp.arange(M, dtype=jnp.int32)
            )
        )
        valid = jnp.concatenate([o[0] for o in outs])
        succs = jnp.concatenate([o[1] for o in outs])
        rank = jnp.concatenate([o[2] for o in outs])
        ovf = jnp.concatenate([o[3] for o in outs])
        return succs, valid, rank, ovf

    # ------ shared AdvanceCommitIndex kernel (round-5 dedup; joint
    # :613-653 dual-quorum, add/remove :605-642 member quorum) ---------

    def _commit_quorum_ok(self, d, i, idxs, match_row, ks):
        raise NotImplementedError  # [L] bool: quorum agrees at each idx

    def _commit_config_upd(self, d, i, new_ci) -> dict:
        raise NotImplementedError  # config re-derivation field updates

    def _commit_removed(self, d, i, in_range):
        raise NotImplementedError  # IsRemovedFromCluster over the window

    def _advance_commit_index(self, s, i):
        p = self.p
        S, L, V = p.n_servers, p.max_log, p.n_values
        d = self._dec(s)
        ll_i = d["log_len"][i]
        ci_i = d["commitIndex"][i]
        match_row = d["matchIndex"][i]
        idxs = jnp.arange(1, L + 1, dtype=jnp.int32)
        ks = jnp.arange(S, dtype=jnp.int32)
        quorum_ok = self._commit_quorum_ok(d, i, idxs, match_row, ks)
        is_agree = quorum_ok & (idxs <= ll_i)
        max_agree = jnp.max(jnp.where(is_agree, idxs, 0))
        term_at = d["log_term"][i][jnp.clip(max_agree - 1, 0)]
        new_ci = jnp.where(
            (max_agree > 0) & (term_at == d["currentTerm"][i]), max_agree, ci_i
        )
        valid = (d["state"][i] == LEADER) & (ci_i < new_ci)
        lanes = jnp.arange(L, dtype=jnp.int32)
        in_range = (lanes + 1 > ci_i) & (lanes + 1 <= new_ci)
        # MayBeAckClient: only AppendCommand entries can ack a value
        vals_row = jnp.where(d["log_cmd"][i] == self.CMD_APPEND,
                             d["log_val"][i], 0)
        committed = jnp.any(
            in_range[None, :]
            & (vals_row[None, :] == jnp.arange(1, V + 1, dtype=jnp.int32)[:, None]),
            axis=1,
        )
        acked = jnp.where((d["acked"] == ACK_FALSE) & committed, ACK_TRUE, d["acked"])
        upd = self._commit_config_upd(d, i, new_ci)
        upd["acked"] = acked
        removed = self._commit_removed(d, i, in_range)
        upd["state"] = jnp.where(
            removed, d["state"].at[i].set(NOTMEMBER), d["state"])
        upd["votesGranted"] = jnp.where(
            removed, d["votesGranted"].at[i].set(0), d["votesGranted"]
        )
        upd["nextIndex"] = jnp.where(
            removed,
            d["nextIndex"].at[i].set(jnp.ones((S,), jnp.int32)),
            d["nextIndex"],
        )
        upd["matchIndex"] = jnp.where(
            removed,
            d["matchIndex"].at[i].set(jnp.zeros((S,), jnp.int32)),
            d["matchIndex"],
        )
        upd["commitIndex"] = jnp.where(
            removed,
            d["commitIndex"].at[i].set(0),
            d["commitIndex"].at[i].set(new_ci),
        )
        succ = self._asm(d, **upd)
        return valid, succ, jnp.int32(R_ADVANCECOMMIT), jnp.asarray(False)

    def init_states(self) -> np.ndarray:
        """Init — :341-354: pre-installed cluster seeded with a
        NewConfigCommand; CHOOSE realized as lowest indices."""
        p = self.p
        S = p.n_servers
        lay = self.layout
        vec = lay.zeros((1,))
        members = list(range(p.init_cluster_size))
        mask = sum(1 << i for i in members)
        leader = 0
        vec[0, lay.sl("config_id")] = [1 if i in members else 0 for i in range(S)]
        vec[0, lay.sl("config_members")] = [
            mask if i in members else 0 for i in range(S)
        ]
        vec[0, lay.sl("config_committed")] = [
            1 if i in members else 0 for i in range(S)
        ]
        vec[0, lay.sl("currentTerm")] = [1 if i in members else 0 for i in range(S)]
        vec[0, lay.sl("state")] = [
            LEADER if i == leader else FOLLOWER if i in members else NOTMEMBER
            for i in range(S)
        ]
        ni = np.ones((S, S), np.int32)
        mi = np.zeros((S, S), np.int32)
        for j in members:
            ni[leader, j] = 2
            mi[leader, j] = 1
        vec[0, lay.sl("nextIndex")] = ni.reshape(-1)
        vec[0, lay.sl("matchIndex")] = mi.reshape(-1)
        lt = np.zeros((S, p.max_log), np.int32)
        lc = np.zeros((S, p.max_log), np.int32)
        lcid = np.zeros((S, p.max_log), np.int32)
        lcm = np.zeros((S, p.max_log), np.int32)
        for i in members:
            lt[i, 0] = 1
            lc[i, 0] = self.CMD_SEED
            lcid[i, 0] = 1
            lcm[i, 0] = mask
        vec[0, lay.sl("log_term")] = lt.reshape(-1)
        vec[0, lay.sl("log_cmd")] = lc.reshape(-1)
        vec[0, lay.sl("log_cid")] = lcid.reshape(-1)
        vec[0, lay.sl(f"log_{self.MEMBERS_FIELD}")] = lcm.reshape(-1)
        vec[0, lay.sl("log_len")] = [1 if i in members else 0 for i in range(S)]
        vec[0, lay.sl("commitIndex")] = [1 if i in members else 0 for i in range(S)]
        for k in range(self.n_words):
            vec[0, lay.sl(f"msg_w{k}")] = int(EMPTY)
        vec[0, lay.sl("acked")] = ACK_NIL
        return vec

    # ---------------- invariants ----------------

    def encode_msg(self, rec: tuple) -> tuple:
        d = dict(rec)
        mtype = {v: k for k, v in MTYPE_NAMES.items()}[d["mtype"]]
        kw = dict(
            mtype=mtype, mterm=d["mterm"], msource=d["msource"], mdest=d["mdest"]
        )
        if mtype == RVREQ:
            kw.update(
                mlastLogTerm=d["mlastLogTerm"], mlastLogIndex=d["mlastLogIndex"]
            )
        elif mtype == RVRESP:
            kw.update(mvoteGranted=int(d["mvoteGranted"]))
        elif mtype == AEREQ:
            kw.update(
                mprevLogIndex=d["mprevLogIndex"],
                mprevLogTerm=d["mprevLogTerm"],
                nentries=len(d["mentries"]),
                mcommitIndex=d["mcommitIndex"],
            )
            if d["mentries"]:
                kw.update(
                    {f"e_{n}": v for n, v in self._encode_entry(d["mentries"][0]).items()}
                )
        elif mtype == AERESP:
            inv_rc = {v: k for k, v in RC_NAMES.items()}
            kw.update(mresult=inv_rc[d["mresult"]], mmatchIndex=d["mmatchIndex"])
        elif mtype == SNAPREQ:
            kw.update(
                mloglen=len(d["mlog"]),
                mcommitIndex=d["mcommitIndex"],
                mmembers=sum(1 << j for j in d["mmembers"]),
            )
            for k, e in enumerate(d["mlog"]):
                kw.update({f"l{k}_{n}": v for n, v in self._encode_entry(e).items()})
        elif mtype == SNAPRESP:
            kw.update(msuccess=int(d["msuccess"]), mmatchIndex=d["mmatchIndex"])
        return self.packer.pack(**kw)

    # ---------------- host encode/decode (shared; round-5 dedup) ----------
    # Variant hooks: ``counter_fields`` (spec-bounding counters beyond
    # electionCtr/restartCtr), ``_decode_config``/``_encode_config`` (the
    # per-server configuration tuples differ: joint carries old/new
    # member sets, add/remove a single member set), and the per-entry
    # ``_decode_entry``/``_encode_entry`` the log/message paths call.

    counter_fields: tuple = ()

    def _fs(self, mask) -> frozenset:
        return frozenset(
            j for j in range(self.p.n_servers) if (int(mask) >> j) & 1
        )

    def _decode_config(self, g):
        raise NotImplementedError  # variant-specific config tuple schema

    def _encode_config(self, vec, st) -> None:
        raise NotImplementedError

    def decode(self, vec: np.ndarray) -> dict:
        lay, p = self.layout, self.p
        g = lambda n: np.asarray(vec[lay.sl(n)])
        S, L = p.n_servers, p.max_log
        EF = self.ENTRY_FIELDS
        rows = {n: g(f"log_{n}").reshape(S, L) for n in EF}
        ll = g("log_len")
        log = tuple(
            tuple(
                self._decode_entry(*(rows[n][i, k] for n in EF))
                for k in range(int(ll[i]))
            )
            for i in range(S)
        )
        vg = g("votesGranted")
        votes = tuple(
            frozenset(j for j in range(S) if (int(vg[i]) >> j) & 1)
            for i in range(S)
        )
        pr = g("pendingResponse")
        pending = tuple(
            tuple(bool((int(pr[i]) >> j) & 1) for j in range(S))
            for i in range(S)
        )
        msgs = {}
        word_arrs = [g(f"msg_w{k}") for k in range(self.n_words)]
        cnt = g("msg_cnt")
        for k in range(p.msg_slots):
            if int(word_arrs[0][k]) == int(EMPTY):
                continue
            key = tuple(int(w[k]) for w in word_arrs)
            msgs[self.decode_msg(key)] = int(cnt[k])
        out = {
            "config": self._decode_config(g),
            "currentTerm": tuple(int(x) for x in g("currentTerm")),
            "state": tuple(int(x) for x in g("state")),
            "votedFor": tuple(
                int(x) - 1 if x > 0 else None for x in g("votedFor")
            ),
            "votesGranted": votes,
            "nextIndex": tuple(
                tuple(int(x) for x in row) for row in g("nextIndex").reshape(S, S)
            ),
            "matchIndex": tuple(
                tuple(int(x) for x in row) for row in g("matchIndex").reshape(S, S)
            ),
            "pendingResponse": pending,
            "log": log,
            "commitIndex": tuple(int(x) for x in g("commitIndex")),
            "messages": frozenset(msgs.items()),
            "acked": tuple(
                {ACK_NIL: None, ACK_FALSE: False, ACK_TRUE: True}[int(x)]
                for x in g("acked")
            ),
            "electionCtr": int(vec[lay.fields["electionCtr"].offset]),
            "restartCtr": int(vec[lay.fields["restartCtr"].offset]),
        }
        for cname in self.counter_fields:
            out[cname] = int(vec[lay.fields[cname].offset])
        out["valueCtr"] = tuple(int(x) for x in g("valueCtr"))
        return out

    def decode_msg(self, key: tuple) -> tuple:
        u = self.packer.unpack_all(key)
        EF = self.ENTRY_FIELDS
        mtype = int(u["mtype"])
        rec = {
            "mtype": MTYPE_NAMES[mtype],
            "mterm": int(u["mterm"]),
            "msource": int(u["msource"]),
            "mdest": int(u["mdest"]),
        }
        if mtype == RVREQ:
            rec["mlastLogTerm"] = int(u["mlastLogTerm"])
            rec["mlastLogIndex"] = int(u["mlastLogIndex"])
        elif mtype == RVRESP:
            rec["mvoteGranted"] = bool(u["mvoteGranted"])
        elif mtype == AEREQ:
            rec["mprevLogIndex"] = int(u["mprevLogIndex"])
            rec["mprevLogTerm"] = int(u["mprevLogTerm"])
            rec["mentries"] = (
                (self._decode_entry(*(u[f"e_{n}"] for n in EF)),)
                if u["nentries"]
                else ()
            )
            rec["mcommitIndex"] = int(u["mcommitIndex"])
        elif mtype == AERESP:
            rec["mresult"] = RC_NAMES[int(u["mresult"])]
            rec["mmatchIndex"] = int(u["mmatchIndex"])
        elif mtype == SNAPREQ:
            ll = int(u["mloglen"])
            rec["mlog"] = tuple(
                self._decode_entry(*(u[f"l{k}_{n}"] for n in EF))
                for k in range(ll)
            )
            rec["mcommitIndex"] = int(u["mcommitIndex"])
            rec["mmembers"] = self._fs(u["mmembers"])
        elif mtype == SNAPRESP:
            rec["msuccess"] = bool(u["msuccess"])
            rec["mmatchIndex"] = int(u["mmatchIndex"])
        return tuple(sorted(rec.items()))

    def encode(self, st: dict) -> np.ndarray:
        lay, p = self.layout, self.p
        S, L = p.n_servers, p.max_log
        vec = lay.zeros(())
        self._encode_config(vec, st)
        vec[lay.sl("currentTerm")] = st["currentTerm"]
        vec[lay.sl("state")] = st["state"]
        vec[lay.sl("votedFor")] = [
            0 if v is None else v + 1 for v in st["votedFor"]
        ]
        vec[lay.sl("votesGranted")] = [
            sum(1 << j for j in vs) for vs in st["votesGranted"]
        ]
        rows = {n: np.zeros((S, L), np.int32) for n in self.ENTRY_FIELDS}
        for i, lg in enumerate(st["log"]):
            for k, e in enumerate(lg):
                for n, v in self._encode_entry(e).items():
                    rows[n][i, k] = v
        for n in rows:
            vec[lay.sl(f"log_{n}")] = rows[n].reshape(-1)
        vec[lay.sl("log_len")] = [len(lg) for lg in st["log"]]
        vec[lay.sl("commitIndex")] = st["commitIndex"]
        vec[lay.sl("nextIndex")] = np.asarray(st["nextIndex"]).reshape(-1)
        vec[lay.sl("matchIndex")] = np.asarray(st["matchIndex"]).reshape(-1)
        vec[lay.sl("pendingResponse")] = [
            sum(1 << j for j, b in enumerate(row) if b)
            for row in st["pendingResponse"]
        ]
        keys = sorted((self.encode_msg(rec), cnt) for rec, cnt in st["messages"])
        if len(keys) > p.msg_slots:
            raise OverflowError("message bag exceeds msg_slots")
        word_arrs = [
            np.full(p.msg_slots, int(EMPTY), np.int32)
            for _ in range(self.n_words)
        ]
        cn = np.zeros(p.msg_slots, np.int32)
        for k, (key, c) in enumerate(keys):
            for w, arr in zip(key, word_arrs):
                arr[k] = w
            cn[k] = c
        for k, arr in enumerate(word_arrs):
            vec[lay.sl(f"msg_w{k}")] = arr
        vec[lay.sl("msg_cnt")] = cn
        vec[lay.sl("acked")] = [
            {None: ACK_NIL, False: ACK_FALSE, True: ACK_TRUE}[a]
            for a in st["acked"]
        ]
        vec[lay.fields["electionCtr"].offset] = st["electionCtr"]
        vec[lay.fields["restartCtr"].offset] = st["restartCtr"]
        for cname in self.counter_fields:
            vec[lay.fields[cname].offset] = st[cname]
        vec[lay.sl("valueCtr")] = st["valueCtr"]
        return vec

