"""Shared action/invariant core of the two reconfiguration Raft lowerings.

``RaftWithReconfigJointConsensus.tla`` and ``RaftWithReconfigAddRemove.tla``
share their non-reconfig machinery almost verbatim (the reference itself
copy-inlines it); ``models/joint_raft.py`` and ``models/reconfig_raft.py``
mirrored that, leaving ~1k duplicated lines where a shared-action fix had
to land twice (round-2 verdict Weak #8). This mixin holds the common
kernels once, parameterized by three class attributes the variants set:

  ENTRY_FIELDS   log-entry lane suffixes (``log_{n}`` layout fields and
                 ``e_{n}`` / ``l{k}_{n}`` packed message fields)
  CMD_APPEND     the AppendCommand enum value (the two specs number their
                 command sets differently)
  ACTION_NAMES   Next-disjunct labels for traces

The shared Next-disjunct RANKS are identical by construction in both
specs (verified by asserts in each variant module): positions 0-11 for
the core-Raft actions and 14-16 for the snapshot trio.

Everything genuinely variant-specific — dual old/new quorums vs.
member-set quorums, reconfig append actions, LogOk strictness, the fused
receipt kernel — stays in the variant modules.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..ops import bag

# enums shared by both variants (identical values in both specs' lowerings)
FOLLOWER, CANDIDATE, LEADER, NOTMEMBER = range(4)
NIL = 0
ACK_NIL, ACK_FALSE, ACK_TRUE = 0, 1, 2
RVREQ, RVRESP, AEREQ, AERESP, SNAPREQ, SNAPRESP = 1, 2, 3, 4, 5, 6
PENDING_SNAP_REQUEST = -1  # JointConsensus :293 / AddRemove :271
PENDING_SNAP_RESPONSE = -2

# shared Next-disjunct ranks (both variants lay their Next out so these
# land at the same indices; asserted in the variant modules)
(
    R_RESTART,
    R_UPDATETERM,
    R_REQUESTVOTE,
    R_BECOMELEADER,
    R_HANDLE_RVREQ,
    R_HANDLE_RVRESP,
    R_CLIENTREQUEST,
    R_ADVANCECOMMIT,
    R_APPENDENTRIES,
    R_REJECT_AE,
    R_ACCEPT_AE,
    R_HANDLE_AERESP,
) = range(12)
R_SENDSNAP, R_HANDLE_SNAPREQ, R_HANDLE_SNAPRESP = 14, 15, 16


class ConfigRaftCommon:
    """Mixin with the kernels common to both reconfig lowerings.

    Subclass contract: ``self.p`` (params with n_servers/max_log/
    max_term/max_elections/max_restarts/max_values_per_term/n_values),
    ``self.layout``/``self.packer``/``self.n_words``/``self.bindings``,
    layout fields named as in the variants (``config_members``,
    ``log_{n}`` for n in ENTRY_FIELDS, ...), and the three class attrs
    documented in the module docstring."""

    ENTRY_FIELDS: tuple[str, ...]
    CMD_APPEND: int
    ACTION_NAMES: list[str]

    def action_label(self, rank: int, cand: int) -> str:
        name, binding = self.bindings[cand]
        if name == "HandleMessage":
            return f"{self.ACTION_NAMES[rank]}(slot {binding[0]})"
        return f"{name}{binding}"

    # ---------------- field access helpers ----------------

    def _dec(self, s):
        g = self.layout.get
        return {f: g(s, f) for f in self.layout.fields}

    def _asm(self, d, **updates):
        parts = []
        for name, f in self.layout.fields.items():
            arr = updates.get(name, d[name])
            arr = jnp.asarray(arr, jnp.int32)
            parts.append(arr.reshape(-1) if f.shape else arr.reshape(1))
        return jnp.concatenate(parts)

    def _pack(self, **vals):
        return tuple(jnp.asarray(w, jnp.int32) for w in self.packer.pack(**vals))

    def _words(self, d):
        return [d[f"msg_w{k}"] for k in range(self.n_words)]

    def _bag_put(self, words, cnt, key):
        return bag.wide_bag_put(words, cnt, key)

    def _word_upd(self, words, cnt):
        upd = {f"msg_w{k}": w for k, w in enumerate(words)}
        upd["msg_cnt"] = cnt
        return upd

    @staticmethod
    def _last_term(d, i):
        """LastTerm — JointConsensus :252 / AddRemove :173."""
        ll = d["log_len"][i]
        return jnp.where(ll > 0, d["log_term"][i][jnp.clip(ll - 1, 0)], 0)

    @staticmethod
    def _popcount(x, S):
        return jnp.sum((x >> jnp.arange(S, dtype=jnp.int32)) & 1)

    # ---------------- shared action kernels ----------------

    def _restart(self, s, i):
        """Restart(i) — JointConsensus :362-374 / AddRemove :346-358:
        keeps config, currentTerm, votedFor, log."""
        p, S = self.p, self.p.n_servers
        d = self._dec(s)
        valid = d["restartCtr"] < p.max_restarts
        succ = self._asm(
            d,
            state=d["state"].at[i].set(FOLLOWER),
            votesGranted=d["votesGranted"].at[i].set(0),
            nextIndex=d["nextIndex"].at[i].set(jnp.ones((S,), jnp.int32)),
            matchIndex=d["matchIndex"].at[i].set(jnp.zeros((S,), jnp.int32)),
            pendingResponse=d["pendingResponse"].at[i].set(0),
            commitIndex=d["commitIndex"].at[i].set(0),
            restartCtr=d["restartCtr"] + 1,
        )
        return valid, succ, jnp.int32(R_RESTART), jnp.asarray(False)

    def _request_vote(self, s, i):
        """RequestVote(i) — JointConsensus :431-450 / AddRemove :425-444:
        member-only; RequestVoteRequests to the member set via
        SendMultipleOnce."""
        p, S = self.p, self.p.n_servers
        d = self._dec(s)
        st_i = d["state"][i]
        members = d["config_members"][i]
        valid = (
            (d["electionCtr"] < p.max_elections)
            & ((st_i == FOLLOWER) | (st_i == CANDIDATE))
            & (((members >> i) & 1) > 0)
        )
        new_term = d["currentTerm"][i] + 1
        last_t = self._last_term(d, i)
        ll_i = d["log_len"][i]
        words, cnt = self._words(d), d["msg_cnt"]
        ovf = jnp.asarray(False)
        for delta in range(1, S):
            j = jnp.mod(i + delta, S)
            is_member = ((members >> j) & 1) > 0
            key = self._pack(
                mtype=RVREQ,
                mterm=new_term,
                mlastLogTerm=last_t,
                mlastLogIndex=ll_i,
                msource=i,
                mdest=j,
            )
            w2, c2, existed, o = self._bag_put(words, cnt, key)
            valid &= (~is_member) | ~existed  # SendMultipleOnce
            ovf |= is_member & o
            words = [jnp.where(is_member, a, b) for a, b in zip(w2, words)]
            cnt = jnp.where(is_member, c2, cnt)
        succ = self._asm(
            d,
            state=d["state"].at[i].set(CANDIDATE),
            currentTerm=d["currentTerm"].at[i].set(new_term),
            votedFor=d["votedFor"].at[i].set(i + 1),
            votesGranted=d["votesGranted"].at[i].set(jnp.int32(1) << i),
            electionCtr=d["electionCtr"] + 1,
            **self._word_upd(words, cnt),
        )
        return valid, succ, jnp.int32(R_REQUESTVOTE), ovf & valid

    def _client_request(self, s, i, v):
        """ClientRequest(i, v) — JointConsensus :535-550 / AddRemove
        :525-540 (acked gate + per-term valueCtr)."""
        p, L = self.p, self.p.max_log
        d = self._dec(s)
        term = d["currentTerm"][i]
        tpos = jnp.clip(term - 1, 0, p.max_term - 1)
        valid = (
            (d["state"][i] == LEADER)
            & (d["acked"][v] == ACK_NIL)
            & (d["valueCtr"][tpos] < p.max_values_per_term)
        )
        pos = d["log_len"][i]
        ovf = valid & (pos >= L)
        posc = jnp.clip(pos, 0, L - 1)
        succ = self._asm(
            d,
            log_term=d["log_term"].at[i, posc].set(term),
            log_cmd=d["log_cmd"].at[i, posc].set(self.CMD_APPEND),
            log_val=d["log_val"].at[i, posc].set(v + 1),
            log_len=d["log_len"].at[i].add(1),
            acked=d["acked"].at[v].set(ACK_FALSE),
            valueCtr=d["valueCtr"].at[tpos].add(1),
        )
        return valid, succ, jnp.int32(R_CLIENTREQUEST), ovf

    def _append_entries(self, s, i, j):
        """AppendEntries(i, j) — JointConsensus :556-582 / AddRemove
        :546-572: member- and snapshot-sentinel-gated; empty requests are
        send-once."""
        p = self.p
        L = p.max_log
        d = self._dec(s)
        ni_ij = d["nextIndex"][i, j]
        valid = (
            (d["state"][i] == LEADER)
            & (((d["config_members"][i] >> j) & 1) > 0)
            & (ni_ij >= 0)
            & (((d["pendingResponse"][i] >> j) & 1) == 0)
        )
        prev_idx = ni_ij - 1
        prev_term = jnp.where(
            prev_idx > 0, d["log_term"][i][jnp.clip(prev_idx - 1, 0, L - 1)], 0
        )
        last_entry = jnp.minimum(d["log_len"][i], ni_ij)
        nent = (last_entry >= ni_ij).astype(jnp.int32)
        epos = jnp.clip(ni_ij - 1, 0, L - 1)
        z = jnp.int32(0)
        kw = dict(
            mtype=AEREQ,
            mterm=d["currentTerm"][i],
            mprevLogIndex=jnp.clip(prev_idx, 0),
            mprevLogTerm=prev_term,
            nentries=nent,
            mcommitIndex=jnp.clip(jnp.minimum(d["commitIndex"][i], last_entry), 0),
            msource=i,
            mdest=j,
        )
        for n in self.ENTRY_FIELDS:
            kw[f"e_{n}"] = jnp.where(nent > 0, d[f"log_{n}"][i][epos], z)
        key = self._pack(**kw)
        words, cnt, existed, ovf = self._bag_put(self._words(d), d["msg_cnt"], key)
        valid &= (nent > 0) | ~existed  # empty AEReq is send-once
        succ = self._asm(
            d,
            pendingResponse=d["pendingResponse"].at[i].set(
                d["pendingResponse"][i] | (jnp.int32(1) << j)
            ),
            **self._word_upd(words, cnt),
        )
        return valid, succ, jnp.int32(R_APPENDENTRIES), ovf & valid

    def _send_snapshot(self, s, i, j):
        """SendSnapshot(i, j) — JointConsensus :885-901 / AddRemove
        :862-878: embeds the whole log in the request."""
        p, L = self.p, self.p.max_log
        d = self._dec(s)
        valid = (
            (d["state"][i] == LEADER)
            & (((d["config_members"][i] >> j) & 1) > 0)
            & (d["nextIndex"][i, j] == PENDING_SNAP_REQUEST)
        )
        kw = dict(
            mtype=SNAPREQ,
            mterm=d["currentTerm"][i],
            mcommitIndex=d["commitIndex"][i],
            mmembers=d["config_members"][i],
            mloglen=d["log_len"][i],
            msource=i,
            mdest=j,
        )
        lanes = jnp.arange(L, dtype=jnp.int32)
        live = lanes < d["log_len"][i]
        for k in range(L):
            for n in self.ENTRY_FIELDS:
                kw[f"l{k}_{n}"] = jnp.where(live[k], d[f"log_{n}"][i][k], 0)
        key = self._pack(**kw)
        words, cnt, _existed, ovf = self._bag_put(self._words(d), d["msg_cnt"], key)
        succ = self._asm(
            d,
            nextIndex=d["nextIndex"].at[i, j].set(PENDING_SNAP_RESPONSE),
            **self._word_upd(words, cnt),
        )
        return valid, succ, jnp.int32(R_SENDSNAP), ovf & valid

    # ---------------- shared invariants ----------------

    def _inv_no_log_divergence(self, states):
        """NoLogDivergence — JointConsensus :1066-1074 / AddRemove
        :1017-1025 (full-entry equality over all entry lanes)."""
        lay, L = self.layout, self.p.max_log
        ci = lay.get(states, "commitIndex")
        mci = jnp.minimum(ci[:, :, None], ci[:, None, :])
        lanes = jnp.arange(1, L + 1, dtype=jnp.int32)
        in_common = lanes[None, None, None, :] <= mci[..., None]
        eq = jnp.ones(in_common.shape, dtype=bool)
        for n in self.ENTRY_FIELDS:
            f = lay.get(states, f"log_{n}")
            eq &= f[:, :, None, :] == f[:, None, :, :]
        return jnp.all(~in_common | eq, axis=(1, 2, 3))

    def _inv_leader_has_acked(self, states):
        """LeaderHasAllAckedValues — JointConsensus :1109-1125 / AddRemove
        :1047-1063."""
        lay, V = self.layout, self.p.n_values
        ct = lay.get(states, "currentTerm")
        st = lay.get(states, "state")
        lv = lay.get(states, "log_val")
        cmd = lay.get(states, "log_cmd")
        acked = lay.get(states, "acked")
        not_stale = jnp.all(ct[:, :, None] >= ct[:, None, :], axis=2)
        is_lead = (st == LEADER) & not_stale
        vals = jnp.arange(1, V + 1, dtype=jnp.int32)
        lv_app = jnp.where(cmd == self.CMD_APPEND, lv, 0)
        has_v = jnp.any(lv_app[:, :, None, :] == vals[None, None, :, None], axis=3)
        bad = jnp.any(
            (acked[:, None, :] == ACK_TRUE) & is_lead[:, :, None] & ~has_v,
            axis=(1, 2),
        )
        return ~bad
