"""Spec registry: maps a TLA+ module name to its TPU lowering builder.

Each builder consumes a parsed TLC cfg (utils/cfg.py) and returns a ready
model plus checking options — the ``CHECKER=tpu`` toggle's dispatch table.
Variants land here as they are lowered (SURVEY.md §7.1 order).
"""

from __future__ import annotations

from dataclasses import dataclass

from ..utils.cfg import Cfg, CfgError
from .kraft import KRaftModel, KRaftParams
from .pull_raft import PullRaftModel, PullRaftParams
from .raft import RaftModel, RaftParams


@dataclass
class CheckSetup:
    model: object
    invariants: tuple[str, ...]
    symmetry: bool
    server_names: list[str]
    value_names: list[str]


def _require_int(cfg: Cfg, name: str) -> int:
    if name not in cfg.constants:
        raise CfgError(f"{cfg.path}: required constant {name} is missing")
    v = cfg.constants[name]
    if not isinstance(v, int) or isinstance(v, bool):
        raise CfgError(f"{cfg.path}: constant {name} must be a number, got {v!r}")
    return v


def _require_bool(cfg: Cfg, name: str) -> bool:
    if name not in cfg.constants:
        raise CfgError(f"{cfg.path}: required constant {name} is missing")
    v = cfg.constants[name]
    if not isinstance(v, bool):
        raise CfgError(f"{cfg.path}: constant {name} must be TRUE/FALSE, got {v!r}")
    return v


def _check_invariants(cfg: Cfg, model) -> None:
    unknown = [i for i in cfg.invariants if i not in model.invariants]
    if unknown:
        raise CfgError(f"{cfg.path}: unknown invariant(s) {unknown}")


def build_raft(
    cfg: Cfg, msg_slots: int | None = None, net_faults: bool = False
) -> CheckSetup:
    """standard-raft/Raft.tla + Raft.cfg."""
    servers = cfg.server_like("Server")
    values = cfg.server_like("Value")
    params = RaftParams(
        n_servers=len(servers),
        n_values=len(values),
        max_elections=_require_int(cfg, "MaxElections"),
        max_restarts=_require_int(cfg, "MaxRestarts"),
        msg_slots=msg_slots if msg_slots is not None else 48,
        net_faults=net_faults,
    )
    model = RaftModel(params, server_names=servers, value_names=values)
    _check_invariants(cfg, model)
    return CheckSetup(
        model=model,
        invariants=tuple(cfg.invariants),
        symmetry=cfg.symmetry is not None,
        server_names=servers,
        value_names=values,
    )


def build_flexible_raft(
    cfg: Cfg, msg_slots: int | None = None, net_faults: bool = False
) -> CheckSetup:
    """flexible-raft/FlexibleRaft.tla + FlexibleRaft.cfg: structurally core
    Raft with count-based quorums (FlexibleRaft.tla:262,296), strictly
    send-once messaging (:127-151), no pendingResponse (:109), and
    term-mismatch-only truncation (:413-416)."""
    servers = cfg.server_like("Server")
    values = cfg.server_like("Value")
    params = RaftParams(
        n_servers=len(servers),
        n_values=len(values),
        max_elections=_require_int(cfg, "MaxElections"),
        max_restarts=_require_int(cfg, "MaxRestarts"),
        msg_slots=msg_slots if msg_slots is not None else 48,
        election_quorum=_require_int(cfg, "ElectionQuorumSize"),
        replication_quorum=_require_int(cfg, "ReplicationQuorumSize"),
        strict_send_once=True,
        has_pending_response=False,
        trunc_term_mismatch=True,
        net_faults=net_faults,
    )
    model = RaftModel(params, server_names=servers, value_names=values)
    model.name = "FlexibleRaft"
    _check_invariants(cfg, model)
    return CheckSetup(
        model=model,
        invariants=tuple(cfg.invariants),
        symmetry=cfg.symmetry is not None,
        server_names=servers,
        value_names=values,
    )


def build_raft_fsync(
    cfg: Cfg, msg_slots: int | None = None, net_faults: bool = False
) -> CheckSetup:
    """raft-and-fsync/RaftFsync.tla + RaftFsync.cfg: core Raft plus
    fsyncIndex durability (RaftFsync.tla:92), crash-truncation restart
    (:203-218), split Timeout/RequestVote (:222-243), AdvanceFsyncIndex
    (:339), three fsync policy constants (:50-52), strictly send-once
    messaging (:132-152), and no pendingResponse flow control."""
    servers = cfg.server_like("Server")
    values = cfg.server_like("Value")
    params = RaftParams(
        n_servers=len(servers),
        n_values=len(values),
        max_elections=_require_int(cfg, "MaxElections"),
        max_restarts=_require_int(cfg, "MaxRestarts"),
        msg_slots=msg_slots if msg_slots is not None else 48,
        strict_send_once=True,
        has_pending_response=False,
        trunc_term_mismatch=True,
        has_fsync=True,
        fsync_leader_before_ae=_require_bool(cfg, "LeaderFsyncBeforeAppendEntries"),
        fsync_leader_quorum=_require_bool(cfg, "LeaderFsyncBeforeIncludeInQuorum"),
        fsync_follower_reply=_require_bool(cfg, "FollowerFsyncBeforeReply"),
        net_faults=net_faults,
    )
    model = RaftModel(params, server_names=servers, value_names=values)
    model.name = "RaftFsync"
    _check_invariants(cfg, model)
    return CheckSetup(
        model=model,
        invariants=tuple(cfg.invariants),
        symmetry=cfg.symmetry is not None,
        server_names=servers,
        value_names=values,
    )


def _build_pull(cfg: Cfg, msg_slots: int | None, variant2: bool) -> CheckSetup:
    servers = cfg.server_like("Server")
    values = cfg.server_like("Value")
    params = PullRaftParams(
        n_servers=len(servers),
        n_values=len(values),
        max_elections=_require_int(cfg, "MaxElections"),
        max_restarts=_require_int(cfg, "MaxRestarts"),
        # pull specs need extra bag headroom: every message type is
        # send-once, so count-0 records pile up across a behavior
        msg_slots=msg_slots if msg_slots is not None else 64,
        variant2=variant2,
    )
    model = PullRaftModel(params, server_names=servers, value_names=values)
    _check_invariants(cfg, model)
    return CheckSetup(
        model=model,
        invariants=tuple(cfg.invariants),
        symmetry=cfg.symmetry is not None,
        server_names=servers,
        value_names=values,
    )


def build_pull_raft(cfg: Cfg, msg_slots: int | None = None) -> CheckSetup:
    """pull-raft/PullRaft.tla + PullRaft.cfg (note: the reference cfg
    references the undeclared model value `v2`, PullRaft.cfg:9-11 — parse
    with lenient=True to diagnose-and-repair)."""
    return _build_pull(cfg, msg_slots, variant2=False)


def build_pull_raft_v2(cfg: Cfg, msg_slots: int | None = None) -> CheckSetup:
    """pull-raft/PullRaftVariant2.tla + PullRaftVariant2.cfg (same cfg bug)."""
    return _build_pull(cfg, msg_slots, variant2=True)


def build_kraft(cfg: Cfg, msg_slots: int | None = None) -> CheckSetup:
    """pull-raft/KRaft.tla + KRaft.cfg: Kafka KRaft (KIP-595) with five
    server states + IllegalState, fetch-based replication with correlation,
    error codes, and the BeginQuorumRequest leadership notify."""
    servers = cfg.server_like("Server")
    values = cfg.server_like("Value")
    params = KRaftParams(
        n_servers=len(servers),
        n_values=len(values),
        max_elections=_require_int(cfg, "MaxElections"),
        max_restarts=_require_int(cfg, "MaxRestarts"),
        # fetch responses carry full correlation records, so distinct-record
        # counts run higher than the push-based variants
        msg_slots=msg_slots if msg_slots is not None else 80,
    )
    model = KRaftModel(params, server_names=servers, value_names=values)
    _check_invariants(cfg, model)
    return CheckSetup(
        model=model,
        invariants=tuple(cfg.invariants),
        symmetry=cfg.symmetry is not None,
        server_names=servers,
        value_names=values,
    )


def build_reconfig_add_remove(cfg: Cfg, msg_slots: int | None = None) -> CheckSetup:
    """standard-raft/RaftWithReconfigAddRemove.tla + its cfg. The reference
    cfg omits the required ``MaxClusterSize`` constant
    (RaftWithReconfigAddRemove.tla:88 vs the cfg; SURVEY.md §2.2) — strict
    mode raises, lenient mode repairs it to |Server| (the physical bound)
    and records a diagnostic."""
    from .reconfig_raft import ReconfigRaftModel, ReconfigRaftParams

    servers = cfg.server_like("Server")
    values = cfg.server_like("Value")
    if "MaxClusterSize" not in cfg.constants:
        diag = (
            f"{cfg.path}: required constant MaxClusterSize "
            "(RaftWithReconfigAddRemove.tla:88) is missing from the cfg; "
            f"lenient mode repairs this by defaulting it to |Server| = {len(servers)}"
        )
        if not cfg.lenient:
            raise CfgError(diag)
        cfg.diagnostics.append(diag)
        cfg.constants["MaxClusterSize"] = len(servers)
    params = ReconfigRaftParams(
        n_servers=len(servers),
        n_values=len(values),
        init_cluster_size=_require_int(cfg, "InitClusterSize"),
        max_elections=_require_int(cfg, "MaxElections"),
        max_restarts=_require_int(cfg, "MaxRestarts"),
        max_values_per_term=_require_int(cfg, "MaxValuesPerTerm"),
        max_add_reconfigs=_require_int(cfg, "MaxAddReconfigs"),
        max_remove_reconfigs=_require_int(cfg, "MaxRemoveReconfigs"),
        min_cluster_size=_require_int(cfg, "MinClusterSize"),
        max_cluster_size=_require_int(cfg, "MaxClusterSize"),
        include_thesis_bug=_require_bool(cfg, "IncludeThesisBug"),
        # snapshot records embed whole logs and AppendEntries pile up per
        # (term, prev, entry) combination: needs the most headroom so far
        msg_slots=msg_slots if msg_slots is not None else 112,
    )
    model = ReconfigRaftModel(params, server_names=servers, value_names=values)
    _check_invariants(cfg, model)
    return CheckSetup(
        model=model,
        invariants=tuple(cfg.invariants),
        symmetry=cfg.symmetry is not None,
        server_names=servers,
        value_names=values,
    )


def build_reconfig_joint(cfg: Cfg, msg_slots: int | None = None) -> CheckSetup:
    """standard-raft/RaftWithReconfigJointConsensus.tla + its cfg: joint
    consensus reconfiguration with dual quorums and the ReconfigType knob
    (RaftWithReconfigJointConsensus.tla:79-80)."""
    from .joint_raft import JointRaftModel, JointRaftParams

    servers = cfg.server_like("Server")
    values = cfg.server_like("Value")
    params = JointRaftParams(
        n_servers=len(servers),
        n_values=len(values),
        init_cluster_size=_require_int(cfg, "InitClusterSize"),
        max_elections=_require_int(cfg, "MaxElections"),
        max_restarts=_require_int(cfg, "MaxRestarts"),
        max_reconfigs=_require_int(cfg, "MaxReconfigs"),
        max_values_per_term=_require_int(cfg, "MaxValuesPerTerm"),
        reconfig_type=_require_int(cfg, "ReconfigType"),
        msg_slots=msg_slots if msg_slots is not None else 112,
    )
    model = JointRaftModel(params, server_names=servers, value_names=values)
    _check_invariants(cfg, model)
    return CheckSetup(
        model=model,
        invariants=tuple(cfg.invariants),
        symmetry=cfg.symmetry is not None,
        server_names=servers,
        value_names=values,
    )


def build_kraft_reconfig(cfg: Cfg, msg_slots: int | None = None) -> CheckSetup:
    """pull-raft/KRaftWithReconfig.tla + its cfg: the dynamic-server
    universe spec, device-lowered with MaxSpawnedServers identity slots
    (its cfg prescribes simulation, KRaftWithReconfig.cfg:5). The cfg
    shares PullRaft.cfg's latent bug: Value = {v1, v2} with v2 undeclared
    (lenient repairs)."""
    from .kraft_reconfig import KRaftReconfigParams

    hosts = cfg.server_like("Hosts")
    values = cfg.server_like("Value")
    params = KRaftReconfigParams(
        n_hosts=len(hosts),
        n_values=len(values),
        init_cluster_size=_require_int(cfg, "InitClusterSize"),
        min_cluster_size=_require_int(cfg, "MinClusterSize"),
        max_cluster_size=_require_int(cfg, "MaxClusterSize"),
        max_elections=_require_int(cfg, "MaxElections"),
        max_restarts=_require_int(cfg, "MaxRestarts"),
        max_values_per_epoch=_require_int(cfg, "MaxValuesPerEpoch"),
        max_add_reconfigs=_require_int(cfg, "MaxAddReconfigs"),
        max_remove_reconfigs=_require_int(cfg, "MaxRemoveReconfigs"),
        max_spawned_servers=_require_int(cfg, "MaxSpawnedServers"),
        msg_slots=msg_slots if msg_slots is not None else 40,
    )
    # fresh model per setup (names differ per cfg; the lru cache is keyed
    # on params only, so mutating a cached instance would alias setups)
    from .kraft_reconfig import KRaftReconfigModel

    model = KRaftReconfigModel(params, server_names=hosts, value_names=values)
    _check_invariants(cfg, model)
    return CheckSetup(
        model=model,
        invariants=tuple(cfg.invariants),
        symmetry=cfg.symmetry is not None,
        server_names=hosts,
        value_names=values,
    )


BUILDERS = {
    "Raft": build_raft,
    "FlexibleRaft": build_flexible_raft,
    "RaftFsync": build_raft_fsync,
    "PullRaft": build_pull_raft,
    "PullRaftVariant2": build_pull_raft_v2,
    "KRaft": build_kraft,
    "RaftWithReconfigAddRemove": build_reconfig_add_remove,
    "RaftWithReconfigJointConsensus": build_reconfig_joint,
    "KRaftWithReconfig": build_kraft_reconfig,
}


def oracle_for_setup(setup: CheckSetup):
    """Pure-Python differential oracle matching the setup's model params."""
    p = setup.model.p
    if isinstance(p, PullRaftParams):
        from ..oracle.pull_oracle import PullRaftOracle

        return PullRaftOracle(
            p.n_servers, p.n_values, p.max_elections, p.max_restarts,
            variant2=p.variant2,
        )
    if isinstance(p, KRaftParams):
        from ..oracle.kraft_oracle import KRaftOracle

        return KRaftOracle(p.n_servers, p.n_values, p.max_elections, p.max_restarts)
    from .reconfig_raft import ReconfigRaftParams

    if isinstance(p, ReconfigRaftParams):
        from ..oracle.reconfig_oracle import ReconfigRaftOracle

        return ReconfigRaftOracle(
            p.n_servers, p.n_values, p.init_cluster_size, p.max_elections,
            p.max_restarts, p.max_values_per_term, p.max_add_reconfigs,
            p.max_remove_reconfigs, p.min_cluster_size, p.max_cluster_size,
            include_thesis_bug=p.include_thesis_bug,
        )
    from .kraft_reconfig import KRaftReconfigParams

    if isinstance(p, KRaftReconfigParams):
        from ..oracle.kraft_reconfig_oracle import KRaftReconfigOracle

        return KRaftReconfigOracle(
            p.n_hosts, p.n_values, p.init_cluster_size, p.min_cluster_size,
            p.max_cluster_size, p.max_elections, p.max_restarts,
            p.max_values_per_epoch, p.max_add_reconfigs,
            p.max_remove_reconfigs, p.max_spawned_servers,
        )
    from .joint_raft import JointRaftParams

    if isinstance(p, JointRaftParams):
        from ..oracle.joint_oracle import JointRaftOracle

        return JointRaftOracle(
            p.n_servers, p.n_values, p.init_cluster_size, p.max_elections,
            p.max_restarts, p.max_reconfigs, p.max_values_per_term,
            p.reconfig_type,
        )
    from ..oracle.raft_oracle import oracle_for

    return oracle_for(p)


# Spec families whose lowering implements the opt-in DuplicateMessage /
# DropMessage kernels (Raft.tla:508-523).
NET_FAULT_SPECS = ("Raft", "FlexibleRaft", "RaftFsync")


def build_from_cfg(
    cfg: Cfg,
    spec: str | None = None,
    msg_slots: int | None = None,
    net_faults: bool = False,
) -> CheckSetup:
    import os

    name = spec or os.path.splitext(os.path.basename(cfg.path))[0]
    if name not in BUILDERS:
        raise CfgError(
            f"no TPU lowering registered for spec {name!r} "
            f"(available: {', '.join(sorted(BUILDERS))})"
        )
    if net_faults:
        if name not in NET_FAULT_SPECS:
            raise CfgError(
                f"{cfg.path}: --net-faults is only lowered for the Raft "
                f"family (available: {', '.join(NET_FAULT_SPECS)}), not "
                f"{name!r}"
            )
        return BUILDERS[name](cfg, msg_slots=msg_slots, net_faults=True)
    return BUILDERS[name](cfg, msg_slots=msg_slots)
