"""TPU lowering of the Kafka KRaft spec.

Reference: ``/root/reference/specifications/pull-raft/KRaft.tla`` (961
lines). Every action kernel cites the TLA+ lines it lowers. The lowering is
*not* a translation: actions become branchless, ``vmap``-able successor
kernels over a packed int32 state vector.

Structural notes:
  - five server states + IllegalState (``KRaft.tla:69,87``) encoded as a
    small-integer enum; the QuorumState transition machine
    (``HasConsistentLeader:316``, ``MaybeTransition:351``,
    ``MaybeHandleCommonResponse:369``) is a branchless select chain;
  - ``pendingFetch`` (``KRaft.tla:123``) holds the exact FetchRequest the
    follower sent; its ``msource`` is the row index, so it decomposes into
    four plain per-server lanes (epoch/offset/lastFetchedEpoch/dest) with
    epoch > 0 doubling as the non-Nil flag;
  - FetchResponses embed the request as a ``correlation`` field
    (``KRaft.tla:649``); the request's source/dest are the response's
    dest/source, so only its three scalar fields pack into the key;
  - the ``Reply`` anti-cycle rule — a FetchResponse may not be duplicated
    (``KRaft.tla:220-227``) — becomes ``valid &= ~existed``;
  - epochs live in [1, 1+MaxElections] (only ``RequestVote:439`` mints);
    per-server log length is bounded by |Value| (``acked[v] = Nil`` gate,
    ``KRaft.tla:596``); quorums are popcount thresholds.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import bag
from ..ops.packing import EMPTY, BitPacker, bits_for
from .base import (
    ActionLabelMixin,
    Layout,
    SparseExpandMixin,
    messages_are_valid_kernel,
)

# state[i] enum, shared with oracle/kraft_oracle.py (KRaft.tla:69,87)
UNATTACHED, VOTED, FOLLOWER, CANDIDATE, LEADER, ILLEGAL = range(6)
NIL = 0  # votedFor/leader Nil; server i stored as i+1
ACK_NIL, ACK_FALSE, ACK_TRUE = 0, 1, 2

# mtype (KRaft.tla:75-78); BeginQuorumResponse records are sent but never
# received (header note, KRaft.tla:17-21)
RVREQ, RVRESP, BQREQ, BQRESP, FETCHREQ, FETCHRESP = 1, 2, 3, 4, 5, 6
# merror (KRaft.tla:84); 0 = Nil
E_NONE, E_FENCED, E_NOTLEADER, E_UNKNOWN = 0, 1, 2, 3
# mresult (KRaft.tla:81); 0 = absent (non-fetch-response records)
R_NONE, R_OK, R_NOTOK, R_DIVERGING = 0, 1, 2, 3

# Next-disjunct order (KRaft.tla:823-840), for trace labels.
(
    K_RESTART,
    K_REQUESTVOTE,
    K_HANDLE_RVREQ,
    K_HANDLE_RVRESP,
    K_BECOMELEADER,
    K_CLIENTREQUEST,
    K_REJECT_FETCH,
    K_DIVERGING_FETCH,
    K_ACCEPT_FETCH,
    K_HANDLE_BQREQ,
    K_SENDFETCH,
    K_HANDLE_FETCH_OK,
    K_HANDLE_FETCH_DIV,
    K_HANDLE_FETCH_ERR,
) = range(14)

ACTION_NAMES = [
    "Restart",
    "RequestVote",
    "HandleRequestVoteRequest",
    "HandleRequestVoteResponse",
    "BecomeLeader",
    "ClientRequest",
    "RejectFetchRequest",
    "DivergingFetchRequest",
    "AcceptFetchRequest",
    "HandleBeginQuorumRequest",
    "SendFetchRequest",
    "HandleSuccessFetchResponse",
    "HandleDivergingFetchResponse",
    "HandleErrorFetchResponse",
]

STATE_NAMES = {
    UNATTACHED: "Unattached",
    VOTED: "Voted",
    FOLLOWER: "Follower",
    CANDIDATE: "Candidate",
    LEADER: "Leader",
    ILLEGAL: "IllegalState",
}
MTYPE_NAMES = {
    RVREQ: "RequestVoteRequest",
    RVRESP: "RequestVoteResponse",
    BQREQ: "BeginQuorumRequest",
    BQRESP: "BeginQuorumResponse",
    FETCHREQ: "FetchRequest",
    FETCHRESP: "FetchResponse",
}
ERROR_NAMES = {E_NONE: None, E_FENCED: "FencedLeaderEpoch",
               E_NOTLEADER: "NotLeader", E_UNKNOWN: "UnknownLeader"}
RESULT_NAMES = {R_OK: "Ok", R_NOTOK: "NotOk", R_DIVERGING: "Diverging"}


@dataclass(frozen=True)
class KRaftParams:
    n_servers: int
    n_values: int
    max_elections: int
    max_restarts: int
    msg_slots: int = 64

    @property
    def max_epoch(self) -> int:
        return 1 + self.max_elections

    @property
    def max_log(self) -> int:
        return max(1, self.n_values)


def _build_layout(p: KRaftParams) -> Layout:
    S, V, L, M = p.n_servers, p.n_values, p.max_log, p.msg_slots
    lay = Layout(S)
    # VIEW (KRaft.tla:154) = messages, serverVars, candidateVars,
    # leaderVars, logVars AND acked; only electionCtr/restartCtr are aux.
    lay.add("currentEpoch", "per_server", (S,))
    lay.add("state", "per_server", (S,))
    lay.add("votedFor", "per_server_val", (S,))
    lay.add("leader", "per_server_val", (S,))
    # pendingFetch (KRaft.tla:123) decomposed; pf_epoch > 0 <=> non-Nil
    lay.add("pf_epoch", "per_server", (S,))
    lay.add("pf_offset", "per_server", (S,))
    lay.add("pf_lastepoch", "per_server", (S,))
    lay.add("pf_dest", "per_server_val", (S,))
    lay.add("log_epoch", "per_server", (S, L))
    lay.add("log_value", "per_server", (S, L))
    lay.add("log_len", "per_server", (S,))
    lay.add("highWatermark", "per_server", (S,))
    lay.add("votesGranted", "server_bitmask", (S,))
    lay.add("endOffset", "per_server_pair", (S, S))
    lay.add("acked", "scalar", (V,))  # in VIEW (KRaft.tla:154)
    lay.add("msg_hi", "msg_hi", (M,))
    lay.add("msg_lo", "msg_lo", (M,))
    lay.add("msg_cnt", "msg_cnt", (M,))
    lay.add("electionCtr", "aux")
    lay.add("restartCtr", "aux")
    return lay.finish()


def _build_packer(p: KRaftParams) -> BitPacker:
    tb = bits_for(p.max_epoch)
    sb = bits_for(p.n_servers - 1)
    nb = bits_for(p.n_servers)  # nil-valued server fields (0..S)
    lb = bits_for(p.max_log + 1)
    vb = bits_for(p.n_values)
    return BitPacker(
        [
            ("mtype", 3),
            ("mepoch", tb),
            ("msource", sb),
            ("mdest", sb),
            ("mlastLogEpoch", tb),  # RequestVoteRequest (KRaft.tla:450-455)
            ("mlastLogOffset", lb),
            ("mleader", nb),  # RequestVote/Fetch responses (KRaft.tla:500)
            ("mvoteGranted", 1),
            ("merror", 2),
            ("mresult", 2),  # FetchResponse only (KRaft.tla:81)
            ("mfetchOffset", lb),  # FetchRequest (KRaft.tla:616-621)
            ("mlastFetchedEpoch", tb),
            ("mhwm", lb),
            ("nentries", 1),  # <=1 entry per response (KRaft.tla:710-712)
            ("eepoch", tb),
            ("evalue", vb),
            ("mdivergingEpoch", tb),  # Diverging response (KRaft.tla:671-672)
            ("mdivergingEndOffset", lb),
            ("cepoch", tb),  # correlation = embedded request (KRaft.tla:649);
            ("cfetchOffset", lb),  # its source/dest are implied (swapped)
            ("clastFetchedEpoch", tb),
        ]
    )


def cached_model(params: "KRaftParams") -> "KRaftModel":
    return _cached_model(params)


class KRaftModel(SparseExpandMixin, ActionLabelMixin):
    """Vectorized successor/invariant kernels for one (spec, constants) pair."""

    name = "KRaft"
    ACTION_NAMES = ACTION_NAMES
    # symmetry: mleader is a nil-valued server field inside packed records
    msg_server_fields = ("msource", "mdest")
    msg_server_nil_fields = ("mleader",)

    def __init__(self, params: KRaftParams, server_names=None, value_names=None):
        self.p = params
        self.layout = _build_layout(params)
        self.packer = _build_packer(params)
        S, V, M = params.n_servers, params.n_values, params.msg_slots
        self.server_names = list(server_names or [f"s{i+1}" for i in range(S)])
        self.value_names = list(value_names or [f"v{i+1}" for i in range(V)])

        # Candidate table: non-receipt disjuncts in Next order
        # (KRaft.tla:823-840), receipt disjuncts fused per slot at the end
        # (mutually exclusive per record; rank resolved dynamically).
        self.bindings: list[tuple[str, tuple]] = []
        self._pairs = [(i, j) for i in range(S) for j in range(S) if i != j]
        for i in range(S):
            self.bindings.append(("Restart", (i,)))
        for i in range(S):
            self.bindings.append(("RequestVote", (i,)))
        for i in range(S):
            self.bindings.append(("BecomeLeader", (i,)))
        for i in range(S):
            for v in range(V):
                self.bindings.append(("ClientRequest", (i, v)))
        for ij in self._pairs:
            self.bindings.append(("SendFetchRequest", ij))
        for m in range(M):
            self.bindings.append(("HandleMessage", (m,)))
        self.A = len(self.bindings)

        self.expand = jax.jit(jax.vmap(self._expand1))
        self.invariants = {
            "MessagesAreValid": jax.jit(
                messages_are_valid_kernel(self.layout, self.packer)
            ),
            "NoIllegalState": jax.jit(self._inv_no_illegal),
            "NoLogDivergence": jax.jit(self._inv_no_log_divergence),
            "NeverTwoLeadersInSameEpoch": jax.jit(self._inv_never_two_leaders),
            "LeaderHasAllAckedValues": jax.jit(self._inv_leader_has_acked),
            "CommittedEntriesReachMajority": jax.jit(self._inv_committed_majority),
            "TestInv": jax.jit(lambda s: jnp.ones(s.shape[:-1], dtype=bool)),
        }
        # ValuesNotStuck == \A v : []<> ValueAllOrNothing(v)
        # (KRaft.tla:867-879; same shape as core Raft's, checker/liveness.py)
        self.liveness = {
            "ValuesNotStuck": [
                (self.value_names[v], None,
                 jax.jit(partial(self._live_value_all_or_nothing, v)))
                for v in range(V)
            ],
        }

    # ---------------- field access helpers ----------------

    def _dec(self, s):
        g = self.layout.get
        return {f: g(s, f) for f in self.layout.fields}

    def _asm(self, d, **updates):
        parts = []
        for name, f in self.layout.fields.items():
            arr = updates.get(name, d[name])
            arr = jnp.asarray(arr, jnp.int32)
            parts.append(arr.reshape(-1) if f.shape else arr.reshape(1))
        return jnp.concatenate(parts)

    def _pack(self, **vals):
        hi, lo = self.packer.pack(**vals)
        return jnp.asarray(hi, jnp.int32), jnp.asarray(lo, jnp.int32)

    @staticmethod
    def _last_epoch(d, i):
        """LastEpoch(log[i]) — KRaft.tla:165."""
        ll = d["log_len"][i]
        return jnp.where(ll > 0, d["log_epoch"][i][jnp.clip(ll - 1, 0)], 0)

    # ---------------- transition machine (KRaft.tla:312-392) ----------------
    # All helpers take/return (state, epoch, leader_enc) int32 triples with
    # leader_enc in 0..S (0 = Nil).

    def _maybe_transition(self, d, i, leader_enc, epoch):
        """MaybeTransition — KRaft.tla:351-367."""
        st_i = d["state"][i]
        cur = d["currentEpoch"][i]
        led = d["leader"][i]
        # HasConsistentLeader (KRaft.tla:316-327)
        hcl = jnp.where(
            leader_enc == i + 1,
            st_i == LEADER,
            (epoch != cur) | (leader_enc == NIL) | (led == NIL) | (led == leader_enc),
        )
        # TransitionToFollower (KRaft.tla:344-349)
        tf_ill = (cur == epoch) & ((st_i == FOLLOWER) | (st_i == LEADER))
        tf = (
            jnp.where(tf_ill, ILLEGAL, FOLLOWER),
            jnp.where(tf_ill, 0, epoch),
            jnp.where(tf_ill, 0, leader_enc),
        )
        una = (jnp.int32(UNATTACHED), epoch, jnp.int32(NIL))
        noop = (st_i, cur, led)
        # CASE chain, first match wins
        c1 = ~hcl
        c2 = epoch > cur
        c2_pick = jnp.where(leader_enc == NIL, 1, 2)  # 1=unattached, 2=follower
        c3 = (leader_enc != NIL) & (led == NIL)
        sel = jnp.where(
            c1, 0, jnp.where(c2, c2_pick, jnp.where(c3, 2, 3))
        )  # 0=illegal,1=unattached,2=follower,3=noop
        out = []
        ill = (jnp.int32(ILLEGAL), jnp.int32(0), jnp.int32(NIL))
        for k in range(3):
            out.append(
                jnp.where(
                    sel == 0,
                    ill[k],
                    jnp.where(sel == 1, una[k], jnp.where(sel == 2, tf[k], noop[k])),
                )
            )
        return tuple(out)

    def _maybe_handle_common(self, d, i, leader_enc, epoch, err):
        """MaybeHandleCommonResponse — KRaft.tla:369-392.
        Returns (state, epoch, leader_enc, handled)."""
        st_i = d["state"][i]
        cur = d["currentEpoch"][i]
        led = d["leader"][i]
        mt = self._maybe_transition(d, i, leader_enc, epoch)
        c_stale = epoch < cur
        c_trans = (epoch > cur) | (err != E_NONE)
        c_follow = (epoch == cur) & (leader_enc != NIL) & (led == NIL)
        sel = jnp.where(
            c_stale, 0, jnp.where(c_trans, 1, jnp.where(c_follow, 2, 3))
        )
        fol = (jnp.int32(FOLLOWER), cur, leader_enc)
        noop = (st_i, cur, led)
        out = []
        for k in range(3):
            out.append(
                jnp.where(
                    sel == 0,
                    noop[k],
                    jnp.where(sel == 1, mt[k], jnp.where(sel == 2, fol[k], noop[k])),
                )
            )
        handled = sel != 3
        return out[0], out[1], out[2], handled

    # ---------------- log-position math (KRaft.tla:247-310) ----------------

    def _end_offset_for_epoch(self, d, i, last_fetched_epoch):
        """EndOffsetForEpoch — KRaft.tla:285-301: (offset, epoch) of the
        highest entry with epoch <= last_fetched_epoch; (0,0) if none."""
        L = self.p.max_log
        lanes = jnp.arange(L, dtype=jnp.int32)
        row = d["log_epoch"][i]
        mask = (lanes < d["log_len"][i]) & (row <= last_fetched_epoch)
        off = jnp.max(jnp.where(mask, lanes + 1, 0))
        ep = jnp.where(off > 0, row[jnp.clip(off - 1, 0)], 0)
        return off, ep

    def _highest_common_offset(self, d, i, end_off, epoch):
        """HighestCommonOffset — KRaft.tla:255-273: highest offset with
        CompareEntries(offset, entry.epoch, end_off, epoch) <= 0."""
        L = self.p.max_log
        lanes = jnp.arange(L, dtype=jnp.int32)
        row = d["log_epoch"][i]
        le = (row < epoch) | ((row == epoch) & (lanes + 1 <= end_off))
        mask = (lanes < d["log_len"][i]) & le
        return jnp.max(jnp.where(mask, lanes + 1, 0))

    def _valid_fetch_position(self, d, i, fetch_off, last_fetched_epoch):
        """ValidFetchPosition — KRaft.tla:305-310."""
        off, ep = self._end_offset_for_epoch(d, i, last_fetched_epoch)
        zero = (fetch_off == 0) & (last_fetched_epoch == 0)
        return zero | ((fetch_off <= off) & (last_fetched_epoch == ep))

    # ---------------- action kernels ----------------

    def _restart(self, s, i):
        """Restart(i) — KRaft.tla:423-432: keeps currentEpoch, votedFor,
        log; loses leader belief, votes, endOffset, hwm, pendingFetch."""
        p, S = self.p, self.p.n_servers
        d = self._dec(s)
        valid = d["restartCtr"] < p.max_restarts
        succ = self._asm(
            d,
            state=d["state"].at[i].set(FOLLOWER),
            leader=d["leader"].at[i].set(NIL),
            votesGranted=d["votesGranted"].at[i].set(0),
            endOffset=d["endOffset"].at[i].set(jnp.zeros((S,), jnp.int32)),
            highWatermark=d["highWatermark"].at[i].set(0),
            pf_epoch=d["pf_epoch"].at[i].set(0),
            pf_offset=d["pf_offset"].at[i].set(0),
            pf_lastepoch=d["pf_lastepoch"].at[i].set(0),
            pf_dest=d["pf_dest"].at[i].set(0),
            restartCtr=d["restartCtr"] + 1,
        )
        return valid, succ, jnp.int32(K_RESTART), jnp.asarray(False)

    def _request_vote(self, s, i):
        """RequestVote(i) — KRaft.tla:439-456 (fused Timeout+RequestVote;
        enabled from Follower, Candidate or Unattached)."""
        p, S = self.p, self.p.n_servers
        d = self._dec(s)
        st_i = d["state"][i]
        valid = (d["electionCtr"] < p.max_elections) & (
            (st_i == FOLLOWER) | (st_i == CANDIDATE) | (st_i == UNATTACHED)
        )
        new_epoch = d["currentEpoch"][i] + 1
        last_ep = self._last_epoch(d, i)
        ll_i = d["log_len"][i]
        hi, lo, cnt = d["msg_hi"], d["msg_lo"], d["msg_cnt"]
        ovf = jnp.asarray(False)
        for delta in range(1, S):
            j = jnp.mod(i + delta, S)
            khi, klo = self._pack(
                mtype=RVREQ,
                mepoch=new_epoch,
                mlastLogEpoch=last_ep,
                mlastLogOffset=ll_i,
                msource=i,
                mdest=j,
            )
            hi, lo, cnt, existed, o = bag.bag_put(hi, lo, cnt, khi, klo)
            valid &= ~existed  # SendMultipleOnce (KRaft.tla:199-201)
            ovf |= o
        succ = self._asm(
            d,
            state=d["state"].at[i].set(CANDIDATE),
            currentEpoch=d["currentEpoch"].at[i].set(new_epoch),
            leader=d["leader"].at[i].set(NIL),
            votedFor=d["votedFor"].at[i].set(i + 1),
            votesGranted=d["votesGranted"].at[i].set(jnp.int32(1) << i),
            pf_epoch=d["pf_epoch"].at[i].set(0),
            pf_offset=d["pf_offset"].at[i].set(0),
            pf_lastepoch=d["pf_lastepoch"].at[i].set(0),
            pf_dest=d["pf_dest"].at[i].set(0),
            electionCtr=d["electionCtr"] + 1,
            msg_hi=hi,
            msg_lo=lo,
            msg_cnt=cnt,
        )
        return valid, succ, jnp.int32(K_REQUESTVOTE), ovf & valid

    def _become_leader(self, s, i):
        """BecomeLeader(i) — KRaft.tla:546-558."""
        S = self.p.n_servers
        d = self._dec(s)
        votes = jnp.sum((d["votesGranted"][i] >> jnp.arange(S, dtype=jnp.int32)) & 1)
        valid = (d["state"][i] == CANDIDATE) & (2 * votes > S)
        hi, lo, cnt = d["msg_hi"], d["msg_lo"], d["msg_cnt"]
        ovf = jnp.asarray(False)
        for delta in range(1, S):
            j = jnp.mod(i + delta, S)
            khi, klo = self._pack(
                mtype=BQREQ, mepoch=d["currentEpoch"][i], msource=i, mdest=j
            )
            hi, lo, cnt, existed, o = bag.bag_put(hi, lo, cnt, khi, klo)
            valid &= ~existed  # SendMultipleOnce
            ovf |= o
        succ = self._asm(
            d,
            state=d["state"].at[i].set(LEADER),
            leader=d["leader"].at[i].set(i + 1),
            endOffset=d["endOffset"].at[i].set(jnp.zeros((S,), jnp.int32)),
            msg_hi=hi,
            msg_lo=lo,
            msg_cnt=cnt,
        )
        return valid, succ, jnp.int32(K_BECOMELEADER), ovf & valid

    def _client_request(self, s, i, v):
        """ClientRequest(i, v) — KRaft.tla:594-603."""
        L = self.p.max_log
        d = self._dec(s)
        valid = (d["state"][i] == LEADER) & (d["acked"][v] == ACK_NIL)
        pos = d["log_len"][i]
        ovf = valid & (pos >= L)
        posc = jnp.clip(pos, 0, L - 1)
        succ = self._asm(
            d,
            log_epoch=d["log_epoch"].at[i, posc].set(d["currentEpoch"][i]),
            log_value=d["log_value"].at[i, posc].set(v + 1),
            log_len=d["log_len"].at[i].add(1),
            acked=d["acked"].at[v].set(ACK_FALSE),
        )
        return valid, succ, jnp.int32(K_CLIENTREQUEST), ovf

    def _send_fetch_request(self, s, i, j):
        """SendFetchRequest(i, j) — KRaft.tla:607-624. FetchRequest is an
        unrestricted send (KRaft.tla:190-194); the pendingFetch[i] = Nil
        gate provides the flow control."""
        d = self._dec(s)
        valid = (
            (d["state"][i] == FOLLOWER)
            & (d["leader"][i] == j + 1)
            & (d["pf_epoch"][i] == 0)
        )
        ll_i = d["log_len"][i]
        last_ep = self._last_epoch(d, i)
        khi, klo = self._pack(
            mtype=FETCHREQ,
            mepoch=d["currentEpoch"][i],
            mfetchOffset=ll_i,
            mlastFetchedEpoch=last_ep,
            msource=i,
            mdest=j,
        )
        hi, lo, cnt, _existed, ovf = bag.bag_put(
            d["msg_hi"], d["msg_lo"], d["msg_cnt"], khi, klo
        )
        succ = self._asm(
            d,
            pf_epoch=d["pf_epoch"].at[i].set(d["currentEpoch"][i]),
            pf_offset=d["pf_offset"].at[i].set(ll_i),
            pf_lastepoch=d["pf_lastepoch"].at[i].set(last_ep),
            pf_dest=d["pf_dest"].at[i].set(j + 1),
            msg_hi=hi,
            msg_lo=lo,
            msg_cnt=cnt,
        )
        return valid, succ, jnp.int32(K_SENDFETCH), ovf & valid

    # -------- fused message-receipt kernel (slot m) --------
    # The nine receipt disjuncts of Next (KRaft.tla:827-840) are mutually
    # exclusive for a fixed record (they partition on mtype, then on
    # error/validity/mresult), so one kernel per slot computes whichever
    # fires; `rank` reports which for trace labels.

    def _handle_message(self, s, m):
        p, packer = self.p, self.packer
        S, L = p.n_servers, p.max_log
        d = self._dec(s)
        hi, lo, cnt = d["msg_hi"], d["msg_lo"], d["msg_cnt"]
        khi, klo, kcnt = hi[m], lo[m], cnt[m]
        occupied = khi != EMPTY
        u = partial(packer.unpack, khi, klo)
        mtype, mepoch = u("mtype"), u("mepoch")
        src, dst = u("msource"), u("mdest")
        cur = d["currentEpoch"][dst]
        st_dst = d["state"][dst]
        led_dst = d["leader"][dst]
        recv = occupied & (kcnt > 0)  # ReceivableMessage (KRaft.tla:230-235)
        equal_epoch = mepoch == cur

        def reply(resp_hi, resp_lo):
            """Reply — KRaft.tla:220-227; caller enforces the FetchResponse
            no-duplicate rule via the returned `existed`."""
            c2 = bag.bag_discard_at(cnt, m)
            return bag.bag_put(hi, lo, c2, resp_hi, resp_lo)

        def clear_pf(upd):
            upd["pf_epoch"] = d["pf_epoch"].at[dst].set(0)
            upd["pf_offset"] = d["pf_offset"].at[dst].set(0)
            upd["pf_lastepoch"] = d["pf_lastepoch"].at[dst].set(0)
            upd["pf_dest"] = d["pf_dest"].at[dst].set(0)
            return upd

        # --- HandleRequestVoteRequest (KRaft.tla:464-513)
        b_rvreq = recv & (mtype == RVREQ)
        rv_err = mepoch < cur  # FencedLeaderEpoch
        # state0 (KRaft.tla:472-474)
        s0_st = jnp.where(mepoch > cur, UNATTACHED, st_dst)
        s0_ep = jnp.where(mepoch > cur, mepoch, cur)
        s0_ld = jnp.where(mepoch > cur, NIL, led_dst)
        last_ep = self._last_epoch(d, dst)
        ll_dst = d["log_len"][dst]
        # logOk: CompareEntries(mllo, mlle, Len, LastEpoch) >= 0 (:475-478)
        log_ok = (u("mlastLogEpoch") > last_ep) | (
            (u("mlastLogEpoch") == last_ep) & (u("mlastLogOffset") >= ll_dst)
        )
        grant = (
            (s0_st == UNATTACHED) | ((s0_st == VOTED) & (d["votedFor"][dst] == src + 1))
        ) & log_ok
        # finalState: TransitionToVoted when grant from Unattached (:483-485);
        # the Unattached precondition makes the illegal arm unreachable.
        take_voted = grant & (s0_st == UNATTACHED)
        f_st = jnp.where(take_voted, VOTED, s0_st)
        f_ep = jnp.where(take_voted, mepoch, s0_ep)
        f_ld = jnp.where(take_voted, NIL, s0_ld)
        # error path replies with (cur, leader[i]); normal with (mepoch, final)
        r_ep = jnp.where(rv_err, cur, mepoch)
        r_ld = jnp.where(rv_err, led_dst, f_ld)
        r_grant = jnp.where(rv_err, 0, grant.astype(jnp.int32))
        r_err = jnp.where(rv_err, E_FENCED, E_NONE)
        rhi, rlo = self._pack(
            mtype=RVRESP,
            mepoch=r_ep,
            mleader=r_ld,
            mvoteGranted=r_grant,
            merror=r_err,
            msource=dst,
            mdest=src,
        )
        hi1, lo1, cnt1, _ex1, ovf1 = reply(rhi, rlo)
        upd1 = dict(msg_hi=hi1, msg_lo=lo1, msg_cnt=cnt1)
        no_err = ~rv_err
        upd1["state"] = jnp.where(no_err, d["state"].at[dst].set(f_st), d["state"])
        upd1["currentEpoch"] = jnp.where(
            no_err, d["currentEpoch"].at[dst].set(f_ep), d["currentEpoch"]
        )
        upd1["leader"] = jnp.where(no_err, d["leader"].at[dst].set(f_ld), d["leader"])
        upd1["votedFor"] = jnp.where(
            no_err & grant, d["votedFor"].at[dst].set(src + 1), d["votedFor"]
        )
        # IF state # state' THEN reset pendingFetch (KRaft.tla:495-497)
        pf_reset = no_err & (f_st != st_dst)
        for pf in ("pf_epoch", "pf_offset", "pf_lastepoch", "pf_dest"):
            upd1[pf] = jnp.where(pf_reset, d[pf].at[dst].set(0), d[pf])
        s_rvreq = self._asm(d, **upd1)

        # --- HandleRequestVoteResponse (KRaft.tla:519-541)
        mh_st, mh_ep, mh_ld, handled = self._maybe_handle_common(
            d, dst, u("mleader"), mepoch, u("merror")
        )
        b_rvresp = recv & (mtype == RVRESP) & (handled | (st_dst == CANDIDATE))
        cnt_disc = bag.bag_discard_at(cnt, m)
        granted_bit = (u("mvoteGranted") > 0) & ~handled
        upd2 = dict(
            state=jnp.where(handled, d["state"].at[dst].set(mh_st), d["state"]),
            currentEpoch=jnp.where(
                handled, d["currentEpoch"].at[dst].set(mh_ep), d["currentEpoch"]
            ),
            leader=jnp.where(handled, d["leader"].at[dst].set(mh_ld), d["leader"]),
            votesGranted=jnp.where(
                granted_bit,
                d["votesGranted"].at[dst].set(d["votesGranted"][dst] | (jnp.int32(1) << src)),
                d["votesGranted"],
            ),
            msg_cnt=cnt_disc,
        )
        s_rvresp = self._asm(d, **upd2)

        # --- HandleBeginQuorumRequest (KRaft.tla:563-590)
        b_bqreq = recv & (mtype == BQREQ)
        bq_err = mepoch < cur
        bt_st, bt_ep, bt_ld = self._maybe_transition(d, dst, src + 1, mepoch)
        bq_rep = jnp.where(bq_err, cur, mepoch)
        bq_rerr = jnp.where(bq_err, E_FENCED, E_NONE)
        bhi, blo = self._pack(
            mtype=BQRESP, mepoch=bq_rep, msource=dst, mdest=src, merror=bq_rerr
        )
        hi3, lo3, cnt3, _ex3, ovf3 = reply(bhi, blo)
        upd3 = dict(msg_hi=hi3, msg_lo=lo3, msg_cnt=cnt3)
        ok3 = ~bq_err
        upd3["state"] = jnp.where(ok3, d["state"].at[dst].set(bt_st), d["state"])
        upd3["currentEpoch"] = jnp.where(
            ok3, d["currentEpoch"].at[dst].set(bt_ep), d["currentEpoch"]
        )
        upd3["leader"] = jnp.where(ok3, d["leader"].at[dst].set(bt_ld), d["leader"])
        for pf in ("pf_epoch", "pf_offset", "pf_lastepoch", "pf_dest"):
            upd3[pf] = jnp.where(ok3, d[pf].at[dst].set(0), d[pf])
        s_bqreq = self._asm(d, **upd3)

        # --- FetchRequest branches (KRaft.tla:631-736)
        is_fetchreq = recv & (mtype == FETCHREQ)
        is_leader = st_dst == LEADER
        ferr = jnp.where(
            ~is_leader,
            E_NOTLEADER,
            jnp.where(
                mepoch < cur, E_FENCED, jnp.where(mepoch > cur, E_UNKNOWN, E_NONE)
            ),
        )
        foff = u("mfetchOffset")
        flep = u("mlastFetchedEpoch")
        valid_pos = self._valid_fetch_position(d, dst, foff, flep)
        eo_off, eo_ep = self._end_offset_for_epoch(d, dst, flep)

        # RejectFetchRequest (KRaft.tla:631-651)
        b_reject = is_fetchreq & (ferr != E_NONE)
        rjhi, rjlo = self._pack(
            mtype=FETCHRESP,
            mresult=R_NOTOK,
            merror=ferr,
            mleader=led_dst,
            mepoch=cur,
            mhwm=d["highWatermark"][dst],
            msource=dst,
            mdest=src,
            cepoch=mepoch,
            cfetchOffset=foff,
            clastFetchedEpoch=flep,
        )
        hi4, lo4, cnt4, ex4, ovf4 = reply(rjhi, rjlo)
        b_reject &= ~ex4  # FetchResponse no-duplicate rule (KRaft.tla:224-227)
        s_reject = self._asm(d, msg_hi=hi4, msg_lo=lo4, msg_cnt=cnt4)

        # DivergingFetchRequest (KRaft.tla:658-679)
        b_div = is_fetchreq & equal_epoch & is_leader & ~valid_pos
        dvhi, dvlo = self._pack(
            mtype=FETCHRESP,
            mepoch=cur,
            mresult=R_DIVERGING,
            merror=E_NONE,
            mdivergingEpoch=eo_ep,
            mdivergingEndOffset=eo_off,
            mleader=led_dst,
            mhwm=d["highWatermark"][dst],
            msource=dst,
            mdest=src,
            cepoch=mepoch,
            cfetchOffset=foff,
            clastFetchedEpoch=flep,
        )
        hi5, lo5, cnt5, ex5, ovf5 = reply(dvhi, dvlo)
        b_div &= ~ex5
        s_div = self._asm(d, msg_hi=hi5, msg_lo=lo5, msg_cnt=cnt5)

        # AcceptFetchRequest (KRaft.tla:703-736)
        b_accept = is_fetchreq & equal_epoch & is_leader & valid_pos
        offset = foff + 1
        have_entry = offset <= d["log_len"][dst]
        epos = jnp.clip(offset - 1, 0, L - 1)
        ent_ep = jnp.where(have_entry, d["log_epoch"][dst][epos], 0)
        ent_v = jnp.where(have_entry, d["log_value"][dst][epos], 0)
        new_end = d["endOffset"][dst].at[src].set(foff)
        # NewHighwaterMark (KRaft.tla:689-701)
        idxs = jnp.arange(1, L + 1, dtype=jnp.int32)
        self_in = jnp.arange(S, dtype=jnp.int32)[None, :] == dst
        agree = self_in | (new_end[None, :] >= idxs[:, None])
        quorum_ok = 2 * jnp.sum(agree, axis=1) > S
        in_log = idxs <= d["log_len"][dst]
        max_agree = jnp.max(jnp.where(quorum_ok & in_log, idxs, 0))
        ep_at = d["log_epoch"][dst][jnp.clip(max_agree - 1, 0)]
        hwm_old = d["highWatermark"][dst]
        new_hwm = jnp.where(
            (max_agree > 0) & (ep_at == cur), max_agree, hwm_old
        )
        # acked: FALSE -> committed in (hwm_old, new_hwm] (KRaft.tla:721-724)
        lanes = jnp.arange(L, dtype=jnp.int32)
        in_range = (lanes + 1 > hwm_old) & (lanes + 1 <= new_hwm)
        vals_row = d["log_value"][dst]
        committed = jnp.any(
            in_range[None, :]
            & (vals_row[None, :] == jnp.arange(1, p.n_values + 1, dtype=jnp.int32)[:, None]),
            axis=1,
        )
        acked = jnp.where(
            (d["acked"] == ACK_FALSE) & committed, ACK_TRUE, d["acked"]
        )
        achi, aclo = self._pack(
            mtype=FETCHRESP,
            mepoch=cur,
            mleader=led_dst,
            mresult=R_OK,
            merror=E_NONE,
            nentries=have_entry.astype(jnp.int32),
            eepoch=ent_ep,
            evalue=ent_v,
            mhwm=jnp.minimum(new_hwm, offset),
            msource=dst,
            mdest=src,
            cepoch=mepoch,
            cfetchOffset=foff,
            clastFetchedEpoch=flep,
        )
        hi6, lo6, cnt6, ex6, ovf6 = reply(achi, aclo)
        b_accept &= ~ex6
        s_accept = self._asm(
            d,
            endOffset=d["endOffset"].at[dst].set(new_end),
            highWatermark=d["highWatermark"].at[dst].set(new_hwm),
            acked=acked,
            msg_hi=hi6,
            msg_lo=lo6,
            msg_cnt=cnt6,
        )

        # --- FetchResponse branches (KRaft.tla:742-801)
        is_fresp = recv & (mtype == FETCHRESP)
        # correlation match: pendingFetch[dst] = m.correlation (:749); the
        # request's msource is dst (implied) and mdest is the responder src.
        corr = (
            (d["pf_epoch"][dst] > 0)
            & (d["pf_epoch"][dst] == u("cepoch"))
            & (d["pf_offset"][dst] == u("cfetchOffset"))
            & (d["pf_lastepoch"][dst] == u("clastFetchedEpoch"))
            & (d["pf_dest"][dst] == src + 1)
        )
        mres = u("mresult")

        # HandleSuccessFetchResponse (KRaft.tla:742-757)
        b_ok = is_fresp & ~handled & corr & (mres == R_OK)
        app = u("nentries") > 0
        ll_dst2 = d["log_len"][dst]
        apos = jnp.clip(ll_dst2, 0, L - 1)
        ok_ovf = b_ok & app & (ll_dst2 >= L)
        upd7 = dict(
            highWatermark=d["highWatermark"].at[dst].set(u("mhwm")),
            log_epoch=jnp.where(
                app, d["log_epoch"].at[dst, apos].set(u("eepoch")), d["log_epoch"]
            ),
            log_value=jnp.where(
                app, d["log_value"].at[dst, apos].set(u("evalue")), d["log_value"]
            ),
            log_len=jnp.where(app, d["log_len"].at[dst].add(1), d["log_len"]),
            msg_cnt=cnt_disc,
        )
        s_ok = self._asm(d, **clear_pf(upd7))

        # HandleDivergingFetchResponse (KRaft.tla:766-780)
        b_divr = is_fresp & ~handled & corr & (mres == R_DIVERGING)
        hco = self._highest_common_offset(
            d, dst, u("mdivergingEndOffset"), u("mdivergingEpoch")
        )
        keep = jnp.arange(L, dtype=jnp.int32) < hco
        upd8 = dict(
            log_epoch=d["log_epoch"].at[dst].set(
                jnp.where(keep, d["log_epoch"][dst], 0)
            ),
            log_value=d["log_value"].at[dst].set(
                jnp.where(keep, d["log_value"][dst], 0)
            ),
            log_len=d["log_len"].at[dst].set(hco),
            msg_cnt=cnt_disc,
        )
        s_divr = self._asm(d, **clear_pf(upd8))

        # HandleErrorFetchResponse (KRaft.tla:786-801)
        b_err = is_fresp & handled & corr
        upd9 = dict(
            state=d["state"].at[dst].set(mh_st),
            currentEpoch=d["currentEpoch"].at[dst].set(mh_ep),
            leader=d["leader"].at[dst].set(mh_ld),
            msg_cnt=cnt_disc,
        )
        s_err = self._asm(d, **clear_pf(upd9))

        branches = [
            (b_rvreq, s_rvreq, K_HANDLE_RVREQ, ovf1),
            (b_rvresp, s_rvresp, K_HANDLE_RVRESP, jnp.asarray(False)),
            (b_reject, s_reject, K_REJECT_FETCH, ovf4),
            (b_div, s_div, K_DIVERGING_FETCH, ovf5),
            (b_accept, s_accept, K_ACCEPT_FETCH, ovf6),
            (b_bqreq, s_bqreq, K_HANDLE_BQREQ, ovf3),
            (b_ok, s_ok, K_HANDLE_FETCH_OK, ok_ovf),
            (b_divr, s_divr, K_HANDLE_FETCH_DIV, jnp.asarray(False)),
            (b_err, s_err, K_HANDLE_FETCH_ERR, jnp.asarray(False)),
        ]
        valid = jnp.asarray(False)
        succ = s
        rank = jnp.int32(-1)
        ovf = jnp.asarray(False)
        for b, sb, rk, ob in branches:
            valid = valid | b
            succ = jnp.where(b, sb, succ)
            rank = jnp.where(b, jnp.int32(rk), rank)
            ovf = ovf | (b & ob)
        return valid, succ, rank, ovf

    # ---------------- full expansion ----------------

    def _expand1(self, s):
        """All successor candidates of one state.

        Returns (succs [A, W], valid [A], rank [A], ovf [A])."""
        p = self.p
        S, V, M = p.n_servers, p.n_values, p.msg_slots
        iota_s = jnp.arange(S, dtype=jnp.int32)
        pr_i = jnp.asarray([ij[0] for ij in self._pairs], jnp.int32)
        pr_j = jnp.asarray([ij[1] for ij in self._pairs], jnp.int32)
        outs = []
        outs.append(jax.vmap(lambda i: self._restart(s, i))(iota_s))
        outs.append(jax.vmap(lambda i: self._request_vote(s, i))(iota_s))
        outs.append(jax.vmap(lambda i: self._become_leader(s, i))(iota_s))
        cr_i = jnp.repeat(iota_s, V)
        cr_v = jnp.tile(jnp.arange(V, dtype=jnp.int32), S)
        outs.append(jax.vmap(lambda i, v: self._client_request(s, i, v))(cr_i, cr_v))
        outs.append(
            jax.vmap(lambda i, j: self._send_fetch_request(s, i, j))(pr_i, pr_j)
        )
        outs.append(
            jax.vmap(lambda m: self._handle_message(s, m))(jnp.arange(M, dtype=jnp.int32))
        )
        valid = jnp.concatenate([o[0] for o in outs])
        succs = jnp.concatenate([o[1] for o in outs])
        rank = jnp.concatenate([o[2] for o in outs])
        ovf = jnp.concatenate([o[3] for o in outs])
        return succs, valid, rank, ovf

    # ---------------- initial states ----------------

    def init_states(self) -> np.ndarray:
        """Init — KRaft.tla:397-415. A single state; all Unattached."""
        vec = self.layout.zeros((1,))
        lay = self.layout
        vec[0, lay.sl("currentEpoch")] = 1
        vec[0, lay.sl("state")] = UNATTACHED
        vec[0, lay.sl("msg_hi")] = int(EMPTY)
        vec[0, lay.sl("msg_lo")] = int(EMPTY)
        vec[0, lay.sl("acked")] = ACK_NIL
        return vec

    # ---------------- invariants ----------------

    def _live_value_all_or_nothing(self, v, states):
        """ValueAllOrNothing(v) — KRaft.tla:867-875: TRUE when the last
        permissible election failed with no leader, else v must be on
        EVERY server log or on NONE."""
        lay, L = self.layout, self.p.max_log
        ec = lay.get(states, "electionCtr")
        st = lay.get(states, "state")
        lv = lay.get(states, "log_value")
        ll = lay.get(states, "log_len")
        lanes = jnp.arange(L, dtype=jnp.int32)
        in_log = lanes[None, None, :] < ll[..., None]
        has_v = jnp.any(in_log & (lv == v + 1), axis=2)
        all_have = jnp.all(has_v, axis=1)
        none_have = ~jnp.any(has_v, axis=1)
        no_leader = ~jnp.any(st == LEADER, axis=1)
        spent = ec == self.p.max_elections
        return (spent & no_leader) | all_have | none_have

    def _inv_no_illegal(self, states):
        """NoIllegalState — KRaft.tla:887-889."""
        st = self.layout.get(states, "state")
        return jnp.all(st != ILLEGAL, axis=1)

    def _inv_no_log_divergence(self, states):
        """NoLogDivergence — KRaft.tla:894-907 (common prefix up to the
        pairwise-minimum highWatermark)."""
        lay, L = self.layout, self.p.max_log
        hwm = lay.get(states, "highWatermark")
        lt = lay.get(states, "log_epoch")
        lv = lay.get(states, "log_value")
        mh = jnp.minimum(hwm[:, :, None], hwm[:, None, :])
        lanes = jnp.arange(1, L + 1, dtype=jnp.int32)
        in_common = lanes[None, None, None, :] <= mh[..., None]
        eq = (lt[:, :, None, :] == lt[:, None, :, :]) & (
            lv[:, :, None, :] == lv[:, None, :, :]
        )
        return jnp.all(~in_common | eq, axis=(1, 2, 3))

    def _inv_never_two_leaders(self, states):
        """NeverTwoLeadersInSameEpoch — KRaft.tla:916-921."""
        lay = self.layout
        led = lay.get(states, "leader")
        ep = lay.get(states, "currentEpoch")
        both = (led[:, :, None] != NIL) & (led[:, None, :] != NIL)
        conflict = (
            both
            & (led[:, :, None] != led[:, None, :])
            & (ep[:, :, None] == ep[:, None, :])
        )
        return ~jnp.any(conflict, axis=(1, 2))

    def _inv_leader_has_acked(self, states):
        """LeaderHasAllAckedValues — KRaft.tla:925-941."""
        lay, V = self.layout, self.p.n_values
        ep = lay.get(states, "currentEpoch")
        st = lay.get(states, "state")
        lv = lay.get(states, "log_value")
        acked = lay.get(states, "acked")
        not_stale = jnp.all(ep[:, :, None] >= ep[:, None, :], axis=2)
        is_lead = (st == LEADER) & not_stale
        vals = jnp.arange(1, V + 1, dtype=jnp.int32)
        has_v = jnp.any(lv[:, :, None, :] == vals[None, None, :, None], axis=3)
        bad = jnp.any(
            (acked[:, None, :] == ACK_TRUE) & is_lead[:, :, None] & ~has_v,
            axis=(1, 2),
        )
        return ~bad

    def _inv_committed_majority(self, states):
        """CommittedEntriesReachMajority — KRaft.tla:946-957."""
        lay, S, L = self.layout, self.p.n_servers, self.p.max_log
        st = lay.get(states, "state")
        hwm = lay.get(states, "highWatermark")
        ll = lay.get(states, "log_len")
        lt = lay.get(states, "log_epoch")
        lv = lay.get(states, "log_value")
        lead = (st == LEADER) & (hwm > 0)
        pos = jnp.clip(hwm - 1, 0, L - 1)
        lt_i = jnp.take_along_axis(lt, pos[:, :, None], axis=2)[:, :, 0]
        lv_i = jnp.take_along_axis(lv, pos[:, :, None], axis=2)[:, :, 0]
        posj = jnp.broadcast_to(pos[:, :, None], pos.shape + (S,))
        lt_j = jnp.take_along_axis(
            jnp.broadcast_to(lt[:, None, :, :], lt.shape[:1] + (S,) + lt.shape[1:]),
            posj[..., None],
            axis=3,
        )[..., 0]
        lv_j = jnp.take_along_axis(
            jnp.broadcast_to(lv[:, None, :, :], lv.shape[:1] + (S,) + lv.shape[1:]),
            posj[..., None],
            axis=3,
        )[..., 0]
        match = (
            (ll[:, None, :] >= hwm[:, :, None])
            & (lt_j == lt_i[..., None])
            & (lv_j == lv_i[..., None])
        )
        enough = jnp.sum(match, axis=2) >= (S // 2 + 1)
        ok_exists = jnp.any(lead & enough, axis=1)
        return ~jnp.any(lead, axis=1) | ok_exists

    # ---------------- host-side decode/encode ----------------

    def decode(self, vec: np.ndarray) -> dict:
        """Decode one packed state into the canonical python form shared
        with oracle/kraft_oracle.py."""
        lay, p = self.layout, self.p
        g = lambda n: np.asarray(vec[lay.sl(n)])
        S, L = p.n_servers, p.max_log
        lt = g("log_epoch").reshape(S, L)
        lv = g("log_value").reshape(S, L)
        ll = g("log_len")
        log = tuple(
            tuple((int(lt[i, k]), int(lv[i, k]) - 1) for k in range(int(ll[i])))
            for i in range(S)
        )
        vg = g("votesGranted")
        votes = tuple(
            frozenset(j for j in range(S) if (int(vg[i]) >> j) & 1) for i in range(S)
        )
        pf_ep, pf_off = g("pf_epoch"), g("pf_offset")
        pf_le, pf_d = g("pf_lastepoch"), g("pf_dest")
        pending = []
        for i in range(S):
            if int(pf_ep[i]) == 0:
                pending.append(None)
            else:
                pending.append(
                    tuple(
                        sorted(
                            {
                                "mtype": "FetchRequest",
                                "mepoch": int(pf_ep[i]),
                                "mfetchOffset": int(pf_off[i]),
                                "mlastFetchedEpoch": int(pf_le[i]),
                                "msource": i,
                                "mdest": int(pf_d[i]) - 1,
                            }.items()
                        )
                    )
                )
        msgs = {}
        hi, lo, cnt = g("msg_hi"), g("msg_lo"), g("msg_cnt")
        for k in range(p.msg_slots):
            if int(hi[k]) == int(EMPTY):
                continue
            msgs[self.decode_msg(int(hi[k]), int(lo[k]))] = int(cnt[k])
        return {
            "currentEpoch": tuple(int(x) for x in g("currentEpoch")),
            "state": tuple(int(x) for x in g("state")),
            "votedFor": tuple(int(x) - 1 if x > 0 else None for x in g("votedFor")),
            "leader": tuple(int(x) - 1 if x > 0 else None for x in g("leader")),
            "pendingFetch": tuple(pending),
            "votesGranted": votes,
            "endOffset": tuple(
                tuple(int(x) for x in row) for row in g("endOffset").reshape(S, S)
            ),
            "log": log,
            "highWatermark": tuple(int(x) for x in g("highWatermark")),
            "messages": frozenset(msgs.items()),
            "acked": tuple(
                {ACK_NIL: None, ACK_FALSE: False, ACK_TRUE: True}[int(x)]
                for x in g("acked")
            ),
            "electionCtr": int(vec[lay.fields["electionCtr"].offset]),
            "restartCtr": int(vec[lay.fields["restartCtr"].offset]),
        }

    def decode_msg(self, hi: int, lo: int) -> tuple:
        u = self.packer.unpack_all(hi, lo)
        mtype = int(u["mtype"])
        rec = {
            "mtype": MTYPE_NAMES[mtype],
            "mepoch": int(u["mepoch"]),
            "msource": int(u["msource"]),
            "mdest": int(u["mdest"]),
        }
        if mtype == RVREQ:
            rec["mlastLogEpoch"] = int(u["mlastLogEpoch"])
            rec["mlastLogOffset"] = int(u["mlastLogOffset"])
        elif mtype == RVRESP:
            rec["mleader"] = int(u["mleader"]) - 1 if u["mleader"] else None
            rec["mvoteGranted"] = bool(u["mvoteGranted"])
            rec["merror"] = ERROR_NAMES[int(u["merror"])]
        elif mtype == BQRESP:
            rec["merror"] = ERROR_NAMES[int(u["merror"])]
        elif mtype == FETCHREQ:
            rec["mfetchOffset"] = int(u["mfetchOffset"])
            rec["mlastFetchedEpoch"] = int(u["mlastFetchedEpoch"])
        elif mtype == FETCHRESP:
            res = int(u["mresult"])
            rec["mresult"] = RESULT_NAMES[res]
            rec["merror"] = ERROR_NAMES[int(u["merror"])]
            rec["mleader"] = int(u["mleader"]) - 1 if u["mleader"] else None
            rec["mhwm"] = int(u["mhwm"])
            if res == R_OK:
                rec["mentries"] = (
                    ((int(u["eepoch"]), int(u["evalue"]) - 1),)
                    if u["nentries"]
                    else ()
                )
            if res == R_DIVERGING:
                rec["mdivergingEpoch"] = int(u["mdivergingEpoch"])
                rec["mdivergingEndOffset"] = int(u["mdivergingEndOffset"])
            rec["correlation"] = tuple(
                sorted(
                    {
                        "mtype": "FetchRequest",
                        "mepoch": int(u["cepoch"]),
                        "mfetchOffset": int(u["cfetchOffset"]),
                        "mlastFetchedEpoch": int(u["clastFetchedEpoch"]),
                        "msource": int(u["mdest"]),
                        "mdest": int(u["msource"]),
                    }.items()
                )
            )
        return tuple(sorted(rec.items()))

    def encode_msg(self, rec: tuple) -> tuple[int, int]:
        d = dict(rec)
        inv_err = {v: k for k, v in ERROR_NAMES.items()}
        inv_res = {v: k for k, v in RESULT_NAMES.items()}
        mtype = {v: k for k, v in MTYPE_NAMES.items()}[d["mtype"]]
        kw = dict(
            mtype=mtype, mepoch=d["mepoch"], msource=d["msource"], mdest=d["mdest"]
        )
        if mtype == RVREQ:
            kw.update(
                mlastLogEpoch=d["mlastLogEpoch"], mlastLogOffset=d["mlastLogOffset"]
            )
        elif mtype == RVRESP:
            kw.update(
                mleader=0 if d["mleader"] is None else d["mleader"] + 1,
                mvoteGranted=int(d["mvoteGranted"]),
                merror=inv_err[d["merror"]],
            )
        elif mtype == BQRESP:
            kw.update(merror=inv_err[d["merror"]])
        elif mtype == FETCHREQ:
            kw.update(
                mfetchOffset=d["mfetchOffset"],
                mlastFetchedEpoch=d["mlastFetchedEpoch"],
            )
        elif mtype == FETCHRESP:
            corr = dict(d["correlation"])
            kw.update(
                mresult=inv_res[d["mresult"]],
                merror=inv_err[d["merror"]],
                mleader=0 if d["mleader"] is None else d["mleader"] + 1,
                mhwm=d["mhwm"],
                cepoch=corr["mepoch"],
                cfetchOffset=corr["mfetchOffset"],
                clastFetchedEpoch=corr["mlastFetchedEpoch"],
            )
            if d["mresult"] == "Ok":
                ent = d["mentries"]
                kw.update(
                    nentries=len(ent),
                    eepoch=ent[0][0] if ent else 0,
                    evalue=ent[0][1] + 1 if ent else 0,
                )
            if d["mresult"] == "Diverging":
                kw.update(
                    mdivergingEpoch=d["mdivergingEpoch"],
                    mdivergingEndOffset=d["mdivergingEndOffset"],
                )
        return self.packer.pack(**kw)

    def encode(self, st: dict) -> np.ndarray:
        lay, p = self.layout, self.p
        S, L = p.n_servers, p.max_log
        vec = lay.zeros(())
        vec[lay.sl("currentEpoch")] = st["currentEpoch"]
        vec[lay.sl("state")] = st["state"]
        vec[lay.sl("votedFor")] = [0 if v is None else v + 1 for v in st["votedFor"]]
        vec[lay.sl("leader")] = [0 if v is None else v + 1 for v in st["leader"]]
        pf_ep = [0] * S
        pf_off = [0] * S
        pf_le = [0] * S
        pf_d = [0] * S
        for i, pf in enumerate(st["pendingFetch"]):
            if pf is None:
                continue
            c = dict(pf)
            pf_ep[i] = c["mepoch"]
            pf_off[i] = c["mfetchOffset"]
            pf_le[i] = c["mlastFetchedEpoch"]
            pf_d[i] = c["mdest"] + 1
        vec[lay.sl("pf_epoch")] = pf_ep
        vec[lay.sl("pf_offset")] = pf_off
        vec[lay.sl("pf_lastepoch")] = pf_le
        vec[lay.sl("pf_dest")] = pf_d
        lt = np.zeros((S, L), np.int32)
        lv = np.zeros((S, L), np.int32)
        for i, lg in enumerate(st["log"]):
            for k, (t, v) in enumerate(lg):
                lt[i, k] = t
                lv[i, k] = v + 1
        vec[lay.sl("log_epoch")] = lt.reshape(-1)
        vec[lay.sl("log_value")] = lv.reshape(-1)
        vec[lay.sl("log_len")] = [len(lg) for lg in st["log"]]
        vec[lay.sl("highWatermark")] = st["highWatermark"]
        vec[lay.sl("votesGranted")] = [
            sum(1 << j for j in vs) for vs in st["votesGranted"]
        ]
        vec[lay.sl("endOffset")] = np.asarray(st["endOffset"]).reshape(-1)
        vec[lay.sl("acked")] = [
            {None: ACK_NIL, False: ACK_FALSE, True: ACK_TRUE}[a] for a in st["acked"]
        ]
        keys = sorted((self.encode_msg(rec), cnt) for rec, cnt in st["messages"])
        if len(keys) > p.msg_slots:
            raise OverflowError("message bag exceeds msg_slots")
        hi = np.full(p.msg_slots, int(EMPTY), np.int32)
        lo = np.full(p.msg_slots, int(EMPTY), np.int32)
        cn = np.zeros(p.msg_slots, np.int32)
        for k, ((h, l), c) in enumerate(keys):
            hi[k], lo[k], cn[k] = h, l, c
        vec[lay.sl("msg_hi")] = hi
        vec[lay.sl("msg_lo")] = lo
        vec[lay.sl("msg_cnt")] = cn
        vec[lay.fields["electionCtr"].offset] = st["electionCtr"]
        vec[lay.fields["restartCtr"].offset] = st["restartCtr"]
        return vec


@lru_cache(maxsize=None)
def _cached_model(params: KRaftParams) -> "KRaftModel":
    return KRaftModel(params)
