"""TPU lowering of the PullRaft / PullRaftVariant2 specs.

Reference: ``/root/reference/specifications/pull-raft/PullRaft.tla`` (631
lines) and ``PullRaftVariant2.tla`` (648 lines). Same lowering discipline as
models/raft.py: branchless ``vmap``-able action kernels over a packed int32
state vector, enabling conditions as masks, ``CHOOSE`` sites (Min/Max,
``PullRaft.tla:175-177``; ``LastCommonEntry``, ``:211-226``) as lane
reductions.

Variant-defining structure (see oracle/pull_oracle.py for the full delta
list): pull-based replication, ``leader`` belief var, strictly send-once
messaging for ALL messages (``PullRaft.tla:137-161``), and — in Variant2 —
``votedFor`` + ``votesLastEntry`` with last-common-entry piggybacking on
the LeaderNotify (``PullRaftVariant2.tla:361-379``).

Bound note: unlike core Raft, a follower's log can transiently exceed
|Value| entries (stale success PullEntriesResponses with distinct
``mcommitIndex`` each append; ``PullRaft.tla:493-503`` appends
unconditionally), so ``max_log`` is a parameter with headroom above
|Value| and overflow is a hard error, never silent.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import bag
from ..ops.packing import EMPTY, BitPacker, bits_for
from .base import (
    ActionLabelMixin,
    FleetConstMixin,
    Layout,
    SparseExpandMixin,
    messages_are_valid_kernel,
)

FOLLOWER, CANDIDATE, LEADER = 0, 1, 2
NIL = 0  # leader/votedFor Nil; server i stored as i+1
ACK_NIL, ACK_FALSE, ACK_TRUE = 0, 1, 2
RVREQ, RVRESP, PULLREQ, PULLRESP, NOTIFY = 1, 2, 3, 4, 5

# Next-disjunct order (PullRaft.tla:542-558 == PullRaftVariant2.tla:560-576).
(
    R_RESTART,
    R_UPDATETERM,
    R_REQUESTVOTE,
    R_HANDLE_RVREQ,
    R_HANDLE_RVRESP,
    R_BECOMELEADER,
    R_CLIENTREQUEST,
    R_REJECT_PULL,
    R_ACCEPT_PULL,
    R_LEARNOFLEADER,
    R_SENDPULL,
    R_HANDLE_SUCCESS_PULL,
    R_HANDLE_FAIL_PULL,
) = range(13)

ACTION_NAMES = [
    "Restart",
    "UpdateTerm",
    "RequestVote",
    "HandleRequestVoteRequest",
    "HandleRequestVoteResponse",
    "BecomeLeader",
    "ClientRequest",
    "RejectPullEntriesRequest",
    "AcceptPullEntriesRequest",
    "LearnOfLeader",
    "SendPullEntriesRequest",
    "HandleSuccessPullEntriesResponse",
    "HandleFailPullEntriesResponse",
]

STATE_NAMES = {FOLLOWER: "Follower", CANDIDATE: "Candidate", LEADER: "Leader"}
MTYPE_NAMES = {
    RVREQ: "RequestVoteRequest",
    RVRESP: "RequestVoteResponse",
    PULLREQ: "PullEntriesRequest",
    PULLRESP: "PullEntriesResponse",
    NOTIFY: "LeaderNotifyRequest",
}


@dataclass(frozen=True)
class PullRaftParams:
    n_servers: int
    n_values: int
    max_elections: int
    max_restarts: int
    msg_slots: int = 64
    variant2: bool = False
    # headroom above |Value| for stale-response appends (see module note);
    # 0 means auto (n_values + 4). Overflow is a hard error either way.
    max_log_override: int = 0
    # Fleet packing (models/base.py FleetConstMixin), same contract as
    # RaftParams: guards for dyn_consts read per-state lanes.
    dyn_consts: tuple = ()
    fleet: bool = False

    @property
    def max_term(self) -> int:
        return 1 + self.max_elections

    @property
    def max_log(self) -> int:
        if self.max_log_override:
            return self.max_log_override
        return self.n_values + 4


def _build_layout(p: PullRaftParams) -> Layout:
    S, V, L, M = p.n_servers, p.n_values, p.max_log, p.msg_slots
    lay = Layout(S)
    # VIEW (PullRaft.tla:123: messages, serverVars, candidateVars,
    # leaderVars, logVars, acked; Variant2.tla:114 drops acked).
    lay.add("currentTerm", "per_server", (S,))
    lay.add("state", "per_server", (S,))
    lay.add("leader", "per_server_val", (S,))
    if p.variant2:
        lay.add("votedFor", "per_server_val", (S,))
        lay.add("vle_has", "per_server_pair", (S, S))  # votesLastEntry # Nil
        lay.add("vle_idx", "per_server_pair", (S, S))
        lay.add("vle_term", "per_server_pair", (S, S))
    lay.add("votesGranted", "server_bitmask", (S,))
    lay.add("log_term", "per_server", (S, L))
    lay.add("log_value", "per_server", (S, L))
    lay.add("log_len", "per_server", (S,))
    lay.add("commitIndex", "per_server", (S,))
    lay.add("matchIndex", "per_server_pair", (S, S))
    lay.add("msg_hi", "msg_hi", (M,))
    lay.add("msg_lo", "msg_lo", (M,))
    lay.add("msg_cnt", "msg_cnt", (M,))
    if p.fleet:
        # Fleet config axis (models/base.py FleetConstMixin): VIEW
        # scalars, before the first aux field in either variant.
        lay.add("fleet_job", "scalar")
        for nm in p.dyn_consts:
            lay.add("c_" + nm, "scalar")
    # acked is IN the view for PullRaft (PullRaft.tla:123) but aux for
    # Variant2 (PullRaftVariant2.tla:114)
    lay.add("acked", "aux" if p.variant2 else "scalar", (V,))
    lay.add("electionCtr", "aux")
    lay.add("restartCtr", "aux")
    return lay.finish()


def _build_packer(p: PullRaftParams) -> BitPacker:
    tb = bits_for(p.max_term)
    sb = bits_for(p.n_servers - 1)
    lb = bits_for(p.max_log + 1)
    vb = bits_for(p.n_values)
    return BitPacker(
        [
            ("mtype", 3),
            ("mterm", tb),
            ("msource", sb),
            ("mdest", sb),
            ("mlastLogTerm", tb),  # RVReq/PullReq (+V2 RVResp)
            ("mlastLogIndex", lb),
            ("mvoteGranted", 1),
            ("msuccess", 1),
            ("nentries", 1),  # success PullResp carries exactly 1 entry
            ("eterm", tb),
            ("evalue", vb),
            ("mcommitIndex", lb),
            ("mlcHas", 1),  # mlastCommonEntry # Nil (V2 notify; fail resp)
            ("mlcIndex", lb),
            ("mlcTerm", tb),
        ]
    )


class PullRaftModel(SparseExpandMixin, FleetConstMixin, ActionLabelMixin):
    """Vectorized successor/invariant kernels for one (spec, constants)."""

    name = "PullRaft"
    ACTION_NAMES = ACTION_NAMES

    def __init__(self, params: PullRaftParams, server_names=None, value_names=None):
        self.p = params
        self.layout = _build_layout(params)
        self.packer = _build_packer(params)
        S, V, M = params.n_servers, params.n_values, params.msg_slots
        self.server_names = list(server_names or [f"s{i+1}" for i in range(S)])
        self.value_names = list(value_names or [f"v{i+1}" for i in range(V)])
        if params.variant2:
            self.name = "PullRaftVariant2"

        self.bindings: list[tuple[str, tuple]] = []
        self._pairs = [(i, j) for i in range(S) for j in range(S) if i != j]
        for i in range(S):
            self.bindings.append(("Restart", (i,)))
        for i in range(S):
            self.bindings.append(("RequestVote", (i,)))
        for i in range(S):
            self.bindings.append(("BecomeLeader", (i,)))
        for i in range(S):
            for v in range(V):
                self.bindings.append(("ClientRequest", (i, v)))
        for ij in self._pairs:
            self.bindings.append(("SendPullEntriesRequest", ij))
        for m in range(M):
            self.bindings.append(("HandleMessage", (m,)))
        self.A = len(self.bindings)

        self.expand = jax.jit(jax.vmap(self._expand1))
        self.invariants = {
            "MessagesAreValid": jax.jit(
                messages_are_valid_kernel(self.layout, self.packer)
            ),
            "NoLogDivergence": jax.jit(self._inv_no_log_divergence),
            "LeaderHasAllAckedValues": jax.jit(self._inv_leader_has_acked),
            "CommittedEntriesReachMajority": jax.jit(self._inv_committed_majority),
            "TestInv": jax.jit(lambda s: jnp.ones(s.shape[:-1], dtype=bool)),
        }

    # ---------------- helpers ----------------

    def _dec(self, s):
        g = self.layout.get
        return {f: g(s, f) for f in self.layout.fields}

    def _asm(self, d, **updates):
        parts = []
        for name, f in self.layout.fields.items():
            arr = updates.get(name, d[name])
            arr = jnp.asarray(arr, jnp.int32)
            parts.append(arr.reshape(-1) if f.shape else arr.reshape(1))
        return jnp.concatenate(parts)

    def _pack(self, **vals):
        hi, lo = self.packer.pack(**vals)
        return jnp.asarray(hi, jnp.int32), jnp.asarray(lo, jnp.int32)

    @staticmethod
    def _last_term(d, i):
        """LastTerm(log[i]) — PullRaft.tla:134."""
        ll = d["log_len"][i]
        return jnp.where(ll > 0, d["log_term"][i][jnp.clip(ll - 1, 0)], 0)

    def _last_common(self, lt_row, ll, last_idx, last_term):
        """LastCommonEntry — PullRaft.tla:211-226. Highest index k in
        1..ll with entry (k, term[k]) <= (last_idx, last_term) under
        CompareEntries' term-precedence order (:203-207); (0,0) if none.
        The CHOOSE is deterministic: max satisfying index."""
        L = self.p.max_log
        lanes = jnp.arange(1, L + 1, dtype=jnp.int32)
        ok = (lanes <= ll) & (
            (lt_row < last_term) | ((lt_row == last_term) & (lanes <= last_idx))
        )
        idx = jnp.max(jnp.where(ok, lanes, 0))
        term = jnp.where(idx > 0, lt_row[jnp.clip(idx - 1, 0, L - 1)], 0)
        return idx, term

    # ---------------- action kernels ----------------

    def _restart(self, s, i):
        """Restart(i) — PullRaft.tla:258-265 (keeps currentTerm, leader,
        log); Variant2 (PullRaftVariant2.tla:251-260) keeps votedFor but
        clears leader and votesLastEntry."""
        p, S = self.p, self.p.n_servers
        d = self._dec(s)
        valid = d["restartCtr"] < self._cv(d, "max_restarts")
        upd = dict(
            state=d["state"].at[i].set(FOLLOWER),
            votesGranted=d["votesGranted"].at[i].set(0),
            matchIndex=d["matchIndex"].at[i].set(jnp.zeros((S,), jnp.int32)),
            commitIndex=d["commitIndex"].at[i].set(0),
            restartCtr=d["restartCtr"] + 1,
        )
        if p.variant2:
            upd["leader"] = d["leader"].at[i].set(NIL)
            upd["vle_has"] = d["vle_has"].at[i].set(jnp.zeros((S,), jnp.int32))
            upd["vle_idx"] = d["vle_idx"].at[i].set(jnp.zeros((S,), jnp.int32))
            upd["vle_term"] = d["vle_term"].at[i].set(jnp.zeros((S,), jnp.int32))
        succ = self._asm(d, **upd)
        return valid, succ, jnp.int32(R_RESTART), jnp.asarray(False)

    def _request_vote(self, s, i):
        """RequestVote(i) — PullRaft.tla:283-298 (leader[i] := i);
        Variant2 (PullRaftVariant2.tla:279-295): votedFor := i, leader := Nil."""
        p, S = self.p, self.p.n_servers
        d = self._dec(s)
        st_i = d["state"][i]
        valid = (d["electionCtr"] < self._cv(d, "max_elections")) & (
            (st_i == FOLLOWER) | (st_i == CANDIDATE)
        )
        new_term = d["currentTerm"][i] + 1
        last_t = self._last_term(d, i)
        ll_i = d["log_len"][i]
        hi, lo, cnt = d["msg_hi"], d["msg_lo"], d["msg_cnt"]
        ovf = jnp.asarray(False)
        for delta in range(1, S):
            j = jnp.mod(i + delta, S)
            khi, klo = self._pack(
                mtype=RVREQ,
                mterm=new_term,
                mlastLogTerm=last_t,
                mlastLogIndex=ll_i,
                msource=i,
                mdest=j,
            )
            hi, lo, cnt, existed, o = bag.bag_put(hi, lo, cnt, khi, klo)
            valid &= ~existed  # SendMultiple (PullRaft.tla:141-143)
            ovf |= o
        upd = dict(
            state=d["state"].at[i].set(CANDIDATE),
            currentTerm=d["currentTerm"].at[i].set(new_term),
            votesGranted=d["votesGranted"].at[i].set(jnp.int32(1) << i),
            electionCtr=d["electionCtr"] + 1,
            msg_hi=hi,
            msg_lo=lo,
            msg_cnt=cnt,
        )
        if p.variant2:
            upd["votedFor"] = d["votedFor"].at[i].set(i + 1)
            upd["leader"] = d["leader"].at[i].set(NIL)
        else:
            upd["leader"] = d["leader"].at[i].set(i + 1)
        succ = self._asm(d, **upd)
        return valid, succ, jnp.int32(R_REQUESTVOTE), ovf & valid

    def _become_leader(self, s, i):
        """BecomeLeader(i) — PullRaft.tla:354-366: LeaderNotifyRequest to
        Server \\ votesGranted[i]; Variant2 (PullRaftVariant2.tla:361-379):
        notify ALL peers with embedded mlastCommonEntry, leader[i] := i."""
        p, S = self.p, self.p.n_servers
        d = self._dec(s)
        votes = jnp.sum((d["votesGranted"][i] >> jnp.arange(S, dtype=jnp.int32)) & 1)
        valid = (d["state"][i] == CANDIDATE) & (2 * votes > S)
        hi, lo, cnt = d["msg_hi"], d["msg_lo"], d["msg_cnt"]
        ovf = jnp.asarray(False)
        for delta in range(1, S):
            j = jnp.mod(i + delta, S)
            if p.variant2:
                send_j = jnp.asarray(True)
                has = d["vle_has"][i, j] > 0
                lce_i, lce_t = self._last_common(
                    d["log_term"][i],
                    d["log_len"][i],
                    d["vle_idx"][i, j],
                    d["vle_term"][i, j],
                )
                khi, klo = self._pack(
                    mtype=NOTIFY,
                    mterm=d["currentTerm"][i],
                    mlcHas=has.astype(jnp.int32),
                    mlcIndex=jnp.where(has, lce_i, 0),
                    mlcTerm=jnp.where(has, lce_t, 0),
                    msource=i,
                    mdest=j,
                )
            else:
                # only peers that did NOT vote for i (PullRaft.tla:364)
                send_j = ((d["votesGranted"][i] >> j) & 1) == 0
                khi, klo = self._pack(
                    mtype=NOTIFY, mterm=d["currentTerm"][i], msource=i, mdest=j
                )
            nhi, nlo, ncnt, existed, o = bag.bag_put(hi, lo, cnt, khi, klo)
            valid &= ~(existed & send_j)
            ovf |= o & send_j
            hi = jnp.where(send_j, nhi, hi)
            lo = jnp.where(send_j, nlo, lo)
            cnt = jnp.where(send_j, ncnt, cnt)
        upd = dict(
            state=d["state"].at[i].set(LEADER),
            matchIndex=d["matchIndex"].at[i].set(jnp.zeros((S,), jnp.int32)),
            msg_hi=hi,
            msg_lo=lo,
            msg_cnt=cnt,
        )
        if p.variant2:
            upd["leader"] = d["leader"].at[i].set(i + 1)
        succ = self._asm(d, **upd)
        return valid, succ, jnp.int32(R_BECOMELEADER), ovf & valid

    def _client_request(self, s, i, v):
        """ClientRequest(i, v) — PullRaft.tla:370-379."""
        L = self.p.max_log
        d = self._dec(s)
        valid = (d["state"][i] == LEADER) & (d["acked"][v] == ACK_NIL)
        pos = d["log_len"][i]
        ovf = valid & (pos >= L)
        posc = jnp.clip(pos, 0, L - 1)
        succ = self._asm(
            d,
            log_term=d["log_term"].at[i, posc].set(d["currentTerm"][i]),
            log_value=d["log_value"].at[i, posc].set(v + 1),
            log_len=d["log_len"].at[i].add(1),
            acked=d["acked"].at[v].set(ACK_FALSE),
        )
        return valid, succ, jnp.int32(R_CLIENTREQUEST), ovf

    def _send_pull(self, s, i, j):
        """SendPullEntriesRequest(i, j) — PullRaft.tla:396-411."""
        d = self._dec(s)
        valid = (d["state"][i] == FOLLOWER) & (d["leader"][i] == j + 1)
        khi, klo = self._pack(
            mtype=PULLREQ,
            mterm=d["currentTerm"][i],
            mlastLogIndex=d["log_len"][i],
            mlastLogTerm=self._last_term(d, i),
            msource=i,
            mdest=j,
        )
        hi, lo, cnt, existed, ovf = bag.bag_put(
            d["msg_hi"], d["msg_lo"], d["msg_cnt"], khi, klo
        )
        valid &= ~existed  # Send (PullRaft.tla:137-139)
        succ = self._asm(d, msg_hi=hi, msg_lo=lo, msg_cnt=cnt)
        return valid, succ, jnp.int32(R_SENDPULL), ovf & valid

    # -------- fused message-receipt kernel (slot m) --------
    # The eight receipt disjuncts (UpdateTerm, HandleRVReq, HandleRVResp,
    # RejectPull, AcceptPull, LearnOfLeader, HandleSuccessPull,
    # HandleFailPull) are mutually exclusive per record: they partition on
    # mtype, the term comparison, ValidPullPosition and msuccess.

    def _handle_message(self, s, m):
        p, packer = self.p, self.packer
        S, L, V = p.n_servers, p.max_log, p.n_values
        d = self._dec(s)
        hi, lo, cnt = d["msg_hi"], d["msg_lo"], d["msg_cnt"]
        khi, klo, kcnt = hi[m], lo[m], cnt[m]
        occupied = khi != EMPTY
        u = partial(packer.unpack, khi, klo)
        mtype, mterm = u("mtype"), u("mterm")
        src, dst = u("msource"), u("mdest")
        ct_dst = d["currentTerm"][dst]
        st_dst = d["state"][dst]
        recv = occupied & (kcnt > 0)  # ReceivableMessage (PullRaft.tla:166-172)
        ll_dst = d["log_len"][dst]
        lt_dst = d["log_term"][dst]
        lv_dst = d["log_value"][dst]

        def reply(resp_hi, resp_lo):
            """Reply — PullRaft.tla:158-161 (response must be absent)."""
            c2 = bag.bag_discard_at(cnt, m)
            return bag.bag_put(hi, lo, c2, resp_hi, resp_lo)

        # --- UpdateTerm (PullRaft.tla:269-276): count-0 records included.
        b_upd = occupied & (mterm > ct_dst)
        upd_u = dict(
            currentTerm=d["currentTerm"].at[dst].set(mterm),
            state=d["state"].at[dst].set(FOLLOWER),
            leader=d["leader"].at[dst].set(NIL),
        )
        if p.variant2:
            upd_u["votedFor"] = d["votedFor"].at[dst].set(NIL)
        s_upd = self._asm(d, **upd_u)

        # --- HandleRequestVoteRequest (PullRaft.tla:306-330;
        # PullRaftVariant2.tla:303-326)
        last_t = self._last_term(d, dst)
        rv_logok = (u("mlastLogTerm") > last_t) | (
            (u("mlastLogTerm") == last_t) & (u("mlastLogIndex") >= ll_dst)
        )
        vote_var = d["votedFor"] if p.variant2 else d["leader"]
        grant = (
            (mterm == ct_dst)
            & rv_logok
            & ((vote_var[dst] == NIL) | (vote_var[dst] == src + 1))
        )
        b_rvreq = recv & (mtype == RVREQ) & (mterm <= ct_dst)
        resp_kw = dict(
            mtype=RVRESP,
            mterm=ct_dst,
            mvoteGranted=grant.astype(jnp.int32),
            msource=dst,
            mdest=src,
        )
        if p.variant2:  # response carries last entry (PullRaftVariant2.tla:320-321)
            resp_kw["mlastLogIndex"] = ll_dst
            resp_kw["mlastLogTerm"] = last_t
        rhi, rlo = self._pack(**resp_kw)
        hi1, lo1, cnt1, ex1, ovf1 = reply(rhi, rlo)
        b_rvreq &= ~ex1
        upd_rv = dict(msg_hi=hi1, msg_lo=lo1, msg_cnt=cnt1)
        granted_var = jnp.where(grant, vote_var.at[dst].set(src + 1), vote_var)
        if p.variant2:
            upd_rv["votedFor"] = granted_var
        else:
            upd_rv["leader"] = granted_var
        s_rvreq = self._asm(d, **upd_rv)

        # --- HandleRequestVoteResponse (PullRaft.tla:335-350;
        # Variant2 also records votesLastEntry, PullRaftVariant2.tla:339-344)
        b_rvresp = recv & (mtype == RVRESP) & (mterm == ct_dst)
        g = u("mvoteGranted") > 0
        vg = jnp.where(
            g,
            d["votesGranted"].at[dst].set(d["votesGranted"][dst] | (jnp.int32(1) << src)),
            d["votesGranted"],
        )
        upd_rvr = dict(votesGranted=vg, msg_cnt=bag.bag_discard_at(cnt, m))
        if p.variant2:
            upd_rvr["vle_has"] = jnp.where(
                g, d["vle_has"].at[dst, src].set(1), d["vle_has"]
            )
            upd_rvr["vle_idx"] = jnp.where(
                g, d["vle_idx"].at[dst, src].set(u("mlastLogIndex")), d["vle_idx"]
            )
            upd_rvr["vle_term"] = jnp.where(
                g, d["vle_term"].at[dst, src].set(u("mlastLogTerm")), d["vle_term"]
            )
        s_rvresp = self._asm(d, **upd_rvr)

        # --- pull-request handling: ValidPullPosition (PullRaft.tla:192-196)
        pull_idx = u("mlastLogIndex")
        pull_term = u("mlastLogTerm")
        valid_pos = (pull_idx == 0) | (
            (pull_idx > 0)
            & (pull_idx <= ll_dst)
            & (pull_term == lt_dst[jnp.clip(pull_idx - 1, 0, L - 1)])
        )
        is_pullreq = recv & (mtype == PULLREQ) & (mterm == ct_dst) & (st_dst == LEADER)

        # --- RejectPullEntriesRequest (PullRaft.tla:418-436)
        b_reject = is_pullreq & ~valid_pos
        lce_i, lce_t = self._last_common(lt_dst, ll_dst, pull_idx, pull_term)
        rjhi, rjlo = self._pack(
            mtype=PULLRESP,
            mterm=ct_dst,
            msuccess=0,
            mlcHas=1,
            mlcIndex=lce_i,
            mlcTerm=lce_t,
            msource=dst,
            mdest=src,
        )
        hi2, lo2, cnt2, ex2, ovf2 = reply(rjhi, rjlo)
        b_reject &= ~ex2
        s_reject = self._asm(d, msg_hi=hi2, msg_lo=lo2, msg_cnt=cnt2)

        # --- AcceptPullEntriesRequest (PullRaft.tla:460-488)
        index = pull_idx + 1
        b_accept = is_pullreq & valid_pos & (index <= ll_dst)
        new_match = d["matchIndex"].at[dst, src].set(pull_idx)
        # NewCommitIndex (PullRaft.tla:446-458)
        idxs = jnp.arange(1, L + 1, dtype=jnp.int32)
        self_in = jnp.arange(S, dtype=jnp.int32)[None, :] == dst
        agree = self_in | (new_match[dst][None, :] >= idxs[:, None])
        quorum_ok = 2 * jnp.sum(agree, axis=1) > S
        is_agree = quorum_ok & (idxs <= ll_dst)
        max_agree = jnp.max(jnp.where(is_agree, idxs, 0))
        term_at = lt_dst[jnp.clip(max_agree - 1, 0, L - 1)]
        ci_dst = d["commitIndex"][dst]
        new_ci = jnp.where(
            (max_agree > 0) & (term_at == ct_dst), max_agree, ci_dst
        )
        # acked[v]: FALSE -> v committed in (ci, new_ci] (PullRaft.tla:476-479)
        lanes0 = jnp.arange(L, dtype=jnp.int32)
        in_range = (lanes0 + 1 > ci_dst) & (lanes0 + 1 <= new_ci)
        committed = jnp.any(
            in_range[None, :]
            & (lv_dst[None, :] == jnp.arange(1, V + 1, dtype=jnp.int32)[:, None]),
            axis=1,
        )
        acked2 = jnp.where((d["acked"] == ACK_FALSE) & committed, ACK_TRUE, d["acked"])
        epos = jnp.clip(index - 1, 0, L - 1)
        achi, aclo = self._pack(
            mtype=PULLRESP,
            mterm=ct_dst,
            msuccess=1,
            nentries=1,
            eterm=lt_dst[epos],
            evalue=lv_dst[epos],
            mcommitIndex=jnp.minimum(new_ci, index),
            msource=dst,
            mdest=src,
        )
        hi3, lo3, cnt3, ex3, ovf3 = reply(achi, aclo)
        b_accept &= ~ex3
        s_accept = self._asm(
            d,
            matchIndex=new_match,
            commitIndex=d["commitIndex"].at[dst].set(new_ci),
            acked=acked2,
            msg_hi=hi3,
            msg_lo=lo3,
            msg_cnt=cnt3,
        )

        # --- LearnOfLeader (PullRaft.tla:383-391; Variant2 may truncate,
        # PullRaftVariant2.tla:398-410)
        b_learn = recv & (mtype == NOTIFY) & (mterm == ct_dst)
        upd_learn = dict(
            leader=d["leader"].at[dst].set(src + 1),
            msg_cnt=bag.bag_discard_at(cnt, m),
        )
        if p.variant2:
            # NeedsTruncation (PullRaftVariant2.tla:171-173): mlcHas and
            # Len(log) >= index; TruncateLog to the index (:176-179).
            mlc_has = u("mlcHas") > 0
            mlc_idx = u("mlcIndex")
            do_trunc = mlc_has & (ll_dst >= mlc_idx)
            new_ll_l = jnp.where(do_trunc, mlc_idx, ll_dst)
            keep = lanes0 < new_ll_l
            upd_learn["log_term"] = d["log_term"].at[dst].set(
                jnp.where(keep, lt_dst, 0)
            )
            upd_learn["log_value"] = d["log_value"].at[dst].set(
                jnp.where(keep, lv_dst, 0)
            )
            upd_learn["log_len"] = d["log_len"].at[dst].set(new_ll_l)
        s_learn = self._asm(d, **upd_learn)

        # --- HandleSuccessPullEntriesResponse (PullRaft.tla:493-503)
        is_pullresp = recv & (mtype == PULLRESP) & (mterm == ct_dst)
        b_succ = is_pullresp & (u("msuccess") > 0)
        app_pos = jnp.clip(ll_dst, 0, L - 1)
        suc_ovf = b_succ & (ll_dst >= L)
        s_succ = self._asm(
            d,
            commitIndex=d["commitIndex"].at[dst].set(u("mcommitIndex")),
            log_term=d["log_term"].at[dst, app_pos].set(u("eterm")),
            log_value=d["log_value"].at[dst, app_pos].set(u("evalue")),
            log_len=d["log_len"].at[dst].add(1),
            msg_cnt=bag.bag_discard_at(cnt, m),
        )

        # --- HandleFailPullEntriesResponse (PullRaft.tla:510-520):
        # TruncateLog to mlastCommonEntry.index (clamped to Len).
        b_fail = is_pullresp & (u("msuccess") == 0)
        new_ll_f = jnp.minimum(u("mlcIndex"), ll_dst)
        keep_f = lanes0 < new_ll_f
        s_fail = self._asm(
            d,
            log_term=d["log_term"].at[dst].set(jnp.where(keep_f, lt_dst, 0)),
            log_value=d["log_value"].at[dst].set(jnp.where(keep_f, lv_dst, 0)),
            log_len=d["log_len"].at[dst].set(new_ll_f),
            msg_cnt=bag.bag_discard_at(cnt, m),
        )

        branches = [
            (b_upd, s_upd, R_UPDATETERM, jnp.asarray(False)),
            (b_rvreq, s_rvreq, R_HANDLE_RVREQ, ovf1),
            (b_rvresp, s_rvresp, R_HANDLE_RVRESP, jnp.asarray(False)),
            (b_reject, s_reject, R_REJECT_PULL, ovf2),
            (b_accept, s_accept, R_ACCEPT_PULL, ovf3),
            (b_learn, s_learn, R_LEARNOFLEADER, jnp.asarray(False)),
            (b_succ, s_succ, R_HANDLE_SUCCESS_PULL, suc_ovf),
            (b_fail, s_fail, R_HANDLE_FAIL_PULL, jnp.asarray(False)),
        ]
        valid = jnp.asarray(False)
        succ = s
        rank = jnp.int32(-1)
        ovf = jnp.asarray(False)
        for b, sb, rk, ob in branches:
            valid = valid | b
            succ = jnp.where(b, sb, succ)
            rank = jnp.where(b, jnp.int32(rk), rank)
            ovf = ovf | (b & ob)
        return valid, succ, rank, ovf

    # ---------------- full expansion ----------------

    def _kernel_overrides(self) -> dict:
        return {"SendPullEntriesRequest": self._send_pull}

    def _expand1(self, s):
        p = self.p
        S, V, M = p.n_servers, p.n_values, p.msg_slots
        iota_s = jnp.arange(S, dtype=jnp.int32)
        pr_i = jnp.asarray([ij[0] for ij in self._pairs], jnp.int32)
        pr_j = jnp.asarray([ij[1] for ij in self._pairs], jnp.int32)
        outs = []
        outs.append(jax.vmap(lambda i: self._restart(s, i))(iota_s))
        outs.append(jax.vmap(lambda i: self._request_vote(s, i))(iota_s))
        outs.append(jax.vmap(lambda i: self._become_leader(s, i))(iota_s))
        cr_i = jnp.repeat(iota_s, V)
        cr_v = jnp.tile(jnp.arange(V, dtype=jnp.int32), S)
        outs.append(jax.vmap(lambda i, v: self._client_request(s, i, v))(cr_i, cr_v))
        outs.append(jax.vmap(lambda i, j: self._send_pull(s, i, j))(pr_i, pr_j))
        outs.append(
            jax.vmap(lambda m: self._handle_message(s, m))(jnp.arange(M, dtype=jnp.int32))
        )
        valid = jnp.concatenate([o[0] for o in outs])
        succs = jnp.concatenate([o[1] for o in outs])
        rank = jnp.concatenate([o[2] for o in outs])
        ovf = jnp.concatenate([o[3] for o in outs])
        return succs, valid, rank, ovf

    # ---------------- initial states ----------------

    def init_states(self) -> np.ndarray:
        """Init — PullRaft.tla:231-250."""
        lay = self.layout
        vec = lay.zeros((1,))
        vec[0, lay.sl("currentTerm")] = 1
        vec[0, lay.sl("msg_hi")] = int(EMPTY)
        vec[0, lay.sl("msg_lo")] = int(EMPTY)
        return self._fleet_stamp(vec)

    # ---------------- invariants (PullRaft.tla:578-627) ----------------

    def _inv_no_log_divergence(self, states):
        lay, L = self.layout, self.p.max_log
        ci = lay.get(states, "commitIndex")
        lt = lay.get(states, "log_term")
        lv = lay.get(states, "log_value")
        mci = jnp.minimum(ci[:, :, None], ci[:, None, :])
        lanes = jnp.arange(1, L + 1, dtype=jnp.int32)
        in_common = lanes[None, None, None, :] <= mci[..., None]
        eq = (lt[:, :, None, :] == lt[:, None, :, :]) & (
            lv[:, :, None, :] == lv[:, None, :, :]
        )
        return jnp.all(~in_common | eq, axis=(1, 2, 3))

    def _inv_leader_has_acked(self, states):
        lay, V = self.layout, self.p.n_values
        ct = lay.get(states, "currentTerm")
        st = lay.get(states, "state")
        lv = lay.get(states, "log_value")
        acked = lay.get(states, "acked")
        not_stale = jnp.all(ct[:, :, None] >= ct[:, None, :], axis=2)
        is_lead = (st == LEADER) & not_stale
        vals = jnp.arange(1, V + 1, dtype=jnp.int32)
        has_v = jnp.any(lv[:, :, None, :] == vals[None, None, :, None], axis=3)
        bad = jnp.any(
            (acked[:, None, :] == ACK_TRUE) & is_lead[:, :, None] & ~has_v, axis=(1, 2)
        )
        return ~bad

    def _inv_committed_majority(self, states):
        lay, S, L = self.layout, self.p.n_servers, self.p.max_log
        st = lay.get(states, "state")
        ci = lay.get(states, "commitIndex")
        ll = lay.get(states, "log_len")
        lt = lay.get(states, "log_term")
        lv = lay.get(states, "log_value")
        lead = (st == LEADER) & (ci > 0)
        pos = jnp.clip(ci - 1, 0, L - 1)
        lt_i = jnp.take_along_axis(lt, pos[:, :, None], axis=2)[:, :, 0]
        lv_i = jnp.take_along_axis(lv, pos[:, :, None], axis=2)[:, :, 0]
        posj = jnp.broadcast_to(pos[:, :, None], pos.shape + (S,))
        lt_j = jnp.take_along_axis(
            jnp.broadcast_to(lt[:, None, :, :], lt.shape[:1] + (S,) + lt.shape[1:]),
            posj[..., None],
            axis=3,
        )[..., 0]
        lv_j = jnp.take_along_axis(
            jnp.broadcast_to(lv[:, None, :, :], lv.shape[:1] + (S,) + lv.shape[1:]),
            posj[..., None],
            axis=3,
        )[..., 0]
        match = (ll[:, None, :] >= ci[:, :, None]) & (lt_j == lt_i[..., None]) & (
            lv_j == lv_i[..., None]
        )
        enough = jnp.sum(match, axis=2) >= (S // 2 + 1)
        ok_exists = jnp.any(lead & enough, axis=1)
        return ~jnp.any(lead, axis=1) | ok_exists

    # ---------------- host-side decode/encode ----------------

    def decode(self, vec: np.ndarray) -> dict:
        lay, p = self.layout, self.p
        g = lambda n: np.asarray(vec[lay.sl(n)])
        S, L = p.n_servers, p.max_log
        lt = g("log_term").reshape(S, L)
        lv = g("log_value").reshape(S, L)
        ll = g("log_len")
        log = tuple(
            tuple((int(lt[i, k]), int(lv[i, k]) - 1) for k in range(int(ll[i])))
            for i in range(S)
        )
        vg = g("votesGranted")
        votes = tuple(
            frozenset(j for j in range(S) if (int(vg[i]) >> j) & 1) for i in range(S)
        )
        msgs = {}
        hi, lo, cnt = g("msg_hi"), g("msg_lo"), g("msg_cnt")
        for k in range(p.msg_slots):
            if int(hi[k]) == int(EMPTY):
                continue
            msgs[self.decode_msg(int(hi[k]), int(lo[k]))] = int(cnt[k])
        extra = {}
        if p.variant2:
            vh = g("vle_has").reshape(S, S)
            vi = g("vle_idx").reshape(S, S)
            vt = g("vle_term").reshape(S, S)
            extra["votedFor"] = tuple(
                int(x) - 1 if x > 0 else None for x in g("votedFor")
            )
            extra["votesLastEntry"] = tuple(
                tuple(
                    (int(vi[a, b]), int(vt[a, b])) if vh[a, b] else None
                    for b in range(S)
                )
                for a in range(S)
            )
        return extra | {
            "currentTerm": tuple(int(x) for x in g("currentTerm")),
            "state": tuple(int(x) for x in g("state")),
            "leader": tuple(int(x) - 1 if x > 0 else None for x in g("leader")),
            "votesGranted": votes,
            "log": log,
            "commitIndex": tuple(int(x) for x in g("commitIndex")),
            "matchIndex": tuple(
                tuple(int(x) for x in row) for row in g("matchIndex").reshape(S, S)
            ),
            "messages": frozenset(msgs.items()),
            "acked": tuple(
                {ACK_NIL: None, ACK_FALSE: False, ACK_TRUE: True}[int(x)]
                for x in g("acked")
            ),
            "electionCtr": int(vec[lay.fields["electionCtr"].offset]),
            "restartCtr": int(vec[lay.fields["restartCtr"].offset]),
        }

    def decode_msg(self, hi: int, lo: int) -> tuple:
        u = self.packer.unpack_all(hi, lo)
        mtype = int(u["mtype"])
        rec = {
            "mtype": MTYPE_NAMES[mtype],
            "mterm": int(u["mterm"]),
            "msource": int(u["msource"]),
            "mdest": int(u["mdest"]),
        }
        if mtype == RVREQ:
            rec["mlastLogTerm"] = int(u["mlastLogTerm"])
            rec["mlastLogIndex"] = int(u["mlastLogIndex"])
        elif mtype == RVRESP:
            rec["mvoteGranted"] = bool(u["mvoteGranted"])
            if self.p.variant2:
                rec["mlastLogIndex"] = int(u["mlastLogIndex"])
                rec["mlastLogTerm"] = int(u["mlastLogTerm"])
        elif mtype == PULLREQ:
            rec["mlastLogIndex"] = int(u["mlastLogIndex"])
            rec["mlastLogTerm"] = int(u["mlastLogTerm"])
        elif mtype == PULLRESP:
            rec["msuccess"] = bool(u["msuccess"])
            if u["msuccess"]:
                rec["mentries"] = ((int(u["eterm"]), int(u["evalue"]) - 1),)
                rec["mcommitIndex"] = int(u["mcommitIndex"])
            else:
                rec["mlastCommonEntry"] = (int(u["mlcIndex"]), int(u["mlcTerm"]))
        elif mtype == NOTIFY:
            if self.p.variant2:
                rec["mlastCommonEntry"] = (
                    (int(u["mlcIndex"]), int(u["mlcTerm"]))
                    if u["mlcHas"]
                    else None
                )
        return tuple(sorted(rec.items()))

    def encode_msg(self, rec: tuple) -> tuple[int, int]:
        d = dict(rec)
        mtype = {v: k for k, v in MTYPE_NAMES.items()}[d["mtype"]]
        kw = dict(mtype=mtype, mterm=d["mterm"], msource=d["msource"], mdest=d["mdest"])
        if mtype == RVREQ:
            kw.update(mlastLogTerm=d["mlastLogTerm"], mlastLogIndex=d["mlastLogIndex"])
        elif mtype == RVRESP:
            kw.update(mvoteGranted=int(d["mvoteGranted"]))
            if self.p.variant2:
                kw.update(
                    mlastLogIndex=d["mlastLogIndex"], mlastLogTerm=d["mlastLogTerm"]
                )
        elif mtype == PULLREQ:
            kw.update(
                mlastLogIndex=d["mlastLogIndex"], mlastLogTerm=d["mlastLogTerm"]
            )
        elif mtype == PULLRESP:
            kw.update(msuccess=int(d["msuccess"]))
            if d["msuccess"]:
                ent = d["mentries"][0]
                kw.update(
                    nentries=1,
                    eterm=ent[0],
                    evalue=ent[1] + 1,
                    mcommitIndex=d["mcommitIndex"],
                )
            else:
                lce = d["mlastCommonEntry"]
                kw.update(mlcHas=1, mlcIndex=lce[0], mlcTerm=lce[1])
        elif mtype == NOTIFY:
            if self.p.variant2:
                lce = d["mlastCommonEntry"]
                if lce is not None:
                    kw.update(mlcHas=1, mlcIndex=lce[0], mlcTerm=lce[1])
        return self.packer.pack(**kw)

    def encode(self, st: dict) -> np.ndarray:
        lay, p = self.layout, self.p
        S, L = p.n_servers, p.max_log
        vec = lay.zeros(())
        vec[lay.sl("currentTerm")] = st["currentTerm"]
        vec[lay.sl("state")] = st["state"]
        vec[lay.sl("leader")] = [0 if v is None else v + 1 for v in st["leader"]]
        if p.variant2:
            vec[lay.sl("votedFor")] = [
                0 if v is None else v + 1 for v in st["votedFor"]
            ]
            vh = np.zeros((S, S), np.int32)
            vi = np.zeros((S, S), np.int32)
            vt = np.zeros((S, S), np.int32)
            for a in range(S):
                for b in range(S):
                    e = st["votesLastEntry"][a][b]
                    if e is not None:
                        vh[a, b], vi[a, b], vt[a, b] = 1, e[0], e[1]
            vec[lay.sl("vle_has")] = vh.reshape(-1)
            vec[lay.sl("vle_idx")] = vi.reshape(-1)
            vec[lay.sl("vle_term")] = vt.reshape(-1)
        vec[lay.sl("votesGranted")] = [
            sum(1 << j for j in vs) for vs in st["votesGranted"]
        ]
        lt = np.zeros((S, L), np.int32)
        lv = np.zeros((S, L), np.int32)
        for i, lg in enumerate(st["log"]):
            for k, (t, v) in enumerate(lg):
                lt[i, k] = t
                lv[i, k] = v + 1
        vec[lay.sl("log_term")] = lt.reshape(-1)
        vec[lay.sl("log_value")] = lv.reshape(-1)
        vec[lay.sl("log_len")] = [len(lg) for lg in st["log"]]
        vec[lay.sl("commitIndex")] = st["commitIndex"]
        vec[lay.sl("matchIndex")] = np.asarray(st["matchIndex"]).reshape(-1)
        keys = sorted((self.encode_msg(rec), cnt) for rec, cnt in st["messages"])
        if len(keys) > p.msg_slots:
            raise OverflowError("message bag exceeds msg_slots")
        hi = np.full(p.msg_slots, int(EMPTY), np.int32)
        lo = np.full(p.msg_slots, int(EMPTY), np.int32)
        cn = np.zeros(p.msg_slots, np.int32)
        for k, ((h, l), c) in enumerate(keys):
            hi[k], lo[k], cn[k] = h, l, c
        vec[lay.sl("msg_hi")] = hi
        vec[lay.sl("msg_lo")] = lo
        vec[lay.sl("msg_cnt")] = cn
        vec[lay.sl("acked")] = [
            {None: ACK_NIL, False: ACK_FALSE, True: ACK_TRUE}[a] for a in st["acked"]
        ]
        vec[lay.fields["electionCtr"].offset] = st["electionCtr"]
        vec[lay.fields["restartCtr"].offset] = st["restartCtr"]
        return vec


@lru_cache(maxsize=None)
def cached_model(params: PullRaftParams) -> "PullRaftModel":
    return PullRaftModel(params)
