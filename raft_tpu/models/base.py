"""State-vector layout machinery shared by all spec lowerings.

Every spec variant lowers its TLA+ variables to a single flat ``int32[W]``
vector per state. The layout records, per field, the *kind* of the field —
how it transforms under a permutation of the server set — which lets the
generic symmetry canonicalizer (ops/symmetry.py) serve every variant.

Field ordering convention: all VIEW fields first, aux (VIEW-excluded)
fields last, so the VIEW projection (``Raft.tla:115`` excludes
``acked/electionCtr/restartCtr``) is the contiguous prefix
``vec[:layout.view_len]``.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass

import jax.numpy as jnp

import numpy as np

# Field kinds and their transformation under a server permutation sigma
# (sigma maps old server index -> new server index):
#   scalar           unaffected
#   per_server       shape (S, ...): row r moves to row sigma(r)
#   per_server_val   shape (S,), values in 0..S with 0 = Nil: rows move AND
#                    values remap v -> sigma(v-1)+1
#   server_bitmask   shape (S,), each element a bitmask over servers: rows
#                    move AND bit j moves to bit sigma(j)
#   per_server_pair  shape (S, S): new[sigma(a), sigma(b)] = old[a, b]
#   msg_hi/msg_lo/   shape (M,): the message bag; server-valued fields inside
#   msg_cnt          the packed key remap, then slots re-sort
#   msg_word         shape (M,): one word of an N-word bag key (WidePacker);
#                    declared in word order, word 0 first (sort-major)
#   aux              VIEW-excluded scalar/vector (must come last)
KINDS = (
    "scalar",
    "per_server",
    "per_server_val",
    "server_bitmask",
    "per_server_pair",
    "msg_hi",
    "msg_lo",
    "msg_cnt",
    "msg_word",
    "aux",
)


@dataclass(frozen=True)
class Field:
    name: str
    kind: str
    shape: tuple[int, ...]
    offset: int

    @property
    def size(self) -> int:
        return int(math.prod(self.shape)) if self.shape else 1


class Layout:
    def __init__(self, n_servers: int):
        self.n_servers = n_servers
        self.fields: dict[str, Field] = {}
        self.W = 0
        self.view_len: int | None = None  # set when the first aux field lands

    def add(self, name: str, kind: str, shape: tuple[int, ...] = ()) -> Field:
        if kind not in KINDS:
            raise ValueError(f"unknown kind {kind}")
        if name in self.fields:
            raise ValueError(f"duplicate field {name}")
        if kind == "aux":
            if self.view_len is None:
                self.view_len = self.W
        elif self.view_len is not None:
            raise ValueError("non-aux field added after aux fields")
        f = Field(name, kind, shape, self.W)
        self.fields[name] = f
        self.W += f.size
        return f

    def finish(self):
        if self.view_len is None:
            self.view_len = self.W
        return self

    def sl(self, name: str) -> slice:
        f = self.fields[name]
        return slice(f.offset, f.offset + f.size)

    def get(self, vec, name: str):
        """Slice field `name` out of a [..., W] vector, reshaped to its shape."""
        f = self.fields[name]
        out = vec[..., f.offset : f.offset + f.size]
        if f.shape:
            return out.reshape(vec.shape[:-1] + f.shape)
        return out[..., 0]

    def zeros(self, batch: tuple[int, ...] = ()) -> np.ndarray:
        return np.zeros(batch + (self.W,), dtype=np.int32)


class ActionLabelMixin:
    """Human-readable labels for expansion candidates, shared by every
    spec lowering.

    Subclass contract: ``self.bindings`` (the candidate table of
    ``(kernel_name, binding_tuple)`` pairs) and ``self.ACTION_NAMES``
    (the Next-disjunct rank -> action-name table; index == the rank
    that ``_expand1`` reports). Fused ``HandleMessage`` kernels resolve
    their disjunct at run time, so the label comes from the fired rank;
    every other kernel is named by its binding."""

    ACTION_NAMES: list[str]

    def action_label(self, rank: int, cand: int) -> str:
        name, binding = self.bindings[cand]
        if name == "HandleMessage":
            return f"{self.ACTION_NAMES[rank]}(slot {binding[0]})"
        return f"{name}{binding}"


FLEET_JOB = "fleet_job"


class FleetConstMixin:
    """Fleet packing: a config axis embedded in the state vector.

    A fleet-packed model carries two kinds of extra VIEW scalar fields
    (added by the lowering's ``_build_layout`` when ``params.fleet``):

      fleet_job   which manifest job a state belongs to. Because it is a
                  VIEW field, fingerprints of different jobs never
                  collide, so many jobs share one frontier / seen-set /
                  journal without any cross-job dedup.
      c_<name>    one lane per *dynamic* constant in ``params.dyn_consts``
                  (e.g. ``c_max_restarts``). Guards read the lane via
                  ``_cv`` instead of the static param, so one compiled
                  program serves every CONSTANTS point in the group.

    The lanes are inserted after the message-bag fields and before the
    first aux field — scalar kind, so the symmetry canonicalizer leaves
    them alone (PullRaft's ``acked``-after-``msg_cnt`` field pins that
    this position is legal).

    Subclass contract: every lowering's ``init_states`` ends with
    ``return self._fleet_stamp(vec)`` (identity when no fleet table is
    bound), and every guard that reads a dynamic constant goes through
    ``self._cv(d, name)`` / ``self._cv_batch(states, name)``.
    """

    def fleet_bind(self, table) -> None:
        """Bind the per-job dynamic-constant table.

        ``table`` is [J, len(params.dyn_consts)] ints: row j holds job
        j's value for each dynamic constant, in ``dyn_consts`` order.
        The static params must be the element-wise max over the table
        (capacity sizing — e.g. ``max_term`` — is derived from them)."""
        table = np.asarray(table, np.int64)
        dyn = tuple(self.p.dyn_consts)
        if table.ndim != 2 or table.shape[1] != len(dyn):
            raise ValueError(
                f"fleet table must be [J, {len(dyn)}] for dyn_consts {dyn}"
            )
        for k, name in enumerate(dyn):
            cap = int(getattr(self.p, name))
            hi = int(table[:, k].max()) if len(table) else 0
            if hi > cap:
                raise ValueError(
                    f"fleet table {name} max {hi} exceeds static param {cap}"
                    " (representative params must be the per-constant max)"
                )
        self._fleet_table = table
        self._fleet_sel: int | None = None

    @property
    def fleet_jobs(self) -> int:
        t = getattr(self, "_fleet_table", None)
        return 0 if t is None else len(t)

    def fleet_select(self, j: int | None) -> None:
        """Restrict ``init_states`` stamping to job ``j`` (None = all
        jobs). The queue arm runs jobs one at a time through the SAME
        compiled program by re-selecting between runs."""
        if getattr(self, "_fleet_table", None) is None:
            raise ValueError("fleet_select before fleet_bind")
        self._fleet_sel = j

    def fleet_job_of(self, states) -> np.ndarray:
        """[n] job index of each row of a [n, W] state batch."""
        off = self.layout.fields[FLEET_JOB].offset
        return np.asarray(states)[..., off]

    def _fleet_stamp(self, vec: np.ndarray) -> np.ndarray:
        """Stamp init states with the job lane and constant lanes, one
        copy per selected job, job-major. Identity when unbound, so
        serial (non-fleet) models are untouched."""
        table = getattr(self, "_fleet_table", None)
        if table is None:
            return vec
        lay = self.layout
        sel = getattr(self, "_fleet_sel", None)
        jobs = range(len(table)) if sel is None else [sel]
        out = []
        for j in jobs:
            v = vec.copy()
            v[:, lay.fields[FLEET_JOB].offset] = j
            for k, name in enumerate(self.p.dyn_consts):
                v[:, lay.fields["c_" + name].offset] = int(table[j, k])
            out.append(v)
        return np.concatenate(out, axis=0)

    def _cv(self, d: dict, name: str):
        """A constant's value inside a per-state kernel: the state lane
        when fleet-packed, the static param otherwise (bit-identical to
        the pre-fleet guards in the serial case)."""
        key = "c_" + name
        if key in self.layout.fields:
            return d[key]
        return getattr(self.p, name)

    def _cv_batch(self, states, name: str):
        """Batched form of ``_cv`` for invariant/liveness kernels that
        work on [..., W] state batches rather than decoded dicts."""
        key = "c_" + name
        if key in self.layout.fields:
            return self.layout.get(states, key)
        return getattr(self.p, name)


@dataclass(frozen=True)
class SparseGroup:
    """One contiguous run of same-named bindings in ``self.bindings``:
    the unit of the guard-first sparse expansion. ``params`` is the
    static [n, arity] int32 binding table the apply pass gathers its
    kernel arguments from."""

    name: str
    off: int  # first candidate index of the group
    n: int  # candidates in the group
    params: np.ndarray  # [n, arity] int32


class SparseExpandMixin:
    """Guard-first sparse expansion, shared by every spec lowering.

    ``_expand1`` materializes a full-width successor row for every one
    of the A candidate bindings — even though coverage shows most are
    guard-disabled on every wave. This mixin splits that contract in
    two without touching (or trusting) any kernel code:

      guards1     valid/rank/ovf over all A candidates of one state,
                  derived from ``_expand1``'s own jaxpr by dead-code-
                  eliminating the succs output. Bit-identical to the
                  dense pass by construction (DCE removes equations, it
                  never rewrites values), and cheap: every W-wide
                  successor assembly and bag sort-insert is dead once
                  succs is unused (ops/bag.py computes existed/overflow
                  BEFORE the sort-insert for exactly this reason).
      apply1      full (valid, succ, rank, ovf) of ONE (state, cand)
                  pair: a lax.switch over the binding groups. With a
                  scalar cand only the selected branch executes.
      sparse_apply  the engine-facing batched apply: successor rows for
                  a compacted [VC] worklist of enabled candidates,
                  built per GROUP in fixed-budget blocks so every wave
                  stays on one precompiled signature. Per-lane switch
                  would execute ALL branches under vmap (costing more
                  than the dense pass it replaces); segmenting the
                  worklist by group runs each kernel only on its own
                  lanes.

    Subclass contract: ``self.bindings`` (same-named candidates
    contiguous, as every lowering already builds them), kernels named
    ``_snake_case`` of the binding name, overridable per model via
    ``_kernel_overrides`` for the lowerings whose method names predate
    the convention.
    """

    def _kernel_overrides(self) -> dict:
        """binding name -> bound kernel, for names that do not follow
        the ``_snake_case`` derivation."""
        return {}

    def kernel_for(self, name: str):
        """The per-action kernel ``(s, *binding) -> (valid, succ, rank,
        ovf)`` registered for binding name ``name``."""
        ov = self._kernel_overrides()
        if name in ov:
            return ov[name]
        attr = "_" + re.sub(r"(?<!^)(?=[A-Z])", "_", name).lower()
        kern = getattr(self, attr, None)
        if kern is None:
            raise AttributeError(
                f"{type(self).__name__} has no kernel {attr} for binding "
                f"{name!r} (declare it in _kernel_overrides)"
            )
        return kern

    def sparse_groups(self) -> list[SparseGroup]:
        """Contiguous same-named runs of ``self.bindings`` with their
        static parameter tables (cached; bindings are frozen after
        __init__)."""
        cached = self.__dict__.get("_sparse_groups")
        if cached is not None:
            return cached
        b = self.bindings
        groups: list[SparseGroup] = []
        i = 0
        while i < len(b):
            name = b[i][0]
            j = i
            while j < len(b) and b[j][0] == name:
                j += 1
            params = np.asarray(
                [list(t[1]) for t in b[i:j]], np.int32
            ).reshape(j - i, -1)
            groups.append(SparseGroup(name, i, j - i, params))
            i = j
        names = [g.name for g in groups]
        if len(set(names)) != len(names):
            raise ValueError(f"non-contiguous binding groups: {names}")
        self.__dict__["_sparse_groups"] = groups
        return groups

    # ---------------- guard pass ----------------

    @property
    def guards1(self):
        """``(s [W]) -> (valid [A], rank [A], ovf [A])`` — the dense
        guard grid of one state, with every successor write DCE'd out
        of ``_expand1``'s jaxpr (lazy-built, cached)."""
        fn = self.__dict__.get("_guards1_fn")
        if fn is None:
            fn = self._build_guards1()
            self.__dict__["_guards1_fn"] = fn
        return fn

    def _build_guards1(self):
        import jax
        from jax import core
        from jax.interpreters import partial_eval as pe

        closed = jax.make_jaxpr(self._expand1)(
            jax.ShapeDtypeStruct((self.layout.W,), jnp.int32)
        )
        jaxpr = pe.convert_constvars_jaxpr(closed.jaxpr)
        n_const = len(closed.consts)
        # _expand1 returns (succs, valid, rank, ovf): drop succs, keep
        # the three guard outputs
        dced, used = pe.dce_jaxpr(jaxpr, [False, True, True, True])
        kept = [c for c, u in zip(closed.consts, used[:n_const]) if u]
        state_used = used[n_const]

        def guards1(s):
            args = [*kept, s] if state_used else list(kept)
            valid, rank, ovf = core.eval_jaxpr(dced, [], *args)
            return valid, rank, ovf

        guards1.jaxpr = dced  # the no-W-wide-writes pin inspects this
        return guards1

    # ---------------- apply pass ----------------

    def apply1(self, s, cand):
        """Full (valid, succ [W], rank, ovf) of ONE (state, candidate)
        pair — trace reconstruction / parity checks; ``cand`` must be a
        scalar so lax.switch executes a single branch."""
        from jax import lax

        groups = self.sparse_groups()
        group_of = np.zeros((self.A,), np.int32)
        for gi, g in enumerate(groups):
            group_of[g.off : g.off + g.n] = gi
        cand = jnp.asarray(cand, jnp.int32)

        def branch(g):
            tbl = jnp.asarray(g.params)
            kern = self.kernel_for(g.name)

            def run(s, cand):
                k = jnp.clip(cand - g.off, 0, g.n - 1)
                args = [tbl[:, c][k] for c in range(tbl.shape[1])]
                return kern(s, *args)

            return run

        return lax.switch(
            jnp.asarray(group_of)[cand], [branch(g) for g in groups], s, cand
        )

    def sparse_plan(
        self,
        chunk: int,
        worklist: int,
        valid_per_group: float | dict | None = None,
    ) -> tuple[int, ...]:
        """Static per-group apply budgets EB_g for a [chunk]-state wave
        chunk whose enabled worklist is [worklist] lanes long.

        ``valid_per_group`` caps the enabled candidates a group may
        contribute per chunk, in per-state units (CHUNK-AGGREGATE:
        EB_g = chunk * cap — a few dense states inside an average
        chunk don't overflow it). A dict maps group name -> cap for
        per-group tuning (groups absent from the dict stay loose);
        fractions are legal (0.25 = one enabled candidate per four
        states). None keeps the loose ``min(chunk * n_g, worklist)``
        bound, under which budget overflow is impossible (a group can
        never hold more enabled worklist lanes than that) but wide
        groups (the message bag) still pay for every slot. The
        per-wave ``enabled_density`` gauge and the coverage table's
        enabled column are the tuning inputs."""
        plan = []
        for g in self.sparse_groups():
            if isinstance(valid_per_group, dict):
                vpg = valid_per_group.get(g.name)
            else:
                vpg = valid_per_group
            cap = g.n if vpg is None else min(g.n, vpg)
            plan.append(int(min(math.ceil(chunk * cap), worklist)))
        return tuple(plan)

    def sparse_apply(self, batch, sel, selv, plan):
        """Successor rows of a compacted enabled worklist.

        ``batch`` [C, W] chunk states; ``sel`` [VC] flat candidate ids
        (lane * A + cand) with the drop value C*A past the enabled
        prefix; ``selv`` = sel < C*A; ``plan`` the static per-group
        budgets from sparse_plan. Returns (flatc [VC, W], apply_ovf):
        bit-identical to the dense ``flatp[sel]`` gather for every
        in-budget worklist lane (drop lanes select a zeros row, exactly
        as the dense path's appended pad row). Lanes of a group past
        its budget also land on the zeros row, with ``apply_ovf`` set —
        the engines fold it into the overflow abort, so no surviving
        wave ever reads one."""
        import jax

        C, W = batch.shape
        A = self.A
        groups = self.sparse_groups()
        VC = sel.shape[0]
        total = sum(plan)
        group_of = np.zeros((A,), np.int32)
        for gi, g in enumerate(groups):
            group_of[g.off : g.off + g.n] = gi
        wg = jnp.where(
            selv,
            jnp.asarray(group_of)[jnp.clip(sel, 0, C * A - 1) % A],
            len(groups),
        )
        selp = jnp.concatenate([sel, jnp.full((1,), C * A, jnp.int32)])
        row = jnp.full((VC,), total, jnp.int32)  # default: the zeros row
        apply_ovf = jnp.zeros((), bool)
        blocks = []
        base = 0
        for gi, (g, eb) in enumerate(zip(groups, plan)):
            mask = wg == gi
            pos = jnp.cumsum(mask.astype(jnp.int32)) - 1
            apply_ovf = apply_ovf | (jnp.sum(mask.astype(jnp.int32)) > eb)
            # compact the group's worklist lanes to a dense [eb] prefix
            # (same confined one-hot scatter as the engines' valid-lane
            # compaction; destination eb is the drop slot)
            edst = jnp.where(mask, jnp.minimum(pos, eb), eb)
            idx = (
                jnp.full((eb + 1,), VC, jnp.int32)
                .at[edst]
                .set(jnp.arange(VC, dtype=jnp.int32))[:eb]
            )
            flat = selp[idx]  # [eb] flat candidate ids, drop -> C*A
            lane = jnp.clip(flat // A, 0, C - 1)
            k = jnp.clip(flat % A - g.off, 0, g.n - 1)
            srows = batch[lane]
            tbl = jnp.asarray(g.params)
            kern = self.kernel_for(g.name)
            args = [tbl[:, c][k] for c in range(tbl.shape[1])]
            blocks.append(
                jax.vmap(lambda s, *a, _k=kern: _k(s, *a)[1])(srows, *args)
            )
            row = jnp.where(
                mask & (pos < eb), base + jnp.minimum(pos, eb - 1), row
            )
            base += eb
        allb = jnp.concatenate(
            blocks + [jnp.zeros((1, W), jnp.int32)], axis=0
        )
        return allb[row], apply_ovf

    # ---------------- host-engine apply ----------------

    def host_apply(self, batch_np, flat_idx, block: int = 1024):
        """Successor rows for the enabled flat candidates ``flat_idx``
        (sorted, lane * A + cand) of one host chunk ``batch_np`` [C, W].

        Per-group jitted blocks of a fixed ``block`` size keep every
        call on a precompiled signature; a group larger than one block
        LOOPS instead of aborting (the host engine has no fixed device
        worklist), and the extra batches are reported so the engine can
        surface them as the ``expand_budget_ovf`` gauge. Returns
        (rows [len(flat_idx), W] np.int32, extra_batches)."""
        import jax

        A = self.A
        groups = self.sparse_groups()
        out = np.zeros((len(flat_idx), self.layout.W), np.int32)
        cands = flat_idx % A
        extra = 0
        for gi, g in enumerate(groups):
            m = (cands >= g.off) & (cands < g.off + g.n)
            if not m.any():
                continue
            idxs = flat_idx[m]
            srows = batch_np[idxs // A]
            ks = (idxs % A - g.off).astype(np.int32)
            fn = self._host_group_fn(gi, block)
            parts = []
            n = len(idxs)
            extra += (n - 1) // block
            for o in range(0, n, block):
                sb = srows[o : o + block]
                kb = ks[o : o + block]
                if len(sb) < block:
                    pad = block - len(sb)
                    sb = np.concatenate(
                        [sb, np.repeat(sb[-1:], pad, axis=0)]
                    )
                    kb = np.concatenate([kb, np.repeat(kb[-1:], pad)])
                parts.append(np.asarray(jax.device_get(fn(sb, kb))))
            out[m] = np.concatenate(parts, axis=0)[:n]
        return out, extra

    def _host_group_fn(self, gi: int, block: int):
        import jax

        cache = self.__dict__.setdefault("_host_group_cache", {})
        key = (gi, block)
        if key not in cache:
            g = self.sparse_groups()[gi]
            tbl = jnp.asarray(g.params)
            kern = self.kernel_for(g.name)

            @jax.jit
            def fn(srows, ks):
                args = [tbl[:, c][ks] for c in range(tbl.shape[1])]
                return jax.vmap(lambda s, *a: kern(s, *a)[1])(srows, *args)

            cache[key] = fn
        return cache[key]


def onehot_row(arr, i):
    """``arr[i]`` along axis 0 via a one-hot select.

    Per-instance dynamic row gathers under vmap serialize badly on the
    axon TPU backend when the indices are scattered (measured: the
    expansion kernel ran 118 ms/chunk on real frontiers vs 35 ms on
    zeros, round 5); the first axis here is the tiny server axis, so an
    S-term select is effectively free and data-independent."""
    S = arr.shape[0]
    oh = jnp.arange(S, dtype=jnp.int32) == i
    ohx = oh.reshape((S,) + (1,) * (arr.ndim - 1))
    return jnp.sum(jnp.where(ohx, arr, 0), axis=0)


def onehot_set(arr, i, val):
    """``arr.at[i].set(val)`` along axis 0 via a one-hot select (see
    onehot_row: dynamic-index row scatters serialize the same way)."""
    S = arr.shape[0]
    oh = jnp.arange(S, dtype=jnp.int32) == i
    ohx = oh.reshape((S,) + (1,) * (arr.ndim - 1))
    return jnp.where(ohx, val, arr)


def onehot_set2(arr, i, j, val):
    """``arr.at[i, j].set(val)`` on an [S, S] matrix via one-hot."""
    S = arr.shape[0]
    ohi = (jnp.arange(S, dtype=jnp.int32) == i)[:, None]
    ohj = (jnp.arange(S, dtype=jnp.int32) == j)[None, :]
    return jnp.where(ohi & ohj, val, arr)


def messages_are_valid_kernel(layout: Layout, packer):
    """MessagesAreValid — MessagePassing.tla:81-83: no record in the bag
    domain is self-addressed (msource = mdest). A checker self-check
    (SURVEY.md §5.2): the spec never sends to self, so a violation means
    the lowering (not the protocol) corrupted a key. Works for both the
    2-word BitPacker (msg_hi/msg_lo) and N-word WidePacker (msg_w*) bag
    layouts; batched over [..., W] states."""
    import jax.numpy as jnp

    from ..ops.packing import EMPTY, WidePacker

    wide = [f.name for f in layout.fields.values() if f.kind == "msg_word"]

    def kernel(states):
        if isinstance(packer, WidePacker):
            words = tuple(layout.get(states, n) for n in wide)
            occ = words[0] != EMPTY
            src = packer.unpack(words, "msource")
            dst = packer.unpack(words, "mdest")
        else:
            hi = layout.get(states, "msg_hi")
            lo = layout.get(states, "msg_lo")
            occ = hi != EMPTY
            src = packer.unpack(hi, lo, "msource")
            dst = packer.unpack(hi, lo, "mdest")
        return ~jnp.any(occ & (src == dst), axis=-1)

    return kernel
