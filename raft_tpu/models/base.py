"""State-vector layout machinery shared by all spec lowerings.

Every spec variant lowers its TLA+ variables to a single flat ``int32[W]``
vector per state. The layout records, per field, the *kind* of the field —
how it transforms under a permutation of the server set — which lets the
generic symmetry canonicalizer (ops/symmetry.py) serve every variant.

Field ordering convention: all VIEW fields first, aux (VIEW-excluded)
fields last, so the VIEW projection (``Raft.tla:115`` excludes
``acked/electionCtr/restartCtr``) is the contiguous prefix
``vec[:layout.view_len]``.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax.numpy as jnp

import numpy as np

# Field kinds and their transformation under a server permutation sigma
# (sigma maps old server index -> new server index):
#   scalar           unaffected
#   per_server       shape (S, ...): row r moves to row sigma(r)
#   per_server_val   shape (S,), values in 0..S with 0 = Nil: rows move AND
#                    values remap v -> sigma(v-1)+1
#   server_bitmask   shape (S,), each element a bitmask over servers: rows
#                    move AND bit j moves to bit sigma(j)
#   per_server_pair  shape (S, S): new[sigma(a), sigma(b)] = old[a, b]
#   msg_hi/msg_lo/   shape (M,): the message bag; server-valued fields inside
#   msg_cnt          the packed key remap, then slots re-sort
#   msg_word         shape (M,): one word of an N-word bag key (WidePacker);
#                    declared in word order, word 0 first (sort-major)
#   aux              VIEW-excluded scalar/vector (must come last)
KINDS = (
    "scalar",
    "per_server",
    "per_server_val",
    "server_bitmask",
    "per_server_pair",
    "msg_hi",
    "msg_lo",
    "msg_cnt",
    "msg_word",
    "aux",
)


@dataclass(frozen=True)
class Field:
    name: str
    kind: str
    shape: tuple[int, ...]
    offset: int

    @property
    def size(self) -> int:
        return int(math.prod(self.shape)) if self.shape else 1


class Layout:
    def __init__(self, n_servers: int):
        self.n_servers = n_servers
        self.fields: dict[str, Field] = {}
        self.W = 0
        self.view_len: int | None = None  # set when the first aux field lands

    def add(self, name: str, kind: str, shape: tuple[int, ...] = ()) -> Field:
        if kind not in KINDS:
            raise ValueError(f"unknown kind {kind}")
        if name in self.fields:
            raise ValueError(f"duplicate field {name}")
        if kind == "aux":
            if self.view_len is None:
                self.view_len = self.W
        elif self.view_len is not None:
            raise ValueError("non-aux field added after aux fields")
        f = Field(name, kind, shape, self.W)
        self.fields[name] = f
        self.W += f.size
        return f

    def finish(self):
        if self.view_len is None:
            self.view_len = self.W
        return self

    def sl(self, name: str) -> slice:
        f = self.fields[name]
        return slice(f.offset, f.offset + f.size)

    def get(self, vec, name: str):
        """Slice field `name` out of a [..., W] vector, reshaped to its shape."""
        f = self.fields[name]
        out = vec[..., f.offset : f.offset + f.size]
        if f.shape:
            return out.reshape(vec.shape[:-1] + f.shape)
        return out[..., 0]

    def zeros(self, batch: tuple[int, ...] = ()) -> np.ndarray:
        return np.zeros(batch + (self.W,), dtype=np.int32)


class ActionLabelMixin:
    """Human-readable labels for expansion candidates, shared by every
    spec lowering.

    Subclass contract: ``self.bindings`` (the candidate table of
    ``(kernel_name, binding_tuple)`` pairs) and ``self.ACTION_NAMES``
    (the Next-disjunct rank -> action-name table; index == the rank
    that ``_expand1`` reports). Fused ``HandleMessage`` kernels resolve
    their disjunct at run time, so the label comes from the fired rank;
    every other kernel is named by its binding."""

    ACTION_NAMES: list[str]

    def action_label(self, rank: int, cand: int) -> str:
        name, binding = self.bindings[cand]
        if name == "HandleMessage":
            return f"{self.ACTION_NAMES[rank]}(slot {binding[0]})"
        return f"{name}{binding}"


def onehot_row(arr, i):
    """``arr[i]`` along axis 0 via a one-hot select.

    Per-instance dynamic row gathers under vmap serialize badly on the
    axon TPU backend when the indices are scattered (measured: the
    expansion kernel ran 118 ms/chunk on real frontiers vs 35 ms on
    zeros, round 5); the first axis here is the tiny server axis, so an
    S-term select is effectively free and data-independent."""
    S = arr.shape[0]
    oh = jnp.arange(S, dtype=jnp.int32) == i
    ohx = oh.reshape((S,) + (1,) * (arr.ndim - 1))
    return jnp.sum(jnp.where(ohx, arr, 0), axis=0)


def onehot_set(arr, i, val):
    """``arr.at[i].set(val)`` along axis 0 via a one-hot select (see
    onehot_row: dynamic-index row scatters serialize the same way)."""
    S = arr.shape[0]
    oh = jnp.arange(S, dtype=jnp.int32) == i
    ohx = oh.reshape((S,) + (1,) * (arr.ndim - 1))
    return jnp.where(ohx, val, arr)


def onehot_set2(arr, i, j, val):
    """``arr.at[i, j].set(val)`` on an [S, S] matrix via one-hot."""
    S = arr.shape[0]
    ohi = (jnp.arange(S, dtype=jnp.int32) == i)[:, None]
    ohj = (jnp.arange(S, dtype=jnp.int32) == j)[None, :]
    return jnp.where(ohi & ohj, val, arr)


def messages_are_valid_kernel(layout: Layout, packer):
    """MessagesAreValid — MessagePassing.tla:81-83: no record in the bag
    domain is self-addressed (msource = mdest). A checker self-check
    (SURVEY.md §5.2): the spec never sends to self, so a violation means
    the lowering (not the protocol) corrupted a key. Works for both the
    2-word BitPacker (msg_hi/msg_lo) and N-word WidePacker (msg_w*) bag
    layouts; batched over [..., W] states."""
    import jax.numpy as jnp

    from ..ops.packing import EMPTY, WidePacker

    wide = [f.name for f in layout.fields.values() if f.kind == "msg_word"]

    def kernel(states):
        if isinstance(packer, WidePacker):
            words = tuple(layout.get(states, n) for n in wide)
            occ = words[0] != EMPTY
            src = packer.unpack(words, "msource")
            dst = packer.unpack(words, "mdest")
        else:
            hi = layout.get(states, "msg_hi")
            lo = layout.get(states, "msg_lo")
            occ = hi != EMPTY
            src = packer.unpack(hi, lo, "msource")
            dst = packer.unpack(hi, lo, "mdest")
        return ~jnp.any(occ & (src == dst), axis=-1)

    return kernel
