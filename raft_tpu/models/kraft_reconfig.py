"""KRaftWithReconfig checker parameters + backend dispatch.

Reference: ``/root/reference/specifications/pull-raft/
KRaftWithReconfig.tla`` (1,918 lines) — the dynamic-server-universe spec.
The full semantics are implemented in
``oracle/kraft_reconfig_oracle.py`` (the CHECKER=oracle backend and the
spec's own prescribed simulation mode, ``KRaftWithReconfig.cfg:5`` "too
big for brute force, only simulation").

The vectorized TPU lowering needs fixed identity slots (MaxSpawnedServers
many, with an alive mask — SURVEY.md §7.2 "dynamic server universe") plus
a data-dependent symmetry canonicalization (host permutations re-sort the
slot table), and lands as its own milestone; until then the registry
entry dispatches this spec to the oracle backends and reports a clear
error for the device BFS path.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class KRaftReconfigParams:
    n_hosts: int
    n_values: int
    init_cluster_size: int
    min_cluster_size: int
    max_cluster_size: int
    max_elections: int
    max_restarts: int
    max_values_per_epoch: int
    max_add_reconfigs: int
    max_remove_reconfigs: int
    max_spawned_servers: int


class KRaftReconfigSpec:
    """Backendless spec descriptor: names + invariant table for cfg
    validation; the oracle carries the executable semantics."""

    name = "KRaftWithReconfig"

    INVARIANT_NAMES = (
        "NoIllegalState",
        "NoLogDivergence",
        "StatesMatchRoles",
        "NeverTwoLeadersInSameEpoch",
        "LeaderHasAllAckedValues",
        "MessagesAreValid",
        "TestInv",
    )

    def __init__(self, params: KRaftReconfigParams, server_names=None,
                 value_names=None):
        self.p = params
        self.server_names = list(
            server_names or [f"h{i+1}" for i in range(params.n_hosts)]
        )
        self.value_names = list(
            value_names or [f"v{i+1}" for i in range(params.n_values)]
        )
        # dict-shaped like the device models' invariant tables so the
        # registry's unknown-invariant check works unchanged
        self.invariants = {n: None for n in self.INVARIANT_NAMES}
