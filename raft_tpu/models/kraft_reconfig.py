"""TPU lowering of KRaftWithReconfig — the dynamic-server-universe spec.

Reference: ``/root/reference/specifications/pull-raft/KRaftWithReconfig.tla``
(1,918 lines, 22-action Next at :1730-1756) + the ``MessagePassing.tla`` it
EXTENDS. Every action kernel cites the TLA+ lines it lowers; the
independent Python interpreter (``oracle/kraft_reconfig_oracle.py``) is the
differential ground truth.

Lowering strategy (SURVEY.md §7.2 "dynamic server universe"):
  - the growing ``servers`` universe (``StartNewServer:1492`` mints fresh
    ``[host, diskId]`` identities bounded by MaxSpawnedServers) becomes
    ``NS = MaxSpawnedServers`` fixed identity SLOTS with a ``used`` mask;
    a new identity takes the next free slot, so slot order = creation
    order and — because diskId equals the creation counter — the slot of
    an identity is a function of the identity itself: initial ``(h, 0)``
    sits in slot h, spawned ``(h, d)`` in slot ``ics + d - 1``;
  - all server references (leader/votedFor/msource/mdest/member sets/...)
    are slot indices (0 = Nil / bitmasks over slots);
  - ``endOffset``'s domain is itself dynamic state (extended by
    ``MaybeSwitchConfigurations:767-771`` and ``AcceptJoinRequest:1581``)
    and is carried as an ``eo_dom`` bitmask next to the value matrix;
  - log entries ``(command, epoch, value)`` with value = v |
    (id, members) | (id, identity, members) flatten into six fixed lanes
    per entry (cmd/epoch/val/cfgid/who/members);
  - messages pack into N-word WidePacker keys (correlation embeds the
    originating FetchRequest with source/dest implied-swapped, like the
    KRaft lowering);
  - SYMMETRY (``symmHostsAndValues:462-463``) permutes HOSTS, not slots,
    so the canonical fingerprint is data-dependent: for each (sigma, tau)
    remap host/value fields, re-sort slots by permuted identity
    (reproducing the oracle's sorted-identity view order), remap slot
    references through the sort, re-sort the message bag, hash, and take
    the min (``SlotCanonicalizer``).

Faithfully-reproduced reference quirks (same as the oracle):
  - ``RestartWithoutState:906-924`` is never enabled (its guard :913
    compares a STATE to the ROLE value Voter) — lowered as nothing;
  - ``_addReconfigCtr`` is only ever gated on (``SendJoinRequest:1526``),
    never incremented, so it is a constant 0 and not stored;
  - ``HandleRejectJoinResponse:1643-1674`` only reaches its Discard arm.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import bag
from ..ops.hashing import hash_lanes
from ..ops.packing import EMPTY, WidePacker, bits_for
from .base import ActionLabelMixin, Layout, SparseExpandMixin

# server states (KRaftWithReconfig.tla:354-360). UNATTACHED = 0 doubles as
# the all-zero unused-slot filler; every kernel gates on `used`.
UNATTACHED, VOTED, FOLLOWER, CANDIDATE, LEADER, RESIGNED, DEAD, ILLEGAL = range(8)
# roles (:349-351); 0 = unused slot
R_NONE, R_VOTER, R_OBSERVER, R_DEAD = range(4)
NIL = 0  # leader/votedFor Nil; slot i stored as i+1
ACK_NIL, ACK_FALSE, ACK_TRUE = 0, 1, 2

# mtype; BeginQuorumResponse is never sent in this spec (no reply arm)
RVREQ, RVRESP, BQREQ, FETCHREQ, FETCHRESP, JOINREQ, JOINRESP = range(1, 8)
# merror (:375-376); 0 = Nil. ReconfigInProgress/LeaderNotReady are
# deliberately never answered (:1596-1604) so they never appear in a key.
E_NONE, E_FENCED, E_NOTLEADER, E_UNKNOWN_LEADER, E_UNKNOWN_MEMBER, E_ALREADY_MEMBER = range(6)
# mresult; 0 = absent
R_RESULT_NONE, R_OK, R_NOTOK, R_DIVERGING = range(4)
# log entry commands (:363-366); 0 = empty lane
C_NONE, C_INIT, C_APPEND, C_ADD, C_REMOVE = range(5)

# Next-disjunct order (:1730-1756) for trace labels
(
    KR_RESTART,
    KR_REQUESTVOTE,
    KR_HANDLE_RVREQ,
    KR_HANDLE_RVRESP,
    KR_BECOMELEADER,
    KR_CLIENTREQUEST,
    KR_REJECT_FETCH,
    KR_DIVERGING_FETCH,
    KR_ACCEPT_FETCH_VOTER,
    KR_ACCEPT_FETCH_OBSERVER,
    KR_ACCEPT_BQREQ,
    KR_SENDFETCH,
    KR_HANDLE_FETCH_OK,
    KR_HANDLE_FETCH_DIV,
    KR_HANDLE_FETCH_NONSUCCESS,
    KR_STARTNEWSERVER,
    KR_SENDJOIN,
    KR_ACCEPT_JOIN,
    KR_REJECT_JOIN,
    KR_HANDLE_REJECT_JOIN,
    KR_HANDLE_REMOVE,
) = range(21)

ACTION_NAMES = [
    "RestartWithState",
    "RequestVote",
    "HandleRequestVoteRequest",
    "HandleRequestVoteResponse",
    "BecomeLeader",
    "ClientRequest",
    "RejectFetchRequest",
    "DivergingFetchRequest",
    "AcceptFetchRequestFromVoter",
    "AcceptFetchRequestFromObserver",
    "AcceptBeginQuorumRequest",
    "SendFetchRequest",
    "HandleSuccessFetchResponse",
    "HandleDivergingFetchResponse",
    "HandleNonSuccessFetchResponse",
    "StartNewServer",
    "SendJoinRequest",
    "AcceptJoinRequest",
    "RejectJoinRequest",
    "HandleRejectJoinResponse",
    "HandleRemoveRequest",
]

STATE_NAMES = {
    UNATTACHED: "Unattached", VOTED: "Voted", FOLLOWER: "Follower",
    CANDIDATE: "Candidate", LEADER: "Leader", RESIGNED: "Resigned",
    DEAD: "DeadNoState", ILLEGAL: "IllegalState",
}
ROLE_NAMES = {R_VOTER: "Voter", R_OBSERVER: "Observer", R_DEAD: "DeadNoState"}
MTYPE_NAMES = {
    RVREQ: "RequestVoteRequest", RVRESP: "RequestVoteResponse",
    BQREQ: "BeginQuorumRequest", FETCHREQ: "FetchRequest",
    FETCHRESP: "FetchResponse", JOINREQ: "JoinRequest",
    JOINRESP: "JoinResponse",
}
ERROR_NAMES = {
    E_NONE: None, E_FENCED: "FencedLeaderEpoch", E_NOTLEADER: "NotLeader",
    E_UNKNOWN_LEADER: "UnknownLeader", E_UNKNOWN_MEMBER: "UnknownMember",
    E_ALREADY_MEMBER: "AlreadyMember",
}
RESULT_NAMES = {R_OK: "Ok", R_NOTOK: "NotOk", R_DIVERGING: "Diverging"}
CMD_NAMES = {
    C_INIT: "InitClusterCommand", C_APPEND: "AppendCommand",
    C_ADD: "AddServerCommand", C_REMOVE: "RemoveServerCommand",
}


@dataclass(frozen=True)
class KRaftReconfigParams:
    n_hosts: int
    n_values: int
    init_cluster_size: int
    min_cluster_size: int
    max_cluster_size: int
    max_elections: int
    max_restarts: int
    max_values_per_epoch: int
    max_add_reconfigs: int
    max_remove_reconfigs: int
    max_spawned_servers: int
    msg_slots: int = 40

    @property
    def max_epoch(self) -> int:
        return 1 + self.max_elections

    @property
    def max_log(self) -> int:
        # values (bounded per epoch) + InitClusterCommand + config commands
        return (
            self.max_values_per_epoch * self.max_epoch
            + 1
            + self.max_add_reconfigs
            + self.max_remove_reconfigs
        )

    @property
    def max_cfg_id(self) -> int:
        return 1 + self.max_add_reconfigs + self.max_remove_reconfigs


def _build_layout(p: KRaftReconfigParams) -> Layout:
    NS, V, L, M, E = (p.max_spawned_servers, p.n_values, p.max_log,
                      p.msg_slots, p.max_epoch)
    lay = Layout(NS)
    # VIEW (:460) = everything except the _-prefixed aux vars, including
    # acked. Identity slots first (host/diskId/used encode `servers`).
    lay.add("host", "per_server", (NS,))
    lay.add("diskId", "per_server", (NS,))
    lay.add("used", "per_server", (NS,))
    lay.add("role", "per_server", (NS,))
    lay.add("state", "per_server", (NS,))
    lay.add("currentEpoch", "per_server", (NS,))
    lay.add("leader", "per_server_val", (NS,))
    lay.add("votedFor", "per_server_val", (NS,))
    # pendingFetch (:409) decomposed; pf_active is the non-Nil flag
    # (mepoch can legitimately be 0 for a spawned server's first fetch)
    lay.add("pf_active", "per_server", (NS,))
    lay.add("pf_epoch", "per_server", (NS,))
    lay.add("pf_offset", "per_server", (NS,))
    lay.add("pf_lastepoch", "per_server", (NS,))
    lay.add("pf_dest", "per_server_val", (NS,))
    lay.add("pf_observer", "per_server", (NS,))
    lay.add("votesGranted", "server_bitmask", (NS,))
    # config cache (:397): (id, members, committed) per server
    lay.add("cfg_id", "per_server", (NS,))
    lay.add("cfg_members", "server_bitmask", (NS,))
    lay.add("cfg_committed", "per_server", (NS,))
    lay.add("eo_dom", "server_bitmask", (NS,))  # endOffset domain mask
    lay.add("endOffset", "per_server_pair", (NS, NS))
    lay.add("log_cmd", "per_server", (NS, L))
    lay.add("log_epoch", "per_server", (NS, L))
    lay.add("log_val", "per_server", (NS, L))
    lay.add("log_cfgid", "per_server", (NS, L))
    lay.add("log_who", "per_server", (NS, L))  # slot+1 of added/removed id
    lay.add("log_members", "per_server", (NS, L))  # member bitmask
    lay.add("log_len", "per_server", (NS,))
    lay.add("highWatermark", "per_server", (NS,))
    lay.add("acked", "scalar", (V,))  # in VIEW (:460)
    n_words = _build_packer(p).n_words
    for k in range(n_words):
        lay.add(f"msg_w{k}", "msg_word", (M,))
    lay.add("msg_cnt", "msg_cnt", (M,))
    lay.add("electionCtr", "aux")
    lay.add("restartCtr", "aux")
    lay.add("removeCtr", "aux")
    lay.add("diskIdGen", "aux")
    lay.add("valueCtr", "aux", (E,))  # per-epoch value counter (:446)
    return lay.finish()


def _build_packer(p: KRaftReconfigParams) -> WidePacker:
    NS = p.max_spawned_servers
    eb = bits_for(p.max_epoch)
    sb = bits_for(NS - 1)  # slot index
    nb = bits_for(NS)  # nil-valued slot (0..NS)
    lb = bits_for(p.max_log)
    vb = bits_for(p.n_values)
    cb = bits_for(p.max_cfg_id)
    fields = [
        ("mtype", 3),
        ("mepoch", eb),
        ("msource", sb),
        ("mdest", sb),
        ("mlastLogEpoch", eb),  # RequestVoteRequest (:947-952)
        ("mlastLogOffset", lb),
        ("mleader", nb),
        ("mvoteGranted", 1),
        ("merror", 3),
        ("mresult", 2),
        ("mfetchOffset", lb),  # FetchRequest (:1155-1162)
        ("mlastFetchedEpoch", eb),
        ("mobserver", 1),
        ("mhwm", lb),
        ("nentries", 1),  # <=1 entry per response (:1306-1310)
        ("e_cmd", 3),  # entry = (command, epoch, value-parts)
        ("e_epoch", eb),
        ("e_val", vb),
        ("e_cfgid", cb),
        ("e_who", nb),
        ("e_members", NS),
        ("mdivergingEpoch", eb),  # Diverging response (:1236-1241)
        ("mdivergingEndOffset", lb),
        ("cepoch", eb),  # correlation = embedded FetchRequest (:1203 etc.);
        ("cfetchOffset", lb),  # its source/dest are implied (swapped)
        ("clastFetchedEpoch", eb),
        ("cobserver", 1),
    ]
    total = sum(b for _n, b in fields)
    for n_words in range(max(1, (total + 29) // 30), 8):
        try:
            return WidePacker(fields, n_words)
        except ValueError:
            continue
    raise ValueError("message schema does not fit in 7 words")


def cached_model(params: "KRaftReconfigParams") -> "KRaftReconfigModel":
    return _cached_model(params)


class KRaftReconfigModel(SparseExpandMixin, ActionLabelMixin):
    """Vectorized successor/invariant kernels for one constants binding."""

    name = "KRaftWithReconfig"
    ACTION_NAMES = ACTION_NAMES

    def __init__(self, params: KRaftReconfigParams, server_names=None,
                 value_names=None):
        self.p = params
        self.layout = _build_layout(params)
        self.packer = _build_packer(params)
        NS, V, H, M = (params.max_spawned_servers, params.n_values,
                       params.n_hosts, params.msg_slots)
        self.NS = NS
        self.server_names = list(server_names or [f"h{i+1}" for i in range(H)])
        self.value_names = list(value_names or [f"v{i+1}" for i in range(V)])

        # candidate table: non-receipt disjuncts in Next order (:1730-1756),
        # receipt disjuncts fused per message slot at the end
        self.bindings: list[tuple[str, tuple]] = []
        self._pairs = [(i, j) for i in range(NS) for j in range(NS) if i != j]
        for i in range(NS):
            self.bindings.append(("RestartWithState", (i,)))
        for i in range(NS):
            self.bindings.append(("RequestVote", (i,)))
        for i in range(NS):
            self.bindings.append(("BecomeLeader", (i,)))
        for i in range(NS):
            for v in range(V):
                self.bindings.append(("ClientRequest", (i, v)))
        for ij in self._pairs:
            self.bindings.append(("SendFetchRequest", ij))
        for h in range(H):
            for j in range(NS):
                self.bindings.append(("StartNewServer", (h, j)))
        for ij in self._pairs:
            self.bindings.append(("SendJoinRequest", ij))
        for i in range(NS):
            for r in range(NS):
                self.bindings.append(("HandleRemoveRequest", (i, r)))
        for m in range(M):
            self.bindings.append(("HandleMessage", (m,)))
        self.A = len(self.bindings)

        self.expand = jax.jit(jax.vmap(self._expand1))
        self.invariants = {
            "NoIllegalState": jax.jit(self._inv_no_illegal),
            "NoLogDivergence": jax.jit(self._inv_no_log_divergence),
            "StatesMatchRoles": jax.jit(self._inv_states_match_roles),
            "NeverTwoLeadersInSameEpoch": jax.jit(self._inv_never_two_leaders),
            "LeaderHasAllAckedValues": jax.jit(self._inv_leader_has_acked),
            "MessagesAreValid": jax.jit(self._inv_messages_are_valid),
            "TestInv": jax.jit(lambda s: jnp.ones(s.shape[:-1], dtype=bool)),
        }

        # temporal properties (:1810-1839), checker/liveness.py:
        # ValuesNotStuck = \A v : []<> CommittedValueOrNothing(v);
        # ReconfigurationNotStuck = \A cid in 1..(MaxAdd+MaxRemove) :
        # []<> ConfigAllOrNothing(cid)
        self.liveness = {
            "ValuesNotStuck": [
                (self.value_names[v], None,
                 jax.jit(partial(self._live_committed_value_or_nothing, v)))
                for v in range(V)
            ],
            "ReconfigurationNotStuck": [
                (f"config_id={cid}", None,
                 jax.jit(partial(self._live_config_all_or_nothing, cid)))
                for cid in range(
                    1, params.max_add_reconfigs + params.max_remove_reconfigs + 1
                )
            ],
        }

    def make_canonicalizer(self, symmetry: bool = True, seed: int = 0) -> "SlotCanonicalizer":
        return SlotCanonicalizer(self, symmetry, seed=seed)

    # ---------------- field access helpers ----------------

    def _dec(self, s):
        g = self.layout.get
        return {f: g(s, f) for f in self.layout.fields}

    def _asm(self, d, **updates):
        parts = []
        for name, f in self.layout.fields.items():
            arr = updates.get(name, d[name])
            arr = jnp.asarray(arr, jnp.int32)
            parts.append(arr.reshape(-1) if f.shape else arr.reshape(1))
        return jnp.concatenate(parts)

    def _pack(self, **vals):
        return tuple(jnp.asarray(w, jnp.int32) for w in self.packer.pack(**vals))

    def _words(self, d):
        return [d[f"msg_w{k}"] for k in range(self.packer.n_words)]

    def _wupd(self, words, cnt):
        upd = {f"msg_w{k}": words[k] for k in range(self.packer.n_words)}
        upd["msg_cnt"] = cnt
        return upd

    def _popcount(self, mask):
        return jnp.sum((mask >> jnp.arange(self.NS, dtype=jnp.int32)) & 1, axis=-1)

    @staticmethod
    def _last_epoch(d, i):
        """LastEpoch(log[i]) — :498."""
        ll = d["log_len"][i]
        return jnp.where(ll > 0, d["log_epoch"][i][jnp.clip(ll - 1, 0)], 0)

    # -------- transition machine (:599-715) --------
    # Triples are (state, epoch, leader_enc) int32 with leader_enc 0..NS.

    def _has_consistent_leader(self, d, i, leader_enc, epoch):
        """HasConsistentLeader — :599-616 (resigned/observer carve-outs)."""
        cur, st_i, led = d["currentEpoch"][i], d["state"][i], d["leader"][i]
        self_case = jnp.where(
            (cur == epoch)
            & ((d["role"][i] == R_OBSERVER) | (st_i == RESIGNED)),
            True,
            st_i == LEADER,
        )
        other = (
            (epoch != cur) | (leader_enc == NIL) | (led == NIL)
            | (led == leader_enc)
        )
        return jnp.where(leader_enc == i + 1, self_case, other)

    def _to_follower(self, d, i, leader_enc, epoch):
        """TransitionToFollower — :645-653 (illegal arm folded in)."""
        ill = (d["currentEpoch"][i] == epoch) & (
            (d["state"][i] == FOLLOWER) | (d["state"][i] == LEADER)
        )
        return (
            jnp.where(ill, ILLEGAL, FOLLOWER),
            jnp.where(ill, 0, epoch),
            jnp.where(ill, 0, leader_enc),
        )

    def _maybe_transition(self, d, i, leader_enc, epoch):
        """MaybeTransition — :656-675 (case 3 adds leaderId # i)."""
        cur, st_i, led = d["currentEpoch"][i], d["state"][i], d["leader"][i]
        hcl = self._has_consistent_leader(d, i, leader_enc, epoch)
        tf = self._to_follower(d, i, leader_enc, epoch)
        una = (jnp.int32(UNATTACHED), epoch, jnp.int32(NIL))
        noop = (st_i, cur, led)
        ill = (jnp.int32(ILLEGAL), jnp.int32(0), jnp.int32(NIL))
        c2 = epoch > cur
        c2_pick = jnp.where(leader_enc == NIL, 1, 2)  # 1=unattached 2=follower
        c3 = (leader_enc != NIL) & (led == NIL) & (leader_enc != i + 1)
        sel = jnp.where(~hcl, 0, jnp.where(c2, c2_pick, jnp.where(c3, 2, 3)))
        out = []
        for k in range(3):
            out.append(
                jnp.where(
                    sel == 0, ill[k],
                    jnp.where(sel == 1, una[k], jnp.where(sel == 2, tf[k], noop[k])),
                )
            )
        return tuple(out)

    def _mhcr(self, d, i, leader_enc, epoch, err):
        """MaybeHandleCommonResponse — :683-715.
        Returns (state, epoch, leader_enc, handled)."""
        cur, st_i, led = d["currentEpoch"][i], d["state"][i], d["leader"][i]
        mt = self._maybe_transition(d, i, leader_enc, epoch)
        c_stale = epoch < cur
        c_trans = (epoch > cur) | (err == E_FENCED) | (err == E_NOTLEADER)
        c_follow = (epoch == cur) & (leader_enc != NIL) & (led == NIL)
        sel = jnp.where(c_stale, 0, jnp.where(c_trans, 1, jnp.where(c_follow, 2, 3)))
        fol = (jnp.int32(FOLLOWER), cur, leader_enc)
        noop = (st_i, cur, led)
        out = []
        for k in range(3):
            out.append(
                jnp.where(
                    sel == 0, noop[k],
                    jnp.where(sel == 1, mt[k], jnp.where(sel == 2, fol[k], noop[k])),
                )
            )
        handled = jnp.where(
            sel == 2, err != E_NONE, (sel == 0) | (sel == 1)
        )
        return out[0], out[1], out[2], handled

    def _handle_message_part2(
        self, s, d, m, u, recv, mtype, mepoch, src, dst, cnt_disc, handled,
        mh_st, mh_ep, mh_ld, branches,
    ):
        """FetchResponse + Join receipt branches and the final select."""
        p, NS, L = self.p, self.NS, self.p.max_log
        is_fresp = recv & (mtype == FETCHRESP)
        # correlation match: pendingFetch[dst] = m.correlation (:1390); the
        # request's msource is dst (implied) and mdest is the responder src
        corr = (
            (d["pf_active"][dst] > 0)
            & (d["pf_epoch"][dst] == u("cepoch"))
            & (d["pf_offset"][dst] == u("cfetchOffset"))
            & (d["pf_lastepoch"][dst] == u("clastFetchedEpoch"))
            & (d["pf_observer"][dst] == u("cobserver"))
            & (d["pf_dest"][dst] == src + 1)
        )
        mres = u("mresult")
        mhwm = u("mhwm")
        used_mask = self._used_mask(d)

        def maybe_switch(upd, cfg_id_v, cfg_members_v, cfg_committed_v,
                         log_cmd_v, log_epoch_v, log_val_v, log_cfgid_v,
                         log_who_v, log_members_v, log_len_v):
            """MaybeSwitchConfigurations (:753-771): leader/config update,
            Voter<->Observer flip on membership change, endOffset domain
            padded to all servers. Applies to row `dst`; the new-state
            (from _mhcr) supplies leader and the default state."""
            member = ((cfg_members_v >> dst) & 1) > 0
            was_voter = d["role"][dst] == R_VOTER
            was_obs = d["role"][dst] == R_OBSERVER
            demote = was_voter & ~member
            promote = was_obs & member
            new_role = jnp.where(
                demote, R_OBSERVER, jnp.where(promote, R_VOTER, d["role"][dst])
            )
            new_state = jnp.where(demote | promote, FOLLOWER, mh_st)
            upd["leader"] = d["leader"].at[dst].set(mh_ld)
            upd["cfg_id"] = d["cfg_id"].at[dst].set(cfg_id_v)
            upd["cfg_members"] = d["cfg_members"].at[dst].set(cfg_members_v)
            upd["cfg_committed"] = d["cfg_committed"].at[dst].set(cfg_committed_v)
            upd["role"] = d["role"].at[dst].set(new_role)
            upd["state"] = d["state"].at[dst].set(new_state)
            upd["eo_dom"] = d["eo_dom"].at[dst].set(d["eo_dom"][dst] | used_mask)
            upd["log_cmd"] = d["log_cmd"].at[dst].set(log_cmd_v)
            upd["log_epoch"] = d["log_epoch"].at[dst].set(log_epoch_v)
            upd["log_val"] = d["log_val"].at[dst].set(log_val_v)
            upd["log_cfgid"] = d["log_cfgid"].at[dst].set(log_cfgid_v)
            upd["log_who"] = d["log_who"].at[dst].set(log_who_v)
            upd["log_members"] = d["log_members"].at[dst].set(log_members_v)
            upd["log_len"] = d["log_len"].at[dst].set(log_len_v)
            return upd

        # --- HandleSuccessFetchResponse (:1383-1409)
        b_ok = is_fresp & ~handled & corr & (mres == R_OK)
        app = u("nentries") > 0
        ll_dst = d["log_len"][dst]
        apos = jnp.clip(ll_dst, 0, L - 1)
        ok_ovf = b_ok & app & (ll_dst >= L)
        nl_cmd = jnp.where(
            app, d["log_cmd"][dst].at[apos].set(u("e_cmd")), d["log_cmd"][dst]
        )
        nl_ep = jnp.where(
            app, d["log_epoch"][dst].at[apos].set(u("e_epoch")), d["log_epoch"][dst]
        )
        nl_val = jnp.where(
            app, d["log_val"][dst].at[apos].set(u("e_val")), d["log_val"][dst]
        )
        nl_cfgid = jnp.where(
            app, d["log_cfgid"][dst].at[apos].set(u("e_cfgid")), d["log_cfgid"][dst]
        )
        nl_who = jnp.where(
            app, d["log_who"][dst].at[apos].set(u("e_who")), d["log_who"][dst]
        )
        nl_members = jnp.where(
            app,
            d["log_members"][dst].at[apos].set(u("e_members")),
            d["log_members"][dst],
        )
        nl_len = ll_dst + app.astype(jnp.int32)
        ok_cfg_off = self._most_recent_reconfig(d, nl_cmd, nl_len)
        b_ok &= ok_cfg_off > 0  # log always has a config cmd when reachable
        ok_lane = jnp.clip(ok_cfg_off - 1, 0, L - 1)
        upd8 = maybe_switch(
            dict(msg_cnt=cnt_disc),
            nl_cfgid[ok_lane], nl_members[ok_lane],
            (mhwm >= ok_cfg_off).astype(jnp.int32),
            nl_cmd, nl_ep, nl_val, nl_cfgid, nl_who, nl_members, nl_len,
        )
        upd8["highWatermark"] = d["highWatermark"].at[dst].set(mhwm)
        upd8 = {**upd8, **self._pf_clear_upd(d, dst)}
        s_ok = self._asm(d, **upd8)

        # --- HandleDivergingFetchResponse (:1419-1445): truncate, refresh
        # config from the truncated log, hwm NOT updated
        b_divr = is_fresp & ~handled & corr & (mres == R_DIVERGING)
        hco = self._highest_common_offset(
            d, dst, u("mdivergingEndOffset"), u("mdivergingEpoch")
        )
        keep = jnp.arange(L, dtype=jnp.int32) < hco
        tl_cmd = jnp.where(keep, d["log_cmd"][dst], 0)
        tl_ep = jnp.where(keep, d["log_epoch"][dst], 0)
        tl_val = jnp.where(keep, d["log_val"][dst], 0)
        tl_cfgid = jnp.where(keep, d["log_cfgid"][dst], 0)
        tl_who = jnp.where(keep, d["log_who"][dst], 0)
        tl_members = jnp.where(keep, d["log_members"][dst], 0)
        dv_cfg_off = self._most_recent_reconfig(d, tl_cmd, hco)
        b_divr &= dv_cfg_off > 0
        dv_lane = jnp.clip(dv_cfg_off - 1, 0, L - 1)
        upd9 = maybe_switch(
            dict(msg_cnt=cnt_disc),
            tl_cfgid[dv_lane], tl_members[dv_lane],
            (mhwm >= dv_cfg_off).astype(jnp.int32),
            tl_cmd, tl_ep, tl_val, tl_cfgid, tl_who, tl_members, hco,
        )
        upd9 = {**upd9, **self._pf_clear_upd(d, dst)}
        s_divr = self._asm(d, **upd9)

        # --- HandleNonSuccessFetchResponse (:1459-1483)
        b_err = is_fresp & handled & corr
        upd10 = dict(
            state=d["state"].at[dst].set(mh_st),
            currentEpoch=d["currentEpoch"].at[dst].set(mh_ep),
            leader=d["leader"].at[dst].set(mh_ld),
            role=jnp.where(
                u("merror") == E_UNKNOWN_MEMBER,
                d["role"].at[dst].set(R_OBSERVER),
                d["role"],
            ),
            msg_cnt=cnt_disc,
        )
        upd10 = {**upd10, **self._pf_clear_upd(d, dst)}
        s_err = self._asm(d, **upd10)

        # --- Join flow (:1524-1674)
        is_joinreq = recv & (mtype == JOINREQ)
        members = d["cfg_members"][dst]
        msize = self._popcount(members)
        # JoinCheck (:1551-1556)
        jc_notleader = d["state"][dst] != LEADER
        jc_already = ((members >> src) & 1) > 0
        jc_pending = d["cfg_committed"][dst] == 0
        jc_notready = ~self._leader_committed_in_epoch(d, dst)
        jc_ok = ~jc_notleader & ~jc_already & ~jc_pending & ~jc_notready

        # AcceptJoinRequest (:1558-1590)
        b_jacc = is_joinreq & (msize < p.max_cluster_size) & jc_ok
        pos = d["log_len"][dst]
        ja_ovf = b_jacc & (pos >= L)
        posc = jnp.clip(pos, 0, L - 1)
        new_len = pos + 1
        add_members = members | (jnp.int32(1) << src)
        jakey = self._pack(
            mtype=JOINRESP, mepoch=d["currentEpoch"][dst],
            mleader=d["leader"][dst], mresult=R_OK, merror=E_NONE,
            mdest=src, msource=dst,
        )
        wj, cj, _exj, ovfj = self._reply(d, m, jakey)
        updj = dict(
            log_cmd=d["log_cmd"].at[dst, posc].set(C_ADD),
            log_epoch=d["log_epoch"].at[dst, posc].set(d["currentEpoch"][dst]),
            log_cfgid=d["log_cfgid"].at[dst, posc].set(d["cfg_id"][dst] + 1),
            log_who=d["log_who"].at[dst, posc].set(src + 1),
            log_members=d["log_members"].at[dst, posc].set(add_members),
            log_len=d["log_len"].at[dst].set(new_len),
            cfg_id=d["cfg_id"].at[dst].set(d["cfg_id"][dst] + 1),
            cfg_members=d["cfg_members"].at[dst].set(add_members),
            cfg_committed=d["cfg_committed"].at[dst].set(
                (d["highWatermark"][dst] >= new_len).astype(jnp.int32)
            ),
            eo_dom=d["eo_dom"].at[dst].set(
                d["eo_dom"][dst] | (jnp.int32(1) << src)
            ),
            **self._wupd(wj, cj),
        )
        s_jacc = self._asm(d, **updj)

        # RejectJoinRequest (:1605-1623): only NotLeader/AlreadyMember are
        # answered; ReconfigInProgress/LeaderNotReady stay unanswered
        b_jrej = is_joinreq & (jc_notleader | (~jc_notleader & jc_already))
        jr_err = jnp.where(jc_notleader, E_NOTLEADER, E_ALREADY_MEMBER)
        jrkey = self._pack(
            mtype=JOINRESP, mepoch=d["currentEpoch"][dst],
            mleader=d["leader"][dst], mresult=R_NOTOK, merror=jr_err,
            mdest=src, msource=dst,
        )
        wr, cr, _exr, ovfr = self._reply(d, m, jrkey)
        s_jrej = self._asm(d, **self._wupd(wr, cr))

        # HandleRejectJoinResponse (:1643-1674): only the Discard arm is
        # reachable (the CASE tests mresult against ERROR values)
        b_jrr = (
            recv & (mtype == JOINRESP) & (d["role"][dst] == R_OBSERVER)
            & (mres == R_NOTOK)
        )
        s_jrr = self._asm(d, msg_cnt=cnt_disc)

        branches = branches + [
            (b_ok, s_ok, KR_HANDLE_FETCH_OK, ok_ovf),
            (b_divr, s_divr, KR_HANDLE_FETCH_DIV, jnp.asarray(False)),
            (b_err, s_err, KR_HANDLE_FETCH_NONSUCCESS, jnp.asarray(False)),
            (b_jacc, s_jacc, KR_ACCEPT_JOIN, (ja_ovf | ovfj) & b_jacc),
            (b_jrej, s_jrej, KR_REJECT_JOIN, ovfr & b_jrej),
            (b_jrr, s_jrr, KR_HANDLE_REJECT_JOIN, jnp.asarray(False)),
        ]
        valid = jnp.asarray(False)
        succ = s
        rank = jnp.int32(-1)
        ovf = jnp.asarray(False)
        for b, sb, rk, ob in branches:
            valid = valid | b
            succ = jnp.where(b, sb, succ)
            rank = jnp.where(b, jnp.int32(rk), rank)
            ovf = ovf | (b & ob)
        return valid, succ, rank, ovf

    # -------- log-position math (:498-576) --------

    def _end_offset_for_epoch(self, d, i, lfe):
        """EndOffsetForEpoch — :551-567."""
        L = self.p.max_log
        lanes = jnp.arange(L, dtype=jnp.int32)
        row = d["log_epoch"][i]
        mask = (lanes < d["log_len"][i]) & (row <= lfe)
        off = jnp.max(jnp.where(mask, lanes + 1, 0))
        ep = jnp.where(off > 0, row[jnp.clip(off - 1, 0)], 0)
        return off, ep

    def _highest_common_offset(self, d, i, end_off, epoch):
        """HighestCommonOffset — :521-539."""
        L = self.p.max_log
        lanes = jnp.arange(L, dtype=jnp.int32)
        row = d["log_epoch"][i]
        le = (row < epoch) | ((row == epoch) & (lanes + 1 <= end_off))
        mask = (lanes < d["log_len"][i]) & le
        return jnp.max(jnp.where(mask, lanes + 1, 0))

    def _valid_fetch_position(self, d, i, fetch_off, lfe):
        """ValidFetchPosition — :571-576."""
        off, ep = self._end_offset_for_epoch(d, i, lfe)
        zero = (fetch_off == 0) & (lfe == 0)
        return zero | ((fetch_off <= off) & (lfe == ep))

    # -------- config machinery (:718-777) --------

    def _most_recent_reconfig(self, d, log_cmd_row, log_len):
        """MostRecentReconfigEntry — :729-735: (offset, lane index) of the
        last config command; offset 0 if none (callers guard on that)."""
        L = self.p.max_log
        lanes = jnp.arange(L, dtype=jnp.int32)
        is_cfg = (
            (log_cmd_row == C_INIT) | (log_cmd_row == C_ADD)
            | (log_cmd_row == C_REMOVE)
        ) & (lanes < log_len)
        off = jnp.max(jnp.where(is_cfg, lanes + 1, 0))
        return off

    def _leader_committed_in_epoch(self, d, i):
        """LeaderHasCommittedOffsetsInCurrentEpoch — :774-777."""
        L = self.p.max_log
        lanes = jnp.arange(L, dtype=jnp.int32)
        return jnp.any(
            (lanes < d["log_len"][i])
            & (d["log_epoch"][i] == d["currentEpoch"][i])
            & (d["highWatermark"][i] >= lanes + 1)
        )

    # -------- send helpers (MessagePassing.tla) --------

    def _cond_put(self, words, cnt, key, do):
        """bag_put applied only where `do`; returns (words, cnt, existed,
        ovf) with existed/ovf masked by `do`."""
        w2, c2, existed, ovf = bag.wide_bag_put(words, cnt, key)
        words = [jnp.where(do, a, b) for a, b in zip(w2, words)]
        cnt = jnp.where(do, c2, cnt)
        return words, cnt, existed & do, ovf & do

    def _reply(self, d, m, resp_key):
        """Reply — MessagePassing.tla:72-79: discard the request at slot m,
        add the response; returns (words, cnt, resp_existed, ovf)."""
        cnt2 = bag.bag_discard_at(d["msg_cnt"], m)
        return bag.wide_bag_put(self._words(d), cnt2, resp_key)

    # ---------------- action kernels ----------------

    def _restart_with_state(self, s, i):
        """RestartWithState — :873-896: a leader restarts as Resigned
        (voter) or Unattached (observer); keeps epoch/role/votedFor/log."""
        p, NS = self.p, self.NS
        d = self._dec(s)
        valid = (
            (d["restartCtr"] < p.max_restarts)
            & (d["used"][i] > 0)
            & (d["state"][i] != DEAD)
        )
        was_leader = d["state"][i] == LEADER
        new_state = jnp.where(
            was_leader,
            jnp.where(d["role"][i] == R_VOTER, RESIGNED, UNATTACHED),
            d["state"][i],
        )
        used_mask = self._used_mask(d)
        succ = self._asm(
            d,
            state=d["state"].at[i].set(new_state),
            leader=d["leader"].at[i].set(
                jnp.where(was_leader, NIL, d["leader"][i])
            ),
            votesGranted=d["votesGranted"].at[i].set(0),
            eo_dom=d["eo_dom"].at[i].set(used_mask),
            endOffset=d["endOffset"].at[i].set(jnp.zeros((NS,), jnp.int32)),
            highWatermark=d["highWatermark"].at[i].set(0),
            **self._pf_clear_upd(d, i),
            restartCtr=d["restartCtr"] + 1,
        )
        return valid, succ, jnp.int32(KR_RESTART), jnp.asarray(False)

    def _used_mask(self, d):
        NS = self.NS
        return jnp.sum(
            jnp.where(d["used"] > 0, jnp.int32(1) << jnp.arange(NS, dtype=jnp.int32), 0)
        ).astype(jnp.int32)

    def _pf_clear_upd(self, d, i):
        return dict(
            pf_active=d["pf_active"].at[i].set(0),
            pf_epoch=d["pf_epoch"].at[i].set(0),
            pf_offset=d["pf_offset"].at[i].set(0),
            pf_lastepoch=d["pf_lastepoch"].at[i].set(0),
            pf_dest=d["pf_dest"].at[i].set(0),
            pf_observer=d["pf_observer"].at[i].set(0),
        )

    def _request_vote(self, s, i):
        """RequestVote — :932-955: Voter only, member of its own config;
        RequestVoteRequests to the config members via SendMultipleOnce."""
        p, NS = self.p, self.NS
        d = self._dec(s)
        st_i = d["state"][i]
        member = ((d["cfg_members"][i] >> i) & 1) > 0
        valid = (
            (d["electionCtr"] < p.max_elections)
            & (d["used"][i] > 0)
            & (d["role"][i] == R_VOTER)
            & ((st_i == FOLLOWER) | (st_i == CANDIDATE) | (st_i == UNATTACHED))
            & member
        )
        new_epoch = d["currentEpoch"][i] + 1
        last_ep = self._last_epoch(d, i)
        ll_i = d["log_len"][i]
        words, cnt = self._words(d), d["msg_cnt"]
        ovf = jnp.asarray(False)
        for delta in range(1, NS):
            j = jnp.mod(i + delta, NS)
            is_member = ((d["cfg_members"][i] >> j) & 1) > 0
            key = self._pack(
                mtype=RVREQ, mepoch=new_epoch, mlastLogEpoch=last_ep,
                mlastLogOffset=ll_i, msource=i, mdest=j,
            )
            words, cnt, existed, o = self._cond_put(words, cnt, key, is_member)
            valid &= ~existed  # SendMultipleOnce (MessagePassing.tla:49-56)
            ovf |= o
        succ = self._asm(
            d,
            state=d["state"].at[i].set(CANDIDATE),
            currentEpoch=d["currentEpoch"].at[i].set(new_epoch),
            leader=d["leader"].at[i].set(NIL),
            votedFor=d["votedFor"].at[i].set(i + 1),
            votesGranted=d["votesGranted"].at[i].set(jnp.int32(1) << i),
            **self._pf_clear_upd(d, i),
            electionCtr=d["electionCtr"] + 1,
            **self._wupd(words, cnt),
        )
        return valid, succ, jnp.int32(KR_REQUESTVOTE), ovf & valid

    def _become_leader(self, s, i):
        """BecomeLeader — :1056-1071: quorum of the candidate's own config;
        BeginQuorumRequests via SendMultipleOnce; endOffset reset over ALL
        servers."""
        NS = self.NS
        d = self._dec(s)
        members = d["cfg_members"][i]
        vg = d["votesGranted"][i]
        votes = self._popcount(vg)
        msize = self._popcount(members)
        vg_subset = (vg & ~members) == 0
        valid = (
            (d["used"][i] > 0)
            & (d["state"][i] == CANDIDATE)
            & vg_subset
            & (2 * votes > msize)
        )
        words, cnt = self._words(d), d["msg_cnt"]
        ovf = jnp.asarray(False)
        for delta in range(1, NS):
            j = jnp.mod(i + delta, NS)
            is_member = ((members >> j) & 1) > 0
            key = self._pack(
                mtype=BQREQ, mepoch=d["currentEpoch"][i], msource=i, mdest=j
            )
            words, cnt, existed, o = self._cond_put(words, cnt, key, is_member)
            valid &= ~existed
            ovf |= o
        used_mask = self._used_mask(d)
        succ = self._asm(
            d,
            state=d["state"].at[i].set(LEADER),
            leader=d["leader"].at[i].set(i + 1),
            eo_dom=d["eo_dom"].at[i].set(used_mask),
            endOffset=d["endOffset"].at[i].set(jnp.zeros((NS,), jnp.int32)),
            **self._wupd(words, cnt),
        )
        return valid, succ, jnp.int32(KR_BECOMELEADER), ovf & valid

    def _client_request(self, s, i, v):
        """ClientRequest — :1110-1126: bounded per-epoch by valueCtr."""
        p, L = self.p, self.p.max_log
        d = self._dec(s)
        ep = d["currentEpoch"][i]
        epc = jnp.clip(ep - 1, 0, p.max_epoch - 1)
        valid = (
            (d["used"][i] > 0)
            & (d["state"][i] == LEADER)
            & (d["acked"][v] == ACK_NIL)
            & (d["valueCtr"][epc] < p.max_values_per_epoch)
        )
        pos = d["log_len"][i]
        ovf = valid & (pos >= L)
        posc = jnp.clip(pos, 0, L - 1)
        succ = self._asm(
            d,
            log_cmd=d["log_cmd"].at[i, posc].set(C_APPEND),
            log_epoch=d["log_epoch"].at[i, posc].set(ep),
            log_val=d["log_val"].at[i, posc].set(v + 1),
            log_len=d["log_len"].at[i].add(1),
            acked=d["acked"].at[v].set(ACK_FALSE),
            valueCtr=d["valueCtr"].at[epc].add(1),
        )
        return valid, succ, jnp.int32(KR_CLIENTREQUEST), ovf

    def _send_fetch_request(self, s, i, j):
        """SendFetchRequest — :1137-1169: known-leader follower fetch, or
        an Unattached observer probing a voter of its config."""
        d = self._dec(s)
        path_a = (d["leader"][i] == j + 1) & (d["state"][i] == FOLLOWER)
        path_b = (
            (d["role"][i] == R_OBSERVER)
            & (d["state"][i] == UNATTACHED)
            & (((d["cfg_members"][i] >> j) & 1) > 0)
        )
        valid = (
            (d["used"][i] > 0) & (d["used"][j] > 0)
            & (d["pf_active"][i] == 0)
            & (path_a | path_b)
        )
        ll_i = d["log_len"][i]
        last_ep = self._last_epoch(d, i)
        is_obs = (d["role"][i] == R_OBSERVER).astype(jnp.int32)
        key = self._pack(
            mtype=FETCHREQ, mepoch=d["currentEpoch"][i], mfetchOffset=ll_i,
            mlastFetchedEpoch=last_ep, mobserver=is_obs, msource=i, mdest=j,
        )
        words, cnt, _existed, ovf = bag.wide_bag_put(
            self._words(d), d["msg_cnt"], key
        )
        succ = self._asm(
            d,
            pf_active=d["pf_active"].at[i].set(1),
            pf_epoch=d["pf_epoch"].at[i].set(d["currentEpoch"][i]),
            pf_offset=d["pf_offset"].at[i].set(ll_i),
            pf_lastepoch=d["pf_lastepoch"].at[i].set(last_ep),
            pf_dest=d["pf_dest"].at[i].set(j + 1),
            pf_observer=d["pf_observer"].at[i].set(is_obs),
            **self._wupd(words, cnt),
        )
        return valid, succ, jnp.int32(KR_SENDFETCH), ovf & valid

    def _start_new_server(self, s, h, j):
        """StartNewServer — :1492-1511: mints a fresh [host, diskId]
        observer in the next free slot; its first fetch targets a current
        leader. endOffset domain = the servers BEFORE the spawn."""
        NS = self.NS
        d = self._dec(s)
        n_used = jnp.sum((d["used"] > 0).astype(jnp.int32))
        valid = (n_used < NS) & (d["used"][j] > 0) & (d["state"][j] == LEADER)
        slot = jnp.clip(n_used, 0, NS - 1)
        disk_id = d["diskIdGen"] + 1
        old_mask = self._used_mask(d)
        key = self._pack(
            mtype=FETCHREQ, mepoch=0, mfetchOffset=0, mlastFetchedEpoch=0,
            mobserver=1, msource=slot, mdest=j,
        )
        words, cnt, _existed, ovf = bag.wide_bag_put(
            self._words(d), d["msg_cnt"], key
        )
        succ = self._asm(
            d,
            used=d["used"].at[slot].set(1),
            host=d["host"].at[slot].set(h),
            diskId=d["diskId"].at[slot].set(disk_id),
            role=d["role"].at[slot].set(R_OBSERVER),
            state=d["state"].at[slot].set(UNATTACHED),
            currentEpoch=d["currentEpoch"].at[slot].set(0),
            leader=d["leader"].at[slot].set(NIL),
            votedFor=d["votedFor"].at[slot].set(NIL),
            votesGranted=d["votesGranted"].at[slot].set(0),
            cfg_id=d["cfg_id"].at[slot].set(0),
            cfg_members=d["cfg_members"].at[slot].set(0),
            cfg_committed=d["cfg_committed"].at[slot].set(0),
            eo_dom=d["eo_dom"].at[slot].set(old_mask),
            endOffset=d["endOffset"].at[slot].set(jnp.zeros((NS,), jnp.int32)),
            log_len=d["log_len"].at[slot].set(0),
            highWatermark=d["highWatermark"].at[slot].set(0),
            pf_active=d["pf_active"].at[slot].set(1),
            pf_epoch=d["pf_epoch"].at[slot].set(0),
            pf_offset=d["pf_offset"].at[slot].set(0),
            pf_lastepoch=d["pf_lastepoch"].at[slot].set(0),
            pf_dest=d["pf_dest"].at[slot].set(j + 1),
            pf_observer=d["pf_observer"].at[slot].set(1),
            diskIdGen=disk_id,
            **self._wupd(words, cnt),
        )
        return valid, succ, jnp.int32(KR_STARTNEWSERVER), ovf & valid

    def _send_join_request(self, s, i, j):
        """SendJoinRequest — :1524-1538: observer, non-member, to its
        known leader; JoinRequest is send-once. The _addReconfigCtr gate
        (:1526) is a constant (the ctr is never incremented)."""
        d = self._dec(s)
        valid = (
            jnp.asarray(self.p.max_add_reconfigs > 0)
            & (d["used"][i] > 0) & (d["used"][j] > 0)
            & (d["role"][i] == R_OBSERVER)
            & (((d["cfg_members"][i] >> i) & 1) == 0)
            & (d["leader"][i] == j + 1)
        )
        key = self._pack(
            mtype=JOINREQ, mepoch=d["currentEpoch"][i], mdest=j, msource=i
        )
        words, cnt, existed, ovf = bag.wide_bag_put(
            self._words(d), d["msg_cnt"], key
        )
        valid &= ~existed  # send-once (MessagePassing.tla:40-45)
        succ = self._asm(d, **self._wupd(words, cnt))
        return valid, succ, jnp.int32(KR_SENDJOIN), ovf & valid

    def _handle_remove_request(self, s, i, r):
        """HandleRemoveRequest — :1699-1724: admin removal appends a
        RemoveServerCommand; a self-removing leader becomes an observer
        but stays leader."""
        p, L = self.p, self.p.max_log
        d = self._dec(s)
        members = d["cfg_members"][i]
        msize = self._popcount(members)
        # RemoveCheck (:1692-1697) = Ok
        check_ok = (
            (d["state"][i] == LEADER)
            & (((members >> r) & 1) > 0)
            & (d["cfg_committed"][i] > 0)  # no pending config
            & self._leader_committed_in_epoch(d, i)
        )
        valid = (
            (d["used"][i] > 0) & (d["used"][r] > 0)
            & (d["removeCtr"] < p.max_remove_reconfigs)
            & check_ok
            & (msize > p.min_cluster_size)
        )
        new_members = members & ~(jnp.int32(1) << r)
        pos = d["log_len"][i]
        ovf = valid & (pos >= L)
        posc = jnp.clip(pos, 0, L - 1)
        new_len = pos + 1
        succ = self._asm(
            d,
            log_cmd=d["log_cmd"].at[i, posc].set(C_REMOVE),
            log_epoch=d["log_epoch"].at[i, posc].set(d["currentEpoch"][i]),
            log_cfgid=d["log_cfgid"].at[i, posc].set(d["cfg_id"][i] + 1),
            log_who=d["log_who"].at[i, posc].set(r + 1),
            log_members=d["log_members"].at[i, posc].set(new_members),
            log_len=d["log_len"].at[i].set(new_len),
            cfg_id=d["cfg_id"].at[i].set(d["cfg_id"][i] + 1),
            cfg_members=d["cfg_members"].at[i].set(new_members),
            cfg_committed=d["cfg_committed"].at[i].set(
                (d["highWatermark"][i] >= new_len).astype(jnp.int32)
            ),
            role=d["role"].at[i].set(
                jnp.where(i == r, R_OBSERVER, d["role"][i])
            ),
            removeCtr=d["removeCtr"] + 1,
        )
        return valid, succ, jnp.int32(KR_HANDLE_REMOVE), ovf

    # -------- fused message-receipt kernel (slot m) --------
    # The 13 receipt disjuncts of Next are mutually exclusive for a fixed
    # record (they partition on mtype, then on error/validity/mresult/
    # handled), so one kernel per slot computes whichever fires; `rank`
    # reports which for trace labels.

    def _handle_message(self, s, m):
        p, NS, L = self.p, self.NS, self.p.max_log
        d = self._dec(s)
        words, cnt = self._words(d), d["msg_cnt"]
        key = tuple(w[m] for w in words)
        occupied = key[0] != EMPTY
        u = partial(self.packer.unpack, key)
        mtype, mepoch = u("mtype"), u("mepoch")
        src, dst = u("msource"), u("mdest")
        cur = d["currentEpoch"][dst]
        st_dst = d["state"][dst]
        led_dst = d["leader"][dst]
        role_dst = d["role"][dst]
        # ReceivableMessage (:471-477): count > 0 and dest not DeadNoState
        recv = occupied & (cnt[m] > 0) & (d["used"][dst] > 0) & (st_dst != DEAD)
        equal_epoch = mepoch == cur

        def pf_clear(upd):
            return {**upd, **self._pf_clear_upd(d, dst)}

        cnt_disc = bag.bag_discard_at(cnt, m)

        # --- HandleRequestVoteRequest (:967-1018)
        b_rvreq = recv & (mtype == RVREQ)
        rv_err = mepoch < cur  # FencedLeaderEpoch
        s0_st = jnp.where(mepoch > cur, UNATTACHED, st_dst)
        s0_ep = jnp.where(mepoch > cur, mepoch, cur)
        s0_ld = jnp.where(mepoch > cur, NIL, led_dst)
        last_ep = self._last_epoch(d, dst)
        ll_dst = d["log_len"][dst]
        log_ok = (u("mlastLogEpoch") > last_ep) | (
            (u("mlastLogEpoch") == last_ep) & (u("mlastLogOffset") >= ll_dst)
        )
        grant = (
            (s0_st == UNATTACHED)
            | ((s0_st == VOTED) & (d["votedFor"][dst] == src + 1))
        ) & log_ok
        # TransitionToVoted (:630-637) when granting from Unattached; the
        # Unattached precondition makes its illegal arm unreachable
        take_voted = grant & (s0_st == UNATTACHED)
        f_st = jnp.where(take_voted, VOTED, s0_st)
        f_ep = jnp.where(take_voted, mepoch, s0_ep)
        f_ld = jnp.where(take_voted, NIL, s0_ld)
        r_ep = jnp.where(rv_err, cur, mepoch)
        r_ld = jnp.where(rv_err, led_dst, f_ld)
        r_grant = jnp.where(rv_err, 0, grant.astype(jnp.int32))
        r_err = jnp.where(rv_err, E_FENCED, E_NONE)
        rkey = self._pack(
            mtype=RVRESP, mepoch=r_ep, mleader=r_ld, mvoteGranted=r_grant,
            merror=r_err, msource=dst, mdest=src,
        )
        w1, c1, _ex1, ovf1 = self._reply(d, m, rkey)
        no_err = ~rv_err
        upd1 = self._wupd(w1, c1)
        upd1["state"] = jnp.where(no_err, d["state"].at[dst].set(f_st), d["state"])
        upd1["currentEpoch"] = jnp.where(
            no_err, d["currentEpoch"].at[dst].set(f_ep), d["currentEpoch"]
        )
        upd1["leader"] = jnp.where(no_err, d["leader"].at[dst].set(f_ld), d["leader"])
        upd1["votedFor"] = jnp.where(
            no_err & grant, d["votedFor"].at[dst].set(src + 1), d["votedFor"]
        )
        pf_reset = no_err & (f_st != st_dst)
        for pf in ("pf_active", "pf_epoch", "pf_offset", "pf_lastepoch",
                   "pf_dest", "pf_observer"):
            upd1[pf] = jnp.where(pf_reset, d[pf].at[dst].set(0), d[pf])
        s_rvreq = self._asm(d, **upd1)

        # --- HandleRequestVoteResponse (:1025-1050; adds the Voter gate)
        mh_st, mh_ep, mh_ld, handled = self._mhcr(
            d, dst, u("mleader"), mepoch, u("merror")
        )
        b_rvresp = (
            recv & (mtype == RVRESP) & (role_dst == R_VOTER)
            & (handled | (st_dst == CANDIDATE))
        )
        granted_bit = (u("mvoteGranted") > 0) & ~handled
        upd2 = dict(
            state=jnp.where(handled, d["state"].at[dst].set(mh_st), d["state"]),
            currentEpoch=jnp.where(
                handled, d["currentEpoch"].at[dst].set(mh_ep), d["currentEpoch"]
            ),
            leader=jnp.where(handled, d["leader"].at[dst].set(mh_ld), d["leader"]),
            votesGranted=jnp.where(
                granted_bit,
                d["votesGranted"].at[dst].set(
                    d["votesGranted"][dst] | (jnp.int32(1) << src)
                ),
                d["votesGranted"],
            ),
            msg_cnt=cnt_disc,
        )
        s_rvresp = self._asm(d, **upd2)

        # --- AcceptBeginQuorumRequest (:1082-1102): Voter only; stale
        # requests are NOT answered (no reply arm in this spec)
        b_bqreq = (
            recv & (mtype == BQREQ) & (mepoch >= cur) & (role_dst == R_VOTER)
        )
        bt_st, bt_ep, bt_ld = self._maybe_transition(d, dst, src + 1, mepoch)
        upd3 = pf_clear(dict(
            state=d["state"].at[dst].set(bt_st),
            currentEpoch=d["currentEpoch"].at[dst].set(bt_ep),
            leader=d["leader"].at[dst].set(bt_ld),
            msg_cnt=cnt_disc,
        ))
        s_bqreq = self._asm(d, **upd3)

        # --- FetchRequest branches (:1195-1376)
        is_fetchreq = recv & (mtype == FETCHREQ)
        is_leader = st_dst == LEADER
        foff = u("mfetchOffset")
        flep = u("mlastFetchedEpoch")
        fobs = u("mobserver")
        corr_kw = dict(
            cepoch=mepoch, cfetchOffset=foff, clastFetchedEpoch=flep,
            cobserver=fobs,
        )
        ferr = jnp.where(
            ~is_leader, E_NOTLEADER,
            jnp.where(mepoch < cur, E_FENCED,
                      jnp.where(mepoch > cur, E_UNKNOWN_LEADER, E_NONE)),
        )
        valid_pos = self._valid_fetch_position(d, dst, foff, flep)
        eo_off, eo_ep = self._end_offset_for_epoch(d, dst, flep)

        # RejectFetchRequest (:1195-1217)
        b_reject = is_fetchreq & (ferr != E_NONE)
        rjkey = self._pack(
            mtype=FETCHRESP, mresult=R_NOTOK, merror=ferr, mleader=led_dst,
            mepoch=cur, mhwm=d["highWatermark"][dst], msource=dst, mdest=src,
            **corr_kw,
        )
        w4, c4, ex4, ovf4 = self._reply(d, m, rjkey)
        b_reject &= ~ex4  # FetchResponse no-duplicate (MessagePassing:72-79)
        s_reject = self._asm(d, **self._wupd(w4, c4))

        # DivergingFetchRequest (:1225-1248)
        b_div = is_fetchreq & equal_epoch & is_leader & ~valid_pos
        dvkey = self._pack(
            mtype=FETCHRESP, mepoch=cur, mresult=R_DIVERGING, merror=E_NONE,
            mdivergingEpoch=eo_ep, mdivergingEndOffset=eo_off,
            mleader=led_dst, mhwm=d["highWatermark"][dst],
            msource=dst, mdest=src, **corr_kw,
        )
        w5, c5, ex5, ovf5 = self._reply(d, m, dvkey)
        b_div &= ~ex5
        s_div = self._asm(d, **self._wupd(w5, c5))

        # shared accept-fetch entry lookup
        offset = foff + 1
        have_entry = offset <= ll_dst
        epos = jnp.clip(offset - 1, 0, L - 1)
        ent = {
            f: jnp.where(have_entry, d[f][dst][epos], 0)
            for f in ("log_cmd", "log_epoch", "log_val", "log_cfgid",
                      "log_who", "log_members")
        }
        ent_kw = dict(
            nentries=have_entry.astype(jnp.int32), e_cmd=ent["log_cmd"],
            e_epoch=ent["log_epoch"], e_val=ent["log_val"],
            e_cfgid=ent["log_cfgid"], e_who=ent["log_who"],
            e_members=ent["log_members"],
        )

        # AcceptFetchRequestFromVoter (:1286-1342)
        b_acc_v = is_fetchreq & equal_epoch & is_leader & valid_pos & (fobs == 0)
        new_end = d["endOffset"][dst].at[src].set(foff)
        new_eo_dom = d["eo_dom"].at[dst].set(
            d["eo_dom"][dst] | (jnp.int32(1) << src)
        )
        members = d["cfg_members"][dst]
        msize = self._popcount(members)
        # NewHighwaterMark (:1266-1284): leader self-exclusion when removed
        idxs = jnp.arange(1, L + 1, dtype=jnp.int32)
        mem_bits = ((members >> jnp.arange(NS, dtype=jnp.int32)) & 1) > 0
        is_self = jnp.arange(NS, dtype=jnp.int32) == dst
        agree = mem_bits[None, :] & (
            (new_end[None, :] >= idxs[:, None]) | is_self[None, :]
        )
        quorum_ok = 2 * jnp.sum(agree, axis=1) > msize
        in_log = idxs <= ll_dst
        best = jnp.max(jnp.where(quorum_ok & in_log, idxs, 0))
        ep_at = d["log_epoch"][dst][jnp.clip(best - 1, 0)]
        hwm_old = d["highWatermark"][dst]
        new_hwm = jnp.where((best > 0) & (ep_at == cur), best, hwm_old)
        advanced = new_hwm > hwm_old
        # IsRemovedFromCluster (:1259-1264) over (hwm_old, new_hwm]
        lanes = jnp.arange(L, dtype=jnp.int32)
        in_range = (lanes + 1 > hwm_old) & (lanes + 1 <= new_hwm)
        leaves = advanced & jnp.any(
            in_range
            & (d["log_cmd"][dst] == C_REMOVE)
            & (((d["log_members"][dst] >> dst) & 1) == 0)
        )
        # config refresh from the most recent reconfig entry (ci = new_hwm)
        cfg_off = self._most_recent_reconfig(d, d["log_cmd"][dst], ll_dst)
        cfg_lane = jnp.clip(cfg_off - 1, 0, L - 1)
        # acked: in-flight values committed in (hwm_old, new_hwm] (:1331-1338)
        committed = jnp.any(
            in_range[None, :]
            & (d["log_cmd"][dst][None, :] == C_APPEND)
            & (
                d["log_val"][dst][None, :]
                == jnp.arange(1, p.n_values + 1, dtype=jnp.int32)[:, None]
            ),
            axis=1,
        )
        acked_v = jnp.where(
            advanced & (d["acked"] == ACK_FALSE) & committed, ACK_TRUE, d["acked"]
        )
        used_mask = self._used_mask(d)
        upd6 = dict(
            acked=acked_v,
            cfg_id=jnp.where(
                advanced,
                d["cfg_id"].at[dst].set(d["log_cfgid"][dst][cfg_lane]),
                d["cfg_id"],
            ),
            cfg_members=jnp.where(
                advanced,
                d["cfg_members"].at[dst].set(d["log_members"][dst][cfg_lane]),
                d["cfg_members"],
            ),
            cfg_committed=jnp.where(
                advanced,
                d["cfg_committed"].at[dst].set(
                    (new_hwm >= cfg_off).astype(jnp.int32)
                ),
                d["cfg_committed"],
            ),
            role=jnp.where(
                leaves, d["role"].at[dst].set(R_OBSERVER), d["role"]
            ),
            state=jnp.where(
                leaves, d["state"].at[dst].set(UNATTACHED), d["state"]
            ),
            leader=jnp.where(leaves, d["leader"].at[dst].set(NIL), d["leader"]),
            votesGranted=jnp.where(
                leaves, d["votesGranted"].at[dst].set(0), d["votesGranted"]
            ),
            eo_dom=jnp.where(
                leaves,
                d["eo_dom"].at[dst].set(used_mask),
                new_eo_dom,
            ),
            endOffset=jnp.where(
                leaves,
                d["endOffset"].at[dst].set(jnp.zeros((NS,), jnp.int32)),
                d["endOffset"].at[dst].set(new_end),
            ),
            highWatermark=jnp.where(
                leaves,
                d["highWatermark"].at[dst].set(0),
                jnp.where(
                    advanced,
                    d["highWatermark"].at[dst].set(new_hwm),
                    d["highWatermark"],
                ),
            ),
        )
        ackey = self._pack(
            mtype=FETCHRESP, mepoch=cur,
            mleader=jnp.where(leaves, NIL, led_dst), mresult=R_OK,
            merror=E_NONE, mhwm=jnp.minimum(new_hwm, offset),
            msource=dst, mdest=src, **ent_kw, **corr_kw,
        )
        w6, c6, ex6, ovf6 = self._reply(d, m, ackey)
        b_acc_v &= ~ex6
        s_acc_v = self._asm(d, **upd6, **self._wupd(w6, c6))

        # AcceptFetchRequestFromObserver (:1349-1376): response only
        b_acc_o = is_fetchreq & equal_epoch & is_leader & valid_pos & (fobs == 1)
        aokey = self._pack(
            mtype=FETCHRESP, mepoch=cur, mleader=led_dst, mresult=R_OK,
            merror=E_NONE, mhwm=jnp.minimum(offset, hwm_old),
            msource=dst, mdest=src, **ent_kw, **corr_kw,
        )
        w7, c7, ex7, ovf7 = self._reply(d, m, aokey)
        b_acc_o &= ~ex7
        s_acc_o = self._asm(d, **self._wupd(w7, c7))

        # Part 4 (fetch responses, join handling, branch select) below.
        return self._handle_message_part2(
            s, d, m, u, recv, mtype, mepoch, src, dst, cnt_disc, handled,
            mh_st, mh_ep, mh_ld,
            [
                (b_rvreq, s_rvreq, KR_HANDLE_RVREQ, ovf1),
                (b_rvresp, s_rvresp, KR_HANDLE_RVRESP, jnp.asarray(False)),
                (b_reject, s_reject, KR_REJECT_FETCH, ovf4),
                (b_div, s_div, KR_DIVERGING_FETCH, ovf5),
                (b_acc_v, s_acc_v, KR_ACCEPT_FETCH_VOTER, ovf6),
                (b_acc_o, s_acc_o, KR_ACCEPT_FETCH_OBSERVER, ovf7),
                (b_bqreq, s_bqreq, KR_ACCEPT_BQREQ, jnp.asarray(False)),
            ],
        )


    # -------- temporal-property kernels (:1775-1839) --------

    def _no_progress_possible(self, states):
        r"""NoProgressPossible — :1775-1781. The \E j conjunct compares
        state[j] to the ROLE model value Voter (:1780), which no state
        assignment ever produces — same quirk class as
        RestartWithoutState:913 — so the ~\E i arm is vacuously TRUE and
        the definition reduces to _electionCtr = MaxElections; reproduced
        faithfully."""
        ec = self.layout.get(states, "electionCtr")
        return ec == self.p.max_elections

    def _is_current_leader(self, states):
        """IsCurrentLeader(i) — :1787-1792: Leader with no higher-epoch
        peer. [B, NS] mask (used slots only)."""
        lay = self.layout
        used = lay.get(states, "used") > 0
        st = lay.get(states, "state")
        ep = lay.get(states, "currentEpoch")
        higher = jnp.any(
            used[:, None, :] & (ep[:, None, :] > ep[:, :, None]), axis=2
        )
        return used & (st == LEADER) & ~higher

    def _live_committed_value_or_nothing(self, v, states):
        """CommittedValueOrNothing(v) — :1794-1808: a current leader's
        whole member set either has v committed or has v nowhere."""
        lay, L, NS = self.layout, self.p.max_log, self.NS
        cmd = lay.get(states, "log_cmd")
        lv = lay.get(states, "log_val")
        ll = lay.get(states, "log_len")
        hwm = lay.get(states, "highWatermark")
        lanes = jnp.arange(L, dtype=jnp.int32)
        has = (
            (lanes[None, None, :] < ll[..., None])
            & (cmd == C_APPEND)
            & (lv == v + 1)
        )
        in_log = jnp.any(has, axis=2)  # ValueNotInServerLog = ~in_log
        committed = jnp.any(
            has & (hwm[..., None] >= lanes[None, None, :] + 1), axis=2
        )
        return self._live_all_or_nothing(states, committed, in_log)

    def _live_all_or_nothing(self, states, committed, in_log):
        """Shared tail of the []<> formulas (:1804-1808 / :1829-1834):
        NoProgressPossible, or some current leader whose whole member set
        either has the thing committed or lacks it entirely. `committed`
        and `in_log` are [B, NS] per-server presence masks."""
        lay, NS = self.layout, self.NS
        icl = self._is_current_leader(states)
        member = (
            (lay.get(states, "cfg_members")[:, :, None]
             >> jnp.arange(NS, dtype=jnp.int32)[None, None, :]) & 1
        ) > 0  # [B, l, i]
        all_committed = jnp.all(~member | committed[:, None, :], axis=2)
        all_absent = jnp.all(~member | ~in_log[:, None, :], axis=2)
        ok = jnp.any(icl & (all_committed | all_absent), axis=1)
        return self._no_progress_possible(states) | ok

    def _live_config_all_or_nothing(self, cid, states):
        """ConfigAllOrNothing(config_id) — :1817-1834."""
        lay, L, NS = self.layout, self.p.max_log, self.NS
        cmd = lay.get(states, "log_cmd")
        cfgid = lay.get(states, "log_cfgid")
        ll = lay.get(states, "log_len")
        hwm = lay.get(states, "highWatermark")
        lanes = jnp.arange(L, dtype=jnp.int32)
        is_cfg = (
            ((cmd == C_INIT) | (cmd == C_ADD) | (cmd == C_REMOVE))
            & (lanes[None, None, :] < ll[..., None])
            & (cfgid == cid)
        )
        in_log = jnp.any(is_cfg, axis=2)
        committed = jnp.any(
            is_cfg & (hwm[..., None] >= lanes[None, None, :] + 1), axis=2
        )
        return self._live_all_or_nothing(states, committed, in_log)

    # ---------------- full expansion ----------------

    def _expand1(self, s):
        """All successor candidates of one state.

        Returns (succs [A, W], valid [A], rank [A], ovf [A])."""
        p, NS = self.p, self.NS
        V, H, M = p.n_values, p.n_hosts, p.msg_slots
        iota = jnp.arange(NS, dtype=jnp.int32)
        pr_i = jnp.asarray([ij[0] for ij in self._pairs], jnp.int32)
        pr_j = jnp.asarray([ij[1] for ij in self._pairs], jnp.int32)
        outs = []
        outs.append(jax.vmap(lambda i: self._restart_with_state(s, i))(iota))
        outs.append(jax.vmap(lambda i: self._request_vote(s, i))(iota))
        outs.append(jax.vmap(lambda i: self._become_leader(s, i))(iota))
        cr_i = jnp.repeat(iota, V)
        cr_v = jnp.tile(jnp.arange(V, dtype=jnp.int32), NS)
        outs.append(jax.vmap(lambda i, v: self._client_request(s, i, v))(cr_i, cr_v))
        outs.append(
            jax.vmap(lambda i, j: self._send_fetch_request(s, i, j))(pr_i, pr_j)
        )
        sn_h = jnp.repeat(jnp.arange(H, dtype=jnp.int32), NS)
        sn_j = jnp.tile(iota, H)
        outs.append(jax.vmap(lambda h, j: self._start_new_server(s, h, j))(sn_h, sn_j))
        outs.append(
            jax.vmap(lambda i, j: self._send_join_request(s, i, j))(pr_i, pr_j)
        )
        rm_i = jnp.repeat(iota, NS)
        rm_r = jnp.tile(iota, NS)
        outs.append(
            jax.vmap(lambda i, r: self._handle_remove_request(s, i, r))(rm_i, rm_r)
        )
        outs.append(
            jax.vmap(lambda m: self._handle_message(s, m))(
                jnp.arange(M, dtype=jnp.int32)
            )
        )
        valid = jnp.concatenate([o[0] for o in outs])
        succs = jnp.concatenate([o[1] for o in outs])
        rank = jnp.concatenate([o[2] for o in outs])
        ovf = jnp.concatenate([o[3] for o in outs])
        return succs, valid, rank, ovf

    # ---------------- initial states ----------------

    def init_states(self) -> np.ndarray:
        """Init — :845-859: pre-installed cluster of the first
        InitClusterSize hosts (identities (h, 0) in slot h), leader = the
        lowest identity, one InitClusterCommand entry committed."""
        p, lay = self.p, self.layout
        NS, ics = self.NS, p.init_cluster_size
        vec = lay.zeros((1,))
        members_mask = (1 << ics) - 1
        host = np.zeros(NS, np.int32)
        used = np.zeros(NS, np.int32)
        role = np.zeros(NS, np.int32)
        state = np.zeros(NS, np.int32)
        epoch = np.zeros(NS, np.int32)
        leader = np.zeros(NS, np.int32)
        cfg_id = np.zeros(NS, np.int32)
        cfg_members = np.zeros(NS, np.int32)
        cfg_committed = np.zeros(NS, np.int32)
        eo_dom = np.zeros(NS, np.int32)
        hwm = np.zeros(NS, np.int32)
        log_cmd = np.zeros((NS, p.max_log), np.int32)
        log_epoch = np.zeros((NS, p.max_log), np.int32)
        log_cfgid = np.zeros((NS, p.max_log), np.int32)
        log_members = np.zeros((NS, p.max_log), np.int32)
        log_len = np.zeros(NS, np.int32)
        eo = np.zeros((NS, NS), np.int32)
        for h in range(ics):
            host[h] = h
            used[h] = 1
            role[h] = R_VOTER
            state[h] = LEADER if h == 0 else FOLLOWER
            epoch[h] = 1
            leader[h] = 1  # slot 0 + 1 (lowest identity, CHOOSE as min)
            cfg_id[h] = 1
            cfg_members[h] = members_mask
            cfg_committed[h] = 1
            eo_dom[h] = members_mask
            hwm[h] = 1
            log_cmd[h, 0] = C_INIT
            log_epoch[h, 0] = 1
            log_cfgid[h, 0] = 1
            log_members[h, 0] = members_mask
            log_len[h] = 1
            eo[h, :ics] = 1
        vec[0, lay.sl("host")] = host
        vec[0, lay.sl("used")] = used
        vec[0, lay.sl("role")] = role
        vec[0, lay.sl("state")] = state
        vec[0, lay.sl("currentEpoch")] = epoch
        vec[0, lay.sl("leader")] = leader
        vec[0, lay.sl("cfg_id")] = cfg_id
        vec[0, lay.sl("cfg_members")] = cfg_members
        vec[0, lay.sl("cfg_committed")] = cfg_committed
        vec[0, lay.sl("eo_dom")] = eo_dom
        vec[0, lay.sl("endOffset")] = eo.reshape(-1)
        vec[0, lay.sl("log_cmd")] = log_cmd.reshape(-1)
        vec[0, lay.sl("log_epoch")] = log_epoch.reshape(-1)
        vec[0, lay.sl("log_cfgid")] = log_cfgid.reshape(-1)
        vec[0, lay.sl("log_members")] = log_members.reshape(-1)
        vec[0, lay.sl("log_len")] = log_len
        vec[0, lay.sl("highWatermark")] = hwm
        for k in range(self.packer.n_words):
            vec[0, lay.sl(f"msg_w{k}")] = int(EMPTY)
        return vec

    # ---------------- invariants (:1848-1912) ----------------

    def _inv_no_illegal(self, states):
        """NoIllegalState — :1848-1850."""
        st = self.layout.get(states, "state")
        return jnp.all(st != ILLEGAL, axis=1)

    def _inv_no_log_divergence(self, states):
        """NoLogDivergence — :1860-1868: committed prefixes (up to the
        pairwise-min hwm) must agree on FULL entry equality."""
        lay, L = self.layout, self.p.max_log
        used = lay.get(states, "used") > 0
        hwm = lay.get(states, "highWatermark")
        mh = jnp.minimum(hwm[:, :, None], hwm[:, None, :])
        lanes = jnp.arange(1, L + 1, dtype=jnp.int32)
        in_common = lanes[None, None, None, :] <= mh[..., None]
        eq = jnp.ones_like(in_common)
        for f in ("log_cmd", "log_epoch", "log_val", "log_cfgid",
                  "log_who", "log_members"):
            v = lay.get(states, f)
            eq &= v[:, :, None, :] == v[:, None, :, :]
        both = used[:, :, None] & used[:, None, :]
        return jnp.all(~(both[..., None] & in_common) | eq, axis=(1, 2, 3))

    def _inv_states_match_roles(self, states):
        """StatesMatchRoles — :1876-1881."""
        lay = self.layout
        used = lay.get(states, "used") > 0
        role = lay.get(states, "role")
        st = lay.get(states, "state")
        led = lay.get(states, "leader")
        obs_ok = (
            (st == LEADER) | (st == FOLLOWER) | (st == UNATTACHED) | (st == VOTED)
        )
        bad = used & (
            ((role == R_OBSERVER) & ~obs_ok)
            | ((st == UNATTACHED) & (led != NIL))
        )
        return ~jnp.any(bad, axis=1)

    def _inv_never_two_leaders(self, states):
        """NeverTwoLeadersInSameEpoch — :1886-1892."""
        lay = self.layout
        used = lay.get(states, "used") > 0
        led = lay.get(states, "leader")
        ep = lay.get(states, "currentEpoch")
        both = (
            used[:, :, None] & used[:, None, :]
            & (led[:, :, None] != NIL) & (led[:, None, :] != NIL)
        )
        conflict = (
            both
            & (led[:, :, None] != led[:, None, :])
            & (ep[:, :, None] == ep[:, None, :])
        )
        return ~jnp.any(conflict, axis=(1, 2))

    def _inv_leader_has_acked(self, states):
        """LeaderHasAllAckedValues — :1896-1912 (APPEND entries only)."""
        lay, V = self.layout, self.p.n_values
        used = lay.get(states, "used") > 0
        ep = lay.get(states, "currentEpoch")
        st = lay.get(states, "state")
        cmd = lay.get(states, "log_cmd")
        lv = lay.get(states, "log_val")
        acked = lay.get(states, "acked")
        # "no other server has a strictly higher epoch"; l = i contributes
        # nothing (ep[i] > ep[i] is false), so no off-diagonal mask needed
        higher = used[:, None, :] & (ep[:, None, :] > ep[:, :, None])
        not_stale = ~jnp.any(higher, axis=2)
        is_lead = used & (st == LEADER) & not_stale
        vals = jnp.arange(1, V + 1, dtype=jnp.int32)
        has_v = jnp.any(
            (cmd[:, :, None, :] == C_APPEND)
            & (lv[:, :, None, :] == vals[None, None, :, None]),
            axis=3,
        )
        bad = jnp.any(
            (acked[:, None, :] == ACK_TRUE) & is_lead[:, :, None] & ~has_v,
            axis=(1, 2),
        )
        return ~bad

    def _inv_messages_are_valid(self, states):
        """MessagesAreValid — MessagePassing.tla:81-83: no self-addressed
        record in the bag domain (checker self-check)."""
        lay = self.layout
        w0 = lay.get(states, "msg_w0")
        occupied = w0 != EMPTY
        src = self.packer.unpack([lay.get(states, f"msg_w{k}")
                                  for k in range(self.packer.n_words)], "msource")
        dst = self.packer.unpack([lay.get(states, f"msg_w{k}")
                                  for k in range(self.packer.n_words)], "mdest")
        return ~jnp.any(occupied & (src == dst), axis=1)

    # ---------------- host-side decode/encode ----------------
    # Slot assignment rule (see module docstring): initial identity (h, 0)
    # <-> slot h; spawned identity (h, d) with d >= 1 <-> slot ics + d - 1.
    # Device evolution preserves it (new servers take the next free slot
    # and diskId equals the creation counter), so encode() of any oracle-
    # reachable state round-trips through the device kernels.

    def _slot_ident(self, vec, slot: int) -> tuple[int, int]:
        lay = self.layout
        return (
            int(vec[lay.fields["host"].offset + slot]),
            int(vec[lay.fields["diskId"].offset + slot]),
        )

    def decode(self, vec: np.ndarray) -> dict:
        """Decode a packed state into the oracle's dict format
        (identity-keyed maps, entry tuples, frozenset message bag)."""
        lay, p = self.layout, self.p
        NS = self.NS
        vec = np.asarray(vec)
        g = lambda n: np.asarray(vec[lay.sl(n)])
        used = g("used")
        slots = [i for i in range(NS) if used[i]]
        ids = {i: self._slot_ident(vec, i) for i in slots}

        def ref(v):  # slot+1 encoded reference -> identity | None
            return None if v == 0 else ids[int(v) - 1]

        def mask_set(mask):
            return frozenset(ids[i] for i in slots if (int(mask) >> i) & 1)

        from ..oracle import kraft_reconfig_oracle as KO

        state_names = {
            UNATTACHED: KO.UNATTACHED, VOTED: KO.VOTED, FOLLOWER: KO.FOLLOWER,
            CANDIDATE: KO.CANDIDATE, LEADER: KO.LEADER, RESIGNED: KO.RESIGNED,
            DEAD: KO.DEAD, ILLEGAL: KO.ILLEGAL,
        }
        role_names = {R_VOTER: KO.VOTER, R_OBSERVER: KO.OBSERVER, R_DEAD: KO.DEAD}

        lt = {
            f: g(f).reshape(NS, p.max_log)
            for f in ("log_cmd", "log_epoch", "log_val", "log_cfgid",
                      "log_who", "log_members")
        }
        ll = g("log_len")

        def entry(i, k):
            cmd = int(lt["log_cmd"][i, k])
            ep = int(lt["log_epoch"][i, k])
            if cmd == C_APPEND:
                return (KO.APPEND_CMD, ep, int(lt["log_val"][i, k]) - 1)
            members = mask_set(lt["log_members"][i, k])
            cid = int(lt["log_cfgid"][i, k])
            if cmd == C_INIT:
                return (KO.INIT_CMD, ep, (cid, members))
            who = ids[int(lt["log_who"][i, k]) - 1]
            name = KO.ADD_CMD if cmd == C_ADD else KO.REMOVE_CMD
            return (name, ep, (cid, who, members))

        pf_act, pf_ep = g("pf_active"), g("pf_epoch")
        pf_off, pf_le = g("pf_offset"), g("pf_lastepoch")
        pf_d, pf_o = g("pf_dest"), g("pf_observer")

        def pending(i):
            if not pf_act[i]:
                return None
            return KO.rec(
                mtype="FetchRequest", mepoch=int(pf_ep[i]),
                mfetchOffset=int(pf_off[i]), mlastFetchedEpoch=int(pf_le[i]),
                mobserver=bool(pf_o[i]), msource=ids[i], mdest=ref(pf_d[i]),
            )

        eo = g("endOffset").reshape(NS, NS)
        eo_dom = g("eo_dom")
        words = [g(f"msg_w{k}") for k in range(self.packer.n_words)]
        cnts = g("msg_cnt")
        msgs = {}
        for k in range(p.msg_slots):
            if int(words[0][k]) == int(EMPTY):
                continue
            keyk = tuple(int(w[k]) for w in words)
            msgs[self.decode_msg(keyk, ids)] = int(cnts[k])
        ack_map = {ACK_NIL: None, ACK_FALSE: False, ACK_TRUE: True}
        scalar = lambda n: int(vec[lay.fields[n].offset])
        return {
            "servers": frozenset(ids.values()),
            "config": {
                ids[i]: (
                    int(g("cfg_id")[i]),
                    mask_set(g("cfg_members")[i]),
                    bool(g("cfg_committed")[i]),
                )
                for i in slots
            },
            "currentEpoch": {ids[i]: int(g("currentEpoch")[i]) for i in slots},
            "role": {ids[i]: role_names[int(g("role")[i])] for i in slots},
            "state": {ids[i]: state_names[int(g("state")[i])] for i in slots},
            "leader": {ids[i]: ref(g("leader")[i]) for i in slots},
            "votedFor": {ids[i]: ref(g("votedFor")[i]) for i in slots},
            "pendingFetch": {ids[i]: pending(i) for i in slots},
            "votesGranted": {ids[i]: mask_set(g("votesGranted")[i]) for i in slots},
            "endOffset": {
                ids[i]: {
                    ids[j]: int(eo[i, j])
                    for j in slots
                    if (int(eo_dom[i]) >> j) & 1
                }
                for i in slots
            },
            "log": {
                ids[i]: tuple(entry(i, k) for k in range(int(ll[i])))
                for i in slots
            },
            "highWatermark": {ids[i]: int(g("highWatermark")[i]) for i in slots},
            "messages": frozenset(msgs.items()),
            "_acked": tuple(ack_map[int(a)] for a in g("acked")),
            "_electionCtr": scalar("electionCtr"),
            "_valueCtr": tuple(int(x) for x in g("valueCtr")),
            "_restartCtr": scalar("restartCtr"),
            "_addReconfigCtr": 0,  # never incremented (:1526) — constant
            "_removeReconfigCtr": scalar("removeCtr"),
            "_diskIdGen": scalar("diskIdGen"),
        }

    def decode_msg(self, key: tuple, ids: dict) -> tuple:
        from ..oracle import kraft_reconfig_oracle as KO

        u = self.packer.unpack_all(key)
        mtype = int(u["mtype"])
        src, dst = ids[int(u["msource"])], ids[int(u["mdest"])]
        kw = dict(
            mtype=MTYPE_NAMES[mtype], mepoch=int(u["mepoch"]),
            msource=src, mdest=dst,
        )
        mlead = None if not u["mleader"] else ids[int(u["mleader"]) - 1]
        if mtype == RVREQ:
            kw.update(
                mlastLogEpoch=int(u["mlastLogEpoch"]),
                mlastLogOffset=int(u["mlastLogOffset"]),
            )
        elif mtype == RVRESP:
            kw.update(
                mleader=mlead, mvoteGranted=bool(u["mvoteGranted"]),
                merror=ERROR_NAMES[int(u["merror"])],
            )
        elif mtype == FETCHREQ:
            kw.update(
                mfetchOffset=int(u["mfetchOffset"]),
                mlastFetchedEpoch=int(u["mlastFetchedEpoch"]),
                mobserver=bool(u["mobserver"]),
            )
        elif mtype == JOINRESP:
            kw.update(
                mleader=mlead, mresult=RESULT_NAMES[int(u["mresult"])],
                merror=ERROR_NAMES[int(u["merror"])],
            )
        elif mtype == FETCHRESP:
            res = int(u["mresult"])
            kw.update(
                mresult=RESULT_NAMES[res],
                merror=ERROR_NAMES[int(u["merror"])],
                mleader=mlead, mhwm=int(u["mhwm"]),
            )
            if res == R_OK:
                if int(u["nentries"]):
                    cmd = int(u["e_cmd"])
                    ep = int(u["e_epoch"])
                    if cmd == C_APPEND:
                        ent = (KO.APPEND_CMD, ep, int(u["e_val"]) - 1)
                    else:
                        members = frozenset(
                            ids[i] for i in ids if (int(u["e_members"]) >> i) & 1
                        )
                        cid = int(u["e_cfgid"])
                        if cmd == C_INIT:
                            ent = (KO.INIT_CMD, ep, (cid, members))
                        else:
                            ent = (
                                KO.ADD_CMD if cmd == C_ADD else KO.REMOVE_CMD,
                                ep,
                                (cid, ids[int(u["e_who"]) - 1], members),
                            )
                    kw["mentries"] = (ent,)
                else:
                    kw["mentries"] = ()
            if res == R_DIVERGING:
                kw.update(
                    mdivergingEpoch=int(u["mdivergingEpoch"]),
                    mdivergingEndOffset=int(u["mdivergingEndOffset"]),
                )
            kw["correlation"] = KO.rec(
                mtype="FetchRequest", mepoch=int(u["cepoch"]),
                mfetchOffset=int(u["cfetchOffset"]),
                mlastFetchedEpoch=int(u["clastFetchedEpoch"]),
                mobserver=bool(u["cobserver"]), msource=dst, mdest=src,
            )
        return KO.rec(**kw)

    def _ident_slot(self, ident: tuple[int, int]) -> int:
        h, dk = ident
        if dk == 0:
            assert h < self.p.init_cluster_size, ident
            return h
        return self.p.init_cluster_size + dk - 1

    def encode_msg(self, m: tuple, slot_of: dict) -> tuple:
        from ..oracle import kraft_reconfig_oracle as KO

        d = dict(m)
        inv_err = {v: k for k, v in ERROR_NAMES.items()}
        inv_res = {v: k for k, v in RESULT_NAMES.items()}
        inv_mt = {v: k for k, v in MTYPE_NAMES.items()}
        mtype = inv_mt[d["mtype"]]
        kw = dict(
            mtype=mtype, mepoch=d["mepoch"],
            msource=slot_of[d["msource"]], mdest=slot_of[d["mdest"]],
        )
        if mtype == RVREQ:
            kw.update(
                mlastLogEpoch=d["mlastLogEpoch"],
                mlastLogOffset=d["mlastLogOffset"],
            )
        elif mtype == RVRESP:
            kw.update(
                mleader=0 if d["mleader"] is None else slot_of[d["mleader"]] + 1,
                mvoteGranted=int(d["mvoteGranted"]),
                merror=inv_err[d["merror"]],
            )
        elif mtype == FETCHREQ:
            kw.update(
                mfetchOffset=d["mfetchOffset"],
                mlastFetchedEpoch=d["mlastFetchedEpoch"],
                mobserver=int(d["mobserver"]),
            )
        elif mtype == JOINRESP:
            kw.update(
                mleader=0 if d["mleader"] is None else slot_of[d["mleader"]] + 1,
                mresult=inv_res[d["mresult"]],
                merror=inv_err[d["merror"]],
            )
        elif mtype == FETCHRESP:
            corr = dict(d["correlation"])
            kw.update(
                mresult=inv_res[d["mresult"]],
                merror=inv_err[d["merror"]],
                mleader=0 if d["mleader"] is None else slot_of[d["mleader"]] + 1,
                mhwm=d["mhwm"],
                cepoch=corr["mepoch"],
                cfetchOffset=corr["mfetchOffset"],
                clastFetchedEpoch=corr["mlastFetchedEpoch"],
                cobserver=int(corr["mobserver"]),
            )
            if d["mresult"] == "Ok" and d.get("mentries"):
                cmd_name, ep, val = d["mentries"][0]
                inv_cmd = {v: k for k, v in CMD_NAMES.items()}
                cmd = inv_cmd[cmd_name]
                kw.update(nentries=1, e_cmd=cmd, e_epoch=ep)
                if cmd == C_APPEND:
                    kw["e_val"] = val + 1
                else:
                    if cmd == C_INIT:
                        cid, members = val
                    else:
                        cid, who, members = val
                        kw["e_who"] = slot_of[who] + 1
                    kw["e_cfgid"] = cid
                    kw["e_members"] = sum(
                        1 << slot_of[x] for x in members
                    )
            elif d["mresult"] == "Ok":
                kw["nentries"] = 0
            if d["mresult"] == "Diverging":
                kw.update(
                    mdivergingEpoch=d["mdivergingEpoch"],
                    mdivergingEndOffset=d["mdivergingEndOffset"],
                )
        return self.packer.pack(**kw)

    def encode(self, st: dict) -> np.ndarray:
        """Encode an oracle state dict into the packed slot vector."""
        from ..oracle import kraft_reconfig_oracle as KO

        lay, p = self.layout, self.p
        NS = self.NS
        vec = lay.zeros(())
        slot_of = {ident: self._ident_slot(ident) for ident in st["servers"]}
        inv_state = {v: k for k, v in STATE_NAMES.items()}
        inv_role = {v: k for k, v in ROLE_NAMES.items()}

        def put(name, slot, val):
            vec[lay.fields[name].offset + slot] = val

        def mask_of(idset):
            return sum(1 << slot_of[x] for x in idset)

        for ident, slot in slot_of.items():
            put("host", slot, ident[0])
            put("diskId", slot, ident[1])
            put("used", slot, 1)
            put("role", slot, inv_role[st["role"][ident]])
            put("state", slot, inv_state[st["state"][ident]])
            put("currentEpoch", slot, st["currentEpoch"][ident])
            led = st["leader"][ident]
            put("leader", slot, 0 if led is None else slot_of[led] + 1)
            vf = st["votedFor"][ident]
            put("votedFor", slot, 0 if vf is None else slot_of[vf] + 1)
            pf = st["pendingFetch"][ident]
            if pf is not None:
                c = dict(pf)
                put("pf_active", slot, 1)
                put("pf_epoch", slot, c["mepoch"])
                put("pf_offset", slot, c["mfetchOffset"])
                put("pf_lastepoch", slot, c["mlastFetchedEpoch"])
                put("pf_dest", slot, slot_of[c["mdest"]] + 1)
                put("pf_observer", slot, int(c["mobserver"]))
            put("votesGranted", slot, mask_of(st["votesGranted"][ident]))
            cid, members, committed = st["config"][ident]
            put("cfg_id", slot, cid)
            put("cfg_members", slot, mask_of(members))
            put("cfg_committed", slot, int(committed))
            eo = st["endOffset"][ident]
            put("eo_dom", slot, mask_of(eo.keys()))
            for j, v in eo.items():
                vec[lay.fields["endOffset"].offset + slot * NS + slot_of[j]] = v
            for k, e in enumerate(st["log"][ident]):
                cmd_name, ep, val = e
                inv_cmd = {v: kk for kk, v in CMD_NAMES.items()}
                cmd = inv_cmd[cmd_name]
                base = slot * p.max_log + k
                vec[lay.fields["log_cmd"].offset + base] = cmd
                vec[lay.fields["log_epoch"].offset + base] = ep
                if cmd == C_APPEND:
                    vec[lay.fields["log_val"].offset + base] = val + 1
                else:
                    if cmd == C_INIT:
                        cid2, mem2 = val
                    else:
                        cid2, who2, mem2 = val
                        vec[lay.fields["log_who"].offset + base] = slot_of[who2] + 1
                    vec[lay.fields["log_cfgid"].offset + base] = cid2
                    vec[lay.fields["log_members"].offset + base] = mask_of(mem2)
            put("log_len", slot, len(st["log"][ident]))
            put("highWatermark", slot, st["highWatermark"][ident])
        ack_inv = {None: ACK_NIL, False: ACK_FALSE, True: ACK_TRUE}
        vec[lay.sl("acked")] = [ack_inv[a] for a in st["_acked"]]
        keys = sorted(
            (self.encode_msg(rec, slot_of), cnt) for rec, cnt in st["messages"]
        )
        if len(keys) > p.msg_slots:
            raise OverflowError("message bag exceeds msg_slots")
        nw = self.packer.n_words
        words = [np.full(p.msg_slots, int(EMPTY), np.int32) for _ in range(nw)]
        cn = np.zeros(p.msg_slots, np.int32)
        for k, (kt, c) in enumerate(keys):
            for w in range(nw):
                words[w][k] = kt[w]
            cn[k] = c
        for w in range(nw):
            vec[lay.sl(f"msg_w{w}")] = words[w]
        vec[lay.sl("msg_cnt")] = cn
        vec[lay.fields["electionCtr"].offset] = st["_electionCtr"]
        vec[lay.fields["restartCtr"].offset] = st["_restartCtr"]
        vec[lay.fields["removeCtr"].offset] = st["_removeReconfigCtr"]
        vec[lay.fields["diskIdGen"].offset] = st["_diskIdGen"]
        vec[lay.sl("valueCtr")] = list(st["_valueCtr"])
        return vec


class SlotCanonicalizer:
    """Canonical fingerprints for the slot encoding under
    ``symmHostsAndValues`` (:462-463).

    A host permutation sigma maps identity (h, d) -> (sigma(h), d); slots
    do NOT move (they are creation-order), but the oracle's view serializes
    servers in sorted-identity order, so canonicalization is data-dependent:
    for each (sigma, tau) (1) remap host values, (2) argsort slots by the
    permuted (host, diskId) key — used slots first, creation order as the
    stable tie-break for unused — (3) remap every slot reference (leader/
    votedFor/pf_dest/bitmasks/endOffset axes/message source/dest/leader/
    e_who/e_members) through the sort, (4) remap values through tau
    (log_val/e_val/acked lanes), (5) re-sort the message bag, (6) hash the
    VIEW prefix. The fingerprint is the min over all permutations —
    exactly the oracle's ``canon`` equivalence, hashed.

    With symmetry off only the identity permutation runs; the slot sort is
    then a no-op by construction (device slot order IS sorted-identity
    order for unpermuted states), kept for uniformity.
    """

    def __init__(self, model: KRaftReconfigModel, symmetry: bool = True,
                 seed: int = 0):
        self.model = model
        self.symmetry = symmetry
        self.seed = seed
        H, V = model.p.n_hosts, model.p.n_values
        if symmetry:
            sigmas = list(itertools.permutations(range(H)))
            taus = list(itertools.permutations(range(V)))
        else:
            sigmas = [tuple(range(H))]
            taus = [tuple(range(V))]
        pairs = [(s, t) for s in sigmas for t in taus]
        self._sigmas = jnp.asarray([p0 for p0, _ in pairs], jnp.int32)
        self._taus = jnp.asarray([t for _, t in pairs], jnp.int32)
        self.fingerprints = jax.jit(self._fingerprints)

    def _fingerprints(self, states):
        states = jnp.asarray(states, jnp.int32)
        return jax.vmap(self._fp1)(states)

    def _fp1(self, vec):
        hashes = jax.vmap(lambda sg, tu: self._canon_hash(vec, sg, tu))(
            self._sigmas, self._taus
        )
        return jnp.min(hashes)

    def _canon_hash(self, vec, sigma, tau):
        model = self.model
        d = model._dec(vec)
        NS, L = model.NS, model.p.max_log
        iota = jnp.arange(NS, dtype=jnp.int32)
        used = d["used"] > 0

        # 1. permuted identity sort key; unused slots last in stable order
        host2 = sigma[jnp.clip(d["host"], 0, model.p.n_hosts - 1)]
        BIG = jnp.int32(max(NS, model.p.n_hosts) + 2)  # > any diskId/host
        key = jnp.where(used, host2 * BIG + d["diskId"], BIG * BIG + iota)
        order = jnp.argsort(key, stable=True)  # new row r <- old slot order[r]
        inv = jnp.zeros((NS,), jnp.int32).at[order].set(iota)  # old -> new

        def gather(x):  # per-slot rows
            return x[order]

        def refmap(x):  # slot+1 valued (0 = Nil)
            return jnp.where(x > 0, inv[jnp.clip(x - 1, 0)] + 1, 0)

        def maskmap(mask):  # bitmask over slots; mask shape [...]
            bits = (mask[..., None] >> order) & 1  # new bit r from old order[r]
            return jnp.sum(bits << iota, axis=-1).astype(jnp.int32)

        upd = {}
        upd["host"] = jnp.where(used, host2, 0)[order]
        for f in ("diskId", "used", "role", "state", "currentEpoch",
                  "pf_active", "pf_epoch", "pf_offset", "pf_lastepoch",
                  "pf_observer", "cfg_id", "cfg_committed", "log_cmd",
                  "log_epoch", "log_cfgid", "log_len", "highWatermark"):
            upd[f] = gather(d[f])
        for f in ("leader", "votedFor", "pf_dest"):
            upd[f] = gather(refmap(d[f]))
        for f in ("votesGranted", "cfg_members", "eo_dom", "log_members"):
            upd[f] = gather(maskmap(d[f]))
        upd["log_who"] = gather(refmap(d["log_who"]))
        upd["endOffset"] = d["endOffset"][order][:, order]
        # value permutation tau: log_val lanes (APPEND entries only carry a
        # value) + acked reorder (acked'[tau[v]] = acked[v])
        lv = d["log_val"]
        lv2 = jnp.where(
            (d["log_cmd"] == C_APPEND) & (lv > 0),
            tau[jnp.clip(lv - 1, 0)] + 1,
            lv,
        )
        upd["log_val"] = gather(lv2)
        upd["acked"] = jnp.zeros_like(d["acked"]).at[tau].set(d["acked"])

        # message bag: remap slot/value fields inside the packed keys of
        # occupied slots, then re-sort
        words = model._words(d)
        occ = words[0] != EMPTY
        pk = model.packer

        def wreplace(ws, name, val):
            out = pk.replace(tuple(ws), name, val)
            return [jnp.where(occ, o, w) for o, w in zip(out, ws)]

        u = partial(pk.unpack, tuple(words))
        src, dst = u("msource"), u("mdest")
        ws = list(words)
        ws = wreplace(ws, "msource", inv[jnp.clip(src, 0, NS - 1)])
        ws = wreplace(ws, "mdest", inv[jnp.clip(dst, 0, NS - 1)])
        ws = wreplace(ws, "mleader", refmap(u("mleader")))
        ws = wreplace(ws, "e_who", refmap(u("e_who")))
        ws = wreplace(ws, "e_members", maskmap(u("e_members")))
        ev = u("e_val")
        ws = wreplace(
            ws, "e_val",
            jnp.where(
                (u("e_cmd") == C_APPEND) & (ev > 0),
                tau[jnp.clip(ev - 1, 0)] + 1,
                ev,
            ),
        )
        sw, scnt = bag.wide_bag_sort(ws, d["msg_cnt"])
        for k in range(pk.n_words):
            upd[f"msg_w{k}"] = sw[k]
        upd["msg_cnt"] = scnt

        out = model._asm(d, **upd)
        return hash_lanes(out[: model.layout.view_len], seed=self.seed)


@lru_cache(maxsize=None)
def _cached_model(params: KRaftReconfigParams) -> "KRaftReconfigModel":
    return KRaftReconfigModel(params)
