"""Auto-resume supervisor: wraps ``engine.run()`` and turns hard aborts
into classified, bounded recovery.

The BFS engines stay simple and fail loudly — capacity overflow,
device flakes and torn checkpoints all raise. This driver owns the
policy layer TLC keeps in its outer loop:

  CapacityOverflow   -> ask the engine for a growth policy for the
                        offending bits (``grow_for_overflow``), rebuild
                        with the grown capacities, resume from the
                        wave-start checkpoint the engine saved before
                        raising. Bits with no growth story (msg-slots
                        is model shape, not buffer size) stay fatal.
  transient/crash    -> exponential backoff + seeded jitter, rebuild a
                        fresh engine, resume from the newest intact
                        checkpoint generation.
  ShardLost          -> one shard's device died mid-wave: ask the
                        engine for the surviving device list
                        (``survivors_for_shard_loss``), rebuild on the
                        D-1 mesh, resume from the wave-start checkpoint
                        the engine spilled — the load-time reshard pass
                        re-routes every segment by fp mod (D-1). A
                        single-device mesh has no survivors: fatal.
  ShardStall         -> the per-shard stall watchdog classified a wave
                        as pathologically slow; treated like a
                        transient (backoff + resume, same mesh).
  CheckpointCorrupt  -> when OUR resume checkpoint won't load, fall
                        back to a fresh start (correct, just slower).
  CheckpointMismatch -> unsound to resume; fatal immediately.
  exit_cause
    == "preempted"   -> not a failure: return the result, the CLI maps
                        it to rc 4 and the scheduler restarts us.

Because each resume starts from a wave-start checkpoint of engines
whose exploration is deterministic, a supervised chaos-ridden run ends
with final counts bit-identical to a fault-free run — pinned by the
parity tests in tests/test_resilience.py.
"""

from __future__ import annotations

import os
import random
import time

from .ckpt import DEFAULT_KEEP, generation_path
from .errors import (
    CapacityOverflow,
    CheckpointCorrupt,
    CheckpointError,
    CheckpointMismatch,
    InjectedCrash,
    ShardLost,
    ShardStall,
    UnrecoverableError,
    is_transient,
)

DEFAULT_MAX_RETRIES = 5


def has_checkpoint(path: str | None, keep: int = DEFAULT_KEEP) -> bool:
    """True when any generation of ``path`` exists on disk."""
    if not path:
        return False
    return any(
        os.path.exists(generation_path(path, g)) for g in range(max(1, keep))
    )


def _growth_summary(overrides: dict) -> str:
    return ",".join(f"{k}={overrides[k]}" for k in sorted(overrides)) or "-"


def supervise(
    engine_factory,
    run_kw: dict,
    *,
    max_retries: int = DEFAULT_MAX_RETRIES,
    backoff_base: float = 0.5,
    backoff_max: float = 30.0,
    seed: int = 0,
    telemetry=None,
    verbose: bool = False,
    stats_out: dict | None = None,
):
    """Run ``engine_factory(overrides).run(**run_kw)`` to completion.

    ``engine_factory`` builds a FRESH engine from a dict of constructor
    overrides (empty on the first attempt; grown capacities after an
    overflow, a shrunk device list after a shard loss). A factory may
    return a cached engine when the overrides are empty — that is what
    keeps fleet recoveries recompile-free. ``run_kw`` must route
    checkpoints (``checkpoint_path``) for any recovery beyond pure
    transient-retry to be possible; the supervisor flips its ``resume``
    to the newest intact generation on each recovery attempt.
    ``max_retries`` bounds RECOVERIES, not attempts: attempt 1 is free,
    and every classified failure after it consumes one retry.

    ``stats_out``: optional dict the supervisor fills in place —
    ``recoveries`` (classified failures recovered from) and ``causes``
    (one classification string per recovery) — so fleet drivers can
    record per-job recovery counts without parsing telemetry.

    Returns whatever ``engine.run`` returns. Raises UnrecoverableError
    (with the last failure as ``__cause__``) when the budget is spent
    or a failure has no recovery policy.
    """
    rng = random.Random(seed)
    run_kw = dict(run_kw)
    ckpt_path = run_kw.get("checkpoint_path")
    keep = int(run_kw.get("checkpoint_keep", DEFAULT_KEEP) or DEFAULT_KEEP)
    overrides: dict = {}
    attempt = 0
    retries_left = int(max_retries)

    def _emit_retry(cause: str, backoff_s: float):
        if telemetry is not None:
            telemetry.event(
                "retry",
                attempt=attempt,
                cause=cause,
                backoff_s=round(float(backoff_s), 3),
                growth=_growth_summary(overrides),
            )
        if verbose:
            print(
                f"[supervisor] attempt {attempt} failed ({cause}); "
                f"retrying in {backoff_s:.1f}s"
                + (f" with growth {_growth_summary(overrides)}"
                   if overrides else "")
            )

    def _backoff() -> float:
        if backoff_base <= 0:
            return 0.0
        raw = min(backoff_max, backoff_base * (2.0 ** (attempt - 1)))
        return raw * (0.5 + 0.5 * rng.random())

    def _spend(exc: BaseException, cause: str):
        nonlocal retries_left
        if retries_left <= 0:
            raise UnrecoverableError(
                f"retry budget exhausted after {attempt} attempts "
                f"(last failure: {type(exc).__name__}: {exc})"
            ) from exc
        retries_left -= 1
        if stats_out is not None:
            stats_out["recoveries"] = stats_out.get("recoveries", 0) + 1
            stats_out.setdefault("causes", []).append(cause)
        delay = _backoff()
        _emit_retry(cause, delay)
        if delay > 0:
            time.sleep(delay)

    while True:
        attempt += 1
        engine = engine_factory(dict(overrides))
        try:
            result = engine.run(**run_kw)
        except CapacityOverflow as exc:
            growth = engine.grow_for_overflow(exc.bits)
            if growth is None:
                raise UnrecoverableError(
                    f"capacity overflow with no growth policy "
                    f"(bits={exc.bits:#x}, what={exc.what}): {exc}"
                ) from exc
            _spend(exc, f"overflow:{'+'.join(exc.what) or exc.bits}")
            overrides.update(growth)
            # resume from the newest checkpoint when one exists; every
            # engine (the sharded one included, since it learned to
            # subtract the aborted wave's fingerprints back out of its
            # LSM) writes a wave-start checkpoint at the abort point
            # whenever a checkpoint path is routed, so a grown resume
            # normally loses zero work. A fresh start remains the
            # fallback — sound, just re-explores.
            run_kw["resume"] = (
                ckpt_path
                if exc.checkpoint_saved or has_checkpoint(ckpt_path, keep)
                else None
            )
            continue
        except ShardLost as exc:
            survivors = getattr(engine, "survivors_for_shard_loss", None)
            shrink = survivors(exc.shard) if survivors is not None else None
            if shrink is None:
                raise UnrecoverableError(
                    f"shard {exc.shard} lost with no surviving mesh to "
                    f"reshard onto: {exc}"
                ) from exc
            _spend(exc, f"shard-lost:{exc.shard}")
            overrides.update(shrink)
            run_kw["resume"] = (
                ckpt_path
                if exc.checkpoint_saved or has_checkpoint(ckpt_path, keep)
                else None
            )
            continue
        except ShardStall as exc:
            _spend(exc, f"shard-stall:{exc.shard}")
            if exc.checkpoint_saved or has_checkpoint(ckpt_path, keep):
                run_kw["resume"] = ckpt_path
            continue
        except CheckpointMismatch:
            raise  # unsound to recover; the caller picked a wrong file
        except (CheckpointCorrupt, CheckpointError) as exc:
            # our own resume checkpoint won't load: start over, fresh
            _spend(exc, "ckpt-load")
            run_kw["resume"] = None
            continue
        except Exception as exc:
            if not (isinstance(exc, InjectedCrash) or is_transient(exc)):
                raise
            cause = ("crash" if isinstance(exc, InjectedCrash)
                     else "transient")
            _spend(exc, cause)
            if has_checkpoint(ckpt_path, keep):
                run_kw["resume"] = ckpt_path
            continue
        return result
