"""Preemption handling: turn SIGTERM/SIGINT into a clean wave-boundary
checkpoint instead of losing everything since the last timer tick.

Preemptible TPU VMs get a SIGTERM and a grace window; a wave in these
engines is seconds, so the right response is "finish the wave, write
the checkpoint, exit rc 4" — the scheduler restarts with ``--resume``
and no work is lost. The guard only sets a flag from the handler
(async-signal-safe); engines poll ``requested`` at the wave boundary,
save, and return a result whose ``exit_cause`` is ``"preempted"``.
"""

from __future__ import annotations

import signal


class PreemptionGuard:
    """Installs SIGTERM/SIGINT handlers that record the request.

    Use as a context manager (the CLI does) or via install()/uninstall().
    A second signal while one is pending falls through to the previous
    handler, so a double Ctrl-C still kills a wedged process.
    """

    SIGNALS = (signal.SIGTERM, signal.SIGINT)

    def __init__(self):
        self.requested = False
        self.signame: str | None = None
        self._previous = {}

    def _handle(self, signum, frame):
        if self.requested:
            prev = self._previous.get(signum, signal.SIG_DFL)
            if callable(prev):
                prev(signum, frame)
                return
            signal.signal(signum, prev)
            signal.raise_signal(signum)
            return
        self.requested = True
        self.signame = signal.Signals(signum).name

    def install(self) -> "PreemptionGuard":
        for sig in self.SIGNALS:
            try:
                self._previous[sig] = signal.signal(sig, self._handle)
            except (ValueError, OSError):
                # not the main thread / unsupported platform: stay inert
                pass
        return self

    def uninstall(self) -> None:
        for sig, prev in self._previous.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, OSError):
                pass
        self._previous.clear()

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False
