"""Crash-safe checkpoint I/O shared by all three BFS engines.

TLC's durability contract (SURVEY.md §5.4) is that a long run survives a
crash at ANY instant and resumes bit-identically. The bare ``np.savez``
the engines used before this module had three holes:

  * a crash mid-write left a half-written file AT the final path on
    filesystems where the tmp rename raced the flush — and even with
    tmp+rename, a crash between write and fsync could surface an empty
    file after power loss;
  * nothing detected a truncated/corrupt file at load time: resume
    failed with a numpy ``KeyError``/``BadZipFile`` deep in the loader,
    or worse, loaded stale bytes silently;
  * one file was the only generation — a corruption cost the whole run.

``save_npz`` therefore writes tmp + flush + ``os.fsync`` + ``os.replace``
(+ best-effort directory fsync), embeds ``format_version`` and a
content hash over every array's name/dtype/shape/bytes, and rotates the
previous file through ``path.gen1 .. path.gen{keep-1}`` before the
replace. ``load_npz`` verifies the hash and falls back to the newest
intact generation, reporting what it skipped, so one truncated write
costs at most one checkpoint interval of progress.

Format versions:
  1  pre-resilience (no hash, no coverage field on old files): still
     accepted on load — verification is skipped, engines zero-fill the
     missing fields (pinned by tests/test_resilience.py back-compat).
  2  this module: + format_version, + content_hash, written atomically.

The hash covers the PAYLOAD (sorted field name, dtype, shape, raw
bytes), not the zip container, so it survives numpy/zlib container
differences across versions while still catching any flipped or missing
payload byte.
"""

from __future__ import annotations

import hashlib
import os
import re

import numpy as np

from .errors import CheckpointCorrupt, CheckpointMismatch

FORMAT_VERSION = 2
HASH_KEY = "content_hash"
DEFAULT_KEEP = 3


def generation_path(path: str, gen: int) -> str:
    """On-disk name of generation ``gen`` (0 = the live file)."""
    return path if gen == 0 else f"{path}.gen{gen}"


def content_hash(payload: dict) -> str:
    """Deterministic digest of a checkpoint payload: every field's name,
    dtype, shape and raw bytes, in sorted-name order (the zip member
    order np.savez uses is an implementation detail; this is not)."""
    h = hashlib.blake2b(digest_size=16)
    for key in sorted(payload):
        if key == HASH_KEY:
            continue
        arr = np.asarray(payload[key])
        h.update(key.encode())
        h.update(str(arr.dtype).encode())
        h.update(repr(arr.shape).encode())
        h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()


def _fsync_dir(dirname: str) -> None:
    """Durably record the rename in the directory entry (best effort:
    not every filesystem/platform allows opening a directory)."""
    try:
        fd = os.open(dirname or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def save_npz(path: str, payload: dict, keep: int = DEFAULT_KEEP,
             chaos=None) -> None:
    """Atomically persist ``payload`` at ``path`` with hash + rotation.

    Write order is crash-safe at every step: (1) tmp file written,
    flushed and fsynced — a crash here leaves the old generations
    untouched; (2) existing generations rotate path -> path.gen1 -> ...
    (oldest dropped) — each rename is atomic, and a crash mid-rotation
    leaves every file intact under SOME candidate name the loader
    tries; (3) ``os.replace(tmp, path)`` publishes the new file;
    (4) directory fsync (best effort) makes the renames durable.

    ``chaos``: a ChaosInjector whose ``checkpoint_written`` hook may
    truncate the published file — the deterministic stand-in for a
    crash mid-write that tests drive the generation-fallback path with.
    """
    keep = max(1, int(keep))
    payload = dict(payload)
    payload["format_version"] = np.int64(FORMAT_VERSION)
    payload[HASH_KEY] = content_hash(payload)
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    tmp = f"{path}.tmp.npz"  # .npz suffix stops savez appending one
    with open(tmp, "wb") as fh:
        # uncompressed: multi-GB checkpoints on a 1-core host must not
        # stall the device loop for minutes of zlib
        np.savez(fh, **payload)
        fh.flush()
        os.fsync(fh.fileno())
    # rotate: path -> .gen1 -> .gen2 ... (newest-first numbering)
    for gen in range(keep - 1, 0, -1):
        older = generation_path(path, gen)
        newer = generation_path(path, gen - 1)
        if os.path.exists(newer):
            os.replace(newer, older)
    os.replace(tmp, path)
    _fsync_dir(parent)
    if chaos is not None:
        chaos.checkpoint_written(path)


def _read_verify(path: str) -> dict:
    """Load one candidate file fully and verify it. Raises CheckpointCorrupt
    (truncated/unreadable/hash mismatch) or returns the payload dict.
    Version-1 files (no hash) load unverified for back-compat."""
    try:
        with np.load(path, allow_pickle=False) as ck:
            payload = {k: np.asarray(ck[k]) for k in ck.files}
    except Exception as e:  # zipfile.BadZipFile, OSError, ValueError ...
        raise CheckpointCorrupt(
            f"{path}: unreadable ({type(e).__name__}: {e})"
        ) from e
    # hash verification is keyed on the CONTAINER revision
    # (format_version, stamped by save_npz) — not on the engines' own
    # payload-layout "version" field, which revs independently (the
    # sharded engine's mesh-portable layout is payload v2 but any
    # container may carry it)
    version = (
        int(payload["format_version"]) if "format_version" in payload else 1
    )
    if version >= 2:
        stored = str(payload.get(HASH_KEY, ""))
        if not stored:
            raise CheckpointCorrupt(f"{path}: format v{version} but no hash")
        if content_hash(payload) != stored:
            raise CheckpointCorrupt(
                f"{path}: content hash mismatch (truncated or corrupt write)"
            )
    return payload


def load_npz(path: str, keep: int = DEFAULT_KEEP) -> tuple[dict, int, list[str]]:
    """Load the newest intact generation of ``path``.

    Tries ``path``, then ``path.gen1`` .. ``path.gen{keep-1}``; the
    first candidate whose content hash verifies wins. Returns
    ``(payload, generation, skipped)`` where ``skipped`` holds one
    diagnostic line per rejected newer candidate (for the
    ``ckpt_generation`` telemetry event and the operator's log).
    Raises CheckpointCorrupt when no generation is intact and
    FileNotFoundError when no candidate exists at all.
    """
    skipped: list[str] = []
    tried_any = False
    for gen in range(max(1, int(keep))):
        cand = generation_path(path, gen)
        if not os.path.exists(cand):
            continue
        tried_any = True
        try:
            payload = _read_verify(cand)
        except CheckpointCorrupt as e:
            skipped.append(str(e))
            continue
        return payload, gen, skipped
    if not tried_any:
        raise FileNotFoundError(
            f"no checkpoint at {path} (or any .gen* generation)"
        )
    raise CheckpointCorrupt(
        f"no intact checkpoint generation at {path}",
        problems=tuple(skipped),
    )


def format_version_of(payload: dict) -> int:
    """The payload's checkpoint-format version (1 for pre-resilience
    files that only carried the engine's own ``version=1`` field)."""
    if "format_version" in payload:
        return int(payload["format_version"])
    return int(payload.get("version", 1))


# The sharded engine's ident embeds its mesh size as /D=<n>/ — that D
# is PROVENANCE (which mesh wrote the file), not identity: the payload
# is a set of per-shard sorted-fingerprint segments that reshard onto
# any mesh by fp mod D_new. These helpers strip/extract it so check_spec
# can tell "different model" from "same model, different mesh".
_MESH_D_RE = re.compile(r"/D=(\d+)")


def mesh_d_of(spec: str) -> int | None:
    """Mesh size recorded in a checkpoint ident, or None when the ident
    has no /D=<n>/ component (host and single-device engines)."""
    m = _MESH_D_RE.search(spec)
    return int(m.group(1)) if m else None


def mesh_neutral(spec: str) -> str:
    """The ident with its /D=<n> provenance component removed — two
    specs with equal neutral forms differ only in mesh size."""
    return _MESH_D_RE.sub("", spec)


def lineage_name(name: str, index: int) -> str:
    """Per-job checkpoint filename inside a fleet's checkpoint_dir.

    Sanitizing alone is ambiguous — "a/b" and "a_b" both sanitize to
    "a_b" — so the job's position in the fleet disambiguates the
    lineage (job order is part of the packed layout, hence stable)."""
    safe = re.sub(r"[^A-Za-z0-9._=-]", "_", name)
    return f"{safe}.j{int(index)}.ckpt.npz"


def check_spec(payload: dict, expect_ident: str, path: str,
               allow_reshard: bool = False) -> None:
    """Refuse a checkpoint whose identity or format this build cannot
    soundly resume. The messages are load-bearing: the "checkpoint is
    for spec" prefix is a documented contract (tests match it), and a
    future format version must fail HERE with a clear sentence, not
    later with a numpy KeyError.

    ``allow_reshard``: accept a checkpoint whose ident differs from
    ``expect_ident`` ONLY in its /D=<n> mesh-size component — the
    sharded engine re-routes the segments by fp mod D_new at load time.
    When False, a pure mesh mismatch still fails, but with a message
    naming both mesh sizes and the reshard path instead of the generic
    spec mismatch."""
    version = format_version_of(payload)
    if version > FORMAT_VERSION:
        raise CheckpointMismatch(
            f"{path}: checkpoint format v{version} is newer than this "
            f"build's v{FORMAT_VERSION}; upgrade raft_tpu to resume it"
        )
    spec = str(payload.get("spec", "<missing spec field>"))
    if spec == expect_ident:
        return
    d_ck, d_run = mesh_d_of(spec), mesh_d_of(expect_ident)
    if (d_ck is not None and d_run is not None and d_ck != d_run
            and mesh_neutral(spec) == mesh_neutral(expect_ident)):
        if allow_reshard:
            return
        raise CheckpointMismatch(
            f"{path}: checkpoint was written on a D={d_ck} mesh, this run "
            f"is on D={d_run} — the payload is mesh-portable; drop "
            f"--no-reshard to re-route the shards by fp mod {d_run} on "
            f"resume"
        )
    raise CheckpointMismatch(
        f"checkpoint is for spec {spec}, model is {expect_ident}"
    )


def validate_resume(path: str, expect_ident: str,
                    keep: int = DEFAULT_KEEP,
                    allow_reshard: bool = False) -> tuple[int, int]:
    """Fail-fast --resume validation: prove the checkpoint exists, loads
    (falling back through generations), and matches the model identity —
    BEFORE the caller pays the multi-second precompile. Returns
    ``(generation, depth)`` of the checkpoint that will be used."""
    payload, gen, _skipped = load_npz(path, keep=keep)
    check_spec(payload, expect_ident, path, allow_reshard=allow_reshard)
    return gen, int(payload.get("depth", 0))
