"""Self-healing runtime: crash-safe checkpoints, fault classification,
auto-resume supervision, preemption handling and deterministic chaos.

See the individual modules for the design notes; README "Fault
tolerance & resume" has the operator-facing story.
"""

from .chaos import ChaosInjector, ChaosSpec
from .ckpt import (
    DEFAULT_KEEP,
    FORMAT_VERSION,
    check_spec,
    content_hash,
    format_version_of,
    generation_path,
    lineage_name,
    load_npz,
    mesh_d_of,
    mesh_neutral,
    save_npz,
    validate_resume,
)
from .errors import (
    CapacityOverflow,
    CheckpointCorrupt,
    CheckpointError,
    CheckpointMismatch,
    InjectedCrash,
    InjectedTransient,
    ShardLost,
    ShardStall,
    UnrecoverableError,
    is_transient,
)
from .preempt import PreemptionGuard
from .supervisor import DEFAULT_MAX_RETRIES, has_checkpoint, supervise

__all__ = [
    "CapacityOverflow",
    "ChaosInjector",
    "ChaosSpec",
    "CheckpointCorrupt",
    "CheckpointError",
    "CheckpointMismatch",
    "DEFAULT_KEEP",
    "DEFAULT_MAX_RETRIES",
    "FORMAT_VERSION",
    "InjectedCrash",
    "InjectedTransient",
    "PreemptionGuard",
    "ShardLost",
    "ShardStall",
    "UnrecoverableError",
    "check_spec",
    "content_hash",
    "format_version_of",
    "generation_path",
    "has_checkpoint",
    "is_transient",
    "lineage_name",
    "load_npz",
    "mesh_d_of",
    "mesh_neutral",
    "save_npz",
    "supervise",
    "validate_resume",
]
