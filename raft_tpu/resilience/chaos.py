"""Deterministic fault injection for the self-healing runtime.

A resilience story nobody can exercise is a resilience story that rots.
This harness turns "what if the process dies at wave 3 and the latest
checkpoint write was torn" into a one-flag reproducible run:

    raft_tpu raft.cfg --supervise --checkpoint ck.npz \
        --chaos crash=3,truncate=2,ovf=4,seed=7

Spec grammar (comma-separated ``key=int`` pairs, each fault fires once):

  crash=K      raise InjectedCrash at the start of wave K (process-death
               stand-in; the supervisor rebuilds and resumes)
  transient=K  raise InjectedTransient at the start of wave K (flaky
               dispatch stand-in; retried with backoff, same engine)
  ovf=K        OR a spurious frontier-overflow bit into wave K's overflow
               word, forcing the abort-with-wave-start-checkpoint path
               and the supervisor's grow-and-resume policy
  truncate=N   tear the N-th checkpoint write (truncate the published
               file to a third) so load must fall back a generation
  preempt=K    deliver a real SIGTERM to this process at the start of
               wave K, exercising the actual signal handler and the
               rc-4 checkpoint-at-wave-boundary path
  shard_loss=K kill one shard's device mid-wave K (sharded engine only):
               the engine spills a redistributable wave-start checkpoint
               and raises ShardLost; the supervisor reshards onto the
               surviving D-1 mesh. The doomed shard is seed % D so the
               scenario replays from the command line alone.
  seed=S       seeds the truncation cut point and the doomed shard;
               recorded so a chaos run is reproducible from its command
               line alone

Hooks are called from engine wave loops (``wave_start``, ``ovf_bits``)
and from ``ckpt.save_npz`` (``checkpoint_written``). One injector
instance is shared across supervisor attempts, so a consumed fault
never re-fires after recovery — which is what lets the parity tests
assert the chaos run's final counts equal the fault-free run's.
"""

from __future__ import annotations

import os
import random
import signal

from .errors import InjectedCrash, InjectedTransient

# "seed" must stay last: __str__ iterates _KEYS[:-1] for the fault keys
_KEYS = ("crash", "transient", "ovf", "truncate", "preempt", "shard_loss",
         "seed")


class ChaosSpec:
    """Parsed, validated ``--chaos`` specification."""

    def __init__(self, crash=None, transient=None, ovf=None,
                 truncate=None, preempt=None, shard_loss=None, seed=0):
        self.crash = crash
        self.transient = transient
        self.ovf = ovf
        self.truncate = truncate
        self.preempt = preempt
        self.shard_loss = shard_loss
        self.seed = int(seed)

    @classmethod
    def parse(cls, text: str) -> "ChaosSpec":
        kw = {}
        for part in filter(None, (p.strip() for p in text.split(","))):
            key, eq, val = part.partition("=")
            if not eq or key not in _KEYS:
                raise ValueError(
                    f"bad chaos spec element {part!r}: expected key=int with "
                    f"key in {_KEYS}"
                )
            try:
                ival = int(val)
            except ValueError:
                raise ValueError(
                    f"bad chaos spec element {part!r}: {val!r} is not an int"
                ) from None
            if key != "seed" and ival < 1:
                raise ValueError(
                    f"bad chaos spec element {part!r}: wave/count must be >= 1"
                )
            if key in kw:
                raise ValueError(f"duplicate chaos spec key {key!r}")
            kw[key] = ival
        return cls(**kw)

    def __str__(self):
        parts = [f"{k}={getattr(self, k)}" for k in _KEYS[:-1]
                 if getattr(self, k) is not None]
        parts.append(f"seed={self.seed}")
        return ",".join(parts)


class ChaosInjector:
    """Executes a ChaosSpec. Each fault is consumed exactly once across
    the lifetime of THIS object — share one injector across supervisor
    retries so recovery runs re-execute the faulted wave cleanly."""

    def __init__(self, spec: ChaosSpec):
        self.spec = spec
        self._rng = random.Random(spec.seed)
        self._pending = {
            k: getattr(spec, k)
            for k in ("crash", "transient", "ovf", "preempt", "shard_loss")
            if getattr(spec, k) is not None
        }
        self._writes_seen = 0
        self._truncate_at = spec.truncate
        self.fired: list[str] = []

    def _consume(self, key: int) -> bool:
        if key in self._pending:
            del self._pending[key]
            self.fired.append(key)
            return True
        return False

    # --- engine hooks -------------------------------------------------

    def wave_start(self, wave: int) -> None:
        """Called at the top of each wave with the 1-based wave number
        about to be explored. May raise or signal; ordering is
        preempt < crash < transient when several target the same wave
        (a SIGTERM only sets a flag, so it composes with the others)."""
        if self._pending.get("preempt") == wave and self._consume("preempt"):
            os.kill(os.getpid(), signal.SIGTERM)
        if self._pending.get("crash") == wave and self._consume("crash"):
            raise InjectedCrash(f"chaos: injected crash at wave {wave}")
        if self._pending.get("transient") == wave and self._consume("transient"):
            raise InjectedTransient(
                f"chaos: injected transient dispatch failure at wave {wave}"
            )

    def ovf_bits(self, bits: int, wave: int, frontier_bit: int) -> int:
        """Called with the wave's fetched overflow word; ORs in a
        spurious frontier-capacity bit once at the configured wave."""
        if self._pending.get("ovf") == wave and self._consume("ovf"):
            return int(bits) | int(frontier_bit)
        return int(bits)

    def shard_loss(self, wave: int, n_shards: int) -> int | None:
        """Called from the sharded engine's chunk loop with the 1-based
        wave in flight; returns the shard to kill (seed % n_shards, so
        the scenario is reproducible from the spec alone) once at the
        configured wave, None otherwise."""
        if (self._pending.get("shard_loss") == wave
                and self._consume("shard_loss")):
            return self.spec.seed % max(1, int(n_shards))
        return None

    def checkpoint_written(self, path: str) -> None:
        """Called by ckpt.save_npz after each successful publish; tears
        the configured N-th write by truncating the file partway."""
        if self._truncate_at is None:
            return
        self._writes_seen += 1
        if self._writes_seen != self._truncate_at:
            return
        self._truncate_at = None
        self.fired.append("truncate")
        size = os.path.getsize(path)
        # cut somewhere in the middle third: enough bytes survive that
        # np.load gets past the magic, not enough that the hash verifies
        cut = max(1, size // 3 + self._rng.randrange(max(1, size // 3)))
        with open(path, "r+b") as fh:
            fh.truncate(cut)
