"""Typed failure taxonomy of the self-healing runtime.

The supervisor (resilience/supervisor.py) retries on *classes*, not on
string-matched messages, so every abort path an engine can take gets a
type here. Subclassing keeps old callers working: ``CapacityOverflow``
IS-A ``OverflowError`` (every pre-existing ``pytest.raises(OverflowError)``
still passes) and ``CheckpointMismatch`` IS-A ``ValueError`` (the
"checkpoint is for spec ..." contract tests keep matching).
"""

from __future__ import annotations


class CapacityOverflow(OverflowError):
    """A static device capacity was exceeded mid-run.

    ``what`` names the offending capacities (subset of ``frontier``,
    ``journal``, ``valid``, ``route``, ``msg``, ``seen``), derived from
    the engine's overflow bits. ``bits`` keeps the raw engine-specific
    bit vector for the message. ``checkpoint_saved`` is True when the
    engine spilled a resumable wave-start checkpoint before raising —
    the supervisor only regrows-and-resumes when it did.
    """

    def __init__(
        self,
        message: str,
        what: tuple[str, ...] = (),
        bits: int = 0,
        checkpoint_saved: bool = False,
    ):
        super().__init__(message)
        self.what = tuple(what)
        self.bits = int(bits)
        self.checkpoint_saved = bool(checkpoint_saved)


class CheckpointError(RuntimeError):
    """Base for any checkpoint load/save problem."""


class CheckpointCorrupt(CheckpointError):
    """No intact generation could be loaded (truncation, hash mismatch,
    unreadable zip). ``problems`` lists one line per rejected candidate
    so the operator sees exactly what was tried."""

    def __init__(self, message: str, problems: tuple[str, ...] = ()):
        super().__init__(message)
        self.problems = tuple(problems)


class CheckpointMismatch(CheckpointError, ValueError):
    """The checkpoint loaded fine but belongs to a different spec/format
    (wrong model ident, wrong mesh, future format version). Resuming
    would be unsound, never merely slow — no retry."""


class InjectedCrash(RuntimeError):
    """Deterministic fault from the chaos harness standing in for a
    process death (power loss, OOM-kill, TPU preemption without grace)."""


class InjectedTransient(RuntimeError):
    """Deterministic fault standing in for a transient device/dispatch
    error (flaky ICI link, one-off XLA runtime error) — the class the
    supervisor retries with backoff WITHOUT rebuilding capacities."""


class ShardLost(RuntimeError):
    """One shard's device died mid-wave (real preemption or the chaos
    harness's ``shard_loss=K`` stand-in). ``shard`` is the dead shard's
    index on the mesh that observed the loss; ``checkpoint_saved`` is
    True when the engine spilled a redistributable wave-start checkpoint
    before raising — the supervisor reshards that checkpoint onto the
    surviving D-1 mesh and continues."""

    def __init__(self, message: str, shard: int = -1,
                 checkpoint_saved: bool = False):
        super().__init__(message)
        self.shard = int(shard)
        self.checkpoint_saved = bool(checkpoint_saved)


class ShardStall(RuntimeError):
    """The per-shard stall watchdog classified a wave as pathologically
    slow (``wave_s`` > factor x the rolling-median wave time) and the
    engine aborted at the wave boundary instead of hanging the
    all-to-all. ``shard`` is the suspect (most-loaded) shard. The
    supervisor treats this like a transient: backoff and resume from the
    wave-start checkpoint (``checkpoint_saved``) or the newest periodic
    generation."""

    def __init__(self, message: str, shard: int = -1, wave_s: float = 0.0,
                 median_s: float = 0.0, checkpoint_saved: bool = False):
        super().__init__(message)
        self.shard = int(shard)
        self.wave_s = float(wave_s)
        self.median_s = float(median_s)
        self.checkpoint_saved = bool(checkpoint_saved)


class UnrecoverableError(RuntimeError):
    """The supervisor exhausted its retry budget (or hit a failure with
    no recovery policy). Carries the last underlying failure as
    ``__cause__``; the CLI maps this to exit code 5."""


# exception type NAMES treated as transient device/dispatch failures
# (matched by name so importing jaxlib internals is not required; a
# rebuilt engine + resume is the correct response to all of them)
TRANSIENT_TYPE_NAMES = (
    "XlaRuntimeError",
    "InternalError",
    "UnavailableError",
    "JaxRuntimeError",
)


def is_transient(exc: BaseException) -> bool:
    """Transient device/dispatch failures: retry with backoff, same
    capacities. Anything raised by the chaos harness's transient hook
    counts by construction."""
    if isinstance(exc, InjectedTransient):
        return True
    for klass in type(exc).__mro__:
        if klass.__name__ in TRANSIENT_TYPE_NAMES:
            return True
    return False
