"""raft_tpu.obs — run-time telemetry for the BFS engines.

Live counterpart of the offline stage profiler (checker/profile.py):
per-wave JSONL metrics (events.py), a TLC-style progress line
(progress.py), jax.profiler trace hooks (trace.py), the
collector/facade threading them through the engines (collector.py),
and TLC-style per-action coverage rendering (coverage.py).

    from raft_tpu.obs import Telemetry
    tel = Telemetry(metrics_path="m.jsonl", progress_every=10.0)
    res = DeviceBFS(model, ...).run(telemetry=tel)
    tel.close()
"""

from .collector import (
    JobTaggedTelemetry,
    MetricsCollector,
    NULL_TELEMETRY,
    Telemetry,
)
from .coverage import coverage_digest, dead_actions, render_coverage_table
from .events import (
    CKPT_GENERATION_KEYS,
    COVERAGE_KEYS,
    DECLARED_EVENTS,
    EVENT_KEYS,
    EXIT_CAUSES,
    MANIFEST_KEYS,
    MEMWATCH_KEYS,
    PREEMPT_KEYS,
    RESUME_KEYS,
    RETRY_KEYS,
    SHARD_WAVE_KEYS,
    STALL_KEYS,
    SUMMARY_KEYS,
    TIMELINE_KEYS,
    TIMELINE_STAGES,
    WAVE_KEYS,
    hashv_of,
    validate_event,
    validate_lines,
)
from .memwatch import MemWatch, budget_from_env
from .progress import ProgressRenderer, format_count
from .trace import TraceHooks

__all__ = [
    "CKPT_GENERATION_KEYS",
    "COVERAGE_KEYS",
    "DECLARED_EVENTS",
    "EVENT_KEYS",
    "EXIT_CAUSES",
    "MANIFEST_KEYS",
    "MEMWATCH_KEYS",
    "PREEMPT_KEYS",
    "RESUME_KEYS",
    "RETRY_KEYS",
    "SHARD_WAVE_KEYS",
    "STALL_KEYS",
    "SUMMARY_KEYS",
    "TIMELINE_KEYS",
    "TIMELINE_STAGES",
    "WAVE_KEYS",
    "JobTaggedTelemetry",
    "MemWatch",
    "MetricsCollector",
    "NULL_TELEMETRY",
    "ProgressRenderer",
    "Telemetry",
    "TraceHooks",
    "budget_from_env",
    "coverage_digest",
    "dead_actions",
    "format_count",
    "hashv_of",
    "render_coverage_table",
    "validate_event",
    "validate_lines",
]
