"""TLC-style action coverage: table rendering, dead-action detection
and the digest attached to BENCH provenance.

The input everywhere is the cumulative per-action counter block the
engines accumulate on device — ``actions[rank] = [enabled, fired,
new_distinct]`` with ``rank`` indexing the model's ``ACTION_NAMES``
(the Next-disjunct order):

  enabled       (state, action) pairs where the disjunct's guard held
                on a live frontier state — i.e. at least one candidate
                of that rank was valid for the state
  fired         successor generations attributed to the rank (every
                valid candidate lane, pre-dedup)
  new_distinct  distinct states the rank contributed first (post-dedup,
                post-symmetry; first-writer-wins under TLC tie order)

TLC's ``-coverage`` prints fired/distinct per action; ``enabled`` is
the extra column our lowering needs, because a dead disjunct whose
guard also never holds is a *model-scale* artifact, while a disjunct
that is enabled but never fires is a *lowering bug*.

Dependency-free (no jax/numpy): the CLI table, scripts/obs_report.py
and scripts/check_metrics_schema.py all render from plain lists.
"""

from __future__ import annotations

COLUMNS = ("enabled", "fired", "new distinct")


def _rows(action_names, actions) -> list[tuple[str, int, int, int]]:
    names = list(action_names)
    out = []
    for r, row in enumerate(actions):
        name = names[r] if r < len(names) else f"action[{r}]"
        e, f, n = (int(row[0]), int(row[1]), int(row[2]))
        out.append((name, e, f, n))
    return out


def dead_actions(action_names, actions) -> list[str]:
    """Names of actions that never fired (fired == 0), in rank order."""
    return [name for name, _e, f, _n in _rows(action_names, actions) if f == 0]


def render_coverage_table(action_names, actions, title: str | None = None) -> str:
    """The end-of-run ``--coverage`` table (TLC -coverage analog), one
    row per Next disjunct, with an explicit WARNING line per action
    that never fired."""
    rows = _rows(action_names, actions)
    lines = [title or "Action coverage (cumulative over the run):"]
    if not rows:
        lines.append("  (no per-action coverage recorded)")
        return "\n".join(lines)
    wname = max(len("action"), max(len(r[0]) for r in rows))
    head = f"  {'action':<{wname}}"
    for c in COLUMNS:
        head += f"  {c:>12}"
    lines.append(head)
    for name, e, f, n in rows:
        lines.append(f"  {name:<{wname}}  {e:>12}  {f:>12}  {n:>12}")
    for name in dead_actions(action_names, actions):
        lines.append(f"WARNING: action {name} never fired")
    return "\n".join(lines)


def coverage_digest(action_names, actions) -> dict:
    """Provenance block for BENCH rows: exploration completeness in four
    scalars, so rows stay comparable on coverage, not just throughput."""
    rows = _rows(action_names, actions)
    if not rows:
        return {"actions_total": 0, "actions_fired": 0,
                "min_fire_action": None, "min_fire_count": None}
    least = min(rows, key=lambda r: r[2])
    return {
        "actions_total": len(rows),
        "actions_fired": sum(1 for r in rows if r[2] > 0),
        "min_fire_action": least[0],
        "min_fire_count": least[2],
    }
