"""Run-time metrics collection for the BFS engines.

``MetricsCollector`` consumes the per-wave host-side snapshot of the
device stats vector — the engines already fetch it once per wave to
drive the loop (overflow check, frontier count), so collection adds ZERO
extra device syncs; tests/test_obs.py pins that. Output is one JSONL
event per wave (events.py schema) plus a manifest/summary pair per run.

The file write is double-buffered: the line for wave N hits disk when
wave N+1's snapshot arrives (or at close), so file I/O overlaps the
device's next wave and never sits between a dispatch and its sync. A
tailing reader therefore lags the run by at most one event.

The wall-clock watchdog keeps a rolling window of wave times and emits a
``stall`` event whenever a wave exceeds ``stall_factor`` x the window
median — the symptom of a mid-run recompile, a growth retrace, a
checkpoint spill on a slow disk, or a preempted device.

``Telemetry`` is the facade the engines thread through ``run()``: one
object bundling the collector, the optional TLC-style progress renderer
and the jax.profiler trace hooks. ``NULL_TELEMETRY`` is the do-nothing
instance engines default to, so the hot loop never branches on None.
"""

from __future__ import annotations

import json
import statistics
import time
from contextlib import nullcontext

from .events import EVENT_KEYS
from .progress import ProgressRenderer
from .trace import TraceHooks


class MetricsCollector:
    """Per-wave event sink with cadence, watchdog and JSONL output."""

    def __init__(
        self,
        path: str | None = None,
        every: int = 1,
        stall_factor: float = 4.0,
        stall_window: int = 16,
        stall_min_waves: int = 5,
        keep: bool = True,
    ):
        assert every >= 1, "cadence is in waves; minimum 1"
        self.every = int(every)
        self.stall_factor = float(stall_factor)
        self.stall_min_waves = int(stall_min_waves)
        self.events: list[dict] = [] if keep else None
        self._fh = open(path, "w") if path else None
        self._pending: str | None = None  # double-buffered JSONL line
        self._listeners: list = []
        self._wave = 0
        self._wave_times: list[float] = []
        self._wave_window = int(stall_window)
        self._last_skipped: dict | None = None
        self._last_skipped_cov: dict | None = None
        self.stalls = 0
        self.last_summary: dict | None = None

    # ---------------- sinks ----------------

    def add_listener(self, fn) -> None:
        """fn(event) is called for EVERY event (cadence does not apply:
        a progress renderer throttles by wall clock, not wave count)."""
        self._listeners.append(fn)

    def _write(self, ev: dict) -> None:
        if self.events is not None:
            self.events.append(ev)
        if self._fh is not None:
            if self._pending is not None:
                self._fh.write(self._pending + "\n")
            self._pending = json.dumps(ev)

    def _notify(self, ev: dict) -> None:
        for fn in self._listeners:
            fn(ev)

    # ---------------- event entry points ----------------

    def manifest(self, fields: dict) -> None:
        """Open a run: reset per-run state, emit the manifest event."""
        self._wave = 0
        self._wave_times = []
        self._last_skipped = None
        self._last_skipped_cov = None
        self.stalls = 0
        ev = {"event": "manifest", **fields}
        self._write(ev)
        self._notify(ev)

    def wave(self, fields: dict) -> None:
        """One wave's host-side snapshot (all values already on host)."""
        self._wave += 1
        ev = {"event": "wave", "wave": self._wave, **fields}
        # watchdog BEFORE the current wave joins the window (a stalled
        # wave must not drag the median it is judged against)
        wave_s = float(fields.get("wave_s", 0.0))
        if len(self._wave_times) >= self.stall_min_waves:
            med = statistics.median(self._wave_times)
            if med > 0 and wave_s > self.stall_factor * med:
                self.stalls += 1
                stall = {
                    "event": "stall",
                    "wave": self._wave,
                    "depth": fields.get("depth"),
                    "wave_s": round(wave_s, 3),
                    "median_wave_s": round(med, 3),
                    "factor": round(wave_s / med, 1),
                }
                self._write(stall)
                self._notify(stall)
        self._wave_times.append(wave_s)
        if len(self._wave_times) > self._wave_window:
            self._wave_times.pop(0)
        if (self._wave - 1) % self.every == 0:
            self._write(ev)
            self._last_skipped = None
        else:
            self._last_skipped = ev
        self._notify(ev)

    def coverage(self, fields: dict, final: bool = False) -> None:
        """Cumulative coverage snapshot for the wave just reported (call
        after ``wave()``; shares its cadence so the JSONL pairs up). The
        ``final`` snapshot — the engine's end-of-run cumulative totals,
        the only one carrying the canon-memo fill ratio — always writes
        and supersedes any cadence-skipped snapshot."""
        ev = {
            "event": "coverage", "wave": self._wave, **fields,
            "final": bool(final),
        }
        if final or (self._wave - 1) % self.every == 0 or self._wave == 0:
            self._write(ev)
            self._last_skipped_cov = None
        else:
            self._last_skipped_cov = ev
        self._notify(ev)

    def event(self, etype: str, **fields) -> None:
        """Low-volume out-of-band event (the resilience events: retry,
        resume, ckpt_generation, preempt). Always written — cadence is
        for per-wave volume; a recovery narrative must never be
        sampled away."""
        assert etype in EVENT_KEYS, f"unknown event type {etype!r}"
        ev = {"event": etype, **fields}
        self._write(ev)
        self._notify(ev)

    def summary(self, fields: dict) -> None:
        """Close a run: flush the newest skipped wave (the stream must
        end count-accurate at any cadence), emit the summary event."""
        if self._last_skipped is not None:
            self._write(self._last_skipped)
            self._last_skipped = None
        if self._last_skipped_cov is not None:
            self._write(self._last_skipped_cov)
            self._last_skipped_cov = None
        ev = {
            "event": "summary",
            **fields,
            "waves": self._wave,
            "stalls": self.stalls,
        }
        self.last_summary = ev
        self._write(ev)
        self._notify(ev)

    def close(self) -> None:
        if self._fh is not None:
            if self._pending is not None:
                self._fh.write(self._pending + "\n")
                self._pending = None
            self._fh.close()
            self._fh = None

    # ---------------- convenience ----------------

    def events_of(self, etype: str) -> list[dict]:
        assert etype in EVENT_KEYS, f"unknown event type {etype!r}"
        return [e for e in (self.events or ()) if e["event"] == etype]


class Telemetry:
    """Everything an engine run() threads through: collector + progress
    renderer + trace hooks. Construct once, pass as ``telemetry=``;
    reusable across multiple runs (each emits manifest..summary);
    ``close()`` (or the context manager) flushes the JSONL file and
    stops the profiler trace."""

    active = True

    def __init__(
        self,
        metrics_path: str | None = None,
        every: int = 1,
        progress_every: float | None = None,
        progress_stream=None,
        trace_dir: str | None = None,
        stall_factor: float = 4.0,
        keep_events: bool = True,
        timeline_every: int = 0,
    ):
        # timeline_every > 0 asks the engines to run every Nth wave as
        # separately timed stage dispatches (`timeline` events); 0 = off
        # and every wave keeps the fused program. See obs/events.py
        # TIMELINE_STAGES and the engines' _run_timeline_wave.
        self.timeline_every = int(timeline_every)
        self.collector = MetricsCollector(
            path=metrics_path, every=every, stall_factor=stall_factor,
            keep=keep_events,
        )
        self.progress = None
        if progress_every is not None:
            self.progress = ProgressRenderer(
                every_s=progress_every, stream=progress_stream
            )
            self.collector.add_listener(self.progress)
        self.trace = TraceHooks(trace_dir)

    # -- engine-facing --

    def open_run(self, manifest: dict) -> None:
        self.trace.ensure_started()
        self.collector.manifest(manifest)

    def wave(self, fields: dict) -> None:
        self.collector.wave(fields)

    def coverage(self, fields: dict, final: bool = False) -> None:
        self.collector.coverage(fields, final=final)

    def event(self, etype: str, **fields) -> None:
        self.collector.event(etype, **fields)

    def close_run(self, summary: dict) -> None:
        self.collector.summary(summary)

    def wave_annotation(self, depth: int):
        return self.trace.wave(depth)

    def annotate(self, name: str):
        return self.trace.section(name)

    # -- caller-facing --

    @property
    def events(self) -> list[dict]:
        return self.collector.events or []

    @property
    def last_summary(self) -> dict | None:
        return self.collector.last_summary

    def wave_events(self) -> list[dict]:
        return self.collector.events_of("wave")

    def coverage_events(self) -> list[dict]:
        return self.collector.events_of("coverage")

    def close(self) -> None:
        self.collector.close()
        self.trace.stop()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class JobTaggedTelemetry:
    """Proxy that stamps a ``job`` field onto every event it forwards.

    The fleet queue arm (DeviceBFS/ShardedBFS.run_fleet) wraps the
    caller's Telemetry with one of these per job, so N sequential runs
    multiplex into ONE metrics stream that obs_report and
    check_metrics_schema can split back out per job. ``close()`` is a
    no-op — the owner of the inner Telemetry closes it once after the
    whole fleet."""

    def __init__(self, inner, job: str):
        self._inner = inner if inner is not None else NULL_TELEMETRY
        self.job = job

    @property
    def active(self) -> bool:
        return self._inner.active

    @property
    def timeline_every(self) -> int:
        return getattr(self._inner, "timeline_every", 0)

    def open_run(self, manifest: dict) -> None:
        self._inner.open_run({**manifest, "job": self.job})

    def wave(self, fields: dict) -> None:
        self._inner.wave({**fields, "job": self.job})

    def coverage(self, fields: dict, final: bool = False) -> None:
        self._inner.coverage({**fields, "job": self.job}, final=final)

    def event(self, etype: str, **fields) -> None:
        self._inner.event(etype, job=self.job, **fields)

    def close_run(self, summary: dict) -> None:
        self._inner.close_run({**summary, "job": self.job})

    def wave_annotation(self, depth: int):
        return self._inner.wave_annotation(depth)

    def annotate(self, name: str):
        return self._inner.annotate(name)

    @property
    def events(self):
        return self._inner.events

    @property
    def last_summary(self):
        return self._inner.last_summary

    def close(self) -> None:
        pass


class _NullTelemetry:
    """Shared inert instance: the engines' default, so the wave loop
    calls methods unconditionally instead of branching on None."""

    active = False
    events = ()
    last_summary = None
    timeline_every = 0

    def open_run(self, manifest: dict) -> None:
        pass

    def wave(self, fields: dict) -> None:
        pass

    def coverage(self, fields: dict, final: bool = False) -> None:
        pass

    def event(self, etype: str, **fields) -> None:
        pass

    def close_run(self, summary: dict) -> None:
        pass

    def wave_annotation(self, depth: int):
        return nullcontext()

    def annotate(self, name: str):
        return nullcontext()

    def close(self) -> None:
        pass


NULL_TELEMETRY = _NullTelemetry()
