"""Analytic HBM watermark accounting (the out-of-core planning input).

The engines already know every buffer's geometry — frontier capacity
and fill, the VC-wide chunk block, the seen-set ladder / LSM runs, the
journal cursor, the canon memo table. ``MemWatch`` turns that geometry
into live-bytes per wave WITHOUT reading the device (no syncs, no
allocator introspection — this is the planning model, not a profiler):
each wave the engine hands it a ``{buffer family: live bytes}``
breakdown, it tracks the running peak, and it emits a ``memwatch``
event whenever a wave sets a new watermark (so the stream stays
low-volume and peak_bytes is monotone within a run by construction).

``frac`` = total live bytes / budget is the gauge the progress line
renders (``hbm NN%``) and the wave event carries (``hbm_frac``). The
budget defaults to the ``RAFT_TPU_HBM_BUDGET`` environment variable
(bytes) or 16 GiB — one TPUv4 core's HBM — because the point of the
gauge on a CPU dry-run is to predict where the same geometry will sit
on the real chip. A frac above 1.0 is legal and is exactly the signal
ROADMAP item 2 (out-of-core BFS) plans from.

Dependency-free (no jax/numpy): byte math is host ints.
"""

from __future__ import annotations

import os

# one TPUv4 core's HBM; override with RAFT_TPU_HBM_BUDGET (bytes)
DEFAULT_BUDGET_BYTES = 16 << 30


def budget_from_env(default: int = DEFAULT_BUDGET_BYTES) -> int:
    raw = os.environ.get("RAFT_TPU_HBM_BUDGET", "")
    try:
        v = int(raw)
    except ValueError:
        return default
    return v if v > 0 else default


class MemWatch:
    """Per-run watermark tracker; one instance per engine run().

    ``update(wave, depth, breakdown)`` returns the fraction-of-budget
    gauge for the wave event and emits a ``memwatch`` event through
    ``tel`` iff the wave set a new peak. ``tel`` may be None (or an
    inactive telemetry facade): the gauge still computes, nothing is
    emitted.
    """

    def __init__(self, tel=None, budget_bytes: int | None = None):
        self.tel = tel
        self.budget_bytes = int(budget_bytes or budget_from_env())
        self.peak_bytes = 0
        self.peak_wave = 0
        self.peak_breakdown: dict[str, int] = {}

    def update(self, wave: int, depth: int, breakdown: dict) -> float:
        clean = {k: int(v) for k, v in breakdown.items() if v}
        total = sum(clean.values())
        frac = total / self.budget_bytes
        if total > self.peak_bytes:
            self.peak_bytes = total
            self.peak_wave = int(wave)
            self.peak_breakdown = clean
            if self.tel is not None and getattr(self.tel, "active", False):
                self.tel.event(
                    "memwatch",
                    wave=int(wave),
                    depth=int(depth),
                    total_bytes=total,
                    peak_bytes=self.peak_bytes,
                    budget_bytes=self.budget_bytes,
                    frac=frac,
                    breakdown=clean,
                )
        return frac

    def summary_fields(self) -> dict:
        """Extras for the run's summary event."""
        return {
            "hbm_peak_bytes": self.peak_bytes,
            "hbm_peak_wave": self.peak_wave,
            "hbm_budget_bytes": self.budget_bytes,
            "hbm_peak_frac": self.peak_bytes / self.budget_bytes,
        }
