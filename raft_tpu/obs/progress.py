"""TLC-style live progress line on stderr.

TLC's killer usability feature is the periodic progress report ("N
states generated, M distinct states, queue depth D") — the reference
workflow assumes you watch it for hours. This renderer is the
equivalent, fed from the telemetry wave-event stream:

    Progress (depth 7): 1.2M generated, 310k distinct, 2,648/s, memo 71%

Throttled by wall clock (``every_s``); the first wave always prints so a
short run is not silent. Stall events render immediately — a watchdog
warning you cannot see is worthless.
"""

from __future__ import annotations

import sys
import time


def format_count(n) -> str:
    """Humanized count: 1234 -> '1,234', 310000 -> '310k', 1.2e6 -> '1.2M'."""
    n = int(n)
    if n >= 1_000_000_000:
        return f"{n / 1e9:.1f}B"
    if n >= 1_000_000:
        return f"{n / 1e6:.1f}M"
    if n >= 10_000:
        return f"{n / 1e3:.0f}k"
    return f"{n:,}"


class ProgressRenderer:
    """Wave-event listener rendering the TLC-style progress line."""

    # wave-event keys the renderer reads; the tier-1 smoke test asserts
    # these stay inside events.WAVE_KEYS so the renderer and the schema
    # cannot drift apart
    CONSUMES = (
        "depth", "generated_total", "distinct", "distinct_per_s",
        "canon_memo_hit_rate", "exchange_share", "hbm_frac",
    )

    def __init__(self, every_s: float = 10.0, stream=None):
        self.every_s = float(every_s)
        self.stream = stream if stream is not None else sys.stderr
        self._last: float | None = None

    def render_wave(self, ev: dict) -> str:
        line = (
            f"Progress (depth {ev['depth']}): "
            f"{format_count(ev['generated_total'])} generated, "
            f"{format_count(ev['distinct'])} distinct, "
            f"{ev['distinct_per_s']:,.0f}/s, "
            f"memo {ev['canon_memo_hit_rate']:.0%}"
        )
        # observatory gauges render only when present and non-zero so
        # the base line (pinned by tests) is unchanged on engines /
        # waves that don't carry them
        if ev.get("exchange_share"):
            line += f", a2a {ev['exchange_share']:.0%}"
        if ev.get("hbm_frac"):
            line += f", hbm {ev['hbm_frac']:.0%}"
        return line

    def __call__(self, ev: dict) -> None:
        etype = ev.get("event")
        if etype == "stall":
            print(
                f"Warning: wave {ev['wave']} (depth {ev['depth']}) took "
                f"{ev['wave_s']:.1f}s — {ev['factor']:.1f}x the rolling "
                f"median of {ev['median_wave_s']:.1f}s",
                file=self.stream, flush=True,
            )
            return
        if etype == "summary":
            print(
                f"Finished (depth {ev['depth']}): "
                f"{format_count(ev['total'])} generated, "
                f"{format_count(ev['distinct'])} distinct, "
                f"{ev['terminal']} terminal, {ev['seconds']:.1f}s "
                f"({ev['exit_cause']})",
                file=self.stream, flush=True,
            )
            return
        if etype == "retry":
            print(
                f"Recovery: attempt {ev['attempt']} failed ({ev['cause']}); "
                f"retrying in {ev['backoff_s']:.1f}s"
                + ("" if ev.get("growth") in (None, "-")
                   else f", growing {ev['growth']}"),
                file=self.stream, flush=True,
            )
            return
        if etype == "resume":
            print(
                f"Resumed from {ev['path']} (generation "
                f"{ev['generation']}) at depth {ev['depth']}, "
                f"{format_count(ev['distinct'])} distinct",
                file=self.stream, flush=True,
            )
            return
        if etype == "ckpt_generation":
            print(
                f"Warning: {len(ev['skipped'])} corrupt checkpoint "
                f"generation(s) skipped; loaded generation "
                f"{ev['generation']} of {ev['path']}",
                file=self.stream, flush=True,
            )
            return
        if etype == "preempt":
            print(
                f"Preempted ({ev['signame']}): checkpoint written to "
                f"{ev['checkpoint']} at depth {ev['depth']}",
                file=self.stream, flush=True,
            )
            return
        if etype != "wave":
            return
        now = time.monotonic()
        if self._last is not None and now - self._last < self.every_s:
            return
        self._last = now
        print(self.render_wave(ev), file=self.stream, flush=True)
