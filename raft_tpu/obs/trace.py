"""jax.profiler hooks: make an xprof trace line up with the checker.

With ``--trace-dir=DIR`` every wave is bracketed by a
``StepTraceAnnotation("wave", step_num=depth)`` (xprof's step view then
shows one step per BFS wave) and the named host-side phases —
``precompile``, ``seen_merge``, ``checkpoint``, ``consolidate`` — carry
``TraceAnnotation`` spans whose names match the offline stage profiler's
vocabulary (checker/profile.py), so a live trace and a PROFILE.md row
talk about the same things.

Without a trace dir every hook degrades to a shared nullcontext — zero
per-wave overhead on the hot path.
"""

from __future__ import annotations

from contextlib import nullcontext

_NULL = nullcontext()


class TraceHooks:
    """Owns jax.profiler trace lifetime + annotation factories."""

    def __init__(self, trace_dir: str | None = None):
        self.trace_dir = trace_dir
        self._started = False

    @property
    def enabled(self) -> bool:
        return self.trace_dir is not None

    def ensure_started(self) -> None:
        if self.trace_dir is None or self._started:
            return
        import jax

        jax.profiler.start_trace(self.trace_dir)
        self._started = True

    def stop(self) -> None:
        if not self._started:
            return
        import jax

        jax.profiler.stop_trace()
        self._started = False

    def wave(self, depth: int):
        """Context manager bracketing one BFS wave (xprof step = depth)."""
        if self.trace_dir is None:
            return _NULL
        import jax

        self.ensure_started()
        return jax.profiler.StepTraceAnnotation("wave", step_num=depth)

    def section(self, name: str):
        """Named span for a host-side phase (precompile/merge/...)."""
        if self.trace_dir is None:
            return _NULL
        import jax

        self.ensure_started()
        return jax.profiler.TraceAnnotation(name)
