"""Event schema of the live telemetry stream (the run-time counterpart
of checker/profile.py's DECLARED_STAGES).

A run emits one JSON object per line (JSONL), in order:

  manifest   once per run(), before the first wave: everything a BENCH /
             PROFILE artifact needs to cite its provenance — engine,
             fingerprint-formula identity (the checkpoint ident string),
             capacities, memo geometry, device/mesh topology.
  wave       one per BFS wave (at the collector's cadence): depth,
             frontier lanes, per-wave and cumulative generated/distinct,
             canon-memo hit rate, terminal count, overflow bits, LSM
             occupancy, wall seconds, rolling distinct/s.
  stall      emitted by the wall-clock watchdog when a wave exceeds
             stall_factor x the rolling median wave time.
  summary    once per run(), after the last wave: final counts, exit
             cause, peak buffer geometry, fleet memo hit rate.

``DECLARED_EVENTS`` mirrors ``DECLARED_STAGES``: the tier-1 smoke test
pins it, so the schema cannot silently rot when an engine's stats
plumbing changes. Engines may add EXTRA keys (e.g. the sharded checker's
all-to-all volume and per-shard skew); every DECLARED key must be
present. This module is dependency-free (no jax/numpy) so schema
validation runs anywhere — see scripts/check_metrics_schema.py.
"""

from __future__ import annotations

import json
import re

MANIFEST_KEYS = (
    "event", "engine", "ident", "hashv", "model", "platform", "device",
    "device_count", "chunk", "frontier_cap", "journal_cap",
    "max_seen_cap", "valid_cap", "canon_memo_cap", "symmetry",
    "invariants", "when",
)

WAVE_KEYS = (
    "event", "wave", "depth", "frontier", "new", "distinct",
    "generated", "generated_total", "terminal", "dedup_hit_rate",
    "canon_memo_hits", "canon_memo_hit_rate", "overflow_bits",
    "lsm_runs", "lsm_lanes", "wave_s", "elapsed_s", "distinct_per_s",
)

STALL_KEYS = (
    "event", "wave", "depth", "wave_s", "median_wave_s", "factor",
)

SUMMARY_KEYS = (
    "event", "engine", "ident", "exit_cause", "violation", "distinct",
    "total", "depth", "terminal", "seconds", "distinct_per_s",
    "exhausted", "waves", "stalls", "peak_frontier_cap",
    "peak_journal_cap", "seen_lanes", "canon_memo_hit_rate",
)

DECLARED_EVENTS = (
    ("manifest", MANIFEST_KEYS),
    ("wave", WAVE_KEYS),
    ("stall", STALL_KEYS),
    ("summary", SUMMARY_KEYS),
)

EVENT_KEYS = dict(DECLARED_EVENTS)

# exit causes a summary event may carry (one run, one cause)
EXIT_CAUSES = (
    "exhausted", "violation", "max_depth", "time_budget",
)


def hashv_of(ident: str) -> int:
    """Fingerprint-formula revision from a checkpoint ident string (the
    single source of truth for hashv — see DeviceBFS._ckpt_ident)."""
    m = re.search(r"hashv=(\d+)", ident)
    return int(m.group(1)) if m else 0


def validate_event(ev: object, lineno: int | None = None) -> list[str]:
    """Problems with one decoded event (empty list = valid). Extra keys
    are allowed — engines extend the schema; they never shrink it."""
    where = f"line {lineno}: " if lineno is not None else ""
    if not isinstance(ev, dict):
        return [f"{where}not a JSON object: {type(ev).__name__}"]
    etype = ev.get("event")
    if etype not in EVENT_KEYS:
        return [
            f"{where}unknown event type {etype!r} "
            f"(declared: {', '.join(EVENT_KEYS)})"
        ]
    missing = [k for k in EVENT_KEYS[etype] if k not in ev]
    problems = []
    if missing:
        problems.append(
            f"{where}{etype} event missing declared keys: {missing}"
        )
    if etype == "summary" and ev.get("exit_cause") not in EXIT_CAUSES:
        problems.append(
            f"{where}summary exit_cause {ev.get('exit_cause')!r} not in "
            f"{EXIT_CAUSES}"
        )
    return problems


def validate_lines(lines) -> tuple[dict, list[str]]:
    """Validate an iterable of JSONL lines against DECLARED_EVENTS.

    Returns (counts, problems): counts maps event type -> occurrences.
    Structural rules beyond per-event keys: every line must parse; wave
    indices must be strictly increasing within a run (a new manifest
    starts a new run and resets the expectation); a run's summary must
    come after its waves.
    """
    counts: dict[str, int] = {}
    problems: list[str] = []
    last_wave = 0
    summarized = False
    for lineno, raw in enumerate(lines, start=1):
        raw = raw.strip()
        if not raw:
            continue
        try:
            ev = json.loads(raw)
        except ValueError as e:
            problems.append(f"line {lineno}: not valid JSON ({e})")
            continue
        problems += validate_event(ev, lineno)
        etype = ev.get("event") if isinstance(ev, dict) else None
        if etype not in EVENT_KEYS:
            continue
        counts[etype] = counts.get(etype, 0) + 1
        if etype == "manifest":
            last_wave = 0
            summarized = False
        elif etype == "wave":
            if summarized:
                problems.append(
                    f"line {lineno}: wave event after the run's summary"
                )
            w = ev.get("wave")
            if not isinstance(w, int) or w <= last_wave:
                problems.append(
                    f"line {lineno}: wave index {w!r} not strictly "
                    f"increasing (previous {last_wave})"
                )
            else:
                last_wave = w
        elif etype == "summary":
            summarized = True
    return counts, problems
