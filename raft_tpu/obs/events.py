"""Event schema of the live telemetry stream (the run-time counterpart
of checker/profile.py's DECLARED_STAGES).

A run emits one JSON object per line (JSONL), in order:

  manifest   once per run(), before the first wave: everything a BENCH /
             PROFILE artifact needs to cite its provenance — engine,
             fingerprint-formula identity (the checkpoint ident string),
             capacities, memo geometry, device/mesh topology.
  wave       one per BFS wave (at the collector's cadence): depth,
             frontier lanes, per-wave and cumulative generated/distinct,
             canon-memo hit rate, terminal count, overflow bits, LSM
             occupancy, wall seconds, rolling distinct/s.
  stall      emitted by the wall-clock watchdog when a wave exceeds
             stall_factor x the rolling median wave time.
  coverage   cumulative state-space cartography at the collector's
             cadence plus one final snapshot (``final: true``) right
             before the summary: per-action [enabled, fired,
             new-distinct] counters (index == the model's ACTION_NAMES
             rank), seen-set lane occupancy, fingerprint probe depth,
             frontier depth histogram, canon-memo fill ratio (final
             snapshot only; null mid-run — reading the memo table
             mid-run would cost a device sync).
  summary    once per run(), after the last wave: final counts, exit
             cause, peak buffer geometry, fleet memo hit rate.

The self-healing runtime (raft_tpu/resilience/) adds four low-volume
events — ``retry`` / ``resume`` / ``ckpt_generation`` / ``preempt`` —
documented at their key tuples below; they interleave with the above
(retry between attempts, resume/ckpt_generation right after a resumed
run's manifest, preempt just before a "preempted" summary).

The wave-timeline observatory adds three more: ``timeline`` (stage
seconds of a sampled ``--timeline[=EVERY_N]`` wave, names drawn from
``TIMELINE_STAGES``), ``memwatch`` (analytic HBM live-bytes watermarks
from obs/memwatch.py, peak monotone within a run), and ``shard_wave``
(per-shard critical-path rows of a sampled sharded wave: exchange vs
compute seconds, emigrant lanes/bytes, work share). All three come
before their run's summary.

``DECLARED_EVENTS`` mirrors ``DECLARED_STAGES``: the tier-1 smoke test
pins it, so the schema cannot silently rot when an engine's stats
plumbing changes. Engines may add EXTRA keys (e.g. the sharded checker's
all-to-all volume and per-shard skew); every DECLARED key must be
present. This module is dependency-free (no jax/numpy) so schema
validation runs anywhere — see scripts/check_metrics_schema.py.

``job`` is the reserved extra key of fleet sweeps (raft_tpu/fleet/):
``raft_tpu sweep`` multiplexes every job of a manifest into ONE stream,
and each job-attributed event carries its job name there — the queue arm
stamps it on every forwarded event (obs/collector.py
JobTaggedTelemetry), the packed host arm synthesizes one per-job
manifest/coverage/summary triple after the shared group run. When
present it must be a non-empty string, each job's wave indices must be
strictly increasing within its run, and every job manifest must be
answered by exactly one summary with the same tag (validate_lines
enforces all three).
"""

from __future__ import annotations

import json
import re

MANIFEST_KEYS = (
    "event", "engine", "ident", "hashv", "model", "platform", "device",
    "device_count", "chunk", "frontier_cap", "journal_cap",
    "max_seen_cap", "valid_cap", "canon_memo_cap", "symmetry",
    "invariants", "action_names", "when",
)

# Stage names the wave-timeline observatory attributes seconds to.
# Shared by all three engines; an engine reports the subset it can
# split (e.g. "exchange" only exists on the sharded mesh, "dedup" folds
# into "emit" where the fused program cannot separate them). The offline
# counterpart is checker/profile.py DECLARED_STAGES — these are coarser
# because they time real dispatches of a real run, not isolated re-runs.
TIMELINE_STAGES = (
    "expand",      # guard pass + budgeted sparse apply (or dense expand)
    "canon",       # canonical fingerprints (memoized symmetry reduction)
    "dedup",       # seen-set probes + intra-wave first-occurrence
    "emit",        # cursor-append emit + coverage + invariants + stats
    "exchange",    # sharded only: the all-to-all pair on the ICI
    "seen_merge",  # LSM ladder cascade + end-of-wave seen merge
    "checkpoint",  # wave-boundary checkpoint I/O
    "host",        # host bookkeeping not covered by a device stage
)

# emit_rows/emit_bytes/frontier_fill (round 6): rows the wave's
# contiguous cursor-append emit landed, bytes it wrote, and frontier-
# buffer occupancy (worst shard; 0.0 on the unbounded host engine) — so
# the stall watchdog can tell an emit-bound or growth/recompile wave
# from a compute-bound one (the depth-32 cliff of BENCH_r05.json was
# attributed with exactly these gauges).
# enabled_density/expand_budget_ovf (guard-first sparse expansion):
# enabled fraction of the dense [chunk, A] candidate grid this wave
# (the guard-first win scales with its inverse — tune valid_per_group
# from it), and apply-budget overflow (device engines: the abort bit,
# 0 on surviving waves; host engine: extra fixed-size apply blocks run
# beyond one per chunk — it loops instead of aborting). Both derive
# from counters the wave already fetched: zero extra device syncs.
# device_s/host_s/ckpt_s/tel_s (wave-timeline observatory): the
# host-side phase split of the wave's wall clock — seconds blocked on
# device work (dispatch + the one stats fetch), residual host
# bookkeeping, checkpoint I/O, and the telemetry emission cost of the
# PREVIOUS wave (this wave's own emission cost is only known after the
# event is written; 0.0 on wave 1). All four come from perf_counter
# brackets around code the wave already runs: zero extra device syncs.
# exchange_share: sharded engine only, fraction of the sampled wave's
# device seconds spent in the all-to-all (null on other engines and on
# unsampled waves). hbm_frac: analytic live-bytes / budget from
# obs/memwatch.py (null when memwatch is off).
WAVE_KEYS = (
    "event", "wave", "depth", "frontier", "new", "distinct",
    "generated", "generated_total", "terminal", "dedup_hit_rate",
    "canon_memo_hits", "canon_memo_hit_rate", "overflow_bits",
    "lsm_runs", "lsm_lanes", "wave_s", "elapsed_s", "distinct_per_s",
    "emit_rows", "emit_bytes", "frontier_fill",
    "enabled_density", "expand_budget_ovf",
    "device_s", "host_s", "ckpt_s", "tel_s",
    "exchange_share", "hbm_frac",
)

STALL_KEYS = (
    "event", "wave", "depth", "wave_s", "median_wave_s", "factor",
)

# actions: [n_actions][3] cumulative [enabled, fired, new_distinct]
# rows, index == the model's ACTION_NAMES rank (manifest carries the
# names); seen_lanes: allocated seen-set lanes per occupied LSM level
# (occupancy histogram; the host engine reports one level); seen_real:
# real (non-padding) fingerprints resident; probe_runs: sorted runs a
# membership probe binary-searches (fingerprint probe length);
# frontier_hist: distinct states first seen at each depth 0..d;
# canon_memo_fill: filled/capacity of the canon memo, null until the
# final snapshot (and when no memo is configured).
COVERAGE_KEYS = (
    "event", "wave", "depth", "actions", "actions_total",
    "actions_fired", "seen_lanes", "seen_real", "probe_runs",
    "frontier_hist", "canon_memo_fill", "final",
)

SUMMARY_KEYS = (
    "event", "engine", "ident", "exit_cause", "violation", "distinct",
    "total", "depth", "terminal", "seconds", "distinct_per_s",
    "exhausted", "waves", "stalls", "peak_frontier_cap",
    "peak_journal_cap", "seen_lanes", "canon_memo_hit_rate",
)

# resilience events (self-healing runtime): the supervisor and the
# engines narrate recovery in the same stream the waves go to, so a
# chaos-ridden or preempted run is explicable from its JSONL alone.
#   retry            emitted by the supervisor between attempts:
#                    monotone ``attempt`` counter, classified ``cause``
#                    (overflow:<what> / crash / transient / ckpt-load),
#                    chosen ``backoff_s``, cumulative capacity
#                    ``growth`` summary string ("-" when none).
#   resume           emitted by an engine that restored state from a
#                    checkpoint, before its first wave: which file,
#                    which generation won, restored depth/distinct.
#   ckpt_generation  emitted when load had to SKIP newer generations
#                    (truncated/corrupt): the generation that verified
#                    and one diagnostic line per rejected candidate.
#   preempt          emitted when SIGTERM/SIGINT caused a wave-boundary
#                    checkpoint-and-exit (summary follows with
#                    exit_cause "preempted"; the CLI maps it to rc 4).
RETRY_KEYS = (
    "event", "attempt", "cause", "backoff_s", "growth",
)

RESUME_KEYS = (
    "event", "path", "generation", "depth", "distinct",
)

CKPT_GENERATION_KEYS = (
    "event", "path", "generation", "skipped",
)

PREEMPT_KEYS = (
    "event", "signame", "depth", "checkpoint",
)

# elastic-mesh events (sharded engine + supervisor):
#   shard_lost   a shard's device died mid-wave (chaos shard_loss=K or a
#                real preemption observed by the engine): which shard of
#                how many, the wave in flight, and whether a
#                redistributable wave-start checkpoint was spilled.
#                Emitted before the engine raises ShardLost — so it must
#                come before the run's summary.
#   reshard      a resume re-routed a checkpoint written on a different
#                mesh size by fp mod D_new. Emitted right after the
#                resumed run's manifest, before any wave.
#   shard_stall  the per-shard stall watchdog aborted a pathologically
#                slow wave instead of hanging the all-to-all: the
#                suspect (most-loaded) shard, the wave's seconds vs the
#                rolling median, and the configured factor. Emitted
#                before the engine raises ShardStall.
SHARD_LOST_KEYS = (
    "event", "wave", "depth", "shard", "device_count", "checkpoint_saved",
)

RESHARD_KEYS = (
    "event", "path", "from_d", "to_d", "depth", "distinct",
)

SHARD_STALL_KEYS = (
    "event", "wave", "depth", "shard", "wave_s", "median_wave_s", "factor",
)

# wave-timeline observatory events (obs/memwatch.py + the engines'
# sampled `--timeline[=EVERY_N]` mode):
#   timeline    one per SAMPLED wave: the wave re-run as separately
#               timed stage dispatches (block_until_ready between
#               stages), bit-identical to the fused program by
#               construction (integer-only wave math; parity-gated by
#               tests). ``stages`` maps a TIMELINE_STAGES name to
#               seconds; ``every`` is the sampling stride; ``wave_s``
#               the sampled wave's total wall clock.
#   memwatch    analytic HBM live-bytes watermark, emitted when a wave
#               sets a new peak (so the stream stays low-volume and
#               peak_bytes is monotone within a run by construction).
#               ``breakdown`` maps a buffer family (frontier / chunk /
#               seen / journal / memo / ...) to live bytes; ``frac`` =
#               total_bytes / budget_bytes (may exceed 1.0 — that is
#               the out-of-core planning signal).
#   shard_wave  per-shard critical-path row of a SAMPLED sharded wave:
#               owner-side new states, routed (emigrant) lanes/bytes,
#               this shard's share of the wave's work, and its
#               estimated busy seconds (lockstep SPMD means wall time
#               is shared; shard_s = compute_s * work_share * D is the
#               analytic attribution, from which skew = max - median).
TIMELINE_KEYS = (
    "event", "wave", "depth", "every", "stages", "wave_s",
)

MEMWATCH_KEYS = (
    "event", "wave", "depth", "total_bytes", "peak_bytes",
    "budget_bytes", "frac", "breakdown",
)

SHARD_WAVE_KEYS = (
    "event", "wave", "depth", "shard", "device_count", "new",
    "routed_lanes", "routed_bytes", "work_share", "shard_s",
    "exchange_s", "compute_s",
)

DECLARED_EVENTS = (
    ("manifest", MANIFEST_KEYS),
    ("wave", WAVE_KEYS),
    ("stall", STALL_KEYS),
    ("coverage", COVERAGE_KEYS),
    ("summary", SUMMARY_KEYS),
    ("retry", RETRY_KEYS),
    ("resume", RESUME_KEYS),
    ("ckpt_generation", CKPT_GENERATION_KEYS),
    ("preempt", PREEMPT_KEYS),
    ("shard_lost", SHARD_LOST_KEYS),
    ("reshard", RESHARD_KEYS),
    ("shard_stall", SHARD_STALL_KEYS),
    ("timeline", TIMELINE_KEYS),
    ("memwatch", MEMWATCH_KEYS),
    ("shard_wave", SHARD_WAVE_KEYS),
)

EVENT_KEYS = dict(DECLARED_EVENTS)

# exit causes a summary event may carry (one run, one cause);
# "preempted" = SIGTERM/SIGINT honored at a wave boundary with a
# checkpoint written (restart with --resume loses nothing)
EXIT_CAUSES = (
    "exhausted", "violation", "max_depth", "time_budget", "preempted",
)


def hashv_of(ident: str) -> int:
    """Fingerprint-formula revision from a checkpoint ident string (the
    single source of truth for hashv — see DeviceBFS._ckpt_ident)."""
    m = re.search(r"hashv=(\d+)", ident)
    return int(m.group(1)) if m else 0


def validate_event(ev: object, lineno: int | None = None) -> list[str]:
    """Problems with one decoded event (empty list = valid). Extra keys
    are allowed — engines extend the schema; they never shrink it."""
    where = f"line {lineno}: " if lineno is not None else ""
    if not isinstance(ev, dict):
        return [f"{where}not a JSON object: {type(ev).__name__}"]
    etype = ev.get("event")
    if etype not in EVENT_KEYS:
        return [
            f"{where}unknown event type {etype!r} "
            f"(declared: {', '.join(EVENT_KEYS)})"
        ]
    missing = [k for k in EVENT_KEYS[etype] if k not in ev]
    problems = []
    if missing:
        problems.append(
            f"{where}{etype} event missing declared keys: {missing}"
        )
    if "job" in ev and (not isinstance(ev["job"], str) or not ev["job"]):
        problems.append(
            f"{where}job tag {ev['job']!r} must be a non-empty string"
        )
    if etype == "wave":
        dens = ev.get("enabled_density")
        if dens is not None and (
            isinstance(dens, bool) or not isinstance(dens, (int, float))
            or not 0.0 <= dens <= 1.0
        ):
            problems.append(
                f"{where}wave enabled_density {dens!r} must be a number "
                f"in [0, 1] (enabled fraction of the chunk*A grid)"
            )
        bovf = ev.get("expand_budget_ovf")
        if bovf is not None and (
            isinstance(bovf, bool) or not isinstance(bovf, int)
            or bovf < 0
        ):
            problems.append(
                f"{where}wave expand_budget_ovf {bovf!r} must be a "
                f"non-negative int"
            )
        for key in ("device_s", "host_s", "ckpt_s", "tel_s"):
            v = ev.get(key)
            if v is not None and (
                isinstance(v, bool) or not isinstance(v, (int, float))
                or v < 0
            ):
                problems.append(
                    f"{where}wave {key} {v!r} must be a non-negative "
                    f"number (seconds)"
                )
        share = ev.get("exchange_share")
        if share is not None and (
            isinstance(share, bool) or not isinstance(share, (int, float))
            or not 0.0 <= share <= 1.0
        ):
            problems.append(
                f"{where}wave exchange_share {share!r} must be null or a "
                f"number in [0, 1]"
            )
        frac = ev.get("hbm_frac")
        if frac is not None and (
            isinstance(frac, bool) or not isinstance(frac, (int, float))
            or frac < 0
        ):
            problems.append(
                f"{where}wave hbm_frac {frac!r} must be null or a "
                f"non-negative number"
            )
    if etype == "timeline":
        stages = ev.get("stages")
        if not isinstance(stages, dict):
            problems.append(
                f"{where}timeline stages must be a dict of stage -> "
                f"seconds, got {type(stages).__name__}"
            )
        else:
            unknown = [s for s in stages if s not in TIMELINE_STAGES]
            if unknown:
                problems.append(
                    f"{where}timeline stage names {unknown} not in the "
                    f"declared stage set {TIMELINE_STAGES}"
                )
            bad = [
                s for s, v in stages.items()
                if isinstance(v, bool) or not isinstance(v, (int, float))
                or v < 0
            ]
            if bad:
                problems.append(
                    f"{where}timeline stage seconds must be non-negative "
                    f"numbers (bad: {bad})"
                )
        every = ev.get("every")
        if isinstance(every, bool) or not isinstance(every, int) \
                or every < 1:
            problems.append(
                f"{where}timeline every {every!r} must be an int >= 1 "
                f"(the sampling stride)"
            )
    if etype == "memwatch":
        for key in ("total_bytes", "peak_bytes", "budget_bytes"):
            v = ev.get(key)
            if isinstance(v, bool) or not isinstance(v, int) or v < 0:
                problems.append(
                    f"{where}memwatch {key} {v!r} must be a non-negative "
                    f"int"
                )
        tot, peak = ev.get("total_bytes"), ev.get("peak_bytes")
        if isinstance(tot, int) and isinstance(peak, int) \
                and not isinstance(tot, bool) and not isinstance(peak, bool) \
                and tot > peak:
            problems.append(
                f"{where}memwatch total_bytes {tot} exceeds peak_bytes "
                f"{peak} (the peak must cover the wave that set it)"
            )
        br = ev.get("breakdown")
        if not isinstance(br, dict) or any(
            not isinstance(k, str) or isinstance(v, bool)
            or not isinstance(v, int) or v < 0
            for k, v in br.items()
        ):
            problems.append(
                f"{where}memwatch breakdown must map buffer family "
                f"names to non-negative int bytes"
            )
    if etype == "shard_wave":
        shard = ev.get("shard")
        if isinstance(shard, bool) or not isinstance(shard, int) \
                or shard < 0:
            problems.append(
                f"{where}shard_wave shard {shard!r} must be an int >= 0"
            )
        dc = ev.get("device_count")
        if isinstance(dc, bool) or not isinstance(dc, int) or dc < 1:
            problems.append(
                f"{where}shard_wave device_count {dc!r} must be an "
                f"int >= 1"
            )
        elif isinstance(shard, int) and not isinstance(shard, bool) \
                and not 0 <= shard < dc:
            problems.append(
                f"{where}shard_wave shard {shard} out of range for "
                f"device_count {dc}"
            )
        for key in ("shard_s", "exchange_s", "compute_s", "work_share"):
            v = ev.get(key)
            if v is not None and (
                isinstance(v, bool) or not isinstance(v, (int, float))
                or v < 0
            ):
                problems.append(
                    f"{where}shard_wave {key} {v!r} must be a "
                    f"non-negative number"
                )
    if etype == "summary" and ev.get("exit_cause") not in EXIT_CAUSES:
        problems.append(
            f"{where}summary exit_cause {ev.get('exit_cause')!r} not in "
            f"{EXIT_CAUSES}"
        )
    if etype == "retry":
        att = ev.get("attempt")
        if isinstance(att, bool) or not isinstance(att, int) or att < 1:
            problems.append(
                f"{where}retry attempt {att!r} must be an int >= 1"
            )
        back = ev.get("backoff_s")
        if isinstance(back, bool) or not isinstance(back, (int, float)) \
                or back < 0:
            problems.append(
                f"{where}retry backoff_s {back!r} must be a non-negative "
                f"number"
            )
    if etype in ("resume", "ckpt_generation"):
        gen = ev.get("generation")
        if isinstance(gen, bool) or not isinstance(gen, int) or gen < 0:
            problems.append(
                f"{where}{etype} generation {gen!r} must be an int >= 0"
            )
        if etype == "ckpt_generation":
            sk = ev.get("skipped")
            if not isinstance(sk, list) or any(
                not isinstance(s, str) for s in sk
            ):
                problems.append(
                    f"{where}ckpt_generation skipped must be a list of "
                    f"diagnostic strings"
                )
    if etype in ("shard_lost", "shard_stall"):
        shard = ev.get("shard")
        if isinstance(shard, bool) or not isinstance(shard, int) or shard < 0:
            problems.append(
                f"{where}{etype} shard {shard!r} must be an int >= 0"
            )
        if etype == "shard_lost":
            dc = ev.get("device_count")
            if isinstance(dc, bool) or not isinstance(dc, int) or dc < 1:
                problems.append(
                    f"{where}shard_lost device_count {dc!r} must be an "
                    f"int >= 1"
                )
            elif isinstance(shard, int) and not isinstance(shard, bool) \
                    and not 0 <= shard < dc:
                problems.append(
                    f"{where}shard_lost shard {shard} out of range for "
                    f"device_count {dc}"
                )
    if etype == "reshard":
        for key in ("from_d", "to_d"):
            d = ev.get(key)
            if isinstance(d, bool) or not isinstance(d, int) or d < 1:
                problems.append(
                    f"{where}reshard {key} {d!r} must be an int >= 1"
                )
        fd, td = ev.get("from_d"), ev.get("to_d")
        if isinstance(fd, int) and isinstance(td, int) and fd == td:
            problems.append(
                f"{where}reshard from_d == to_d == {fd} (a same-size "
                f"resume must not emit a reshard event)"
            )
    if etype == "coverage":
        acts = ev.get("actions")
        if not isinstance(acts, list) or any(
            not isinstance(row, list) or len(row) != 3
            or any(not isinstance(c, int) or c < 0 for c in row)
            for row in acts
        ):
            problems.append(
                f"{where}coverage actions must be a list of "
                f"[enabled, fired, new] non-negative int triples"
            )
        elif ev.get("actions_total") != len(acts):
            problems.append(
                f"{where}coverage actions_total {ev.get('actions_total')!r}"
                f" != len(actions) {len(acts)}"
            )
    return problems


def validate_lines(lines) -> tuple[dict, list[str]]:
    """Validate an iterable of JSONL lines against DECLARED_EVENTS.

    Returns (counts, problems): counts maps event type -> occurrences.
    Structural rules beyond per-event keys: every line must parse; wave
    indices must be strictly increasing within a run (a new manifest
    starts a new run and resets the expectation); a run's summary must
    come after its waves; coverage events must come before the run's
    summary, carry non-decreasing wave indices (the final snapshot may
    repeat the last wave), and their cumulative per-action counters
    must be monotone non-decreasing cell-by-cell. Supervisor ``retry``
    attempts must be strictly increasing across a supervised session (a
    summary ends the session and resets the counter — a completed run
    means any later retry belongs to a new invocation).

    Elastic-mesh rules: a ``reshard`` event belongs to the load phase —
    it must come after its run's manifest but before the first wave and
    never after the summary; ``shard_lost``/``shard_stall`` abort an
    in-flight wave, so they must come before the run's summary and
    carry a wave index no smaller than the last completed wave (a new
    manifest resets these expectations too, which is the per-job reset
    in a multiplexed fleet stream).

    Job-tagged streams (fleet sweeps) add: per-job wave indices must be
    strictly increasing within that job's run (its ``job``-tagged
    manifest resets the expectation), and every job manifest must be
    matched by exactly one summary carrying the same job tag.

    Wave-timeline observatory rules: ``timeline`` / ``memwatch`` /
    ``shard_wave`` events must come before their run's summary, and
    ``memwatch`` peak_bytes must be monotone non-decreasing within a
    run (a new manifest resets the watermark).
    """
    counts: dict[str, int] = {}
    problems: list[str] = []
    last_wave = 0
    summarized = False
    last_cov_wave = 0
    prev_actions: list | None = None
    last_retry_attempt = 0
    last_memwatch_peak = 0
    job_wave: dict[str, int] = {}
    job_manifests: dict[str, int] = {}
    job_summaries: dict[str, int] = {}
    for lineno, raw in enumerate(lines, start=1):
        raw = raw.strip()
        if not raw:
            continue
        try:
            ev = json.loads(raw)
        except ValueError as e:
            problems.append(f"line {lineno}: not valid JSON ({e})")
            continue
        problems += validate_event(ev, lineno)
        etype = ev.get("event") if isinstance(ev, dict) else None
        if etype not in EVENT_KEYS:
            continue
        counts[etype] = counts.get(etype, 0) + 1
        job = ev.get("job")
        job = job if isinstance(job, str) and job else None
        if etype == "manifest":
            last_wave = 0
            summarized = False
            last_cov_wave = 0
            prev_actions = None
            last_memwatch_peak = 0
            if job is not None:
                job_manifests[job] = job_manifests.get(job, 0) + 1
                job_wave[job] = 0
        elif etype == "coverage":
            if summarized:
                problems.append(
                    f"line {lineno}: coverage event after the run's summary"
                )
            w = ev.get("wave")
            if not isinstance(w, int) or w < last_cov_wave:
                problems.append(
                    f"line {lineno}: coverage wave index {w!r} not "
                    f"non-decreasing (previous {last_cov_wave})"
                )
            else:
                last_cov_wave = w
            acts = ev.get("actions")
            if isinstance(acts, list) and prev_actions is not None and (
                len(acts) == len(prev_actions)
            ):
                for r, (row, prow) in enumerate(zip(acts, prev_actions)):
                    if (isinstance(row, list) and isinstance(prow, list)
                            and len(row) == len(prow) == 3
                            and any(c < p for c, p in zip(row, prow))):
                        problems.append(
                            f"line {lineno}: coverage counters for action "
                            f"rank {r} not monotone ({prow} -> {row})"
                        )
            if isinstance(acts, list):
                prev_actions = acts
        elif etype == "wave":
            if summarized:
                problems.append(
                    f"line {lineno}: wave event after the run's summary"
                )
            w = ev.get("wave")
            if not isinstance(w, int) or w <= last_wave:
                problems.append(
                    f"line {lineno}: wave index {w!r} not strictly "
                    f"increasing (previous {last_wave})"
                )
            else:
                last_wave = w
            if job is not None and isinstance(w, int):
                if w <= job_wave.get(job, 0):
                    problems.append(
                        f"line {lineno}: job {job!r} wave index {w} not "
                        f"strictly increasing "
                        f"(previous {job_wave.get(job, 0)})"
                    )
                else:
                    job_wave[job] = w
        elif etype == "reshard":
            if summarized:
                problems.append(
                    f"line {lineno}: reshard event after the run's summary"
                )
            elif last_wave > 0:
                problems.append(
                    f"line {lineno}: reshard event after wave {last_wave} "
                    f"(resharding happens at load time, before any wave)"
                )
        elif etype in ("shard_lost", "shard_stall"):
            if summarized:
                problems.append(
                    f"line {lineno}: {etype} event after the run's summary"
                )
            w = ev.get("wave")
            if isinstance(w, int) and not isinstance(w, bool) \
                    and w < last_wave:
                problems.append(
                    f"line {lineno}: {etype} wave index {w} behind the "
                    f"run's last completed wave {last_wave}"
                )
        elif etype in ("timeline", "memwatch", "shard_wave"):
            if summarized:
                problems.append(
                    f"line {lineno}: {etype} event after the run's summary"
                )
            if etype == "memwatch":
                peak = ev.get("peak_bytes")
                if isinstance(peak, int) and not isinstance(peak, bool):
                    if peak < last_memwatch_peak:
                        problems.append(
                            f"line {lineno}: memwatch peak_bytes {peak} "
                            f"regressed below the run's watermark "
                            f"{last_memwatch_peak} (peaks are monotone "
                            f"within a run)"
                        )
                    else:
                        last_memwatch_peak = peak
        elif etype == "retry":
            att = ev.get("attempt")
            if isinstance(att, int) and not isinstance(att, bool):
                if att <= last_retry_attempt:
                    problems.append(
                        f"line {lineno}: retry attempt {att} not strictly "
                        f"increasing (previous {last_retry_attempt})"
                    )
                else:
                    last_retry_attempt = att
        elif etype == "summary":
            summarized = True
            last_retry_attempt = 0
            if job is not None:
                job_summaries[job] = job_summaries.get(job, 0) + 1
    for job in sorted(set(job_manifests) | set(job_summaries)):
        nm = job_manifests.get(job, 0)
        ns = job_summaries.get(job, 0)
        if nm != ns:
            problems.append(
                f"job {job!r}: {nm} manifest(s) but {ns} summar"
                f"{'y' if ns == 1 else 'ies'} (one summary per job "
                f"manifest)"
            )
    return counts, problems
