"""TLC-style pretty-printing of decoded states and counterexample traces.

Formats states in TLA+ value syntax (records, functions, sequences, bags)
the way TLC prints them in error traces, using the cfg's model-value names
— the human-facing half of "bit-for-bit counterexample parity".
"""

from __future__ import annotations

STATE_NAMES = {0: "Follower", 1: "Candidate", 2: "Leader", 3: "NotMember"}


def _srv(setup, i) -> str:
    return setup.server_names[i]


def _val(setup, v) -> str:
    return setup.value_names[v]


def _fmt_fun(pairs) -> str:
    return "(" + " @@ ".join(f"{k} :> {v}" for k, v in pairs) + ")"


def _fmt_value(setup, v) -> str:
    """Generic python-value -> TLA+ value syntax (fallback for decoded
    fields the hand-tuned standard-raft path doesn't know: reconfig
    config tuples, KRaft epochs, ...)."""
    if v is None:
        return "Nil"
    if isinstance(v, bool):
        return "TRUE" if v else "FALSE"
    if isinstance(v, str):
        return v
    if isinstance(v, (frozenset, set)):
        return "{" + ", ".join(sorted(_fmt_value(setup, e) for e in v)) + "}"
    if isinstance(v, tuple):
        return "<<" + ", ".join(_fmt_value(setup, e) for e in v) + ">>"
    try:
        return str(int(v))
    except (TypeError, ValueError):
        return str(v)


def _fmt_msg(setup, rec) -> str:
    parts = []
    for k, v in rec:
        if k in ("msource", "mdest"):
            v = _srv(setup, v)
        elif k == "mentries" and all(
            isinstance(e, tuple) and len(e) == 2 for e in v
        ):
            v = (
                "<<"
                + ", ".join(
                    f"[term |-> {t}, value |-> {_val(setup, val)}]" for t, val in v
                )
                + ">>"
            )
        else:
            v = _fmt_value(setup, v)
        parts.append(f"{k} |-> {v}")
    return "[" + ", ".join(parts) + "]"


def format_state(setup, st: dict) -> str:
    S = len(st["currentTerm"])
    sv = lambda i: _srv(setup, i)
    lines = []
    handled: set = set()

    def put(name, text):
        handled.add(name)
        lines.append(f"/\\ {name} = {text}")

    put(
        "currentTerm",
        _fmt_fun((sv(i), st["currentTerm"][i]) for i in range(S)),
    )
    if "state" in st:
        put(
            "state",
            _fmt_fun(
                (sv(i), STATE_NAMES.get(st["state"][i], st["state"][i]))
                for i in range(S)
            ),
        )
    if "votedFor" in st:
        put(
            "votedFor",
            _fmt_fun(
                (sv(i), "Nil" if st["votedFor"][i] is None else sv(st["votedFor"][i]))
                for i in range(S)
            ),
        )
    if "votesGranted" in st:
        put(
            "votesGranted",
            _fmt_fun(
                (sv(i), "{" + ", ".join(sv(j) for j in sorted(st["votesGranted"][i])) + "}")
                for i in range(S)
            ),
        )
    if "log" in st:
        if all(
            isinstance(e, tuple) and len(e) == 2
            for row in st["log"] for e in row
        ):
            put(
                "log",
                _fmt_fun(
                    (
                        sv(i),
                        "<<"
                        + ", ".join(
                            f"[term |-> {t}, value |-> {_val(setup, v)}]"
                            for t, v in st["log"][i]
                        )
                        + ">>",
                    )
                    for i in range(S)
                ),
            )
        else:  # reconfig/KRaft entries carry extra fields — generic form
            put(
                "log",
                _fmt_fun(
                    (sv(i), _fmt_value(setup, st["log"][i])) for i in range(S)
                ),
            )
    if "commitIndex" in st:
        put(
            "commitIndex",
            _fmt_fun((sv(i), st["commitIndex"][i]) for i in range(S)),
        )
    if "fsyncIndex" in st:  # RaftFsync (RaftFsync.tla:92)
        put(
            "fsyncIndex",
            _fmt_fun((sv(i), st["fsyncIndex"][i]) for i in range(S)),
        )
    for name in ("nextIndex", "matchIndex", "pendingResponse"):
        if name not in st:
            continue
        put(
            name,
            _fmt_fun(
                (
                    sv(i),
                    _fmt_fun(
                        (
                            sv(j),
                            "TRUE"
                            if st[name][i][j] is True
                            else ("FALSE" if st[name][i][j] is False else st[name][i][j]),
                        )
                        for j in range(S)
                    ),
                )
                for i in range(S)
            ),
        )
    if "messages" in st:
        msgs = sorted(st["messages"])
        put(
            "messages",
            "("
            + " @@ ".join(f"{_fmt_msg(setup, m)} :> {c}" for m, c in msgs)
            + ")",
        )
    if "acked" in st:
        put(
            "acked",
            _fmt_fun(
                (
                    _val(setup, v),
                    {None: "Nil", False: "FALSE", True: "TRUE"}[st["acked"][v]],
                )
                for v in range(len(st["acked"]))
            ),
        )
    for name in ("electionCtr", "restartCtr"):
        if name in st:
            put(name, str(st[name]))
    # any remaining decoded variables (reconfig config tuples, counters,
    # KRaft epochs, ...) print via the generic TLA+ value formatter, as
    # a per-server function when server-shaped
    for key, v in st.items():
        if key in handled:
            continue
        if isinstance(v, tuple) and len(v) == S:
            lines.append(
                f"/\\ {key} = "
                + _fmt_fun((sv(i), _fmt_value(setup, v[i])) for i in range(S))
            )
        else:
            lines.append(f"/\\ {key} = {_fmt_value(setup, v)}")
    return "\n".join(lines)


def format_trace(trace, setup) -> str:
    out = []
    for n, (label, st) in enumerate(trace, start=1):
        out.append(f"State {n}: <{label}>")
        out.append(format_state(setup, st))
        out.append("")
    return "\n".join(out)


def format_trace_tlc(trace, setup, violated: str | None = None) -> str:
    """TLC error-trace format (``--trace-format tlc``): the textual shape
    `tlc` prints on an invariant violation, so a counterexample can be
    diffed offline against a real TLC run the day a JVM is available
    (BASELINE.json north-star parity clause; no JVM is in this image).
    Action labels carry the action name and arguments — TLC's labels add
    file line/col spans ("<RequestVote line 253, col 5 ... of module
    Raft>"), which a diff normalizes away; the parity-bearing content is
    the `/\\ var = value` lines."""
    out = []
    if violated is not None:
        out.append(f"Error: Invariant {violated} is violated.")
    out.append("Error: The behavior up to this point is:")
    for n, (label, st) in enumerate(trace, start=1):
        out.append(f"State {n}: <{label}>")
        out.append(format_state(setup, st))
        out.append("")
    return "\n".join(out)
