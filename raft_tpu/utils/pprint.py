"""TLC-style pretty-printing of decoded states and counterexample traces.

Formats states in TLA+ value syntax (records, functions, sequences, bags)
the way TLC prints them in error traces, using the cfg's model-value names
— the human-facing half of "bit-for-bit counterexample parity".
"""

from __future__ import annotations

STATE_NAMES = {0: "Follower", 1: "Candidate", 2: "Leader"}


def _srv(setup, i) -> str:
    return setup.server_names[i]


def _val(setup, v) -> str:
    return setup.value_names[v]


def _fmt_fun(pairs) -> str:
    return "(" + " @@ ".join(f"{k} :> {v}" for k, v in pairs) + ")"


def _fmt_msg(setup, rec) -> str:
    d = dict(rec)
    parts = []
    for k, v in rec:
        if k in ("msource", "mdest"):
            v = _srv(setup, v)
        elif k == "mentries":
            v = (
                "<<"
                + ", ".join(
                    f"[term |-> {t}, value |-> {_val(setup, val)}]" for t, val in v
                )
                + ">>"
            )
        elif isinstance(v, bool):
            v = "TRUE" if v else "FALSE"
        parts.append(f"{k} |-> {v}")
    return "[" + ", ".join(parts) + "]"


def format_state(setup, st: dict) -> str:
    S = len(st["currentTerm"])
    sv = lambda i: _srv(setup, i)
    lines = []
    lines.append(
        "/\\ currentTerm = "
        + _fmt_fun((sv(i), st["currentTerm"][i]) for i in range(S))
    )
    lines.append(
        "/\\ state = "
        + _fmt_fun((sv(i), STATE_NAMES[st["state"][i]]) for i in range(S))
    )
    lines.append(
        "/\\ votedFor = "
        + _fmt_fun(
            (sv(i), "Nil" if st["votedFor"][i] is None else sv(st["votedFor"][i]))
            for i in range(S)
        )
    )
    lines.append(
        "/\\ votesGranted = "
        + _fmt_fun(
            (sv(i), "{" + ", ".join(sv(j) for j in sorted(st["votesGranted"][i])) + "}")
            for i in range(S)
        )
    )
    lines.append(
        "/\\ log = "
        + _fmt_fun(
            (
                sv(i),
                "<<"
                + ", ".join(
                    f"[term |-> {t}, value |-> {_val(setup, v)}]" for t, v in st["log"][i]
                )
                + ">>",
            )
            for i in range(S)
        )
    )
    lines.append(
        "/\\ commitIndex = "
        + _fmt_fun((sv(i), st["commitIndex"][i]) for i in range(S))
    )
    if "fsyncIndex" in st:  # RaftFsync (RaftFsync.tla:92)
        lines.append(
            "/\\ fsyncIndex = "
            + _fmt_fun((sv(i), st["fsyncIndex"][i]) for i in range(S))
        )
    for name in ("nextIndex", "matchIndex", "pendingResponse"):
        lines.append(
            f"/\\ {name} = "
            + _fmt_fun(
                (
                    sv(i),
                    _fmt_fun(
                        (
                            sv(j),
                            "TRUE"
                            if st[name][i][j] is True
                            else ("FALSE" if st[name][i][j] is False else st[name][i][j]),
                        )
                        for j in range(S)
                    ),
                )
                for i in range(S)
            )
        )
    msgs = sorted(st["messages"])
    lines.append(
        "/\\ messages = ("
        + " @@ ".join(f"{_fmt_msg(setup, m)} :> {c}" for m, c in msgs)
        + ")"
    )
    lines.append(
        "/\\ acked = "
        + _fmt_fun(
            (
                _val(setup, v),
                {None: "Nil", False: "FALSE", True: "TRUE"}[st["acked"][v]],
            )
            for v in range(len(st["acked"]))
        )
    )
    lines.append(f"/\\ electionCtr = {st['electionCtr']}")
    lines.append(f"/\\ restartCtr = {st['restartCtr']}")
    return "\n".join(lines)


def format_trace(trace, setup) -> str:
    out = []
    for n, (label, st) in enumerate(trace, start=1):
        out.append(f"State {n}: <{label}>")
        out.append(format_state(setup, st))
        out.append("")
    return "\n".join(out)
