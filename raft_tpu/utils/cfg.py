"""TLC ``.cfg`` parser — the compatibility surface of the checker.

``CHECKER=tpu`` must load the reference's per-variant cfg files unmodified
(SURVEY.md §5.6), covering the grammar actually used by the nine configs:
``CONSTANTS`` (model values, model-value sets, numbers, booleans),
``INIT``/``NEXT``, ``VIEW``, ``SYMMETRY``, ``INVARIANT``, plus
commented-out ``SPECIFICATION``/``PROPERTY`` lines. Two reference cfgs are
deliberately broken and must be *diagnosed*, not crashed on
(SURVEY.md §2.2): ``PullRaft.cfg`` references undeclared model value
``v2``; ``RaftWithReconfigAddRemove.cfg`` omits the required
``MaxClusterSize`` constant (checked by the per-spec builder).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field


class CfgError(Exception):
    pass


@dataclass
class ModelValue:
    """A TLC model value (``n1 = n1``): an opaque symbolic constant."""

    name: str

    def __repr__(self):
        return self.name


@dataclass
class Cfg:
    path: str
    constants: dict[str, object] = field(default_factory=dict)  # name -> value
    init: str | None = None
    next: str | None = None
    view: str | None = None
    symmetry: str | None = None
    invariants: list[str] = field(default_factory=list)
    properties: list[str] = field(default_factory=list)
    constraints: list[str] = field(default_factory=list)
    specification: str | None = None
    # declaration order of model values (TLC set/order determinism)
    model_values: list[str] = field(default_factory=list)
    # recoverable cfg bugs found while parsing (e.g. PullRaft.cfg's
    # undeclared `v2`); parse_cfg raises on these unless lenient=True
    diagnostics: list[str] = field(default_factory=list)
    # whether recoverable bugs should be repaired (set by parse_cfg; spec
    # builders consult this for builder-level diagnoses such as the missing
    # MaxClusterSize in RaftWithReconfigAddRemove.cfg)
    lenient: bool = False

    def server_like(self, name: str) -> list[str]:
        v = self.constants.get(name)
        if not isinstance(v, tuple):
            raise CfgError(f"{self.path}: constant {name} is not a set")
        return [x.name for x in v]


_SECTIONS = {
    "SPECIFICATION",
    "CONSTANTS",
    "CONSTANT",
    "INIT",
    "NEXT",
    "VIEW",
    "SYMMETRY",
    "INVARIANT",
    "INVARIANTS",
    "PROPERTY",
    "PROPERTIES",
    "CONSTRAINT",
    "CONSTRAINTS",
}


def _strip_comment(line: str) -> str:
    i = line.find("\\*")
    return line[:i] if i >= 0 else line


def parse_cfg(path: str, text: str | None = None, lenient: bool = False) -> Cfg:
    """Parse a TLC cfg. ``lenient=True`` downgrades recoverable cfg bugs
    (see Cfg.diagnostics) from errors to recorded diagnostics, applying the
    obvious repair — e.g. ``PullRaft.cfg:9-11`` uses ``v2`` in the Value set
    without declaring it as a model value; the repair declares it."""
    if text is None:
        with open(path) as f:
            text = f.read()
    cfg = Cfg(path=path, lenient=lenient)
    section = None
    pending: list[str] = []  # tokens for CONSTANTS assignments spanning lines

    def flush_assignment(tokens: list[str]):
        if not tokens:
            return
        m = re.match(r"^\s*(\w+)\s*=\s*(.+?)\s*$", " ".join(tokens))
        if not m:
            raise CfgError(f"{path}: cannot parse constant assignment: {' '.join(tokens)!r}")
        name, rhs = m.group(1), m.group(2)
        cfg.constants[name] = _parse_value(cfg, name, rhs, path)

    for raw in text.splitlines():
        line = _strip_comment(raw).strip()
        if not line:
            continue
        head = line.split()[0]
        if head in _SECTIONS:
            flush_assignment(pending)
            pending = []
            section = head
            rest = line[len(head) :].strip()
            if not rest:
                continue
            line = rest
        if section in ("CONSTANTS", "CONSTANT"):
            # assignments may span lines; a new assignment starts with `name =`
            if re.match(r"^\w+\s*=", line) and pending:
                flush_assignment(pending)
                pending = []
            pending.append(line)
            if _balanced(" ".join(pending)) and "=" in " ".join(pending):
                flush_assignment(pending)
                pending = []
        elif section == "SPECIFICATION":
            cfg.specification = line
        elif section == "INIT":
            cfg.init = line
        elif section == "NEXT":
            cfg.next = line
        elif section == "VIEW":
            cfg.view = line
        elif section == "SYMMETRY":
            cfg.symmetry = line
        elif section in ("INVARIANT", "INVARIANTS"):
            cfg.invariants += line.split()
        elif section in ("PROPERTY", "PROPERTIES"):
            cfg.properties += line.split()
        elif section in ("CONSTRAINT", "CONSTRAINTS"):
            cfg.constraints += line.split()
        elif section is None:
            raise CfgError(f"{path}: content before any section keyword: {line!r}")
    flush_assignment(pending)
    if cfg.diagnostics and not lenient:
        raise CfgError("; ".join(cfg.diagnostics))
    return cfg


def _balanced(s: str) -> bool:
    return s.count("{") == s.count("}")


def _parse_value(cfg: Cfg, name: str, rhs: str, path: str):
    rhs = rhs.strip()
    if rhs.startswith("{"):
        if not rhs.endswith("}"):
            raise CfgError(f"{path}: unterminated set literal for {name}")
        items = [t for t in re.split(r"[\s,]+", rhs[1:-1].strip()) if t]
        out = []
        for t in items:
            if re.fullmatch(r"-?\d+", t):
                out.append(int(t))
                continue
            mv = _lookup_model_value(cfg, t)
            if mv is None:
                cfg.diagnostics.append(
                    f"{path}: set {name} references undeclared model value {t!r} "
                    f"(declared: {', '.join(cfg.model_values) or 'none'}); "
                    f"lenient mode repairs this by declaring it"
                )
                mv = ModelValue(t)
                cfg.constants[t] = mv
                cfg.model_values.append(t)
            out.append(mv)
        return tuple(out)
    if re.fullmatch(r"-?\d+", rhs):
        return int(rhs)
    if rhs == "TRUE":
        return True
    if rhs == "FALSE":
        return False
    if rhs == name:  # model value declaration: `n1 = n1`
        mv = ModelValue(name)
        cfg.model_values.append(name)
        return mv
    # reference to a previously declared model value or constant
    if rhs in cfg.constants:
        return cfg.constants[rhs]
    raise CfgError(f"{path}: cannot parse value {rhs!r} for constant {name}")


def _lookup_model_value(cfg: Cfg, token: str):
    v = cfg.constants.get(token)
    if isinstance(v, ModelValue):
        return v
    return None
