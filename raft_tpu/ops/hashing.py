"""64-bit state fingerprinting — formula v4 (u32-pair internals).

TLC dedups on 64-bit fingerprints of the (VIEW-projected, symmetry-reduced)
state; we reproduce the same collision budget with a vectorized
Zobrist-style hash: each lane of the int32 state vector is avalanche-mixed
together with its position, lanes reduce, and a final mix finishes.

v4 (round 5): all MIXING arithmetic runs as TWO INDEPENDENT 32-bit
streams (murmur3-style fmix32 with distinct multiplicative constants and
positional salts), combined into one u64 only at the end. Rationale,
measured on this TPU backend (scripts/hash32_micro.py + /tmp chained
micro-benches, round 5):

  u64 multiply   ~150 ms / 12.5M lanes   (emulated/scalarized)
  u64 == / sort  ~55-58 ms / 12.5M       (comparator path)
  u32 mix stream  ~0.2 ms / 75M lanes    (native VPU)

i.e. the v1-v3 splitmix64 hash paid a ~400x penalty on every lane, which
is why canonicalization owned 96-98% of chunk time through round 4
(VERDICT.md Weak #2/#3). Two independent 32-bit streams keep the
2^-64-class collision budget (the audit's second hash family still
fails independently via `seed`).

Empirical TPU rules encoded here (see also `sort_u64` / `ne_u64`):
  - never MULTIPLY u64 lanes -> u32-pair streams
  - never jnp.sort a u64 array -> 2-key (hi, lo) u32 lax.sort
  - never ==/!= u64 lanes at scale -> decomposed u32 compares
  - u64 xor/shift/add/min/searchsorted/argsort are fine

One fusion caveat: TWO separate reductions over one producer hit an XLA
fusion cliff (~400x); the pair streams are therefore STACKED into one
array and reduced by a single op (`_reduce_pair`).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from jax import lax

_C1 = np.uint64(0x9E3779B97F4A7C15)  # golden-ratio increment (splitmix64)
_C2 = np.uint64(0xC2B2AE3D27D4EB4F)
_M1 = np.uint64(0xBF58476D1CE4E5B9)
_M2 = np.uint64(0x94D049BB133111EB)

U64_MAX = np.uint64(0xFFFFFFFFFFFFFFFF)  # "no fingerprint" sentinel
_MASK32 = np.uint64(0xFFFFFFFF)

# u32 stream constants (murmur3 c1/c2 + fmix32 multipliers + golden ratios)
KA = np.uint32(0xCC9E2D51)
KB = np.uint32(0x1B873593)
PA = np.uint32(0x9E3779B9)
PB = np.uint32(0x85EBCA77)
_F1 = np.uint32(0x85EBCA6B)
_F2 = np.uint32(0xC2B2AE35)


def mix64(z):
    """splitmix64 finalizer — full-avalanche 64-bit mix. HOST/setup-time
    and tiny-array use only: u64 multiplies are ~400x slow on this TPU."""
    z = (z ^ (z >> np.uint64(30))) * _M1
    z = (z ^ (z >> np.uint64(27))) * _M2
    return z ^ (z >> np.uint64(31))


def mix32(z):
    """murmur3 fmix32 — full-avalanche 32-bit mix (native TPU u32 ops)."""
    z = (z ^ (z >> np.uint32(16))) * _F1
    z = (z ^ (z >> np.uint32(13))) * _F2
    return z ^ (z >> np.uint32(16))


def combine_pair(a, b):
    """(u32, u32) stream pair -> u64, with a final cross-avalanche so a
    change in either stream diffuses into both output words (u32 ops
    only — no u64 multiply)."""
    a2 = mix32(a + (b ^ KA))
    b2 = mix32(b + (a ^ KB))
    return a2.astype(jnp.uint64) << np.uint64(32) | b2.astype(jnp.uint64)


def _reduce_pair(ha, hb, op="xor"):
    """Reduce two [..., K] u32 streams over the lane axis with ONE reduce
    op (two separate reduces over a shared producer hit the fusion
    cliff, see module docstring)."""
    h = jnp.stack([ha, hb], axis=-1)  # [..., K, 2]
    if op == "xor":
        r = jnp.bitwise_xor.reduce(h, axis=-2)
    else:
        r = jnp.sum(h, axis=-2, dtype=jnp.uint32)
    return r[..., 0], r[..., 1]


def seed_salts(seed: int) -> tuple[np.uint32, np.uint32]:
    """Host-derived per-seed u32 salt pair; (0, 0) for seed=0 so the
    default family is the plain stream."""
    if not seed:
        return np.uint32(0), np.uint32(0)
    m = 0xFFFFFFFFFFFFFFFF
    z = (seed * 0x9E3779B97F4A7C15) & m
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & m
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & m
    z ^= z >> 31
    return np.uint32(z >> 32), np.uint32(z & 0xFFFFFFFF)


def hash_lanes_pair(vec, seed: int = 0):
    """Hash an int32 [..., K] vector to a (u32, u32) stream pair.

    A nonzero seed selects an independent hash family by XORing a
    seed-derived per-lane stream into the inputs BEFORE the multiply —
    a constant additive seed would merely translate every lane's pre-mix
    input, leaving the family invariant on the collision class where two
    states' multisets of pre-mix lane values coincide (the collision
    audit, checker/audit.py, relies on families failing independently)."""
    k = vec.shape[-1]
    x = vec.astype(jnp.uint32)
    pos = jnp.arange(k, dtype=jnp.uint32)
    pa = pos * PA
    pb = pos * PB
    xa = x
    xb = x
    if seed:
        sa, sb = seed_salts(seed)
        xa = x ^ mix32(pa + sa)
        xb = x ^ mix32(pb + sb)
    ha = mix32(xa * KA + pa)
    hb = mix32(xb * KB + pb)
    acc_a, acc_b = _reduce_pair(ha, hb, op="xor")
    ka = np.uint32((k * int(KA)) & 0xFFFFFFFF)
    kb = np.uint32((k * int(KB)) & 0xFFFFFFFF)
    return acc_a ^ ka, acc_b ^ kb


def hash_lanes(vec, seed: int = 0):
    """Hash an int32 [..., K] vector to uint64 [...] (v4 pair scheme)."""
    return combine_pair(*hash_lanes_pair(vec, seed))


# ---------------- u64 lane helpers (decomposed fast paths) ----------------


def split_u64(x):
    """u64 [...] -> (hi, lo) u32 pair (shifts/ands only — fast)."""
    return (x >> np.uint64(32)).astype(jnp.uint32), (x & _MASK32).astype(
        jnp.uint32
    )


def join_u64(hi, lo):
    return hi.astype(jnp.uint64) << np.uint64(32) | lo.astype(jnp.uint64)


def sort_u64(x, axis=-1):
    """Sort u64 values (ascending) via a 2-key u32 lax.sort — ~300x the
    single-array u64 sort on this TPU."""
    hi, lo = split_u64(x)
    shi, slo = lax.sort((hi, lo), num_keys=2, dimension=axis)
    return join_u64(shi, slo)


def sort_u64_with_idx(x, axis=-1):
    """Stable ascending u64 sort returning (sorted, original_index):
    a 3-key u32 sort with the index iota as the tie-breaking key, so
    equal values keep first-occurrence order (gid-numbering parity)."""
    hi, lo = split_u64(x)
    idx = lax.broadcasted_iota(jnp.int32, x.shape, x.ndim - 1 if axis == -1 else axis)
    shi, slo, sidx = lax.sort((hi, lo, idx), num_keys=3, dimension=axis)
    return join_u64(shi, slo), sidx


def ge_u64(a, b):
    """Elementwise a >= b on u64 via u32 compares (u64 comparator lanes
    are slow on this TPU)."""
    ah, al = split_u64(a)
    bh, bl = split_u64(b)
    return (ah > bh) | ((ah == bh) & (al >= bl))


def ne_u64(a, b):
    """Elementwise a != b on u64 via u32 compares (u64 ==/!= lanes are
    ~180x slow on this TPU)."""
    ah, al = split_u64(a)
    bh, bl = split_u64(b)
    return (ah != bh) | (al != bl)


def eq_u64(a, b):
    ah, al = split_u64(a)
    bh, bl = split_u64(b)
    return (ah == bh) & (al == bl)


def memo_slot(fp, mcap: int):
    """Direct-mapped slot of a u64 fingerprint in a table of ``mcap``
    (power-of-two) rows: both u32 halves remixed through fmix32 — no
    u64 arithmetic — so raw fingerprints that share a half still spread
    across slots."""
    hi, lo = split_u64(fp)
    idx = mix32(lo ^ (mix32(hi + KB) + KA))
    return (idx & np.uint32(mcap - 1)).astype(jnp.int32)
