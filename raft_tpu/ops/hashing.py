"""64-bit state fingerprinting.

TLC dedups on 64-bit fingerprints of the (VIEW-projected, symmetry-reduced)
state; we reproduce the same collision budget with a vectorized
Zobrist-style hash: each lane of the int32 state vector is avalanche-mixed
together with its position, lanes XOR-reduce, and a final mix finishes.
XOR-reduction keeps the hash embarrassingly parallel (MXU/VPU friendly)
while position mixing preserves order sensitivity.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

_C1 = np.uint64(0x9E3779B97F4A7C15)  # golden-ratio increment (splitmix64)
_C2 = np.uint64(0xC2B2AE3D27D4EB4F)
_M1 = np.uint64(0xBF58476D1CE4E5B9)
_M2 = np.uint64(0x94D049BB133111EB)

U64_MAX = np.uint64(0xFFFFFFFFFFFFFFFF)  # "no fingerprint" sentinel


def mix64(z):
    """splitmix64 finalizer — full-avalanche 64-bit mix."""
    z = (z ^ (z >> np.uint64(30))) * _M1
    z = (z ^ (z >> np.uint64(27))) * _M2
    return z ^ (z >> np.uint64(31))


def hash_lanes(vec, seed: int = 0):
    """Hash an int32 [..., K] vector to uint64 [...].

    A nonzero seed selects an independent hash family by XORing a
    seed-derived per-lane stream into the inputs BEFORE the multiply —
    a constant additive seed would merely translate every lane's pre-mix
    input, leaving the family invariant on the collision class where two
    states' multisets of pre-mix lane values coincide (the collision
    audit, checker/audit.py, relies on families failing independently).
    seed=0 is the identity stream, keeping default fingerprints stable
    across this change (checkpoints store them)."""
    k = vec.shape[-1]
    x = vec.astype(jnp.uint64)
    pos = jnp.arange(k, dtype=jnp.uint64)
    if seed:
        x = x ^ mix64(pos * _C2 + np.uint64(seed))
    h = mix64(x * _C1 + pos * _C2)
    acc = jnp.bitwise_xor.reduce(h, axis=-1)
    kmix = np.uint64((k * int(_C1)) & 0xFFFFFFFFFFFFFFFF)
    return mix64(acc ^ kmix)
