"""Branchless message-bag kernels.

TLA+ semantics being reproduced (reference ``standard-raft/Raft.tla``):
  - the bag is a function record -> delivery count (``Raft.tla:55-58``);
  - ``Discard`` decrements the count but the record STAYS in the domain
    (``Raft.tla:164-167``) — this is what makes ``_SendOnce`` a permanent
    action-disable latch (``Raft.tla:134-138``). Hence slots are never
    freed: the slot table grows monotonically within a behavior and
    count-0 slots are genuine state that must fingerprint.

Encoding: three int32 lanes per bag — sorted key words ``hi``/``lo``
(30 bits each, see ops/packing.py) plus ``cnt``. Unused slots hold
(EMPTY, EMPTY, 0) and sort last; keys are unique, so the sorted triple is
a canonical form and bag equality is array equality.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .packing import EMPTY


def bag_sort(hi, lo, cnt):
    """Canonicalize: sort slots lexicographically by (hi, lo); empties last."""
    hi, lo, cnt = lax.sort((hi, lo, cnt), num_keys=2)
    return hi, lo, cnt


def bag_count(hi, lo, cnt, khi, klo):
    """Delivery count of a key (0 if not in the domain)."""
    eq = (hi == khi) & (lo == klo)
    return jnp.sum(jnp.where(eq, cnt, 0))


def bag_put(hi, lo, cnt, khi, klo):
    """Add one delivery of key (khi, klo) — TLA+ ``_SendNoRestriction``
    (``Raft.tla:129-132``): increment if the record is in the domain, else
    insert with count 1.

    Returns (hi, lo, cnt, existed, overflow). ``existed`` lets callers
    implement ``_SendOnce`` (valid iff not existed). ``overflow`` is True
    when an insert was needed but no slot was free — the driver must abort
    and re-run with more slots (never silently dropped).
    """
    eq = (hi == khi) & (lo == klo)
    existed = eq.any()
    cnt_inc = cnt + eq.astype(cnt.dtype)

    is_empty = hi == EMPTY
    slot = jnp.argmax(is_empty)  # empties are sorted last; any empty works
    have_empty = is_empty.any()
    hi_ins = hi.at[slot].set(khi)
    lo_ins = lo.at[slot].set(klo)
    cnt_ins = cnt.at[slot].set(jnp.int32(1))

    hi2 = jnp.where(existed, hi, hi_ins)
    lo2 = jnp.where(existed, lo, lo_ins)
    cnt2 = jnp.where(existed, cnt_inc, cnt_ins)
    overflow = (~existed) & (~have_empty)
    hi2, lo2, cnt2 = bag_sort(hi2, lo2, cnt2)
    return hi2, lo2, cnt2, existed, overflow


def bag_discard_at(cnt, slot):
    """``Discard`` (``Raft.tla:164-167``): one fewer delivery; domain keeps
    the record, so keys don't move and no re-sort is needed."""
    return cnt.at[slot].add(jnp.int32(-1))
