"""Branchless message-bag kernels.

TLA+ semantics being reproduced (reference ``standard-raft/Raft.tla``):
  - the bag is a function record -> delivery count (``Raft.tla:55-58``);
  - ``Discard`` decrements the count but the record STAYS in the domain
    (``Raft.tla:164-167``) — this is what makes ``_SendOnce`` a permanent
    action-disable latch (``Raft.tla:134-138``). Hence slots are never
    freed: the slot table grows monotonically within a behavior and
    count-0 slots are genuine state that must fingerprint.

Encoding: N key words + a count lane per slot (see ops/packing.py).
``words`` is a list of [M] int32 arrays in lexicographic sort order;
unused slots hold (EMPTY, ..., 0) and sort last; keys are unique, so the
sorted slot table is a canonical form and bag equality is array equality.
The 2-word (hi, lo) kernels used by the BitPacker models are thin
wrappers over the N-word ones.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .packing import EMPTY


def wide_bag_sort(words, cnt):
    """Canonicalize: sort slots lexicographically by the key words."""
    out = lax.sort((*words, cnt), num_keys=len(words))
    return list(out[:-1]), out[-1]


def wide_bag_put(words, cnt, key):
    """Add one delivery of the key tuple — TLA+ ``_SendNoRestriction``
    (``Raft.tla:129-132``): increment if the record is in the domain, else
    insert with count 1.

    Returns (words, cnt, existed, overflow). ``existed`` lets callers
    implement ``_SendOnce`` (valid iff not existed). ``overflow`` is True
    when an insert was needed but no slot was free — the driver must abort
    and re-run with more slots (never silently dropped).

    The slot table is ALWAYS sorted on entry (the bag invariant: states
    are canonical, and put/discard preserve sort order), so the insert is
    a branchless shift at the key's lexicographic position — bit-identical
    to the retired insert-into-an-empty-then-``lax.sort`` kernel (unique
    keys, ``EMPTY`` = 2**WORD_BITS strictly above every packed word, so
    the insertion point is unique and empties stay a suffix), at a
    fraction of the cost: the M-lane sort network was ~2/3 of every
    message-sending action kernel, paid once per put per candidate lane.
    Elementwise where/roll instead of a traced-index scatter also keeps
    the kernel immune to the axon TPU scatter-drop miscompile that bit
    the round-2 one-hot rewrite (silent dedup miscounts at batch >=
    4096); the systematic defense for the remaining traced scatters is
    the two-chunk parity gate (checker/parity.py) plus the CPU
    chunk-sweep tests.
    """
    eq = jnp.ones_like(words[0], dtype=bool)
    for w, k in zip(words, key):
        eq &= w == k
    existed = eq.any()
    cnt_inc = cnt + eq.astype(cnt.dtype)

    have_empty = (words[0] == EMPTY).any()
    # lexicographic rank of the key among the resident slots; empties
    # hold (EMPTY, ..., 0) and EMPTY exceeds every packed word, so they
    # never count and the insert position lands before the empty suffix
    less = jnp.zeros_like(words[0], dtype=bool)
    tie = jnp.ones_like(words[0], dtype=bool)
    for w, k in zip(words, key):
        less |= tie & (w < k)
        tie &= w == k
    pos = jnp.sum(less.astype(jnp.int32))
    lane = jnp.arange(cnt.shape[0], dtype=jnp.int32)
    # lanes < pos keep their slot, lane pos takes the key, lanes > pos
    # take their left neighbor (the shifted-out last lane is an empty
    # whenever a free slot exists; without one, overflow aborts the run
    # before any lane is trusted). roll()'s lane-0 wraparound is never
    # selected: lane 0 is either < pos or == pos.
    ins = [
        jnp.where(lane < pos, w, jnp.where(lane == pos, k, jnp.roll(w, 1)))
        for w, k in zip(words, key)
    ]
    cnt_ins = jnp.where(
        lane < pos, cnt, jnp.where(lane == pos, jnp.int32(1), jnp.roll(cnt, 1))
    )

    out = [jnp.where(existed, w, wi) for w, wi in zip(words, ins)]
    cnt2 = jnp.where(existed, cnt_inc, cnt_ins)
    overflow = (~existed) & (~have_empty)
    return out, cnt2, existed, overflow


def bag_sort(hi, lo, cnt):
    """2-word canonicalization: sort by (hi, lo); empties last."""
    words, cnt = wide_bag_sort([hi, lo], cnt)
    return words[0], words[1], cnt


def bag_count(hi, lo, cnt, khi, klo):
    """Delivery count of a key (0 if not in the domain)."""
    eq = (hi == khi) & (lo == klo)
    return jnp.sum(jnp.where(eq, cnt, 0))


def bag_put(hi, lo, cnt, khi, klo):
    """2-word ``_SendNoRestriction``; see wide_bag_put."""
    words, cnt2, existed, overflow = wide_bag_put([hi, lo], cnt, (khi, klo))
    return words[0], words[1], cnt2, existed, overflow


def bag_discard_at(cnt, slot):
    """``Discard`` (``Raft.tla:164-167``): one fewer delivery; domain keeps
    the record, so keys don't move and no re-sort is needed.

    One-hot subtract for the same axon scatter-miscompile reason as
    wide_bag_put."""
    onehot = jnp.arange(cnt.shape[0], dtype=jnp.int32) == slot
    return cnt - onehot.astype(cnt.dtype)
