"""Bit-packing of TLA+ message records into two non-negative int32 words.

The reference specs model the network as a bag: a function from message
records to delivery counts (``Raft.tla:55-58``). Record equality is
full-field equality, so a record packs losslessly into a fixed-width bit
string; bag membership / lookup then becomes integer comparison, and bag
canonicalization becomes an integer sort.

We pack into two 30-bit words (``hi``, ``lo``) kept in int32 lanes of the
state vector. 30 bits per word keeps every word non-negative, so
lexicographic (hi, lo) sorting with signed comparisons gives the correct
unsigned order, and the EMPTY sentinel (1 << 30) sorts after all real keys.
"""

from __future__ import annotations

import numpy as np

WORD_BITS = 30
EMPTY = np.int32(1 << WORD_BITS)  # sentinel word for unused message slots


class BitPacker:
    """Packs a fixed schema of small unsigned fields into (hi, lo) words.

    Fields are laid out low-bit-first in declaration order; a field that
    would straddle the 30-bit word boundary is bumped to the next word.
    Works on numpy arrays, jax arrays and plain ints (pure arithmetic).
    """

    def __init__(self, fields: list[tuple[str, int]]):
        self.fields: dict[str, tuple[int, int]] = {}  # name -> (offset, bits)
        off = 0
        for name, bits in fields:
            if bits <= 0:
                raise ValueError(f"field {name} has non-positive width")
            word, in_word = divmod(off, WORD_BITS)
            if in_word + bits > WORD_BITS:  # would straddle: bump to next word
                off = (word + 1) * WORD_BITS
            if off + bits > 2 * WORD_BITS:
                raise ValueError("message schema exceeds 60 bits")
            self.fields[name] = (off, bits)
            off += bits
        self.total_bits = off

    def field_names(self) -> list[str]:
        return list(self.fields)

    def pack(self, **vals):
        """Pack named field values into (hi, lo). Missing fields are 0."""
        unknown = set(vals) - set(self.fields)
        if unknown:
            raise KeyError(f"unknown message fields {unknown}")
        hi = 0
        lo = 0
        for name, v in vals.items():
            off, bits = self.fields[name]
            if isinstance(v, (int, np.integer)):
                if v < 0 or v >= (1 << bits):
                    raise ValueError(f"{name}={v} out of range for {bits} bits")
                v = int(v)
            word, in_word = divmod(off, WORD_BITS)
            placed = v << in_word
            if word == 0:
                lo = lo + placed
            else:
                hi = hi + placed
        return hi, lo

    def unpack(self, hi, lo, name: str):
        """Extract one field from (hi, lo); works on arrays or ints."""
        off, bits = self.fields[name]
        word, in_word = divmod(off, WORD_BITS)
        src = hi if word == 1 else lo
        return (src >> in_word) & ((1 << bits) - 1)

    def unpack_all(self, hi, lo) -> dict:
        return {name: self.unpack(hi, lo, name) for name in self.fields}

    def replace(self, hi, lo, name: str, value):
        """Return (hi, lo) with one field replaced; array-friendly."""
        off, bits = self.fields[name]
        word, in_word = divmod(off, WORD_BITS)
        mask = ((1 << bits) - 1) << in_word
        if word == 1:
            hi = (hi & ~mask) | (value << in_word)
        else:
            lo = (lo & ~mask) | (value << in_word)
        return hi, lo


def bits_for(max_value: int) -> int:
    """Width needed to store values in [0, max_value]."""
    return max(1, int(max_value).bit_length())
