"""Bit-packing of TLA+ message records into N non-negative int32 words.

The reference specs model the network as a bag: a function from message
records to delivery counts (``Raft.tla:55-58``). Record equality is
full-field equality, so a record packs losslessly into a fixed-width bit
string; bag membership / lookup then becomes integer comparison, and bag
canonicalization becomes an integer sort.

Words are 30-bit so every word stays non-negative in an int32 lane:
lexicographic sorting with signed comparisons then gives the correct
unsigned order, and the EMPTY sentinel (1 << 30) sorts after all real
keys. ``WidePacker`` is the general N-word form (needed for records too
big for 60 bits, e.g. the reconfig specs' snapshot messages that embed a
whole log, ``RaftWithReconfigAddRemove.tla:870-876``); ``BitPacker`` is
the 2-word case behind the original (hi, lo) API.
"""

from __future__ import annotations

import numpy as np

WORD_BITS = 30
EMPTY = np.int32(1 << WORD_BITS)  # sentinel word for unused message slots


class WidePacker:
    """Packs a fixed schema of small unsigned fields into an n_words tuple.

    Fields are laid out low-bit-first in declaration order starting in
    word 0; a field that would straddle a 30-bit word boundary is bumped
    to the next word. Works on numpy arrays, jax arrays and plain ints
    (pure arithmetic). Unused bag slots hold EMPTY in every word.
    """

    def __init__(self, fields: list[tuple[str, int]], n_words: int):
        self.n_words = n_words
        self.fields: dict[str, tuple[int, int]] = {}  # name -> (offset, bits)
        off = 0
        for name, bits in fields:
            if bits <= 0:
                raise ValueError(f"field {name} has non-positive width")
            word, in_word = divmod(off, WORD_BITS)
            if in_word + bits > WORD_BITS:  # would straddle: bump to next word
                off = (word + 1) * WORD_BITS
            if off + bits > n_words * WORD_BITS:
                raise ValueError(
                    f"message schema exceeds {n_words * WORD_BITS} bits"
                )
            self.fields[name] = (off, bits)
            off += bits
        self.total_bits = off

    def field_names(self) -> list[str]:
        return list(self.fields)

    def pack(self, **vals) -> tuple:
        """Pack named field values into an n_words tuple (missing = 0)."""
        unknown = set(vals) - set(self.fields)
        if unknown:
            raise KeyError(f"unknown message fields {unknown}")
        words = [0] * self.n_words
        for name, v in vals.items():
            off, bits = self.fields[name]
            if isinstance(v, (int, np.integer)):
                if v < 0 or v >= (1 << bits):
                    raise ValueError(f"{name}={v} out of range for {bits} bits")
                v = int(v)
            word, in_word = divmod(off, WORD_BITS)
            words[word] = words[word] + (v << in_word)
        return tuple(words)

    def unpack(self, words, name: str):
        off, bits = self.fields[name]
        word, in_word = divmod(off, WORD_BITS)
        return (words[word] >> in_word) & ((1 << bits) - 1)

    def unpack_all(self, words) -> dict:
        return {name: self.unpack(words, name) for name in self.fields}

    def replace(self, words, name: str, value) -> tuple:
        off, bits = self.fields[name]
        word, in_word = divmod(off, WORD_BITS)
        mask = ((1 << bits) - 1) << in_word
        out = list(words)
        out[word] = (out[word] & ~mask) | (value << in_word)
        return tuple(out)


class BitPacker:
    """Two-word packer behind the original (hi, lo) API.

    Delegates to a ``WidePacker(fields, 2)``: the low word (offset-0
    fields) is ``lo`` and the second word is ``hi``, preserving the
    historical (hi, lo) lexicographic sort order of the 2-word bags.
    """

    def __init__(self, fields: list[tuple[str, int]]):
        self._w = WidePacker(fields, 2)
        self.fields = self._w.fields
        self.total_bits = self._w.total_bits

    def field_names(self) -> list[str]:
        return list(self.fields)

    def pack(self, **vals):
        lo, hi = self._w.pack(**vals)
        return hi, lo

    def unpack(self, hi, lo, name: str):
        return self._w.unpack((lo, hi), name)

    def unpack_all(self, hi, lo) -> dict:
        return self._w.unpack_all((lo, hi))

    def replace(self, hi, lo, name: str, value):
        lo2, hi2 = self._w.replace((lo, hi), name, value)
        return hi2, lo2


def bits_for(max_value: int) -> int:
    """Width needed to store values in [0, max_value]."""
    return max(1, int(max_value).bit_length())
