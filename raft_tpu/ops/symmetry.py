"""VIEW projection + SYMMETRY reduction + fingerprinting, layout-driven.

Reproduces TLC's distinct-state semantics for cfgs that declare
``VIEW view`` / ``SYMMETRY symmServers`` (e.g. ``standard-raft/Raft.cfg:28-29``):

  - VIEW: aux counters are excluded from the fingerprint
    (``Raft.tla:115`` — ``view`` omits ``acked/electionCtr/restartCtr``).
    By layout convention the view is the contiguous prefix
    ``vec[:layout.view_len]``.
  - SYMMETRY: two states related by a server permutation are the same
    distinct state (``Raft.tla:116``). We canonicalize by taking the MIN
    over all S! permutations of the permuted view's 64-bit hash — a
    permutation-invariant fingerprint with TLC's collision budget.

A permutation sigma acts on the packed view as (see models/base.py kinds):
row gathers for server-indexed axes, value remaps for server-valued fields
and bitmasks, and field remaps inside packed message keys followed by a
bag re-sort. The row gathers compose into ONE precomputed lane-gather per
permutation, so the device work per permutation is a gather + two tiny
fixups + an M-lane sort + hash.

Message keys may be 2-word (BitPacker: msg_hi/msg_lo/msg_cnt kinds) or
N-word (WidePacker: msg_word kinds, declared in word order). A model
declares which packed fields transform under sigma either via
``msg_server_fields`` / ``msg_server_nil_fields`` (plain / nil-valued
server ids) or a full ``msg_perm_spec`` of (field, kind) pairs with kind
in {"server", "server_nil", "server_bitmask"} — the bitmask kind covers
member sets inside reconfig-spec messages
(``RaftWithReconfigAddRemove.tla:874``).
"""

from __future__ import annotations

import itertools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .hashing import hash_lanes
from .packing import EMPTY, BitPacker, WidePacker
from ..models.base import Layout


class Canonicalizer:
    @classmethod
    def for_model(cls, model, symmetry: bool = True, seed: int = 0) -> "Canonicalizer":
        """Build from a model's declared message-field symmetry contract
        (keeps the model -> canonicalization plumbing in one place).

        A model with data-dependent canonicalization (e.g. the
        KRaftWithReconfig slot encoding, where a host permutation re-sorts
        the identity slots) supplies its own via ``make_canonicalizer``;
        the returned object provides the same ``fingerprints`` /
        ``_fingerprints`` / ``symmetry`` surface the checkers use."""
        if hasattr(model, "make_canonicalizer"):
            return model.make_canonicalizer(symmetry, seed=seed)
        return cls(
            model.layout,
            model.packer,
            msg_server_fields=getattr(
                model, "msg_server_fields", ("msource", "mdest")
            ),
            msg_server_nil_fields=getattr(model, "msg_server_nil_fields", ()),
            msg_perm_spec=getattr(model, "msg_perm_spec", None),
            symmetry=symmetry,
            seed=seed,
        )

    def __init__(
        self,
        layout: Layout,
        packer,
        msg_server_fields: tuple[str, ...] = ("msource", "mdest"),
        msg_server_nil_fields: tuple[str, ...] = (),
        msg_perm_spec: tuple[tuple[str, str], ...] | None = None,
        symmetry: bool = True,
        seed: int = 0,
    ):
        S = layout.n_servers
        VL = layout.view_len
        assert VL is not None
        self.layout = layout
        self.packer = packer
        self.symmetry = symmetry
        # fingerprint hash seed: a second independent hash family for the
        # collision audit (checker/audit.py)
        self.seed = seed
        # Unified remap spec: (packed field, kind) with kind one of
        #   server          plain server index (msource/mdest)
        #   server_nil      0 = Nil, i+1 = server i (KRaft mleader)
        #   server_bitmask  member set as a bitmask over servers
        if msg_perm_spec is None:
            msg_perm_spec = tuple(
                (f, "server") for f in msg_server_fields
            ) + tuple((f, "server_nil") for f in msg_server_nil_fields)
        self.msg_perm_spec = msg_perm_spec

        if symmetry:
            perms = np.array(list(itertools.permutations(range(S))), dtype=np.int32)
        else:
            perms = np.arange(S, dtype=np.int32)[None, :]
        P = perms.shape[0]
        inv = np.argsort(perms, axis=1).astype(np.int32)

        # Per-permutation lane gather over the view prefix.
        gidx = np.tile(np.arange(VL, dtype=np.int32), (P, 1))
        val_lanes: list[int] = []
        bm_lanes: list[int] = []
        # key-word slices, ordered by sort significance: (hi, lo) for the
        # 2-word BitPacker bags (collected by kind, so layout declaration
        # order cannot silently flip them), msg_word declaration order for
        # the N-word WidePacker bags (word 0 = sort-major by contract)
        hi_sl: slice | None = None
        lo_sl: slice | None = None
        wide_sls: list[slice] = []
        msg_cnt_sl: slice | None = None
        for f in layout.fields.values():
            if f.offset >= VL:
                continue  # aux: not fingerprinted
            if f.kind in ("per_server", "per_server_val", "server_bitmask"):
                rest = int(math.prod(f.shape[1:])) if len(f.shape) > 1 else 1
                base = f.offset + inv[:, :, None] * rest + np.arange(rest)  # [P,S,rest]
                gidx[:, f.offset : f.offset + f.size] = base.reshape(P, -1)
                lanes = list(range(f.offset, f.offset + f.size))
                if f.kind == "per_server_val":
                    val_lanes += lanes
                elif f.kind == "server_bitmask":
                    bm_lanes += lanes
            elif f.kind == "per_server_pair":
                src = f.offset + inv[:, :, None] * S + inv[:, None, :]  # [P,S,S]
                gidx[:, f.offset : f.offset + f.size] = src.reshape(P, -1)
            elif f.kind == "msg_hi":
                hi_sl = layout.sl(f.name)
            elif f.kind == "msg_lo":
                lo_sl = layout.sl(f.name)
            elif f.kind == "msg_word":
                wide_sls.append(layout.sl(f.name))
            elif f.kind == "msg_cnt":
                msg_cnt_sl = layout.sl(f.name)
        if hi_sl is not None or lo_sl is not None:
            assert hi_sl is not None and lo_sl is not None and not wide_sls
            msg_word_sls = [hi_sl, lo_sl]
        else:
            msg_word_sls = wide_sls
        if msg_word_sls:
            n_expected = 2 if hi_sl is not None else getattr(packer, "n_words", None)
            assert n_expected is None or len(msg_word_sls) == n_expected

        # value remap: 0 stays Nil, v in 1..S maps to sigma[v-1]+1
        valmap = np.zeros((P, S + 1), dtype=np.int32)
        valmap[:, 1:] = perms + 1
        pow2sig = (1 << perms).astype(np.int32)

        self.S, self.P, self.VL = S, P, VL
        self._gidx = jnp.asarray(gidx)
        self._sigma = jnp.asarray(perms)
        self._valmap = jnp.asarray(valmap)
        self._pow2sig = jnp.asarray(pow2sig)
        self._val_lanes = np.array(sorted(val_lanes), dtype=np.int32)
        self._bm_lanes = np.array(sorted(bm_lanes), dtype=np.int32)
        self._msg_word_sls = msg_word_sls
        self._msg_cnt_sl = msg_cnt_sl
        self.fingerprints = jax.jit(self._fingerprints)

    # packer adapters: BitPacker works on (hi, lo), WidePacker on tuples
    def _unpack_key(self, words, name):
        if isinstance(self.packer, WidePacker):
            return self.packer.unpack(words, name)
        return self.packer.unpack(words[0], words[1], name)

    def _replace_key(self, words, name, value):
        if isinstance(self.packer, WidePacker):
            return list(self.packer.replace(words, name, value))
        hi, lo = self.packer.replace(words[0], words[1], name, value)
        return [hi, lo]

    def _one_perm(self, view, gi, valmap, pow2, sigma):
        """Apply one permutation to [B, VL] views and hash."""
        S = self.S
        v = view[:, gi]
        if self._val_lanes.size:
            vl = v[:, self._val_lanes]
            v = v.at[:, self._val_lanes].set(valmap[vl])
        if self._bm_lanes.size:
            x = v[:, self._bm_lanes]
            bits = (x[..., None] >> jnp.arange(S, dtype=jnp.int32)) & 1
            v = v.at[:, self._bm_lanes].set(jnp.sum(bits * pow2, axis=-1).astype(jnp.int32))
        if self._msg_word_sls:
            words = [v[:, sl] for sl in self._msg_word_sls]
            cnt = v[:, self._msg_cnt_sl]
            occ = words[0] != EMPTY
            nwords = list(words)
            for fname, kind in self.msg_perm_spec:
                val = self._unpack_key(nwords, fname)
                if kind == "server":
                    mapped = sigma[jnp.clip(val, 0, S - 1)]
                elif kind == "server_nil":
                    mapped = jnp.where(
                        val > 0, sigma[jnp.clip(val - 1, 0, S - 1)] + 1, 0
                    )
                elif kind == "server_bitmask":
                    bits = (val[..., None] >> jnp.arange(S, dtype=jnp.int32)) & 1
                    mapped = jnp.sum(bits * pow2, axis=-1).astype(jnp.int32)
                else:
                    raise ValueError(f"unknown msg perm kind {kind}")
                nwords = self._replace_key(nwords, fname, mapped)
            nwords = [jnp.where(occ, nw, w) for nw, w in zip(nwords, words)]
            sorted_all = lax.sort((*nwords, cnt), num_keys=len(nwords))
            for sl, arr in zip(self._msg_word_sls, sorted_all[:-1]):
                v = v.at[:, sl].set(arr)
            v = v.at[:, self._msg_cnt_sl].set(sorted_all[-1])
        return hash_lanes(v, seed=self.seed)

    def _fingerprints(self, states):
        """[B, W] int32 -> uint64 [B] canonical fingerprints."""
        view = states[:, : self.VL]
        fps = jax.vmap(
            lambda gi, vm, p2, sg: self._one_perm(view, gi, vm, p2, sg)
        )(self._gidx, self._valmap, self._pow2sig, self._sigma)
        return jnp.min(fps, axis=0)
