"""VIEW projection + SYMMETRY reduction + fingerprinting, layout-driven.

Reproduces TLC's distinct-state semantics for cfgs that declare
``VIEW view`` / ``SYMMETRY symmServers`` (e.g. ``standard-raft/Raft.cfg:28-29``):

  - VIEW: aux counters are excluded from the fingerprint
    (``Raft.tla:115`` — ``view`` omits ``acked/electionCtr/restartCtr``).
    By layout convention the view is the contiguous prefix
    ``vec[:layout.view_len]``.
  - SYMMETRY: two states related by a server permutation are the same
    distinct state (``Raft.tla:116``).

Fingerprint formula v4 (round 5): identical STRUCTURE to v3 below, but
all mixing arithmetic runs as two independent u32 streams combined into
one u64 at the end (u64 multiplies/compares are ~400x/180x slow on this
TPU backend — measured numbers in ops/hashing.py), and the bag multiset
combine is ADDITION mod 2^32 rather than XOR (nonlinear carries; round-4
advisor note). Every fingerprint changed vs v3 (hashv=4 in the
checkpoint identity).

Fingerprint formula v3 (round 4 — the perf round). Two changes vs the
round-1..3 formula (min of a positional hash over ALL S! permutations of
the slot-sorted view):

  1. **Sort-free bag hashing.** The message bag is hashed as a MULTISET:
     each occupied slot's record (key words + delivery count) is hashed
     position-independently and the per-slot hashes XOR-reduce. Slots
     hold DISTINCT keys by construction (bag canonicalization,
     ops/packing.py), so XOR cannot cancel duplicates; the collision
     budget stays 2^-64-class. This removes the M-lane ``lax.sort``
     that every permutation previously paid.

  2. **Signature-pruned permutation set.** A permutation-EQUIVARIANT
     per-server signature (1-WL style: per-server invariant content +
     one refinement round folding neighbor signatures through
     server-valued fields, matrices, bitmask members and message
     endpoints) orders the servers. The canonical fingerprint is the
     min of the permuted view's hash over the *admissible* permutations
     only — those that sort the signature sequence. Equivariance makes
     the admissible set correspond across orbit representatives, so the
     result is exactly as canonical as the full-S! min (property-tested
     bit-identical against the brute-force mask in tests/test_symmetry_v3.py).
     States whose signatures are totally ordered (the common case deep
     in a run) need ONE permutation — the argsort — instead of S!.

  Per chunk the kernel computes the fast single-permutation fingerprint
  for every lane (tier 1), resolves tie groups of size <= 2 with the
  static disjoint-adjacent-swap tables (tier 2), compacts the rare
  lanes holding a tie group >= 3 (budget = B//8) through the static
  S!-table masked min (tier 3), and falls back to the masked min on
  ALL lanes via ``lax.cond`` when a batch is heavy-tie-dense (early
  BFS waves, where frontiers are tiny anyway).

A permutation sigma acts on the packed view as: row gathers for
server-indexed axes, value remaps for server-valued fields and bitmasks,
and field remaps inside packed message keys (no slot re-sort — multiset
hash). Message keys may be 2-word (BitPacker: msg_hi/msg_lo/msg_cnt
kinds) or N-word (WidePacker: msg_word kinds, declared in word order).
A model declares which packed fields transform under sigma either via
``msg_server_fields`` / ``msg_server_nil_fields`` (plain / nil-valued
server ids) or a full ``msg_perm_spec`` of (field, kind) pairs with kind
in {"server", "server_nil", "server_bitmask"} — the bitmask kind covers
member sets inside reconfig-spec messages
(``RaftWithReconfigAddRemove.tla:874``).
"""

from __future__ import annotations

import itertools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .hashing import (
    KA,
    KB,
    U64_MAX,
    _reduce_pair,
    combine_pair,
    eq_u64,
    ge_u64,
    hash_lanes_pair,
    mix32,
)
from .packing import EMPTY, BitPacker, WidePacker
from ..models.base import Layout

_C1 = np.uint64(0x9E3779B97F4A7C15)
_C2 = np.uint64(0xC2B2AE3D27D4EB4F)
_MASK64 = (1 << 64) - 1


def _host_mix64(z: int) -> int:
    """splitmix64 finalizer on python ints (for setup-time salts)."""
    z &= _MASK64
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & _MASK64
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & _MASK64
    return z ^ (z >> 31)


def _salt(field_offset: int, role: int) -> tuple[np.uint32, np.uint32]:
    """Deterministic per-(field, role) u32 salt pair for signature folds.
    Depends only on the field's layout offset and the fold role — never
    on a server index (equivariance)."""
    z = _host_mix64(field_offset * 0x100 + role + 0x5A17)
    return np.uint32(z >> 32), np.uint32(z & 0xFFFFFFFF)


# ---- u32 stream-pair helpers (v4: all device hashing avoids u64 muls) ----


def _pmix(x, salt):
    """int array -> (u32, u32) mixed stream pair under a salt pair."""
    sa, sb = salt
    xx = x.astype(jnp.uint32)
    return mix32(xx * KA + sa), mix32(xx * KB + sb)


def _pfold(pair, salt):
    """Re-avalanche an existing stream pair under a salt pair."""
    sa, sb = salt
    a, b = pair
    return mix32(a + sa), mix32(b + sb)


def _padd(p, q):
    return p[0] + q[0], p[1] + q[1]


def _pwhere(cond, p, zero=np.uint32(0)):
    return jnp.where(cond, p[0], zero), jnp.where(cond, p[1], zero)


def _psum_last(p):
    """Sum a stream pair over the LAST axis with one reduce op (two
    reduces over a shared producer hit the fusion cliff — hashing.py)."""
    return _reduce_pair(p[0], p[1], op="sum")


def _pgather(p, idx):
    return (
        jnp.take_along_axis(p[0], idx, axis=1),
        jnp.take_along_axis(p[1], idx, axis=1),
    )


def _adj_swap_products(S: int):
    """All non-identity products of pairwise-DISJOINT adjacent
    transpositions of 0..S-1 (the independent edge subsets of the path
    graph): [T, S] perms + [T, S-1] bool masks of the edges each uses."""
    combos = []
    edges = range(S - 1)
    for r in range(1, S):
        for combo in itertools.combinations(edges, r):
            if all(b - a > 1 for a, b in zip(combo, combo[1:])):
                combos.append(combo)
    perms, masks = [], []
    for combo in combos:
        p = list(range(S))
        for k in combo:
            p[k], p[k + 1] = p[k + 1], p[k]
        perms.append(p)
        masks.append([k in combo for k in range(S - 1)])
    return np.array(perms, np.int32), np.array(masks, bool)


class Canonicalizer:
    @classmethod
    def for_model(cls, model, symmetry: bool = True, seed: int = 0,
                  mode: str = "auto") -> "Canonicalizer":
        """Build from a model's declared message-field symmetry contract
        (keeps the model -> canonicalization plumbing in one place).

        A model with data-dependent canonicalization (e.g. the
        KRaftWithReconfig slot encoding, where a host permutation re-sorts
        the identity slots) supplies its own via ``make_canonicalizer``;
        the returned object provides the same ``fingerprints`` /
        ``_fingerprints`` / ``symmetry`` surface the checkers use."""
        from .. import enable_compcache

        enable_compcache()  # covers custom make_canonicalizer models too
        if hasattr(model, "make_canonicalizer"):
            return model.make_canonicalizer(symmetry, seed=seed)
        return cls(
            model.layout,
            model.packer,
            msg_server_fields=getattr(
                model, "msg_server_fields", ("msource", "mdest")
            ),
            msg_server_nil_fields=getattr(model, "msg_server_nil_fields", ()),
            msg_perm_spec=getattr(model, "msg_perm_spec", None),
            symmetry=symmetry,
            seed=seed,
            mode=mode,
        )

    def __init__(
        self,
        layout: Layout,
        packer,
        msg_server_fields: tuple[str, ...] = ("msource", "mdest"),
        msg_server_nil_fields: tuple[str, ...] = (),
        msg_perm_spec: tuple[tuple[str, str], ...] | None = None,
        symmetry: bool = True,
        seed: int = 0,
        mode: str = "auto",
    ):
        from .. import enable_compcache

        enable_compcache()  # direct constructions (tests, tools)
        S = layout.n_servers
        VL = layout.view_len
        assert VL is not None
        assert mode in ("auto", "full")
        self.layout = layout
        self.packer = packer
        self.symmetry = symmetry
        self.mode = mode
        # fingerprint hash seed: a second independent hash family for the
        # collision audit (checker/audit.py)
        self.seed = seed
        # Unified remap spec: (packed field, kind) with kind one of
        #   server          plain server index (msource/mdest)
        #   server_nil      0 = Nil, i+1 = server i (KRaft mleader)
        #   server_bitmask  member set as a bitmask over servers
        if msg_perm_spec is None:
            msg_perm_spec = tuple(
                (f, "server") for f in msg_server_fields
            ) + tuple((f, "server_nil") for f in msg_server_nil_fields)
        self.msg_perm_spec = msg_perm_spec

        if symmetry:
            perms = np.array(list(itertools.permutations(range(S))), dtype=np.int32)
        else:
            perms = np.arange(S, dtype=np.int32)[None, :]
        P = perms.shape[0]

        val_lanes: list[int] = []
        bm_lanes: list[int] = []
        # key-word slices, ordered by sort significance: (hi, lo) for the
        # 2-word BitPacker bags (collected by kind, so layout declaration
        # order cannot silently flip them), msg_word declaration order for
        # the N-word WidePacker bags (word 0 = sort-major by contract)
        hi_sl: slice | None = None
        lo_sl: slice | None = None
        wide_sls: list[slice] = []
        msg_cnt_sl: slice | None = None
        view_fields = []  # (kind, offset, shape, size), offset order
        for f in layout.fields.values():
            if f.offset >= VL:
                continue  # aux: not fingerprinted
            view_fields.append((f.kind, f.offset, f.shape, f.size))
            if f.kind in ("per_server", "per_server_val", "server_bitmask"):
                lanes = list(range(f.offset, f.offset + f.size))
                if f.kind == "per_server_val":
                    val_lanes += lanes
                elif f.kind == "server_bitmask":
                    bm_lanes += lanes
            elif f.kind == "msg_hi":
                hi_sl = layout.sl(f.name)
            elif f.kind == "msg_lo":
                lo_sl = layout.sl(f.name)
            elif f.kind == "msg_word":
                wide_sls.append(layout.sl(f.name))
            elif f.kind == "msg_cnt":
                msg_cnt_sl = layout.sl(f.name)
        if hi_sl is not None or lo_sl is not None:
            assert hi_sl is not None and lo_sl is not None and not wide_sls
            msg_word_sls = [hi_sl, lo_sl]
        else:
            msg_word_sls = wide_sls
        if msg_word_sls:
            n_expected = 2 if hi_sl is not None else getattr(packer, "n_words", None)
            assert n_expected is None or len(msg_word_sls) == n_expected

        self.S, self.P, self.VL = S, P, VL
        # signature pruning pays only past ~24 permutations (see
        # _fingerprints); the choice is per-layout so fingerprints stay
        # consistent across every checker path for a given model
        self.prune = symmetry and S >= 5
        self._val_lanes = np.array(sorted(val_lanes), dtype=np.int32)
        self._bm_lanes = np.array(sorted(bm_lanes), dtype=np.int32)
        self._msg_word_sls = msg_word_sls
        self._msg_cnt_sl = msg_cnt_sl
        self._view_fields = sorted(view_fields, key=lambda t: t[1])
        assert sum(t[3] for t in self._view_fields) == VL, "view lane gap"
        # static per-permutation tables for the masked-min path (the
        # tier-2 tables below come from the same builder, so the
        # permutation action lives in exactly one place)
        (self._gidx, self._sigma,
         self._valmap, self._pow2sig) = self._build_tables(perms)
        self._inv_sigma = jnp.asarray(np.argsort(perms, axis=1).astype(np.int32))
        # non-bag view lanes for the positional half of the hash
        bag_lanes: set[int] = set()
        for sl in msg_word_sls:
            bag_lanes |= set(range(sl.start, sl.stop))
        if msg_cnt_sl is not None:
            bag_lanes |= set(range(msg_cnt_sl.start, msg_cnt_sl.stop))
        self._nonbag_lanes = np.array(
            [i for i in range(VL) if i not in bag_lanes], dtype=np.int32
        )
        if self.prune:
            # tier-2 static tables: all non-identity products of DISJOINT
            # adjacent transpositions (7 at S=5; the identity is tier 1's
            # argsort). Applied to the signature-
            # SORTED view these are exactly the block permutations of any
            # tie pattern whose groups have size <= 2 — measured to be
            # >98% of tied states past depth ~9 on the 5-server workload
            # (the rest fall to the masked full-S! path).
            tperms, tmask = _adj_swap_products(S)
            tg, tsg, tvm, tp2 = self._build_tables(tperms)
            self._t_gidx, self._t_sigma = tg, tsg
            self._t_valmap, self._t_pow2 = tvm, tp2
            self._t_edge_mask = jnp.asarray(tmask)  # [T, S-1]
        self.fingerprints = jax.jit(self._fingerprints)

    def _build_tables(self, perms: np.ndarray):
        """Static per-permutation tables (lane gather, sigma, value remap,
        bitmask remap) for an arbitrary [T, S] permutation set."""
        S, VL = self.S, self.VL
        T = perms.shape[0]
        inv = np.argsort(perms, axis=1).astype(np.int32)
        gidx = np.tile(np.arange(VL, dtype=np.int32), (T, 1))
        for kind, off, shape, size in self._view_fields:
            if kind in ("per_server", "per_server_val", "server_bitmask"):
                rest = size // S
                base = off + inv[:, :, None] * rest + np.arange(rest)
                gidx[:, off : off + size] = base.reshape(T, -1)
            elif kind == "per_server_pair":
                src = off + inv[:, :, None] * S + inv[:, None, :]
                gidx[:, off : off + size] = src.reshape(T, -1)
        valmap = np.zeros((T, S + 1), dtype=np.int32)
        valmap[:, 1:] = perms + 1
        pow2 = (1 << perms).astype(np.int32)
        return (jnp.asarray(gidx), jnp.asarray(perms),
                jnp.asarray(valmap), jnp.asarray(pow2))

    # packer adapters: BitPacker works on (hi, lo), WidePacker on tuples
    def _unpack_key(self, words, name):
        if isinstance(self.packer, WidePacker):
            return self.packer.unpack(words, name)
        return self.packer.unpack(words[0], words[1], name)

    def _replace_key(self, words, name, value):
        if isinstance(self.packer, WidePacker):
            return list(self.packer.replace(words, name, value))
        hi, lo = self.packer.replace(words[0], words[1], name, value)
        return [hi, lo]

    # ---------------- the v3 hash ----------------

    def _bag_hash_pair(self, v):
        """Multiset hash of the message bag region of [B, VL] views as a
        (u32, u32) stream pair: occupied slots' position-independent
        record hashes combine by ADDITION mod 2^32 (nonlinear carries —
        a slightly better multiset structure than the round-4 XOR, which
        was linear over GF(2); slots hold distinct keys by construction
        either way, so neither combine can cancel duplicates)."""
        if not self._msg_word_sls:
            z = jnp.zeros(v.shape[:-1], jnp.uint32)
            return z, z
        words = [v[..., sl] for sl in self._msg_word_sls]  # each [B, M]
        cnt = v[..., self._msg_cnt_sl]
        occ = words[0] != EMPTY
        ha = jnp.zeros_like(words[0], dtype=jnp.uint32)
        hb = jnp.zeros_like(words[0], dtype=jnp.uint32)
        for w_i, w in enumerate([*words, cnt]):
            x = w.astype(jnp.uint32)
            if self.seed:
                sw = _host_mix64(w_i * int(_C2) + self.seed)
                x = x ^ np.uint32(sw & 0xFFFFFFFF)
            wa, wb = _salt(w_i, 20)
            ha = ha ^ mix32(x * KA + wa)
            hb = hb ^ mix32(x * KB + wb)
        # per-slot finalize, then a single stacked multiset-sum reduce
        ha = mix32(ha + KB)
        hb = mix32(hb + KA)
        return _psum_last(_pwhere(occ, (ha, hb)))

    def _perm_hash(self, v):
        """u64 hash of a permuted [B, VL] view: positional over the
        non-bag lanes XOR the slot-order-free bag multiset hash (all
        mixing in u32 stream pairs; one u64 combine at the end)."""
        na, nb = hash_lanes_pair(v[..., self._nonbag_lanes], seed=self.seed)
        ba, bb = self._bag_hash_pair(v)
        return combine_pair(na ^ ba, nb ^ bb)

    # ---------------- equivariant per-server signatures ----------------

    def _signatures(self, view):
        """[B, VL] -> u64 [B, S] permutation-EQUIVARIANT signatures:
        sig(perm(x))[sigma(i)] == sig(x)[i]. Built from per-server
        invariant content plus one 1-WL refinement round; every fold is
        either self-relative or an unordered multiset sum, and no fold
        reads a raw server index. All mixing runs as u32 stream pairs
        (v4 — u64 multiplies are ~400x slow on this TPU, hashing.py);
        the streams combine into one orderable u64 at the very end."""
        S, B = self.S, view.shape[0]
        srange = jnp.arange(S, dtype=jnp.int32)
        acc = (jnp.zeros((B, S), jnp.uint32), jnp.zeros((B, S), jnp.uint32))

        # ---- round 0: invariant content ----
        val_fields = []  # (offset, vals [B,S]) for refinement
        bm_fields = []  # (offset, masks [B,S])
        pair_fields = []  # (offset, mat [B,S,S])
        for kind, off, shape, size in self._view_fields:
            seg = view[:, off : off + size]
            if kind == "per_server":
                rest = size // S
                rows = seg.reshape(B, S, rest)
                acc = _padd(acc, _pfold(hash_lanes_pair(rows), _salt(off, 0)))
            elif kind == "per_server_val":
                vals = seg  # [B, S], 0 = Nil, i+1 = server i
                cat = jnp.where(
                    vals == 0, 0, jnp.where(vals - 1 == srange, 1, 2)
                )
                acc = _padd(acc, _pmix(cat, _salt(off, 1)))
                indeg = jnp.sum(
                    (vals[:, :, None] - 1 == srange[None, None, :])
                    & (vals[:, :, None] > 0),
                    axis=1,
                )
                acc = _padd(acc, _pmix(indeg, _salt(off, 2)))
                val_fields.append((off, vals))
            elif kind == "server_bitmask":
                masks = seg  # [B, S]
                bits = (masks[:, :, None] >> srange[None, None, :]) & 1  # [B,S,S]
                selfbit = (masks >> srange) & 1
                pop = jnp.sum(bits, axis=2)
                acc = _padd(acc, _pmix(pop * 2 + selfbit, _salt(off, 3)))
                acc = _padd(acc, _pmix(jnp.sum(bits, axis=1), _salt(off, 4)))
                bm_fields.append((off, masks))
            elif kind == "per_server_pair":
                mat = seg.reshape(B, S, S)
                diag = mat[:, srange, srange]
                acc = _padd(acc, _pmix(diag, _salt(off, 5)))
                offd = srange[:, None] != srange[None, :]
                e_row = _pwhere(offd, _pmix(mat, _salt(off, 6)))
                acc = _padd(acc, _psum_last(e_row))
                # column fold: transpose so the multiset sum is over the
                # LAST axis (single stacked reduce, hashing.py cliff note)
                e_col = _pwhere(
                    offd, _pmix(mat.transpose(0, 2, 1), _salt(off, 7))
                )
                acc = _padd(acc, _psum_last(e_col))
                pair_fields.append((off, mat))
            # scalar / msg_* handled below; aux excluded by view

        # messages, round 0: fold each record (server fields masked out)
        # into the servers it references
        msg = None
        if self._msg_word_sls:
            words = [view[:, sl] for sl in self._msg_word_sls]  # [B, M]
            cnt = view[:, self._msg_cnt_sl]
            occ = words[0] != EMPTY
            zwords = list(words)
            for fname, _kind in self.msg_perm_spec:
                zwords = self._replace_key(
                    zwords, fname, jnp.zeros_like(zwords[0])
                )
            r0a = jnp.zeros_like(words[0], dtype=jnp.uint32)
            r0b = jnp.zeros_like(words[0], dtype=jnp.uint32)
            for w_i, w in enumerate([*zwords, cnt]):
                x = w.astype(jnp.uint32)
                wa, wb = _salt(w_i, 21)
                r0a = r0a ^ mix32(x * KA + wa)
                r0b = r0b ^ mix32(x * KB + wb)
            rec0 = (mix32(r0a), mix32(r0b))
            cnt32 = jnp.where(occ, cnt, 0).astype(jnp.uint32)
            msg = (words, cnt32, occ, rec0)
            for k, (fname, kind) in enumerate(self.msg_perm_spec):
                val = self._unpack_key(words, fname)  # [B, M]
                ck = _pfold(rec0, _salt(k, 8))
                c = (cnt32 * ck[0], cnt32 * ck[1])  # [B, M]
                acc = _padd(acc, self._scatter_by_server(c, val, kind, occ))

        sig0 = (mix32(acc[0]), mix32(acc[1]))

        # ---- refinement: fold neighbor signatures ----
        acc1 = (jnp.zeros((B, S), jnp.uint32), jnp.zeros((B, S), jnp.uint32))
        for off, vals in val_fields:
            tgt = jnp.clip(vals - 1, 0, S - 1)
            nsig = _pgather(sig0, tgt)
            valid = (vals > 0) & (vals - 1 != srange)
            sa, sb = _salt(off, 9)
            acc1 = _padd(
                acc1,
                _pwhere(valid, (mix32(nsig[0] ^ sa), mix32(nsig[1] ^ sb))),
            )
        for off, masks in bm_fields:
            bits = ((masks[:, :, None] >> srange[None, None, :]) & 1) == 1
            sa, sb = _salt(off, 10)
            e = (mix32(sig0[0] ^ sa), mix32(sig0[1] ^ sb))  # [B, S]
            contrib = _pwhere(
                bits,
                (
                    jnp.broadcast_to(e[0][:, None, :], bits.shape),
                    jnp.broadcast_to(e[1][:, None, :], bits.shape),
                ),
            )
            acc1 = _padd(acc1, _psum_last(contrib))
        for off, mat in pair_fields:
            sa, sb = _salt(off, 11)
            m32 = mat.astype(jnp.uint32)
            era = mix32(m32 * KA + (sig0[0] ^ sa)[:, None, :])
            erb = mix32(m32 * KB + (sig0[1] ^ sb)[:, None, :])
            acc1 = _padd(acc1, _psum_last((era, erb)))
            sa2, sb2 = _salt(off, 12)
            mt32 = mat.transpose(0, 2, 1).astype(jnp.uint32)
            eca = mix32(mt32 * KA + (sig0[0] ^ sa2)[:, None, :])
            ecb = mix32(mt32 * KB + (sig0[1] ^ sb2)[:, None, :])
            acc1 = _padd(acc1, _psum_last((eca, ecb)))
        if msg is not None:
            words, cnt32, occ, rec0 = msg
            # per-slot fold of every referenced server's sig0, then
            # re-scatter: binds a record's endpoints together
            svals = []
            osum = (jnp.zeros_like(rec0[0]), jnp.zeros_like(rec0[1]))
            for k, (fname, kind) in enumerate(self.msg_perm_spec):
                val = self._unpack_key(words, fname)
                svals.append(val)
                osum = _padd(
                    osum, self._gather_sig_fold(sig0, val, kind, _salt(k, 13))
                )
            for k, (fname, kind) in enumerate(self.msg_perm_spec):
                # exclude the target's own contribution so its fold is
                # over the OTHER endpoints
                own = self._gather_sig_fold(sig0, svals[k], kind, _salt(k, 13))
                sa, sb = _salt(k, 14)
                c = (
                    cnt32 * mix32(rec0[0] + (osum[0] - own[0]) + sa),
                    cnt32 * mix32(rec0[1] + (osum[1] - own[1]) + sb),
                )
                acc1 = _padd(acc1, self._scatter_by_server(c, svals[k], kind, occ))

        fa = mix32(sig0[0] + mix32(acc1[0]))
        fb = mix32(sig0[1] + mix32(acc1[1]))
        return combine_pair(fa, fb)

    def _scatter_by_server(self, contrib, val, kind, occ):
        """Sum [B, M] stream-pair contributions onto the servers
        referenced by a message field ([B, M] values, interpretation per
        kind) -> [B, S] pair. Laid out [B, S, M] so the multiset sum is a
        single stacked last-axis reduce."""
        S = self.S
        srange = jnp.arange(S, dtype=jnp.int32)
        ca = jnp.where(occ, contrib[0], 0)
        cb = jnp.where(occ, contrib[1], 0)
        vt = val[:, None, :]  # [B, 1, M]
        if kind == "server":
            onehot = vt == srange[None, :, None]
        elif kind == "server_nil":
            onehot = (vt - 1 == srange[None, :, None]) & (vt > 0)
        elif kind == "server_bitmask":
            onehot = ((vt >> srange[None, :, None]) & 1) == 1
        else:
            raise ValueError(f"unknown msg perm kind {kind}")
        pa = jnp.where(onehot, ca[:, None, :], 0)
        pb = jnp.where(onehot, cb[:, None, :], 0)
        return _psum_last((pa, pb))

    def _gather_sig_fold(self, sig0, val, kind, salt):
        """Fold the sig0 of servers referenced by a [B, M] message field
        into a per-slot stream pair (multiset sum; 0 when Nil/absent)."""
        S = self.S
        sa, sb = salt
        if kind == "server":
            nsig = _pgather(sig0, jnp.clip(val, 0, S - 1))
            return mix32(nsig[0] ^ sa), mix32(nsig[1] ^ sb)
        if kind == "server_nil":
            nsig = _pgather(sig0, jnp.clip(val - 1, 0, S - 1))
            return _pwhere(val > 0, (mix32(nsig[0] ^ sa), mix32(nsig[1] ^ sb)))
        if kind == "server_bitmask":
            srange = jnp.arange(S, dtype=jnp.int32)
            bits = ((val[:, :, None] >> srange[None, None, :]) & 1) == 1
            ea = mix32(sig0[0] ^ sa)  # [B, S]
            eb = mix32(sig0[1] ^ sb)
            pa = jnp.where(bits, jnp.broadcast_to(ea[:, None, :], bits.shape), 0)
            pb = jnp.where(bits, jnp.broadcast_to(eb[:, None, :], bits.shape), 0)
            return _psum_last((pa, pb))
        raise ValueError(f"unknown msg perm kind {kind}")

    # ---------------- applying a permutation ----------------

    def _dyn_gidx(self, inv):
        """Per-state lane gather indices from [B, S] inverse perms (new
        row k takes old row inv[k]) -> [B, VL]."""
        B = inv.shape[0]
        S = self.S
        segs = []
        for kind, off, shape, size in self._view_fields:
            if kind in ("per_server", "per_server_val", "server_bitmask"):
                rest = size // S
                idx = (
                    off
                    + inv[:, :, None] * rest
                    + jnp.arange(rest, dtype=jnp.int32)[None, None, :]
                )
                segs.append(idx.reshape(B, size))
            elif kind == "per_server_pair":
                idx = off + inv[:, :, None] * S + inv[:, None, :]
                segs.append(idx.reshape(B, size))
            else:
                ident = jnp.arange(off, off + size, dtype=jnp.int32)
                segs.append(jnp.broadcast_to(ident[None, :], (B, size)))
        return jnp.concatenate(segs, axis=1)

    def _apply_sigma_values(self, v, sigma):
        """Remap server-VALUED content of row-gathered [B, VL] views under
        per-state sigma [B, S] (old server i -> new index sigma[i])."""
        S = self.S
        if self._val_lanes.size:
            vl = v[:, self._val_lanes]
            idx = jnp.clip(vl - 1, 0, S - 1)
            mapped = jnp.take_along_axis(sigma, idx, axis=1) + 1
            v = v.at[:, self._val_lanes].set(jnp.where(vl > 0, mapped, 0))
        if self._bm_lanes.size:
            x = v[:, self._bm_lanes]
            out = jnp.zeros_like(x)
            for j in range(S):
                out = out | (((x >> j) & 1) << sigma[:, j : j + 1])
            v = v.at[:, self._bm_lanes].set(out)
        if self._msg_word_sls:
            words = [v[:, sl] for sl in self._msg_word_sls]
            occ = words[0] != EMPTY
            nwords = list(words)
            for fname, kind in self.msg_perm_spec:
                val = self._unpack_key(nwords, fname)
                if kind == "server":
                    mapped = jnp.take_along_axis(
                        sigma, jnp.clip(val, 0, S - 1), axis=1
                    )
                elif kind == "server_nil":
                    m2 = (
                        jnp.take_along_axis(
                            sigma, jnp.clip(val - 1, 0, S - 1), axis=1
                        )
                        + 1
                    )
                    mapped = jnp.where(val > 0, m2, 0)
                elif kind == "server_bitmask":
                    out = jnp.zeros_like(val)
                    for j in range(S):
                        out = out | (((val >> j) & 1) << sigma[:, j : j + 1])
                    mapped = out
                else:
                    raise ValueError(f"unknown msg perm kind {kind}")
                nwords = self._replace_key(nwords, fname, mapped)
            nwords = [jnp.where(occ, nw, w) for nw, w in zip(nwords, words)]
            for sl, arr in zip(self._msg_word_sls, nwords):
                v = v.at[:, sl].set(arr)
        return v

    # ---------------- the static masked-min (tie / full path) ----------------

    def _one_perm(self, view, sig, gi, valmap, pow2, sigma, inv_p):
        """Apply one STATIC permutation to [B, VL] views; hash; mask to
        U64_MAX unless the permutation sorts the signature sequence."""
        S = self.S
        v = view[:, gi]
        if self._val_lanes.size:
            vl = v[:, self._val_lanes]
            v = v.at[:, self._val_lanes].set(valmap[vl])
        if self._bm_lanes.size:
            x = v[:, self._bm_lanes]
            bits = (x[..., None] >> jnp.arange(S, dtype=jnp.int32)) & 1
            v = v.at[:, self._bm_lanes].set(
                jnp.sum(bits * pow2, axis=-1).astype(jnp.int32)
            )
        if self._msg_word_sls:
            words = [v[:, sl] for sl in self._msg_word_sls]
            occ = words[0] != EMPTY
            nwords = list(words)
            for fname, kind in self.msg_perm_spec:
                val = self._unpack_key(nwords, fname)
                if kind == "server":
                    mapped = sigma[jnp.clip(val, 0, S - 1)]
                elif kind == "server_nil":
                    mapped = jnp.where(
                        val > 0, sigma[jnp.clip(val - 1, 0, S - 1)] + 1, 0
                    )
                elif kind == "server_bitmask":
                    bits = (val[..., None] >> jnp.arange(S, dtype=jnp.int32)) & 1
                    mapped = jnp.sum(bits * pow2, axis=-1).astype(jnp.int32)
                else:
                    raise ValueError(f"unknown msg perm kind {kind}")
                nwords = self._replace_key(nwords, fname, mapped)
            nwords = [jnp.where(occ, nw, w) for nw, w in zip(nwords, words)]
            for sl, arr in zip(self._msg_word_sls, nwords):
                v = v.at[:, sl].set(arr)
        h = self._perm_hash(v)
        if sig is None:  # unpruned: every permutation admissible
            return h
        ssig = sig[:, inv_p]
        adm = jnp.all(ge_u64(ssig[:, 1:], ssig[:, :-1]), axis=1)
        return jnp.where(adm, h, U64_MAX)

    def _masked_min(self, view, sig):
        """min over the admissible static permutations (brute force over
        the S! table; sig=None means no mask — the plain full-S! min).

        The table is processed in scanned blocks with a running min: a
        flat vmap materializes a [P, B, VL] gather temp, which at P=120
        and chunk-sized B overflows HBM (observed on the 5-server
        workload); blocking caps the temp at PBLK*B*VL."""
        B = view.shape[0]
        per_perm = max(1, B * self.VL * 4)
        # 512MB of gather temp per block: small perm sets (S<=4, P<=24)
        # stay a single flat vmap; P=120 splits into ~10-perm blocks
        PBLK = max(1, min(self.P, (512 << 20) // per_perm))
        nblk = (self.P + PBLK - 1) // PBLK
        pad = nblk * PBLK - self.P

        def padt(t):
            if not pad:
                return t
            # duplicate perm 0: duplicates cannot change a min
            return jnp.concatenate([t, jnp.repeat(t[:1], pad, axis=0)])

        tables = tuple(
            padt(t).reshape((nblk, PBLK) + t.shape[1:])
            for t in (self._gidx, self._valmap, self._pow2sig, self._sigma,
                      self._inv_sigma)
        )

        def block(best, tb):
            gi, vm, p2, sg, ip = tb
            h = jax.vmap(
                lambda g, v, p, s, i_: self._one_perm(view, sig, g, v, p, s, i_)
            )(gi, vm, p2, sg, ip)
            return jnp.minimum(best, jnp.min(h, axis=0)), None

        # derive the init from `view` so it carries the same varying-
        # manual-axes type as the body output under shard_map (a plain
        # jnp.full is unvarying and the scan carry types would mismatch)
        init = (view[:, 0].astype(jnp.uint64) & jnp.uint64(0)) | U64_MAX
        best, _ = lax.scan(block, init, tables)
        return best

    # ---------------- entry point ----------------

    def _fingerprints(self, states):
        """[B, W] int32 -> uint64 [B] canonical fingerprints.

        Formula per layout (fixed at construction, so every checker path
        agrees): S <= 4 -> plain min over all S! permutations (the
        signature machinery costs more than it saves at 6-24 perms,
        measured on the TPU); S >= 5 -> signature-pruned masked min
        (at 120+ perms the brute force is ~9x the whole chunk budget)."""
        view = states[:, : self.VL]
        B = view.shape[0]
        if not self.symmetry:
            return self._perm_hash(view)
        if not self.prune:
            return self._masked_min(view, None)
        sig = self._signatures(view)
        if self.mode == "full":
            return self._masked_min(view, sig)

        # ---- tier 1: one dynamic permutation (the signature argsort) ----
        order = jnp.argsort(sig, axis=1).astype(jnp.int32)  # = inv
        ssig = jnp.take_along_axis(sig, order, axis=1)
        adj_eq = eq_u64(ssig[:, 1:], ssig[:, :-1])  # [B, S-1]
        sigma = jnp.argsort(order, axis=1).astype(jnp.int32)
        v0 = jnp.take_along_axis(view, self._dyn_gidx(order), axis=1)
        v0 = self._apply_sigma_values(v0, sigma)
        fp = self._perm_hash(v0)

        # ---- tier 2: disjoint adjacent-swap products on the SORTED view.
        # t composed with the argsort is admissible iff every swapped pair
        # is signature-tied; for states whose tie groups are all <= 2
        # these are ALL the admissible permutations, so min(tier1, tier2)
        # is exactly the masked full-S! min for them.
        t_fps = jax.vmap(
            lambda gi, vm, p2, sg: self._one_perm(v0, None, gi, vm, p2, sg, None)
        )(self._t_gidx, self._t_valmap, self._t_pow2, self._t_sigma)  # [T, B]
        t_valid = jnp.all(
            adj_eq[None, :, :] | ~self._t_edge_mask[:, None, :], axis=2
        )  # [T, B]
        fp = jnp.minimum(
            fp, jnp.min(jnp.where(t_valid, t_fps, U64_MAX), axis=0)
        )

        # ---- tier 3: states with a tie group >= 3 (a run of 2+ adjacent
        # equalities) need the masked full-table min; they are rare past
        # the first waves (~1.5% at depth 10 on the 5-server workload),
        # so compact them into a small buffer. A tie-heavy batch (early
        # BFS, tiny frontiers) falls back to the full path wholesale.
        heavy = jnp.any(adj_eq[:, :-1] & adj_eq[:, 1:], axis=1)
        # B//8: the AVERAGE heavy rate past depth ~9 on the 5-server
        # workload is ~1.5%, but heavy states cluster within chunks
        # (frontier slots follow discovery order), so a tighter B//16
        # budget pushed many real chunks into the full-table fallback —
        # measured 2.7x slower canon at depth 9/10 than B//8
        TCH = max(64, B // 8)
        n_heavy = jnp.sum(heavy)

        def compact_heavy(_):
            hpos = (jnp.cumsum(heavy) - 1).astype(jnp.int32)
            hdst = jnp.where(heavy, jnp.minimum(hpos, TCH), TCH)
            hsel = (
                jnp.full((TCH + 1,), B, jnp.int32)
                .at[hdst]
                .set(jnp.arange(B, dtype=jnp.int32))[:TCH]
            )
            hselv = hsel < B
            viewp = jnp.concatenate(
                [view, jnp.zeros((1, self.VL), view.dtype)], axis=0
            )
            sigp = jnp.concatenate(
                [sig, jnp.zeros((1, self.S), sig.dtype)], axis=0
            )
            heavy_fps = self._masked_min(viewp[hsel], sigp[hsel])  # [TCH]
            fpp = jnp.concatenate([fp, jnp.zeros((1,), jnp.uint64)])
            dst = jnp.where(hselv, hsel, B)
            return fpp.at[dst].set(jnp.where(hselv, heavy_fps, 0))[:B]

        def full_all(_):
            return self._masked_min(view, sig)

        return lax.cond(n_heavy > TCH, full_all, compact_heavy, None)
