"""VIEW projection + SYMMETRY reduction + fingerprinting, layout-driven.

Reproduces TLC's distinct-state semantics for cfgs that declare
``VIEW view`` / ``SYMMETRY symmServers`` (e.g. ``standard-raft/Raft.cfg:28-29``):

  - VIEW: aux counters are excluded from the fingerprint
    (``Raft.tla:115`` — ``view`` omits ``acked/electionCtr/restartCtr``).
    By layout convention the view is the contiguous prefix
    ``vec[:layout.view_len]``.
  - SYMMETRY: two states related by a server permutation are the same
    distinct state (``Raft.tla:116``). We canonicalize by taking the MIN
    over all S! permutations of the permuted view's 64-bit hash — a
    permutation-invariant fingerprint with TLC's collision budget.

A permutation sigma acts on the packed view as (see models/base.py kinds):
row gathers for server-indexed axes, value remaps for server-valued fields
and bitmasks, msource/mdest remap inside packed message keys followed by a
bag re-sort. The row gathers compose into ONE precomputed lane-gather per
permutation, so the device work per permutation is a gather + two tiny
fixups + an M-lane sort + hash.
"""

from __future__ import annotations

import itertools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .hashing import hash_lanes
from .packing import EMPTY, BitPacker
from ..models.base import Layout


class Canonicalizer:
    @classmethod
    def for_model(cls, model, symmetry: bool = True) -> "Canonicalizer":
        """Build from a model's declared message-field symmetry contract
        (keeps the model -> canonicalization plumbing in one place)."""
        return cls(
            model.layout,
            model.packer,
            msg_server_fields=getattr(
                model, "msg_server_fields", ("msource", "mdest")
            ),
            msg_server_nil_fields=getattr(model, "msg_server_nil_fields", ()),
            symmetry=symmetry,
        )

    def __init__(
        self,
        layout: Layout,
        packer: BitPacker,
        msg_server_fields: tuple[str, ...] = ("msource", "mdest"),
        msg_server_nil_fields: tuple[str, ...] = (),
        symmetry: bool = True,
    ):
        S = layout.n_servers
        VL = layout.view_len
        assert VL is not None
        self.layout = layout
        self.packer = packer
        self.msg_server_fields = msg_server_fields
        # Nil-valued server fields inside packed records (0 = Nil, i+1 = i),
        # e.g. KRaft's mleader (KRaft.tla:500,644): 0 stays, v -> sigma(v-1)+1.
        self.msg_server_nil_fields = msg_server_nil_fields

        if symmetry:
            perms = np.array(list(itertools.permutations(range(S))), dtype=np.int32)
        else:
            perms = np.arange(S, dtype=np.int32)[None, :]
        P = perms.shape[0]
        inv = np.argsort(perms, axis=1).astype(np.int32)

        # Per-permutation lane gather over the view prefix.
        gidx = np.tile(np.arange(VL, dtype=np.int32), (P, 1))
        val_lanes: list[int] = []
        bm_lanes: list[int] = []
        msg_sl: dict[str, slice] = {}
        for f in layout.fields.values():
            if f.offset >= VL:
                continue  # aux: not fingerprinted
            if f.kind in ("per_server", "per_server_val", "server_bitmask"):
                rest = int(math.prod(f.shape[1:])) if len(f.shape) > 1 else 1
                base = f.offset + inv[:, :, None] * rest + np.arange(rest)  # [P,S,rest]
                gidx[:, f.offset : f.offset + f.size] = base.reshape(P, -1)
                lanes = list(range(f.offset, f.offset + f.size))
                if f.kind == "per_server_val":
                    val_lanes += lanes
                elif f.kind == "server_bitmask":
                    bm_lanes += lanes
            elif f.kind == "per_server_pair":
                src = f.offset + inv[:, :, None] * S + inv[:, None, :]  # [P,S,S]
                gidx[:, f.offset : f.offset + f.size] = src.reshape(P, -1)
            elif f.kind in ("msg_hi", "msg_lo", "msg_cnt"):
                msg_sl[f.kind] = layout.sl(f.name)

        # value remap: 0 stays Nil, v in 1..S maps to sigma[v-1]+1
        valmap = np.zeros((P, S + 1), dtype=np.int32)
        valmap[:, 1:] = perms + 1
        pow2sig = (1 << perms).astype(np.int32)

        self.S, self.P, self.VL = S, P, VL
        self._gidx = jnp.asarray(gidx)
        self._sigma = jnp.asarray(perms)
        self._valmap = jnp.asarray(valmap)
        self._pow2sig = jnp.asarray(pow2sig)
        self._val_lanes = np.array(sorted(val_lanes), dtype=np.int32)
        self._bm_lanes = np.array(sorted(bm_lanes), dtype=np.int32)
        self._msg_sl = msg_sl
        self.fingerprints = jax.jit(self._fingerprints)

    def _one_perm(self, view, gi, valmap, pow2, sigma):
        """Apply one permutation to [B, VL] views and hash."""
        S = self.S
        v = view[:, gi]
        if self._val_lanes.size:
            vl = v[:, self._val_lanes]
            v = v.at[:, self._val_lanes].set(valmap[vl])
        if self._bm_lanes.size:
            x = v[:, self._bm_lanes]
            bits = (x[..., None] >> jnp.arange(S, dtype=jnp.int32)) & 1
            v = v.at[:, self._bm_lanes].set(jnp.sum(bits * pow2, axis=-1).astype(jnp.int32))
        if self._msg_sl:
            hi = v[:, self._msg_sl["msg_hi"]]
            lo = v[:, self._msg_sl["msg_lo"]]
            cnt = v[:, self._msg_sl["msg_cnt"]]
            occ = hi != EMPTY
            nhi, nlo = hi, lo
            for fname in self.msg_server_fields:
                val = self.packer.unpack(nhi, nlo, fname)
                nhi, nlo = self.packer.replace(nhi, nlo, fname, sigma[jnp.clip(val, 0, S - 1)])
            for fname in self.msg_server_nil_fields:
                val = self.packer.unpack(nhi, nlo, fname)
                mapped = jnp.where(val > 0, sigma[jnp.clip(val - 1, 0, S - 1)] + 1, 0)
                nhi, nlo = self.packer.replace(nhi, nlo, fname, mapped)
            nhi = jnp.where(occ, nhi, hi)
            nlo = jnp.where(occ, nlo, lo)
            nhi, nlo, cnt = lax.sort((nhi, nlo, cnt), num_keys=2)
            v = (
                v.at[:, self._msg_sl["msg_hi"]].set(nhi)
                .at[:, self._msg_sl["msg_lo"]].set(nlo)
                .at[:, self._msg_sl["msg_cnt"]].set(cnt)
            )
        return hash_lanes(v)

    def _fingerprints(self, states):
        """[B, W] int32 -> uint64 [B] canonical fingerprints."""
        view = states[:, : self.VL]
        fps = jax.vmap(
            lambda gi, vm, p2, sg: self._one_perm(view, gi, vm, p2, sg)
        )(self._gidx, self._valmap, self._pow2sig, self._sigma)
        return jnp.min(fps, axis=0)
