"""VIEW projection + SYMMETRY reduction + fingerprinting, layout-driven.

Reproduces TLC's distinct-state semantics for cfgs that declare
``VIEW view`` / ``SYMMETRY symmServers`` (e.g. ``standard-raft/Raft.cfg:28-29``):

  - VIEW: aux counters are excluded from the fingerprint
    (``Raft.tla:115`` — ``view`` omits ``acked/electionCtr/restartCtr``).
    By layout convention the view is the contiguous prefix
    ``vec[:layout.view_len]``.
  - SYMMETRY: two states related by a server permutation are the same
    distinct state (``Raft.tla:116``).

Fingerprint formula v5 (round 6): v4 below with the 1-WL signature
refinement iterated to a bounded depth (``refine_rounds``, default 3)
instead of exactly one round. Deeper refinement shrinks tie groups of
size >= 3 before any permutation is enumerated, but it also changes
WHICH permutations are admissible for still-tied states — so the masked
min lands on a different (equally canonical) orbit representative and
tied-state fingerprints changed vs v4 (hashv=5 in the checkpoint
identity, with the round count recorded alongside). Canonicalization
itself is restructured around three compounding optimisations, all
value-preserving given the signature: a direct-mapped canon memo table
keyed by the raw (identity-permutation) view hash
(``fingerprints_memo``), tie-group-LOCAL masked mins over per-pattern
static tables for lanes whose tie groups stay small, and an adaptive
blocked ``lax.while_loop`` budget replacing the old static ``B//8``
compaction + whole-batch ``lax.cond`` fallback.

Fingerprint formula v4 (round 5): identical STRUCTURE to v3 below, but
all mixing arithmetic runs as two independent u32 streams combined into
one u64 at the end (u64 multiplies/compares are ~400x/180x slow on this
TPU backend — measured numbers in ops/hashing.py), and the bag multiset
combine is ADDITION mod 2^32 rather than XOR (nonlinear carries; round-4
advisor note). Every fingerprint changed vs v3 (hashv=4 in the
checkpoint identity).

Fingerprint formula v3 (round 4 — the perf round). Two changes vs the
round-1..3 formula (min of a positional hash over ALL S! permutations of
the slot-sorted view):

  1. **Sort-free bag hashing.** The message bag is hashed as a MULTISET:
     each occupied slot's record (key words + delivery count) is hashed
     position-independently and the per-slot hashes XOR-reduce. Slots
     hold DISTINCT keys by construction (bag canonicalization,
     ops/packing.py), so XOR cannot cancel duplicates; the collision
     budget stays 2^-64-class. This removes the M-lane ``lax.sort``
     that every permutation previously paid.

  2. **Signature-pruned permutation set.** A permutation-EQUIVARIANT
     per-server signature (1-WL style: per-server invariant content +
     one refinement round folding neighbor signatures through
     server-valued fields, matrices, bitmask members and message
     endpoints) orders the servers. The canonical fingerprint is the
     min of the permuted view's hash over the *admissible* permutations
     only — those that sort the signature sequence. Equivariance makes
     the admissible set correspond across orbit representatives, so the
     result is exactly as canonical as the full-S! min (property-tested
     bit-identical against the brute-force mask in tests/test_symmetry_v3.py).
     States whose signatures are totally ordered (the common case deep
     in a run) need ONE permutation — the argsort — instead of S!.

  Per chunk the kernel computes the fast single-permutation fingerprint
  for every lane (tier 1), resolves tie groups of size <= 2 with the
  static disjoint-adjacent-swap tables (tier 2), and routes the rare
  lanes holding a tie group >= 3 through tier 3: lanes whose tie
  PATTERN has a small admissible block-permutation group (<= the
  largest non-full pattern, e.g. 24 perms at S=5) take the
  tie-group-LOCAL masked min over a per-pattern static table composed
  with the argsort; only all-tied lanes (admissible group = the full
  S!) still pay the S!-table masked min. Both tier-3 buckets drain
  through fixed-size blocks inside a ``lax.while_loop`` whose trip
  count adapts to the actual heavy-lane count — no static budget, no
  whole-batch fallback cliff.

A permutation sigma acts on the packed view as: row gathers for
server-indexed axes, value remaps for server-valued fields and bitmasks,
and field remaps inside packed message keys (no slot re-sort — multiset
hash). Message keys may be 2-word (BitPacker: msg_hi/msg_lo/msg_cnt
kinds) or N-word (WidePacker: msg_word kinds, declared in word order).
A model declares which packed fields transform under sigma either via
``msg_server_fields`` / ``msg_server_nil_fields`` (plain / nil-valued
server ids) or a full ``msg_perm_spec`` of (field, kind) pairs with kind
in {"server", "server_nil", "server_bitmask"} — the bitmask kind covers
member sets inside reconfig-spec messages
(``RaftWithReconfigAddRemove.tla:874``).
"""

from __future__ import annotations

import itertools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .hashing import (
    KA,
    KB,
    PA,
    PB,
    U64_MAX,
    _reduce_pair,
    combine_pair,
    eq_u64,
    ge_u64,
    hash_lanes_pair,
    memo_slot,
    mix32,
    ne_u64,
    seed_salts,
    sort_u64_with_idx,
)
from .packing import EMPTY, BitPacker, WidePacker
from ..models.base import Layout

_C1 = np.uint64(0x9E3779B97F4A7C15)
_C2 = np.uint64(0xC2B2AE3D27D4EB4F)
_MASK64 = (1 << 64) - 1


def _host_mix64(z: int) -> int:
    """splitmix64 finalizer on python ints (for setup-time salts)."""
    z &= _MASK64
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & _MASK64
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & _MASK64
    return z ^ (z >> 31)


def _np_mix32(z: np.ndarray) -> np.ndarray:
    """murmur3 fmix32 on numpy arrays (u64 intermediate, masked) — for
    building static seed-family xor-mask tables at construction time."""
    m = 0xFFFFFFFF
    z = z.astype(np.uint64) & m
    z = ((z ^ (z >> 16)) * 0x85EBCA6B) & m
    z = ((z ^ (z >> 13)) * 0xC2B2AE35) & m
    return (z ^ (z >> 16)).astype(np.uint32)


def _salt(field_offset: int, role: int) -> tuple[np.uint32, np.uint32]:
    """Deterministic per-(field, role) u32 salt pair for signature folds.
    Depends only on the field's layout offset and the fold role — never
    on a server index (equivariance)."""
    z = _host_mix64(field_offset * 0x100 + role + 0x5A17)
    return np.uint32(z >> 32), np.uint32(z & 0xFFFFFFFF)


# ---- u32 stream-pair helpers (v4: all device hashing avoids u64 muls) ----


def _pmix(x, salt):
    """int array -> (u32, u32) mixed stream pair under a salt pair."""
    sa, sb = salt
    xx = x.astype(jnp.uint32)
    return mix32(xx * KA + sa), mix32(xx * KB + sb)


def _pfold(pair, salt):
    """Re-avalanche an existing stream pair under a salt pair."""
    sa, sb = salt
    a, b = pair
    return mix32(a + sa), mix32(b + sb)


def _padd(p, q):
    return p[0] + q[0], p[1] + q[1]


def _pwhere(cond, p, zero=np.uint32(0)):
    return jnp.where(cond, p[0], zero), jnp.where(cond, p[1], zero)


def _psum_last(p):
    """Sum a stream pair over the LAST axis with one reduce op (two
    reduces over a shared producer hit the fusion cliff — hashing.py)."""
    return _reduce_pair(p[0], p[1], op="sum")


def _pgather(p, idx):
    return (
        jnp.take_along_axis(p[0], idx, axis=1),
        jnp.take_along_axis(p[1], idx, axis=1),
    )


def _adj_swap_products(S: int):
    """All non-identity products of pairwise-DISJOINT adjacent
    transpositions of 0..S-1 (the independent edge subsets of the path
    graph): [T, S] perms + [T, S-1] bool masks of the edges each uses."""
    combos = []
    edges = range(S - 1)
    for r in range(1, S):
        for combo in itertools.combinations(edges, r):
            if all(b - a > 1 for a, b in zip(combo, combo[1:])):
                combos.append(combo)
    perms, masks = [], []
    for combo in combos:
        p = list(range(S))
        for k in combo:
            p[k], p[k + 1] = p[k + 1], p[k]
        perms.append(p)
        masks.append([k in combo for k in range(S - 1)])
    return np.array(perms, np.int32), np.array(masks, bool)


def _tie_pattern_groups(S: int, pat: int) -> list[list[int]]:
    """Sorted-position tie groups of an adjacent-equality bit pattern
    (bit j set <=> sorted positions j and j+1 hold equal signatures)."""
    groups, cur = [], [0]
    for j in range(S - 1):
        if (pat >> j) & 1:
            cur.append(j + 1)
        else:
            groups.append(cur)
            cur = [j + 1]
    groups.append(cur)
    return groups


def _tie_pattern_tables(S: int):
    """Per-tie-pattern admissible block permutations of the SORTED
    positions (products of per-group symmetric groups), for the
    tie-group-local tier-3 min.

    Returns (tab [NP, LCAP, S] int32, mask [NP, LCAP] bool,
    local [NP] bool) over the NP = 2^(S-1) adjacent-equality patterns.
    LCAP is the largest admissible-group size among patterns that
    contain a tie group >= 3 but are not all-tied (24 at S=5: the
    {4,1} pattern). Every pattern whose group fits in LCAP is marked
    ``local`` and its table rows enumerate the COMPLETE admissible set
    (identity included), so the local min is exactly the masked
    full-S! min for those lanes; the rest (the all-tied pattern at
    S=5) route to the full S!-table path."""
    NP = 1 << (S - 1)
    nfull = math.factorial(S)
    counts = []
    for pat in range(NP):
        groups = _tie_pattern_groups(S, pat)
        counts.append(int(np.prod([math.factorial(len(g)) for g in groups])))
    lcap = max(
        (c for pat, c in enumerate(counts)
         if c < nfull
         and max(len(g) for g in _tie_pattern_groups(S, pat)) >= 3),
        default=1,
    )
    tab = np.tile(np.arange(S, dtype=np.int32), (NP, lcap, 1))
    mask = np.zeros((NP, lcap), dtype=bool)
    local = np.zeros(NP, dtype=bool)
    for pat in range(NP):
        if counts[pat] > lcap:
            continue
        local[pat] = True
        groups = _tie_pattern_groups(S, pat)
        row = 0
        for combo in itertools.product(
            *[itertools.permutations(g) for g in groups]
        ):
            p = np.arange(S, dtype=np.int32)
            for g, pg in zip(groups, combo):
                for j, tgt in zip(g, pg):
                    p[j] = tgt
            tab[pat, row] = p
            mask[pat, row] = True
            row += 1
        assert row == counts[pat]
    return tab, mask, local


class Canonicalizer:
    @classmethod
    def for_model(cls, model, symmetry: bool = True, seed: int = 0,
                  mode: str = "auto",
                  refine_rounds: int = 3) -> "Canonicalizer":
        """Build from a model's declared message-field symmetry contract
        (keeps the model -> canonicalization plumbing in one place).

        A model with data-dependent canonicalization (e.g. the
        KRaftWithReconfig slot encoding, where a host permutation re-sorts
        the identity slots) supplies its own via ``make_canonicalizer``;
        the returned object provides the same ``fingerprints`` /
        ``_fingerprints`` / ``symmetry`` surface the checkers use."""
        from .. import enable_compcache

        enable_compcache()  # covers custom make_canonicalizer models too
        if hasattr(model, "make_canonicalizer"):
            return model.make_canonicalizer(symmetry, seed=seed)
        return cls(
            model.layout,
            model.packer,
            msg_server_fields=getattr(
                model, "msg_server_fields", ("msource", "mdest")
            ),
            msg_server_nil_fields=getattr(model, "msg_server_nil_fields", ()),
            msg_perm_spec=getattr(model, "msg_perm_spec", None),
            symmetry=symmetry,
            seed=seed,
            mode=mode,
            refine_rounds=refine_rounds,
        )

    def __init__(
        self,
        layout: Layout,
        packer,
        msg_server_fields: tuple[str, ...] = ("msource", "mdest"),
        msg_server_nil_fields: tuple[str, ...] = (),
        msg_perm_spec: tuple[tuple[str, str], ...] | None = None,
        symmetry: bool = True,
        seed: int = 0,
        mode: str = "auto",
        refine_rounds: int = 3,
    ):
        from .. import enable_compcache

        enable_compcache()  # direct constructions (tests, tools)
        S = layout.n_servers
        VL = layout.view_len
        assert VL is not None
        assert mode in ("auto", "full")
        self.layout = layout
        self.packer = packer
        self.symmetry = symmetry
        self.mode = mode
        # 1-WL refinement depth: part of the fingerprint formula (it
        # selects the admissible permutation set for tied states), so it
        # is fixed per canonicalizer and recorded in the checkpoint
        # identity (hashv=5/wl=k). k=3 empirically reaches the fixpoint
        # on the raft workloads; every round is equivariant, so any k
        # yields a correct (bit-self-consistent) canonical form.
        self.refine_rounds = max(1, int(refine_rounds))
        # fingerprint hash seed: a second independent hash family for the
        # collision audit (checker/audit.py)
        self.seed = seed
        # Unified remap spec: (packed field, kind) with kind one of
        #   server          plain server index (msource/mdest)
        #   server_nil      0 = Nil, i+1 = server i (KRaft mleader)
        #   server_bitmask  member set as a bitmask over servers
        if msg_perm_spec is None:
            msg_perm_spec = tuple(
                (f, "server") for f in msg_server_fields
            ) + tuple((f, "server_nil") for f in msg_server_nil_fields)
        self.msg_perm_spec = msg_perm_spec

        if symmetry:
            perms = np.array(list(itertools.permutations(range(S))), dtype=np.int32)
        else:
            perms = np.arange(S, dtype=np.int32)[None, :]
        P = perms.shape[0]

        val_lanes: list[int] = []
        bm_lanes: list[int] = []
        # key-word slices, ordered by sort significance: (hi, lo) for the
        # 2-word BitPacker bags (collected by kind, so layout declaration
        # order cannot silently flip them), msg_word declaration order for
        # the N-word WidePacker bags (word 0 = sort-major by contract)
        hi_sl: slice | None = None
        lo_sl: slice | None = None
        wide_sls: list[slice] = []
        msg_cnt_sl: slice | None = None
        view_fields = []  # (kind, offset, shape, size), offset order
        for f in layout.fields.values():
            if f.offset >= VL:
                continue  # aux: not fingerprinted
            view_fields.append((f.kind, f.offset, f.shape, f.size))
            if f.kind in ("per_server", "per_server_val", "server_bitmask"):
                lanes = list(range(f.offset, f.offset + f.size))
                if f.kind == "per_server_val":
                    val_lanes += lanes
                elif f.kind == "server_bitmask":
                    bm_lanes += lanes
            elif f.kind == "msg_hi":
                hi_sl = layout.sl(f.name)
            elif f.kind == "msg_lo":
                lo_sl = layout.sl(f.name)
            elif f.kind == "msg_word":
                wide_sls.append(layout.sl(f.name))
            elif f.kind == "msg_cnt":
                msg_cnt_sl = layout.sl(f.name)
        if hi_sl is not None or lo_sl is not None:
            assert hi_sl is not None and lo_sl is not None and not wide_sls
            msg_word_sls = [hi_sl, lo_sl]
        else:
            msg_word_sls = wide_sls
        if msg_word_sls:
            n_expected = 2 if hi_sl is not None else getattr(packer, "n_words", None)
            assert n_expected is None or len(msg_word_sls) == n_expected

        self.S, self.P, self.VL = S, P, VL
        # signature pruning pays only past ~24 permutations (see
        # _fingerprints); the choice is per-layout so fingerprints stay
        # consistent across every checker path for a given model
        self.prune = symmetry and S >= 5
        self._val_lanes = np.array(sorted(val_lanes), dtype=np.int32)
        self._bm_lanes = np.array(sorted(bm_lanes), dtype=np.int32)
        self._msg_word_sls = msg_word_sls
        self._msg_cnt_sl = msg_cnt_sl
        self._view_fields = sorted(view_fields, key=lambda t: t[1])
        assert sum(t[3] for t in self._view_fields) == VL, "view lane gap"
        # non-bag view lanes for the positional half of the hash
        bag_lanes: set[int] = set()
        for sl in msg_word_sls:
            bag_lanes |= set(range(sl.start, sl.stop))
        if msg_cnt_sl is not None:
            bag_lanes |= set(range(msg_cnt_sl.start, msg_cnt_sl.stop))
        self._nonbag_lanes = np.array(
            [i for i in range(VL) if i not in bag_lanes], dtype=np.int32
        )
        # ---- direct-hash structure (round 5): the permuted view is
        # never materialized. The positional hash of a permuted view is
        # the lane-wise XOR of mix32(value * K + position*P) — so a lane
        # permutation is just a permutation of the POSITIONAL SALTS
        # (precomputed numpy tables for static permutation sets; cheap
        # elementwise arithmetic for the dynamic tier-1 argsort), and a
        # value remap is a one-hot select over <= S+1 values. Non-bag
        # lanes split into three groups by how values transform:
        #   plain  value invariant (per_server rows, pair matrices,
        #          scalars) — only the position moves
        #   val    server-valued lanes (0 = Nil, i+1 = server i)
        #   bm     bitmask lanes (member sets over servers)
        # XOR-combining the three group reduces equals the single
        # all-lanes reduce of hash_lanes_pair (XOR is commutative and
        # the salt carries the position), so fingerprints are
        # BIT-IDENTICAL to the round-4 v4 formula (hashv stays 4).
        nb = self._nonbag_lanes
        self._K_nb = len(nb)
        nb_inv = np.full(VL, -1, dtype=np.int64)
        nb_inv[nb] = np.arange(len(nb))
        self._nb_inv = nb_inv
        vset, bset = set(val_lanes), set(bm_lanes)
        self._ln_plain = np.array(
            [l for l in nb if l not in vset and l not in bset], np.int32
        )
        self._ln_val = np.array([l for l in nb if l in vset], np.int32)
        self._ln_bm = np.array([l for l in nb if l in bset], np.int32)
        # dynamic-permutation segment recipe, per group in lane order
        # (view_fields are offset-sorted, so per-group concatenation
        # matches the _ln_* lane order)
        self._dyn_segs: list[tuple[str, str, int, int]] = []
        for kind, off, shape, size in self._view_fields:
            if kind in ("msg_hi", "msg_lo", "msg_word", "msg_cnt"):
                continue
            nbbase = int(nb_inv[off])
            if kind in ("per_server", "per_server_val", "server_bitmask"):
                group = {"per_server_val": "val",
                         "server_bitmask": "bm"}.get(kind, "plain")
                self._dyn_segs.append((group, "rows", nbbase, size // S))
            elif kind == "per_server_pair":
                self._dyn_segs.append(("plain", "pair", nbbase, S))
            else:
                self._dyn_segs.append(("plain", "static", nbbase, size))
        if symmetry:
            self._dt_full = self._build_direct(perms)
        if self.prune:
            # tier-2 static tables: all non-identity products of DISJOINT
            # adjacent transpositions (7 non-identity products at S=5;
            # tier 1's argsort is the identity on the sorted view).
            # Applied to the signature-
            # SORTED view these are exactly the block permutations of any
            # tie pattern whose groups have size <= 2 — measured to be
            # >98% of tied states past depth ~9 on the 5-server workload
            # (the rest fall to the masked full-S! path).
            tperms, tmask = _adj_swap_products(S)
            self._t_sigma = jnp.asarray(tperms)  # [T, S] for composition
            self._t_edge_mask = jnp.asarray(tmask)  # [T, S-1]
            # tier-3 tie-pattern tables: complete admissible block-perm
            # sets for every pattern small enough to enumerate locally
            ptab, pmask, plocal = _tie_pattern_tables(S)
            self._p_tab = jnp.asarray(ptab)  # [NP, LCAP, S]
            self._p_mask = jnp.asarray(pmask)  # [NP, LCAP]
            self._p_local = jnp.asarray(plocal)  # [NP]
        self.fingerprints = jax.jit(self._fingerprints)

    def _np_gidx(self, perms: np.ndarray) -> np.ndarray:
        """[T, VL] lane-gather table: permuted[l] = view[gidx[t, l]]."""
        S, VL = self.S, self.VL
        T = perms.shape[0]
        inv = np.argsort(perms, axis=1).astype(np.int32)
        gidx = np.tile(np.arange(VL, dtype=np.int32), (T, 1))
        for kind, off, shape, size in self._view_fields:
            if kind in ("per_server", "per_server_val", "server_bitmask"):
                rest = size // S
                base = off + inv[:, :, None] * rest + np.arange(rest)
                gidx[:, off : off + size] = base.reshape(T, -1)
            elif kind == "per_server_pair":
                src = off + inv[:, :, None] * S + inv[:, None, :]
                gidx[:, off : off + size] = src.reshape(T, -1)
        return gidx

    def _build_direct(self, perms: np.ndarray) -> dict:
        """Direct-hash tables for a static [T, S] permutation set: per
        nonbag GROUP, u32 positional-salt tables (and seed-family xor
        masks), plus value-remap tables and the inverse permutations for
        the admissibility mask. All numpy at build time; jnp constants."""
        S = self.S
        T = perms.shape[0]
        nb = self._nonbag_lanes
        K = self._K_nb
        gidx = self._np_gidx(perms)
        # outpos[t, j] = hash position (index within the nonbag subset of
        # the PERMUTED view) that source nonbag lane j lands at
        src = self._nb_inv[gidx[:, nb]]  # [T, K] src nonbag idx per outpos
        outpos = np.empty((T, K), dtype=np.int64)
        rows = np.repeat(np.arange(T), K)
        outpos[rows, src.reshape(-1)] = np.tile(np.arange(K), T)
        dt: dict = {
            "perms": jnp.asarray(perms.astype(np.int32)),
            "inv": jnp.asarray(np.argsort(perms, axis=1).astype(np.int32)),
            "pow2": jnp.asarray((1 << perms).astype(np.int32)),
        }
        valmap = np.zeros((T, S + 1), dtype=np.int32)
        valmap[:, 1:] = perms + 1
        dt["valmap"] = jnp.asarray(valmap)
        if self.seed:
            sa, sb = seed_salts(self.seed)
        for gname, lanes in (("plain", self._ln_plain),
                             ("val", self._ln_val), ("bm", self._ln_bm)):
            kpos = self._nb_inv[lanes]  # this group's nonbag indices
            op = outpos[:, kpos] if len(lanes) else outpos[:, :0]
            pa = ((op * int(PA)) & 0xFFFFFFFF).astype(np.uint32)
            pb = ((op * int(PB)) & 0xFFFFFFFF).astype(np.uint32)
            dt[f"pa_{gname}"] = jnp.asarray(pa)
            dt[f"pb_{gname}"] = jnp.asarray(pb)
            if self.seed:
                dt[f"xa_{gname}"] = jnp.asarray(_np_mix32(pa + sa))
                dt[f"xb_{gname}"] = jnp.asarray(_np_mix32(pb + sb))
        return dt

    # packer adapters: BitPacker works on (hi, lo), WidePacker on tuples
    def _unpack_key(self, words, name):
        if isinstance(self.packer, WidePacker):
            return self.packer.unpack(words, name)
        return self.packer.unpack(words[0], words[1], name)

    def _replace_key(self, words, name, value):
        if isinstance(self.packer, WidePacker):
            return list(self.packer.replace(words, name, value))
        hi, lo = self.packer.replace(words[0], words[1], name, value)
        return [hi, lo]

    # ---------------- the v3 hash ----------------

    def _bag_hash_pair(self, v):
        """Multiset hash of the message bag region of [B, VL] views as a
        (u32, u32) stream pair: occupied slots' position-independent
        record hashes combine by ADDITION mod 2^32 (nonlinear carries —
        a slightly better multiset structure than the round-4 XOR, which
        was linear over GF(2); slots hold distinct keys by construction
        either way, so neither combine can cancel duplicates)."""
        if not self._msg_word_sls:
            z = jnp.zeros(v.shape[:-1], jnp.uint32)
            return z, z
        words = [v[..., sl] for sl in self._msg_word_sls]  # each [B, M]
        cnt = v[..., self._msg_cnt_sl]
        occ = words[0] != EMPTY
        ha = jnp.zeros_like(words[0], dtype=jnp.uint32)
        hb = jnp.zeros_like(words[0], dtype=jnp.uint32)
        for w_i, w in enumerate([*words, cnt]):
            x = w.astype(jnp.uint32)
            if self.seed:
                sw = _host_mix64(w_i * int(_C2) + self.seed)
                x = x ^ np.uint32(sw & 0xFFFFFFFF)
            wa, wb = _salt(w_i, 20)
            ha = ha ^ mix32(x * KA + wa)
            hb = hb ^ mix32(x * KB + wb)
        # per-slot finalize, then a single stacked multiset-sum reduce
        ha = mix32(ha + KB)
        hb = mix32(hb + KA)
        return _psum_last(_pwhere(occ, (ha, hb)))

    def _perm_hash(self, v):
        """u64 hash of a permuted [B, VL] view: positional over the
        non-bag lanes XOR the slot-order-free bag multiset hash (all
        mixing in u32 stream pairs; one u64 combine at the end)."""
        na, nb = hash_lanes_pair(v[..., self._nonbag_lanes], seed=self.seed)
        ba, bb = self._bag_hash_pair(v)
        return combine_pair(na ^ ba, nb ^ bb)

    # ---------------- equivariant per-server signatures ----------------

    def _signatures(self, view, rounds: int | None = None):
        """[B, VL] -> u64 [B, S] permutation-EQUIVARIANT signatures:
        sig(perm(x))[sigma(i)] == sig(x)[i]. Built from per-server
        invariant content plus ``rounds`` 1-WL refinement rounds
        (default ``self.refine_rounds``); every fold is either
        self-relative or an unordered multiset sum, and no fold reads a
        raw server index — each round preserves equivariance, so any
        depth yields a correct admissible set. All mixing runs as u32
        stream pairs (v4 — u64 multiplies are ~400x slow on this TPU,
        hashing.py); the streams combine into one orderable u64 at the
        very end. ``rounds=1`` reproduces the v4 signature exactly
        (round-0 fold salts are depth-offset only for r >= 1)."""
        S, B = self.S, view.shape[0]
        srange = jnp.arange(S, dtype=jnp.int32)
        acc = (jnp.zeros((B, S), jnp.uint32), jnp.zeros((B, S), jnp.uint32))

        # ---- round 0: invariant content ----
        val_fields = []  # (offset, vals [B,S]) for refinement
        bm_fields = []  # (offset, masks [B,S])
        pair_fields = []  # (offset, mat [B,S,S])
        for kind, off, shape, size in self._view_fields:
            seg = view[:, off : off + size]
            if kind == "per_server":
                rest = size // S
                rows = seg.reshape(B, S, rest)
                acc = _padd(acc, _pfold(hash_lanes_pair(rows), _salt(off, 0)))
            elif kind == "per_server_val":
                vals = seg  # [B, S], 0 = Nil, i+1 = server i
                cat = jnp.where(
                    vals == 0, 0, jnp.where(vals - 1 == srange, 1, 2)
                )
                acc = _padd(acc, _pmix(cat, _salt(off, 1)))
                indeg = jnp.sum(
                    (vals[:, :, None] - 1 == srange[None, None, :])
                    & (vals[:, :, None] > 0),
                    axis=1,
                )
                acc = _padd(acc, _pmix(indeg, _salt(off, 2)))
                val_fields.append((off, vals))
            elif kind == "server_bitmask":
                masks = seg  # [B, S]
                bits = (masks[:, :, None] >> srange[None, None, :]) & 1  # [B,S,S]
                selfbit = (masks >> srange) & 1
                pop = jnp.sum(bits, axis=2)
                acc = _padd(acc, _pmix(pop * 2 + selfbit, _salt(off, 3)))
                acc = _padd(acc, _pmix(jnp.sum(bits, axis=1), _salt(off, 4)))
                bm_fields.append((off, masks))
            elif kind == "per_server_pair":
                mat = seg.reshape(B, S, S)
                diag = mat[:, srange, srange]
                acc = _padd(acc, _pmix(diag, _salt(off, 5)))
                offd = srange[:, None] != srange[None, :]
                e_row = _pwhere(offd, _pmix(mat, _salt(off, 6)))
                acc = _padd(acc, _psum_last(e_row))
                # column fold: transpose so the multiset sum is over the
                # LAST axis (single stacked reduce, hashing.py cliff note)
                e_col = _pwhere(
                    offd, _pmix(mat.transpose(0, 2, 1), _salt(off, 7))
                )
                acc = _padd(acc, _psum_last(e_col))
                pair_fields.append((off, mat))
            # scalar / msg_* handled below; aux excluded by view

        # messages, round 0: fold each record (server fields masked out)
        # into the servers it references
        msg = None
        if self._msg_word_sls:
            words = [view[:, sl] for sl in self._msg_word_sls]  # [B, M]
            cnt = view[:, self._msg_cnt_sl]
            occ = words[0] != EMPTY
            zwords = list(words)
            for fname, _kind in self.msg_perm_spec:
                zwords = self._replace_key(
                    zwords, fname, jnp.zeros_like(zwords[0])
                )
            r0a = jnp.zeros_like(words[0], dtype=jnp.uint32)
            r0b = jnp.zeros_like(words[0], dtype=jnp.uint32)
            for w_i, w in enumerate([*zwords, cnt]):
                x = w.astype(jnp.uint32)
                wa, wb = _salt(w_i, 21)
                r0a = r0a ^ mix32(x * KA + wa)
                r0b = r0b ^ mix32(x * KB + wb)
            rec0 = (mix32(r0a), mix32(r0b))
            cnt32 = jnp.where(occ, cnt, 0).astype(jnp.uint32)
            msg = (words, cnt32, occ, rec0)
            for k, (fname, kind) in enumerate(self.msg_perm_spec):
                val = self._unpack_key(words, fname)  # [B, M]
                ck = _pfold(rec0, _salt(k, 8))
                c = (cnt32 * ck[0], cnt32 * ck[1])  # [B, M]
                acc = _padd(acc, self._scatter_by_server(c, val, kind, occ))

        sig0 = (mix32(acc[0]), mix32(acc[1]))

        # ---- refinement: fold neighbor signatures, k rounds ----
        def refine(sigp, r):
            rr = 32 * r  # depth-offset every fold salt past round 0
            acc1 = (jnp.zeros((B, S), jnp.uint32),
                    jnp.zeros((B, S), jnp.uint32))
            for off, vals in val_fields:
                tgt = jnp.clip(vals - 1, 0, S - 1)
                nsig = _pgather(sigp, tgt)
                valid = (vals > 0) & (vals - 1 != srange)
                sa, sb = _salt(off, 9 + rr)
                acc1 = _padd(
                    acc1,
                    _pwhere(valid, (mix32(nsig[0] ^ sa), mix32(nsig[1] ^ sb))),
                )
            for off, masks in bm_fields:
                bits = ((masks[:, :, None] >> srange[None, None, :]) & 1) == 1
                sa, sb = _salt(off, 10 + rr)
                e = (mix32(sigp[0] ^ sa), mix32(sigp[1] ^ sb))  # [B, S]
                contrib = _pwhere(
                    bits,
                    (
                        jnp.broadcast_to(e[0][:, None, :], bits.shape),
                        jnp.broadcast_to(e[1][:, None, :], bits.shape),
                    ),
                )
                acc1 = _padd(acc1, _psum_last(contrib))
            for off, mat in pair_fields:
                sa, sb = _salt(off, 11 + rr)
                m32 = mat.astype(jnp.uint32)
                era = mix32(m32 * KA + (sigp[0] ^ sa)[:, None, :])
                erb = mix32(m32 * KB + (sigp[1] ^ sb)[:, None, :])
                acc1 = _padd(acc1, _psum_last((era, erb)))
                sa2, sb2 = _salt(off, 12 + rr)
                mt32 = mat.transpose(0, 2, 1).astype(jnp.uint32)
                eca = mix32(mt32 * KA + (sigp[0] ^ sa2)[:, None, :])
                ecb = mix32(mt32 * KB + (sigp[1] ^ sb2)[:, None, :])
                acc1 = _padd(acc1, _psum_last((eca, ecb)))
            if msg is not None:
                words, cnt32, occ, rec0 = msg
                # per-slot fold of every referenced server's sig, then
                # re-scatter: binds a record's endpoints together
                svals = []
                osum = (jnp.zeros_like(rec0[0]), jnp.zeros_like(rec0[1]))
                for k, (fname, kind) in enumerate(self.msg_perm_spec):
                    val = self._unpack_key(words, fname)
                    svals.append(val)
                    osum = _padd(
                        osum,
                        self._gather_sig_fold(sigp, val, kind,
                                              _salt(k, 13 + rr)),
                    )
                for k, (fname, kind) in enumerate(self.msg_perm_spec):
                    # exclude the target's own contribution so its fold
                    # is over the OTHER endpoints
                    own = self._gather_sig_fold(sigp, svals[k], kind,
                                                _salt(k, 13 + rr))
                    sa, sb = _salt(k, 14 + rr)
                    c = (
                        cnt32 * mix32(rec0[0] + (osum[0] - own[0]) + sa),
                        cnt32 * mix32(rec0[1] + (osum[1] - own[1]) + sb),
                    )
                    acc1 = _padd(
                        acc1,
                        self._scatter_by_server(c, svals[k], kind, occ),
                    )
            return (mix32(sigp[0] + mix32(acc1[0])),
                    mix32(sigp[1] + mix32(acc1[1])))

        sigp = sig0
        for r in range(self.refine_rounds if rounds is None else rounds):
            sigp = refine(sigp, r)
        return combine_pair(sigp[0], sigp[1])

    def _scatter_by_server(self, contrib, val, kind, occ):
        """Sum [B, M] stream-pair contributions onto the servers
        referenced by a message field ([B, M] values, interpretation per
        kind) -> [B, S] pair. Laid out [B, S, M] so the multiset sum is a
        single stacked last-axis reduce."""
        S = self.S
        srange = jnp.arange(S, dtype=jnp.int32)
        ca = jnp.where(occ, contrib[0], 0)
        cb = jnp.where(occ, contrib[1], 0)
        vt = val[:, None, :]  # [B, 1, M]
        if kind == "server":
            onehot = vt == srange[None, :, None]
        elif kind == "server_nil":
            onehot = (vt - 1 == srange[None, :, None]) & (vt > 0)
        elif kind == "server_bitmask":
            onehot = ((vt >> srange[None, :, None]) & 1) == 1
        else:
            raise ValueError(f"unknown msg perm kind {kind}")
        pa = jnp.where(onehot, ca[:, None, :], 0)
        pb = jnp.where(onehot, cb[:, None, :], 0)
        return _psum_last((pa, pb))

    def _gather_sig_fold(self, sig0, val, kind, salt):
        """Fold the sig0 of servers referenced by a [B, M] message field
        into a per-slot stream pair (multiset sum; 0 when Nil/absent)."""
        S = self.S
        sa, sb = salt
        if kind == "server":
            nsig = _pgather(sig0, jnp.clip(val, 0, S - 1))
            return mix32(nsig[0] ^ sa), mix32(nsig[1] ^ sb)
        if kind == "server_nil":
            nsig = _pgather(sig0, jnp.clip(val - 1, 0, S - 1))
            return _pwhere(val > 0, (mix32(nsig[0] ^ sa), mix32(nsig[1] ^ sb)))
        if kind == "server_bitmask":
            srange = jnp.arange(S, dtype=jnp.int32)
            bits = ((val[:, :, None] >> srange[None, None, :]) & 1) == 1
            ea = mix32(sig0[0] ^ sa)  # [B, S]
            eb = mix32(sig0[1] ^ sb)
            pa = jnp.where(bits, jnp.broadcast_to(ea[:, None, :], bits.shape), 0)
            pb = jnp.where(bits, jnp.broadcast_to(eb[:, None, :], bits.shape), 0)
            return _psum_last((pa, pb))
        raise ValueError(f"unknown msg perm kind {kind}")

    # ------------- direct permuted hashing (no materialization) -------------

    def _group_stream(self, vals, pa, pb, xa_m, xb_m):
        """XOR-reduced (u32, u32) stream pair of one lane group: vals
        int32 [..., B, K] (already value-remapped), pa/pb u32 positional
        salts (broadcastable), xa_m/xb_m the seed-family xor masks (None
        for seed=0). One stacked reduce (hashing.py fusion-cliff note)."""
        x = vals.astype(jnp.uint32)
        xa = x ^ xa_m if xa_m is not None else x
        xb = x ^ xb_m if xb_m is not None else x
        ha = mix32(xa * KA + pa)
        hb = mix32(xb * KB + pb)
        return _reduce_pair(ha, hb, op="xor")

    def _nb_const(self):
        ka = np.uint32((self._K_nb * int(KA)) & 0xFFFFFFFF)
        kb = np.uint32((self._K_nb * int(KB)) & 0xFFFFFFFF)
        return ka, kb

    def _remap_val_static(self, xv, valmap):
        """One-hot server-value remap under [T, S+1] tables -> [T, B, Kv]."""
        out = jnp.zeros((valmap.shape[0],) + xv.shape, jnp.int32)
        for u in range(1, self.S + 1):  # value 0 (Nil) maps to 0
            out = out + jnp.where(xv[None] == u, valmap[:, u, None, None], 0)
        return out

    def _remap_bm_static(self, xb, pow2):
        """Bitmask remap under [T, S] bit-target tables -> [T, B, Kb]."""
        out = jnp.zeros((pow2.shape[0],) + xb.shape, jnp.int32)
        for j in range(self.S):
            out = out + ((xb[None] >> j) & 1) * pow2[:, j, None, None]
        return out

    def _bag_streams(self, view, remap_field):
        """Shared bag-hash skeleton: ``remap_field(val, kind)`` supplies
        the permuted value of each server-referencing message field
        (with any leading permutation axes); returns the multiset-summed
        stream pair [..., B] (bit-identical to _bag_hash_pair on the
        materialized permuted view — unoccupied slots are masked out
        either way, so their word values never contribute)."""
        words = [view[:, sl] for sl in self._msg_word_sls]
        cnt = view[:, self._msg_cnt_sl]
        occ = words[0] != EMPTY
        nwords = list(words)  # remapped values carry any leading perm axes
        for fname, kind in self.msg_perm_spec:
            val = self._unpack_key(words, fname)  # [B, M], original bits
            nwords = self._replace_key(nwords, fname, remap_field(val, kind))
        ha = hb = jnp.uint32(0)
        for w_i, w in enumerate([*nwords, cnt]):
            x = w.astype(jnp.uint32)
            if self.seed:
                sw = _host_mix64(w_i * int(_C2) + self.seed)
                x = x ^ np.uint32(sw & 0xFFFFFFFF)
            wa, wb = _salt(w_i, 20)
            ha = ha ^ mix32(x * KA + wa)
            hb = hb ^ mix32(x * KB + wb)
        ha = mix32(ha + KB)
        hb = mix32(hb + KA)
        return _psum_last(_pwhere(occ, (ha, hb)))

    def _hash_static(self, view, dt):
        """u64 [T, B] hashes of ``view`` under every permutation of a
        static direct-table set — without materializing permuted views:
        per group, the original values (plain) or one-hot-remapped values
        (val/bm) mix against the PERMUTED positional-salt tables."""
        parts = []
        if self._ln_plain.size:
            parts.append(self._group_stream(
                view[:, self._ln_plain],
                dt["pa_plain"][:, None, :], dt["pb_plain"][:, None, :],
                dt["xa_plain"][:, None, :] if self.seed else None,
                dt["xb_plain"][:, None, :] if self.seed else None,
            ))
        if self._ln_val.size:
            vals = self._remap_val_static(view[:, self._ln_val], dt["valmap"])
            parts.append(self._group_stream(
                vals, dt["pa_val"][:, None, :], dt["pb_val"][:, None, :],
                dt["xa_val"][:, None, :] if self.seed else None,
                dt["xb_val"][:, None, :] if self.seed else None,
            ))
        if self._ln_bm.size:
            vals = self._remap_bm_static(view[:, self._ln_bm], dt["pow2"])
            parts.append(self._group_stream(
                vals, dt["pa_bm"][:, None, :], dt["pb_bm"][:, None, :],
                dt["xa_bm"][:, None, :] if self.seed else None,
                dt["xb_bm"][:, None, :] if self.seed else None,
            ))
        ka, kb = self._nb_const()
        na = parts[0][0]
        nb_ = parts[0][1]
        for a, b in parts[1:]:
            na = na ^ a
            nb_ = nb_ ^ b
        na = na ^ ka
        nb_ = nb_ ^ kb
        if self._msg_word_sls:
            S = self.S

            def remap(val, kind):
                if kind == "server":
                    out = jnp.zeros(dt["perms"].shape[:1] + val.shape, jnp.int32)
                    for u in range(S):
                        out = out + jnp.where(
                            val[None] == u, dt["perms"][:, u, None, None], 0)
                    return out
                if kind == "server_nil":
                    out = jnp.zeros(dt["perms"].shape[:1] + val.shape, jnp.int32)
                    for u in range(S):
                        out = out + jnp.where(
                            val[None] == u + 1,
                            dt["perms"][:, u, None, None] + 1, 0)
                    return out
                if kind == "server_bitmask":
                    out = jnp.zeros(dt["pow2"].shape[:1] + val.shape, jnp.int32)
                    for j in range(S):
                        out = out + ((val[None] >> j) & 1) * dt["pow2"][:, j, None, None]
                    return out
                raise ValueError(f"unknown msg perm kind {kind}")

            ba, bb = self._bag_streams(view, remap)
            na = na ^ ba
            nb_ = nb_ ^ bb
        return combine_pair(na, nb_)

    def _dyn_outpos(self, sigma):
        """Per-group hash positions under dynamic sigma [..., B, S] (old
        server i -> new index sigma[..., i]) -> dict of [..., B, Kg]
        int32. Pure elementwise arithmetic — permutations move whole
        server blocks, so a lane's destination is affine in sigma."""
        S = self.S
        lead = sigma.shape[:-1]  # (..., B)
        segs: dict[str, list] = {"plain": [], "val": [], "bm": []}
        for group, skind, nbbase, n in self._dyn_segs:
            if skind == "rows":
                rest = n
                seg = (nbbase
                       + sigma[..., :, None] * rest
                       + jnp.arange(rest, dtype=jnp.int32))
                seg = seg.reshape(lead + (S * rest,))
            elif skind == "pair":
                seg = nbbase + sigma[..., :, None] * S + sigma[..., None, :]
                seg = seg.reshape(lead + (S * S,))
            else:  # static: scalar lanes keep their position
                seg = jnp.broadcast_to(
                    jnp.arange(nbbase, nbbase + n, dtype=jnp.int32),
                    lead + (n,),
                )
            segs[group].append(seg)
        return {
            g: (jnp.concatenate(s, axis=-1) if len(s) > 1 else s[0])
            if s else None
            for g, s in segs.items()
        }

    def _hash_dyn(self, view, sigma):
        """u64 [..., B] hash of ``view`` under dynamic per-state sigma
        [..., B, S] (leading axes broadcast a permutation batch, e.g.
        tier 2's composed swaps) — again with no materialized view."""
        S = self.S
        outpos = self._dyn_outpos(sigma)
        sa = sbm = None
        if self.seed:
            sa, sbm = seed_salts(self.seed)
        parts = []

        def stream(vals, op):
            pa = op.astype(jnp.uint32) * PA
            pb = op.astype(jnp.uint32) * PB
            xa_m = mix32(pa + sa) if self.seed else None
            xb_m = mix32(pb + sbm) if self.seed else None
            return self._group_stream(vals, pa, pb, xa_m, xb_m)

        if self._ln_plain.size:
            parts.append(stream(view[:, self._ln_plain], outpos["plain"]))
        if self._ln_val.size:
            xv = view[:, self._ln_val]
            out = jnp.zeros(sigma.shape[:-2] + xv.shape, jnp.int32)
            for u in range(S):
                out = out + jnp.where(
                    xv == u + 1, sigma[..., u][..., None] + 1, 0)
            parts.append(stream(out, outpos["val"]))
        if self._ln_bm.size:
            xb = view[:, self._ln_bm]
            out = jnp.zeros(sigma.shape[:-2] + xb.shape, jnp.int32)
            for j in range(S):
                out = out | ((xb >> j) & 1) << sigma[..., j][..., None]
            parts.append(stream(out, outpos["bm"]))
        ka, kb = self._nb_const()
        na = parts[0][0]
        nb_ = parts[0][1]
        for a, b in parts[1:]:
            na = na ^ a
            nb_ = nb_ ^ b
        na = na ^ ka
        nb_ = nb_ ^ kb
        if self._msg_word_sls:
            def remap(val, kind):
                # sigma [..., B, S]; val [B, M] -> [..., B, M]
                if kind == "server":
                    out = jnp.zeros(sigma.shape[:-1] + val.shape[-1:], jnp.int32)
                    for u in range(S):
                        out = out + jnp.where(
                            val == u, sigma[..., u][..., None], 0)
                    return out
                if kind == "server_nil":
                    out = jnp.zeros(sigma.shape[:-1] + val.shape[-1:], jnp.int32)
                    for u in range(S):
                        out = out + jnp.where(
                            val == u + 1, sigma[..., u][..., None] + 1, 0)
                    return out
                if kind == "server_bitmask":
                    out = jnp.zeros(sigma.shape[:-1] + val.shape[-1:], jnp.int32)
                    for j in range(S):
                        out = out | ((val >> j) & 1) << sigma[..., j][..., None]
                    return out
                raise ValueError(f"unknown msg perm kind {kind}")

            # _bag_streams broadcasts words [1, B, M] against the remap's
            # leading axes; for dyn the lead is sigma's [..., ] prefix of
            # [..., B, S] — i.e. [..., B, M] after remap
            ba, bb = self._bag_streams(view, remap)
            na = na ^ ba
            nb_ = nb_ ^ bb
        return combine_pair(na, nb_)

    # ---------------- the static masked-min (tie / full path) ----------------

    def _masked_min(self, view, sig):
        """min over the admissible static permutations (brute force over
        the S! direct tables; sig=None means no mask — the plain full-S!
        min). Blocked scan with a running min: the [PBLK, B, K] stream
        temps are bounded to ~512MB per block (P=120 at chunk-sized B
        would otherwise overflow HBM)."""
        B = view.shape[0]
        per_perm = max(1, B * max(1, self._K_nb) * 8)
        PBLK = max(1, min(self.P, (512 << 20) // per_perm))
        nblk = (self.P + PBLK - 1) // PBLK
        pad = nblk * PBLK - self.P

        def padt(t):
            if not pad:
                return t
            # duplicate perm 0: duplicates cannot change a min
            return jnp.concatenate([t, jnp.repeat(t[:1], pad, axis=0)])

        stacked = {
            k: padt(t).reshape((nblk, PBLK) + t.shape[1:])
            for k, t in self._dt_full.items()
        }

        def block(best, tb):
            h = self._hash_static(view, tb)  # [PBLK, B]
            if sig is not None:
                ssig = jnp.take(sig, tb["inv"], axis=1)  # [B, PBLK, S]
                adm = jnp.all(
                    ge_u64(ssig[..., 1:], ssig[..., :-1]), axis=-1
                ).T  # [PBLK, B]
                h = jnp.where(adm, h, U64_MAX)
            return jnp.minimum(best, jnp.min(h, axis=0)), None

        # derive the init from `view` so it carries the same varying-
        # manual-axes type as the body output under shard_map (a plain
        # jnp.full is unvarying and the scan carry types would mismatch)
        init = (view[:, 0].astype(jnp.uint64) & jnp.uint64(0)) | U64_MAX
        best, _ = lax.scan(block, init, stacked)
        return best

    # ---------------- entry point ----------------

    def _fingerprints(self, states):
        """[B, W] int32 -> uint64 [B] canonical fingerprints.

        Formula per layout (fixed at construction, so every checker path
        agrees): S <= 4 -> plain min over all S! permutations (the
        signature machinery costs more than it saves at 6-24 perms,
        measured on the TPU); S >= 5 -> signature-pruned masked min
        (at 120+ perms the brute force is ~9x the whole chunk budget)."""
        return self._canon_view(states[:, : self.VL])

    def _canon_view(self, view):
        """Tiered canonical hash of a [B, VL] view batch."""
        if not self.symmetry:
            return self._perm_hash(view)
        if not self.prune:
            return self._masked_min(view, None)
        sig = self._signatures(view)
        if self.mode == "full":
            return self._masked_min(view, sig)
        pre = self._tier_pre(view, sig)
        return self._tier3_apply(view, sig, *pre)

    def _tier_pre(self, view, sig):
        """Tiers 1+2 plus tie-pattern classification. Returns
        ``(fp, sigma, pat, is_local, is_full)``: the running min after
        the signature-argsort permutation (tier 1) and the static
        disjoint-adjacent-swap products (tier 2), the tier-1 sigma, each
        lane's adjacent-equality pattern id, and the two tier-3 route
        masks (tie group >= 3 with a locally enumerable admissible
        set / needing the full S! table)."""
        S = self.S

        # ---- tier 1: one dynamic permutation (the signature argsort) ----
        order = jnp.argsort(sig, axis=1).astype(jnp.int32)  # = inv
        ssig = jnp.take_along_axis(sig, order, axis=1)
        adj_eq = eq_u64(ssig[:, 1:], ssig[:, :-1])  # [B, S-1]
        sigma = jnp.argsort(order, axis=1).astype(jnp.int32)
        fp = self._hash_dyn(view, sigma)

        # ---- tier 2: disjoint adjacent-swap products on the SORTED view.
        # t composed with the argsort is admissible iff every swapped pair
        # is signature-tied; for states whose tie groups are all <= 2
        # these are ALL the admissible permutations, so min(tier1, tier2)
        # is exactly the masked full-S! min for them. The composed
        # permutation sigma_c[i] = t_sigma[sigma[i]] feeds the same
        # direct dynamic hash — no sorted view is ever materialized.
        comp = jnp.zeros(
            (self._t_sigma.shape[0],) + sigma.shape, jnp.int32
        )  # [T, B, S]
        for u in range(S):
            comp = comp + jnp.where(
                sigma[None] == u, self._t_sigma[:, u, None, None], 0
            )
        t_fps = self._hash_dyn(view, comp)  # [T, B]
        t_valid = jnp.all(
            adj_eq[None, :, :] | ~self._t_edge_mask[:, None, :], axis=2
        )  # [T, B]
        fp = jnp.minimum(
            fp, jnp.min(jnp.where(t_valid, t_fps, U64_MAX), axis=0)
        )

        # ---- tie classification for tier 3: a lane is heavy iff some
        # tie group has size >= 3 (a run of 2+ adjacent equalities);
        # its adjacent-equality PATTERN decides the route: every
        # pattern whose admissible block-perm group fits the static
        # per-pattern tables takes the tie-group-local min, the rest
        # (all-tied lanes at S=5) take the full S!-table masked min.
        heavy = jnp.any(adj_eq[:, :-1] & adj_eq[:, 1:], axis=1)
        shifts = jnp.arange(S - 1, dtype=jnp.int32)
        pat = jnp.sum(
            adj_eq.astype(jnp.int32) << shifts[None, :], axis=1
        ).astype(jnp.int32)
        loc = self._p_local[pat]
        return fp, sigma, pat, heavy & loc, heavy & ~loc

    def _tier3_apply(self, view, sig, fp, sigma, pat, is_local, is_full):
        """Resolve the tier-3 lanes of ``_tier_pre``'s classification:
        both buckets drain through fixed-size blocks inside a
        ``lax.while_loop`` whose trip count adapts to the actual heavy
        population of the chunk — no static compaction budget, no
        whole-batch ``lax.cond`` fallback cliff."""
        fp = self._tier3_local(view, fp, sigma, pat, is_local)
        return self._tier3_full(view, fp, sig, is_full)

    def _tier3_local(self, view, fp, sigma, pat, is_local):
        """Tie-group-LOCAL masked min: for a lane whose tie pattern has
        an enumerable admissible group (<= 24 perms at S=5 for every
        non-all-tied heavy pattern), enumerate exactly the block
        permutations of its tied groups composed with the argsort —
        the COMPLETE admissible set, so the result is bit-identical to
        the full-table masked min at a fraction of its cost."""
        B = view.shape[0]
        S = self.S
        LCAP = self._p_tab.shape[1]
        TL = min(B, max(32, B // 16))
        nsel = jnp.sum(is_local)
        lsel = jnp.argsort(~is_local).astype(jnp.int32)  # local lanes first
        lsel = jnp.concatenate([lsel, jnp.full((TL,), B, jnp.int32)])
        viewp = jnp.concatenate([view, jnp.zeros((1, self.VL), view.dtype)])
        sigmap = jnp.concatenate(
            [sigma, jnp.arange(S, dtype=jnp.int32)[None, :]]
        )
        patp = jnp.concatenate([pat, jnp.zeros((1,), jnp.int32)])
        fpp = jnp.concatenate([fp, jnp.zeros((1,), jnp.uint64)])
        jtl = jnp.arange(TL, dtype=jnp.int32)

        def cond(c):
            return c[0] * TL < nsel

        def body(c):
            i, acc = c
            sel = lax.dynamic_slice(lsel, (i * TL,), (TL,))
            # guard the block tail: past nsel the lsel order continues
            # with NON-local lanes, whose pattern tables are incomplete
            sel = jnp.where(i * TL + jtl < nsel, sel, B)
            v = viewp[sel]
            sg = sigmap[sel]
            tbl = jnp.transpose(self._p_tab[patp[sel]], (1, 0, 2))
            msk = jnp.transpose(self._p_mask[patp[sel]], (1, 0))
            # composed[c, b, i] = tbl[c, b, sg[b, i]] — per-lane pattern
            # perms act on SORTED positions, so compose with the argsort
            comp = jnp.zeros((LCAP, TL, S), jnp.int32)
            for u in range(S):
                comp = comp + jnp.where(
                    sg[None] == u, tbl[:, :, u][:, :, None], 0
                )
            h = jnp.where(msk, self._hash_dyn(v, comp), U64_MAX)
            return i + 1, acc.at[sel].set(jnp.min(h, axis=0))

        _, fpp = lax.while_loop(
            cond, body, (jnp.asarray(0, jnp.int32), fpp)
        )
        return fpp[:B]

    def _tier3_full(self, view, fp, sig, is_full):
        """Full S!-table masked min for lanes whose admissible group is
        too large to enumerate locally (the all-tied pattern at S=5:
        near-init states), drained in adaptive fixed-size blocks."""
        B = view.shape[0]
        TF = min(B, max(16, B // 64))
        nsel = jnp.sum(is_full)
        fsel = jnp.argsort(~is_full).astype(jnp.int32)
        fsel = jnp.concatenate([fsel, jnp.full((TF,), B, jnp.int32)])
        viewp = jnp.concatenate([view, jnp.zeros((1, self.VL), view.dtype)])
        sigp = jnp.concatenate([sig, jnp.zeros((1, self.S), sig.dtype)])
        fpp = jnp.concatenate([fp, jnp.zeros((1,), jnp.uint64)])
        jtf = jnp.arange(TF, dtype=jnp.int32)

        def cond(c):
            return c[0] * TF < nsel

        def body(c):
            i, acc = c
            sel = lax.dynamic_slice(fsel, (i * TF,), (TF,))
            sel = jnp.where(i * TF + jtf < nsel, sel, B)
            h = self._masked_min(viewp[sel], sigp[sel])
            return i + 1, acc.at[sel].set(h)

        _, fpp = lax.while_loop(
            cond, body, (jnp.asarray(0, jnp.int32), fpp)
        )
        return fpp[:B]

    # ---------------- raw-keyed canon memoization ----------------

    def raw_fingerprints(self, states):
        """u64 [B] identity-permutation view hashes — the cheap raw key
        the canon memo is indexed by (for symmetry=False this IS the
        canonical fingerprint)."""
        return self._perm_hash(states[:, : self.VL])

    def fingerprints_memo(self, states, valid, memo):
        """Memoized canonical fingerprints of a [B, W] state batch.

        ``memo`` is a [MCAP, 2] u64 direct-mapped table (MCAP a power
        of two): each row holds (raw view hash, canonical fingerprint),
        empty rows keyed U64_MAX. Returns ``(fps, memo, n_hit)`` with
        invalid lanes masked to U64_MAX.

        The miss path first dedups equal raw keys WITHIN the chunk
        (sorted segments, one canon per distinct raw view — duplicate
        successors inside a chunk are common), then drains the
        representatives through the tiered canon in fixed-size blocks
        of an adaptive-trip ``lax.while_loop``: a fully-memoized chunk
        pays one probe, a cold chunk pays one canon per distinct raw
        view. Insertion is always-overwrite, with key+value in ONE
        row-atomic scatter so slot-colliding lanes can never interleave
        one row's key with another's value; an evicted key simply
        recomputes on its next miss. Memoization never changes a value
        — the cached fingerprint was produced by the same tiered canon
        under the same raw view."""
        view = states[:, : self.VL]
        B = view.shape[0]
        memo = jnp.asarray(memo)  # accept host tables (tests, tools)
        raw = self._perm_hash(view)
        if not self.symmetry:
            return (jnp.where(valid, raw, U64_MAX), memo,
                    jnp.asarray(0, jnp.int32))
        MCAP = memo.shape[0]
        slot = memo_slot(raw, MCAP)
        row = memo[slot]  # [B, 2]
        # a raw key equal to the empty sentinel (p = 2^-64) never hits:
        # it recomputes every time rather than aliasing empty rows
        hit = valid & eq_u64(row[:, 0], raw) & ne_u64(raw, U64_MAX)
        need = valid & ~hit
        n_hit = jnp.sum(hit).astype(jnp.int32)

        # in-chunk dedup: sort the missed raw keys, canon only segment
        # heads, forward-fill each segment from its head
        sraw, order = sort_u64_with_idx(jnp.where(need, raw, U64_MAX))
        is_head = jnp.concatenate(
            [jnp.ones((1,), bool), ne_u64(sraw[1:], sraw[:-1])]
        )
        head = is_head & ne_u64(sraw, U64_MAX)
        n_rep = jnp.sum(head)
        CB = min(B, max(64, B // 4))
        psel = jnp.argsort(~head).astype(jnp.int32)  # head positions first
        psel = jnp.concatenate([psel, jnp.full((CB,), B, jnp.int32)])
        orderp = jnp.concatenate([order, jnp.full((1,), B, jnp.int32)])
        viewp = jnp.concatenate([view, jnp.zeros((1, self.VL), view.dtype)])
        canon_sorted = jnp.full((B + 1,), U64_MAX, jnp.uint64)
        jcb = jnp.arange(CB, dtype=jnp.int32)

        def cond(c):
            return c[0] * CB < n_rep

        def body(c):
            i, acc = c
            pos = lax.dynamic_slice(psel, (i * CB,), (CB,))
            pos = jnp.where(i * CB + jcb < n_rep, pos, B)
            cfp = self._canon_view(viewp[orderp[pos]])
            return i + 1, acc.at[pos].set(cfp)

        _, canon_sorted = lax.while_loop(
            cond, body, (jnp.asarray(0, jnp.int32), canon_sorted)
        )
        hidx = lax.associative_scan(
            jnp.maximum,
            jnp.where(is_head, jnp.arange(B, dtype=jnp.int32), 0),
        )
        computed = (
            jnp.zeros((B,), jnp.uint64)
            .at[order]
            .set(canon_sorted[:B][hidx])
        )
        fps = jnp.where(hit, row[:, 1], jnp.where(need, computed, U64_MAX))
        kv = jnp.stack([raw, fps], axis=1)
        memo = memo.at[jnp.where(need, slot, MCAP)].set(kv, mode="drop")
        return fps, memo, n_hit
