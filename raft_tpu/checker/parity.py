"""On-device parity gate — trust-but-verify for the fast path.

The axon TPU compiler has miscompiled fused expansion programs in a
batch-size-dependent way before (a dynamic-index scatter write silently
dropped at chunk>=4096; round-2 verdict Weak #2, fixed in ops/bag.py by
one-hot writes). Counts that are wrong but self-consistent cannot be
caught by any in-run check, so before trusting a long run the driver can
run this gate: explore the same workload to a shallow depth at two chunk
sizes and require bit-identical per-depth counts. A compiler bug of that
class changes results when the batch geometry changes; agreement across
geometries (plus the CPU test suite pinning the same counts) bounds the
risk.

Cost: two shallow BFS runs (seconds); run once per (model, platform).
"""

from __future__ import annotations

from dataclasses import dataclass

from .device_bfs import DeviceBFS


@dataclass
class ParityGateResult:
    ok: bool
    depth: int
    chunks: tuple[int, int]
    counts: tuple[list[int], list[int]]

    def __str__(self):
        s = "PASS" if self.ok else "FAIL"
        return (
            f"parity gate {s}: depth={self.depth} chunks={self.chunks} "
            f"counts={'==' if self.ok else self.counts}"
        )


def parity_gate(
    model=None,
    invariants: tuple[str, ...] = (),
    symmetry: bool = True,
    depth: int = 12,
    chunks: tuple[int, int] = (2048, 4096),
    frontier_cap: int = 1 << 16,
    seen_cap: int = 1 << 20,
    checkers: tuple[DeviceBFS, DeviceBFS] | None = None,
) -> ParityGateResult:
    """Run the workload to `depth` at two chunk geometries; identical
    depth_counts/total/terminal => gate passes.

    Pass prebuilt `checkers` (e.g. to reuse a long run's compiled
    instance as one arm) or let the gate build both from `model`. The
    two arms must have different chunk geometries — identical geometries
    would make the gate vacuous."""
    if checkers is None and model is None:
        raise ValueError("parity_gate requires either `model` or prebuilt `checkers`")
    if checkers is None:
        checkers = tuple(
            DeviceBFS(
                model,
                invariants=invariants,
                symmetry=symmetry,
                chunk=chunk,
                frontier_cap=frontier_cap,
                seen_cap=seen_cap,
                journal_cap=seen_cap,
            )
            for chunk in chunks
        )
    if checkers[0].chunk == checkers[1].chunk:
        raise ValueError(
            f"parity gate arms share chunk={checkers[0].chunk}; the gate "
            "needs two different geometries to mean anything"
        )
    sigs = []
    for checker in checkers:
        res = checker.run(max_depth=depth)
        sigs.append((res.depth_counts, res.total, res.terminal))
    ok = sigs[0] == sigs[1]
    return ParityGateResult(
        ok=ok,
        depth=depth,
        chunks=(checkers[0].chunk, checkers[1].chunk),
        counts=(sigs[0][0], sigs[1][0]),
    )
