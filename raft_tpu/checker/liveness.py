"""Liveness / temporal-property checking under ``WF_vars(Next)``.

The reference defines its liveness formulas against ``LivenessSpec ==
Init /\\ [][Next]_vars /\\ WF_vars(Next)`` (``Raft.tla:545-550``) in two
shapes:

  - ``[]<>P``  — "always eventually P" (``ValuesNotStuck``,
    ``Raft.tla:567-576``; ``ReconfigurationNotStuck``,
    ``KRaftWithReconfig.tla:1837-1839``);
  - ``P ~> Q`` — leads-to (``ReconfigurationCompletes``,
    ``RaftWithReconfigJointConsensus.tla:1039-1054``).

Semantics on a finite fully-explored state graph: a fair behavior under
weak fairness of the full Next is an infinite path (which must eventually
loop) or a behavior that reaches a TERMINAL state (no successors — Next
disabled forever, so stuttering there is fair; ``-deadlock`` semantics,
reference README.md:7). Therefore

  ``P ~> Q`` is violated  iff  some reachable state satisfies P and from
  it there is a Q-avoiding path that can avoid Q forever;
  ``[]<>P``  is the special case ``TRUE ~> P``.

"Can avoid Q forever" is the largest set S of ~Q-states such that every
member is terminal or has a successor in S — computed by iteratively
peeling ~Q-states with no exit (a nu-fixpoint; equivalent to "reaches a
~Q-cycle or ~Q-terminal within the ~Q-subgraph" but needs no SCC
machinery and is trivially iterative). The counterexample is a lasso:
Init-prefix to the P-state, a Q-free path into S, and the Q-free cycle
(or terminal stutter) it sustains.

SYMMETRY note: liveness checking over a symmetry-reduced graph is
unsound in general (TLC refuses the combination); the graph here is
always built with symmetry OFF, whatever the cfg declares.

Model contract: ``model.liveness`` maps property name ->
list of (instance_label, P_kernel_or_None, Q_kernel) — one instance per
quantified value (``\\A v \\in Value``), P = None meaning ``[]<>Q``.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.hashing import hash_lanes


@dataclass
class LivenessViolation:
    prop: str
    instance: str
    prefix: list[tuple[str, dict]]  # Init -> P-state (action label, state)
    cycle: list[tuple[str, dict]]  # the sustained Q-free loop (or terminal)
    terminal: bool  # True: lasso "cycle" is a terminal stutter


@dataclass
class LivenessResult:
    distinct: int
    total_edges: int
    properties: tuple[str, ...]
    violation: LivenessViolation | None
    seconds: float


class LivenessChecker:
    """Explores the FULL graph (host adjacency, symmetry off) and checks
    the model's registered temporal properties. Intended for the small
    bounded configs the reference runs liveness on (``MaxElections = 0``
    guidance, ``RaftWithReconfigAddRemove.tla:988``); the graph must fit
    on the host."""

    def __init__(self, model, properties: tuple[str, ...], chunk: int = 512,
                 max_states: int = 8_000_000):
        from .. import enable_compcache

        enable_compcache()
        self.model = model
        self.properties = tuple(properties)
        self.chunk = chunk
        self.max_states = max_states
        unknown = [p for p in self.properties
                   if p not in getattr(model, "liveness", {})]
        if unknown:
            raise ValueError(
                f"spec {model.name} has no liveness support for: "
                f"{', '.join(unknown)}"
            )
        # FULL-state fingerprints, not the VIEW projection: aux counters
        # gate actions (electionCtr < MaxElections etc.) and the temporal
        # predicates read them, so VIEW-merged nodes would conflate states
        # with different successor structure — unsound for liveness.
        #
        # Collision budget: graph dedup uses one 64-bit hash family, so a
        # fingerprint collision would silently merge two states and could
        # mask a temporal violation (expected collisions ~ n^2/2^65; at
        # the 8M-state default cap that is ~2e-6). Run run(audit_seed=k) to
        # re-explore under a second seeded family and cross-check
        # state/edge counts — a mismatch proves a collision in one family.
        self._fps = jax.jit(lambda v: hash_lanes(v))

    # ---------------- graph construction ----------------

    def _explore(self):
        """Full-graph build, vectorized end-to-end (round-4 verdict
        Next #7 — the per-unique-fingerprint python dict loop previously
        capped practical graphs well under the host's memory):

          - dedup = numpy searchsorted against a sorted (fp, gid) table,
          - device pass A per chunk returns only fingerprints + validity
            (u64/bool lanes — no [B, A, W] state transfer),
          - device pass B re-expands just the chunks that discovered new
            states and gathers exactly those successor vectors.
        """
        model = self.model
        B, W, A = self.chunk, self.model.layout.W, self.model.A
        fps_fn = self._fps
        if getattr(self, "_exp_fps_j", None) is None:
            def _exp_fps(batch):
                succs, valid, _rank, ovf = model.expand(batch)
                flat = succs.reshape(-1, W)
                return fps_fn(flat), valid.reshape(-1), jnp.any(valid & ovf)

            def _exp_sel(batch, lanes):
                succs, _v, _r, _o = model.expand(batch)
                return succs.reshape(-1, W)[lanes]

            self._exp_fps_j = jax.jit(_exp_fps)
            self._exp_sel_j = jax.jit(_exp_sel)

        init = np.asarray(model.init_states())
        fp0 = np.asarray(jax.device_get(fps_fn(init)), dtype=np.uint64)
        _uq, first = np.unique(fp0, return_index=True)
        first.sort()
        init_d = init[first]  # first-occurrence order = gid order
        n = len(init_d)
        state_blocks: list[np.ndarray] = [init_d]
        order0 = np.argsort(fp0[first], kind="stable")
        sorted_fps = fp0[first][order0]
        sorted_gids = order0.astype(np.int64)
        frontier = init_d
        frontier_gids = np.arange(n, dtype=np.int64)
        esrc_l: list[np.ndarray] = []
        edst_l: list[np.ndarray] = []
        ecand_l: list[np.ndarray] = []

        while len(frontier):
            # ---- pass A: fingerprints + validity only ----
            chunk_batches: list[np.ndarray] = []
            chunk_vidx: list[np.ndarray] = []
            wave_srcs: list[np.ndarray] = []
            wave_fps: list[np.ndarray] = []
            for off in range(0, len(frontier), B):
                batch = frontier[off : off + B]
                nb = len(batch)
                if nb < B:
                    batch = np.concatenate(
                        [batch, np.repeat(batch[-1:], B - nb, axis=0)]
                    )
                fps_c, valid_c, ovf_c = jax.device_get(
                    self._exp_fps_j(jnp.asarray(batch))
                )
                valid_c = np.asarray(valid_c).copy()
                valid_c[nb * A:] = False
                if bool(np.asarray(ovf_c)):
                    raise OverflowError(
                        "message-slot overflow during liveness graph build"
                    )
                vidx = np.nonzero(valid_c)[0]
                chunk_batches.append(batch)
                chunk_vidx.append(vidx)
                wave_srcs.append(frontier_gids[off + vidx // A])
                wave_fps.append(np.asarray(fps_c, dtype=np.uint64)[vidx])
            if not chunk_vidx:
                break
            srcs = np.concatenate(wave_srcs)
            cands = np.concatenate(
                [(v % A).astype(np.int32) for v in chunk_vidx]
            )
            fps_w = np.concatenate(wave_fps)
            if len(fps_w) == 0:
                break

            # ---- resolve against the global table ----
            pos = np.searchsorted(sorted_fps, fps_w)
            pos = np.clip(pos, 0, max(0, len(sorted_fps) - 1))
            hit = (
                (sorted_fps[pos] == fps_w)
                if len(sorted_fps) else np.zeros(len(fps_w), bool)
            )
            gid_w = np.where(hit, sorted_gids[pos], -1)
            nf_mask = ~hit
            new_states = np.zeros((0, W), np.int32)
            if nf_mask.any():
                nf = fps_w[nf_mask]
                uq, first_u = np.unique(nf, return_index=True)
                disc = np.argsort(first_u, kind="stable")  # discovery order
                new_count = len(uq)
                if n + new_count > self.max_states:
                    raise OverflowError(
                        "liveness graph exceeds max_states; raise it or "
                        "use a smaller config (liveness needs the full graph)"
                    )
                uq_gids = np.empty(new_count, np.int64)
                uq_gids[disc] = n + np.arange(new_count)
                gid_w[nf_mask] = uq_gids[np.searchsorted(uq, nf)]

                # ---- pass B: fetch exactly the new states' vectors.
                # lanes are padded to power-of-two buckets so jit compiles
                # a handful of shapes, not one per distinct new-count
                # (the remote-compile service costs ~20 s per shape)
                nf_wave_lane = np.nonzero(nf_mask)[0][first_u]  # per uq
                new_states = np.empty((new_count, W), np.int32)
                bounds = np.cumsum([0] + [len(v) for v in chunk_vidx])
                ci = np.searchsorted(bounds, nf_wave_lane, side="right") - 1
                for c in np.unique(ci):
                    sel = np.nonzero(ci == c)[0]  # uq indices in chunk c
                    lanes = chunk_vidx[c][nf_wave_lane[sel] - bounds[c]]
                    k = len(lanes)
                    bucket = 1 << max(5, (k - 1).bit_length())
                    lanes_p = np.zeros(bucket, lanes.dtype)
                    lanes_p[:k] = lanes
                    vecs = np.asarray(jax.device_get(
                        self._exp_sel_j(
                            jnp.asarray(chunk_batches[c]),
                            jnp.asarray(lanes_p),
                        )
                    ))[:k]
                    new_states[uq_gids[sel] - n] = vecs

                state_blocks.append(new_states)
                frontier_gids = n + np.arange(new_count, dtype=np.int64)
                n += new_count
                merged_fps = np.concatenate([sorted_fps, uq])
                merged_gids = np.concatenate([sorted_gids, uq_gids])
                order2 = np.argsort(merged_fps, kind="stable")
                sorted_fps = merged_fps[order2]
                sorted_gids = merged_gids[order2]
            esrc_l.append(srcs)
            edst_l.append(gid_w)
            ecand_l.append(cands)
            frontier = new_states

        self._states = np.concatenate(state_blocks, axis=0)
        self._esrc = np.concatenate(esrc_l) if esrc_l else np.zeros(0, np.int64)
        self._edst = np.concatenate(edst_l) if edst_l else np.zeros(0, np.int64)
        self._ecand = np.concatenate(ecand_l) if ecand_l else np.zeros(0, np.int32)
        self._n_init = len(init)

    def _eval_kernel(self, fn) -> np.ndarray:
        """Batched predicate over all graph states (padded power-of-two
        chunks so jit caches a handful of shapes)."""
        n = len(self._states)
        out = np.zeros(n, dtype=bool)
        B = 1 << min(14, max(8, (self.chunk - 1).bit_length()))
        for off in range(0, n, B):
            part = self._states[off : off + B]
            nb = len(part)
            if nb < B:
                part = np.concatenate([part, np.repeat(part[-1:], B - nb, axis=0)])
            out[off : off + nb] = np.asarray(jax.device_get(fn(part)))[:nb]
        return out

    # ---------------- the nu-fixpoint lasso search ----------------

    def _fwd_adj(self):
        """CSR forward adjacency (edge order, dst-by-src, row starts);
        built once per run and cached."""
        if getattr(self, "_fwd", None) is None:
            n = len(self._states)
            order = np.argsort(self._esrc, kind="stable")
            self._fwd = (
                order,
                self._edst[order],
                np.searchsorted(self._esrc[order], np.arange(n + 1)),
            )
        return self._fwd

    def _sustain_set(self, notq: np.ndarray) -> np.ndarray:
        """Largest S subset of ~Q with: member is terminal (no successors at
        all) or has a successor in S. Incremental peel (round-4 advisor:
        the full per-round recompute was O(rounds*E), quadratic on
        chain-shaped graphs): exit counts are bincounted once, then each
        round only the edges INTO that round's dropped nodes decrement
        their sources — every edge is touched at most once, so the whole
        peel is O(E + rounds*n)."""
        n = len(notq)
        esrc, edst = self._esrc, self._edst
        in_s = notq.copy()
        out_deg = np.bincount(esrc, minlength=n)
        terminal = out_deg == 0
        # reverse CSR (incoming edges by dst) for the incremental rounds
        rev = np.argsort(edst, kind="stable")
        rstart = np.searchsorted(edst[rev], np.arange(n + 1))
        live = in_s[edst] & in_s[esrc]
        exit_count = np.bincount(esrc[live], minlength=n)
        while True:
            drop = in_s & ~terminal & (exit_count == 0)
            dnodes = np.nonzero(drop)[0]
            if not dnodes.size:
                return in_s
            in_s &= ~drop
            # edges into dropped nodes whose src is still a member were
            # all counted (both endpoints were in S) and are dead now
            idx = (
                np.concatenate([rev[rstart[d] : rstart[d + 1]] for d in dnodes])
                if dnodes.size
                else np.empty(0, np.int64)
            )
            srcs = esrc[idx]
            srcs = srcs[in_s[srcs]]
            if srcs.size:
                exit_count -= np.bincount(srcs, minlength=n)

    def _shortest_path(self, from_set: np.ndarray, to_set: np.ndarray):
        """BFS (by gid) from any node in from_set to any node in to_set;
        returns (list of edge indices, target gid), or None."""
        n = len(self._states)
        order, ssorted_dst, sstart = self._fwd_adj()
        prev_edge = np.full(n, -1, np.int64)
        seen = from_set.copy()
        q = list(np.nonzero(seen)[0])
        if any(to_set[g] for g in q):
            g = next(g for g in q if to_set[g])
            return [], int(g)
        qi = 0
        while qi < len(q):
            s = q[qi]
            qi += 1
            for k in range(sstart[s], sstart[s + 1]):
                t = int(ssorted_dst[k])
                if seen[t]:
                    continue
                seen[t] = True
                prev_edge[t] = order[k]
                if to_set[t]:
                    path = []
                    cur = t
                    while prev_edge[cur] >= 0 and not from_set[cur]:
                        path.append(int(prev_edge[cur]))
                        cur = int(self._esrc[prev_edge[cur]])
                    path.reverse()
                    return path, t
                q.append(t)
        return None

    def _decode_path(self, start_gid: int, edge_idxs: list[int]):
        model = self.model
        out = []
        if getattr(self, "_expand1_jit", None) is None:
            self._expand1_jit = jax.jit(model._expand1)  # one cache per checker
        expand1 = self._expand1_jit
        for e in edge_idxs:
            # label via the recorded candidate; re-expand for the rank
            src = int(self._esrc[e])
            cand = int(self._ecand[e])
            succs, valid, rank, _ovf = jax.device_get(
                expand1(self._states[src])
            )
            assert valid[cand]
            out.append(
                (model.action_label(int(rank[cand]), cand),
                 model.decode(np.asarray(self._states[int(self._edst[e])])))
            )
        return out

    # ---------------- driver ----------------

    def run(self, verbose: bool = False,
            audit_seed: int | None = None) -> LivenessResult:
        t0 = time.perf_counter()
        self._explore()
        n = len(self._states)
        if audit_seed is not None:
            if audit_seed == 0:
                # seed 0 IS the primary family (hashing.py): a 0-seed
                # audit would vacuously compare a family against itself
                raise ValueError("audit_seed must be nonzero (seed 0 is "
                                 "the primary fingerprint family)")
            # Two-seed collision audit: rebuild the graph under an
            # independent hash family; a 64-bit collision in either
            # family (merging two distinct states) shifts the
            # state/edge counts with overwhelming probability.
            base = (n, len(self._esrc))
            saved = (self._fps, self._states, self._esrc, self._edst,
                     self._ecand, self._n_init, getattr(self, "_fwd", None),
                     getattr(self, "_exp_fps_j", None))
            self._fps = jax.jit(lambda v: hash_lanes(v, seed=audit_seed))
            self._fwd = self._exp_fps_j = None  # rebuild on the new family
            try:
                try:
                    self._explore()
                except OverflowError as e:
                    # a collision in the PRIMARY family merges states, so
                    # the audit family can see more true states and trip
                    # the cap — that is collision evidence, not a capacity
                    # problem
                    raise RuntimeError(
                        f"liveness collision audit (seed={audit_seed}) "
                        f"overflowed where the primary family did not — "
                        f"likely a fingerprint collision in the primary "
                        f"family merged distinct states ({e})"
                    ) from e
                other = (len(self._states), len(self._esrc))
            finally:
                (self._fps, self._states, self._esrc, self._edst,
                 self._ecand, self._n_init, self._fwd,
                 self._exp_fps_j) = saved
            if other != base:
                raise RuntimeError(
                    f"liveness graph collision audit FAILED: primary family "
                    f"saw {base[0]} states/{base[1]} edges, seed={audit_seed} "
                    f"family saw {other[0]}/{other[1]} — a fingerprint "
                    f"collision merged distinct states in one family"
                )
            if verbose:
                print(f"liveness collision audit (seed={audit_seed}): OK "
                      f"({n} states / {len(self._esrc)} edges both families)")
        if verbose:
            print(f"liveness graph: {n} states, {len(self._esrc)} edges")
        out_deg = np.bincount(self._esrc, minlength=n)
        violation = None
        for prop in self.properties:
            for label, p_fn, q_fn in self.model.liveness[prop]:
                q = self._eval_kernel(q_fn)
                p = (
                    np.ones(n, dtype=bool) if p_fn is None
                    else self._eval_kernel(p_fn)
                )
                sustain = self._sustain_set(~q)
                starts = p & sustain
                if not starts.any():
                    if verbose:
                        print(f"  {prop}[{label}]: OK")
                    continue
                # counterexample lasso
                init_set = np.zeros(n, dtype=bool)
                init_set[: self._n_init] = True
                pre = self._shortest_path(init_set, starts)
                assert pre is not None, "violating state must be reachable"
                pre_edges, s0 = pre
                # inside S: walk to a terminal or until a gid repeats;
                # the walk up to the loop entry is counterexample stem
                walk_edges: list[int] = []
                term = False
                order, ssorted_dst, sstart = self._fwd_adj()
                visited_at: dict[int, int] = {}
                cur = s0
                while True:
                    if out_deg[cur] == 0:
                        term = True
                        stem, loop = walk_edges, []
                        break
                    if cur in visited_at:
                        cut = visited_at[cur]
                        stem, loop = walk_edges[:cut], walk_edges[cut:]
                        break
                    visited_at[cur] = len(walk_edges)
                    nxt = None
                    for k in range(sstart[cur], sstart[cur + 1]):
                        t = int(ssorted_dst[k])
                        if sustain[t]:
                            nxt = (int(order[k]), t)
                            break
                    assert nxt is not None, "sustain set must have an exit"
                    walk_edges.append(nxt[0])
                    cur = nxt[1]
                init_gid = int(self._esrc[pre_edges[0]]) if pre_edges else s0
                prefix = [
                    ("Initial predicate",
                     self.model.decode(np.asarray(self._states[init_gid])))
                ] + self._decode_path(init_gid, pre_edges + stem)
                cycle = self._decode_path(s0, loop)
                violation = LivenessViolation(
                    prop=prop, instance=label, prefix=prefix, cycle=cycle,
                    terminal=term,
                )
                break
            if violation:
                break
        return LivenessResult(
            distinct=n,
            total_edges=len(self._esrc),
            properties=self.properties,
            violation=violation,
            seconds=time.perf_counter() - t0,
        )
