"""Fingerprint-collision audit — bounding the silent-collision risk.

Dedup runs on 64-bit canonical fingerprints (TLC's collision budget). A
hash collision silently MERGES two distinct states: counts drop and the
successors of the swallowed state are never explored, with no in-run
signal (exactly the failure shape of the round-2 axon dedup miscount,
just caused by the hash instead of the compiler). The audit re-runs the
same bounded workload under a SECOND independent hash family (different
splitmix64 seed, ops/hashing.py) and demands bit-identical per-depth
counts: a collision under seed A is astronomically unlikely to have a
matching collision under seed B (probability ~ distinct^2 / 2^64 per
family, independent across families), so agreement bounds the silent-
collision probability at the square of the single-run bound.

Complements checker/parity.py (which varies the BATCH GEOMETRY to catch
compiler miscompiles at a fixed hash); together they cover both silent-
dedup failure classes identified in the round-2 verdict.
"""

from __future__ import annotations

from dataclasses import dataclass

from .device_bfs import DeviceBFS


@dataclass
class AuditResult:
    ok: bool
    depth: int
    seeds: tuple[int, int]
    counts: tuple[list[int], list[int]]
    totals: tuple[int, int]
    terminals: tuple[int, int]

    def __str__(self):
        s = "PASS" if self.ok else "FAIL"
        return (
            f"collision audit {s}: depth={self.depth} seeds={self.seeds} "
            f"counts={'==' if self.ok else self.counts}"
        )


def collision_audit(
    model,
    invariants: tuple[str, ...] = (),
    symmetry: bool = True,
    depth: int = 10,
    seeds: tuple[int, int] = (0, 0x5EED5EED),
    chunk: int = 1024,
    frontier_cap: int | None = None,
    seen_cap: int = 1 << 20,
    journal_cap: int = 1 << 20,
    **caps,
) -> AuditResult:
    """Explore to `depth` under two hash seeds; identical depth_counts/
    total/terminal => audit passes. Extra **caps (max_*_cap) forward to
    DeviceBFS so a CLI-tuned geometry audits at its own sizes."""
    assert seeds[0] != seeds[1], "audit needs two distinct hash families"
    if frontier_cap is None:  # smallest chunk-multiple >= 1<<16
        frontier_cap = ((max(1 << 16, chunk) + chunk - 1) // chunk) * chunk
    runs = []
    for seed in seeds:
        ck = DeviceBFS(
            model, invariants=invariants, symmetry=symmetry, chunk=chunk,
            frontier_cap=frontier_cap, seen_cap=seen_cap,
            journal_cap=journal_cap, fingerprint_seed=seed, **caps,
        )
        runs.append(ck.run(max_depth=depth))
    a, b = runs
    ok = (
        a.depth_counts == b.depth_counts
        and a.total == b.total
        and a.terminal == b.terminal
    )
    return AuditResult(
        ok=ok,
        depth=depth,
        seeds=seeds,
        counts=(a.depth_counts, b.depth_counts),
        totals=(a.total, b.total),
        terminals=(a.terminal, b.terminal),
    )
