"""BFS model-checking driver (single-device v1).

Replaces TLC's exhaustive BFS loop (SURVEY.md §3.1): frontier expansion and
invariant evaluation are batched on device; dedup runs on 64-bit canonical
fingerprints (VIEW + SYMMETRY, ops/symmetry.py) with the seen-set as a
sorted uint64 array merged per wave (vectorized searchsorted — the Pallas
cuckoo set slots in behind the same interface later). `-deadlock` TLC
semantics: terminal states are legitimate, not errors (reference
README.md:7), though we count them.

Trace reconstruction: a parent-pointer journal (global state id, candidate
id) per distinct state; counterexamples replay the action chain from the
initial state (SURVEY.md §5.1).
"""

from __future__ import annotations

import sys
import time
from dataclasses import dataclass, field

import jax
import numpy as np

from ..obs import MemWatch, NULL_TELEMETRY
from ..obs.events import hashv_of
from ..ops.hashing import U64_MAX
from ..ops.symmetry import Canonicalizer
from ..resilience import ckpt as rckpt
from ..resilience.errors import CapacityOverflow


def _in_sorted(sorted_arr: np.ndarray, vals: np.ndarray) -> np.ndarray:
    """Membership mask of vals in a sorted array (vectorized probe)."""
    if not len(sorted_arr):
        return np.zeros(len(vals), dtype=bool)
    pos = np.clip(np.searchsorted(sorted_arr, vals), 0, len(sorted_arr) - 1)
    return sorted_arr[pos] == vals


def _merge_sorted(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Union of two sorted disjoint uint64 arrays, O(len(a)+len(b))-ish."""
    if not len(b):
        return a
    out = np.concatenate([a, b])
    # both halves sorted and disjoint: a stable mergesort exploits the runs
    out.sort(kind="stable")
    return out


class _AppendBuf:
    """Amortized-doubling cursor-append buffer: the host mirror of the
    device engines' contiguous emit (checker/util.py emit_append). Each
    chunk's survivors land at a running cursor in one contiguous copy,
    replacing the per-wave list-of-arrays + concatenate (which held every
    chunk's fragment live and re-walked them all at wave end)."""

    def __init__(self, cols: int | None, dtype):
        self.n = 0
        self._cols = cols
        self._buf = np.empty((0,) if cols is None else (0, cols), dtype)

    def append(self, rows: np.ndarray) -> None:
        need = self.n + len(rows)
        if need > len(self._buf):
            cap = max(1024, len(self._buf))
            while cap < need:
                cap *= 2
            grown = np.empty(
                (cap,) if self._cols is None else (cap, self._cols),
                self._buf.dtype,
            )
            grown[: self.n] = self._buf[: self.n]
            self._buf = grown
        self._buf[self.n : need] = rows
        self.n = need

    @property
    def nbytes(self) -> int:
        """Bytes of REAL rows (the emit-bytes gauge counts written data,
        not the doubling headroom)."""
        return self._buf[: self.n].nbytes

    def take(self) -> np.ndarray:
        """The real rows as an owning array (drops the headroom, so a
        wave's frontier does not pin the oversized append buffer)."""
        return self._buf[: self.n].copy()


@dataclass
class Violation:
    invariant: str
    global_id: int
    depth: int


@dataclass
class CheckResult:
    distinct: int
    total: int
    depth: int  # BFS diameter reached
    depth_counts: list[int]
    violation: Violation | None
    terminal: int  # states with no successors (reported under -deadlock)
    seconds: float
    states_per_sec: float
    exhausted: bool = True  # False if stopped by max_depth/time budget
    trace: list[tuple[str, dict]] | None = None  # (action label, decoded state)
    metrics: list[dict] | None = None  # per-wave metrics (SURVEY.md §5.5)
    # per-action [enabled, fired, new-distinct] in ACTION_NAMES rank
    # order (TLC -coverage analog); None for models without the
    # rank/name contract
    coverage: list[list[int]] | None = None
    # why the run ended (obs.events.EXIT_CAUSES vocabulary); the CLI
    # maps "preempted" to exit code 4
    exit_cause: str | None = None


class BFSChecker:
    def __init__(
        self,
        model,
        invariants: tuple[str, ...] = (),
        symmetry: bool = True,
        chunk: int = 1024,
        check_deadlock: bool = False,
    ):
        # constructor kwargs, for _rebuild (supervisor growth overrides)
        self._ctor_kw = {k: v for k, v in locals().items() if k != "self"}
        self.model = model
        self.invariants = tuple(invariants)
        self.chunk = chunk
        self.check_deadlock = check_deadlock
        self.n_actions = len(getattr(model, "ACTION_NAMES", ()))
        self.canon = Canonicalizer.for_model(model, symmetry=symmetry)
        self._expand = model.expand  # dense path (trace reconstruction)
        # guard-first sparse expansion (SparseExpandMixin models): the
        # wave loop runs the cheap guard pass over the dense [chunk, A]
        # grid and constructs successor rows only for the enabled lanes
        # (model.host_apply); legacy/custom models keep the dense path
        self._sparse = hasattr(model, "host_apply")
        self._guards = (
            jax.jit(jax.vmap(model.guards1)) if self._sparse else None
        )
        self._fps = self.canon.fingerprints
        # journal: per distinct state (beyond init): parent global id + candidate
        self._parents: list[np.ndarray] = []
        self._cands: list[np.ndarray] = []

    # ---------------- main loop ----------------

    def run(
        self,
        max_depth: int | None = None,
        verbose: bool = False,
        time_budget_s: float | None = None,
        collect_metrics: bool = False,
        checkpoint_path: str | None = None,
        checkpoint_every_s: float = 300.0,
        checkpoint_keep: int = rckpt.DEFAULT_KEEP,
        resume: str | None = None,
        telemetry=None,
        preempt=None,
        chaos=None,
    ) -> CheckResult:
        model = self.model
        B = self.chunk
        t0 = time.perf_counter()
        exhausted = True
        exit_cause = None
        tel = telemetry if telemetry is not None else NULL_TELEMETRY
        self._ckpt_keep = checkpoint_keep
        self._chaos = chaos

        init = model.init_states()
        n0 = len(init)
        init_fps = np.asarray(jax.device_get(self._fps(init)), dtype=np.uint64)
        order = np.argsort(init_fps, kind="stable")
        keep = np.ones(len(order), dtype=bool)  # dedup inits (all distinct normally)
        sorted_fps = init_fps[order]
        dup = np.zeros(len(order), dtype=bool)
        dup[1:] = sorted_fps[1:] == sorted_fps[:-1]
        keep[order[dup]] = False
        frontier = init[keep]
        self._init_distinct = frontier  # gid 0..k-1 (post-dedup numbering)
        seen = np.sort(init_fps[keep])

        total = n0
        distinct = len(frontier)
        depth_counts = [distinct]
        terminal = 0
        violation = None
        K = self.n_actions
        cov = np.zeros((K, 3), dtype=np.int64)  # [enabled, fired, new]/rank
        depth = 0
        base_gid = 0  # global id of first state in current frontier
        next_gid = distinct

        ck_gen = 0
        ck_skipped: list[str] = []
        if resume is not None:
            # wave-boundary snapshot: the gid numbering below the saved
            # frontier is deterministic from the model, so only the
            # explored state (frontier/seen/journal/counters) reloads
            ck, ck_gen, ck_skipped = rckpt.load_npz(
                resume, keep=checkpoint_keep
            )
            rckpt.check_spec(ck, self._ckpt_ident(), resume)
            frontier = np.asarray(ck["frontier"], dtype=np.int32)
            seen = np.asarray(ck["seen"], dtype=np.uint64)
            self._parents = [np.asarray(ck["parents"], dtype=np.int64)]
            self._cands = [np.asarray(ck["cands"], dtype=np.int32)]
            distinct = int(ck["distinct"])
            total = int(ck["total"])
            terminal = int(ck["terminal"])
            depth = int(ck["depth"])
            base_gid = int(ck["base_gid"])
            next_gid = int(ck["next_gid"])
            depth_counts = list(int(x) for x in ck["depth_counts"])
            # coverage joined the format after version 1 shipped; older
            # files resume with zeroed counters
            cov = (
                np.asarray(ck["coverage"], dtype=np.int64)
                if "coverage" in ck
                else np.zeros((K, 3), dtype=np.int64)
            )
        else:
            viol = self._check_invariants(frontier, 0, 0)
            if viol is not None:
                violation = viol

        tel.open_run(self._telemetry_manifest())
        if resume is not None:
            if ck_skipped:
                tel.event(
                    "ckpt_generation", path=resume, generation=ck_gen,
                    skipped=list(ck_skipped),
                )
            tel.event(
                "resume", path=resume, generation=ck_gen, depth=depth,
                distinct=distinct,
            )
        metrics: list[dict] | None = [] if collect_metrics else None
        last_ckpt = time.perf_counter()
        # wave-timeline observatory: the host engine's stages are the
        # numpy phases the chunk loop already runs in sequence, so the
        # "sampled" split costs only perf_counter brackets — the wave
        # math is untouched and trivially bit-identical to an unsampled
        # run. device_s counts the jax-facing sections (expand/guards
        # dispatch + fetches, fingerprinting); dedup/emit/merge are host
        # bookkeeping and land in host_s.
        tl_every = int(getattr(tel, "timeline_every", 0) or 0)
        tl_wave_s: list[float] = []
        fused_wave_s: list[float] = []
        memwatch = MemWatch(tel) if tel.active else None
        tel_s_last = 0.0
        while len(frontier) and violation is None:
            if preempt is not None and preempt.requested:
                exhausted = False
                exit_cause = "preempted"
                tel.event(
                    "preempt", signame=preempt.signame, depth=depth,
                    checkpoint=checkpoint_path,
                )
                break
            if chaos is not None:
                chaos.wave_start(depth + 1)
                inj = chaos.ovf_bits(0, depth + 1, 4)
                if inj:
                    # the host engine has no fixed frontier buffer, so a
                    # spurious overflow still aborts at wave-start state
                    # (the supervisor rebuilds with empty growth and
                    # resumes) — exercising the same recovery path the
                    # device engines take
                    if checkpoint_path is not None:
                        self._save_checkpoint(
                            checkpoint_path, frontier, seen, distinct,
                            total, terminal, depth, base_gid, next_gid,
                            depth_counts, cov,
                        )
                    raise CapacityOverflow(
                        "injected frontier overflow (chaos)",
                        what=("frontier",), bits=int(inj),
                        checkpoint_saved=checkpoint_path is not None,
                    )
            if max_depth is not None and depth >= max_depth:
                exhausted = False
                exit_cause = "max_depth"
                break
            if time_budget_s is not None and time.perf_counter() - t0 > time_budget_s:
                exhausted = False
                exit_cause = "time_budget"
                break
            tw = time.perf_counter()
            tl_sample = tl_every > 0 and (depth + 1) % tl_every == 0
            stage_s = {
                "expand": 0.0, "canon": 0.0, "dedup": 0.0, "emit": 0.0,
                "seen_merge": 0.0, "checkpoint": 0.0,
            }
            dev_s = 0.0
            # contiguous cursor-append emit (mirrors the device engines'
            # emit_append): survivors append at a running cursor
            wave_sb = _AppendBuf(model.layout.W, np.int32)
            wave_pb = _AppendBuf(None, np.int64)
            wave_cb = _AppendBuf(None, np.int32)
            # fingerprints first discovered this wave; kept separate from the
            # (much larger) global seen-set so per-chunk dedup only re-sorts
            # wave-sized arrays
            wave_fps = np.empty(0, dtype=np.uint64)
            n_cand_total = 0
            wave_extra = 0  # host apply blocks past one per chunk
            has_succ = np.zeros(len(frontier), dtype=bool)
            with tel.wave_annotation(depth + 1):
                for off in range(0, len(frontier), B):
                    t_exp = time.perf_counter()
                    chunk_states = frontier[off : off + B]
                    nb = len(chunk_states)
                    if nb < B:  # pad to the compiled batch shape
                        pad = np.repeat(chunk_states[-1:], B - nb, axis=0)
                        chunk_states = np.concatenate([chunk_states, pad], axis=0)
                    if self._sparse:
                        # guard pass only: no [B*A, W] successor rows
                        valid, rank, ovf = (
                            np.array(x)
                            for x in jax.device_get(
                                self._guards(chunk_states)
                            )
                        )
                    else:
                        succs, valid, rank, ovf = self._expand(chunk_states)
                        # one fetch for the three per-lane outputs (rank
                        # now feeds the coverage accumulator)
                        valid, rank, ovf = (
                            np.array(x)
                            for x in jax.device_get((valid, rank, ovf))
                        )
                    dev_s += time.perf_counter() - t_exp
                    valid[nb:] = False
                    if np.any(valid & ovf):
                        raise CapacityOverflow(
                            "message-slot overflow: re-run with a larger msg_slots",
                            what=("msg",), bits=1,
                        )
                    if K:
                        # numpy mirror of DeviceBFS._chunk_step 4b:
                        # invalid lanes route to drop bucket K
                        rk = np.where(valid, rank, K)
                        flat_rk = rk.reshape(-1)
                        cov[:, 1] += np.bincount(flat_rk, minlength=K + 1)[:K]
                        hit = np.zeros((len(valid), K + 1), dtype=bool)
                        hit[np.arange(len(valid))[:, None], rk] = True
                        cov[:, 0] += hit[:, :K].sum(axis=0)
                    t_can = time.perf_counter()
                    stage_s["expand"] += t_can - t_exp
                    if self._sparse:
                        # apply pass: construct rows for the enabled
                        # lanes only, then fan their fingerprints back
                        # out to flat-lane indexing so dedup, journal
                        # and coverage below are shared verbatim with
                        # the dense path (bit-identical)
                        en_idx = np.nonzero(valid.reshape(-1))[0]
                        rows, extra = model.host_apply(
                            np.asarray(chunk_states), en_idx
                        )
                        wave_extra += extra
                        fps = np.full(
                            B * model.A, U64_MAX, dtype=np.uint64
                        )
                        if len(en_idx):
                            fps[en_idx] = self._fps_rows(rows)
                    else:
                        flat = succs.reshape(-1, model.layout.W)
                        fps = np.array(
                            jax.device_get(self._fps(flat)),
                            dtype=np.uint64,
                        )
                        fps[~valid.reshape(-1)] = U64_MAX
                    t_dd = time.perf_counter()
                    # the apply+fingerprint section mirrors the device
                    # program's canon stage, so it counts as device-facing
                    # time even on the sparse (host_apply) path
                    stage_s["canon"] += t_dd - t_can
                    dev_s += t_dd - t_can
                    n_cand_total += int(valid.sum())
                    has_succ[off : off + nb] = valid[:nb].any(axis=1)

                    # first-occurrence-in-order selection of unseen fingerprints
                    new_mask = fps != U64_MAX
                    new_mask &= ~_in_sorted(seen, fps)
                    new_mask &= ~_in_sorted(wave_fps, fps)
                    # in-chunk dedup, keeping first occurrence
                    _, first_idx = np.unique(fps, return_index=True)
                    first = np.zeros(len(fps), dtype=bool)
                    first[first_idx] = True
                    new_mask &= first
                    idx = np.nonzero(new_mask)[0]
                    if K:
                        cov[:, 2] += np.bincount(
                            flat_rk[idx], minlength=K + 1)[:K]
                    t_em = time.perf_counter()
                    stage_s["dedup"] += t_em - t_dd
                    if len(idx):
                        if self._sparse:
                            # idx lanes are all enabled (U64_MAX-masked
                            # lanes never survive new_mask), so each has
                            # a row in the compact apply output
                            sel = rows[np.searchsorted(en_idx, idx)]
                        else:
                            sel = np.asarray(jax.device_get(flat[idx]))
                        wave_sb.append(sel)
                        wave_pb.append(base_gid + off + idx // model.A)
                        wave_cb.append((idx % model.A).astype(np.int32))
                        wave_fps = np.sort(np.concatenate([wave_fps, fps[idx]]))
                    stage_s["emit"] += time.perf_counter() - t_em

            total += n_cand_total
            terminal += int((~has_succ).sum())
            if wave_sb.n == 0:
                exit_cause = "exhausted"
                break
            emit_bytes = wave_sb.nbytes + wave_pb.nbytes + wave_cb.nbytes
            wave_states = wave_sb.take()
            wave_parents = wave_pb.take()
            wave_cands = wave_cb.take()
            self._parents.append(wave_parents)
            self._cands.append(wave_cands)
            t_sm = time.perf_counter()
            with tel.annotate("seen_merge"):
                seen = _merge_sorted(seen, wave_fps)
            stage_s["seen_merge"] += time.perf_counter() - t_sm
            depth += 1
            depth_counts.append(len(wave_states))
            violation = self._check_invariants(wave_states, next_gid, depth)
            base_gid = next_gid
            next_gid += len(wave_states)
            distinct += len(wave_states)
            prev_frontier = len(frontier)
            frontier = wave_states
            ckpt_s = 0.0
            if (
                checkpoint_path is not None
                and violation is None  # a saved file must not mask a violation
                and time.perf_counter() - last_ckpt > checkpoint_every_s
            ):
                t_ck = time.perf_counter()
                self._save_checkpoint(
                    checkpoint_path, frontier, seen, distinct, total,
                    terminal, depth, base_gid, next_gid, depth_counts, cov,
                )
                last_ckpt = time.perf_counter()
                ckpt_s = last_ckpt - t_ck
                stage_s["checkpoint"] += ckpt_s
            wave_s_val = time.perf_counter() - tw
            if tl_every:
                (tl_wave_s if tl_sample else fused_wave_s).append(wave_s_val)
            if tel.active or metrics is not None or verbose:
                el = time.perf_counter() - t0
                hbm_frac = None
                if memwatch is not None:
                    # host-RAM analog of the device engines' HBM model:
                    # the live working set is the frontier, the sorted
                    # seen array, the parent/candidate journal and this
                    # wave's emit block
                    frac = memwatch.update(depth, depth, {
                        "frontier": int(frontier.nbytes),
                        "seen": int(seen.nbytes),
                        "journal": int(
                            sum(p.nbytes for p in self._parents)
                            + sum(c.nbytes for c in self._cands)
                        ),
                        "wave_emit": int(emit_bytes),
                    })
                    hbm_frac = round(frac, 6)
                wm = {
                    "depth": depth,
                    "frontier": prev_frontier,
                    "new": len(wave_states),
                    "distinct": distinct,
                    "generated": n_cand_total,
                    "generated_total": total,
                    "terminal": terminal,
                    "dedup_hit_rate": round(
                        1.0 - len(wave_states) / max(1, n_cand_total), 4),
                    # the host engine has no canon memo; the declared keys
                    # still appear so one consumer reads all three engines
                    "canon_memo_hits": 0,
                    "canon_memo_hit_rate": 0.0,
                    "overflow_bits": 0,
                    "lsm_runs": 1,
                    "lsm_lanes": int(len(seen)),
                    # emit gauges (round 6): rows/bytes the cursor-append
                    # emit wrote this wave; the host engine has no fixed-
                    # capacity frontier buffer, so fill is reported as 0
                    "emit_rows": len(wave_states),
                    "emit_bytes": emit_bytes,
                    "frontier_fill": 0.0,
                    # sparse-expand gauges: enabled fraction of the
                    # dense candidate grid this wave, and how many
                    # extra fixed-size apply blocks the host path ran
                    # beyond one per chunk (the host analog of the
                    # device engines' budget-overflow bit — it loops
                    # instead of aborting)
                    "enabled_density": round(
                        n_cand_total / max(1, prev_frontier * model.A), 4
                    ),
                    "expand_budget_ovf": wave_extra,
                    "wave_s": round(wave_s_val, 3),
                    "elapsed_s": round(el, 3),
                    "distinct_per_s": round(distinct / el, 1),
                    "device_s": round(dev_s, 4),
                    "host_s": round(
                        max(0.0, wave_s_val - dev_s - ckpt_s), 4),
                    "ckpt_s": round(ckpt_s, 4),
                    "tel_s": round(tel_s_last, 4),
                    "exchange_share": None,
                    "hbm_frac": hbm_frac,
                }
                t_tel = time.perf_counter()
                tel.wave(wm)
                if tel.active:
                    tel.coverage(self._coverage_fields(
                        depth, cov, len(seen), depth_counts))
                    if tl_sample:
                        tel.event(
                            "timeline", wave=depth, depth=depth,
                            every=tl_every,
                            stages={
                                k: round(v, 5)
                                for k, v in stage_s.items() if v > 0
                            },
                            wave_s=round(wave_s_val, 4),
                        )
                if metrics is not None:
                    metrics.append(wm)
                if verbose:
                    print(
                        f"depth {depth}: frontier {len(wave_states)}, "
                        f"distinct {distinct}, total {total}, "
                        f"{distinct/el:.0f} distinct/s",
                        file=sys.stderr,
                    )
                tel_s_last = time.perf_counter() - t_tel

        if checkpoint_path is not None and violation is None and not exhausted:
            # budget/depth/preemption exit at a wave boundary: save a
            # final resumable snapshot (the periodic timer alone can
            # leave no checkpoint at all on short-budget runs)
            self._save_checkpoint(
                checkpoint_path, frontier, seen, distinct, total,
                terminal, depth, base_gid, next_gid, depth_counts, cov,
            )

        dt = time.perf_counter() - t0
        if violation is not None:
            exit_cause = "violation"
        elif exit_cause is None:
            exit_cause = "exhausted"
        if tel.active:
            tel.coverage(
                self._coverage_fields(depth, cov, len(seen), depth_counts),
                final=True,
            )
        tl_extras = {}
        if tl_every:
            mt = sum(tl_wave_s) / len(tl_wave_s) if tl_wave_s else None
            mf = (
                sum(fused_wave_s) / len(fused_wave_s)
                if fused_wave_s else None
            )
            tl_extras = {
                "timeline_every": tl_every,
                "timeline_waves": len(tl_wave_s),
                # per-wave extra cost of sampling, amortized over the
                # stride (the host engine's stages are the same numpy
                # code either way, so this should hover near zero)
                "timeline_overhead": round((mt - mf) / (mf * tl_every), 4)
                if mt is not None and mf else None,
            }
        tel.close_run({
            "engine": "host",
            "ident": self._ckpt_ident(),
            "exit_cause": exit_cause,
            "violation": violation.invariant if violation else None,
            "distinct": distinct,
            "total": total,
            "depth": depth,
            "terminal": terminal,
            "seconds": round(dt, 3),
            "distinct_per_s": round(distinct / dt, 1) if dt > 0 else 0.0,
            "exhausted": exhausted and violation is None,
            "peak_frontier_cap": int(max(depth_counts)),
            "peak_journal_cap": int(next_gid - len(self._init_distinct)),
            "seen_lanes": int(len(seen)),
            "canon_memo_hit_rate": 0.0,
            **tl_extras,
            **(memwatch.summary_fields() if memwatch is not None else {}),
        })
        trace = self.reconstruct_trace(violation) if violation else None
        return CheckResult(
            distinct=distinct,
            total=total,
            depth=depth,
            depth_counts=depth_counts,
            violation=violation,
            terminal=terminal,
            seconds=dt,
            states_per_sec=distinct / dt if dt > 0 else 0.0,
            exhausted=exhausted and violation is None,
            trace=trace,
            metrics=metrics,
            coverage=[[int(x) for x in row] for row in cov] if K else None,
            exit_cause=exit_cause,
        )

    # ---------------- fleet (packed co-resident jobs) ----------------

    def run_fleet(
        self,
        job_names: list[str] | None = None,
        max_depth: int | None = None,
        verbose: bool = False,
        time_budget_s: float | None = None,
        telemetry=None,
    ) -> list[CheckResult]:
        """Run every job of a fleet-bound model (models/base.py
        FleetConstMixin) through ONE shared BFS: all jobs' stamped init
        states live in one frontier / seen-set / journal, and the job
        lane keeps their fingerprints disjoint.

        Per-job tallies (distinct/total/terminal/coverage/depth_counts)
        are split out of the shared wave with bincounts on the job lane;
        a job that violates an invariant has its rows masked from the
        next frontier, so finished jobs idle at zero cost while the
        rest keep exploring. Because the frontier stays job-major and
        first-occurrence dedup is fingerprint-value-independent, every
        job's emitted state sequence — and therefore its distinct
        count, depth histogram and counterexample trace — is
        bit-identical to a serial ``run()`` of that job (pinned by
        tests/test_fleet.py). ``seconds`` on each result is the GROUP
        wall time: co-resident jobs do not have separable clocks.

        ``max_depth``/``time_budget_s`` are fleet-global (a per-job
        depth limit would desynchronize the shared wave). Checkpointing
        is not multiplexed on this arm — the driver re-runs a packed
        group on resume (fleet/driver.py); the queue arm has per-job
        lineages.
        """
        model = self.model
        B = self.chunk
        J = model.fleet_jobs
        if J == 0:
            raise ValueError("run_fleet needs a fleet-bound model (fleet_bind)")
        names = list(job_names) if job_names else [f"job{j}" for j in range(J)]
        if len(names) != J:
            raise ValueError(f"{len(names)} job names for {J} jobs")
        tel = telemetry if telemetry is not None else NULL_TELEMETRY
        t0 = time.perf_counter()
        K = self.n_actions

        model.fleet_select(None)
        init = model.init_states()
        init_jobs = model.fleet_job_of(init).astype(np.int64)
        n0_by_job = np.bincount(init_jobs, minlength=J).astype(np.int64)
        init_fps = np.asarray(jax.device_get(self._fps(init)), dtype=np.uint64)
        order = np.argsort(init_fps, kind="stable")
        keep = np.ones(len(order), dtype=bool)
        sorted_fps = init_fps[order]
        dup = np.zeros(len(order), dtype=bool)
        dup[1:] = sorted_fps[1:] == sorted_fps[:-1]
        keep[order[dup]] = False
        frontier = init[keep]
        fjobs = init_jobs[keep]
        fgids = np.arange(len(frontier), dtype=np.int64)
        self._init_distinct = frontier
        self._parents, self._cands = [], []  # fleet-global gid journal
        seen = np.sort(init_fps[keep])

        total_j = n0_by_job.copy()
        distinct_j = np.bincount(fjobs, minlength=J).astype(np.int64)
        depth_counts_j = [[int(x)] for x in distinct_j]
        terminal_j = np.zeros(J, np.int64)
        depth_j = np.zeros(J, np.int64)
        violation_j: list[Violation | None] = [None] * J
        cov_j = np.zeros((J, K, 3), dtype=np.int64)
        active = np.ones(J, dtype=bool)
        depth = 0
        next_gid = len(frontier)
        exit_cause_global = None

        tel.open_run({**self._telemetry_manifest(), "fleet_jobs": J})

        self._fleet_check_invariants(
            frontier, fgids, fjobs, 0, violation_j, active
        )
        if not active.all():
            m = active[fjobs]
            frontier, fjobs, fgids = frontier[m], fjobs[m], fgids[m]

        while len(frontier):
            if max_depth is not None and depth >= max_depth:
                exit_cause_global = "max_depth"
                break
            if (
                time_budget_s is not None
                and time.perf_counter() - t0 > time_budget_s
            ):
                exit_cause_global = "time_budget"
                break
            tw = time.perf_counter()
            wave_sb = _AppendBuf(model.layout.W, np.int32)
            wave_pb = _AppendBuf(None, np.int64)
            wave_cb = _AppendBuf(None, np.int32)
            wave_jb = _AppendBuf(None, np.int64)
            wave_fps = np.empty(0, dtype=np.uint64)
            cand_by_job = np.zeros(J, np.int64)
            has_succ = np.zeros(len(frontier), dtype=bool)
            with tel.wave_annotation(depth + 1):
                for off in range(0, len(frontier), B):
                    chunk_states = frontier[off : off + B]
                    nb = len(chunk_states)
                    jrows = fjobs[off : off + nb]
                    if nb < B:
                        pad = np.repeat(chunk_states[-1:], B - nb, axis=0)
                        chunk_states = np.concatenate(
                            [chunk_states, pad], axis=0
                        )
                        jrows_p = np.concatenate(
                            [jrows, np.repeat(jrows[-1:], B - nb)]
                        )
                    else:
                        jrows_p = jrows
                    if self._sparse:
                        valid, rank, ovf = (
                            np.array(x)
                            for x in jax.device_get(
                                self._guards(chunk_states)
                            )
                        )
                    else:
                        succs, valid, rank, ovf = self._expand(chunk_states)
                        valid, rank, ovf = (
                            np.array(x)
                            for x in jax.device_get((valid, rank, ovf))
                        )
                    valid[nb:] = False
                    if np.any(valid & ovf):
                        raise CapacityOverflow(
                            "message-slot overflow: re-run with a larger msg_slots",
                            what=("msg",), bits=1,
                        )
                    jobs_flat = np.repeat(jrows_p, model.A)
                    if K:
                        # per-job composite bincount: job * (K+1) + rank,
                        # with invalid lanes in each job's drop bucket
                        rk = np.where(valid, rank, K)
                        flat_rk = rk.reshape(-1)
                        cnts = np.bincount(
                            jobs_flat * (K + 1) + flat_rk,
                            minlength=J * (K + 1),
                        ).reshape(J, K + 1)
                        cov_j[:, :, 1] += cnts[:, :K]
                        hit = np.zeros((len(valid), K + 1), dtype=bool)
                        hit[np.arange(len(valid))[:, None], rk] = True
                        np.add.at(cov_j[:, :, 0], jrows_p, hit[:, :K])
                    if self._sparse:
                        en_idx = np.nonzero(valid.reshape(-1))[0]
                        rows, _extra = model.host_apply(
                            np.asarray(chunk_states), en_idx
                        )
                        fps = np.full(
                            B * model.A, U64_MAX, dtype=np.uint64
                        )
                        if len(en_idx):
                            fps[en_idx] = self._fps_rows(rows)
                    else:
                        flat = succs.reshape(-1, model.layout.W)
                        fps = np.array(
                            jax.device_get(self._fps(flat)),
                            dtype=np.uint64,
                        )
                        fps[~valid.reshape(-1)] = U64_MAX
                    cand_by_job += np.bincount(
                        jrows, weights=valid[:nb].sum(axis=1),
                        minlength=J,
                    ).astype(np.int64)
                    has_succ[off : off + nb] = valid[:nb].any(axis=1)

                    new_mask = fps != U64_MAX
                    new_mask &= ~_in_sorted(seen, fps)
                    new_mask &= ~_in_sorted(wave_fps, fps)
                    _, first_idx = np.unique(fps, return_index=True)
                    first = np.zeros(len(fps), dtype=bool)
                    first[first_idx] = True
                    new_mask &= first
                    idx = np.nonzero(new_mask)[0]
                    if K and len(idx):
                        cov_j[:, :, 2] += np.bincount(
                            jobs_flat[idx] * (K + 1) + flat_rk[idx],
                            minlength=J * (K + 1),
                        ).reshape(J, K + 1)[:, :K]
                    if len(idx):
                        if self._sparse:
                            sel = rows[np.searchsorted(en_idx, idx)]
                        else:
                            sel = np.asarray(jax.device_get(flat[idx]))
                        wave_sb.append(sel)
                        # parents carry explicit fleet-global gids: the
                        # serial engine's base_gid+offset arithmetic
                        # assumes a contiguous frontier, which per-job
                        # masking breaks
                        wave_pb.append(fgids[off + idx // model.A])
                        wave_cb.append((idx % model.A).astype(np.int32))
                        wave_jb.append(jobs_flat[idx])
                        wave_fps = np.sort(
                            np.concatenate([wave_fps, fps[idx]])
                        )

            total_j += cand_by_job
            terminal_j += np.bincount(fjobs[~has_succ], minlength=J)
            if wave_sb.n == 0:
                break
            wave_states = wave_sb.take()
            wave_parents = wave_pb.take()
            wave_cands = wave_cb.take()
            wave_jobs = wave_jb.take()
            self._parents.append(wave_parents)
            self._cands.append(wave_cands)
            with tel.annotate("seen_merge"):
                seen = _merge_sorted(seen, wave_fps)
            depth += 1
            new_by_job = np.bincount(wave_jobs, minlength=J)
            for j in range(J):
                if new_by_job[j]:
                    depth_j[j] = depth
                    depth_counts_j[j].append(int(new_by_job[j]))
            distinct_j += new_by_job
            wave_gids = next_gid + np.arange(len(wave_states), dtype=np.int64)
            next_gid += len(wave_states)
            self._fleet_check_invariants(
                wave_states, wave_gids, wave_jobs, depth, violation_j, active
            )
            prev_frontier = len(frontier)
            frontier, fjobs, fgids = wave_states, wave_jobs, wave_gids
            if not active.all():
                m = active[fjobs]
                frontier, fjobs, fgids = frontier[m], fjobs[m], fgids[m]
            if tel.active or verbose:
                el = time.perf_counter() - t0
                distinct = int(distinct_j.sum())
                total = int(total_j.sum())
                n_cand_total = int(cand_by_job.sum())
                tel.wave({
                    "depth": depth,
                    "frontier": prev_frontier,
                    "new": len(wave_states),
                    "distinct": distinct,
                    "generated": n_cand_total,
                    "generated_total": total,
                    "terminal": int(terminal_j.sum()),
                    "dedup_hit_rate": round(
                        1.0 - len(wave_states) / max(1, n_cand_total), 4),
                    "canon_memo_hits": 0,
                    "canon_memo_hit_rate": 0.0,
                    "overflow_bits": 0,
                    "lsm_runs": 1,
                    "lsm_lanes": int(len(seen)),
                    "emit_rows": len(wave_states),
                    "emit_bytes": wave_sb.nbytes + wave_pb.nbytes
                    + wave_cb.nbytes,
                    "frontier_fill": 0.0,
                    "enabled_density": round(
                        n_cand_total / max(1, prev_frontier * model.A), 4
                    ),
                    "expand_budget_ovf": 0,
                    "wave_s": round(time.perf_counter() - tw, 3),
                    "elapsed_s": round(el, 3),
                    "distinct_per_s": round(distinct / el, 1),
                    # packed-fleet waves are not phase-split (the shared
                    # group run is throughput-oriented); the declared
                    # observatory keys still appear so one consumer
                    # reads every engine's stream
                    "device_s": 0.0,
                    "host_s": round(time.perf_counter() - tw, 4),
                    "ckpt_s": 0.0,
                    "tel_s": 0.0,
                    "exchange_share": None,
                    "hbm_frac": None,
                    "jobs_active": int(active.sum()),
                })
                if verbose:
                    print(
                        f"fleet depth {depth}: frontier {len(frontier)}, "
                        f"distinct {distinct}, {int(active.sum())}/{J} "
                        f"jobs active",
                        file=sys.stderr,
                    )

        dt = time.perf_counter() - t0
        frontier_jobs = set(int(j) for j in fjobs) if len(frontier) else set()
        results: list[CheckResult] = []
        for j in range(J):
            viol = violation_j[j]
            if viol is not None:
                cause = "violation"
            elif exit_cause_global is not None and j in frontier_jobs:
                cause = exit_cause_global
            else:
                cause = "exhausted"
            exhausted_j = cause == "exhausted"
            results.append(CheckResult(
                distinct=int(distinct_j[j]),
                total=int(total_j[j]),
                depth=int(depth_j[j]),
                depth_counts=depth_counts_j[j],
                violation=viol,
                terminal=int(terminal_j[j]),
                seconds=dt,  # group wall time: jobs are co-resident
                states_per_sec=int(distinct_j[j]) / dt if dt > 0 else 0.0,
                exhausted=exhausted_j,
                trace=self.reconstruct_trace(viol) if viol else None,
                metrics=None,
                coverage=[[int(x) for x in row] for row in cov_j[j]]
                if K else None,
                exit_cause=cause,
            ))

        if tel.active:
            tel.coverage(
                self._coverage_fields(
                    depth, cov_j.sum(axis=0), len(seen),
                    [int(x) for x in np.sum(
                        [np.pad(np.asarray(dc), (0, depth + 1 - len(dc)))
                         for dc in depth_counts_j], axis=0)],
                ),
                final=True,
            )
        first_viol = next((v for v in violation_j if v is not None), None)
        tel.close_run({
            "engine": "host",
            "ident": self._ckpt_ident(),
            "exit_cause": "violation" if first_viol is not None
            else (exit_cause_global or "exhausted"),
            "violation": first_viol.invariant if first_viol else None,
            "distinct": int(distinct_j.sum()),
            "total": int(total_j.sum()),
            "depth": depth,
            "terminal": int(terminal_j.sum()),
            "seconds": round(dt, 3),
            "distinct_per_s": round(int(distinct_j.sum()) / dt, 1)
            if dt > 0 else 0.0,
            "exhausted": all(r.exhausted for r in results),
            "peak_frontier_cap": int(max(
                max(dc) for dc in depth_counts_j)),
            "peak_journal_cap": int(next_gid - len(self._init_distinct)),
            "seen_lanes": int(len(seen)),
            "canon_memo_hit_rate": 0.0,
            "fleet_jobs": J,
        })
        # per-job synthesized runs: one manifest/coverage/summary triple
        # per job so obs_report and the schema checker see per-job
        # digests in the one multiplexed stream
        if tel.active:
            man = self._telemetry_manifest()
            for j, (name, r) in enumerate(zip(names, results)):
                tel.open_run({**man, "job": name})
                tel.coverage(
                    {
                        **self._coverage_fields(
                            r.depth, cov_j[j], len(seen), r.depth_counts
                        ),
                        "job": name,
                    },
                    final=True,
                )
                tel.close_run({
                    "engine": "host",
                    "ident": self._ckpt_ident(),
                    "exit_cause": r.exit_cause,
                    "violation": r.violation.invariant
                    if r.violation else None,
                    "distinct": r.distinct,
                    "total": r.total,
                    "depth": r.depth,
                    "terminal": r.terminal,
                    "seconds": round(dt, 3),
                    "distinct_per_s": round(r.distinct / dt, 1)
                    if dt > 0 else 0.0,
                    "exhausted": r.exhausted,
                    "peak_frontier_cap": int(max(r.depth_counts)),
                    "peak_journal_cap": int(
                        next_gid - len(self._init_distinct)),
                    "seen_lanes": int(len(seen)),
                    "canon_memo_hit_rate": 0.0,
                    "job": name,
                })
        return results

    def _fleet_check_invariants(
        self, states, gids, jobs, depth, violation_j, active
    ) -> None:
        """Per-job first violation of a shared wave: for each still-
        active job, the first invariant (in declaration order) with a
        bad row, and within it the first row in exploration order —
        exactly serial ``_check_invariants`` restricted to the job's
        rows. Deactivates violated jobs in place."""
        n = len(states)
        if n == 0:
            return
        m = 1 << (n - 1).bit_length()
        padded = states
        if m > n:
            padded = np.concatenate(
                [states, np.repeat(states[:1], m - n, axis=0)], axis=0
            )
        for name in self.invariants:
            ok = np.asarray(
                jax.device_get(self.model.invariants[name](padded))
            )[:n]
            bad = ~ok
            if not bad.any():
                continue
            for j in np.unique(jobs[bad]):
                j = int(j)
                if violation_j[j] is None and active[j]:
                    r = int(np.nonzero(bad & (jobs == j))[0][0])
                    violation_j[j] = Violation(
                        invariant=name, global_id=int(gids[r]), depth=depth
                    )
                    active[j] = False

    def _fps_rows(self, rows: np.ndarray) -> np.ndarray:
        """Canonical fingerprints of a compact [n, W] row block, padded
        to the next power of two so the jitted canon sees a log-bounded
        signature set instead of one per distinct worklist length."""
        n = len(rows)
        m = 1
        while m < n:
            m <<= 1
        if m > n:
            rows = np.concatenate(
                [rows, np.repeat(rows[-1:], m - n, axis=0)]
            )
        fps = np.asarray(
            jax.device_get(self._fps(rows)), dtype=np.uint64
        )
        return fps[:n]

    def _coverage_fields(self, depth, cov, seen_len, depth_counts) -> dict:
        """Coverage-event payload (events.COVERAGE_KEYS). The host engine
        keeps one flat sorted seen array (plus the in-wave probe set), so
        the dedup-structure gauges are trivial and there is no canon
        memo."""
        return {
            "depth": depth,
            "actions": [[int(x) for x in row] for row in cov],
            "actions_total": self.n_actions,
            "actions_fired": int(np.count_nonzero(cov[:, 1]))
            if self.n_actions else 0,
            "seen_lanes": [int(seen_len)],
            "seen_real": int(seen_len),
            "probe_runs": 2,  # global seen + current-wave fingerprints
            "frontier_hist": [int(x) for x in depth_counts],
            "canon_memo_fill": None,  # host engine has no canon memo
        }

    def grow_for_overflow(self, bits: int) -> dict | None:
        """Supervisor growth policy. The host engine's buffers are
        unbounded numpy arrays, so every recoverable overflow maps to
        the empty override dict (rebuild identically, resume); only the
        msg-slots bit — model shape, not engine capacity — is fatal."""
        return None if int(bits) & 1 else {}

    def _rebuild(self, overrides: dict) -> "BFSChecker":
        """A fresh engine with this one's constructor kwargs plus
        ``overrides`` (the supervisor's growth dicts)."""
        return type(self)(**{**self._ctor_kw, **overrides})

    def _save_checkpoint(
        self, path, frontier, seen, distinct, total, terminal, depth,
        base_gid, next_gid, depth_counts, cov,
    ):
        """Wave-boundary snapshot via the crash-safe writer
        (resilience/ckpt.py: tmp + fsync + rename, content hash,
        generation rotation). The journal is flattened to two arrays;
        resume reloads it as a single segment — _journal_lookup walks
        segments, so a one-element list is equivalent."""
        parents = (
            np.concatenate(self._parents)
            if self._parents else np.zeros(0, np.int64)
        )
        cands = (
            np.concatenate(self._cands)
            if self._cands else np.zeros(0, np.int32)
        )
        rckpt.save_npz(
            path,
            dict(
                version=1,
                spec=self._ckpt_ident(),
                frontier=np.asarray(frontier, dtype=np.int32),
                seen=np.asarray(seen, dtype=np.uint64),
                parents=parents.astype(np.int64),
                cands=cands.astype(np.int32),
                distinct=distinct,
                total=total,
                terminal=terminal,
                depth=depth,
                base_gid=base_gid,
                next_gid=next_gid,
                depth_counts=np.asarray(depth_counts, dtype=np.int64),
                coverage=np.asarray(cov, dtype=np.int64),
            ),
            keep=getattr(self, "_ckpt_keep", rckpt.DEFAULT_KEEP),
            chaos=getattr(self, "_chaos", None),
        )

    def _ckpt_ident(self) -> str:
        """Same identity grammar as the device engines (hashv marks the
        fingerprint formula revision; see DeviceBFS._ckpt_ident)."""
        wl = getattr(self.canon, "refine_rounds", 1)
        return (
            f"host/{self.model.name}/{self.model.p}/W={self.model.layout.W}"
            f"/sym={self.canon.symmetry}/hashv=5/wl={wl}"
            f"/inv={','.join(self.invariants)}"
        )

    def _telemetry_manifest(self) -> dict:
        """Run-provenance fields of the telemetry manifest event. The
        host engine's arrays are unbounded python/numpy buffers, so the
        capacity fields are 0 (= not capacity-limited)."""
        dev = jax.devices()[0]
        ident = self._ckpt_ident()
        return {
            "engine": "host",
            "ident": ident,
            "hashv": hashv_of(ident),
            "model": self.model.name,
            "platform": dev.platform,
            "device": str(getattr(dev, "device_kind", dev.platform)),
            "device_count": 1,
            "chunk": self.chunk,
            "frontier_cap": 0,
            "journal_cap": 0,
            "max_seen_cap": 0,
            "valid_cap": 0,
            "canon_memo_cap": 0,
            "symmetry": bool(self.canon.symmetry),
            "invariants": list(self.invariants),
            "action_names": list(getattr(self.model, "ACTION_NAMES", ())),
            "when": time.strftime("%Y-%m-%dT%H:%M:%S"),
        }

    def _check_invariants(self, states: np.ndarray, base_gid: int, depth: int):
        """Batched invariant evaluation; returns the first (in exploration
        order) violation, matching TLC's report-first-found behavior.

        Wave sizes vary every depth, so the batch is padded to the next
        power of two: jit caches per shape, and without bucketing every
        wave recompiles the invariant kernels (a real cost on TPU)."""
        n = len(states)
        if n == 0:
            return None
        m = 1 << (n - 1).bit_length()
        if m > n:  # pad with copies of a real state; slice them off below
            states = np.concatenate(
                [states, np.repeat(states[:1], m - n, axis=0)], axis=0
            )
        for name in self.invariants:
            ok = np.asarray(jax.device_get(self.model.invariants[name](states)))
            bad = np.nonzero(~ok[:n])[0]
            if len(bad):
                return Violation(invariant=name, global_id=base_gid + int(bad[0]), depth=depth)
        return None

    # ---------------- trace reconstruction ----------------

    def _journal_lookup(self, gid: int) -> tuple[int, int]:
        """(parent gid, candidate id) of a non-initial distinct state."""
        off = gid - len(self._init_distinct)
        for parents, cands in zip(self._parents, self._cands):
            if off < len(parents):
                return int(parents[off]), int(cands[off])
            off -= len(parents)
        raise KeyError(gid)

    def reconstruct_trace(self, violation: Violation) -> list[tuple[str, dict]]:
        """Replay the action chain from Init to the violating state.

        Mirrors TLC's predecessor-chain trace reconstruction (SURVEY.md
        §1.2): walk parent pointers to the root, then re-apply the recorded
        candidate actions via the expansion kernel."""
        model = self.model
        n0 = len(self._init_distinct)
        chain: list[tuple[int, int]] = []  # (parent, cand) from violation upward
        gid = violation.global_id
        while gid >= n0:
            parent, cand = self._journal_lookup(gid)
            chain.append((parent, cand))
            gid = parent
        chain.reverse()
        state = self._init_distinct[gid]
        out = [("Initial predicate", model.decode(state))]
        for _parent, cand in chain:
            succs, valid, rank, _ovf = jax.device_get(
                self._expand(np.repeat(state[None, :], self.chunk, axis=0))
            )
            assert valid[0, cand], "journalled candidate not enabled on replay"
            state = np.asarray(succs[0, cand])
            out.append(
                (self.model.action_label(int(rank[0, cand]), cand), model.decode(state))
            )
        return out
