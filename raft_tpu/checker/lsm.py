"""LSM of sorted fingerprint runs — the round-4 seen-set shared by the
single-device (DeviceBFS) and sharded (ShardedBFS) checkers.

Level i holds at most one sorted u64 run of ``min(R0 << i, TOPSZ)`` lanes
(tail-padded with U64_MAX). Each chunk's new fingerprints enter at level
0; two runs at the same level merge (sort-concat — measured faster than
scatter-merges on this TPU) into the next level, exactly a binary
counter; the TOPSZ top level absorbs by truncate-merge (sound only while
the engine's capacity guard holds, see the callers). Probing costs one
searchsorted per OCCUPIED level; per-chunk dedup cost is therefore
independent of the total state count.

Lanes live on the LAST axis: DeviceBFS uses [lanes] arrays, ShardedBFS
[D, lanes] sharded arrays — the per-row sorts/concats are identical code,
ShardedBFS just pins shardings via ``jit_kw``/``put``. The cascade is
deterministic (occupancy-driven), so hosts can enqueue merges without
syncing on run contents.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.hashing import U64_MAX, sort_u64
from .util import jit_with_donation


def pow2_at_least(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


class CanonMemo:
    """Device residency of the canon memo table that lives alongside the
    fingerprint runs: a direct-mapped [*lead, MCAP, 2] u64 array of
    (raw view hash, canonical fingerprint) rows, empty rows keyed
    U64_MAX. The probe/insert logic is pure and traced into the chunk
    program (``Canonicalizer.fingerprints_memo``); this class only owns
    allocation/placement so both engines share one geometry:
    DeviceBFS uses lead (), ShardedBFS (D,) with a per-shard table —
    raw keys are shard-local (successors are memoized where they are
    GENERATED, before the all-to-all routes their canonical
    fingerprints to their owners).

    ``cap`` rounds up to a power of two (the slot mask requires it);
    ``put`` pins placement (e.g. a sharded device_put)."""

    def __init__(self, cap: int, lead_shape: tuple[int, ...] = (),
                 put=None):
        self.MCAP = pow2_at_least(max(1, cap))
        self._lead = tuple(lead_shape)
        self._put = put if put is not None else jnp.asarray
        self.table = None

    def reset(self):
        """(Re)allocate the all-empty table and return it. Called at the
        start of every run: memo contents are a pure cache, but a fresh
        table keeps consecutive runs of one engine byte-reproducible."""
        self.table = self._put(
            np.full(self._lead + (self.MCAP, 2), np.uint64(U64_MAX))
        )
        return self.table


class RunLSM:
    """``r0``: level-0 run lanes (a chunk's emission width, pow2);
    ``topsz``: top-level lane cap (>= the engine's max seen capacity);
    ``lead_shape``: leading batch axes of every run array (() or (D,));
    ``put``: host->device placement for empties (defaults to
    jnp.asarray); ``jit_kw``: extra jax.jit kwargs for merge programs
    (e.g. out_shardings)."""

    def __init__(self, r0: int, topsz: int,
                 lead_shape: tuple[int, ...] = (), put=None, jit_kw=None):
        assert r0 and (r0 & (r0 - 1)) == 0, "r0 must be a power of two"
        self.R0 = r0
        self.TOPSZ = pow2_at_least(max(topsz, r0))
        self._lead = lead_shape
        self._put = put if put is not None else jnp.asarray
        self._jit_kw = dict(jit_kw or {})
        # Pre-create the FULL ladder up to TOPSZ: empty levels all alias
        # one cached sentinel constant per size (no HBM until occupied),
        # while creating a level later changes the engine's chunk-program
        # ARITY — a whole retrace (~20 s remote compile) mid-run.
        self._init_levels = 1
        while self.lv_size(self._init_levels - 1) < self.TOPSZ:
            self._init_levels += 1
        self._merge_cache: dict = {}
        self._empty_cache: dict[int, object] = {}
        self.runs: list = []
        self.occ: list[bool] = []
        self.reset()

    # ---------------- geometry ----------------

    def lv_size(self, level: int) -> int:
        return min(self.R0 << level, self.TOPSZ)

    def lanes(self) -> int:
        """Occupied lanes (padding included) — the waste metric."""
        return sum(
            self.lv_size(i) for i in range(len(self.runs)) if self.occ[i]
        )

    def n_levels(self) -> int:
        return len(self.runs)

    # ---------------- internals ----------------

    def _empty_of(self, size: int):
        """Cached read-only all-U64_MAX run. Levels share it and probing
        it is harmless, but it must NEVER reach a merge: merge inputs are
        donated (round 6), and a donated shared sentinel would be deleted
        out from under every other level aliasing it. The cascade never
        does (it only merges occupied runs, which are real buffers);
        warmup/probes use _fresh throwaways."""
        if size not in self._empty_cache:
            self._empty_cache[size] = self._put(
                np.full(self._lead + (size,), np.uint64(U64_MAX))
            )
        return self._empty_cache[size]

    def _fresh(self, size: int):
        """A fresh, never-shared all-sentinel run for donation probes and
        warmup merges (both CONSUME their inputs when donation sticks)."""
        return self._put(np.full(self._lead + (size,), np.uint64(U64_MAX)))

    def _jit(self, key, builder):
        fn = self._merge_cache.get(key)
        if fn is None:
            fn = jax.jit(builder(), **self._jit_kw)
            self._merge_cache[key] = fn
        return fn

    @staticmethod
    def merge_spec(out: int | None = None):
        """The merge program SPEC at a (na, nb, out) signature: the
        traced body plus its donate argnums, before any backend probing.
        The static donation auditor (analysis/donation.py) lowers
        ``jax.jit(body, donate_argnums=donate)`` from this spec — the
        production ``_merge`` wraps the same body through the
        jit_with_donation probe, which may silently fall back to an
        undonated jit on backends that cannot alias (so auditing the
        probed object would prove the wrong thing)."""
        if out is None:
            def body(x, y):
                return sort_u64(jnp.concatenate([x, y], axis=-1), axis=-1)
        else:
            def body(x, y):
                return sort_u64(
                    jnp.concatenate([x, y], axis=-1), axis=-1
                )[..., :out]
        return body, (0, 1)

    def _merge(self, a, b, out: int | None = None):
        """Per-row sort-concat merge along the lane axis (2-key u32 sort:
        a u64 lax.sort is ~300x slower on this TPU, ops/hashing.py).

        Both inputs are DONATED (round 6): the cascade only merges runs
        that are dead afterwards (the occupied run is replaced by the
        merge output or an empty sentinel, the carry is consumed), so on
        backends that alias donations the sort reuses their HBM instead
        of holding both inputs plus the output live. jit_with_donation
        probes once on throwaway runs and falls back to an undonated jit
        where XLA cannot alias (e.g. truncate-merges on CPU)."""
        key = (a.shape[-1], b.shape[-1], out)
        fn = self._merge_cache.get(key)
        if fn is None:
            na, nb = a.shape[-1], b.shape[-1]
            body, donate = self.merge_spec(out)
            fn = jit_with_donation(
                body, donate,
                lambda: (self._fresh(na), self._fresh(nb)),
                **self._jit_kw,
            )
            self._merge_cache[key] = fn
        return fn(a, b)

    # ---------------- static audit surface ----------------

    def audit_programs(self):
        """The cascade's complete merge-signature set (the same closure
        argument as ``warmup``: carries double exactly, so only
        equal-size merges per level plus the top truncate-merge exist),
        as audit entries for the static donation auditor — same schema
        as the engines' ``audit_programs``. ``_pad_run`` is absent by
        policy: its output is strictly larger than its input, so
        aliasing is impossible and the program is exempt from the
        donation contract."""
        import inspect as _inspect

        sds = jax.ShapeDtypeStruct
        _, line = _inspect.getsourcelines(RunLSM.merge_spec)
        site = (__file__, line)
        for i in range(len(self.runs)):
            size = self.lv_size(i)
            top = size >= self.TOPSZ
            body, donate = self.merge_spec(size if top else None)
            run = sds(self._lead + (size,), jnp.uint64)
            yield {
                "name": (f"lsm_merge[L{i}:top]" if top
                         else f"lsm_merge[L{i}]"),
                "fn": jax.jit(body, donate_argnums=donate,
                              **self._jit_kw),
                "args": (run, run),
                "carries": {0: "run_a", 1: "run_b"},
                "pinned": {},
                "site": site, "per_wave": 1,
            }
            if top:
                break

    def _pad_run(self, run, size: int):
        have = run.shape[-1]
        if have == size:
            return run
        assert have < size

        def build():
            pad = size - have
            return lambda r: jnp.concatenate(
                [r, jnp.full(r.shape[:-1] + (pad,), U64_MAX, jnp.uint64)],
                axis=-1)

        return self._jit(("pad", have, size), build)(run)

    # ---------------- operations ----------------

    def reset(self, n_levels: int | None = None):
        n = n_levels if n_levels is not None else self._init_levels
        self.runs = [self._empty_of(self.lv_size(i)) for i in range(n)]
        self.occ = [False] * n

    def add_level(self) -> None:
        """NOTE: changes the engine's chunk-program arg count (retrace)."""
        self.runs.append(self._empty_of(self.lv_size(len(self.runs))))
        self.occ.append(False)

    def insert(self, run) -> None:
        """Binary-counter insert of a sorted run (async device ops only —
        the cascade is occupancy-driven, no host sync on run contents)."""
        self.insert_at(run, 0)

    def insert_at(self, run, level: int) -> None:
        """Insert a sorted run whose lane count equals ``lv_size(level)``
        starting the cascade at that level (the wave-fused engine emits
        one pre-merged ladder per wave rather than per-chunk runs)."""
        assert run.shape[-1] == self.lv_size(level), (
            run.shape, self.lv_size(level))
        lv = level
        carry = run
        while True:
            if lv == len(self.runs):
                self.add_level()
            size = self.lv_size(lv)
            if not self.occ[lv]:
                self.runs[lv] = self._pad_run(carry, size)
                self.occ[lv] = True
                return
            if size >= self.TOPSZ:
                # absorb at the top: truncate-merge. Sound because the
                # engine's pre-wave capacity guard ensures all real lanes
                # fit in TOPSZ.
                self.runs[lv] = self._merge(self.runs[lv], carry, out=size)
                return
            carry = self._merge(self.runs[lv], carry)
            self.runs[lv] = self._empty_of(size)
            self.occ[lv] = False
            lv += 1

    def consolidate(self, bound: int) -> None:
        """Repack every occupied run into one right-sized run, dropping
        sentinel padding (bounds probe count and lane waste). `bound`
        must be an upper bound on the real fingerprints held per row; the
        truncation is then safe (the engine's capacity guard keeps it
        sound at TOPSZ).

        HOST-side (round 5): the round-4 device repack compiled one
        program per (occupied-shapes, target) signature — ~20-40 s each
        on the tunnel's remote-compile service, observed as 30-100 s
        mid-run stalls (a depth-19 wave measured 97 s against a 1.4 s
        neighbor). A numpy sort of a few tens of MB plus one H2D upload
        costs ~0.2 s and compiles NOTHING; seeding pads on the host so
        no pad program is needed either."""
        if sum(self.occ) <= 1:
            return
        rows = self.export_real()
        if self._lead:
            n = max((len(r) for r in rows), default=0)
            target = min(max(self.R0, pow2_at_least(max(1, n))), self.TOPSZ)
            host = np.full(self._lead + (target,), np.uint64(U64_MAX))
            for d, r in enumerate(rows):
                host[d, : len(r)] = r[:target]
        else:
            target = min(
                max(self.R0, pow2_at_least(max(1, len(rows)))), self.TOPSZ
            )
            host = np.full((target,), np.uint64(U64_MAX))
            host[: min(len(rows), target)] = rows[:target]
        self.seed(host)

    def seed(self, host_rows: np.ndarray) -> None:
        """Start from a host array [*lead, n] of per-row sorted real
        fingerprints padded with U64_MAX (Init seeding / resume).

        Padding to the level size happens on the HOST: a device pad
        program costs a ~20 s remote compile per (n, size) signature on
        the tunnel backend, a numpy concatenate costs nothing."""
        n = host_rows.shape[-1]
        if n > self.TOPSZ:
            raise OverflowError(
                f"seen-set seed of {n} lanes exceeds the {self.TOPSZ}-lane "
                "capacity; raise max_seen_cap to at least the checkpoint's "
                "seen size"
            )
        lv = 0
        while self.lv_size(lv) < n:
            lv += 1
        size = self.lv_size(lv)
        host_rows = np.asarray(host_rows, dtype=np.uint64)
        if n < size:
            pad = np.full(
                host_rows.shape[:-1] + (size - n,), np.uint64(U64_MAX)
            )
            host_rows = np.concatenate([host_rows, pad], axis=-1)
        self.reset(max(self._init_levels, lv + 1))
        self.runs[lv] = self._put(host_rows)
        self.occ[lv] = True

    def warmup(self) -> None:
        """Execute one sentinel merge per ladder level so every merge
        signature a run can need is compiled (and lands in the
        persistent compile cache) BEFORE the timed region. The cascade
        only ever merges equal-size runs (carries double exactly), so
        this is the complete signature set. Fresh throwaway runs, never
        the shared _empty_of sentinels: merges donate their inputs."""
        for i in range(len(self.runs)):
            size = self.lv_size(i)
            if size >= self.TOPSZ:
                self._merge(self._fresh(size), self._fresh(size), out=size)
                break
            self._merge(self._fresh(size), self._fresh(size))

    def export_host(self) -> list[np.ndarray]:
        """Occupied runs fetched to host (raw, sentinel-padded)."""
        return [
            np.asarray(jax.device_get(self.runs[i]))
            for i in range(len(self.runs))
            if self.occ[i]
        ]

    def export_real(self):
        """Real fingerprints, sentinel-filtered and sorted: a flat [n]
        array for lead_shape (), a list of per-row arrays for (D,)
        (the checkpoint format both engines share)."""
        parts = self.export_host()
        sent = np.uint64(U64_MAX)

        def pack(arrs):
            cat = (np.concatenate(arrs) if arrs
                   else np.empty(0, np.uint64))
            cat = cat[cat != sent]
            cat.sort()
            return cat

        if not self._lead:
            return pack(parts)
        return [pack([p[d] for p in parts]) for d in range(self._lead[0])]
