"""Device-resident BFS — the fast path of the TPU checker.

Same exploration semantics as checker/bfs.py (the host-dedup v1 driver):
identical distinct sets, gid numbering, first-occurrence tie-breaking and
violation reporting — but the whole hot loop lives in HBM. Per wave the
host transfers only a handful of scalars; states never round-trip.

Pipeline per chunk (one jitted program, all device):
  1. expand `chunk` frontier states (vmap over the per-action kernels)
  2. compact the valid successor lanes (typically <20% of chunk*A) so
     canonicalization/hashing only runs on real candidates
  3. canonical fingerprints (VIEW + SYMMETRY, ops/symmetry.py)
  4. dedup: probe the tiered seen-set runs (searchsorted each),
     first-occurrence within the chunk
  5. compact survivors to a dense prefix block and APPEND it at the
     running cursor of the device next-frontier buffer — and their
     (parent gid, candidate) rows at the journal cursor — with one
     dynamic_update_slice each (contiguous writes; the round-6 emit
     redesign retired the full-capacity scatters this step used to do)
  6. evaluate invariants on the compacted candidates, folding the first
     violating gid per invariant into a device accumulator
  7. emit the chunk's new fingerprints as one small sorted run

The seen-set is an LSM of SORTED RUNS (round-4 redesign): level i holds
at most one sorted u64 run of R0<<i lanes (R0 = the chunk's successor
budget rounded to a power of two). Each chunk's new fingerprints enter
at level 0; two runs at the same level merge (sort-concat — measured
faster than scatter-merges on this TPU, see the note in _chunk_step)
into the next level, exactly a binary counter. Probing costs one
searchsorted per level (<= ~15); per-chunk dedup cost is therefore
O(VC log) and INDEPENDENT of the total state count — the round-3 design
re-sorted an FCAP-lane buffer per chunk and SCAP+FCAP lanes per wave,
which dominated small and deep runs alike (round-3 verdict Weak #2,
Next #4). The cascade is deterministic (occupancy-driven), so the host
enqueues merges without ever syncing on a chunk's result; padding waste
is bounded by wave-boundary consolidation.

This replaces TLC's shared fingerprint set + BFS queue (SURVEY.md §3.1
hot loop); `-deadlock` semantics are preserved (terminal states counted,
not errors, reference README.md:7).
"""

from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..obs import MemWatch, NULL_TELEMETRY
from ..obs.events import hashv_of
from ..ops.hashing import U64_MAX, ne_u64, sort_u64, sort_u64_with_idx
from ..ops.symmetry import Canonicalizer
from ..resilience import ckpt as rckpt
from ..resilience.errors import CapacityOverflow
from .bfs import CheckResult, Violation
from .lsm import CanonMemo, pow2_at_least
from .util import (
    GROWTH, HEADROOM, I32_MAX, dense_prefix_sel, emit_append,
    jit_with_donation, next_cap, probe_sorted as _probe,
)


class DeviceBFS:
    """Single-device BFS with device-resident frontier/seen-runs/journal.

    Capacities are static (XLA shapes). The frontier/journal GROW between
    waves (retracing the chunk program); the seen-set grows by LSM level
    creation (also a retrace, log-many times per run). Overflow flags
    remain a hard backstop that aborts rather than dropping states.
      frontier_cap   per-wave distinct states (frontier buffer rows)
      seen_cap       initial seen-set lane budget (sizes the starting
                     LSM levels; capacity bound is max_seen_cap)
      journal_cap    total distinct states beyond Init (trace journal)
      valid_per_state  compaction budget: avg valid successors per state
                       (Raft-family specs average ~5 of A~53; 16 is
                       generous, overflow-checked)
      valid_per_group  apply budget for guard-first sparse expansion:
                       per-group cap on enabled candidates per state
                       (chunk-aggregate; dict maps group name -> cap,
                       fractions legal). None = loose bound, which is
                       overflow-impossible but pays for every slot of
                       wide groups; tune from the coverage table /
                       enabled_density gauge. Ignored for models
                       without the sparse expand contract.

    Checkpoint/resume (SURVEY.md §5.4; TLC has it built in): pass
    checkpoint_path (+ checkpoint_every_s) to run(), and resume= to pick
    a run back up from the saved seen-set/frontier/journal.
    """

    GROWTH = GROWTH
    HEADROOM = HEADROOM

    # overflow-bit vocabulary (mirrors the in-program stats lane); the
    # seen-set has no in-program bit — its host-side guard raises with
    # this synthetic one so the supervisor's growth policy can key on it
    OVF_NAMES = ((1, "msg"), (2, "valid"), (4, "frontier"), (8, "journal"))
    SEEN_OVF_BIT = 16

    # Donation contract for the wave/chunk programs: argument indices of
    # the capacity-shaped loop carries updated in place every dispatch
    # (next_buf, jparent, jcand, viol, stats, memo, cov). The frontier
    # (argnum 0) is deliberately NOT donated — the host swaps it with
    # next_buf between waves. analysis/donation.py verifies the lowered
    # programs alias exactly these, so an edit that drops one is named
    # before it costs a per-wave buffer copy.
    WAVE_DONATE = (1, 2, 3, 4, 5, 6, 7)
    CHUNK_DONATE = (1, 2, 3, 4, 5, 6, 7)
    # --timeline stage programs: memo in canon; the six state carries in
    # finish; stats in the reset (expand/dedup carry nothing)
    TL_DONATE = {
        "expand": (),
        "canon": (2,),
        "dedup": (),
        "finish": (0, 1, 2, 3, 4, 5),
        "statreset": (0,),
    }

    def __init__(
        self,
        model,
        invariants: tuple[str, ...] = (),
        symmetry: bool = True,
        chunk: int = 1024,
        frontier_cap: int = 1 << 18,
        seen_cap: int = 1 << 22,
        journal_cap: int = 1 << 22,
        valid_per_state: int = 16,
        valid_per_group: float | dict | None = None,
        check_deadlock: bool = False,
        max_frontier_cap: int = 1 << 22,
        max_seen_cap: int = 1 << 25,
        max_journal_cap: int = 1 << 25,
        fingerprint_seed: int = 0,
        canon_memo_cap: int = 1 << 21,
    ):
        # constructor kwargs, for _rebuild (supervisor growth overrides)
        self._ctor_kw = {k: v for k, v in locals().items() if k != "self"}
        self.model = model
        self.invariants = tuple(invariants)
        self.chunk = chunk
        self.check_deadlock = check_deadlock
        self.A = model.A
        self.W = model.layout.W
        # per-action coverage width: one row per Next-disjunct rank
        # (the model's ACTION_NAMES order); 0 disables accumulation for
        # models predating the rank/name contract
        self.n_actions = len(getattr(model, "ACTION_NAMES", ()))
        self.FCAP = frontier_cap
        self.JCAP = journal_cap
        self.MAX_FCAP = max(max_frontier_cap, frontier_cap)
        self.MAX_SCAP = max(max_seen_cap, seen_cap)
        self.MAX_JCAP = max(max_journal_cap, journal_cap)
        self.VC = min(chunk * self.A, chunk * valid_per_state)
        # guard-first sparse expansion (SparseExpandMixin models): cheap
        # guards over the dense [chunk, A] grid, then a vmapped apply
        # over a static per-group budget plan instead of materializing
        # all chunk*A successor rows. valid_per_group tunes the plan
        # (per-state units, chunk-aggregate; dict maps group name ->
        # cap); None keeps the loose overflow-impossible bound. Legacy
        # / custom models without the mixin keep the dense path.
        self._sparse = hasattr(model, "sparse_apply")
        self.valid_per_group = valid_per_group
        self._plan = (
            model.sparse_plan(chunk, self.VC, valid_per_group)
            if self._sparse
            else None
        )
        assert chunk <= frontier_cap
        # the per-chunk dynamic_slice would clamp an out-of-bounds start and
        # silently re-expand earlier rows (while `live` still used the
        # unclamped cursor, skipping tail states); requiring divisibility
        # keeps every slice in bounds
        assert frontier_cap % chunk == 0, "frontier_cap must be a multiple of chunk"
        # seen-set geometry (round 5): ONE device-resident sorted run,
        # sized from a small pow2 ladder and merged with the wave's
        # fingerprint ladder ON DEVICE once per wave. Every extra
        # multi-million-lane run cost ~20-50 ms of searchsorted per
        # CHUNK under the old binary-counter LSM (deep waves probing 3
        # runs measured 352 ms/chunk vs 214 for 1-run neighbours), and
        # host-side repacks moved tens of MB through the ~25 MB/s
        # tunnel; the single-run design probes once and never leaves
        # HBM. The few (size -> size) merge signatures precompile.
        self.R0 = pow2_at_least(self.VC)
        self.SCAP = self.MAX_SCAP  # capacity bound (kept for callers)
        self.TOPSZ = pow2_at_least(self.MAX_SCAP)
        sizes = []
        s = min(max(self.R0, 1 << 18), self.TOPSZ)
        while s < self.TOPSZ:
            sizes.append(s)
            s <<= 2
        sizes.append(self.TOPSZ)
        self._seen_sizes = sizes
        self._seen = None  # device u64 [size], sorted, U64_MAX-padded
        self._seen_real = 0
        self._merge_cache: dict = {}
        self.canon = Canonicalizer.for_model(
            model, symmetry=symmetry, seed=fingerprint_seed
        )
        # canon memo (checker/lsm.py CanonMemo geometry): HBM-resident
        # direct-mapped table caching raw-view-hash -> canonical
        # fingerprint across the whole run; duplicate successors (the
        # majority past the first waves) skip the tiered canon entirely.
        # Custom canonicalizers (make_canonicalizer models) that predate
        # the memo surface fall back to the unmemoized path.
        self._use_memo = (
            canon_memo_cap > 0
            and hasattr(self.canon, "fingerprints_memo")
        )
        self._memo = CanonMemo(canon_memo_cap if self._use_memo else 1)
        self.MCAP = self._memo.MCAP
        # donated: next_buf, jparent, jcand, viol, stats, memo, cov
        # (seen read-only; the donation sets are class attributes so the
        # static donation auditor — analysis/donation.py — can verify
        # the lowered aliasing against CARRY_NAMES independently)
        self._chunk_fn = jax.jit(
            self._chunk_step, donate_argnums=self.CHUNK_DONATE
        )
        self._wave_fn = jax.jit(
            self._wave_step, donate_argnums=self.WAVE_DONATE
        )
        self._flag_true = jnp.asarray(True)
        self._flag_false = jnp.asarray(False)
        self._occ_one = jnp.ones((1,), bool)
        self._init_distinct: np.ndarray | None = None
        self._jparent = None
        self._jcand = None
        self._jcount = 0
        self._tel = NULL_TELEMETRY  # active only inside run(telemetry=...)
        # wave-timeline observatory programs, built on first sampled
        # wave only (a run without --timeline never compiles them)
        self._tl_fns: dict | None = None
        self._tl_merge_cache: dict = {}

    # ---------------- seen-set adapters ----------------

    def _flag(self, v: bool):
        return self._flag_true if v else self._flag_false

    def _seen_size_for(self, n: int) -> int:
        for s in self._seen_sizes:
            if n <= s:
                return s
        raise OverflowError(
            f"seen-set of {n} exceeds the {self.TOPSZ}-lane capacity; "
            "raise max_seen_cap"
        )

    def _seed_seen(self, sorted_fps: np.ndarray) -> None:
        """Upload a sorted host fingerprint array as the seen run,
        host-padded to the ladder size (device pads would compile)."""
        n = len(sorted_fps)
        size = self._seen_size_for(n)
        host = np.full((size,), np.uint64(U64_MAX))
        host[:n] = sorted_fps
        self._seen = jnp.asarray(host)
        self._seen_real = n

    def _merge_seen(self, ladder, new_real: int) -> None:
        """seen <- sort(concat(seen, *ladder)) resized to EXACTLY the
        ladder size `target` on device. Truncation only drops U64_MAX
        padding (new_real <= target by construction); when the concat is
        SHORTER than target the result is padded back up with U64_MAX —
        appending the sort key's own padding value keeps the run sorted,
        and _lsm_export / probe_sorted are padding-blind. Without the
        pad-up, a merge whose target outgrew the concat total left a
        non-ladder-size seen run, and the NEXT wave retraced + recompiled
        the whole wave program at a never-precompiled shape: that one
        mid-run compile was the unexplained 4.3x final-wave cliff at
        depth 32 in BENCH_r05.json (~117 s of the 152.6 s wave)."""
        target = self._seen_size_for(new_real)
        key = (self._seen.shape[0], tuple(l.shape[0] for l in ladder), target)
        fn = self._merge_cache.get(key)
        if fn is None:
            fn = self._make_seen_merge(key)
            self._merge_cache[key] = fn
        self._seen = fn(self._seen, *ladder)
        self._seen_real = new_real

    @staticmethod
    def _seen_merge_spec(key):
        """(body, donate_argnums) of the merge program for one
        (seen size, ladder shapes, target) signature — the single source
        both the production wrapper below and the static donation /
        signature auditors build from. All inputs are donated: the old
        seen run and the wave ladder are dead after the merge. The
        pad-up branch keeps the output EXACTLY ``target`` lanes even
        when the concat total falls short — the signature-closure
        invariant (_merge_seen) depends on it."""
        size, lshapes, target = key
        total = size + sum(lshapes)

        def merge(s, *lv):
            out = sort_u64(jnp.concatenate([s, *lv]))[:target]
            if total < target:
                out = jnp.concatenate(
                    [out, jnp.full((target - total,), U64_MAX, jnp.uint64)]
                )
            return out

        return merge, tuple(range(1 + len(lshapes)))

    def _make_seen_merge(self, key):
        """Build (and compile+probe, via jit_with_donation) the merge
        program for one signature: on backends that alias donations the
        multi-million-lane sort reuses the dead inputs' HBM instead of
        holding old + new + scratch live at once."""
        size, lshapes, _target = key
        merge, donate = self._seen_merge_spec(key)
        return jit_with_donation(
            merge,
            donate,
            lambda: tuple(
                jnp.full((n,), U64_MAX, jnp.uint64) for n in (size, *lshapes)
            ),
        )

    def _lsm_export(self) -> np.ndarray:
        """All real fingerprints, sorted (host array; checkpoint format)."""
        arr = np.asarray(jax.device_get(self._seen))
        return arr[arr != np.uint64(U64_MAX)]

    # ---------------- device programs ----------------
    #
    # The chunk pipeline is factored into four stage methods
    # (_st_expand -> _st_canon -> _st_dedup -> _st_finish) that
    # _chunk_step composes — the fused wave program traces the exact
    # same (integer-only) ops, while the wave-timeline observatory
    # (--timeline) dispatches the same stages as separate jits with
    # block_until_ready between them to attribute a sampled wave's
    # wall clock (obs/events.py TIMELINE_STAGES). Bit-identity of the
    # two paths is parity-gated by tests/test_obs.py.

    def _st_expand(self, frontier, cursor, fcount):
        """Stages 1-2: guard/dense expand + compaction (+ the budgeted
        sparse apply). Returns the compacted successor block and every
        lane the later stages consume."""
        model = self.model
        C, A, W, VC = self.chunk, self.A, self.W, self.VC
        batch = lax.dynamic_slice(frontier, (cursor, jnp.int32(0)), (C, W))
        live = (jnp.arange(C, dtype=jnp.int32) + cursor) < fcount
        if self._sparse:
            # guard pass only: valid/rank/ovf over the dense [C, A]
            # grid without materializing any W-wide successor rows
            # (DCE-derived from _expand1, bit-identical by construction)
            valid, rank, ovf = jax.vmap(model.guards1)(batch)
        else:
            succs, valid, rank, ovf = jax.vmap(model._expand1)(batch)
        valid = valid & live[:, None]
        expand_ovf = jnp.any(valid & ovf)
        n_gen = jnp.sum(valid)
        terminal = jnp.sum(live & ~jnp.any(valid, axis=1))

        # 2. compact valid lanes: sel[j] = flat lane of the j-th valid succ
        vflat = valid.reshape(-1)
        vpos = jnp.cumsum(vflat) - 1
        compact_ovf = n_gen > VC
        sdst = jnp.where(vflat, jnp.minimum(vpos, VC), VC)
        sel = (
            jnp.full((VC + 1,), C * A, jnp.int32)
            .at[sdst]
            .set(jnp.arange(C * A, dtype=jnp.int32))[:VC]
        )
        selv = sel < C * A
        if self._sparse:
            # apply pass: construct successors ONLY for the compacted
            # worklist lanes, vmapped per group over the static budget
            # plan (precompiled signatures). Budget overflow folds into
            # the compaction bit: both mean "a static worklist bound
            # was exceeded, raise the knob".
            flatc, apply_ovf = model.sparse_apply(batch, sel, selv, self._plan)
            compact_ovf = compact_ovf | apply_ovf
        else:
            flatp = jnp.concatenate(
                [succs.reshape(C * A, W), jnp.zeros((1, W), jnp.int32)],
                axis=0,
            )
            flatc = flatp[sel]  # [VC, W]
        return (flatc, sel, selv, valid, rank, n_gen, terminal,
                expand_ovf, compact_ovf)

    def _st_canon(self, flatc, selv, memo):
        """Stage 3: canonical fingerprints on compacted lanes only,
        through the raw-keyed canon memo (duplicate successors skip the
        tiered canon; invalid lanes come back masked to U64_MAX either
        way)."""
        if self._use_memo:
            fps, memo, n_memo_hit = self.canon.fingerprints_memo(
                flatc, selv, memo
            )
        else:
            fps = self.canon._fingerprints(flatc)
            fps = jnp.where(selv, fps, U64_MAX)
            n_memo_hit = jnp.asarray(0, jnp.int32)
        return fps, memo, n_memo_hit

    def _st_dedup(self, fps, occ, *runs):
        """Stage 4: probe every OCCUPIED LSM run, then first-occurrence
        in chunk. Runs inserted by earlier chunks of this wave are in
        ``runs`` already (the cascade is enqueued before the next chunk
        call), so cross-chunk in-wave dedup falls out of the same probe.
        Empty levels skip their binary search at runtime via cond."""
        VC = self.VC
        fresh = ne_u64(fps, U64_MAX)
        for i, r in enumerate(runs):
            hit = lax.cond(
                occ[i],
                lambda rr: _probe(rr, fps),
                lambda rr: jnp.zeros(fps.shape, bool),
                r,
            )
            fresh = fresh & ~hit
        rf, order = sort_u64_with_idx(fps)
        first_s = jnp.ones((VC,), bool).at[1:].set(ne_u64(rf[1:], rf[:-1]))
        first = jnp.zeros((VC,), bool).at[order].set(first_s)
        return fresh & first

    def _st_finish(
        self, next_buf, jparent, jcand, viol, stats, cov, flatc, fps,
        sel, valid, rank, new, n_gen, terminal, expand_ovf, compact_ovf,
        n_memo_hit, cursor, base_gid,
    ):
        """Stages 4b-6: per-action coverage, the cursor-append emit,
        invariants on the new states and the stats fold. Returns the
        updated carries plus the chunk's new fingerprints as a sorted
        R0-lane run."""
        model = self.model
        C, A, W, VC = self.chunk, self.A, self.W, self.VC
        FCAP, JCAP = self.FCAP, self.JCAP
        n_new = jnp.sum(new)

        # 4b. per-action coverage: segment_sum over the rank/valid lanes
        # _expand1 already returns, invalid lanes routed to drop bucket
        # K (rank is -1 only where valid is False, so the id stays in
        # range). enabled counts states where the disjunct's guard held;
        # fired counts valid candidate lanes; new-distinct counts first-
        # writer lanes (rank gathered through the compaction `sel`).
        K = self.n_actions
        if K:
            rk = jnp.where(valid, rank, K)
            fired_k = jax.ops.segment_sum(
                jnp.ones((C * A,), jnp.int64), rk.reshape(-1),
                num_segments=K + 1,
            )[:K]
            en = (rank[:, :, None] == jnp.arange(K, dtype=rank.dtype)) & (
                valid[:, :, None]
            )  # [C, A, K] one-hot (compare beats a scatter on TPU)
            enabled_k = jnp.sum(jnp.any(en, axis=1), axis=0, dtype=jnp.int64)
            flat_rk = jnp.concatenate(
                [rk.reshape(-1), jnp.full((1,), K, rk.dtype)]
            )[sel]  # [VC] rank per compacted lane (drop row -> bucket K)
            new_k = jax.ops.segment_sum(
                new.astype(jnp.int64), jnp.where(new, flat_rk, K),
                num_segments=K + 1,
            )[:K]
            cov = cov + jnp.stack([enabled_k, fired_k, new_k], axis=1)

        # 5. emit: compact survivors to a dense prefix of a [VC, W]
        # block (scatter confined to a chunk-sized index buffer), then
        # ONE dynamic_update_slice per buffer appends the block at the
        # running cursor. The destinations ncount + (cumsum(new) - 1)
        # are provably contiguous, but XLA cannot prove it, so the old
        # `.at[bdst].set()` emit lowered to general scatters over the
        # full (FCAP, W)/(JCAP,) buffers — 71% of the raft3 per-chunk
        # stage sum (PROFILE.md round 5). Rows [FCAP, FCAP+VC) /
        # [JCAP, JCAP+VC) are the drop region replacing the scatter's
        # drop row; overflow semantics are bit-identical (emit_append).
        ncount = stats[0].astype(jnp.int32)
        jcount = stats[1].astype(jnp.int32)
        npos = (jnp.cumsum(new) - 1).astype(jnp.int32)
        esel = dense_prefix_sel(new, npos, VC)
        blk = jnp.concatenate(
            [flatc, jnp.zeros((1, W), jnp.int32)], axis=0
        )[esel]
        jp_blk = jnp.concatenate(
            [base_gid + cursor + sel // A, jnp.zeros((1,), jnp.int32)]
        )[esel]
        jc_blk = jnp.concatenate([sel % A, jnp.zeros((1,), jnp.int32)])[esel]
        next_buf, frontier_ovf = emit_append(next_buf, blk, ncount, n_new, FCAP)
        jparent, journal_ovf = emit_append(jparent, jp_blk, jcount, n_new, JCAP)
        jcand, _ = emit_append(jcand, jc_blk, jcount, n_new, JCAP)
        # NOTE: a searchsorted+scatter linear merge looks asymptotically
        # better than sort-concat for merging sorted sets, but arbitrary-
        # index scatters serialize on this hardware while XLA's bitonic
        # sort is fast (scripts/emit_micro.py reproduces the scatter
        # penalty on the current backend; EMIT_MICRO.json carries the
        # measured numbers that used to live in this comment as
        # folklore). All LSM merges therefore use sort-concat (as 2-key
        # u32 sorts — hashing.py), and the per-chunk sort below is only
        # R0 = 2^ceil(log2(VC)) lanes.
        new_run = sort_u64(jnp.where(new, fps, U64_MAX))
        if self.R0 > VC:
            new_run = jnp.concatenate(
                [new_run, jnp.full((self.R0 - VC,), U64_MAX, jnp.uint64)]
            )

        # 6. invariants on the compacted candidates; fold first-bad gid
        jidx = jnp.where(new, jcount + npos, I32_MAX)
        for k, name in enumerate(self.invariants):
            ok = model.invariants[name](flatc)
            bad = new & ~ok
            viol = viol.at[k].min(jnp.min(jnp.where(bad, jidx, I32_MAX)))

        ovf_bits = (
            expand_ovf.astype(jnp.int64)
            + 2 * compact_ovf.astype(jnp.int64)
            + 4 * frontier_ovf.astype(jnp.int64)
            + 8 * journal_ovf.astype(jnp.int64)
        )
        stats = jnp.stack(
            [
                stats[0] + n_new,
                stats[1] + n_new,
                stats[2] + n_gen,
                stats[3] + terminal,
                stats[4] | ovf_bits,
                stats[5] + n_memo_hit,
            ]
        )
        return next_buf, jparent, jcand, viol, stats, cov, new_run

    def _chunk_step(
        self, frontier, next_buf, jparent, jcand, viol, stats, memo, cov,
        cursor, fcount, base_gid, occ, first, *runs,
    ):
        """One chunk of the current wave (the four stage methods above,
        composed — one traced program). stats is i64[6]:
        [wave new count, journal count, cumulative generated,
         cumulative terminal, overflow bits, cumulative canon memo
        hits]; memo is the [MCAP, 2] canon memo table (threaded through
        the wave loop, donated); cov is the i64[n_actions, 3] per-action
        coverage accumulator — [enabled, fired, new-distinct] per Next-
        disjunct rank, cumulative over the WHOLE run (never reset, so
        host snapshots are monotone); occ is bool[n_levels] (probes of
        unoccupied levels are skipped via lax.cond); first marks the
        wave's first chunk (resets the wave-new and overflow lanes
        in-program, saving a per-wave host->device stats upload — the
        tunnel's dispatch latency dominates small configs). Returns
        the chunk's new fingerprints as a sorted R0-lane run."""
        stats = jnp.where(
            first,
            stats * jnp.asarray([0, 1, 1, 1, 0, 1], dtype=stats.dtype),
            stats,
        )
        (flatc, sel, selv, valid, rank, n_gen, terminal, expand_ovf,
         compact_ovf) = self._st_expand(frontier, cursor, fcount)
        fps, memo, n_memo_hit = self._st_canon(flatc, selv, memo)
        new = self._st_dedup(fps, occ, *runs)
        (next_buf, jparent, jcand, viol, stats, cov,
         new_run) = self._st_finish(
            next_buf, jparent, jcand, viol, stats, cov, flatc, fps, sel,
            valid, rank, new, n_gen, terminal, expand_ovf, compact_ovf,
            n_memo_hit, cursor, base_gid,
        )
        return next_buf, jparent, jcand, viol, stats, memo, cov, new_run

    def _wave_geom(self) -> int:
        """Ladder depth K: levels R0<<0 .. R0<<K, top >= pow2(FCAP), so a
        whole wave's new fingerprints fit in-program (the top absorbs by
        truncate-merge, sound while the wave's real new count <= FCAP —
        the frontier overflow bit aborts the run otherwise)."""
        K = 0
        while (self.R0 << K) < pow2_at_least(self.FCAP):
            K += 1
        return K

    def _wave_step(
        self, frontier, next_buf, jparent, jcand, viol, stats, memo, cov,
        fcount, base_gid, occ, *runs,
    ):
        """One WAVE as a single dispatched program (round 5, verdict Next
        #1): a lax.while_loop drives the chunk pipeline over the frontier,
        deduplicating in-wave against an in-program binary-counter ladder
        of sorted fingerprint runs — so the host dispatches ONCE per wave
        and syncs once, instead of paying the tunnel's per-dispatch
        service cost (~100-150 ms after compile activity) per chunk; a
        170-chunk deep wave collapses from ~170 service slots to 1.
        Returns (next_buf, jparent, jcand, viol, stats, memo, cov,
        *ladder); the host inserts the occupied ladder levels into the
        RunLSM."""
        C = self.chunk
        K = self._wave_geom()
        R0 = self.R0

        stats = stats * jnp.asarray([0, 1, 1, 1, 0, 1], dtype=stats.dtype)
        occ_all = jnp.concatenate(
            [occ, jnp.ones((K + 1,), bool)]
        )  # ladder levels always probed (empties hold U64_MAX padding)
        ladder0 = tuple(
            jnp.full((R0 << i,), U64_MAX, jnp.uint64) for i in range(K + 1)
        )
        topsz = R0 << K

        def cascade(k, new_run, ladder):
            """Binary-counter insert of the chunk's R0-run: after chunk k,
            the ladder encodes counter k+1. The merge chain length is the
            number of trailing one-bits of k (capped at K, where the top
            absorbs by truncate-merge)."""
            kp1 = k + 1
            t = jnp.int32(0)
            for i in range(1, K + 1):
                t = t + (kp1 & ((1 << i) - 1) == 0).astype(jnp.int32)

            def make_branch(tt):
                def branch(r, *lv):
                    out = list(lv)
                    if tt < K:
                        merged = sort_u64(
                            jnp.concatenate([r, *lv[:tt]])
                        )  # R0 * 2^tt lanes
                        for i in range(tt):
                            out[i] = jnp.full((R0 << i,), U64_MAX, jnp.uint64)
                        out[tt] = merged
                    else:
                        merged = sort_u64(jnp.concatenate([r, *lv]))[:topsz]
                        for i in range(K):
                            out[i] = jnp.full((R0 << i,), U64_MAX, jnp.uint64)
                        out[K] = merged
                    return tuple(out)

                return branch

            return lax.switch(
                jnp.clip(t, 0, K), [make_branch(tt) for tt in range(K + 1)],
                new_run, *ladder,
            )

        def body(carry):
            (k, next_buf, jparent, jcand, viol, stats, memo, cov,
             *ladder) = carry
            (next_buf, jparent, jcand, viol, stats, memo, cov,
             new_run) = self._chunk_step(
                frontier, next_buf, jparent, jcand, viol, stats, memo, cov,
                k * C, fcount, base_gid, occ_all, jnp.asarray(False),
                *runs, *ladder,
            )
            ladder = cascade(k, new_run, ladder)
            return (k + 1, next_buf, jparent, jcand, viol, stats, memo,
                    cov, *ladder)

        def cond(carry):
            return carry[0] * C < fcount

        out = lax.while_loop(
            cond, body,
            (jnp.int32(0), next_buf, jparent, jcand, viol, stats, memo,
             cov, *ladder0),
        )
        return out[1:]

    # ---------------- wave-timeline observatory ----------------

    def _tl_programs(self) -> dict:
        """Separately jitted stage programs for sampled --timeline waves.
        The loop-carried buffers donate exactly as in the fused program
        (memo in canon; next_buf/journals/viol/stats/cov in finish) —
        without donation every sampled chunk copies the full
        capacity-shaped frontier + journal + memo through the stage
        outputs, which dominates the sampled wave's wall clock on big
        geometries and breaks the < 5% end-to-end overhead contract.
        _run_timeline_wave rebinds every donated carry from the stage
        return, so the dead inputs are never touched again."""
        if self._tl_fns is None:
            d = self.TL_DONATE
            self._tl_fns = {
                "expand": jax.jit(self._st_expand),
                "canon": jax.jit(self._st_canon, donate_argnums=d["canon"]),
                "dedup": jax.jit(self._st_dedup),
                "finish": jax.jit(
                    self._st_finish, donate_argnums=d["finish"]
                ),
                "statreset": jax.jit(
                    lambda s: s * jnp.asarray([0, 1, 1, 1, 0, 1],
                                              dtype=s.dtype),
                    donate_argnums=d["statreset"],
                ),
            }
        return self._tl_fns

    def _tl_merge_fn(self, tt: int, K: int):
        """Cascade merge program for chain length tt (tt == K truncates
        at the top, mirroring _wave_step.cascade's absorb branch). No
        donation here on purpose: the concatenated sort output can never
        alias the smaller inputs (XLA would warn, not alias), and ladder
        runs are KiB-scale — the buffers worth donating are the
        capacity-shaped carries in the canon/finish stages."""
        key = (tt, K)
        fn = self._tl_merge_cache.get(key)
        if fn is None:
            topsz = self.R0 << K
            if tt < K:
                def merge(r, *lv):
                    return sort_u64(jnp.concatenate([r, *lv]))
            else:
                def merge(r, *lv):
                    return sort_u64(jnp.concatenate([r, *lv]))[:topsz]
            fn = jax.jit(merge)
            self._tl_merge_cache[key] = fn
        return fn

    def _run_timeline_wave(
        self, frontier, next_buf, jparent, jcand, viol, stats, memo, cov,
        fcount, base_gid, stage_s,
    ):
        """Host-driven mirror of _wave_step for a SAMPLED --timeline
        wave: the same stage methods the fused program composes, each
        dispatched as its own jit with block_until_ready between them,
        so the wave's wall clock is attributed to TIMELINE_STAGES
        (accumulated into ``stage_s``). Bit-identical to _wave_fn: the
        stage math is shared (integer-only ops, no FP reassociation
        risk) and the host cascade below replays the binary-counter
        schedule exactly — the parity gate in tests/test_obs.py pins
        it. Returns the same tuple as _wave_fn, so run() continues
        unchanged (ladder shapes match the fused ladder, keeping the
        _merge_seen signature cache warm)."""
        C = self.chunk
        K = self._wave_geom()
        R0 = self.R0
        fns = self._tl_programs()
        pc = time.perf_counter

        def reset_run(i):
            # fresh arrays on purpose: _merge_seen donates the ladder at
            # wave end, so a cached/shared reset template would be
            # consumed by the first merge that receives it
            return jnp.full((R0 << i,), U64_MAX, jnp.uint64)

        stats = fns["statreset"](stats)
        occ_all = jnp.concatenate([self._occ_one, jnp.ones((K + 1,), bool)])
        ladder = [reset_run(i) for i in range(K + 1)]
        n_chunks = -(-int(fcount) // C)
        for k in range(n_chunks):
            t = pc()
            # lint: sync-ok(stage attribution on a sampled wave)
            ex = jax.block_until_ready(
                fns["expand"](frontier, np.int32(k * C), np.int32(fcount))
            )
            stage_s["expand"] += pc() - t
            (flatc, sel, selv, valid, rank, n_gen, terminal, e_ovf,
             c_ovf) = ex
            t = pc()
            # lint: sync-ok(stage attribution on a sampled wave)
            fps, memo, n_memo_hit = jax.block_until_ready(
                fns["canon"](flatc, selv, memo)
            )
            stage_s["canon"] += pc() - t
            t = pc()
            # lint: sync-ok(stage attribution on a sampled wave)
            new = jax.block_until_ready(
                fns["dedup"](fps, occ_all, self._seen, *ladder)
            )
            stage_s["dedup"] += pc() - t
            t = pc()
            # lint: sync-ok(stage attribution on a sampled wave)
            (next_buf, jparent, jcand, viol, stats, cov,
             new_run) = jax.block_until_ready(fns["finish"](
                next_buf, jparent, jcand, viol, stats, cov, flatc, fps,
                sel, valid, rank, new, n_gen, terminal, e_ovf, c_ovf,
                n_memo_hit, np.int32(k * C), np.int32(base_gid),
            ))
            stage_s["emit"] += pc() - t
            # binary-counter cascade, host-replayed: chain length =
            # trailing zero bits of k+1, capped at K where the top
            # absorbs by truncate-merge (same schedule as
            # _wave_step.cascade, so ladder contents stay identical)
            t = pc()
            kp1 = k + 1
            tt = 0
            while tt < K and kp1 % (1 << (tt + 1)) == 0:
                tt += 1
            if tt < K:
                merged = self._tl_merge_fn(tt, K)(new_run, *ladder[:tt])
            else:
                merged = self._tl_merge_fn(K, K)(new_run, *ladder)
            for i in range(tt):
                ladder[i] = reset_run(i)
            ladder[tt] = merged
            jax.block_until_ready(ladder)  # lint: sync-ok(stage attribution)
            stage_s["seen_merge"] += pc() - t
        return (next_buf, jparent, jcand, viol, stats, memo, cov, *ladder)

    # ---------------- precompile ----------------

    def precompile(self, telemetry=None) -> None:
        """Compile (and execute once, on zero/sentinel buffers) every
        device program a run at the CURRENT capacities can need: the
        chunk program and the full LSM merge ladder. Mid-run compiles
        through the tunnel's remote-compile service cost 20-100 s each
        (a depth-19 wave measured 97 s against 1.4 s neighbours purely
        from one consolidation compile, round 5); after this warmup —
        which the persistent compile cache turns into ~2 s disk hits in
        later processes — the timed region never compiles. Growth steps
        still retrace, so benchmark callers should start at their final
        capacities. ``telemetry``: a --trace-dir run brackets the whole
        warmup in a named "precompile" span."""
        tel = telemetry if telemetry is not None else NULL_TELEMETRY
        with tel.annotate("precompile"):
            self._precompile_programs()

    def signature_inventory(self):
        """The FINITE signature universe a run at the CURRENT capacities
        dispatches, in precompile order: a ``("wave", seen_size)`` per
        seen-ladder size, each followed by the per-wave seen merges that
        size can need — ``("merge", size, lshapes, target)`` for every
        ladder target >= size. ``_precompile_programs`` warms exactly
        this set; analysis/signatures.py independently recomputes the
        reachable set from the geometry primitives (_seen_size_for, the
        wave ladder, the pad-up merge contract) and proves the two are
        equal — the BENCH_r05 retrace-cliff class, checked symbolically.
        """
        K = self._wave_geom()
        lshapes = tuple((self.R0 << i) for i in range(K + 1))
        for si, size in enumerate(self._seen_sizes):
            yield ("wave", size)
            # targets >= size only: one wave adds at most pow2(FCAP)
            # real lanes, so targets further than two ladder steps up
            # are unreachable — but warming the whole upper triangle is
            # cheap and keeps the closure argument one-sided
            for target in self._seen_sizes[si:]:
                yield ("merge", size, lshapes, target)

    def _precompile_programs(self) -> None:
        W = self.W
        frontier = jnp.zeros((self.FCAP + self.VC, W), jnp.int32)
        for sig in self.signature_inventory():
            if sig[0] == "wave":
                size = sig[1]
                seen = jnp.full((size,), U64_MAX, jnp.uint64)
                next_buf = jnp.zeros((self.FCAP + self.VC, W), jnp.int32)
                jparent = jnp.zeros((self.JCAP + self.VC,), jnp.int32)
                jcand = jnp.zeros((self.JCAP + self.VC,), jnp.int32)
                viol = jnp.full(
                    (max(1, len(self.invariants)),), I32_MAX, jnp.int32
                )
                stats = jnp.zeros((6,), jnp.int64)
                cov = jnp.zeros((self.n_actions, 3), jnp.int64)
                self._wave_fn(
                    frontier, next_buf, jparent, jcand, viol, stats,
                    self._memo.reset(), cov,
                    np.int32(0), np.int32(0), self._occ_one, seen,
                )
                continue
            # _make_seen_merge compiles AND executes each program once
            # (its donation probe) on fresh throwaway buffers — the
            # cached merges must never be handed shared arrays, since a
            # successful donation consumes its inputs.
            key = sig[1:]
            if key not in self._merge_cache:
                self._merge_cache[key] = self._make_seen_merge(key)

    # ---------------- static audit surface ----------------

    def audit_programs(self):
        """Every device program this engine dispatches, as audit entries
        for the static donation auditor (analysis/donation.py):

          name     program id (``wave`` / ``tl:<stage>`` / ``seen_merge``)
          fn       a ``.lower()``-able jitted callable — the PRODUCTION
                   jit object where one exists
          args     abstract arguments for ``fn.lower(*args)``
          carries  {argnum: name} of the capacity-shaped loop carries
                   that MUST alias an output in the lowered program
          pinned   {argnum: name} of buffers that must NOT be donated
                   (the host reuses them after the dispatch)
          site     (file, line) anchor for findings
          per_wave dispatches per wave (scales the bytes-copied cost of
                   a donation miss)

        Yields entries without lowering or executing anything — tracing
        is the caller's cost, so passes choose their own coverage. The
        ``carries`` maps are written out independently of the
        ``*_DONATE`` declarations on purpose: the auditor compares the
        lowered aliasing against THIS list, so dropping an argnum from a
        donate tuple (the classic regression) diverges the two.
        """
        import inspect as _inspect

        sds = jax.ShapeDtypeStruct
        W, K = self.W, self._wave_geom()
        i32s = sds((), np.int32)
        frontier = sds((self.FCAP + self.VC, W), jnp.int32)
        next_buf = sds((self.FCAP + self.VC, W), jnp.int32)
        jparent = sds((self.JCAP + self.VC,), jnp.int32)
        jcand = sds((self.JCAP + self.VC,), jnp.int32)
        viol = sds((max(1, len(self.invariants)),), jnp.int32)
        stats = sds((6,), jnp.int64)
        memo = sds((self.MCAP, 2), jnp.uint64)
        cov = sds((self.n_actions, 3), jnp.int64)
        occ = sds((1,), jnp.bool_)
        seen = sds((self._seen_sizes[0],), jnp.uint64)
        wave_carries = {
            1: "next_buf", 2: "jparent", 3: "jcand", 4: "viol",
            5: "stats", 6: "memo", 7: "cov",
        }

        def site(fn):
            f = _inspect.unwrap(fn)
            return (__file__, _inspect.getsourcelines(f)[1])

        yield {
            "name": "wave", "fn": self._wave_fn,
            "args": (frontier, next_buf, jparent, jcand, viol, stats,
                     memo, cov, i32s, i32s, occ, seen),
            "carries": dict(wave_carries),
            "pinned": {0: "frontier"},
            "site": site(self._wave_step), "per_wave": 1,
        }
        # NOTE: _chunk_fn (the unfused per-chunk program) shares the
        # donate set but has not been dispatched since the wave fusion
        # (round 5); it is omitted here so the auditor's lowering budget
        # goes to programs a run actually executes.

        # --timeline stage programs: chain abstract shapes through the
        # stage methods with eval_shape (no tracing of the jitted
        # wrappers until the auditor lowers them)
        fns = self._tl_programs()
        ex_out = jax.eval_shape(self._st_expand, frontier, i32s, i32s)
        flatc, sel, selv = ex_out[0], ex_out[1], ex_out[2]
        valid, rank = ex_out[3], ex_out[4]
        n_gen, terminal, e_ovf, c_ovf = ex_out[5:9]
        canon_out = jax.eval_shape(self._st_canon, flatc, selv, memo)
        fps = canon_out[0]
        n_memo_hit = canon_out[2]
        occ_all = sds((K + 2,), jnp.bool_)
        ladder = tuple(
            sds((self.R0 << i,), jnp.uint64) for i in range(K + 1)
        )
        new = jax.eval_shape(
            self._st_dedup, fps, occ_all, seen, *ladder
        )
        yield {
            "name": "tl:canon", "fn": fns["canon"],
            "args": (flatc, selv, memo),
            "carries": {2: "memo"}, "pinned": {},
            "site": site(self._st_canon), "per_wave": 1,
        }
        yield {
            "name": "tl:finish", "fn": fns["finish"],
            "args": (next_buf, jparent, jcand, viol, stats, cov, flatc,
                     fps, sel, valid, rank, new, n_gen, terminal, e_ovf,
                     c_ovf, n_memo_hit, i32s, i32s),
            "carries": {0: "next_buf", 1: "jparent", 2: "jcand",
                        3: "viol", 4: "stats", 5: "cov"},
            "pinned": {},
            "site": site(self._st_finish), "per_wave": 1,
        }
        yield {
            "name": "tl:statreset", "fn": fns["statreset"],
            "args": (stats,),
            "carries": {0: "stats"}, "pinned": {},
            "site": site(self._tl_programs), "per_wave": 1,
        }
        # the per-wave seen merge, at the first (size, target) signature:
        # spec-built jit (production wraps the same body through the
        # jit_with_donation backend probe)
        key = (self._seen_sizes[0],
               tuple((self.R0 << i) for i in range(K + 1)),
               self._seen_sizes[0])
        body, donate = self._seen_merge_spec(key)
        merge_args = tuple(
            sds((n,), jnp.uint64) for n in (key[0], *key[1])
        )
        yield {
            "name": "seen_merge",
            "fn": jax.jit(body, donate_argnums=donate),
            "args": merge_args,
            "carries": {0: "seen",
                        **{1 + i: f"ladder[{i}]" for i in range(K + 1)}},
            "pinned": {},
            "site": site(self._seen_merge_spec), "per_wave": 1,
        }

    # ---------------- capacity growth ----------------

    _next_cap = staticmethod(next_cap)

    def _maybe_grow(self, ncount, frontier, next_buf, jparent, jcand, jcount):
        """Between waves: enlarge any buffer the next wave could outgrow.
        Frontier growth is speculative (next wave's new count is unknown;
        observed BFS wave growth is <=~2.2x, HEADROOM=3 covers it);
        journal growth is exact (it grows by ncount per wave). The
        seen-set needs no growth pass — LSM levels appear on demand."""
        W = self.W
        if ncount * self.HEADROOM > self.FCAP and self.FCAP < self.MAX_FCAP:
            new = self._next_cap(
                ncount * self.HEADROOM, self.FCAP, self.MAX_FCAP, self.GROWTH, self.chunk
            )
            pad = new - self.FCAP  # old buffer already carries its VC pad rows
            frontier = jnp.concatenate(
                [frontier, jnp.zeros((pad, W), jnp.int32)], axis=0
            )
            next_buf = jnp.zeros((new + self.VC, W), jnp.int32)
            self.FCAP = new
        if jcount + ncount * self.HEADROOM > self.JCAP and self.JCAP < self.MAX_JCAP:
            new = self._next_cap(
                jcount + ncount * self.HEADROOM, self.JCAP, self.MAX_JCAP, self.GROWTH, 1
            )
            pad = new - self.JCAP
            jparent = jnp.concatenate([jparent, jnp.zeros((pad,), jnp.int32)])
            jcand = jnp.concatenate([jcand, jnp.zeros((pad,), jnp.int32)])
            self.JCAP = new
        return frontier, next_buf, jparent, jcand

    def grow_for_overflow(self, bits: int) -> dict | None:
        """Constructor overrides that would absorb the overflow ``bits``
        of a CapacityOverflow raised by this instance — the supervisor's
        regrow-and-resume policy. Returns None when a bit has no growth
        story: msg-slots is model SHAPE (the bag width every state row
        carries), not an engine buffer, so rebuilding the engine cannot
        fix it — the model must be re-lowered with more slots."""
        bits = int(bits)
        if bits & 1:
            return None
        g: dict = {}
        if bits & 2:
            vps = max(1, -(-self.VC // self.chunk))
            g["valid_per_state"] = min(self.A, vps * 2)
            g["valid_per_group"] = None  # drop the tight budget plan
        if bits & 4:
            g["frontier_cap"] = self.FCAP * 2
            g["max_frontier_cap"] = max(self.MAX_FCAP, self.FCAP * 4)
        if bits & 8:
            g["journal_cap"] = self.JCAP * 2
            g["max_journal_cap"] = max(self.MAX_JCAP, self.JCAP * 4)
        if bits & self.SEEN_OVF_BIT:
            g["max_seen_cap"] = self.MAX_SCAP * 4
        return g

    def _rebuild(self, overrides: dict) -> "DeviceBFS":
        """A fresh engine with this one's constructor kwargs plus
        ``overrides`` (the supervisor's growth dicts)."""
        return type(self)(**{**self._ctor_kw, **overrides})

    # ---------------- host driver ----------------

    def run(
        self,
        max_depth: int | None = None,
        verbose: bool = False,
        time_budget_s: float | None = None,
        collect_metrics: bool = False,
        checkpoint_path: str | None = None,
        checkpoint_every_s: float = 300.0,
        checkpoint_keep: int = rckpt.DEFAULT_KEEP,
        resume: str | None = None,
        telemetry=None,
        preempt=None,
        chaos=None,
    ) -> CheckResult:
        model = self.model
        C, W = self.chunk, self.W
        t0 = time.perf_counter()
        exhausted = True
        exit_cause = None
        # telemetry consumes the SAME once-per-wave host snapshot the
        # loop already fetches (stats_h below), so an instrumented run
        # adds no device syncs and stays bit-identical (tests/test_obs.py)
        tel = telemetry if telemetry is not None else NULL_TELEMETRY
        self._tel = tel
        self._ckpt_keep = checkpoint_keep
        self._chaos = chaos

        init = model.init_states()
        init_fps = np.asarray(
            jax.device_get(self.canon.fingerprints(init)), dtype=np.uint64
        )
        order = np.argsort(init_fps, kind="stable")
        keep = np.ones(len(order), dtype=bool)
        sf = init_fps[order]
        dup = np.zeros(len(order), dtype=bool)
        dup[1:] = sf[1:] == sf[:-1]
        keep[order[dup]] = False
        init_d = np.asarray(init[keep])
        n0 = len(init_d)
        assert n0 <= self.FCAP, "initial states exceed frontier_cap"
        self._init_distinct = init_d

        ck_gen = 0
        ck_skipped: list[str] = []
        if resume is not None:
            # verified load with generation fallback: a truncated latest
            # file falls back to the newest intact .genN and the skipped
            # candidates surface as a ckpt_generation event below
            ck, ck_gen, ck_skipped = rckpt.load_npz(
                resume, keep=checkpoint_keep
            )
            ident = self._ckpt_ident()
            rckpt.check_spec(ck, ident, resume)
            fcount = int(ck["fcount"])
            scount = int(ck["scount"])
            jcount = int(ck["jcount"])
            # round caps up so the saved contents fit with headroom
            self.FCAP = self._next_cap(
                max(self.FCAP, fcount * self.HEADROOM),
                self.FCAP, self.MAX_FCAP, self.GROWTH, self.chunk)
            self.JCAP = self._next_cap(
                max(self.JCAP, jcount + fcount * self.HEADROOM),
                self.JCAP, self.MAX_JCAP, self.GROWTH, 1)
            seed_rows = (np.asarray(ck["frontier"]), np.asarray(ck["jparent"]),
                         np.asarray(ck["jcand"]))
            self._seed_seen(np.asarray(ck["seen"], dtype=np.uint64))
            violation = None
            distinct = int(ck["distinct"])
            total = int(ck["total"])
            terminal = int(ck["terminal"])
            depth = int(ck["depth"])
            base_gid = int(ck["base_gid"])
            gen_prev = int(ck["gen_prev"])
            depth_counts = [int(x) for x in ck["depth_counts"]]
            stats0 = np.array([0, jcount, gen_prev, terminal, 0, 0],
                              dtype=np.int64)
            # coverage joined the checkpoint format after version 1
            # shipped; older files resume with zeroed counters
            cov_h = (
                np.asarray(ck["coverage"], dtype=np.int64)
                if "coverage" in ck
                else np.zeros((self.n_actions, 3), np.int64)
            )
        else:
            violation = self._check_init(init_d)
            self._seed_seen(np.sort(init_fps[keep]))
            seed_rows = (init_d, np.zeros((0,), np.int32),
                         np.zeros((0,), np.int32))
            fcount = n0
            scount = n0
            distinct = n0
            total = len(init)  # pre-dedup, matching BFSChecker's seeding
            terminal = 0
            depth = 0
            base_gid = 0
            depth_counts = [n0]
            gen_prev = 0
            stats0 = np.zeros((6,), dtype=np.int64)
            cov_h = np.zeros((self.n_actions, 3), np.int64)

        # Buffers are allocated ON DEVICE and only the real rows upload:
        # the tunnel moves ~25-35 MB/s, so the round-4 host-built
        # (FCAP+1, W) staging arrays cost 70-100 s PER run() CALL at the
        # benchmark's 4M-row frontier (round-5 measurement) for buffers
        # that are almost entirely zeros.
        fr_h, jp_h, jc_h = seed_rows
        # rows [FCAP, FCAP+VC) / [JCAP, JCAP+VC) are the emit drop
        # region (checker/util.py emit_append)
        frontier = jnp.zeros((self.FCAP + self.VC, W), jnp.int32)
        if len(fr_h):
            frontier = lax.dynamic_update_slice(
                frontier, jnp.asarray(np.ascontiguousarray(fr_h)),
                (jnp.int32(0), jnp.int32(0)))
        next_buf = jnp.zeros((self.FCAP + self.VC, W), jnp.int32)
        jparent = jnp.zeros((self.JCAP + self.VC,), jnp.int32)
        jcand = jnp.zeros((self.JCAP + self.VC,), jnp.int32)
        if len(jp_h):
            jparent = lax.dynamic_update_slice(
                jparent, jnp.asarray(np.ascontiguousarray(jp_h)),
                (jnp.int32(0),))
            jcand = lax.dynamic_update_slice(
                jcand, jnp.asarray(np.ascontiguousarray(jc_h)),
                (jnp.int32(0),))
        viol = jnp.full((max(1, len(self.invariants)),), I32_MAX, jnp.int32)
        stats = jnp.asarray(stats0)
        cov = jnp.asarray(cov_h)  # i64[n_actions, 3], cumulative
        # fresh memo per run: the table is a pure cache (its contents
        # never change a fingerprint), but starting cold keeps
        # back-to-back runs of one engine instance comparable
        memo = self._memo.reset()
        memo_prev = 0

        tel.open_run(self._telemetry_manifest())
        if resume is not None:
            if ck_skipped:
                tel.event(
                    "ckpt_generation", path=resume, generation=ck_gen,
                    skipped=list(ck_skipped),
                )
            tel.event(
                "resume", path=resume, generation=ck_gen, depth=depth,
                distinct=distinct,
            )
        metrics: list[dict] | None = [] if collect_metrics else None
        last_ckpt = time.perf_counter()

        # wave-timeline observatory state: sampling stride from the
        # telemetry facade (0 = every wave stays fused), per-path wave
        # seconds for the overhead estimate in the summary, HBM
        # watermark tracker (analytic — no device reads), and the
        # previous wave's telemetry-emission cost (tel_s is only known
        # one wave late; 0.0 on wave 1)
        tl_every = int(getattr(tel, "timeline_every", 0) or 0)
        tl_waves = 0
        tl_wave_s: list[float] = []
        fused_wave_s: list[float] = []
        memwatch = MemWatch(tel) if tel.active else None
        ladder_bytes = sum(
            (self.R0 << i) * 8 for i in range(self._wave_geom() + 1)
        )
        tel_s_last = 0.0

        while fcount and violation is None:
            if preempt is not None and preempt.requested:
                # SIGTERM/SIGINT honored at the wave boundary: the final
                # snapshot block below writes the checkpoint, the CLI
                # maps exit_cause "preempted" to rc 4
                exhausted = False
                exit_cause = "preempted"
                tel.event(
                    "preempt", signame=preempt.signame, depth=depth,
                    checkpoint=checkpoint_path,
                )
                break
            if chaos is not None:
                chaos.wave_start(depth + 1)
            if max_depth is not None and depth >= max_depth:
                exhausted = False
                exit_cause = "max_depth"
                break
            if time_budget_s is not None and time.perf_counter() - t0 > time_budget_s:
                exhausted = False
                exit_cause = "time_budget"
                break
            # capacity guard: the top-level absorb truncates at TOPSZ
            # lanes, which is only sound while every real fingerprint is
            # guaranteed to fit; FCAP bounds the wave's new states
            # (conservative vs the round-3 post-wave check, but it spills
            # a resumable checkpoint before raising)
            if scount + min(self.FCAP, fcount * self.VC) > self.TOPSZ:
                if checkpoint_path is not None:
                    self._save_checkpoint(
                        checkpoint_path, frontier, jparent, jcand,
                        fcount, scount, distinct, total, terminal,
                        depth, base_gid, gen_prev, depth_counts, cov_h,
                    )
                raise CapacityOverflow(
                    "seen-set capacity overflow; raise max_seen_cap",
                    what=("seen",), bits=self.SEEN_OVF_BIT,
                    checkpoint_saved=checkpoint_path is not None,
                )
            # a wave whose new count could outgrow even the MAXIMALLY
            # grown frontier will abort mid-wave (not resumable), so
            # spill a resumable snapshot BEFORE attempting it (throttled:
            # every wave in this regime would re-export the whole seen
            # set, which can rival wave time on wide plateaus)
            if (
                checkpoint_path is not None
                and fcount * self.HEADROOM > self.MAX_FCAP
                and time.perf_counter() - last_ckpt > checkpoint_every_s / 4
            ):
                self._save_checkpoint(
                    checkpoint_path, frontier, jparent, jcand, fcount,
                    scount, distinct, total, terminal, depth, base_gid,
                    gen_prev, depth_counts, cov_h,
                )
                last_ckpt = time.perf_counter()
            tw = time.perf_counter()
            tl_sample = tl_every > 0 and (depth + 1) % tl_every == 0
            stage_s = (
                {s: 0.0 for s in ("expand", "canon", "dedup", "emit",
                                  "seen_merge", "checkpoint")}
                if tl_sample else None
            )
            # ONE dispatch per wave: the chunk loop runs device-side
            # (_wave_step) and returns the wave's new fingerprints as a
            # binary-counter ladder, merged into the single seen run
            # below AFTER the overflow check (so an aborted wave leaves
            # the seen-set untouched and the run trivially resumable).
            # A sampled --timeline wave runs the same stages host-driven
            # with per-stage timing instead (bit-identical, parity-
            # gated); untimed waves keep the fused program.
            with tel.wave_annotation(depth + 1):
                if tl_sample:
                    out = self._run_timeline_wave(
                        frontier, next_buf, jparent, jcand, viol, stats,
                        memo, cov, fcount, base_gid, stage_s,
                    )
                else:
                    out = self._wave_fn(
                        frontier, next_buf, jparent, jcand, viol, stats,
                        memo, cov, np.int32(fcount), np.int32(base_gid),
                        self._occ_one, self._seen,
                    )
                next_buf, jparent, jcand, viol, stats, memo, cov = out[:7]
                ladder = out[7:]
                # one host round-trip per wave: stats, the invariant
                # fold and the coverage block fetched together (two
                # device_gets double the tunnel RTT on small configs,
                # where per-wave latency dominates) — and telemetry
                # rides this same snapshot
                # lint: sync-ok(once-per-wave snapshot)
                stats_h, viol_h, cov_w = jax.device_get((stats, viol, cov))
            device_s = time.perf_counter() - tw
            stats_h = np.asarray(stats_h)
            viol_h = np.asarray(viol_h)
            ncount = int(stats_h[0])
            ovf_bits = int(stats_h[4])
            if chaos is not None:
                # spurious frontier-overflow injection: the wave really
                # completed, but we abort exactly as a real bit-4 would —
                # the wave-start checkpoint below is still consistent
                # because nothing (cov/seen/journal counts) was adopted
                ovf_bits = chaos.ovf_bits(ovf_bits, depth + 1, 4)
            if ovf_bits:
                saved = ""
                if checkpoint_path is not None:
                    # the aborted wave never touched the seen run (its
                    # fingerprints live in the discarded ladder), and the
                    # frontier buffer and journal[:jcount] are untouched
                    # (only next_buf and journal rows past jcount were
                    # written), so the wave-start state is exactly
                    # reconstructible and resumable (round-4 advisor #1)
                    self._save_checkpoint(
                        checkpoint_path, frontier, jparent, jcand, fcount,
                        scount, distinct, total, terminal, depth, base_gid,
                        gen_prev, depth_counts, cov_h,
                    )
                    saved = f"; wave-start checkpoint saved to {checkpoint_path}"
                raise CapacityOverflow(
                    f"device BFS capacity overflow (bits={ovf_bits:04b}: "
                    "1=msg-slots 2=valid_per_state/valid_per_group "
                    "4=frontier_cap 8=journal_cap)"
                    + saved,
                    what=tuple(
                        name for bit, name in self.OVF_NAMES
                        if ovf_bits & bit
                    ),
                    bits=ovf_bits,
                    checkpoint_saved=checkpoint_path is not None,
                )
            # the wave completed: adopt its cumulative coverage (the
            # aborted-wave path above deliberately keeps the wave-start
            # cov_h, matching the discarded ladder/journal rows)
            cov_h = np.asarray(cov_w, dtype=np.int64)
            n_gen = int(stats_h[2])
            wave_gen = n_gen - gen_prev
            total += wave_gen
            gen_prev = n_gen
            terminal = int(stats_h[3])
            if ncount == 0:
                exit_cause = "exhausted"
                break
            scount += ncount
            # fold the wave ladder into the single seen run (device-side
            # sort-concat; the merge-program signature set is warmed by
            # precompile)
            with tel.annotate("seen_merge"):
                tm = time.perf_counter()
                self._merge_seen(ladder, scount)
                merge_s = time.perf_counter() - tm
            device_s += merge_s
            if stage_s is not None:
                stage_s["seen_merge"] += merge_s
            depth += 1
            distinct += ncount
            depth_counts.append(ncount)
            if self.invariants:
                for k, name in enumerate(self.invariants):
                    if viol_h[k] != I32_MAX:
                        violation = Violation(
                            invariant=name, global_id=n0 + int(viol_h[k]), depth=depth
                        )
                        break
            base_gid = n0 + int(stats_h[1]) - ncount
            # (the wave-new/overflow stats lanes reset in-program on the
            # next wave's first chunk — no host re-upload needed)
            frontier, next_buf = next_buf, frontier
            prev_fcount = fcount
            fcount = ncount
            frontier, next_buf, jparent, jcand = self._maybe_grow(
                ncount, frontier, next_buf, jparent, jcand, scount - n0
            )
            ckpt_s = 0.0
            if (
                checkpoint_path is not None
                and violation is None  # a saved file must not mask a violation
                and time.perf_counter() - last_ckpt > checkpoint_every_s
            ):
                tck = time.perf_counter()
                self._save_checkpoint(
                    checkpoint_path, frontier, jparent, jcand, fcount,
                    scount, distinct, total, terminal, depth, base_gid,
                    gen_prev, depth_counts, cov_h,
                )
                last_ckpt = time.perf_counter()
                ckpt_s = last_ckpt - tck
                if stage_s is not None:
                    stage_s["checkpoint"] += ckpt_s
            memo_hits = int(stats_h[5])
            wave_memo = memo_hits - memo_prev
            memo_prev = memo_hits
            wave_s_val = time.perf_counter() - tw
            if tl_every:
                (tl_wave_s if tl_sample else fused_wave_s).append(wave_s_val)
                tl_waves += 1 if tl_sample else 0
            hbm_frac = None
            if memwatch is not None:
                # analytic live-bytes: what the run's geometry holds in
                # device memory right now (allocated buffers — fill-
                # level gauges ride the wave event separately). Changes
                # only on growth / seen-resize waves, so the memwatch
                # event stream stays low-volume by construction.
                hbm_frac = memwatch.update(depth, depth, {
                    "frontier": 2 * (self.FCAP + self.VC) * 4 * W,
                    "journal": 2 * (self.JCAP + self.VC) * 4,
                    "seen": int(self._seen.shape[0]) * 8,
                    "wave_ladder": ladder_bytes,
                    "chunk": self.VC * (4 * W + 8),
                    "memo": self.MCAP * 16 if self._use_memo else 0,
                })
            if tel.active or metrics is not None or verbose:
                el = time.perf_counter() - t0
                wm = {
                    "depth": depth,
                    "frontier": prev_fcount,
                    "new": ncount,
                    "distinct": distinct,
                    "generated": wave_gen,
                    "generated_total": total,
                    "terminal": terminal,
                    "dedup_hit_rate": round(1.0 - ncount / max(1, wave_gen), 4),
                    "canon_memo_hits": wave_memo,
                    "canon_memo_hit_rate": round(
                        wave_memo / max(1, wave_gen), 4
                    ),
                    "overflow_bits": ovf_bits,
                    "wave_s": round(time.perf_counter() - tw, 3),
                    "elapsed_s": round(el, 3),
                    "distinct_per_s": round(distinct / el, 1),
                    "lsm_runs": 1,
                    "lsm_lanes": int(self._seen.shape[0]),
                    # emit gauges (round 6): rows appended this wave,
                    # bytes the emit WROTE (one [VC, W] i32 block + two
                    # VC i32 journal lanes per chunk — vs the retired
                    # scatter's full-capacity touch), and how full the
                    # frontier buffer got — the stall watchdog reads
                    # these to attribute growth/cliff waves
                    "emit_rows": ncount,
                    "emit_bytes": (
                        (prev_fcount + C - 1) // C
                    ) * self.VC * (4 * W + 8),
                    "frontier_fill": round(ncount / self.FCAP, 4),
                    # sparse-expand gauges (derived from stats the wave
                    # already fetched — zero extra device syncs):
                    # enabled fraction of the dense [chunk, A] candidate
                    # grid this wave (the guard-first win scales with
                    # its inverse), and whether the apply budget plan
                    # overflowed (always 0 on surviving waves — the
                    # abort above fires first; host BFS reports real
                    # extra-batch counts here)
                    "enabled_density": round(
                        wave_gen / max(1, prev_fcount * self.A), 4
                    ),
                    "expand_budget_ovf": (ovf_bits >> 1) & 1,
                    # host-side phase split (perf_counter brackets the
                    # loop already runs — zero extra device syncs):
                    # device dispatch+sync vs checkpoint I/O vs residual
                    # host bookkeeping; tel_s is the PREVIOUS wave's
                    # telemetry-emission cost (only known one wave late)
                    "device_s": round(device_s, 4),
                    "host_s": round(
                        max(0.0, wave_s_val - device_s - ckpt_s), 4
                    ),
                    "ckpt_s": round(ckpt_s, 4),
                    "tel_s": round(tel_s_last, 4),
                    "exchange_share": None,
                    "hbm_frac": (
                        round(hbm_frac, 4) if hbm_frac is not None else None
                    ),
                }
                t_tel = time.perf_counter()
                tel.wave(wm)
                if tel.active:
                    tel.coverage(self._coverage_fields(
                        depth, cov_h, scount, depth_counts,
                    ))
                    if tl_sample:
                        tel.event(
                            "timeline",
                            wave=depth, depth=depth, every=tl_every,
                            stages={
                                k: round(v, 5)
                                for k, v in stage_s.items() if v > 0
                            },
                            wave_s=round(wave_s_val, 4),
                        )
                if metrics is not None:
                    metrics.append(wm)
                if verbose:
                    print(
                        f"depth {depth}: frontier {ncount}, distinct {distinct}, "
                        f"total {total}, {distinct/el:.0f} distinct/s",
                        file=sys.stderr,
                    )
                tel_s_last = time.perf_counter() - t_tel

        if checkpoint_path is not None and violation is None and not exhausted:
            # budget/depth-capped exit: the loop broke at a wave boundary,
            # so save a final resumable snapshot (the periodic timer alone
            # can leave no checkpoint at all on short-budget runs)
            self._save_checkpoint(
                checkpoint_path, frontier, jparent, jcand, fcount,
                scount, distinct, total, terminal, depth, base_gid,
                gen_prev, depth_counts, cov_h,
            )

        self._jparent = jparent
        self._jcand = jcand
        self._jcount = int(np.asarray(jax.device_get(stats))[1])
        # keep the run-final memo resident: the donated input buffers are
        # dead, but the last wave's OUTPUT table is live — the profiler
        # times the memoized canon against this realistically-warmed
        # table (checker/profile.py)
        self._memo.table = memo

        # canon-memo fill ratio: ONE device reduction, at run end only
        # (mid-run it would add a per-wave sync), and computed whether or
        # not telemetry is attached so instrumented and bare runs keep
        # identical jax.device_get call counts (tests/test_obs.py)
        if self._use_memo:
            filled = int(np.asarray(jax.device_get(
                jnp.sum(ne_u64(memo[:, 0], U64_MAX))
            )))
            memo_fill = round(filled / max(1, self.MCAP), 4)
        else:
            memo_fill = None

        dt = time.perf_counter() - t0
        if violation is not None:
            exit_cause = "violation"
        elif exit_cause is None:
            exit_cause = "exhausted"
        if tel.active:
            cf = self._coverage_fields(depth, cov_h, scount, depth_counts)
            cf["canon_memo_fill"] = memo_fill
            tel.coverage(cf, final=True)
        # timeline overhead estimate: mean sampled vs mean fused wave
        # seconds (null until both kinds of wave have run) — the
        # "--timeline=N costs < 5% end-to-end" contract is checked from
        # this summary field
        tl_extras = {}
        if tl_every:
            overhead = None
            if tl_wave_s and fused_wave_s:
                mf = sum(fused_wave_s) / len(fused_wave_s)
                mt = sum(tl_wave_s) / len(tl_wave_s)
                if mf > 0:
                    overhead = round((mt - mf) / (mf * tl_every), 4)
            tl_extras = {
                "timeline_every": tl_every,
                "timeline_waves": tl_waves,
                "timeline_overhead": overhead,
            }
        tel.close_run({
            "engine": "device",
            "ident": self._ckpt_ident(),
            "exit_cause": exit_cause,
            "violation": violation.invariant if violation else None,
            "distinct": distinct,
            "total": total,
            "depth": depth,
            "terminal": terminal,
            "seconds": round(dt, 3),
            "distinct_per_s": round(distinct / dt, 1) if dt > 0 else 0.0,
            "exhausted": exhausted and violation is None,
            "peak_frontier_cap": self.FCAP,
            "peak_journal_cap": self.JCAP,
            "seen_lanes": int(self._seen.shape[0]),
            "canon_memo_hit_rate": round(memo_prev / max(1, gen_prev), 4),
            **tl_extras,
            **(memwatch.summary_fields() if memwatch is not None else {}),
        })
        trace = self.reconstruct_trace(violation) if violation else None
        res = CheckResult(
            distinct=distinct,
            total=total,
            depth=depth,
            depth_counts=depth_counts,
            violation=violation,
            terminal=terminal,
            seconds=dt,
            states_per_sec=distinct / dt if dt > 0 else 0.0,
            exhausted=exhausted and violation is None,
            trace=trace,
            metrics=metrics,
            coverage=(
                [[int(x) for x in row] for row in cov_h]
                if self.n_actions else None
            ),
            exit_cause=exit_cause,
        )
        return res

    def run_fleet(
        self,
        job_names: list[str] | None = None,
        telemetry=None,
        checkpoint_dir: str | None = None,
        checkpoint_every_s: float = 300.0,
        checkpoint_keep: int = rckpt.DEFAULT_KEEP,
        resume: bool = False,
        skip: tuple[str, ...] = (),
        supervise: int | None = None,
        chaos_by_job: dict | None = None,
        recovery_stats: dict | None = None,
        **run_kw,
    ) -> list:
        """Fleet queue arm: run a fleet-bound model's jobs one at a time
        through THIS engine instance. ``fleet_select(j)`` changes only
        which job's constants get stamped into the init states — the
        compiled programs are shared, so every job after the first is a
        jit-cache hit (one precompile per layout group). Telemetry is
        job-tagged into one multiplexed stream (obs.JobTaggedTelemetry);
        each job checkpoints to its OWN lineage file under
        ``checkpoint_dir`` (resilience/ckpt.py generations, named by
        ``resilience.lineage_name`` so sanitizer collisions between job
        names cannot alias two lineages), so the supervisor restarts /
        resumes only the failed job. Jobs named in ``skip`` (fleet-level
        resume) yield None in the result list.

        ``supervise``: when set, each job runs under the resilience
        supervisor with that per-job recovery budget; empty-override
        recoveries reuse this instance's compiled programs (zero
        recompiles), and a job whose budget is spent contributes its
        terminal exception to the results list instead of killing the
        fleet. ``chaos_by_job`` maps job name -> ChaosInjector for that
        job only; ``recovery_stats`` is filled in place with job name ->
        recovery count."""
        import os

        from ..obs.collector import JobTaggedTelemetry

        model = self.model
        J = model.fleet_jobs
        if J == 0:
            raise ValueError(
                "run_fleet needs a fleet-bound model (fleet_bind)"
            )
        names = list(job_names) if job_names else [f"job{j}" for j in range(J)]
        if len(names) != J:
            raise ValueError(f"{len(names)} job names for {J} jobs")
        results = []
        try:
            for j, name in enumerate(names):
                if name in skip:
                    results.append(None)
                    continue
                model.fleet_select(j)
                kw = dict(run_kw)
                if telemetry is not None:
                    kw["telemetry"] = JobTaggedTelemetry(telemetry, name)
                if chaos_by_job and name in chaos_by_job:
                    kw["chaos"] = chaos_by_job[name]
                if checkpoint_dir is not None:
                    ck = os.path.join(
                        checkpoint_dir, rckpt.lineage_name(name, j))
                    kw.setdefault("checkpoint_path", ck)
                    kw.setdefault("checkpoint_every_s", checkpoint_every_s)
                    kw.setdefault("checkpoint_keep", checkpoint_keep)
                    if resume and os.path.exists(ck):
                        kw.setdefault("resume", ck)
                if supervise is None:
                    results.append(self.run(**kw))
                    continue
                results.append(self._run_supervised(
                    kw, int(supervise), j, name, recovery_stats))
        finally:
            model.fleet_select(None)
        return results

    def _run_supervised(self, kw, budget, job_index, name, recovery_stats):
        """One fleet job under the resilience supervisor. Returns the
        run result, or the terminal exception object when the job's
        recovery budget is spent (the fleet driver maps it to an
        ``unrecoverable`` JobResult)."""
        from ..resilience import (
            CheckpointMismatch,
            UnrecoverableError,
            supervise as _supervise,
        )

        def factory(overrides):
            return self if not overrides else self._rebuild(overrides)

        stats: dict = {}
        try:
            res = _supervise(
                factory, kw, max_retries=budget, backoff_base=0.0,
                seed=job_index, telemetry=kw.get("telemetry"),
                stats_out=stats,
            )
        except (UnrecoverableError, CheckpointMismatch) as exc:
            res = exc
        if recovery_stats is not None:
            recovery_stats[name] = int(stats.get("recoveries", 0))
        return res

    def _coverage_fields(self, depth, cov_h, scount, depth_counts) -> dict:
        """Dedup-structure gauges + the per-action block for a coverage
        event, all from values the wave loop already holds on host."""
        return {
            "depth": depth,
            "actions": [[int(x) for x in row] for row in cov_h],
            "actions_total": self.n_actions,
            "actions_fired": int(np.count_nonzero(cov_h[:, 1]))
            if self.n_actions else 0,
            "seen_lanes": [int(self._seen.shape[0])],
            "seen_real": int(scount),
            "probe_runs": 1,  # single consolidated seen run (round 5)
            "frontier_hist": [int(x) for x in depth_counts],
            "canon_memo_fill": None,  # final snapshot only
        }

    def _telemetry_manifest(self) -> dict:
        """Run-provenance fields of the telemetry manifest event (all
        MANIFEST_KEYS except the auto-added "event")."""
        dev = jax.devices()[0]
        ident = self._ckpt_ident()
        return {
            "engine": "device",
            "ident": ident,
            "hashv": hashv_of(ident),
            "model": self.model.name,
            "platform": dev.platform,
            "device": str(getattr(dev, "device_kind", dev.platform)),
            "device_count": 1,
            "chunk": self.chunk,
            "frontier_cap": self.FCAP,
            "journal_cap": self.JCAP,
            "max_seen_cap": self.MAX_SCAP,
            "valid_cap": self.VC,
            "canon_memo_cap": self.MCAP if self._use_memo else 0,
            "symmetry": bool(self.canon.symmetry),
            "invariants": list(self.invariants),
            "action_names": list(getattr(self.model, "ACTION_NAMES", ())),
            "when": time.strftime("%Y-%m-%dT%H:%M:%S"),
        }

    def _ckpt_ident(self) -> str:
        """Everything the saved run's soundness depends on: symmetry mode
        changes the canonical fingerprints, and the INVARIANT SET must
        match too — states explored before the checkpoint (including Init)
        were only checked against the original run's invariants, so a
        resume with different invariants would silently skip them."""
        # hashv marks fingerprint-formula revisions. v5 (round 6: the
        # 1-WL signature refinement iterates to a bounded depth, which
        # changes the admissible permutation set — and therefore the
        # canonical representative — of signature-tied states), so all
        # pre-v5 checkpoints are refused on load; the refinement depth
        # is part of the formula and recorded alongside. The canon memo
        # and the tie-group-local tier-3 are value-preserving and do
        # NOT participate in the identity.
        wl = getattr(self.canon, "refine_rounds", 1)
        return (
            f"{self.model.name}/{self.model.p}/W={self.W}"
            f"/sym={self.canon.symmetry}/seed={self.canon.seed}"
            f"/hashv=5/wl={wl}/inv={','.join(self.invariants)}"
        )

    def _save_checkpoint(
        self, path, frontier, jparent, jcand, fcount, scount, distinct,
        total, terminal, depth, base_gid, gen_prev, depth_counts,
        coverage,
    ):
        """Spill the resumable run state to an .npz (atomic rename).
        Saved at wave boundaries only, so the arrays are consistent."""
        with self._tel.annotate("checkpoint"):
            self._write_checkpoint(
                path, frontier, jparent, jcand, fcount, scount, distinct,
                total, terminal, depth, base_gid, gen_prev, depth_counts,
                coverage,
            )

    def _write_checkpoint(
        self, path, frontier, jparent, jcand, fcount, scount, distinct,
        total, terminal, depth, base_gid, gen_prev, depth_counts,
        coverage,
    ):
        n0 = len(self._init_distinct)
        jcount = scount - n0
        seen = self._lsm_export()
        assert len(seen) == scount, f"LSM export {len(seen)} != scount {scount}"
        # crash-safe write (resilience/ckpt.py): tmp + fsync + rename,
        # format_version + content hash embedded, previous generations
        # rotated so a torn write costs one interval, not the run
        rckpt.save_npz(
            path,
            dict(
                version=1,  # engine payload layout revision (unchanged)
                spec=self._ckpt_ident(),
                fcount=fcount,
                scount=scount,
                jcount=jcount,
                frontier=np.asarray(jax.device_get(frontier[:fcount])),
                seen=seen,
                jparent=np.asarray(jax.device_get(jparent[:jcount])),
                jcand=np.asarray(jax.device_get(jcand[:jcount])),
                distinct=distinct,
                total=total,
                terminal=terminal,
                depth=depth,
                base_gid=base_gid,
                gen_prev=gen_prev,
                depth_counts=np.asarray(depth_counts, dtype=np.int64),
                coverage=np.asarray(coverage, dtype=np.int64),
            ),
            keep=getattr(self, "_ckpt_keep", rckpt.DEFAULT_KEEP),
            chaos=getattr(self, "_chaos", None),
        )

    def _check_init(self, init_d: np.ndarray) -> Violation | None:
        for name in self.invariants:
            ok = np.asarray(jax.device_get(self.model.invariants[name](init_d)))
            bad = np.nonzero(~ok)[0]
            if len(bad):
                return Violation(invariant=name, global_id=int(bad[0]), depth=0)
        return None

    # ---------------- trace reconstruction ----------------

    def reconstruct_trace(self, violation: Violation) -> list[tuple[str, dict]]:
        """Parent-pointer replay, identical semantics to BFSChecker's
        (journal is flat (parent gid, candidate) arrays here)."""
        model = self.model
        n0 = len(self._init_distinct)
        jc_n = self._jcount
        jp = np.asarray(jax.device_get(self._jparent))[:jc_n]
        jc = np.asarray(jax.device_get(self._jcand))[:jc_n]
        chain: list[int] = []
        gid = violation.global_id
        while gid >= n0:
            chain.append(int(jc[gid - n0]))
            gid = int(jp[gid - n0])
        chain.reverse()
        state = self._init_distinct[gid]
        out = [("Initial predicate", model.decode(state))]
        expand1 = jax.jit(model._expand1)
        for cand in chain:
            succs, valid, rank, _ovf = jax.device_get(expand1(state))
            assert valid[cand], "journalled candidate not enabled on replay"
            state = np.asarray(succs[cand])
            out.append(
                (model.action_label(int(rank[cand]), cand), model.decode(state))
            )
        return out
