"""Per-stage profiling of the DeviceBFS hot loop (SURVEY.md §5.1).

The chunk pipeline is one fused XLA program in production; to attribute
time we re-run each stage as its own jitted function on REAL buffers
captured from a warmed run (a depth-capped run spills a checkpoint, and
the profiler rebuilds the chunk inputs from it). Stages mirror
``DeviceBFS._chunk_step`` 1:1:

  null_dispatch  a no-op jit call: the dispatch/tunnel floor every other
                 row also pays once (subtract it when reading raw ms)
  expand       vmap of the per-action successor kernels
  compact      valid-lane compaction (cumsum + one-hot select)
  canon        VIEW + SYMMETRY canonical fingerprints (the P-permutation
               reduction — the 5-server hot spot, SURVEY.md §7.2)
  probe        membership probe of every LSM seen-run (searchsorted each)
  run_emit     sorting the chunk's new fingerprints into its R0-lane run
  scatter      next-frontier + journal scatter
  invariants   batched invariant kernels
  lsm_merge_2r0  one level-0 run merge (sort of 2*R0 lanes); the cascade
                 triggers a level-l merge every 2^(l+1) chunks, so the
                 AMORTIZED per-chunk merge cost (reported in per_wave_s)
                 is a short geometric-ish series fitted from this point

Per-wave cost model: chunks_per_wave * (fused chunk + amortized merge).
``fused_chunk`` times the production program for cross-checking (the sum
of stages normally OVERESTIMATES it — XLA fuses away intermediates).
"""

from __future__ import annotations

import math
import os
import tempfile
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.hashing import U64_MAX, ne_u64, sort_u64
from .device_bfs import DeviceBFS
from .util import probe_sorted as _probe


def _time(fn, *args, reps: int = 5, inner: int = 1) -> float:
    """Median wall seconds of fn(*args) with block_until_ready."""
    out = fn(*args)
    jax.block_until_ready(out)  # warm / compile
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(inner):
            out = fn(*args)
        jax.block_until_ready(out)
        ts.append((time.perf_counter() - t0) / inner)
    return float(np.median(ts))


def profile_stages(
    model,
    invariants: tuple[str, ...] = (),
    symmetry: bool = True,
    chunk: int = 1024,
    frontier_cap: int = 1 << 17,
    seen_cap: int = 1 << 21,
    warm_depth: int = 8,
    reps: int = 5,
    **caps,
) -> dict:
    """Profile the chunk pipeline on a realistic frontier.

    Runs a depth-capped BFS to ``warm_depth`` (checkpoint spill), then
    rebuilds one representative chunk's inputs from the spill and times
    each stage. Returns a dict with per-stage seconds, per-wave totals
    and workload shape facts.
    """
    dev = DeviceBFS(
        model, invariants=invariants, symmetry=symmetry, chunk=chunk,
        frontier_cap=frontier_cap, seen_cap=seen_cap, **caps,
    )
    with tempfile.TemporaryDirectory() as td:
        ck_path = os.path.join(td, "warm.npz")
        res = dev.run(max_depth=warm_depth, checkpoint_path=ck_path)
        if not os.path.exists(ck_path):
            raise RuntimeError(
                f"workload exhausted at depth {res.depth} < warm_depth="
                f"{warm_depth}; no frontier left to profile"
            )
        ck = np.load(ck_path, allow_pickle=False)
        frontier_h = np.asarray(ck["frontier"])  # [fcount, W]
        seen_h = np.asarray(ck["seen"])  # [scount]
    # caps may have grown during the warm run
    C, A, W, VC = dev.chunk, dev.A, dev.W, dev.VC
    FCAP, JCAP, R0 = dev.FCAP, dev.JCAP, dev.R0
    fcount, scount = len(frontier_h), len(seen_h)

    batch_h = frontier_h[:C]
    if len(batch_h) < C:
        batch_h = np.concatenate(
            [batch_h, np.repeat(batch_h[-1:], C - len(batch_h), axis=0)]
        )
    batch = jnp.asarray(batch_h)
    # the warmed seen-set as LSM runs (same layout production probes)
    dev._lsm.seed(np.sort(seen_h.astype(np.uint64)))
    runs = tuple(dev._lsm.runs)
    occ_dev = jnp.asarray(np.asarray(dev._lsm.occ, dtype=bool))
    occ_runs = tuple(r for r, o in zip(dev._lsm.runs, dev._lsm.occ) if o)

    out: dict = {
        "workload": {
            "model": model.name,
            "warm_depth": int(res.depth),
            "frontier": int(fcount),
            "seen": int(scount),
            "distinct": int(res.distinct),
        },
        "geometry": {
            "chunk": C, "A": A, "W": W, "VC": VC, "R0": R0,
            "FCAP": FCAP, "JCAP": JCAP, "lsm_levels": len(runs),
            "perms": int(dev.canon.P), "symmetry": bool(symmetry),
        },
        "stages_s": {},
    }
    st = out["stages_s"]

    # ---- stage 0: dispatch floor ----
    null_j = jax.jit(lambda x: x + 1)
    st["null_dispatch"] = _time(null_j, jnp.zeros((8,), jnp.int32), reps=reps)

    # ---- stage 1: expand ----
    expand = jax.jit(lambda b: jax.vmap(model._expand1)(b))
    st["expand"] = _time(expand, batch, reps=reps)
    succs, valid, _rank, _ovf = expand(batch)

    # ---- stage 2: compact ----
    def compact(succs, valid):
        vflat = valid.reshape(-1)
        vpos = jnp.cumsum(vflat) - 1
        sdst = jnp.where(vflat, jnp.minimum(vpos, VC), VC)
        sel = (
            jnp.full((VC + 1,), C * A, jnp.int32)
            .at[sdst]
            .set(jnp.arange(C * A, dtype=jnp.int32))[:VC]
        )
        selv = sel < C * A
        flatp = jnp.concatenate(
            [succs.reshape(C * A, W), jnp.zeros((1, W), jnp.int32)], axis=0
        )
        return flatp[sel], selv

    compact_j = jax.jit(compact)
    st["compact"] = _time(compact_j, succs, valid, reps=reps)
    flatc, selv = compact_j(succs, valid)

    # ---- stage 3: canonical fingerprints ----
    canon_j = jax.jit(dev.canon._fingerprints)
    st["canon"] = _time(canon_j, flatc, reps=reps)
    fps = jnp.where(selv, canon_j(flatc), U64_MAX)

    # ---- stage 4: probe the occupied LSM runs (production skips empty
    # levels via cond, so the occupied set is what a chunk pays for) ----
    def probe_all(f, *rs):
        hit = jnp.zeros(f.shape, bool)
        for r in rs:
            hit = hit | _probe(r, f)
        return hit

    st["probe"] = _time(jax.jit(probe_all), fps, *occ_runs, reps=reps)

    # ---- stage 5: emit the chunk's sorted run ----
    def run_emit(f):
        nr = sort_u64(f)
        if R0 > VC:
            nr = jnp.concatenate(
                [nr, jnp.full((R0 - VC,), U64_MAX, jnp.uint64)]
            )
        return nr

    st["run_emit"] = _time(jax.jit(run_emit), fps, reps=reps)

    # ---- stage 5b: scatter into frontier + journal ----
    def scatter(flatc, fps):
        new = ne_u64(fps, U64_MAX)
        npos = (jnp.cumsum(new) - 1).astype(jnp.int32)
        bdst = jnp.where(new, jnp.minimum(npos, FCAP), FCAP)
        nb = jnp.zeros((FCAP + 1, W), jnp.int32).at[bdst].set(flatc)
        jdst = jnp.where(new, jnp.minimum(npos, JCAP), JCAP)
        jp = jnp.zeros((JCAP + 1,), jnp.int32).at[jdst].set(bdst)
        return nb, jp

    st["scatter"] = _time(jax.jit(scatter), flatc, fps, reps=reps)

    # ---- stage 6: invariants ----
    if invariants:
        inv_j = jax.jit(
            lambda v: [model.invariants[n](v) for n in invariants]
        )
        st["invariants"] = _time(inv_j, flatc, reps=reps)
    else:
        st["invariants"] = 0.0

    # ---- LSM merge costs (level 0 measured; series fitted n log n) ----
    r0a = run_emit(fps)
    st["lsm_merge_2r0"] = _time(
        jax.jit(lambda a, b: sort_u64(jnp.concatenate([a, b]))), r0a, r0a,
        reps=reps,
    )
    null = st["null_dispatch"]
    a_fit = max(st["lsm_merge_2r0"] - null, 1e-6) / (2 * R0 * math.log2(2 * R0))
    n_levels = max(1, len(runs))
    amortized = sum(
        a_fit * (R0 << (l + 1)) * math.log2(R0 << (l + 1)) / (1 << (l + 1))
        for l in range(n_levels)
    )

    # ---- the fused production program, for cross-check ----
    frontier_d = jnp.asarray(
        np.concatenate([
            frontier_h,
            np.zeros((FCAP + 1 - fcount, W), np.int32),
        ])
    )

    def fused_once():
        # donated args (next_buf, journal, viol, stats) must be rebuilt
        # per call — donation invalidates their buffers
        nb = jnp.zeros((FCAP + 1, W), jnp.int32)
        jp = jnp.zeros((JCAP + 1,), jnp.int32)
        jc = jnp.zeros((JCAP + 1,), jnp.int32)
        viol = jnp.full((max(1, len(invariants)),), np.int32(2**31 - 1), jnp.int32)
        stats = jnp.zeros((5,), jnp.int64)
        args = [frontier_d, nb, jp, jc, viol, stats,
                np.int32(0), np.int32(min(fcount, C)), np.int32(0),
                occ_dev, jnp.asarray(True), *runs]
        jax.block_until_ready(args)
        t0 = time.perf_counter()
        r = dev._chunk_fn(*args)
        jax.block_until_ready(r)
        return time.perf_counter() - t0

    fused_once()  # compile
    st["fused_chunk"] = float(np.median([fused_once() for _ in range(reps)]))

    timed = ["expand", "compact", "canon", "probe", "run_emit", "scatter"]
    if invariants:
        timed.append("invariants")
    # each TIMED stage row pays one dispatch floor
    chunk_sum = sum(st[k] for k in timed) - len(timed) * null
    n_chunks = max(1, (fcount + C - 1) // C)
    per_chunk = st["fused_chunk"] + amortized
    out["per_wave_s"] = {
        "chunks_per_wave": n_chunks,
        "stage_sum_per_chunk": round(chunk_sum, 6),
        "fused_per_chunk": round(st["fused_chunk"], 6),
        "lsm_merge_amortized_per_chunk": round(amortized, 6),
        "wave_estimate": round(n_chunks * per_chunk, 6),
        "merge_share": round(amortized / per_chunk, 4),
    }
    return out


def render(prof: dict) -> str:
    w, g, s = prof["workload"], prof["geometry"], prof["stages_s"]
    lines = [
        f"workload: {w['model']} depth={w['warm_depth']} "
        f"frontier={w['frontier']} seen={w['seen']}",
        f"geometry: chunk={g['chunk']} VC={g['VC']} R0={g.get('R0')} "
        f"FCAP={g['FCAP']} lsm_levels={g.get('lsm_levels')} "
        f"perms={g['perms']}",
        f"{'stage':<16}{'ms':>10}{'share':>8}",
    ]
    skip = ("fused_chunk", "lsm_merge_2r0", "null_dispatch")
    null = s.get("null_dispatch", 0.0)
    tot = sum(max(0.0, v - null) for k, v in s.items() if k not in skip)
    for k, v in s.items():
        share = max(0.0, v - null) / tot if k not in skip and tot else 0
        lines.append(f"{k:<16}{v * 1e3:>10.2f}{share:>8.1%}")
    pw = prof["per_wave_s"]
    lines.append(
        f"wave: {pw['chunks_per_wave']} chunks x "
        f"({pw['fused_per_chunk']*1e3:.2f} ms fused + "
        f"{pw['lsm_merge_amortized_per_chunk']*1e3:.2f} ms amortized merge)"
    )
    return "\n".join(lines)
