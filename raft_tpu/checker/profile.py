"""Per-stage profiling of the DeviceBFS hot loop (SURVEY.md §5.1).

The chunk pipeline is one fused XLA program in production; to attribute
time we re-run each stage as its own jitted function on REAL buffers
captured from a warmed run (a depth-capped run spills a checkpoint, and
the profiler rebuilds the chunk inputs from it). Stages mirror
``DeviceBFS._chunk_step`` 1:1:

  null_dispatch  a no-op jit call: the dispatch/tunnel floor every other
                 row also pays once (the rendered table's `net` column
                 and all shares have it subtracted)
  guards       the guard pass of guard-first sparse expansion: valid/
               rank/ovf over the dense [chunk, A] candidate grid with
               no W-wide successor rows (DCE-derived from _expand1);
               0.0 for models without the sparse expand contract
  apply        the budgeted apply pass: per-group vmapped successor
               construction over the compacted enabled worklist only
               (models/base.py sparse_apply); 0.0 when not applicable
  expand       vmap of the full per-action successor kernels over every
               [chunk, A] lane — the production expand for legacy dense
               models, a RETIRED diagnostic row (excluded from the
               stage sum, like `scatter`) when the sparse path is
               active, kept so regenerated profiles show the dense-vs-
               sparse cost side by side
  compact      valid-lane compaction (cumsum + one-hot select; under
               the sparse path the [VC, W] successor gather lives in
               `apply`, so this row times the worklist build alone)
  canon        MEMOIZED canonical fingerprints against the warm run's
               live memo table — the realistic mixed hit/miss path a
               production chunk pays (probe + tiered canon of the
               misses + insert). Unmemoized canonicalizers time the
               plain tiered canon here instead.
  canon_memo_hit  the same memoized call against a table that already
               holds every key of this chunk — the pure-hit floor
               (one raw hash + probe, no tiered canon at all)
  canon_tier3_local  the tier-3 resolve alone (tie-group-local blocks +
               full-table drain, ops/symmetry.py _tier3_apply) with
               tiers 1+2 precomputed outside the timer; 0.0 when the
               canonicalizer has no pruned tier path
  probe        membership probe of the seen run (searchsorted)
  run_emit     sorting the chunk's new fingerprints into its R0-lane run
  emit_append  the production emit (round 6): dense-prefix compaction of
               the survivors to a [VC, W] block plus ONE donated
               dynamic_update_slice cursor append per buffer (frontier,
               jparent, jcand) — checker/util.py emit_append
  scatter      RETIRED diagnostic row: the pre-round-6 emit (arbitrary-
               index scatters into the full-capacity frontier/journal
               buffers), kept so regenerated profiles show old-vs-new
               emit cost side by side against archived PROFILE artifacts
  invariants   batched invariant kernels
  lsm_merge_2r0  one R0+R0 run merge (sort of 2*R0 lanes), fitting the
                 n log n constant for the AMORTIZED per-chunk merge cost

Per-wave cost model: chunks_per_wave * (fused chunk + amortized merge).
``fused_chunk`` times the production program for cross-checking (the sum
of stages normally OVERESTIMATES it — XLA fuses away intermediates).
The per-chunk stage sum counts PRODUCTION stages once: canon_memo_hit
and canon_tier3_local are diagnostic re-measures of sub-paths already
inside the ``canon`` row (the all-hit floor and the tier-3 resolve), and
``scatter`` is the retired emit no production chunk executes — all three
are reported (their visibility is the point) but excluded from the sum
and from ``canon_share_of_stage_sum``.
"""

from __future__ import annotations

import math
import os
import tempfile
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.hashing import U64_MAX, ne_u64, sort_u64
from .device_bfs import DeviceBFS
from .util import dense_prefix_sel, emit_append, probe_sorted as _probe

# every stage key profile_stages() promises to report (the tier-1 smoke
# test asserts each one is present so stage accounting can't silently
# rot when the chunk pipeline changes)
DECLARED_STAGES = (
    "null_dispatch",
    "guards",
    "apply",
    "expand",
    "compact",
    "canon",
    "canon_memo_hit",
    "canon_tier3_local",
    "probe",
    "run_emit",
    "emit_append",
    "scatter",
    "invariants",
    "lsm_merge_2r0",
    "fused_chunk",
)


def _time_donated(fn, make_args, reps: int = 5) -> float:
    """Median wall seconds of fn(*make_args()) where fn donates some of
    its arguments: the args are rebuilt OUTSIDE the timed window each
    rep (donation invalidates them), so the row measures the in-place
    program alone, not the rebuild."""
    out = fn(*make_args())
    jax.block_until_ready(out)  # warm / compile
    ts = []
    for _ in range(reps):
        args = make_args()
        jax.block_until_ready(args)
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts))


def _time(fn, *args, reps: int = 5, inner: int = 1) -> float:
    """Median wall seconds of fn(*args) with block_until_ready."""
    out = fn(*args)
    jax.block_until_ready(out)  # warm / compile
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(inner):
            out = fn(*args)
        jax.block_until_ready(out)
        ts.append((time.perf_counter() - t0) / inner)
    return float(np.median(ts))


def profile_stages(
    model,
    invariants: tuple[str, ...] = (),
    symmetry: bool = True,
    chunk: int = 1024,
    frontier_cap: int = 1 << 17,
    seen_cap: int = 1 << 21,
    warm_depth: int = 8,
    reps: int = 5,
    telemetry=None,
    **caps,
) -> dict:
    """Profile the chunk pipeline on a realistic frontier.

    Runs a depth-capped BFS to ``warm_depth`` (checkpoint spill), then
    rebuilds one representative chunk's inputs from the spill and times
    each stage. Returns a dict with per-stage seconds, per-wave totals
    and workload shape facts. ``telemetry`` threads a raft_tpu.obs
    Telemetry through the warm run (its manifest event records the
    profiled engine's exact geometry and identity).
    """
    dev = DeviceBFS(
        model, invariants=invariants, symmetry=symmetry, chunk=chunk,
        frontier_cap=frontier_cap, seen_cap=seen_cap, **caps,
    )
    with tempfile.TemporaryDirectory() as td:
        ck_path = os.path.join(td, "warm.npz")
        res = dev.run(max_depth=warm_depth, checkpoint_path=ck_path,
                      telemetry=telemetry)
        if not os.path.exists(ck_path):
            raise RuntimeError(
                f"workload exhausted at depth {res.depth} < warm_depth="
                f"{warm_depth}; no frontier left to profile"
            )
        ck = np.load(ck_path, allow_pickle=False)
        frontier_h = np.asarray(ck["frontier"])  # [fcount, W]
        seen_h = np.asarray(ck["seen"])  # [scount]
    # caps may have grown during the warm run
    C, A, W, VC = dev.chunk, dev.A, dev.W, dev.VC
    FCAP, JCAP, R0 = dev.FCAP, dev.JCAP, dev.R0
    fcount, scount = len(frontier_h), len(seen_h)

    batch_h = frontier_h[:C]
    if len(batch_h) < C:
        batch_h = np.concatenate(
            [batch_h, np.repeat(batch_h[-1:], C - len(batch_h), axis=0)]
        )
    batch = jnp.asarray(batch_h)
    # the warmed seen-set as the single sorted run production probes
    # (round-5 seen design: one U64_MAX-padded run, no LSM ladder)
    dev._seed_seen(np.sort(seen_h.astype(np.uint64)))
    runs = (dev._seen,)
    occ_dev = dev._occ_one
    occ_runs = runs
    use_memo = getattr(dev, "_use_memo", False)

    out: dict = {
        "workload": {
            "model": model.name,
            "warm_depth": int(res.depth),
            "frontier": int(fcount),
            "seen": int(scount),
            "distinct": int(res.distinct),
        },
        "geometry": {
            "chunk": C, "A": A, "W": W, "VC": VC, "R0": R0,
            "FCAP": FCAP, "JCAP": JCAP, "lsm_levels": len(runs),
            "perms": int(dev.canon.P), "symmetry": bool(symmetry),
            "canon_memo_cap": int(dev.MCAP) if use_memo else 0,
            "refine_rounds": int(getattr(dev.canon, "refine_rounds", 1)),
        },
        "stages_s": {},
    }
    st = out["stages_s"]

    # ---- stage 0: dispatch floor ----
    null_j = jax.jit(lambda x: x + 1)
    st["null_dispatch"] = _time(null_j, jnp.zeros((8,), jnp.int32), reps=reps)

    sparse = getattr(dev, "_sparse", False)

    # ---- stage 1: guard pass (sparse path only) ----
    if sparse:
        guards_j = jax.jit(lambda b: jax.vmap(model.guards1)(b))
        st["guards"] = _time(guards_j, batch, reps=reps)
    else:
        st["guards"] = 0.0
    st["apply"] = 0.0  # placeholder keeps table order; measured below

    # ---- stage 1b: dense expand (production for legacy models; a
    # retired diagnostic when the sparse path is active) ----
    expand = jax.jit(lambda b: jax.vmap(model._expand1)(b))
    st["expand"] = _time(expand, batch, reps=reps)
    succs, valid, _rank, _ovf = expand(batch)

    # ---- stage 2: compact. Under the sparse path the [VC, W]
    # successor gather moved into `apply`, so this times the worklist
    # build alone; the dense variant keeps the gather. ----
    def compact_sel(valid):
        vflat = valid.reshape(-1)
        vpos = jnp.cumsum(vflat) - 1
        sdst = jnp.where(vflat, jnp.minimum(vpos, VC), VC)
        sel = (
            jnp.full((VC + 1,), C * A, jnp.int32)
            .at[sdst]
            .set(jnp.arange(C * A, dtype=jnp.int32))[:VC]
        )
        return sel, sel < C * A

    def compact(succs, valid):
        sel, selv = compact_sel(valid)
        flatp = jnp.concatenate(
            [succs.reshape(C * A, W), jnp.zeros((1, W), jnp.int32)], axis=0
        )
        return flatp[sel], selv

    compact_j = jax.jit(compact)
    sel_j = jax.jit(compact_sel)
    if sparse:
        st["compact"] = _time(sel_j, valid, reps=reps)
    else:
        st["compact"] = _time(compact_j, succs, valid, reps=reps)
    flatc, selv = compact_j(succs, valid)

    # ---- stage 2b: budgeted apply over the compacted worklist (the
    # production successor construction when sparse; its output is
    # bit-identical to the dense gather, so downstream stages reuse
    # flatc either way) ----
    if sparse:
        sel, _ = sel_j(valid)
        apply_j = jax.jit(
            lambda b, s, sv: model.sparse_apply(b, s, sv, dev._plan)
        )
        st["apply"] = _time(apply_j, batch, sel, selv, reps=reps)

    # ---- stage 3: canonical fingerprints ----
    if use_memo:
        fmemo = jax.jit(dev.canon.fingerprints_memo)
        # the warm run left its LAST wave's memo table resident
        # (DeviceBFS.run keeps the final output buffer) — timing
        # against it is the realistic mixed hit/miss path
        m_warm = dev._memo.table
        st["canon"] = _time(fmemo, flatc, selv, m_warm, reps=reps)
        fps, m_hit, _ = fmemo(flatc, selv, m_warm)
        # after one pass the table holds every key of this chunk: the
        # second call is the pure-hit floor
        st["canon_memo_hit"] = _time(fmemo, flatc, selv, m_hit, reps=reps)
    else:
        canon_j = jax.jit(dev.canon._fingerprints)
        st["canon"] = _time(canon_j, flatc, reps=reps)
        fps = jnp.where(selv, canon_j(flatc), U64_MAX)
        st["canon_memo_hit"] = 0.0

    # ---- stage 3b: tier-3 resolve alone (tie-group-local + full-table
    # drain), with the tier-1/2 running min precomputed outside ----
    c = dev.canon
    if (
        c.symmetry and getattr(c, "prune", False)
        and getattr(c, "mode", "full") != "full"
    ):
        view = flatc[:, : c.VL]
        sig = jax.jit(c._signatures)(view)
        pre = jax.jit(c._tier_pre)(view, sig)
        t3_j = jax.jit(c._tier3_apply)
        st["canon_tier3_local"] = _time(t3_j, view, sig, *pre, reps=reps)
    else:
        st["canon_tier3_local"] = 0.0

    # ---- stage 4: probe the occupied LSM runs (production skips empty
    # levels via cond, so the occupied set is what a chunk pays for) ----
    def probe_all(f, *rs):
        hit = jnp.zeros(f.shape, bool)
        for r in rs:
            hit = hit | _probe(r, f)
        return hit

    st["probe"] = _time(jax.jit(probe_all), fps, *occ_runs, reps=reps)

    # ---- stage 5: emit the chunk's sorted run ----
    def run_emit(f):
        nr = sort_u64(f)
        if R0 > VC:
            nr = jnp.concatenate(
                [nr, jnp.full((R0 - VC,), U64_MAX, jnp.uint64)]
            )
        return nr

    st["run_emit"] = _time(jax.jit(run_emit), fps, reps=reps)

    # ---- stage 5b: the production emit — dense-prefix compaction +
    # one donated cursor append per buffer (mirrors _chunk_step step 5;
    # the donated carries are rebuilt outside the timer) ----
    def emit_stage(flatc, fps, nb, jp, jc):
        new = ne_u64(fps, U64_MAX)
        n_new = jnp.sum(new)
        npos = (jnp.cumsum(new) - 1).astype(jnp.int32)
        esel = dense_prefix_sel(new, npos, VC)
        blk = jnp.concatenate(
            [flatc, jnp.zeros((1, W), jnp.int32)], axis=0
        )[esel]
        lanes = jnp.concatenate([npos, jnp.zeros((1,), jnp.int32)])[esel]
        nb, _ = emit_append(nb, blk, jnp.int32(0), n_new, FCAP)
        jp, _ = emit_append(jp, lanes, jnp.int32(0), n_new, JCAP)
        jc, _ = emit_append(jc, lanes, jnp.int32(0), n_new, JCAP)
        return nb, jp, jc

    emit_j = jax.jit(emit_stage, donate_argnums=(2, 3, 4))
    st["emit_append"] = _time_donated(
        emit_j,
        lambda: (
            flatc, fps,
            jnp.zeros((FCAP + VC, W), jnp.int32),
            jnp.zeros((JCAP + VC,), jnp.int32),
            jnp.zeros((JCAP + VC,), jnp.int32),
        ),
        reps=reps,
    )

    # ---- stage 5c (RETIRED, diagnostic): the pre-round-6 emit — full-
    # capacity arbitrary-index scatters. Self-contained (allocates its
    # own buffers in-program) so the row stays comparable with archived
    # PROFILE artifacts; excluded from the stage sum. ----
    def scatter(flatc, fps):
        new = ne_u64(fps, U64_MAX)
        npos = (jnp.cumsum(new) - 1).astype(jnp.int32)
        bdst = jnp.where(new, jnp.minimum(npos, FCAP), FCAP)
        nb = jnp.zeros((FCAP + 1, W), jnp.int32).at[bdst].set(flatc)
        jdst = jnp.where(new, jnp.minimum(npos, JCAP), JCAP)
        jp = jnp.zeros((JCAP + 1,), jnp.int32).at[jdst].set(bdst)
        return nb, jp

    st["scatter"] = _time(jax.jit(scatter), flatc, fps, reps=reps)

    # ---- stage 6: invariants ----
    if invariants:
        inv_j = jax.jit(
            lambda v: [model.invariants[n](v) for n in invariants]
        )
        st["invariants"] = _time(inv_j, flatc, reps=reps)
    else:
        st["invariants"] = 0.0

    # ---- LSM merge costs (level 0 measured; series fitted n log n) ----
    r0a = run_emit(fps)
    st["lsm_merge_2r0"] = _time(
        jax.jit(lambda a, b: sort_u64(jnp.concatenate([a, b]))), r0a, r0a,
        reps=reps,
    )
    null = st["null_dispatch"]
    a_fit = max(st["lsm_merge_2r0"] - null, 1e-6) / (2 * R0 * math.log2(2 * R0))
    n_levels = max(1, len(runs))
    amortized = sum(
        a_fit * (R0 << (l + 1)) * math.log2(R0 << (l + 1)) / (1 << (l + 1))
        for l in range(n_levels)
    )

    # ---- the fused production program, for cross-check ----
    frontier_d = jnp.asarray(
        np.concatenate([
            frontier_h,
            np.zeros((FCAP + VC - fcount, W), np.int32),
        ])
    )

    def fused_once():
        # donated args (next_buf, journal, viol, stats, memo) must be
        # rebuilt per call — donation invalidates their buffers. The
        # memo is a COPY of the warm table so the fused row reflects the
        # production mixed hit/miss path.
        nb = jnp.zeros((FCAP + VC, W), jnp.int32)
        jp = jnp.zeros((JCAP + VC,), jnp.int32)
        jc = jnp.zeros((JCAP + VC,), jnp.int32)
        viol = jnp.full((max(1, len(invariants)),), np.int32(2**31 - 1), jnp.int32)
        stats = jnp.zeros((6,), jnp.int64)
        memo = jnp.array(m_warm) if use_memo else dev._memo.reset()
        cov = jnp.zeros((dev.n_actions, 3), jnp.int64)
        args = [frontier_d, nb, jp, jc, viol, stats, memo, cov,
                np.int32(0), np.int32(min(fcount, C)), np.int32(0),
                occ_dev, jnp.asarray(True), *runs]
        jax.block_until_ready(args)
        t0 = time.perf_counter()
        r = dev._chunk_fn(*args)
        jax.block_until_ready(r)
        return time.perf_counter() - t0

    fused_once()  # compile
    st["fused_chunk"] = float(np.median([fused_once() for _ in range(reps)]))

    # PRODUCTION stages only: canon_memo_hit / canon_tier3_local re-time
    # sub-paths already inside the `canon` row (the all-hit floor and the
    # tier-3 resolve), and `scatter` is the retired emit no production
    # chunk executes — adding them would double-count (or resurrect)
    # work. A chunk pays `canon` and `emit_append` once each. Under the
    # sparse path the production expansion is guards + apply and the
    # dense `expand` row joins the diagnostic set.
    if sparse:
        timed = ["guards", "apply", "compact", "canon", "probe",
                 "run_emit", "emit_append"]
        out["diag_rows"] = [
            "canon_memo_hit", "canon_tier3_local", "scatter", "expand",
        ]
    else:
        timed = [
            "expand", "compact", "canon", "probe", "run_emit",
            "emit_append",
        ]
        out["diag_rows"] = [
            "canon_memo_hit", "canon_tier3_local", "scatter",
        ]
    if invariants:
        timed.append("invariants")
    # each TIMED stage row pays one dispatch floor (floored at 0 so a
    # not-applicable 0.0 stage can't subtract from the sum)
    chunk_sum = sum(max(0.0, st[k] - null) for k in timed)
    n_chunks = max(1, (fcount + C - 1) // C)
    per_chunk = st["fused_chunk"] + amortized
    canon_sum = max(0.0, st["canon"] - null)
    # successor-expansion share: guards + apply under the sparse path,
    # the dense expand row otherwise (the guard-first acceptance gauge)
    exp_sum = sum(
        max(0.0, st[k] - null)
        for k in (("guards", "apply") if sparse else ("expand",))
    )
    out["per_wave_s"] = {
        "chunks_per_wave": n_chunks,
        "stage_sum_per_chunk": round(chunk_sum, 6),
        "canon_share_of_stage_sum": round(
            canon_sum / chunk_sum, 4) if chunk_sum else 0.0,
        "expand_share_of_stage_sum": round(
            exp_sum / chunk_sum, 4) if chunk_sum else 0.0,
        "fused_per_chunk": round(st["fused_chunk"], 6),
        "lsm_merge_amortized_per_chunk": round(amortized, 6),
        "wave_estimate": round(n_chunks * per_chunk, 6),
        "merge_share": round(amortized / per_chunk, 4),
    }
    return out


def render(prof: dict) -> str:
    w, g, s = prof["workload"], prof["geometry"], prof["stages_s"]
    lines = [
        f"workload: {w['model']} depth={w['warm_depth']} "
        f"frontier={w['frontier']} seen={w['seen']}",
        f"geometry: chunk={g['chunk']} VC={g['VC']} R0={g.get('R0')} "
        f"FCAP={g['FCAP']} lsm_levels={g.get('lsm_levels')} "
        f"perms={g['perms']}",
        f"{'stage':<18}{'ms':>10}{'net ms':>10}{'share':>8}",
    ]
    skip = ("fused_chunk", "lsm_merge_2r0", "null_dispatch")
    # diagnostic rows: canon sub-path re-measures, the RETIRED scatter
    # emit, and (sparse-path profiles) the retired dense expand — shown
    # (relative to the production sum) but not part of it, see
    # per_wave_s accounting. Archived PROFILE.json files predate the
    # diag_rows field; the historical tuple is their fallback.
    diag = tuple(prof.get(
        "diag_rows", ("canon_memo_hit", "canon_tier3_local", "scatter")
    ))
    null = s.get("null_dispatch", 0.0)
    tot = sum(max(0.0, v - null) for k, v in s.items()
              if k not in skip and k not in diag)
    for k, v in s.items():
        if v == 0.0 and k in ("guards", "apply"):
            continue  # not-applicable rows (dense-only models)
        net = max(0.0, v - null)
        share = net / tot if k not in skip and tot else 0
        mark = "*" if k in diag else ""
        lines.append(
            f"{k + mark:<18}{v * 1e3:>10.2f}{net * 1e3:>10.2f}"
            f"{share:>8.1%}"
        )
    if any(k in s for k in diag):
        lines.append("(* diagnostic row — canon sub-path re-measure or "
                     "a retired path; not in the stage sum)")
    lines.append(
        "(net ms = ms - null_dispatch: the dispatch/tunnel floor every "
        "row pays once; shares are over net production rows)"
    )
    pw = prof["per_wave_s"]
    lines.append(
        f"wave: {pw['chunks_per_wave']} chunks x "
        f"({pw['fused_per_chunk']*1e3:.2f} ms fused + "
        f"{pw['lsm_merge_amortized_per_chunk']*1e3:.2f} ms amortized merge)"
    )
    return "\n".join(lines)
