"""Simulation-mode checker: batched random walks on device.

TLC's ``-simulate`` is the reference's prescribed fallback when brute
force is infeasible — both ``FlexibleRaft.cfg:5`` ("State space is huge
for this one - run with simulation") and ``KRaftWithReconfig.cfg:5``
("too big for brute force, only simulation") demand it (SURVEY.md §4.6).

TPU-native shape: R independent walks advance in lock-step as one
device-resident [R, W] batch. Each jitted step expands all R states (the
same vmapped successor kernel the BFS uses), samples one enabled
candidate per walk uniformly at random, evaluates the invariants on the
new states, and restarts deadlocked/depth-capped walks from a preloaded
initial-state pool — all on device; only small per-walk arrays (chosen
candidate, flags) come back to the host each step for the behavior
journals. Initial states are invariant-checked once up front, so restart
entry points are covered. A violating behavior replays into a labeled
trace like the BFS checker's counterexamples.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np


@dataclass
class SimViolation:
    invariant: str
    walk: int
    depth: int  # steps from the behavior's start


@dataclass
class SimResult:
    behaviors: int  # completed behaviors (terminal or depth-capped)
    steps: int  # total transitions taken across all walks
    violation: SimViolation | None
    seconds: float
    states_per_sec: float
    trace: list[tuple[str, dict]] | None = None


class Simulator:
    def __init__(
        self,
        model,
        invariants: tuple[str, ...] = (),
        walks: int = 128,
        max_behavior_depth: int = 50,
        seed: int = 0,
    ):
        from .. import enable_compcache

        enable_compcache()
        self.model = model
        self.invariants = tuple(invariants)
        self.R = walks
        self.max_behavior_depth = max_behavior_depth
        self.seed = seed
        self._step = jax.jit(self._step_impl)

    def _step_impl(self, states, depth, init_pool, key):
        """One lock-step move of all R walks, fully on device.

        Returns (next_states, next_depth, chosen, moved, done, restart_idx,
        inv_bad, ovf_any); `inv_bad` is the first violated invariant's
        index per walk (-1 = none)."""
        model = self.model
        R = self.R
        succs, valid, _rank, ovf = jax.vmap(model._expand1)(states)
        n_valid = jnp.sum(valid, axis=1)  # [R]
        ku, kr = jax.random.split(key)
        # uniform pick among enabled candidates: k-th enabled, k ~ U[0, n)
        u = jax.random.uniform(ku, (R,))
        k = jnp.floor(u * jnp.maximum(n_valid, 1)).astype(jnp.int32)
        cum = jnp.cumsum(valid.astype(jnp.int32), axis=1)
        chosen = jnp.argmax(cum > k[:, None], axis=1)  # first idx with cum > k
        moved = n_valid > 0
        nxt = jnp.where(
            moved[:, None],
            jnp.take_along_axis(succs, chosen[:, None, None], axis=1)[:, 0, :],
            states,
        )
        ovf_any = jnp.any(
            jnp.take_along_axis(valid & ovf, chosen[:, None], axis=1) & moved[:, None]
        )
        # batched invariant evaluation on the post-move states (restart
        # targets are pre-checked initial states, see run())
        inv_bad = jnp.full((R,), -1, jnp.int32)
        for idx in range(len(self.invariants) - 1, -1, -1):
            ok = self.model.invariants[self.invariants[idx]](nxt)
            inv_bad = jnp.where(~ok & moved, jnp.int32(idx), inv_bad)
        # restart finished behaviors (deadlock or depth bound) — TLC
        # -simulate starts a fresh behavior; keep violating walks intact
        new_depth = depth + moved.astype(jnp.int32)
        done = ((~moved) | (new_depth >= self.max_behavior_depth)) & (inv_bad < 0)
        restart_idx = jax.random.randint(kr, (R,), 0, init_pool.shape[0])
        nxt = jnp.where(done[:, None], init_pool[restart_idx], nxt)
        new_depth = jnp.where(done, 0, new_depth)
        return nxt, new_depth, chosen, moved, done, restart_idx, inv_bad, ovf_any

    def run(
        self,
        max_steps: int | None = None,
        time_budget_s: float | None = None,
        max_behaviors: int | None = None,
        verbose: bool = False,
    ) -> SimResult:
        model = self.model
        R = self.R
        t0 = time.perf_counter()
        rng = jax.random.PRNGKey(self.seed)

        init = model.init_states()
        # depth-0 check: every initial state (= every restart target)
        for name in self.invariants:
            ok = np.asarray(jax.device_get(model.invariants[name](init)))
            if not ok.all():
                return SimResult(
                    behaviors=0,
                    steps=0,
                    violation=SimViolation(invariant=name, walk=0, depth=0),
                    seconds=time.perf_counter() - t0,
                    states_per_sec=0.0,
                    trace=[
                        (
                            "Initial predicate",
                            model.decode(init[int(np.nonzero(~ok)[0][0])]),
                        )
                    ],
                )
        init_pool = jnp.asarray(init)
        rng, k0 = jax.random.split(rng)
        init_idx = np.asarray(
            jax.device_get(jax.random.randint(k0, (R,), 0, len(init)))
        )
        states = init_pool[jnp.asarray(init_idx)]
        depth = jnp.zeros(R, dtype=jnp.int32)
        # per-walk journal of (init index, chosen candidates) for replay
        journal: list[list[int]] = [[int(i)] for i in init_idx]

        behaviors = 0
        steps = 0
        violation = None

        while violation is None:
            if max_steps is not None and steps >= max_steps:
                break
            if time_budget_s is not None and time.perf_counter() - t0 > time_budget_s:
                break
            if max_behaviors is not None and behaviors >= max_behaviors:
                break
            rng, key = jax.random.split(rng)
            states, depth, chosen, moved, done, ridx, inv_bad, ovf_any = self._step(
                states, depth, init_pool, key
            )
            chosen, moved, done, ridx, inv_bad, ovf_any = jax.device_get(
                (chosen, moved, done, ridx, inv_bad, ovf_any)
            )
            if bool(ovf_any):
                raise OverflowError(
                    "message-slot overflow during simulation: re-run with a "
                    "larger msg_slots"
                )
            steps += int(moved.sum())
            # journal bookkeeping in order: record moves, surface any
            # violation, then reset journals of restarted walks
            for w in np.nonzero(moved)[0]:
                journal[w].append(int(chosen[w]))
            bad = np.nonzero(inv_bad >= 0)[0]
            if len(bad):
                w = int(bad[0])
                violation = SimViolation(
                    invariant=self.invariants[int(inv_bad[w])],
                    walk=w,
                    depth=len(journal[w]) - 1,
                )
                break
            for w in np.nonzero(done)[0]:
                behaviors += 1
                journal[w] = [int(ridx[w])]
            if verbose and steps % (50 * R) < R:
                el = time.perf_counter() - t0
                print(
                    f"simulate: {steps} steps, {behaviors} behaviors, "
                    f"{steps/el:.0f} states/s"
                )

        dt = time.perf_counter() - t0
        init_np = np.asarray(jax.device_get(init_pool))
        trace = (
            self._replay(init_np, journal[violation.walk]) if violation else None
        )
        return SimResult(
            behaviors=behaviors,
            steps=steps,
            violation=violation,
            seconds=dt,
            states_per_sec=steps / dt if dt > 0 else 0.0,
            trace=trace,
        )

    def _replay(self, init, journal: list[int]) -> list[tuple[str, dict]]:
        """Re-run one behavior's recorded choices into a labeled trace."""
        model = self.model
        state = np.asarray(init[journal[0]])
        out = [("Initial predicate", model.decode(state))]
        for cand in journal[1:]:
            succs, valid, rank, _ovf = jax.device_get(
                jax.vmap(model._expand1)(state[None, :])
            )
            assert valid[0, cand], "journalled candidate not enabled on replay"
            state = np.asarray(succs[0, cand])
            out.append(
                (model.action_label(int(rank[0, cand]), cand), model.decode(state))
            )
        return out
