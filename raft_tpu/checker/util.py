"""Capacity-growth policy + sorted-set probe shared by the device-resident
checkers (DeviceBFS and the sharded v2 engine), so a policy fix lands once."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..ops.hashing import eq_u64

GROWTH = 4  # enlarge factor per growth step
HEADROOM = 3  # grow when the next wave could need more than cap/HEADROOM
I32_MAX = np.int32(2**31 - 1)  # "no violation" sentinel in journal folds


def probe_sorted(sorted_arr, vals):
    """Membership of vals in a sorted u64 array padded with U64_MAX.
    (u64 searchsorted is fast on this TPU; elementwise u64 == is not —
    the equality check decomposes to u32, ops/hashing.py.)"""
    pos = jnp.searchsorted(sorted_arr, vals)
    pos = jnp.clip(pos, 0, sorted_arr.shape[0] - 1)
    return eq_u64(sorted_arr[pos], vals)


def next_cap(needed: int, cap: int, max_cap: int, growth: int, unit: int) -> int:
    """Smallest growth**k * cap >= needed, rounded up to a multiple of
    unit, never exceeding max_cap (max_cap is rounded DOWN to a unit
    multiple so the user's bound is a hard ceiling; cap itself is assumed
    unit-aligned already)."""
    eff_max = max(cap, (max_cap // unit) * unit)
    new = cap
    while new < needed and new < eff_max:
        new = min(new * growth, eff_max)
    new = ((new + unit - 1) // unit) * unit
    return min(new, eff_max)
