"""Capacity-growth policy, sorted-set probe and the contiguous
cursor-append emit shared by the device-resident checkers (DeviceBFS and
the sharded engine), so a policy fix lands once."""

from __future__ import annotations

import warnings

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..ops.hashing import eq_u64

GROWTH = 4  # enlarge factor per growth step
HEADROOM = 3  # grow when the next wave could need more than cap/HEADROOM
I32_MAX = np.int32(2**31 - 1)  # "no violation" sentinel in journal folds


def probe_sorted(sorted_arr, vals):
    """Membership of vals in a sorted u64 array padded with U64_MAX.
    (u64 searchsorted is fast on this TPU; elementwise u64 == is not —
    the equality check decomposes to u32, ops/hashing.py.)"""
    pos = jnp.searchsorted(sorted_arr, vals)
    pos = jnp.clip(pos, 0, sorted_arr.shape[0] - 1)
    return eq_u64(sorted_arr[pos], vals)


def dense_prefix_sel(new, npos, n_lanes: int):
    """Gather indices compacting the ``new`` lanes to a dense prefix.

    ``npos = cumsum(new) - 1`` (int32, the destination rank of each new
    lane). Returns ``sel`` [n_lanes] with sel[j] = lane index of the
    j-th new lane for j < n_new, and ``n_lanes`` (the caller's pad/drop
    row) past the prefix. Same one-hot-scatter idiom as the valid-lane
    compaction in the chunk pipeline: the scatter is confined to an
    (n_lanes+1)-sized index buffer, never a capacity-sized one.
    """
    edst = jnp.where(new, npos, n_lanes)
    return (
        jnp.full((n_lanes + 1,), n_lanes, jnp.int32)
        .at[edst]
        .set(jnp.arange(n_lanes, dtype=jnp.int32))[:n_lanes]
    )


def emit_append(buf, block, count, n_new, cap: int):
    """Contiguous cursor-append emit: write ``block`` (B lanes/rows, the
    first n_new of which are real) into ``buf`` at row ``count`` with ONE
    ``lax.dynamic_update_slice``. The destinations of a chunk's survivors
    are provably a dense block at the running cursor, so the append
    lowers to a copy instead of the full-capacity arbitrary-index
    scatter ``.at[dst].set()`` lowers to (scripts/emit_micro.py measures
    the difference; it dominated the stage profile before this path).

    ``buf`` must carry >= B pad rows past ``cap``: rows [cap, cap+B) are
    the drop region — the append analog of the retired scatter's drop
    row ``cap``. The start is clamped to ``cap``, so a cursor past
    capacity (only reachable with the overflow flag already raised, and
    the run aborting) lands the whole block in the pad region and rows
    [0, cap) stay bit-identical to the scatter path's.

    Returns ``(buf, overflow)`` with ``overflow = count + n_new > cap``.
    """
    start = jnp.minimum(count, cap)
    if buf.ndim == 2:
        buf = lax.dynamic_update_slice(buf, block, (start, jnp.int32(0)))
    else:
        buf = lax.dynamic_update_slice(buf, block, (start,))
    return buf, count + n_new > cap


def jit_with_donation(fn, donate_argnums, probe_args, **jit_kw):
    """``jax.jit(fn, donate_argnums=...)`` when the backend can actually
    alias the donated buffers, a plain ``jax.jit(fn)`` otherwise.

    XLA only reports an unusable donation as a UserWarning at the first
    EXECUTION (e.g. a sort-concat-truncate merge never aliases on the
    CPU backend even at matching sizes), so the compiled program is
    probed once on throwaway buffers — fresh from ``probe_args()``,
    because a successful donation consumes them. Production calls then
    never warn and never silently copy a buffer the caller believed was
    updated in place.
    """
    jitted = jax.jit(fn, donate_argnums=donate_argnums, **jit_kw)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        out = jitted(*probe_args())
        jax.block_until_ready(out)
    if any("donated" in str(w.message) for w in caught):
        return jax.jit(fn, **jit_kw)
    return jitted


def next_cap(needed: int, cap: int, max_cap: int, growth: int, unit: int) -> int:
    """Smallest growth**k * cap >= needed, rounded up to a multiple of
    unit, never exceeding max_cap (max_cap is rounded DOWN to a unit
    multiple so the user's bound is a hard ceiling; cap itself is assumed
    unit-aligned already)."""
    eff_max = max(cap, (max_cap // unit) * unit)
    new = cap
    while new < needed and new < eff_max:
        new = min(new * growth, eff_max)
    new = ((new + unit - 1) // unit) * unit
    return min(new, eff_max)
