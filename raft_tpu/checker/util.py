"""Capacity-growth policy + sorted-set probe shared by the device-resident
checkers (DeviceBFS and the sharded v2 engine), so a policy fix lands once."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

GROWTH = 4  # enlarge factor per growth step
HEADROOM = 3  # grow when the next wave could need more than cap/HEADROOM
I32_MAX = np.int32(2**31 - 1)  # "no violation" sentinel in journal folds


def probe_sorted(sorted_arr, vals):
    """Membership of vals in a sorted u64 array padded with U64_MAX."""
    pos = jnp.searchsorted(sorted_arr, vals)
    pos = jnp.clip(pos, 0, sorted_arr.shape[0] - 1)
    return sorted_arr[pos] == vals


def merge_sorted(a, b):
    """Merge two sorted u64 arrays (U64_MAX padding sorts last) into one
    sorted array of length len(a)+len(b), in O(n log n) binary searches +
    two scatters instead of a full O(n log n)-comparison re-sort of the
    concatenation — the distinction matters because XLA sorts are
    expensive at seen-set scale while searchsorted vectorizes flat.

    Placement: a[i] lands at i + |{b < a[i]}| (side='left'), b[j] at
    j + |{a <= b[j]}| (side='right'); ties order a-first, and both maps
    are collision-free (within-array offsets are strictly increasing,
    and for a[i] == b[j] the b element counts the equal a's)."""
    la, lb = a.shape[0], b.shape[0]
    ia = jnp.searchsorted(b, a, side="left")
    ib = jnp.searchsorted(a, b, side="right")
    out = jnp.zeros((la + lb,), a.dtype)
    out = out.at[jnp.arange(la) + ia].set(a)
    out = out.at[jnp.arange(lb) + ib].set(b)
    return out


def next_cap(needed: int, cap: int, max_cap: int, growth: int, unit: int) -> int:
    """Smallest growth**k * cap >= needed, rounded up to a multiple of
    unit, never exceeding max_cap (max_cap is rounded DOWN to a unit
    multiple so the user's bound is a hard ceiling; cap itself is assumed
    unit-aligned already)."""
    eff_max = max(cap, (max_cap // unit) * unit)
    new = cap
    while new < needed and new < eff_max:
        new = min(new * growth, eff_max)
    new = ((new + unit - 1) // unit) * unit
    return min(new, eff_max)
