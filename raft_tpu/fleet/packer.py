"""Build the one packed model a "packed" FleetGroup runs as.

The packed model is the group's representative params with the config
axis switched on: ``fleet=True`` adds the ``fleet_job`` + ``c_<name>``
VIEW lanes to the layout (models/base.py FleetConstMixin), each varying
constant is set to its per-group MAXIMUM (fleet_bind asserts this — the
static value sizes capacity, the lane gates guards), and the per-job
constant table is bound so ``init_states`` stamps one job-major copy of
the initial frontier per job.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from .grouping import FleetGroup


def build_packed(group: FleetGroup):
    assert group.kind == "packed", group.kind
    setups = group.setups
    m0 = setups[0].model
    p0 = m0.p
    over = {
        n: max(int(getattr(s.model.p, n)) for s in setups)
        for n in group.dyn_consts
    }
    rep = dataclasses.replace(
        p0, fleet=True, dyn_consts=tuple(group.dyn_consts), **over
    )
    model = type(m0)(
        rep,
        server_names=list(setups[0].server_names),
        value_names=list(setups[0].value_names),
    )
    # variant builders rename post-construction (e.g. FlexibleRaft,
    # models/registry.py:99) — mirror that on the packed instance
    model.name = m0.name
    table = group.table
    if table is None:
        table = np.zeros((len(setups), 0), dtype=np.int64)
    model.fleet_bind(table)
    return model
