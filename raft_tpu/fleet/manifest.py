"""Fleet manifest schema + parser.

A manifest is one JSON object describing a sweep:

    {
      "spec": "Raft",                      // default spec for every job
      "defaults": {
        "constants":  {"Server": ["s1","s2","s3"], "Value": ["v1"],
                       "MaxElections": 1, "MaxRestarts": 1},
        "invariants": ["NoLogDivergence"],
        "symmetry":   true,                // default true
        "msg_slots":  24,                  // default: spec builder default
        "mode":       "check",             // or "simulate"
        "net_faults": false,               // Raft family only
        "chaos": "crash=2,seed=7",         // per-job fault injection
                                           // (resilience.ChaosSpec grammar)
        "sim": {"walks": 128, "max_behavior_depth": 50, "seed": 0,
                "max_behaviors": null, "max_steps": 100000}  // -simulate knobs
      },
      "grid": {"MaxRestarts": [1,2,3], "MaxElections": [1,2]},
      "jobs": [ {"name": "...", "constants": {...}, ...} ]
    }

``grid`` expands to the cross-product of its value lists in JSON key
order, one job per point, each point overlaid on ``defaults.constants``;
grid jobs are auto-named ``<spec>-K1=v1-K2=v2``. ``jobs`` entries are
explicit single jobs overriding any default field. A manifest needs at
least one of grid/jobs. Every malformed-manifest path raises
ManifestError (the CLI maps it to exit 64, the usage code).

Constant values: ints and booleans pass through; a list of strings is a
TLC model-value set (``Server = {s1, s2, s3}``); a bare string is a
single model value. ``cfg_for_job`` lowers a job to the same
``utils.cfg.Cfg`` object the .cfg parser produces, so the registry
builders serve manifests and cfg files through one code path.
"""

from __future__ import annotations

import itertools
import json
from dataclasses import dataclass, field

from ..utils.cfg import Cfg, ModelValue


class ManifestError(Exception):
    pass


MODES = ("check", "simulate")
SIM_DEFAULTS = {
    "walks": 128,
    "max_behavior_depth": 50,
    "seed": 0,
    "max_behaviors": None,
    # Simulator.run loops until a bound trips; a sweep must terminate,
    # so default a step budget (override with null + a --time-budget)
    "max_steps": 100_000,
}


@dataclass
class FleetJob:
    name: str
    spec: str
    constants: dict
    invariants: tuple[str, ...] = ()
    symmetry: bool = True
    msg_slots: int | None = None
    mode: str = "check"
    net_faults: bool = False
    chaos: str | None = None  # validated ChaosSpec grammar, or None
    sim: dict = field(default_factory=lambda: dict(SIM_DEFAULTS))


@dataclass
class FleetManifest:
    path: str
    jobs: list[FleetJob]


def _req(obj: dict, key: str, path: str):
    if key not in obj:
        raise ManifestError(f"{path}: missing required key {key!r}")
    return obj[key]


def _check_constants(constants, path: str, where: str) -> dict:
    if not isinstance(constants, dict):
        raise ManifestError(f"{path}: {where} constants must be an object")
    for k, v in constants.items():
        ok = (
            isinstance(v, (bool, int, str))
            or (
                isinstance(v, list)
                and v
                and all(isinstance(x, str) for x in v)
            )
        )
        if not ok:
            raise ManifestError(
                f"{path}: {where} constant {k!r} must be an int, bool, "
                f"string, or non-empty list of strings, got {v!r}"
            )
    return constants


def _job_from(obj: dict, defaults: dict, spec: str, path: str,
              name: str | None = None) -> FleetJob:
    spec = obj.get("spec", spec)
    if not isinstance(spec, str) or not spec:
        raise ManifestError(f"{path}: job spec must be a non-empty string")
    constants = dict(defaults.get("constants", {}))
    constants.update(obj.get("constants", {}))
    _check_constants(constants, path, f"job {name or obj.get('name')}")
    mode = obj.get("mode", defaults.get("mode", "check"))
    if mode not in MODES:
        raise ManifestError(
            f"{path}: mode must be one of {MODES}, got {mode!r}"
        )
    msg_slots = obj.get("msg_slots", defaults.get("msg_slots"))
    if msg_slots is not None and (
        not isinstance(msg_slots, int) or isinstance(msg_slots, bool)
        or msg_slots <= 0
    ):
        raise ManifestError(f"{path}: msg_slots must be a positive int")
    invariants = obj.get("invariants", defaults.get("invariants", []))
    if not isinstance(invariants, list) or not all(
        isinstance(x, str) for x in invariants
    ):
        raise ManifestError(f"{path}: invariants must be a list of strings")
    sim = dict(SIM_DEFAULTS)
    sim.update(defaults.get("sim", {}))
    sim.update(obj.get("sim", {}))
    unknown = set(sim) - set(SIM_DEFAULTS)
    if unknown:
        raise ManifestError(f"{path}: unknown sim keys {sorted(unknown)}")
    chaos = obj.get("chaos", defaults.get("chaos"))
    if chaos is not None:
        if not isinstance(chaos, str):
            raise ManifestError(f"{path}: chaos must be a spec string")
        from ..resilience import ChaosSpec

        try:
            ChaosSpec.parse(chaos)
        except ValueError as e:
            raise ManifestError(f"{path}: {e}") from e
    job_name = obj.get("name", name)
    if not job_name:
        raise ManifestError(f"{path}: explicit jobs need a name")
    return FleetJob(
        name=str(job_name),
        spec=spec,
        constants=constants,
        invariants=tuple(invariants),
        symmetry=bool(obj.get("symmetry", defaults.get("symmetry", True))),
        msg_slots=msg_slots,
        mode=mode,
        net_faults=bool(obj.get("net_faults", defaults.get("net_faults", False))),
        chaos=chaos,
        sim=sim,
    )


def parse_manifest_obj(obj, path: str = "<manifest>") -> FleetManifest:
    if not isinstance(obj, dict):
        raise ManifestError(f"{path}: manifest must be a JSON object")
    unknown = set(obj) - {"spec", "defaults", "grid", "jobs"}
    if unknown:
        raise ManifestError(f"{path}: unknown manifest keys {sorted(unknown)}")
    spec = _req(obj, "spec", path)
    if not isinstance(spec, str) or not spec:
        raise ManifestError(f"{path}: spec must be a non-empty string")
    defaults = obj.get("defaults", {})
    if not isinstance(defaults, dict):
        raise ManifestError(f"{path}: defaults must be an object")
    _check_constants(defaults.get("constants", {}), path, "defaults")

    jobs: list[FleetJob] = []
    grid = obj.get("grid", {})
    if grid:
        if not isinstance(grid, dict) or not all(
            isinstance(v, list) and v for v in grid.values()
        ):
            raise ManifestError(
                f"{path}: grid must map constant names to non-empty lists"
            )
        keys = list(grid)  # JSON key order = sweep order
        for point in itertools.product(*(grid[k] for k in keys)):
            pc = dict(zip(keys, point))
            name = spec + "-" + "-".join(f"{k}={v}" for k, v in pc.items())
            jobs.append(
                _job_from({"constants": pc}, defaults, spec, path, name=name)
            )
    for jo in obj.get("jobs", []):
        if not isinstance(jo, dict):
            raise ManifestError(f"{path}: jobs entries must be objects")
        jobs.append(_job_from(jo, defaults, spec, path))
    if not jobs:
        raise ManifestError(f"{path}: manifest has no jobs (grid or jobs)")
    names = [j.name for j in jobs]
    if len(set(names)) != len(names):
        dup = sorted({n for n in names if names.count(n) > 1})
        raise ManifestError(f"{path}: duplicate job names {dup}")
    return FleetManifest(path=path, jobs=jobs)


def parse_manifest(path: str) -> FleetManifest:
    with open(path) as fh:
        try:
            obj = json.load(fh)
        except ValueError as e:
            raise ManifestError(f"{path}: not valid JSON ({e})") from e
    return parse_manifest_obj(obj, path=path)


def cfg_for_job(job: FleetJob, manifest_path: str = "<manifest>") -> Cfg:
    """Lower a manifest job to the Cfg object the registry builders
    expect — the manifest is a programmatic .cfg, one per job."""
    consts: dict = {}
    model_values: list[str] = []
    for k, v in job.constants.items():
        if isinstance(v, list):
            consts[k] = tuple(ModelValue(x) for x in v)
            model_values.extend(v)
        elif isinstance(v, str):
            consts[k] = ModelValue(v)
            model_values.append(v)
        else:
            consts[k] = v
    return Cfg(
        path=f"{manifest_path}#{job.name}",
        constants=consts,
        symmetry="fleet-manifest" if job.symmetry else None,
        invariants=list(job.invariants),
        model_values=model_values,
    )
