"""`raft_tpu sweep MANIFEST.json` — the fleet-checking subcommand.

Exit code is the WORST job rc (the per-run vocabulary from
raft_tpu/__main__.py: 0 clean, 2 violation, 4 preempted, 5
unrecoverable), with the usual 64 usage / 66 not-found for manifest
problems. Under ``--json`` stdout carries one summary object per job
followed by one fleet aggregate object (amortization stats included);
everything else goes to stderr.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .driver import SweepOptions, run_sweep
from .manifest import ManifestError, parse_manifest


def sweep_main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="raft_tpu sweep",
        description="run every job of a sweep manifest, packing "
        "layout-compatible configs into one compiled program",
    )
    ap.add_argument("manifest", help="sweep manifest (JSON; see README "
                    "'Fleet checking' for the grammar)")
    ap.add_argument(
        "--engine",
        default="host",
        choices=["host", "tpu", "sharded"],
        help="host = co-resident packed frontier (BFSChecker); tpu/"
        "sharded = device queue arm, one jit cache per group",
    )
    ap.add_argument("--jobs", default=None, metavar="GLOB",
                    help="fnmatch filter on job names (e.g. 'Raft-*ME=1*')")
    ap.add_argument("--max-depth", type=int, default=None)
    ap.add_argument("--time-budget", type=float, default=None,
                    help="per-run seconds budget (each group/job run)")
    ap.add_argument("--chunk", type=int, default=1024, help="device batch size")
    ap.add_argument(
        "--state-dir",
        default=None,
        metavar="DIR",
        help="sweep state root: fleet_state.json (completed-job ledger) "
        "plus per-job checkpoint lineages under DIR/ckpt/",
    )
    ap.add_argument(
        "--resume",
        action="store_true",
        help="skip jobs already completed per --state-dir's ledger and "
        "resume per-job checkpoints where they exist (packed host "
        "groups rerun wholly unless every member finished)",
    )
    ap.add_argument(
        "--supervise",
        nargs="?",
        const=5,
        default=None,
        type=int,
        metavar="N",
        help="run each job under the resilience supervisor with a "
        "per-job budget of N recoveries (default 5); a job that spends "
        "its budget becomes an rc-5 unrecoverable result without "
        "killing the rest of the sweep, and per-job recovery counts "
        "land in fleet_state.json and each job summary",
    )
    ap.add_argument("--metrics-out", default=None, metavar="PATH",
                    help="one multiplexed JSONL telemetry stream; every "
                    "event carries a 'job' field")
    ap.add_argument("--metrics-every", type=int, default=1, metavar="N")
    ap.add_argument("--json", action="store_true",
                    help="stdout: one summary object per job, then the "
                    "fleet aggregate object")
    ap.add_argument(
        "--platform",
        default=os.environ.get("RAFT_TPU_PLATFORM", "auto"),
        choices=["auto", "cpu", "tpu", "axon"],
    )
    ap.add_argument("--verbose", "-v", action="store_true")
    args = ap.parse_args(argv)

    if args.resume and not args.state_dir:
        print("error: --resume needs --state-dir", file=sys.stderr)
        return 64

    if args.platform != "auto":
        import jax

        jax.config.update(
            "jax_platforms", {"tpu": "axon"}.get(args.platform, args.platform)
        )

    try:
        mf = parse_manifest(args.manifest)
    except FileNotFoundError as e:
        print(f"error: {e}", file=sys.stderr)
        return 66
    except ManifestError as e:
        print(f"manifest error: {e}", file=sys.stderr)
        return 64

    tel = None
    if args.metrics_out is not None:
        dn = os.path.dirname(args.metrics_out)
        if dn:
            os.makedirs(dn, exist_ok=True)
        from ..obs import Telemetry

        tel = Telemetry(metrics_path=args.metrics_out, every=args.metrics_every)

    opts = SweepOptions(
        engine=args.engine,
        jobs_glob=args.jobs,
        max_depth=args.max_depth,
        time_budget_s=args.time_budget,
        chunk=args.chunk,
        state_dir=args.state_dir,
        resume=args.resume,
        verbose=args.verbose,
        supervise=args.supervise,
    )

    from ..utils.cfg import CfgError

    try:
        res = run_sweep(mf, opts, telemetry=tel)
    except (ManifestError, CfgError) as e:
        print(f"sweep error: {e}", file=sys.stderr)
        return 64
    finally:
        if tel is not None:
            tel.close()

    for j in res.jobs:
        if args.json:
            print(json.dumps(j.to_json()))
        else:
            bits = [f"job={j.name}", f"rc={j.rc}"]
            if j.skipped:
                bits.append("skipped")
            elif j.mode == "check":
                bits += [
                    f"distinct={j.distinct}", f"total={j.total}",
                    f"depth={j.depth}", f"terminal={j.terminal}",
                ]
                if j.violation:
                    bits.append(f"VIOLATED={j.violation['invariant']}")
                if j.exit_cause:
                    bits.append(f"exit={j.exit_cause}")
                if j.recoveries:
                    bits.append(f"recoveries={j.recoveries}")
            else:
                bits += [f"behaviors={j.behaviors}", f"steps={j.steps}"]
                if j.violation:
                    bits.append(f"VIOLATED={j.violation['invariant']}")
            print(" ".join(bits))
    am = res.amortization
    if args.json:
        print(json.dumps(res.to_json()))
    else:
        print(
            f"fleet: jobs={am['jobs']} groups={am['groups']} "
            f"precompiles={am['precompiles']} time={res.seconds:.2f}s rc={res.rc}"
        )
    return res.rc
