"""Fleet result aggregation: per-job records + sweep-level amortization.

Per-job exit codes follow the CLI convention (raft_tpu/__main__.py):
0 clean, 2 invariant violation, 4 preempted mid-run, 5 unrecoverable.
The fleet return code is the WORST job rc, so one red job fails the
sweep in CI while the JSON still reports every job individually.
"""

from __future__ import annotations

from dataclasses import dataclass, field


def rc_for(exit_cause: str | None, violation) -> int:
    if violation is not None:
        return 2
    if exit_cause == "preempted":
        return 4
    if exit_cause == "unrecoverable":
        return 5
    return 0


@dataclass
class JobResult:
    name: str
    mode: str  # "check" | "simulate"
    rc: int
    seconds: float
    exit_cause: str | None = None
    # check mode
    distinct: int | None = None
    total: int | None = None
    depth: int | None = None
    terminal: int | None = None
    violation: dict | None = None  # {invariant, global_id, depth}
    trace_len: int | None = None
    # simulate mode
    behaviors: int | None = None
    steps: int | None = None
    skipped: bool = False  # already completed in a resumed sweep
    recoveries: int | None = None  # supervised sweeps: per-job recoveries

    def to_json(self) -> dict:
        out = {
            "job": self.name,
            "mode": self.mode,
            "rc": self.rc,
            "seconds": round(self.seconds, 3),
        }
        if self.skipped:
            out["skipped"] = True
        if self.exit_cause is not None:
            out["exit_cause"] = self.exit_cause
        for k in ("distinct", "total", "depth", "terminal", "trace_len",
                  "behaviors", "steps", "recoveries"):
            v = getattr(self, k)
            if v is not None:
                out[k] = v
        if self.violation is not None:
            out["violation"] = self.violation
        return out


@dataclass
class FleetResult:
    jobs: list[JobResult] = field(default_factory=list)
    groups: int = 0
    precompiles: int = 0
    seconds: float = 0.0

    @property
    def rc(self) -> int:
        return max((j.rc for j in self.jobs), default=0)

    @property
    def amortization(self) -> dict:
        nj = len(self.jobs)
        return {
            "jobs": nj,
            "groups": self.groups,
            "precompiles": self.precompiles,
            "precompile_ratio": round(self.precompiles / nj, 4) if nj else None,
        }

    def to_json(self) -> dict:
        return {
            "fleet": True,
            "rc": self.rc,
            "seconds": round(self.seconds, 3),
            "amortization": self.amortization,
            "jobs": [j.to_json() for j in self.jobs],
        }
