"""raft_tpu.fleet — sweep driver: many configs, one device program.

Checking-as-a-service shape (ROADMAP open item 4): a manifest names N
(spec x CONSTANTS) jobs; grouping.py buckets them by packed-state-layout
compatibility; each bucket runs as ONE compiled program — the host
engine co-resides every job in a shared frontier (BFSChecker.run_fleet,
a config axis embedded in the state vector), the device engines queue
jobs through one jit cache with per-job checkpoint lineages
(DeviceBFS/ShardedBFS.run_fleet). The CLI subcommand is
``raft_tpu sweep MANIFEST.json``.

    from raft_tpu.fleet import parse_manifest, run_sweep, SweepOptions
    res = run_sweep(parse_manifest("sweep.json"), SweepOptions())
    print(res.rc, res.amortization)
"""

from .driver import SweepOptions, run_sweep
from .grouping import FLEET_DYN, FleetGroup, group_jobs
from .manifest import (
    FleetJob,
    FleetManifest,
    ManifestError,
    cfg_for_job,
    parse_manifest,
    parse_manifest_obj,
)
from .packer import build_packed
from .results import FleetResult, JobResult

__all__ = [
    "FLEET_DYN",
    "FleetGroup",
    "FleetJob",
    "FleetManifest",
    "FleetResult",
    "JobResult",
    "ManifestError",
    "SweepOptions",
    "build_packed",
    "cfg_for_job",
    "group_jobs",
    "parse_manifest",
    "parse_manifest_obj",
    "run_sweep",
]
