"""Sweep driver: manifest -> groups -> engines -> FleetResult.

The execution plan per group kind (grouping.py):

- ``packed`` + host engine: ONE BFSChecker.run_fleet over the packed
  model — every job co-resident in a shared frontier, one compile.
- ``packed`` + tpu/sharded engine: DeviceBFS/ShardedBFS.run_fleet queue
  arm — jobs run back-to-back through the packed model's single jit
  cache (fleet_select picks the job), one compile, per-job checkpoint
  lineage and job-tagged telemetry.
- ``serial``: jobs share the first setup's model instance (identical
  params by construction), so N runs still cost one compile.
- ``simulate``: checker/simulate.py random walks per job over the
  group's shared model.

Sweep resume (``--state-dir`` + ``--resume``): ``fleet_state.json``
records each completed job's rc after every group/job; on resume,
completed jobs are skipped — except packed host groups, which rerun
WHOLLY unless every member is done (the co-resident frontier has no
per-job restart point; per-job device lineages do).

Supervised sweeps (``SweepOptions.supervise`` / ``fleet --supervise``):
each serial/packed-device job runs under the resilience supervisor with
that per-job recovery budget. Recoveries that need neither growth nor a
mesh change reuse the group's compiled engine (zero recompiles), a job
whose budget is spent becomes an rc-5 ``unrecoverable`` JobResult
without killing the rest of the sweep, and per-job recovery counts land
in ``fleet_state.json`` (``recoveries``) and each JobResult. Per-job
fault injection comes from the manifest's ``chaos`` field (one
ChaosInjector per job, shared across its retries); packed HOST groups
ignore chaos/supervision — the co-resident frontier has no per-job
recovery point.
"""

from __future__ import annotations

import fnmatch
import json
import os
import time
from dataclasses import dataclass

from ..checker.bfs import BFSChecker
from ..obs import JobTaggedTelemetry
from ..resilience import (
    ChaosInjector,
    ChaosSpec,
    CheckpointMismatch,
    UnrecoverableError,
    lineage_name,
    supervise as _supervise,
)
from .grouping import FleetGroup, group_jobs
from .manifest import FleetJob, FleetManifest, ManifestError
from .packer import build_packed
from .results import FleetResult, JobResult, rc_for

ENGINES = ("host", "tpu", "sharded")


@dataclass
class SweepOptions:
    engine: str = "host"  # host | tpu | sharded
    jobs_glob: str | None = None  # fnmatch filter on job names
    max_depth: int | None = None
    time_budget_s: float | None = None
    chunk: int = 1024
    state_dir: str | None = None  # checkpoints + fleet_state.json
    resume: bool = False
    verbose: bool = False
    supervise: int | None = None  # per-job recovery budget (None: off)


def _state_path(state_dir: str) -> str:
    return os.path.join(state_dir, "fleet_state.json")


def _load_completed(opts: SweepOptions) -> dict[str, int]:
    if not (opts.resume and opts.state_dir):
        return {}
    path = _state_path(opts.state_dir)
    if not os.path.exists(path):
        return {}
    with open(path) as fh:
        return {str(k): int(v) for k, v in json.load(fh)["completed"].items()}


def _save_completed(opts: SweepOptions, completed: dict[str, int],
                    recoveries: dict[str, int] | None = None) -> None:
    if not opts.state_dir:
        return
    os.makedirs(opts.state_dir, exist_ok=True)
    path = _state_path(opts.state_dir)
    tmp = path + ".tmp"
    state: dict = {"completed": completed}
    if recoveries:
        state["recoveries"] = recoveries
    with open(tmp, "w") as fh:
        json.dump(state, fh)
    os.replace(tmp, path)


def _job_chaos(job: FleetJob) -> ChaosInjector | None:
    """One injector per job per sweep — shared across the job's
    supervisor retries so a consumed fault never re-fires."""
    if not job.chaos:
        return None
    return ChaosInjector(ChaosSpec.parse(job.chaos))


def _unrecoverable(name: str, exc: BaseException,
                   recoveries: int) -> JobResult:
    return JobResult(
        name=name, mode="check", rc=rc_for("unrecoverable", None),
        seconds=0.0, exit_cause="unrecoverable", recoveries=recoveries,
    )


def _skipped(job: FleetJob, rc: int) -> JobResult:
    return JobResult(
        name=job.name, mode=job.mode, rc=rc, seconds=0.0, skipped=True
    )


def _check_result(name: str, r) -> JobResult:
    """Lower a CheckResult (host/device) or ShardedResult to a JobResult."""
    viol = getattr(r, "violation", None)
    if viol is not None:
        vd = {
            "invariant": viol.invariant,
            "global_id": int(viol.global_id),
            "depth": int(viol.depth),
        }
    else:
        vi = getattr(r, "violation_invariant", None)
        vd = {"invariant": vi} if vi else None
    return JobResult(
        name=name,
        mode="check",
        rc=rc_for(r.exit_cause, vd),
        seconds=float(r.seconds),
        exit_cause=r.exit_cause,
        distinct=int(r.distinct),
        total=int(r.total),
        depth=int(r.depth),
        terminal=int(r.terminal),
        violation=vd,
        trace_len=len(r.trace) if r.trace else None,
    )


def _make_engine(kind: str, model, setup, opts: SweepOptions):
    if kind == "host":
        return BFSChecker(
            model, invariants=setup.invariants, symmetry=setup.symmetry,
            chunk=opts.chunk,
        )
    if kind == "tpu":
        from ..checker.device_bfs import DeviceBFS

        return DeviceBFS(
            model, invariants=setup.invariants, symmetry=setup.symmetry,
            chunk=opts.chunk,
        )
    if kind == "sharded":
        from ..parallel.sharded import ShardedBFS

        return ShardedBFS(
            model, invariants=setup.invariants, symmetry=setup.symmetry,
            chunk=opts.chunk,
        )
    raise ManifestError(f"unknown engine {kind!r} (available: {ENGINES})")


def _run_simulate_group(group, opts, completed, out) -> int:
    from ..checker.simulate import Simulator

    model = group.setups[0].model  # identical params -> shared kernels
    ran = 0
    for job, setup in zip(group.jobs, group.setups):
        if opts.resume and job.name in completed:
            out[job.name] = _skipped(job, completed[job.name])
            continue
        sim = Simulator(
            model,
            invariants=setup.invariants,
            walks=int(job.sim["walks"]),
            max_behavior_depth=int(job.sim["max_behavior_depth"]),
            seed=int(job.sim["seed"]),
        )
        r = sim.run(
            max_steps=job.sim["max_steps"],
            time_budget_s=opts.time_budget_s,
            max_behaviors=job.sim["max_behaviors"],
            verbose=opts.verbose,
        )
        vd = (
            {
                "invariant": r.violation.invariant,
                "walk": int(r.violation.walk),
                "depth": int(r.violation.depth),
            }
            if r.violation
            else None
        )
        out[job.name] = JobResult(
            name=job.name,
            mode="simulate",
            rc=2 if vd else 0,
            seconds=float(r.seconds),
            behaviors=int(r.behaviors),
            steps=int(r.steps),
            violation=vd,
            trace_len=len(r.trace) if r.trace else None,
        )
        ran += 1
        completed[job.name] = out[job.name].rc
        _save_completed(opts, completed)
    return 1 if ran else 0


def _run_serial_group(group, opts, completed, out, telemetry,
                      recoveries) -> int:
    model = group.setups[0].model  # identical params -> one jit cache
    ran = 0
    for idx, (job, setup) in enumerate(zip(group.jobs, group.setups)):
        if opts.resume and job.name in completed:
            out[job.name] = _skipped(job, completed[job.name])
            continue
        eng = _make_engine(opts.engine, model, setup, opts)
        kw = dict(
            max_depth=opts.max_depth,
            verbose=opts.verbose,
            time_budget_s=opts.time_budget_s,
        )
        if telemetry is not None:
            kw["telemetry"] = JobTaggedTelemetry(telemetry, job.name)
        chaos = _job_chaos(job)
        if chaos is not None:
            kw["chaos"] = chaos
        if opts.state_dir:
            ck = os.path.join(
                opts.state_dir, "ckpt", lineage_name(job.name, idx)
            )
            os.makedirs(os.path.dirname(ck), exist_ok=True)
            kw["checkpoint_path"] = ck
            if opts.resume and os.path.exists(ck):
                kw["resume"] = ck
        if opts.supervise is None:
            out[job.name] = _check_result(job.name, eng.run(**kw))
        else:
            stats: dict = {}

            def factory(ov, _eng=eng):
                return _eng if not ov else _eng._rebuild(ov)

            try:
                r = _supervise(
                    factory, kw, max_retries=int(opts.supervise),
                    backoff_base=0.0, seed=idx,
                    telemetry=kw.get("telemetry"), stats_out=stats,
                )
                out[job.name] = _check_result(job.name, r)
            except (UnrecoverableError, CheckpointMismatch) as exc:
                out[job.name] = _unrecoverable(
                    job.name, exc, int(stats.get("recoveries", 0)))
            out[job.name].recoveries = int(stats.get("recoveries", 0))
            recoveries[job.name] = out[job.name].recoveries
        ran += 1
        completed[job.name] = out[job.name].rc
        _save_completed(opts, completed, recoveries)
    return 1 if ran else 0


def _run_packed_group(group, opts, completed, out, telemetry,
                      recoveries) -> int:
    names = [j.name for j in group.jobs]
    if opts.resume and all(n in completed for n in names):
        for job in group.jobs:
            out[job.name] = _skipped(job, completed[job.name])
        return 0
    model = build_packed(group)
    setup = group.setups[0]
    eng = _make_engine(opts.engine, model, setup, opts)
    if opts.engine == "host":
        # co-resident arm: one shared frontier; no per-job restart
        # point, so a partially-completed group reruns wholly (and
        # chaos/supervision don't apply — there is no per-job recovery)
        results = eng.run_fleet(
            job_names=names,
            max_depth=opts.max_depth,
            verbose=opts.verbose,
            time_budget_s=opts.time_budget_s,
            telemetry=telemetry,
        )
        for name, r in zip(names, results):
            out[name] = _check_result(name, r)
    else:
        skip = tuple(n for n in names if opts.resume and n in completed)
        ckpt_dir = None
        if opts.state_dir:
            ckpt_dir = os.path.join(opts.state_dir, "ckpt")
            os.makedirs(ckpt_dir, exist_ok=True)
        chaos_by_job = {
            j.name: inj for j in group.jobs
            if (inj := _job_chaos(j)) is not None
        }
        rstats: dict[str, int] = {}
        fleet_kw: dict = {}
        if chaos_by_job:
            fleet_kw["chaos_by_job"] = chaos_by_job
        if opts.supervise is not None:
            fleet_kw["supervise"] = int(opts.supervise)
            fleet_kw["recovery_stats"] = rstats
        results = eng.run_fleet(
            job_names=names,
            telemetry=telemetry,
            checkpoint_dir=ckpt_dir,
            resume=opts.resume,
            skip=skip,
            max_depth=opts.max_depth,
            verbose=opts.verbose,
            time_budget_s=opts.time_budget_s,
            **fleet_kw,
        )
        for job, r in zip(group.jobs, results):
            if r is None:
                out[job.name] = _skipped(job, completed[job.name])
            elif isinstance(r, BaseException):
                out[job.name] = _unrecoverable(
                    job.name, r, rstats.get(job.name, 0))
            else:
                out[job.name] = _check_result(job.name, r)
            if job.name in rstats:
                out[job.name].recoveries = rstats[job.name]
                recoveries[job.name] = rstats[job.name]
    for name in names:
        completed[name] = out[name].rc
    _save_completed(opts, completed, recoveries)
    return 1


def run_sweep(
    manifest: FleetManifest,
    opts: SweepOptions | None = None,
    telemetry=None,
) -> FleetResult:
    opts = opts or SweepOptions()
    if opts.engine not in ENGINES:
        raise ManifestError(
            f"unknown engine {opts.engine!r} (available: {ENGINES})"
        )
    jobs = manifest.jobs
    if opts.jobs_glob:
        jobs = [
            j for j in jobs if fnmatch.fnmatchcase(j.name, opts.jobs_glob)
        ]
        if not jobs:
            raise ManifestError(
                f"{manifest.path}: --jobs {opts.jobs_glob!r} matches none of "
                f"{len(manifest.jobs)} jobs"
            )
    mf = FleetManifest(path=manifest.path, jobs=jobs)
    groups = group_jobs(mf)
    completed = _load_completed(opts)
    out: dict[str, JobResult] = {}
    recoveries: dict[str, int] = {}
    precompiles = 0
    t0 = time.perf_counter()
    for gi, group in enumerate(groups):
        if opts.verbose:
            print(
                f"fleet: group {gi + 1}/{len(groups)} kind={group.kind} "
                f"jobs={len(group.jobs)} dyn={list(group.dyn_consts)}"
            )
        if group.kind == "simulate":
            precompiles += _run_simulate_group(group, opts, completed, out)
        elif group.kind == "serial":
            precompiles += _run_serial_group(
                group, opts, completed, out, telemetry, recoveries
            )
        else:
            precompiles += _run_packed_group(
                group, opts, completed, out, telemetry, recoveries
            )
    return FleetResult(
        jobs=[out[j.name] for j in mf.jobs],
        groups=len(groups),
        precompiles=precompiles,
        seconds=time.perf_counter() - t0,
    )
