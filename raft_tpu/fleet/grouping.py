"""Layout-compatibility grouping: which jobs can share one program.

Two check jobs can ride the same compiled program exactly when every
compile-time-shaping parameter agrees. For the Raft family the dynamic
CONSTANTS (FLEET_DYN) only feed guard comparisons and the message
packer's term width, so the group key is the params dataclass with the
dynamic fields zeroed PLUS ``bits_for(max_term)`` — MaxElections 1 and 2
both need 2 term bits and land in one group; MaxElections 4 widens the
packer and splits off. Everything else that shapes the program (spec
class, variant knobs, msg_slots, server/value counts, invariant set,
symmetry) is in the key verbatim, so a mismatch on any of them simply
yields another group rather than an error.

Group kinds:

- ``packed``  — check jobs in a FLEET_DYN family: one packed model with
  a config axis (packer.build_packed), co-resident on the host engine or
  queued through one jit cache on the device engines.
- ``serial``  — check jobs outside FLEET_DYN: the key is the FULL params
  object, so every job in the group has identical params and they share
  one model instance (= one compile), run back-to-back.
- ``simulate``— simulate-mode jobs, grouped by full params the same way.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass

import numpy as np

from ..models.registry import CheckSetup, build_from_cfg
from ..ops.packing import bits_for
from .manifest import FleetJob, FleetManifest, cfg_for_job

# Params classes whose lowering supports per-state dynamic constants
# (guards read a lane via FleetConstMixin._cv), mapping the params field
# name of each packable CONSTANT. Order here is the lane order.
FLEET_DYN = {
    "RaftParams": ("max_elections", "max_restarts"),
    "PullRaftParams": ("max_elections", "max_restarts"),
}


@dataclass
class FleetGroup:
    kind: str  # "packed" | "serial" | "simulate"
    jobs: list[FleetJob]
    setups: list[CheckSetup]
    # dynamic constants that actually VARY across the group, in
    # FLEET_DYN order; () when all jobs agree (jobs are then separated
    # by the fleet_job lane alone)
    dyn_consts: tuple[str, ...] = ()
    # [J, len(dyn_consts)] per-job values, manifest job order
    table: np.ndarray | None = None


def build_setup(job: FleetJob, manifest_path: str = "<manifest>") -> CheckSetup:
    """One job -> one CheckSetup through the registry (CfgError on bad
    spec/constants propagates; the CLI maps it to exit 64)."""
    cfg = cfg_for_job(job, manifest_path)
    return build_from_cfg(
        cfg, spec=job.spec, msg_slots=job.msg_slots, net_faults=job.net_faults
    )


def _group_key(job: FleetJob, setup: CheckSetup):
    p = setup.model.p
    cls = type(p).__name__
    common = (
        cls,
        type(setup.model).__name__,
        setup.model.name,
        setup.invariants,
        setup.symmetry,
        tuple(setup.server_names),
        tuple(setup.value_names),
    )
    if job.mode == "simulate":
        return ("simulate", p) + common
    dyn = FLEET_DYN.get(cls)
    if dyn is None:
        return ("serial", p) + common
    zeroed = dataclasses.replace(p, **{n: 0 for n in dyn})
    # bits_for(max_term) is the only packer width a dynamic constant
    # feeds (models/raft.py:_build_packer) — keep it in the key so the
    # zeroing above cannot merge jobs with different message layouts
    return ("packed", zeroed, bits_for(p.max_term)) + common


def group_jobs(manifest: FleetManifest) -> list[FleetGroup]:
    """Bucket manifest jobs into compiled-program groups, preserving
    manifest order both across groups (by first member) and within."""
    buckets: dict = {}
    order: list = []
    for job in manifest.jobs:
        setup = build_setup(job, manifest.path)
        key = _group_key(job, setup)
        if key not in buckets:
            buckets[key] = ([], [])
            order.append(key)
        buckets[key][0].append(job)
        buckets[key][1].append(setup)
    groups: list[FleetGroup] = []
    for key in order:
        jobs, setups = buckets[key]
        kind = key[0]
        if kind != "packed":
            groups.append(FleetGroup(kind=kind, jobs=jobs, setups=setups))
            continue
        dyn_all = FLEET_DYN[type(setups[0].model.p).__name__]
        cols = {
            n: [int(getattr(s.model.p, n)) for s in setups] for n in dyn_all
        }
        varying = tuple(n for n in dyn_all if len(set(cols[n])) > 1)
        table = np.array(
            [[cols[n][j] for n in varying] for j in range(len(setups))],
            dtype=np.int64,
        ).reshape(len(setups), len(varying))
        groups.append(
            FleetGroup(
                kind="packed", jobs=jobs, setups=setups,
                dyn_consts=varying, table=table,
            )
        )
    return groups
