"""CLI — the `tlc` replacement.

    python -m raft_tpu path/to/Raft.cfg [--checker tpu|oracle] ...

Mirrors the reference workflow `tlc <Spec>.tla -config <Spec>.cfg -deadlock`
(reference README.md:5-7): `-deadlock` semantics are the default (terminal
states are reported, not errors). The CHECKER env var or --checker flag
selects the backend; `oracle` is the pure-Python differential reference.

Exit codes (stable contract, pinned by tests/test_resilience.py):

    0   clean run, no violations (also: `lint` found no findings)
    2   invariant or temporal-property violation found
    3   --coverage=strict dead-action gate tripped; `lint` findings
        (any error, or any warning under --strict)
    4   preempted (SIGTERM/SIGINT): a resumable checkpoint was written
        at the next wave boundary; re-run with --resume to continue
    5   unrecoverable failure (retry budget spent, capacity overflow
        with no growth policy or no checkpoint, all generations corrupt,
        shard lost / shard stalled without --supervise)
    64  usage/config error (bad flags, bad cfg, checkpoint spec mismatch)
    66  input file not found (cfg or --resume path)
    70  fingerprint-collision audit failed
"""

from __future__ import annotations

import argparse
import os
import sys


def main(argv=None):
    argv = sys.argv[1:] if argv is None else list(argv)
    if argv and argv[0] == "sweep":
        # fleet subcommand: `raft_tpu sweep MANIFEST.json` (fleet/cli.py)
        from .fleet.cli import sweep_main

        return sweep_main(argv[1:])
    if argv and argv[0] == "lint":
        # static-analysis subcommand: `raft_tpu lint [--strict] [--json]
        # [--pass NAME] [--mutate NAME]` (analysis/cli.py)
        from .analysis.cli import lint_main

        return lint_main(argv[1:])
    ap = argparse.ArgumentParser(prog="raft_tpu")
    ap.add_argument("cfg", help="TLC .cfg file (the spec is inferred from its name)")
    ap.add_argument("--spec", help="spec/module name override")
    ap.add_argument(
        "--checker",
        default=os.environ.get("CHECKER", "tpu"),
        choices=["tpu", "sharded", "tpu-host", "oracle"],
        help="backend: tpu (single-device BFS), sharded (multi-chip "
        "frontier-sharded BFS over a device mesh — the `tlc -workers N` "
        "replacement), tpu-host (device expansion + host dedup, the v1 "
        "driver), or oracle (pure-Python reference)",
    )
    ap.add_argument(
        "--devices",
        type=int,
        default=None,
        metavar="N",
        help="mesh size for --checker sharded (default: all visible "
        "devices; on CPU set XLA_FLAGS=--xla_force_host_platform_"
        "device_count=N before launch to expose N virtual devices)",
    )
    ap.add_argument("--frontier-cap", type=int, default=None,
                    help="device frontier buffer rows (tpu checker)")
    ap.add_argument("--seen-cap", type=int, default=None,
                    help="device seen-set capacity (tpu checker)")
    ap.add_argument("--journal-cap", type=int, default=None,
                    help="device trace-journal capacity (tpu checker)")
    ap.add_argument("--time-budget", type=float, default=None,
                    help="stop (non-exhausted) after this many seconds")
    ap.add_argument("--checkpoint", default=None, metavar="PATH",
                    help="periodically save resumable run state (tpu checker)")
    ap.add_argument("--checkpoint-every", type=float, default=300.0,
                    metavar="S", help="seconds between checkpoints")
    ap.add_argument("--resume", default=None, metavar="PATH",
                    help="resume a run from a --checkpoint file (tpu checker)")
    ap.add_argument(
        "--checkpoint-keep",
        type=int,
        default=3,
        metavar="N",
        help="checkpoint generations to rotate (PATH, PATH.gen1, ...); "
        "a torn newest generation falls back to the previous intact one",
    )
    ap.add_argument(
        "--supervise",
        nargs="?",
        const=5,
        type=int,
        default=None,
        metavar="RETRIES",
        help="wrap the run in the auto-resume supervisor: capacity "
        "overflows rebuild the engine with grown capacities and resume "
        "from the newest intact checkpoint; transient device failures "
        "retry with exponential backoff (default budget: 5 recoveries)",
    )
    ap.add_argument(
        "--chaos",
        default=None,
        metavar="SPEC",
        help="deterministic fault injection for drills and tests: "
        "comma-separated key=int pairs from crash=WAVE (raise at wave "
        "start), transient=WAVE (injected device flake), ovf=WAVE "
        "(spurious frontier-overflow bit), truncate=NTH (tear the Nth "
        "checkpoint write), preempt=WAVE (SIGTERM self-delivery), "
        "shard_loss=WAVE (kill one shard of the sharded mesh mid-wave; "
        "the lost shard is seed mod D), seed=S; each fault fires once",
    )
    ap.add_argument(
        "--no-reshard",
        action="store_true",
        help="refuse to resume a sharded checkpoint written on a "
        "different mesh size (default: re-route the shards by fp mod D "
        "on load — checkpoints are mesh-portable)",
    )
    ap.add_argument(
        "--stall-abort",
        type=float,
        default=None,
        metavar="FACTOR",
        help="sharded checker: abort a wave that runs longer than FACTOR "
        "times the rolling-median wave time, spilling a wave-start "
        "checkpoint and raising a shard-stall (recoverable under "
        "--supervise); needs at least 3 completed waves to calibrate",
    )
    ap.add_argument("--max-frontier-cap", type=int, default=None,
                    help="frontier growth bound (tpu checker)")
    ap.add_argument("--max-seen-cap", type=int, default=None,
                    help="seen-set growth bound (tpu checker)")
    ap.add_argument("--max-journal-cap", type=int, default=None,
                    help="journal growth bound (tpu checker)")
    ap.add_argument("--max-depth", type=int, default=None)
    ap.add_argument(
        "--collision-audit",
        type=int,
        default=None,
        metavar="DEPTH",
        help="before the main run, explore to DEPTH under two independent "
        "fingerprint hash families and require identical counts (bounds "
        "silent hash-collision risk; tpu checker only)",
    )
    ap.add_argument("--chunk", type=int, default=1024, help="device batch size")
    ap.add_argument(
        "--profile",
        type=int,
        default=None,
        metavar="DEPTH",
        help="instead of checking, warm a BFS to DEPTH and print a per-"
        "stage time breakdown of the chunk pipeline (expand / compact / "
        "canonicalize / probe / run-emit / scatter / invariants; "
        "SURVEY.md §5.1); tpu checker only",
    )
    ap.add_argument(
        "--simulate",
        type=int,
        default=None,
        metavar="N",
        help="simulation mode (TLC -simulate): run N random behaviors "
        "instead of exhaustive BFS — the reference's prescribed mode for "
        "FlexibleRaft.cfg and KRaftWithReconfig.cfg",
    )
    ap.add_argument("--sim-depth", type=int, default=50,
                    help="max behavior length in simulation mode")
    ap.add_argument("--sim-walks", type=int, default=128,
                    help="parallel walks per device batch in simulation mode")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--msg-slots", type=int, default=None,
                    help="message-bag slot count (default: per-spec)")
    ap.add_argument(
        "--net-faults",
        action="store_true",
        help="enable the opt-in DuplicateMessage/DropMessage network-"
        "fault actions (Raft.tla:508-523; Raft family only; duplication "
        "bounded to max_msg_copies per record)",
    )
    ap.add_argument("--no-symmetry", action="store_true", help="ignore SYMMETRY")
    ap.add_argument(
        "--trace-format",
        default="default",
        choices=["default", "tlc"],
        help="counterexample trace format: tlc emits TLC's textual error-"
        "trace shape (Error: headers + State N + /\\ var = value) for "
        "offline bit-for-bit diffing against a real TLC run",
    )
    ap.add_argument(
        "--lenient",
        action="store_true",
        help="downgrade recoverable cfg bugs (e.g. PullRaft.cfg's undeclared "
        "v2) to warnings and apply the obvious repair",
    )
    ap.add_argument(
        "--progress",
        nargs="?",
        const=10.0,
        type=float,
        default=None,
        metavar="SECS",
        help="TLC-style progress line on stderr (throttled to one line "
        "per SECS seconds, default 10; stall warnings print immediately)",
    )
    ap.add_argument(
        "--coverage",
        nargs="?",
        const="table",
        choices=["table", "strict"],
        default=None,
        help="after the run, print a TLC-style per-action coverage table "
        "(enabled / fired / new-distinct states per action, cumulative "
        "over the run) with WARNING lines for actions that never fired; "
        "--coverage=strict additionally exits 3 when any action never "
        "fired (dead-action gate for CI); BFS checkers only",
    )
    ap.add_argument(
        "--metrics-out",
        default=None,
        metavar="PATH",
        help="write the live telemetry event stream (manifest/wave/stall/"
        "summary, one JSON object per line) to PATH; validate with "
        "scripts/check_metrics_schema.py",
    )
    ap.add_argument(
        "--metrics-every",
        type=int,
        default=1,
        metavar="N",
        help="write every Nth wave event (the final wave always flushes, "
        "so the stream stays count-accurate)",
    )
    ap.add_argument(
        "--timeline",
        nargs="?",
        const=8,
        type=int,
        default=0,
        metavar="EVERY_N",
        help="wave-timeline observatory: run every Nth wave (default 8) "
        "as separately timed stage dispatches and emit `timeline` (and, "
        "on the sharded checker, per-shard `shard_wave`) events into the "
        "metrics stream; sampled waves are bit-identical to the fused "
        "program, unsampled waves are untouched; BFS checkers only",
    )
    ap.add_argument(
        "--trace-dir",
        default=None,
        metavar="DIR",
        help="capture a jax.profiler trace: each BFS wave is an xprof "
        "step (StepTraceAnnotation) and precompile/seen_merge/checkpoint "
        "are named spans",
    )
    ap.add_argument(
        "--json",
        action="store_true",
        help="print the run's summary event as the last stdout line "
        "(machine-readable; everything else non-result already goes to "
        "stderr); BFS checkers only",
    )
    ap.add_argument(
        "--platform",
        default=os.environ.get("RAFT_TPU_PLATFORM", "auto"),
        choices=["auto", "cpu", "tpu", "axon"],
        help="JAX platform (the image's axon TPU plugin ignores JAX_PLATFORMS, "
        "so this forces it via jax.config)",
    )
    ap.add_argument("--verbose", "-v", action="store_true")
    args = ap.parse_args(argv)

    chaos_spec = None
    if args.chaos:
        from .resilience import ChaosSpec

        try:
            chaos_spec = ChaosSpec.parse(args.chaos)
        except ValueError as e:
            print(f"error: --chaos: {e}", file=sys.stderr)
            return 64

    if args.platform != "auto":
        import jax

        jax.config.update(
            "jax_platforms", {"tpu": "axon"}.get(args.platform, args.platform)
        )

    from .utils.cfg import CfgError, parse_cfg
    from .models.registry import build_from_cfg

    try:
        cfg = parse_cfg(args.cfg, lenient=args.lenient)
        for diag in cfg.diagnostics:
            print(f"config warning: {diag}", file=sys.stderr)
        setup = build_from_cfg(
            cfg, spec=args.spec, msg_slots=args.msg_slots,
            net_faults=args.net_faults,
        )
    except FileNotFoundError as e:
        print(f"error: {e}", file=sys.stderr)
        return 66
    except CfgError as e:
        # includes the deliberately-broken reference cfgs (SURVEY.md §2.2)
        print(f"config error: {e}", file=sys.stderr)
        return 64
    symmetry = setup.symmetry and not args.no_symmetry
    props = tuple(cfg.properties)
    # non-result chatter (banner, config warnings, audit diagnostics,
    # progress) goes to stderr: stdout carries only the result lines —
    # and, under --json, the summary event as its last line
    print(
        f"spec={setup.model.name} servers={setup.server_names} "
        f"values={setup.value_names} invariants={list(setup.invariants)} "
        f"properties={list(props)} symmetry={symmetry} checker={args.checker}",
        file=sys.stderr,
    )
    if props:
        # PROPERTY lines are temporal formulas; refuse configurations this
        # build cannot check rather than silently dropping them
        # (round-2 verdict item 5)
        supported = getattr(setup.model, "liveness", {})
        unknown = [p for p in props if p not in supported]
        if unknown:
            print(
                f"error: PROPERTY {' '.join(unknown)}: no liveness support "
                f"for spec {setup.model.name}; remove the PROPERTY line or "
                "use a supported formula "
                f"(supported: {', '.join(supported) or 'none'})",
                file=sys.stderr,
            )
            return 64
        if args.simulate is not None or args.checker == "oracle":
            print(
                "error: PROPERTY checking needs the exhaustive device "
                "graph; run with --checker tpu and no --simulate",
                file=sys.stderr,
            )
            return 64
        if args.max_depth is not None or args.time_budget is not None:
            print(
                "error: PROPERTY checking is unsound on a partially "
                "explored graph; drop --max-depth/--time-budget",
                file=sys.stderr,
            )
            return 64

    if args.checker in ("tpu", "sharded", "tpu-host") and not hasattr(setup.model, "expand"):
        print(
            f"error: spec {setup.model.name} has no TPU lowering yet; use "
            "--checker oracle (exhaustive or --simulate)",
            file=sys.stderr,
        )
        return 64

    if args.coverage is not None and (
        args.checker == "oracle" or args.simulate is not None
    ):
        print(
            "error: --coverage needs a BFS checker (tpu, sharded, or "
            "tpu-host) and no --simulate",
            file=sys.stderr,
        )
        return 64

    # device-checker capacity flags, shared by the collision audit and the
    # main run so both execute at the same geometry
    cli_caps = {
        k: v
        for k, v in {
            "frontier_cap": args.frontier_cap,
            "seen_cap": args.seen_cap,
            "journal_cap": args.journal_cap,
            "max_frontier_cap": args.max_frontier_cap,
            "max_seen_cap": args.max_seen_cap,
            "max_journal_cap": args.max_journal_cap,
        }.items()
        if v is not None
    }

    if args.collision_audit is not None:
        if args.checker != "tpu" or args.simulate is not None:
            print(
                "error: --collision-audit needs --checker tpu and no "
                "--simulate (the audit re-runs the exhaustive BFS)",
                file=sys.stderr,
            )
            return 64
        from .checker.audit import collision_audit

        audit = collision_audit(
            setup.model, invariants=setup.invariants, symmetry=symmetry,
            depth=args.collision_audit, chunk=args.chunk, **cli_caps,
        )
        print(audit, file=sys.stderr)
        if not audit.ok:
            print(
                "error: fingerprint-collision audit failed — counts differ "
                "between hash families; results would be untrustworthy",
                file=sys.stderr,
            )
            return 70

    if args.profile is not None:
        if args.checker != "tpu" or args.simulate is not None:
            print(
                "error: --profile needs --checker tpu and no --simulate",
                file=sys.stderr,
            )
            return 64
        from .checker.profile import profile_stages, render

        prof = profile_stages(
            setup.model, invariants=setup.invariants, symmetry=symmetry,
            chunk=args.chunk, warm_depth=args.profile, **cli_caps,
        )
        print(render(prof))
        return 0

    if args.checker == "oracle" and args.simulate is not None:
        from .models.registry import oracle_for_setup

        oracle = oracle_for_setup(setup)
        if not hasattr(oracle, "simulate"):
            print(
                "error: --simulate with the oracle backend is only "
                "supported for specs whose oracle implements it; use the "
                "tpu checker's --simulate instead",
                file=sys.stderr,
            )
            return 64
        res = oracle.simulate(
            invariants=setup.invariants,
            behaviors=args.simulate,
            max_depth=args.sim_depth,
            seed=args.seed,
        )
        print(f"simulate: behaviors={res['behaviors']} steps={res['steps']}")
        if res["violation"]:
            print(f"INVARIANT {res['violation']['invariant']} VIOLATED")
            return 2
        print("no invariant violations (simulation is not exhaustive)")
        return 0

    if args.checker == "oracle":
        from .models.registry import oracle_for_setup

        oracle = oracle_for_setup(setup)  # carries all variant knobs
        res = oracle.bfs(
            invariants=setup.invariants,
            symmetry=symmetry,
            max_depth=args.max_depth,
            time_budget_s=args.time_budget,
        )
        print(
            f"distinct={res['distinct']} total={res['total']} "
            f"depth={len(res['depth_counts']) - 1}"
        )
        if res["violation"]:
            print(f"INVARIANT {res['violation']['invariant']} VIOLATED")
            return 2
        print("no invariant violations")
        return 0

    if args.simulate is not None:
        from .checker.simulate import Simulator

        sim = Simulator(
            setup.model,
            invariants=setup.invariants,
            walks=args.sim_walks,
            max_behavior_depth=args.sim_depth,
            seed=args.seed,
        )
        res = sim.run(max_behaviors=args.simulate, verbose=args.verbose)
        print(
            f"simulate: behaviors={res.behaviors} steps={res.steps} "
            f"time={res.seconds:.2f}s ({res.states_per_sec:.0f} states/s)"
        )
        if res.violation:
            print(
                f"INVARIANT {res.violation.invariant} VIOLATED "
                f"(walk {res.violation.walk}, depth {res.violation.depth})"
            )
            if res.trace:
                from .utils.pprint import format_trace, format_trace_tlc

                if args.trace_format == "tlc":
                    print(format_trace_tlc(res.trace, setup,
                                           res.violation.invariant))
                else:
                    print(format_trace(res.trace, setup))
            return 2
        print("no invariant violations (simulation is not exhaustive)")
        return 0

    if args.checker == "sharded":
        import jax

        from .parallel.sharded import ShardedBFS

        devs = jax.devices()
        if args.devices is not None:
            if args.devices > len(devs):
                print(
                    f"error: --devices {args.devices} > {len(devs)} visible "
                    "devices (on CPU expose more with XLA_FLAGS="
                    "--xla_force_host_platform_device_count=N)",
                    file=sys.stderr,
                )
                return 64
            devs = devs[: args.devices]

        def make_checker(overrides):
            # the supervisor's shard-loss recovery passes a shrunk
            # "devices" override (the survivors); pop it out of the
            # capacity-override dict so it lands on the keyword
            ov = dict(overrides)
            devs_ = ov.pop("devices", devs)
            return ShardedBFS(
                setup.model,
                invariants=setup.invariants,
                symmetry=symmetry,
                devices=devs_,
                chunk=args.chunk,
                **{**cli_caps, **ov},
            )
    elif args.checker == "tpu":
        from .checker.device_bfs import DeviceBFS

        def make_checker(overrides):
            return DeviceBFS(
                setup.model,
                invariants=setup.invariants,
                symmetry=symmetry,
                chunk=args.chunk,
                **{**cli_caps, **overrides},
            )
    else:
        from .checker.bfs import BFSChecker

        def make_checker(overrides):
            # the host engine's buffers are unbounded; overflow growth
            # policies are the empty dict, so overrides carry no keys
            return BFSChecker(
                setup.model,
                invariants=setup.invariants,
                symmetry=symmetry,
                chunk=args.chunk,
            )

    checker = make_checker({})

    if args.resume is not None:
        # fail fast, BEFORE the multi-second precompile: prove the
        # checkpoint exists, loads (falling back through generations)
        # and matches this exact model/capacity identity
        from .resilience import ckpt as rckpt
        from .resilience.errors import CheckpointCorrupt, CheckpointMismatch

        try:
            gen, ck_depth = rckpt.validate_resume(
                args.resume, checker._ckpt_ident(), keep=args.checkpoint_keep,
                allow_reshard=(
                    args.checker == "sharded" and not args.no_reshard
                ),
            )
        except FileNotFoundError as e:
            print(f"error: --resume: {e}", file=sys.stderr)
            return 66
        except CheckpointCorrupt as e:
            print(f"error: --resume: {e}", file=sys.stderr)
            for p in e.problems:
                print(f"  {p}", file=sys.stderr)
            return 5
        except CheckpointMismatch as e:
            print(f"error: --resume: {e}", file=sys.stderr)
            return 64
        print(
            f"resume: validated {args.resume} "
            f"(generation {gen}, depth {ck_depth})",
            file=sys.stderr,
        )

    # parent directories for artifact paths, so a fresh machine can point
    # both at a not-yet-existing run directory
    for _p in (args.checkpoint, args.metrics_out):
        if _p:
            _dn = os.path.dirname(_p)
            if _dn:
                os.makedirs(_dn, exist_ok=True)

    tel = None
    if (
        args.progress is not None or args.metrics_out is not None
        or args.trace_dir is not None or args.json or args.timeline
    ):
        from .obs import Telemetry

        tel = Telemetry(
            metrics_path=args.metrics_out,
            every=args.metrics_every,
            progress_every=args.progress,
            trace_dir=args.trace_dir,
            timeline_every=args.timeline,
        )

    def _finish(rc: int) -> int:
        """Close telemetry and, under --json, make the summary event the
        last stdout line on EVERY BFS-checker return path."""
        if tel is not None:
            tel.close()
            if args.json and tel.last_summary is not None:
                import json

                print(json.dumps(tel.last_summary))
        return rc

    from .resilience import PreemptionGuard
    from .resilience.errors import (
        CapacityOverflow,
        CheckpointCorrupt,
        CheckpointMismatch,
        ShardLost,
        ShardStall,
        UnrecoverableError,
    )

    # all three BFS engines share the checkpoint/resume/preempt surface
    run_kw = dict(
        max_depth=args.max_depth,
        verbose=args.verbose,
        time_budget_s=args.time_budget,
        telemetry=tel,
        checkpoint_path=args.checkpoint,
        checkpoint_every_s=args.checkpoint_every,
        checkpoint_keep=args.checkpoint_keep,
        resume=args.resume,
    )
    if args.checker == "sharded":
        run_kw["reshard"] = not args.no_reshard
        if args.stall_abort is not None:
            run_kw["stall_abort_factor"] = args.stall_abort
    if chaos_spec is not None:
        # ONE injector for the whole session: each fault fires once even
        # across supervisor attempts (a crash-at-wave-3 must not re-fire
        # after the resume passes wave 3 again)
        from .resilience import ChaosInjector

        run_kw["chaos"] = ChaosInjector(chaos_spec)
    guard = PreemptionGuard().install()
    run_kw["preempt"] = guard
    try:
        if args.supervise is not None:
            from .resilience import supervise

            res = supervise(
                make_checker,
                run_kw,
                max_retries=args.supervise,
                seed=args.seed,
                telemetry=tel,
                verbose=args.verbose,
            )
        else:
            res = checker.run(**run_kw)
    except CheckpointMismatch as e:
        print(f"error: {e}", file=sys.stderr)
        return _finish(64)
    except (CheckpointCorrupt, UnrecoverableError) as e:
        print(f"error: {e}", file=sys.stderr)
        return _finish(5)
    except (ShardLost, ShardStall) as e:
        print(f"error: {e}", file=sys.stderr)
        if getattr(e, "checkpoint_saved", False):
            print(
                f"hint: a wave-start checkpoint was spilled to "
                f"{args.checkpoint}; re-run with --supervise to shrink "
                "the mesh onto the survivors and resume automatically",
                file=sys.stderr,
            )
        else:
            print(
                "hint: re-run with --supervise and --checkpoint PATH to "
                "recover shard failures automatically",
                file=sys.stderr,
            )
        return _finish(5)
    except CapacityOverflow as e:
        print(f"error: {e}", file=sys.stderr)
        print(
            "hint: re-run with --supervise (and --checkpoint PATH) to "
            "auto-grow capacities and resume",
            file=sys.stderr,
        )
        return _finish(5)
    finally:
        guard.uninstall()
    viol_name = (
        res.violation_invariant if args.checker == "sharded"
        else (res.violation.invariant if res.violation else None)
    )

    def _print_coverage() -> int:
        """TLC-style per-action coverage table (--coverage); returns the
        strict-mode exit code (3 when an action never fired)."""
        if args.coverage is None:
            return 0
        cov = getattr(res, "coverage", None)
        names = getattr(setup.model, "ACTION_NAMES", None)
        if cov is None or not names:
            print("coverage: not available for this spec", file=sys.stderr)
            return 0
        from .obs import dead_actions, render_coverage_table

        print(render_coverage_table(names, cov))
        if args.coverage == "strict" and dead_actions(names, cov):
            return 3
        return 0

    print(
        f"distinct={res.distinct} total={res.total} depth={res.depth} "
        f"terminal={res.terminal} time={res.seconds:.2f}s "
        f"({res.states_per_sec:.0f} distinct/s)"
        + (f" devices={checker.D}" if args.checker == "sharded" else "")
    )
    if viol_name:
        vdepth = res.depth if args.checker == "sharded" else res.violation.depth
        print(f"INVARIANT {viol_name} VIOLATED (depth {vdepth})")
        if res.trace:
            from .utils.pprint import format_trace, format_trace_tlc

            if args.trace_format == "tlc":
                print(format_trace_tlc(res.trace, setup, viol_name))
            else:
                print(format_trace(res.trace, setup))
        _print_coverage()  # violation rc 2 outranks the strict gate
        return _finish(2)
    if getattr(res, "exit_cause", None) == "preempted":
        # distinct rc so preemptible-TPU schedulers can tell "requeue
        # me with --resume" (4) apart from clean completion (0)
        print(
            f"preempted ({guard.signame}): "
            + (f"resumable checkpoint saved to {args.checkpoint}; "
               f"re-run with --resume {args.checkpoint}"
               if args.checkpoint
               else "no --checkpoint was set, progress is lost")
        )
        return _finish(4)
    print("no invariant violations")
    cov_rc = _print_coverage()

    if props:
        from .checker.liveness import LivenessChecker

        live = LivenessChecker(setup.model, props, chunk=args.chunk).run(
            verbose=args.verbose
        )
        print(
            f"liveness: graph {live.distinct} states / {live.total_edges} "
            f"edges (symmetry off), properties={list(props)}, "
            f"{live.seconds:.2f}s"
        )
        if live.violation:
            v = live.violation
            kind = "terminal stutter" if v.terminal else "cycle"
            print(
                f"PROPERTY {v.prop}[{v.instance}] VIOLATED "
                f"({kind}; prefix {len(v.prefix) - 1} steps, "
                f"loop {len(v.cycle)} steps)"
            )
            from .utils.pprint import format_trace, format_trace_tlc

            if args.trace_format == "tlc":
                # TLC prints a temporal counterexample as one behavior
                # with a "Back to state" marker at the loop entry
                print(format_trace_tlc(v.prefix, setup, None))
                if v.cycle:
                    print("-- Back to state: the loop below repeats --")
                    print(format_trace(v.cycle, setup))
            else:
                print(format_trace(v.prefix, setup))
                if v.cycle:
                    print("-- loop (repeats forever) --")
                    print(format_trace(v.cycle, setup))
            return _finish(2)
        print("no temporal property violations")
    return _finish(cov_rc)


if __name__ == "__main__":
    sys.exit(main())
