"""Sharded-frontier BFS over a ``jax.sharding.Mesh``.

The TPU-native replacement for TLC's shared-memory worker threads
(``tlc -workers N``, SURVEY.md §5.8): each chip owns the slice of
fingerprint space ``fp mod D`` (D = mesh size). A wave is one
``shard_map``-ed program per chip:

    expand local frontier (vmap) -> fingerprint -> route successors to
    their owner chip via ``jax.lax.all_to_all`` over ICI -> local
    sort-unique dedup + probe of the chip-resident seen-set -> append to
    the local frontier; global termination via ``psum`` of new-state
    counts.

All buffers are fixed-capacity (XLA static shapes); every capacity has an
overflow flag that aborts the run rather than dropping states. Multi-host
scale-out is the same collective over DCN (mesh spanning hosts).

State counts are exact and deterministic; within-wave discovery ORDER
differs from the sequential driver (first-occurrence tie-breaking is by
owner chip), which can pick a different—equally shortest—counterexample.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.hashing import U64_MAX
from ..ops.symmetry import Canonicalizer

AXIS = "shards"


@dataclass
class ShardedResult:
    distinct: int
    total: int
    depth: int
    depth_counts: list[int]
    violation_invariant: str | None
    seconds: float
    states_per_sec: float


class ShardedBFS:
    def __init__(
        self,
        model,
        invariants: tuple[str, ...] = (),
        symmetry: bool = True,
        devices=None,
        chunk: int = 256,  # per-device states expanded per wave step
        route_cap: int | None = None,  # per (src,dst) routed successors
        frontier_cap: int = 1 << 15,  # per-device frontier buffer
        seen_cap: int = 1 << 20,  # per-device seen-set capacity
    ):
        self.model = model
        self.invariants = tuple(invariants)
        devices = devices if devices is not None else jax.devices()
        self.D = len(devices)
        self.mesh = Mesh(np.array(devices), (AXIS,))
        self.chunk = chunk
        self.A = model.A
        self.route_cap = route_cap or max(256, (chunk * self.A) // self.D)
        self.frontier_cap = frontier_cap
        self.seen_cap = seen_cap
        self.canon = Canonicalizer.for_model(model, symmetry=symmetry)
        self.W = model.layout.W

        spec = P(AXIS)
        self._wave = jax.jit(
            jax.shard_map(
                self._wave_local,
                mesh=self.mesh,
                in_specs=(spec, spec, spec, spec),
                out_specs=(spec, spec, spec, spec, P(), P()),
            )
        )

    # ---------- device-local wave (runs per chip under shard_map) ----------

    def _wave_local(self, frontier, fcount, seen, scount):
        """frontier [F, W], fcount [1], seen [SC] sorted u64, scount [1].

        Returns (new_frontier [F, W], new_fcount [1], new_seen [SC],
        new_scount [1], global_new, flags) where flags packs overflow bits
        and the index of the first violated invariant (or -1)."""
        model, D, A, W = self.model, self.D, self.A, self.W
        F, RC, SC = self.frontier_cap, self.route_cap, self.seen_cap
        C = self.chunk
        # shard_map hands us the local block with its leading mesh axis of 1
        frontier, fcount, seen, scount = frontier[0], fcount[0], seen[0], scount[0]

        # 1. expand the first `chunk` live states (driver guarantees
        #    fcount <= chunk per wave by sub-stepping)
        live = jnp.arange(C) < fcount[0]
        batch = frontier[:C]
        succs, valid, _rank, ovf = jax.vmap(model._expand1)(batch)
        valid = valid & live[:, None]
        expand_ovf = jnp.any(valid & ovf)
        flat = succs.reshape(C * A, W)
        fps = self.canon._fingerprints(flat)
        fps = jnp.where(valid.reshape(-1), fps, U64_MAX)
        n_generated = jnp.sum(valid)

        # 2. route to owner chip = fp mod D, fixed RC slots per destination
        owner = (fps % np.uint64(D)).astype(jnp.int32)
        owner = jnp.where(fps == U64_MAX, D, owner)  # invalid -> drop lane
        order = jnp.argsort(owner, stable=True)
        owner_s = owner[order]
        fps_s = fps[order]
        start = jnp.searchsorted(owner_s, jnp.arange(D + 1), side="left")
        pos_in_owner = jnp.arange(C * A) - start[owner_s]
        ok = (owner_s < D) & (pos_in_owner < RC)
        route_ovf = jnp.any((owner_s < D) & (pos_in_owner >= RC))
        slot = jnp.where(ok, owner_s * RC + pos_in_owner, D * RC)
        send_states = jnp.zeros((D * RC + 1, W), jnp.int32).at[slot].set(flat[order])[:-1]
        send_fps = jnp.full((D * RC + 1,), U64_MAX, jnp.uint64).at[slot].set(fps_s)[:-1]

        # 3. ICI all-to-all: block d goes to chip d
        recv_states = lax.all_to_all(send_states, AXIS, 0, 0, tiled=True)
        recv_fps = lax.all_to_all(send_fps, AXIS, 0, 0, tiled=True)

        # 4. local dedup: sort by fp, drop repeats + already-seen
        sidx = jnp.argsort(recv_fps)
        rf = recv_fps[sidx]
        uniq = jnp.ones_like(rf, dtype=bool).at[1:].set(rf[1:] != rf[:-1])
        probe = jnp.searchsorted(seen, rf)
        in_seen = seen[jnp.clip(probe, 0, SC - 1)] == rf
        newm = uniq & ~in_seen & (rf != U64_MAX)
        n_new = jnp.sum(newm)

        # 5. append to local frontier buffer (compact the survivors first)
        BUF = max(F, D * RC) + 1  # scatter buffer; last row is the drop lane
        dst = jnp.where(newm, jnp.cumsum(newm) - 1, BUF - 1)
        frontier_ovf = n_new > F
        compact = (
            jnp.zeros((BUF, W), jnp.int32).at[dst].set(recv_states[sidx])[:F]
        )
        new_fps_compact = (
            jnp.full((BUF,), U64_MAX, jnp.uint64)
            .at[dst]
            .set(jnp.where(newm, rf, U64_MAX))[:-1]
        )

        # 6. merge into the seen-set (sorted-array union)
        seen_ovf = scount[0] + n_new > SC
        merged = jnp.sort(jnp.concatenate([seen, new_fps_compact]))[:SC]

        # 7. invariants on the newly discovered states
        inv_viol = jnp.int32(-1)
        if self.invariants:
            livemask = jnp.arange(F) < n_new
            for k, name in reversed(list(enumerate(self.invariants))):
                ok_inv = self.model.invariants[name](compact)
                bad = jnp.any(~ok_inv & livemask)
                inv_viol = jnp.where(bad, jnp.int32(k), inv_viol)
        inv_viol = lax.pmax(inv_viol, AXIS)

        global_new = lax.psum(n_new, AXIS)
        global_total = lax.psum(n_generated, AXIS)
        ovf_bits = (
            expand_ovf.astype(jnp.int32)
            + 2 * route_ovf.astype(jnp.int32)
            + 4 * frontier_ovf.astype(jnp.int32)
            + 8 * seen_ovf.astype(jnp.int32)
        )
        flags = jnp.stack(
            [lax.pmax(ovf_bits, AXIS), inv_viol, global_new.astype(jnp.int32)]
        )
        return (
            compact[None],
            n_new[None, None].astype(jnp.int32),
            merged[None],
            (scount[0] + n_new)[None, None].astype(jnp.int32),
            global_total.astype(jnp.int64),
            flags,
        )

    # ---------- host driver ----------

    def run(self, max_depth: int | None = None, verbose: bool = False) -> ShardedResult:
        import time

        model, D, W = self.model, self.D, self.W
        F, SC, C = self.frontier_cap, self.seen_cap, self.chunk
        t0 = time.perf_counter()

        init = model.init_states()
        init_fps = np.array(jax.device_get(self.canon.fingerprints(init)), dtype=np.uint64)
        frontier = np.zeros((D, F, W), np.int32)
        fcount = np.zeros((D, 1), np.int32)
        seen = np.full((D, SC), U64_MAX, np.uint64)
        scount = np.zeros((D, 1), np.int32)
        for k in range(len(init)):
            d = int(init_fps[k] % D)
            frontier[d, fcount[d, 0]] = init[k]
            seen[d, fcount[d, 0]] = init_fps[k]
            fcount[d, 0] += 1
            scount[d, 0] += 1
        seen = np.sort(seen, axis=1)

        distinct = len(init)
        total = len(init)
        depth_counts = [distinct]
        depth = 0
        violation = None
        sharding = NamedSharding(self.mesh, P(AXIS))
        frontier = jax.device_put(frontier, sharding)
        fcount = jax.device_put(fcount, sharding)
        seen = jax.device_put(seen, sharding)
        scount = jax.device_put(scount, sharding)

        while violation is None:
            if max_depth is not None and depth >= max_depth:
                break
            # NOTE v1: one wave expands at most `chunk` states per device;
            # larger frontiers would need sub-stepping (future work uses a
            # cursor into the frontier buffer).
            if int(np.max(np.array(jax.device_get(fcount)))) > C:
                raise OverflowError(
                    "per-device frontier exceeds chunk; raise chunk for this model"
                )
            frontier, fcount, seen, scount, wave_total, flags = self._wave(
                frontier, fcount, seen, scount
            )
            flags_h = np.array(jax.device_get(flags))
            ovf_bits, inv_idx, global_new = int(flags_h[0]), int(flags_h[1]), int(flags_h[2])
            if ovf_bits:
                raise OverflowError(f"sharded BFS capacity overflow (bits={ovf_bits:04b})")
            total += int(np.array(jax.device_get(wave_total)))
            if global_new == 0:
                break
            depth += 1
            distinct += global_new
            depth_counts.append(global_new)
            if inv_idx >= 0:
                violation = self.invariants[inv_idx]
            if verbose:
                print(f"depth {depth}: +{global_new} distinct={distinct}")

        dt = time.perf_counter() - t0
        return ShardedResult(
            distinct=distinct,
            total=total,
            depth=depth,
            depth_counts=depth_counts,
            violation_invariant=violation,
            seconds=dt,
            states_per_sec=distinct / dt if dt > 0 else 0.0,
        )
